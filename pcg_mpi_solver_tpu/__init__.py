"""pcg_mpi_solver_tpu — a TPU-native massively-parallel matrix-free PCG framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
``ankitskr/PCG-MPI-solver`` (matrix-free preconditioned conjugate-gradient
solver for linear elastostatics on octree-pattern hexahedral meshes,
reference: /root/reference/src/solver/pcg_solver.py).

Design (TPU-first, not a port):

- The per-iteration hot kernel K.p is never assembled: elements are grouped by
  geometric pattern type; each group is one dense (d x d) @ (d x N) matmul on
  the MXU plus a single sorted ``segment_sum`` scatter-add
  (reference computes this per-rank with np.dot + np.bincount,
  pcg_solver.py:279,300).
- Domain decomposition maps to a ``jax.sharding.Mesh`` axis: one mesh
  partition = one device shard, all partitions padded to a common shape so the
  whole solve is ONE jitted SPMD program under ``shard_map``.
- The reference's Isend/Recv halo exchange (pcg_solver.py:317-334) becomes an
  "interface assembly": partial sums on shared dofs are scattered into a small
  global interface vector, combined with one ``lax.psum`` over the mesh axis,
  and gathered back.  Deterministic and ICI-friendly.
- Global reductions (allreduce, pcg_solver.py:622-628) are ``lax.psum``; the
  fused 3-norm reduction (pcg_solver.py:504-507) is kept as a single fused
  psum of a length-3 vector.
- The MATLAB-compatible PCG loop (flags/stagnation/best-iterate semantics,
  pcg_solver.py:356-598) runs entirely inside ``lax.while_loop`` — iterations
  never bounce back to the host.
"""

__version__ = "0.1.0"

# ONE chokepoint for the wedged-tunnel guard: a JAX_PLATFORMS=cpu env
# request becomes an in-process backend pin at package import, BEFORE any
# entry point's first device touch (the env var alone does not stop a
# sitecustomize-registered TPU plugin from initializing — and hanging —
# on a wedged tunnel; see utils/backend_probe.py).  Code that changes
# JAX_PLATFORMS at runtime (bench's CPU fallback) re-pins itself.
from pcg_mpi_solver_tpu.utils.backend_probe import pin_cpu_backend_if_requested

pin_cpu_backend_if_requested()

# jax < 0.5 ships shard_map under jax.experimental with check_rep instead
# of check_vma; alias the modern spelling so all call sites run unchanged.
# Importing the package must NOT itself import jax (bench.py configures
# the accelerator env after importing obs/, and the wedged-tunnel CPU pin
# relies on env ordering) — so only patch here if jax is already loaded;
# the jax-importing root modules (ops/matvec.py, parallel/mesh.py) install
# the alias for every other path.
import sys as _sys

if "jax" in _sys.modules:
    from pcg_mpi_solver_tpu.utils.compat import ensure_shard_map

    ensure_shard_map()

from pcg_mpi_solver_tpu.config import SolverConfig, TimeHistoryConfig, RunConfig

__all__ = [
    "SolverConfig",
    "TimeHistoryConfig",
    "RunConfig",
    "__version__",
]
