"""Admission control + bounded-queue backpressure for the solve service
(ISSUE 19 tentpole).

Every admission is PRICED with the PR 12 analytic cost model: the
solver's roofline-predicted ms/iter at the service's widest standard
block width x the expected iteration count is the predicted wall a job
will wait+run, judged against the job's deadline — infeasible jobs are
rejected at the door with the named ``deadline_infeasible`` reason
instead of admitted into certain SLO violation.  A degraded model
(exotic platform, no profile) prices as None and ADMITS: pricing is an
observability-derived optimization, never a solve gate.

The queue is BOUNDED (``queue_max``).  When an arrival finds it full,
backpressure sheds the oldest already-past-deadline queued job first
(``job_shed`` event + journal record + result file — never silent); if
nothing is sheddable the arrival itself is rejected ``queue_full``.

Every decision outcome — accept, reject, shed — emits a
schema-versioned telemetry event (obs/schema.py: ``job_admit`` /
``job_reject`` / ``job_shed``), which the analysis/
``serve-admission-events`` fast rule statically enforces against THIS
module.

Import-light by contract (no jax/numpy): admission logic unit-tests in
milliseconds with a stub pricer.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

#: Named rejection reasons (the full vocabulary — tests and the RUNBOOK
#: table key off these strings).
REJECT_DEADLINE = "deadline_infeasible"
REJECT_QUEUE_FULL = "queue_full"
REJECT_DRAINING = "draining"
SHED_PAST_DEADLINE = "past_deadline_backpressure"


def price_admission(predicted_ms_per_iter: Optional[float],
                    expected_iters: int) -> Optional[float]:
    """Predicted seconds to serve one block: cost-model ms/iter x the
    expected iteration count.  None (model unavailable) means the
    pricing cannot reject — admission degrades open, loudly."""
    if predicted_ms_per_iter is None:
        return None
    return float(predicted_ms_per_iter) * max(1, int(expected_iters)) \
        / 1e3


class AdmissionController:
    """Bounded admission queue with cost-model pricing and load-shedding
    backpressure.

    ``pricer(nrhs) -> ms_per_iter | None`` is the cost-model hook (the
    daemon passes ``Solver.predicted_ms_per_iter``); ``journal`` and
    ``recorder`` take the durable record and the telemetry event of
    every decision.  The controller owns ordinals (continuing the
    journal's numbering via ``ordinal0``) and the queue; the daemon owns
    dispatch.
    """

    def __init__(self, queue_max: int, *, pricer: Callable, journal,
                 recorder, expected_iters: int, price_width: int = 1,
                 ordinal0: int = 0,
                 on_shed: Optional[Callable] = None):
        self.queue_max = max(1, int(queue_max))
        self._pricer = pricer
        self._journal = journal
        self._rec = recorder
        self.expected_iters = max(1, int(expected_iters))
        self.price_width = max(1, int(price_width))
        self._next_ordinal = int(ordinal0)
        self._on_shed = on_shed      # daemon hook: result file per shed
        self.queue: List[Dict[str, Any]] = []
        self.depth_max = 0
        self.shed_count = 0
        self.draining = False

    # -- decisions ------------------------------------------------------
    def admit(self, spec: Dict[str, Any],
              now: Optional[float] = None) -> Tuple[str, Any]:
        """One admission decision for a validated spec: ``("admitted",
        entry)`` or ``("rejected", reason)``.  Every path journals and
        emits — no silent outcome exists."""
        now = time.time() if now is None else now
        job = spec["job"]
        if self.draining:
            return self._reject(job, REJECT_DRAINING)
        deadline_s = float(spec.get("deadline_s", 0.0))
        predicted_s = price_admission(self._pricer(self.price_width),
                                      self.expected_iters)
        if predicted_s is not None and predicted_s > deadline_s:
            return self._reject(
                job, REJECT_DEADLINE,
                predicted_s=round(predicted_s, 6), deadline_s=deadline_s)
        if len(self.queue) >= self.queue_max:
            self.shed_past_deadline(now)
            if len(self.queue) >= self.queue_max:
                return self._reject(job, REJECT_QUEUE_FULL,
                                    queue_depth=len(self.queue))
        entry = {"job": job, "spec": dict(spec),
                 "ordinal": self._next_ordinal,
                 "deadline_t": now + deadline_s, "admit_t": now}
        self._next_ordinal += 1
        self.queue.append(entry)
        self.depth_max = max(self.depth_max, len(self.queue))
        self._journal.record("admitted", job, spec=entry["spec"],
                             ordinal=entry["ordinal"],
                             deadline_t=entry["deadline_t"])
        self._rec.event("job_admit", job=job, ordinal=entry["ordinal"],
                        predicted_s=predicted_s, deadline_s=deadline_s)
        return "admitted", entry

    def requeue(self, entry: Dict[str, Any]) -> None:
        """Journal replay re-enqueues an already-admitted job with its
        ORIGINAL ordinal/deadline — no second ``admitted`` record, no
        second pricing: the admission already happened and survived the
        crash."""
        self.queue.append(dict(entry))
        self.queue.sort(key=lambda e: e["ordinal"])
        self.depth_max = max(self.depth_max, len(self.queue))
        self._next_ordinal = max(self._next_ordinal,
                                 int(entry["ordinal"]) + 1)

    def shed_past_deadline(self, now: Optional[float] = None
                           ) -> List[Dict[str, Any]]:
        """Backpressure: drop queued jobs already past their deadline,
        oldest first, each with the named ``job_shed`` reason (journal
        record + event; the daemon writes their result files).  Returns
        the shed entries."""
        now = time.time() if now is None else now
        keep, shed = [], []
        for e in sorted(self.queue, key=lambda e: e["ordinal"]):
            (shed if e["deadline_t"] < now else keep).append(e)
        if shed:
            self.queue = keep
            self.shed_count += len(shed)
            for e in shed:
                self._journal.record("shed", e["job"],
                                     reason=SHED_PAST_DEADLINE,
                                     ordinal=e["ordinal"])
                self._rec.event("job_shed", job=e["job"],
                                reason=SHED_PAST_DEADLINE)
                if self._on_shed is not None:
                    self._on_shed(e, SHED_PAST_DEADLINE)
        return shed

    def _reject(self, job: str, reason: str, **fields) -> Tuple[str, str]:
        self._journal.record("rejected", job, reason=reason, **fields)
        self._rec.event("job_reject", job=job, reason=reason, **fields)
        return "rejected", reason
