"""The solve-service daemon (ISSUE 19 tentpole): poll -> admit ->
pack -> dispatch, exactly once per job, forever (or until drained).

One :class:`ServeDaemon` owns one warm :class:`~pcg_mpi_solver_tpu.
solver.driver.Solver` and one spool directory.  The loop:

1. **poll** ``spool/incoming`` (serve/jobs.py) — validate each spec,
   drop duplicates the journal already knows (crash remnants / double
   submissions), and push the rest through admission control
   (serve/admission.py: cost-model pricing, bounded queue, shedding);
2. **pack** compatible queued jobs into an nrhs block of standard
   width (serve/packer.py) and journal the ``packed`` bracket;
3. **dispatch** the block through ``Solver.solve_many`` — the PR 8
   per-column recovery/quarantine path, so one tenant's poisoned RHS
   quarantines ALONE while its co-batched tenants finish unharmed;
4. **finish** each job: atomic result file FIRST, then the terminal
   journal record (``done``/``failed``) — the crash-ordering contract
   that makes replay exactly-once.

**Crash durability**: every lifecycle transition is an fsync'd journal
record (serve/journal.py).  On startup :meth:`ServeDaemon` replays the
journal — terminal jobs stay terminal, a dispatched-but-unrecorded job
whose result file survived is completed from it (``replayed=true``),
anything else re-enqueues with its ORIGINAL ordinal and deadline.  A
SIGKILL therefore never loses a job and never solves one twice.

**Faults**: the ``@job:`` domain of resilience/faultinject.py fires at
the service boundary per absolute admission ordinal (``exc@job:k``
fails the job with a named verdict, ``nan@job:k`` poisons its RHS
column so quarantine isolation is exercised end-to-end, ``sleep@job:k``
delays the block).  Replay pre-consumes ordinals the journal shows as
already dispatched/terminal, so a restart never re-fires a fault a
previous daemon generation already consumed.

**Signals**: SIGTERM flips admission into draining (new arrivals
rejected ``draining``), finishes every in-flight/queued block, stamps
the ``drain`` journal record + ``serve_drain`` event and exits clean.
SIGKILL is the chaos case the journal exists for.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Dict, List, Optional

from pcg_mpi_solver_tpu.serve import jobs as sjobs
from pcg_mpi_solver_tpu.serve.admission import AdmissionController
from pcg_mpi_solver_tpu.serve.journal import (
    JobJournal, next_ordinal, read_journal, replay_jobs)
from pcg_mpi_solver_tpu.serve.packer import (
    STANDARD_WIDTHS, normalize_widths, pack_block)

DEFAULT_QUEUE_MAX = 16
DEFAULT_POLL_S = 0.05


class ServeDaemon:
    """Multi-tenant solve service over one warm solver + one spool.

    ``solver`` must already be constructed (operator partitioned and
    resident); ``spool`` is the filesystem protocol root.  ``run()``
    is the loop; ``poll_once()`` + ``serve_block()`` are the testable
    single steps.  Construction replays the journal, so building a
    daemon over a crashed spool IS the recovery procedure.
    """

    def __init__(self, solver, spool: str, *,
                 queue_max: int = DEFAULT_QUEUE_MAX,
                 widths=STANDARD_WIDTHS,
                 expected_iters: Optional[int] = None,
                 fault_plan=None,
                 poll_s: float = DEFAULT_POLL_S,
                 journal_fsync: Optional[bool] = None):
        self.solver = solver
        self.spool = spool
        sjobs.ensure_spool(spool)
        self._rec = solver.recorder
        self.widths = normalize_widths(widths)
        self.poll_s = float(poll_s)
        self.journal = JobJournal(sjobs.journal_path(spool),
                                  fsync=journal_fsync)
        if fault_plan is None:
            from pcg_mpi_solver_tpu.resilience import FaultPlan

            fault_plan = FaultPlan.from_env(recorder=self._rec)
        self.fault_plan = fault_plan
        if expected_iters is None:
            # conservative default: a job must be feasible even if it
            # runs to the iteration cap (admission prices worst case)
            expected_iters = int(solver.config.solver.max_iter)
        self.admission = AdmissionController(
            queue_max, pricer=solver.predicted_ms_per_iter,
            journal=self.journal, recorder=self._rec,
            expected_iters=expected_iters,
            price_width=max(self.widths),
            on_shed=self._finish_shed)
        self.jobs_done = 0
        self.jobs_failed = 0
        self.blocks = 0
        self._seen: set = set()      # every job id the journal knows
        self._drain_requested = False
        self._replay()

    # -- replay ---------------------------------------------------------
    def _replay(self) -> None:
        """Fold the journal into queue + seen-set + fault state: the
        exactly-once restart path (no-op on a fresh spool)."""
        events, truncated = read_journal(self.journal.path)
        states = replay_jobs(events)
        if truncated:
            self._rec.note(f"serve journal: {truncated} torn line(s) "
                           f"skipped (crash artifact)")
        self.admission._next_ordinal = next_ordinal(states)
        plan = self.fault_plan
        for st in sorted(states.values(),
                         key=lambda s: (s["ordinal"] is None,
                                        s["ordinal"] or 0)):
            job = st["job"]
            self._seen.add(job)
            ordinal = st["ordinal"]
            if st["terminal"]:
                # a consumed service-boundary fault must not re-fire
                if plan is not None and isinstance(ordinal, int):
                    plan.replay_consume_job(ordinal)
                continue
            if plan is not None and isinstance(ordinal, int) \
                    and "dispatched" in st["ops"]:
                plan.replay_consume_job(ordinal)
            result = sjobs.read_result(self.spool, job)
            if result is not None:
                # crashed AFTER the result write but BEFORE the
                # terminal record: complete from the result, never
                # re-solve (the exactly-once ordering contract)
                ok = bool(result.get("ok"))
                verdict = result.get("verdict", "unknown")
                self.journal.record("done" if ok else "failed", job,
                                    verdict=verdict, replayed=True)
                self._rec.event("job_done", job=job, ok=ok,
                                verdict=verdict, replayed=True)
                self._count_finish(ok)
                continue
            if st["spec"] is None or ordinal is None:
                self._finish_failed(
                    {"job": job, "ordinal": -1},
                    "replay_unrecoverable: admitted record incomplete")
                continue
            self.admission.requeue({
                "job": job, "spec": st["spec"], "ordinal": ordinal,
                "deadline_t": st["deadline_t"] or 0.0,
                "admit_t": st["deadline_t"] or 0.0})
        if self.admission.queue:
            self._rec.note(f"serve replay: {len(self.admission.queue)} "
                           f"job(s) re-enqueued from journal")

    # -- admission ------------------------------------------------------
    def poll_once(self, now: Optional[float] = None) -> int:
        """One incoming-directory sweep; returns the number of jobs
        admitted.  Every file is consumed with a journaled outcome —
        admitted, rejected (named reason) or duplicate-dropped."""
        admitted = 0
        for path, spec in sjobs.list_incoming(self.spool):
            job = ((spec or {}).get("job")
                   or os.path.basename(path)[:-len(".json")])
            if not isinstance(job, str) or not job:
                job = os.path.basename(path)[:-len(".json")]
            if job in self._seen:
                # journal already knows this id (crash remnant of a
                # consumed submission, or a double submit): exactly-
                # once means the file is dropped, not re-admitted
                self._unlink(path)
                continue
            err = ("bad_spec: unreadable/unparseable file"
                   if spec is None else sjobs.check_spec(spec))
            self._seen.add(job)
            if err:
                self.journal.record("rejected", job, reason=err)
                self._rec.event("job_reject", job=job, reason=err)
                sjobs.write_result(self.spool, job,
                                   {"ok": False,
                                    "verdict": f"rejected: {err}"})
                self._unlink(path)
                continue
            verdict, out = self.admission.admit(spec, now=now)
            if verdict == "admitted":
                admitted += 1
            else:
                sjobs.write_result(self.spool, job,
                                   {"ok": False,
                                    "verdict": f"rejected: {out}"})
            self._unlink(path)
        return admitted

    def _unlink(self, path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass     # already consumed by a racing sweep — harmless

    # -- dispatch -------------------------------------------------------
    def serve_block(self) -> int:
        """Pack + dispatch ONE block off the queue; returns the number
        of jobs it consumed (0 when idle)."""
        block = pack_block(self.admission.queue, self.widths)
        if not block:
            return 0
        blk = self.blocks
        self.blocks += 1
        self.journal.record("packed", None, block=blk,
                            jobs=[e["job"] for e in block],
                            ordinals=[e["ordinal"] for e in block],
                            width=len(block))
        self._dispatch_block(block, blk)
        return len(block)

    def _dispatch_block(self, block: List[Dict[str, Any]],
                        blk: int) -> None:
        """One packed block through ``Solver.solve_many`` (the PR 8
        per-column recovery path — registered as a dispatch surface in
        analysis/rules_ast.RECOVERY_SURFACES, so the harness-coverage
        lint proves this stays the one way jobs reach the solver)."""
        import numpy as np

        from pcg_mpi_solver_tpu.resilience.faultinject import (
            InjectedDispatchError)
        from pcg_mpi_solver_tpu.solver.pcg import QUARANTINE_FLAG

        t0 = time.monotonic()
        # service-boundary faults: per-job, by absolute ordinal
        plan, poison, live = self.fault_plan, set(), []
        for e in block:
            if plan is not None and plan.job_armed:
                try:
                    p = plan.at_job(e["ordinal"])
                except InjectedDispatchError as exc:
                    self._finish_failed(e, f"injected: {exc}", block=blk)
                    continue
                if p == "nan":
                    poison.add(e["job"])
            live.append(e)
        # build the RHS block; a bad column fails ITS job only
        cols, kept = [], []
        for e in live:
            try:
                col = self._rhs_column(e["spec"])
            except (OSError, ValueError) as exc:
                self._finish_failed(
                    e, f"rhs_load_failed: {type(exc).__name__}: {exc}",
                    block=blk)
                continue
            if e["job"] in poison:
                col = col * np.nan     # injected tenant poison
            if not np.isfinite(col).all():
                # service-boundary quarantine: solve_many's preflight
                # rejects a non-finite column by failing the WHOLE
                # block — one tenant's poison must not do that, so the
                # daemon screens per column and quarantines it alone
                self._rec.event("job_quarantine", job=e["job"],
                                verdict="rhs_nonfinite")
                self._finish_failed(e, "rhs_nonfinite", block=blk)
                continue
            cols.append(col)
            kept.append(e)
        if not kept:
            return
        fb = np.stack(cols, axis=-1)
        self.journal.record("dispatched", None, block=blk,
                            jobs=[e["job"] for e in kept],
                            width=len(kept))
        try:
            res = self.solver.solve_many(fb)
        except Exception as exc:                       # noqa: BLE001
            # whole-block dispatch failure (compile error, device loss
            # past the recovery ladder): every co-batched job fails
            # with a NAMED verdict — never a silent drop
            self._rec.note(f"serve block {blk} dispatch failed: "
                           f"{type(exc).__name__}: {exc}")
            for e in kept:
                self._finish_failed(
                    e, f"dispatch_failed: {type(exc).__name__}: {exc}",
                    block=blk)
            return
        u = self.solver.displacement_global_many(res.x)
        wall = time.monotonic() - t0
        now = time.time()
        for j, e in enumerate(kept):
            flag = int(res.flags[j])
            quarantined = (j in tuple(res.quarantined)
                           or flag == QUARANTINE_FLAG)
            ok = flag == 0
            verdict = ("converged" if ok
                       else "quarantined" if quarantined
                       else f"flag{flag}")
            result = {"ok": ok, "verdict": verdict, "flag": flag,
                      "relres": float(res.relres[j]),
                      "iters": int(res.iters[j]),
                      "block": blk, "width": len(kept),
                      "wall_s": round(wall, 6),
                      "deadline_met": now <= float(e["deadline_t"])}
            # solution first (even quarantined jobs get their min-
            # residual iterate), then result json, then the terminal
            # record: replay's crash-ordering contract
            np.save(sjobs.solution_path(self.spool, e["job"]), u[:, j])
            sjobs.write_result(self.spool, e["job"], result)
            if quarantined:
                self._rec.event("job_quarantine", job=e["job"],
                                verdict=verdict, rhs=j)
            self.journal.record("done" if ok else "failed", e["job"],
                                verdict=verdict, block=blk)
            self._rec.event("job_done", job=e["job"], ok=ok,
                            verdict=verdict)
            self._count_finish(ok)

    def _rhs_column(self, spec: Dict[str, Any]):
        """One (n_dof,) load column from a validated spec: ``scale`` x
        the model's reference load, or an ``rhs`` .npy path."""
        import numpy as np

        n_dof = int(self.solver._model.n_dof)
        if spec.get("rhs"):
            col = np.asarray(np.load(spec["rhs"]), dtype=np.float64)
            col = col.reshape(-1)
            if col.shape[0] != n_dof:
                raise ValueError(
                    f"rhs length {col.shape[0]} != n_dof {n_dof}")
            return col
        return (np.asarray(self.solver._model.F, dtype=np.float64)
                * float(spec["scale"]))

    # -- finishing ------------------------------------------------------
    def _count_finish(self, ok: bool) -> None:
        if ok:
            self.jobs_done += 1
        else:
            self.jobs_failed += 1

    def _finish_failed(self, entry: Dict[str, Any], verdict: str,
                       block: Optional[int] = None) -> None:
        """Terminal failure with a named verdict: result file first,
        then journal record + ``job_done`` event (ok=false)."""
        job = entry["job"]
        sjobs.write_result(self.spool, job,
                           {"ok": False, "verdict": verdict})
        fields = {"verdict": verdict}
        if block is not None:
            fields["block"] = block
        self.journal.record("failed", job, **fields)
        self._rec.event("job_done", job=job, ok=False, verdict=verdict)
        self._count_finish(False)

    def _finish_shed(self, entry: Dict[str, Any], reason: str) -> None:
        """Admission's shed hook: the journal record + ``job_shed``
        event already happened inside the controller — the daemon adds
        the result file (shed is terminal; the submitter must see it)."""
        sjobs.write_result(self.spool, entry["job"],
                           {"ok": False, "verdict": f"shed: {reason}"})

    # -- the loop -------------------------------------------------------
    def request_drain(self, *_args) -> None:
        """SIGTERM handler (also callable directly): reject new
        admissions from now on, finish what is queued, then exit."""
        self._drain_requested = True
        self.admission.draining = True

    def run(self, max_blocks: Optional[int] = None,
            idle_exit_s: Optional[float] = None,
            install_signals: bool = True) -> str:
        """Serve until drained; returns the drain reason.

        ``max_blocks`` bounds the dispatch count (bench/test knob);
        ``idle_exit_s`` drains after that long with an empty queue and
        empty incoming dir (smoke/chaos knob — None serves forever);
        ``install_signals`` wires SIGTERM to the graceful drain (off
        when the daemon runs inside a test's main thread is not
        available)."""
        if install_signals:
            try:
                signal.signal(signal.SIGTERM, self.request_drain)
            except ValueError:
                self._rec.note("serve: not main thread, SIGTERM "
                               "handler not installed")
        last_work = time.monotonic()
        reason = "drained"
        while True:
            admitted = self.poll_once()
            served = self.serve_block() if self.admission.queue else 0
            if admitted or served:
                last_work = time.monotonic()
            if max_blocks is not None and self.blocks >= max_blocks:
                reason = "max_blocks"
                break
            if served:
                continue
            if self._drain_requested:
                reason = "sigterm"
                break
            if (idle_exit_s is not None
                    and time.monotonic() - last_work >= idle_exit_s):
                reason = "idle"
                break
            time.sleep(self.poll_s)
        # drain: reject any straggler submissions by name, then stamp
        # the drain record inside the still-open serve bracket
        self.admission.draining = True
        self.poll_once()
        if self.admission.queue:
            self._rec.note(
                f"serve drain: {len(self.admission.queue)} admitted "
                f"job(s) left queued (journal replays them on restart)")
        self.journal.drain(reason, jobs_done=self.jobs_done,
                           jobs_failed=self.jobs_failed,
                           jobs_shed=self.admission.shed_count,
                           blocks=self.blocks)
        self._rec.event("serve_drain", reason=reason,
                        jobs_done=self.jobs_done,
                        jobs_failed=self.jobs_failed,
                        jobs_shed=self.admission.shed_count)
        self.journal.close()
        return reason
