"""Crash-durable job journal: the exactly-once backbone of the solve
service (ISSUE 19).

Rides the PR 12 flight-recorder idiom (obs/flight.py) rather than
reinventing it: every journal record is an fsync'd ``kind="flight"``
telemetry event, so a SIGKILL loses AT MOST the record being written,
every JSONL consumer (``pcg-tpu summary`` / ``watch``) can ingest the
journal, and the daemon's liveness heartbeats come for free from the
recorder's open ``serve`` bracket.  Job records add ``op`` (the
lifecycle bracket) + ``job`` (the id) + ``journal`` (this module's own
schema tag, versioned independently of the telemetry schema).

Lifecycle ops (:data:`JOB_OPS`)::

    admitted --> packed --> dispatched --> done
        \\                               \\-> failed
         \\-> shed          (queue backpressure, named reason)
    rejected                (never admitted, named reason)

The ``admitted`` record carries the FULL job spec and the absolute
admission ordinal, so replay needs nothing but the journal: a job whose
newest op is non-terminal is re-enqueued with its original ordinal and
deadline; a job whose result file exists but whose ``done`` record was
lost to the kill is completed from the result (``replayed=true``),
never re-solved — no loss, no double-completion (the exactly-once
contract ``tests/test_serve.py`` SIGKILLs its way through).

Import-light by contract (no jax/numpy): replay and the unit tests run
without an accelerator environment.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from pcg_mpi_solver_tpu.obs.flight import FlightRecorder, read_jsonl_tolerant

#: Versioned journal schema tag carried by every job record (bump the
#: suffix on a BREAKING change; additive fields do not bump).
SERVE_JOURNAL_SCHEMA = "pcg-tpu-serve-journal/1"

#: Job lifecycle ops, in bracket order.
JOB_OPS = ("admitted", "packed", "dispatched", "done", "failed",
           "rejected", "shed")

#: Ops after which a job must never run (or run again).
TERMINAL_OPS = ("done", "failed", "rejected", "shed")

#: Daemon lifecycle op: graceful drain record (SIGTERM / idle exit).
DRAIN_OP = "drain"


class JobJournal:
    """fsync-per-record append-only job journal over one
    :class:`~pcg_mpi_solver_tpu.obs.flight.FlightRecorder`.

    Opening the journal opens a ``serve`` flight bracket, so heartbeats
    flow while the daemon lives — ``pcg-tpu watch`` gets its stall
    detector over daemon death for free.  A SIGKILL leaves the bracket
    unclosed (the ``died`` flight verdict); :meth:`close` on a graceful
    drain closes it and stamps the :data:`DRAIN_OP` record first.
    """

    def __init__(self, path: str, fsync: Optional[bool] = None):
        self.path = path
        self._fl = FlightRecorder(
            path, meta={"component": "serve",
                        "journal": SERVE_JOURNAL_SCHEMA},
            fsync=fsync)
        self._seq = self._fl.begin("serve")

    def record(self, op: str, job: Optional[str] = None,
               **fields) -> Dict[str, Any]:
        """Write ONE durable journal record (flush + fsync before the
        call returns — the crash-ordering contract replay depends on)."""
        if job is not None:
            fields["job"] = job
        return self._fl.emit(op, journal=SERVE_JOURNAL_SCHEMA, **fields)

    def drain(self, reason: str, **fields) -> None:
        """Stamp the graceful-drain record (still inside the ``serve``
        bracket, so it is fsync'd before the bracket closes)."""
        self.record(DRAIN_OP, reason=reason, **fields)

    def close(self) -> None:
        self._fl.end(self._seq, "serve")
        self._fl.close()


def read_journal(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Tolerant read of a journal file: ``(events, truncated_count)``.
    The exact artifact a SIGKILLed daemon leaves may end in a line cut
    mid-object — skipped and counted, never raised on (the
    obs/flight.py reader contract)."""
    return read_jsonl_tolerant(path)


def replay_jobs(events: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Fold journal events into per-job final states.

    Returns ``{job_id: state}`` where ``state`` carries ``op`` (the
    newest lifecycle op), ``ops`` (the full op history, replay-audit
    order), ``spec`` / ``ordinal`` / ``deadline_t`` (from the
    ``admitted`` record), ``terminal`` and ``verdict``.  Tolerates
    anything: non-job records, unknown ops and jobs admitted by a
    previous daemon generation all fold in order."""
    jobs: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        op = ev.get("op")
        job = ev.get("job")
        if op not in JOB_OPS or not isinstance(job, str):
            continue
        st = jobs.setdefault(job, {"job": job, "ops": [], "op": None,
                                   "spec": None, "ordinal": None,
                                   "deadline_t": None, "terminal": False,
                                   "verdict": None})
        st["ops"].append(op)
        st["op"] = op
        if op == "admitted":
            st["spec"] = ev.get("spec")
            if isinstance(ev.get("ordinal"), int):
                st["ordinal"] = ev["ordinal"]
            if isinstance(ev.get("deadline_t"), (int, float)):
                st["deadline_t"] = float(ev["deadline_t"])
        if op in TERMINAL_OPS:
            st["terminal"] = True
            st["verdict"] = ev.get("verdict", ev.get("reason"))
    return jobs


def next_ordinal(jobs: Dict[str, Dict[str, Any]]) -> int:
    """The next absolute admission ordinal: ordinals NEVER reset across
    daemon restarts (the ``@job:`` fault domain and replay both index
    by them), so a fresh daemon continues the journal's numbering."""
    taken = [st["ordinal"] for st in jobs.values()
             if isinstance(st.get("ordinal"), int)]
    return max(taken) + 1 if taken else 0
