"""BENCH_SERVE leg (ISSUE 19): sustained solve-service throughput —
saturated queue (nrhs packing engaged) vs one-at-a-time dispatch.

Run via the bench harness front door::

    BENCH_SERVE=1 python -m pcg_mpi_solver_tpu.bench

Both phases serve the SAME jobs through the SAME warm solver from a
fresh spool each: the serial phase pins the width set to {1} (every job
its own dispatch — the no-service baseline an operator would script),
the saturated phase submits everything up front and lets the packer
co-batch into the standard widths.  All engaged block widths are warmed
(compiled) before either timer starts, so the line measures service
throughput, not compile walls.

Emits one schema-versioned bench line — ``metric=serve_jobs_per_s``,
``vs_baseline`` = saturated/serial — stamping the typed detail fields
``jobs_per_s`` / ``jobs_per_s_serial`` / ``queue_depth_max`` /
``jobs_shed`` (obs/schema.py BENCH_DETAIL_NUMERIC: present on this leg,
ABSENT — not null — on every other), and writes the artifact to
``$BENCH_SERVE_OUT`` (default BENCH_SERVE.json).

Knobs: ``BENCH_SERVE_NX`` (cube dims, default ``6,5,5``),
``BENCH_SERVE_JOBS`` (job count per phase, default 12),
``BENCH_SERVE_WIDTHS`` (packed widths, default ``1,2,4,8``).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def _log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def _serve_phase(solver, n_jobs: int, widths, deadline_s: float) -> dict:
    """Submit ``n_jobs`` scale-ramp jobs into a fresh spool, serve them
    to drain, return the phase numbers.  Jobs are pre-submitted
    (saturated arrival) so the queue — not the submitter — paces the
    daemon."""
    from pcg_mpi_solver_tpu.serve import jobs as sjobs
    from pcg_mpi_solver_tpu.serve.daemon import ServeDaemon

    spool = tempfile.mkdtemp(prefix="pcg_bench_serve_")
    for i in range(n_jobs):
        sjobs.submit(spool, {"scale": 1.0 + 0.1 * i,
                             "deadline_s": deadline_s},
                     submit_t=float(i))
    daemon = ServeDaemon(solver, spool, queue_max=n_jobs + 2,
                         widths=widths, fault_plan=None, poll_s=0.001)
    t0 = time.perf_counter()
    daemon.run(idle_exit_s=0.0, install_signals=False)
    wall = time.perf_counter() - t0
    out = {"wall_s": wall, "jobs_done": daemon.jobs_done,
           "jobs_failed": daemon.jobs_failed,
           "jobs_shed": daemon.admission.shed_count,
           "queue_depth_max": daemon.admission.depth_max,
           "blocks": daemon.blocks,
           "jobs_per_s": daemon.jobs_done / max(wall, 1e-9)}
    import shutil

    shutil.rmtree(spool, ignore_errors=True)
    return out


def main() -> int:
    import numpy as np

    from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig
    from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
    from pcg_mpi_solver_tpu.obs.schema import BENCH_SCHEMA
    from pcg_mpi_solver_tpu.serve.packer import normalize_widths, pick_width
    from pcg_mpi_solver_tpu.solver.driver import Solver

    dims = [int(v) for v in
            os.environ.get("BENCH_SERVE_NX", "6,5,5").split(",")]
    dims += [0] * (3 - len(dims))
    n_jobs = int(os.environ.get("BENCH_SERVE_JOBS", 12))
    widths = normalize_widths(
        int(v) for v in
        os.environ.get("BENCH_SERVE_WIDTHS", "1,2,4,8").split(","))
    out_path = os.environ.get("BENCH_SERVE_OUT", "BENCH_SERVE.json")

    model = make_cube_model(dims[0], dims[1], dims[2],
                            heterogeneous=True)
    cfg = RunConfig(solver=SolverConfig(tol=1e-8, max_iter=2000))
    _log(f"serve bench: {model.n_dof} dofs, {n_jobs} jobs, "
         f"widths {widths}")
    solver = Solver(model, cfg, backend="general")

    # warm every width either phase can engage BEFORE any timer: the
    # line is service throughput, not the AOT compile wall
    warm = set()
    left = n_jobs
    while left > 0:
        w = pick_width(left, widths)
        warm.add(w)
        left -= w
    warm.add(1)
    f = np.asarray(model.F, dtype=np.float64)
    for w in sorted(warm):
        _log(f"warming width {w}")
        solver.solve_many(np.stack([f] * w, axis=-1))

    serial = _serve_phase(solver, n_jobs, (1,), deadline_s=3600.0)
    _log(f"serial: {serial['jobs_done']} jobs in "
         f"{serial['wall_s']:.3f}s ({serial['jobs_per_s']:.2f} jobs/s)")
    packed = _serve_phase(solver, n_jobs, widths, deadline_s=3600.0)
    _log(f"saturated: {packed['jobs_done']} jobs in "
         f"{packed['wall_s']:.3f}s ({packed['jobs_per_s']:.2f} jobs/s), "
         f"{packed['blocks']} block(s), "
         f"depth_max {packed['queue_depth_max']}")

    line = {
        "schema": BENCH_SCHEMA,
        "metric": "serve_jobs_per_s",
        "value": round(packed["jobs_per_s"], 3),
        "unit": "jobs/s",
        "vs_baseline": round(packed["jobs_per_s"]
                             / max(serial["jobs_per_s"], 1e-9), 3),
        "detail": {
            "jobs_per_s": round(packed["jobs_per_s"], 3),
            "jobs_per_s_serial": round(serial["jobs_per_s"], 3),
            "queue_depth_max": packed["queue_depth_max"],
            "jobs_shed": packed["jobs_shed"],
            "n_jobs": n_jobs,
            "n_dof": int(model.n_dof),
            "nrhs": max(warm),
            "blocks": packed["blocks"],
            "blocks_serial": serial["blocks"],
            "predicted_ms_per_iter": solver.predicted_ms_per_iter(
                max(warm)),
            "pcg_variant": cfg.solver.pcg_variant,
            "precond": cfg.solver.precond,
        },
    }
    print(json.dumps(line), flush=True)
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(line, fh, indent=1)
        _log(f"artifact written: {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
