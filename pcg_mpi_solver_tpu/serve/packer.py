"""nrhs block packer: co-batch compatible jobs into standard widths.

The AOT program cache (cache/, PR 6) is keyed per block width, so an
arbitrary width would compile a fresh program per queue depth — the
service instead packs from a SMALL set of standard widths and pays at
most ``len(widths)`` compiles over the daemon's lifetime, all warm
after the first block of each width.

Packing is FIFO by admission ordinal (a deadline scheduler would
re-order; the admission pricing already guaranteed each admitted job's
deadline is feasible, so fairness-by-arrival is the simplest policy
that cannot starve).  Import-light by contract (no jax/numpy).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

#: Default standard block widths.  1 MUST be a member (a lone pending
#: job must always be packable); powers of two match the AOT cache's
#: per-nrhs keying and bound the compile count.
STANDARD_WIDTHS = (1, 2, 4, 8)


def normalize_widths(widths: Sequence[int]) -> tuple:
    """Sorted, deduplicated, 1-inclusive widths (1 is forced in: a
    width set without it would strand a single pending job forever)."""
    ws = sorted({int(w) for w in widths if int(w) >= 1} | {1})
    return tuple(ws)


def pick_width(n_pending: int, widths: Sequence[int] = STANDARD_WIDTHS
               ) -> int:
    """Largest standard width <= the pending count (0 when idle)."""
    if n_pending <= 0:
        return 0
    fit = [w for w in normalize_widths(widths) if w <= n_pending]
    return max(fit)


def pack_block(queue: List[Dict[str, Any]],
               widths: Sequence[int] = STANDARD_WIDTHS
               ) -> List[Dict[str, Any]]:
    """Pop the next block off the admission queue: the ``pick_width``
    oldest entries, by admission ordinal.  Mutates ``queue`` (the
    popped entries are the daemon's to journal as ``packed``)."""
    w = pick_width(len(queue), widths)
    if w == 0:
        return []
    queue.sort(key=lambda e: e["ordinal"])
    block = queue[:w]
    del queue[:w]
    return block
