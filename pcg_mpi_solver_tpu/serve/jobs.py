"""Job spool IO: the filesystem submission protocol of the solve
service (ISSUE 19).

A spool directory holds three things::

    spool/incoming/<job>.json    submitted specs (atomic tmp+rename)
    spool/results/<job>.json     outcomes, always with a named verdict
    spool/results/<job>.npy      the solution column (done jobs only)
    spool/journal.jsonl          the crash-durable job journal

Submission is ``write tmp -> os.replace``: the daemon's scan never sees
a half-written spec.  Results are written the same way, and ALWAYS
BEFORE the journal's terminal record — so a crash between the two is
replayed as "complete from the existing result", never as a re-solve
(the exactly-once ordering serve/journal.py documents).

A job spec is a plain dict::

    {"job": "a1b2c3", "scale": 0.5, "deadline_s": 60.0}
    {"job": "a1b2c3", "rhs": "/path/loads.npy", "deadline_s": 60.0}

``scale`` scales the model's reference load vector F (the solve-many
``--scales`` semantics); ``rhs`` names an (n_dof,) .npy column instead.
``deadline_s`` is RELATIVE at submission; admission converts it to the
absolute wall deadline it prices against.

Import-light by contract (no jax/numpy): submission must work from a
login node without the accelerator environment.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

INCOMING_DIR = "incoming"
RESULTS_DIR = "results"
JOURNAL_FILE = "journal.jsonl"

#: A spec may only carry these keys (forward compatibility lives in the
#: journal schema, not in free-form specs a typo'd submission could
#: smuggle past admission).
SPEC_KEYS = ("job", "scale", "rhs", "deadline_s", "submit_t")

DEFAULT_DEADLINE_S = 3600.0


def journal_path(spool: str) -> str:
    return os.path.join(spool, JOURNAL_FILE)


def incoming_dir(spool: str) -> str:
    return os.path.join(spool, INCOMING_DIR)


def results_dir(spool: str) -> str:
    return os.path.join(spool, RESULTS_DIR)


def ensure_spool(spool: str) -> None:
    os.makedirs(incoming_dir(spool), exist_ok=True)
    os.makedirs(results_dir(spool), exist_ok=True)


def new_job_id() -> str:
    return uuid.uuid4().hex[:12]


def write_json_atomic(path: str, obj: Any) -> None:
    """tmp + ``os.replace``: readers never observe a torn file (the
    same-directory rename is atomic on POSIX)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def check_spec(spec: Dict[str, Any]) -> Optional[str]:
    """Spec validation: the named ``bad_spec`` reason, or None when
    admissible.  Mirrors the preflight posture — reject with a reason
    the submitter can act on, never crash the daemon."""
    if not isinstance(spec, dict):
        return f"bad_spec: not an object ({type(spec).__name__})"
    unknown = sorted(set(spec) - set(SPEC_KEYS))
    if unknown:
        return f"bad_spec: unknown key(s) {', '.join(unknown)}"
    has_scale = isinstance(spec.get("scale"), (int, float))
    has_rhs = isinstance(spec.get("rhs"), str) and spec["rhs"]
    if has_scale == bool(has_rhs):
        return "bad_spec: exactly one of scale / rhs required"
    dl = spec.get("deadline_s", DEFAULT_DEADLINE_S)
    if not isinstance(dl, (int, float)) or dl <= 0:
        return f"bad_spec: deadline_s must be > 0 (got {dl!r})"
    return None


def submit(spool: str, spec: Dict[str, Any],
           submit_t: Optional[float] = None) -> str:
    """Atomically drop one job spec into ``spool/incoming``; returns the
    job id (generated when the spec carries none).  Raises ValueError on
    a spec admission would reject as ``bad_spec`` — the submitter finds
    out at submit time, not from a result file."""
    spec = dict(spec)
    spec.setdefault("job", new_job_id())
    spec.setdefault("deadline_s", DEFAULT_DEADLINE_S)
    spec["submit_t"] = float(time.time() if submit_t is None
                             else submit_t)
    err = check_spec(spec)
    if err:
        raise ValueError(f"submit: {err}")
    ensure_spool(spool)
    write_json_atomic(os.path.join(incoming_dir(spool),
                                   f"{spec['job']}.json"), spec)
    return spec["job"]


def list_incoming(spool: str) -> List[Tuple[str, Dict[str, Any]]]:
    """``(path, spec)`` for every readable incoming spec, oldest
    submission first (ties broken by job id, so admission order — and
    with it the ``@job:`` fault ordinals — is deterministic).  An
    unreadable/unparseable file is returned with ``spec=None`` so the
    daemon can reject it by name instead of skipping it silently."""
    d = incoming_dir(spool)
    try:
        names = sorted(n for n in os.listdir(d) if n.endswith(".json"))
    except OSError:
        return []
    out = []
    for name in names:
        path = os.path.join(d, name)
        try:
            with open(path, encoding="utf-8") as f:
                spec = json.load(f)
        except (OSError, ValueError):
            spec = None
        out.append((path, spec))
    out.sort(key=lambda ps: ((ps[1] or {}).get("submit_t", 0.0),
                             (ps[1] or {}).get("job", ps[0])))
    return out


def result_path(spool: str, job_id: str) -> str:
    return os.path.join(results_dir(spool), f"{job_id}.json")


def solution_path(spool: str, job_id: str) -> str:
    return os.path.join(results_dir(spool), f"{job_id}.npy")


def write_result(spool: str, job_id: str, result: Dict[str, Any]) -> None:
    """Atomic result drop.  MUST be called before the journal's terminal
    record for the job — replay completes a dispatched-but-unjournaled
    job from this file instead of re-solving it."""
    ensure_spool(spool)
    write_json_atomic(result_path(spool, job_id), dict(result, job=job_id))


def read_result(spool: str, job_id: str) -> Optional[Dict[str, Any]]:
    try:
        with open(result_path(spool, job_id), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
