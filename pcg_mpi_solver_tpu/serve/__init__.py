"""Multi-tenant solve service (ISSUE 19): admission control,
backpressure, nrhs packing and crash-durable exactly-once job execution
over the blocked solve engine.

The service is a filesystem protocol — no network dependency:

* ``spool/incoming/<job>.json`` — atomically-submitted job specs
  (``pcg-tpu submit``, :mod:`serve.jobs`);
* ``spool/results/<job>.json`` (+ ``.npy``) — atomically-written
  outcomes, ALWAYS carrying a named verdict (done, failed, rejected or
  shed — the no-silent-drops contract);
* ``spool/journal.jsonl`` — the fsync'd job journal
  (:mod:`serve.journal`, riding the PR 12 flight-recorder idiom):
  ``admitted``/``packed``/``dispatched``/``done``/``failed`` brackets
  whose replay gives exactly-once semantics across daemon death.

Layers: :mod:`serve.jobs` (spool IO), :mod:`serve.journal` (durable
journal + replay), :mod:`serve.admission` (cost-model pricing, bounded
queue, load shedding), :mod:`serve.packer` (standard nrhs widths),
:mod:`serve.daemon` (the loop: signals, dispatch through
``Solver.solve_many`` so PR 8 per-column quarantine isolates a
poisoned tenant).  Everything except the daemon is import-light (no
jax/numpy) so admission/journal logic is unit-testable in milliseconds.
"""

from pcg_mpi_solver_tpu.serve.admission import AdmissionController
from pcg_mpi_solver_tpu.serve.daemon import ServeDaemon
from pcg_mpi_solver_tpu.serve.journal import (
    JOB_OPS, SERVE_JOURNAL_SCHEMA, TERMINAL_OPS, JobJournal,
    read_journal, replay_jobs)
from pcg_mpi_solver_tpu.serve.packer import STANDARD_WIDTHS, pack_block

__all__ = [
    "AdmissionController", "JobJournal", "JOB_OPS", "SERVE_JOURNAL_SCHEMA",
    "ServeDaemon", "TERMINAL_OPS", "STANDARD_WIDTHS", "pack_block",
    "read_journal", "replay_jobs",
]
