"""Typed configuration for the framework.

Replaces the reference's three config mechanisms — positional argv, zlib-pickled
``GlobSettings.zpkl``/``ModelDataPaths.zpkl`` dicts, and hardcoded constants
(reference: src/solver/pcg_solver.py:113-139, examples/run_basic_script.bash:30-49)
— with one set of dataclasses.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

# ---------------------------------------------------------------------------
# Canonical PCG loop-formulation name set (SolverConfig.pcg_variant).
# THE single source every variant-name surface derives from, so an
# unknown variant fails loudly everywhere instead of silently falling
# through one layer's default:
#   * SolverConfig.__post_init__ (below) — config construction,
#   * solver/pcg.py VALID_PCG_VARIANTS — the loop builders,
#   * ops/matvec.py PCG_SCALAR_PSUMS — the collective contract table
#     (an import-time assert pins its keys to this tuple),
#   * cache/keys.py step_cache_key — AOT cache keying,
#   * cli.py --pcg-variant choices and bench.py BENCH_PCG_VARIANT.
# Lives here (not ops/) because this module is jax-free by contract and
# every one of those consumers may import it before the accelerator
# environment is configured.
PCG_VARIANTS = ("classic", "fused", "pipelined")

# Canonical preconditioner name set (SolverConfig.precond) — the same
# single-source discipline as PCG_VARIANTS above.  Derived consumers:
#   * ops/precond.py VALID_PRECONDS — the prec builders (an import-time
#     guard pins its value to this tuple),
#   * obs/perf.py — the analytic per-iteration cost model enumerates
#     PCG_VARIANTS x PRECONDS; an unknown name is a loud KeyError,
#   * analysis/ cost-model-completeness rule — proves that enumeration
#     is total,
#   * cli.py --precond choices.
# Lives here because this module is jax-free by contract and obs/ and
# cli.py may consume it before the accelerator environment is
# configured.
PRECONDS = ("jacobi", "block3", "mg")


@dataclasses.dataclass
class SolverConfig:
    """PCG solver parameters (reference SolverParam, pcg_solver.py:131-132)."""

    tol: float = 1e-7
    max_iter: int = 10000
    # Numerical precision of the solve.  The reference is float64 throughout.
    # precision_mode:
    #   "direct" — one PCG in `dtype` (use float64 for reference parity);
    #   "mixed"  — f32 Krylov iterations + f64 iterative-refinement restarts:
    #              reaches f64-grade residuals at f32/MXU speed (the TPU
    #              performance path).
    precision_mode: str = "direct"
    dtype: str = "float64"        # storage dtype: "float32" | "float64"
    dot_dtype: str = "float64"    # accumulation dtype for reductions
    inner_tol: float = 1e-5       # per-refinement-cycle residual reduction (mixed)
    # Mixed mode only, EXPERIMENTAL (default off): exit an f32 inner cycle
    # after this many consecutive iterations without a meaningful (0.1%)
    # new minimal residual, handing back to the f64 refinement early.
    # Off by default because CG's residual is non-monotone pre-
    # asymptotically — a short window false-triggers during healthy
    # convergence (measured: window 5 exits at iteration 1 on a 690-dof
    # cube) and each premature restart discards the Krylov space.  Enable
    # only with an on-hardware A/B at the target scale.
    mixed_plateau_window: int = 0
    # Mixed mode only, default OFF (0): progress-RATE exit for f32 inner
    # cycles.  Every `mixed_progress_window` iterations the MONOTONE
    # minimal residual is compared to a window ago; if the window
    # contracted it by less than 1/mixed_progress_ratio AND the cycle has
    # already contracted the (normalized) rhs by mixed_progress_min_gain,
    # the cycle exits to the f64 refinement restart.  The design target
    # was the f32-floor grind at 10.33M dofs (docs/BENCH_LOG.md), but the
    # first A/B at a scale where the exit actually FIRES measured it
    # NEGATIVE: 96^3 / 2.74M dofs mixed, window 150: 2486 total
    # iterations vs 2009 with the exit off (+24% — premature restarts
    # discard more Krylov progress than the grind they cut), identical
    # convergence otherwise; at 64^3 / 824k dofs the exit never fires
    # (bit-identical).  2026-08-01, examples/bench_progress_ab.py.
    # Kept as an opt-in knob for an on-hardware A/B at the true flagship
    # scale (BENCH_PROGRESS=150), where the floor-grind geometry differs.
    mixed_progress_window: int = 0
    mixed_progress_ratio: float = 0.7
    mixed_progress_min_gain: float = 30.0
    # MATLAB-pcg compatibility knobs (pcg_solver.py:399-404)
    max_stag_steps: int = 3
    # PCG loop formulation (solver/pcg.py):
    #   "classic" — the MATLAB-pcg-compatible loop: three serialized
    #               scalar/fused psums per iteration (rho+inf-prec, p.q,
    #               fused 3-norm).  Bit-exact reference parity; default.
    #   "fused"   — Chronopoulos–Gear single-reduction recurrence: rho,
    #               the p.q denominator, the residual norm, the
    #               stagnation norms and the inf-preconditioner flag all
    #               come from ONE fused psum per iteration, and A.p
    #               advances by recurrence (q = A.z + beta*q) so the
    #               stencil still runs once per iteration.  Cuts the
    #               per-iteration latency spent between the matvecs at
    #               scale (the BENCH_r05 profile: 24.994 ms/iter vs
    #               13.741 ms/matvec at 10.33M dofs).  Convergence
    #               checks lag the iterate by one iteration (the
    #               pipelined-CG tradeoff), so iteration counts differ
    #               from classic by O(1) and results are NOT bit-exact
    #               with the reference — see docs/RUNBOOK.md "Choosing
    #               pcg_variant".
    #   "pipelined" — Ghysels–Vanroose depth-1 pipelined CG
    #               (arXiv:2105.06176 §3): still ONE fused psum per
    #               iteration, but its operands are all PREVIOUS-
    #               iteration recurrence state, so the psum carries no
    #               data dependence on (and none from) the iteration's
    #               stencil matvec — XLA is free to run the reduction
    #               CONCURRENTLY with the matvec, hiding the last
    #               collective's latency entirely (statically proven by
    #               the analysis/ psum-overlap rule).  The price: four
    #               extra recurrence vectors in the carry (u/w/s/z) and
    #               faster residual-recurrence drift than fused
    #               (arXiv:2501.03743 §4) — guarded by a LOWER drift
    #               limit (solver/pcg.PIPELINED_DRIFT_LIMIT) feeding the
    #               same recoverable flag 6.  Iteration counts differ
    #               from classic by O(1); NOT bit-exact with the
    #               reference.  CLI: --pcg-variant; bench:
    #               BENCH_PCG_VARIANT.
    pcg_variant: str = "classic"
    # Default RHS-block width for batched multi-RHS solves
    # (Solver.solve_many / `pcg-tpu solve-many` / bench BENCH_NRHS): the
    # number of load cases solved together against ONE shared partitioned
    # operator, with a per-RHS convergence mask in the while-loop
    # predicate (solver/pcg.pcg_many).  The per-type element matmul
    # batches to (d x d) @ (d x N_elem x nrhs) — higher MXU utilization
    # at near-constant memory traffic — and the per-iteration collective
    # COUNT is independent of nrhs (payloads widen instead; statically
    # proven by tools/check_collectives.py).  Memory cost: the Krylov
    # carry holds ~5 blocked vectors, so HBM grows ~linearly in nrhs.
    # 1 = the scalar paths are untouched.  Consumers: bench.py's timed
    # leg (BENCH_NRHS sets it) solves an nrhs-wide block, and `pcg-tpu
    # solve-many` stamps the request width here so AOT cache keys /
    # snapshot fingerprints / telemetry record it; the block actually
    # passed to Solver.solve_many always defines the executed width.
    nrhs: int = 1
    # Preconditioner: "jacobi" (scalar diag(K)^-1 — the reference's only
    # choice, pcg_solver.py:346-352), "block3" (assembled 3x3 node-block
    # Jacobi, inverted per node — stronger on vector-valued elasticity;
    # beyond-reference, BASELINE.json config 4 "block-Jacobi"), or "mg"
    # (matrix-free geometric multigrid V-cycle on the octree/structured
    # level lattice, ops/mg.py — a FIXED symmetric PSD operator, so
    # plain CG stays valid: fixed-degree Chebyshev–Jacobi smoothing with
    # setup-time eigenvalue bounds, replicated collective-free coarse
    # levels, one restriction psum per cycle.  Cuts iteration counts
    # >=5x on the lattice models at the cost of 2*mg_smooth_degree
    # assembled matvecs per iteration; needs lattice metadata
    # (ModelData.grid or .octree with 2:1-coarsenable even dims —
    # preflight-checked) and the general or structured backend.  The
    # recovery ladder demotes a broken mg hierarchy to scalar Jacobi
    # instead of failing (docs/RUNBOOK.md "Choosing a preconditioner").
    # CLI: --precond; bench: BENCH_PRECOND.)
    precond: str = "jacobi"
    # MG V-cycle shape knobs (precond="mg" only; both are STRUCTURAL —
    # they reshape the traced cycle, so they key the AOT step cache and
    # the snapshot fingerprint via the mg_shape component):
    #   mg_levels        — coarse levels below the fine lattice; 0 =
    #                      auto (halve while every dim stays even, down
    #                      to a few cells per dim).
    #   mg_smooth_degree — Chebyshev smoothing degree per level; the
    #                      fine level pays 2*degree assembled matvecs
    #                      per V-cycle (ops/matvec.precond_cycle_cost).
    mg_levels: int = 0
    mg_smooth_degree: int = 2
    # MG replication scale audit (ISSUE 14): cap on the CUMULATIVE
    # replicated coarse-level dof count.  PR 9 replicates every coarse
    # level on every device (that is what makes the coarse cycle
    # collective-free), but at 1B fine dofs the first coarse level alone
    # is ~125M dofs PER DEVICE — replication becomes the memory ceiling
    # long before the fine level does.  The builder truncates auto-depth
    # hierarchies at the cutoff and REJECTS (named reason,
    # ops/mg.apply_replication_cutoff) configs whose first coarse level
    # cannot fit, or whose explicit mg_levels request would have to be
    # silently truncated; validate/ preflights the same arithmetic.
    # Default 32M dofs ~= 256 MB/level-vector f64 — comfortably inside
    # one device at today's scales, loud long before 1B.  0 = no cutoff.
    # Structural when it bites (it reshapes the hierarchy): rides the
    # solver dict into step_cache_key and the mg_shape fingerprint.
    mg_max_replicated_dofs: int = 32_000_000
    # Split the solve into several device dispatches of at most this many
    # Krylov iterations each (-1 = auto: engage on large problems, sized so
    # one dispatch stays well under a minute; 0 = single dispatch).  Long
    # single dispatches can trip execution watchdogs on remote/tunneled
    # devices; state stays on device between dispatches.
    iters_per_dispatch: int = -1
    # In-graph convergence tracing (obs/trace.py): ring-buffer length for
    # the per-iteration (normr, rho, stag, flag) trace threaded through
    # the PCG carry on device.  0 = off (the compiled program is then
    # bit-identical to no-telemetry).  When on, the ring holds the LAST
    # `trace_resid` iterations (clamped to max_iter) and crosses to the
    # host ONCE per solve.  CLI: --trace-resid.
    trace_resid: int = 0
    # Donated-carry dispatch: donate the resumable Krylov carry (and the
    # previous solution vector of the one-shot step) to XLA across
    # chunked dispatches and mixed-refinement cycles, so the multi-vector
    # carry is updated in place instead of copied every dispatch.
    # Numerically a no-op (bit-identical on/off — asserted in
    # tests/test_cache.py); off is a debugging escape hatch for
    # inspecting carries between dispatches.
    donate_carry: bool = True
    # Resilience (resilience/ subsystem): bounded recovery-ladder
    # attempts for flag-2/4 breakdowns, NaN/Inf carries, and device-loss
    # dispatch failures on the chunked dispatch paths (quasi-static
    # solver/driver.py AND the Newmark stepper) — min-residual restart
    # -> scalar-Jacobi fallback preconditioner -> f64 escalation (mixed
    # mode), each attempt an obs/metrics `recovery` event.  The same
    # budget bounds the time-history drivers' NaN/Inf
    # rollback-to-last-snapshot (solver/dynamics.py, solver/newmark.py).
    # 0 disables recovery (the historical report-and-stop behavior).
    # Healthy solves never enter it, so the default is on.
    # CLI: --max-recoveries.
    max_recoveries: int = 2
    # Device-loss dispatch retries per solve step (resilience dispatch
    # guard): a failed chunked dispatch is retried with backoff from the
    # last mid-Krylov snapshot (PCG_TPU_RETRY_BACKOFF_S tunes the base
    # backoff).  Needs RunConfig.snapshot_every > 0 to have a snapshot
    # to re-dispatch from; without one the failure escalates to the
    # recovery ladder's step restart.
    dispatch_retries: int = 2
    # Fused Pallas matvec kernel for f32 structured-backend matvecs
    # (ops/pallas_matvec.py): "auto" = on TPU devices, "on", "off",
    # "interpret" = force the kernel through the Pallas interpreter on
    # any backend (CI's way to exercise the real solver->kernel dispatch
    # on CPU; far slower than the XLA path — testing only).
    pallas: str = "auto"

    def __post_init__(self):
        # fail at CONSTRUCTION, with the same named set every other
        # surface derives from (PCG_VARIANTS above) — a typo'd variant
        # must never survive to a driver/cache/analysis layer that
        # would each have its own idea of the valid names
        if self.pcg_variant not in PCG_VARIANTS:
            raise ValueError(
                f"SolverConfig.pcg_variant must be one of "
                f"{PCG_VARIANTS}, got {self.pcg_variant!r}")


@dataclasses.dataclass
class TimeHistoryConfig:
    """Quasi-static time stepping + export settings.

    Mirrors the reference TimeHistoryParam (run_basic_script.bash:34-39).
    ``time_step_delta[t]`` scales both the prescribed displacement ``Ud`` and
    the reference load ``F`` at step t (Dirichlet lifting, pcg_solver.py:226-238).
    """

    time_step_delta: Sequence[float] = (0.0, 1.0)
    export_flag: bool = True
    export_frame_rate: int = 1
    export_frames: Sequence[int] = ()
    plot_flag: bool = False
    export_vars: str = "U"   # subset of "U D ES PS PE PS1..PS3 PE1..PE3"
    dt: float = 1.0
    # Probe dofs sampled every step into PlotData (reference RefPlotDofVec,
    # partition_mesh.py:142 + pcg_solver.py:817-838)
    probe_dofs: Sequence[int] = ()


@dataclasses.dataclass
class RunConfig:
    """Top-level run description (paths + partitioning + solver)."""

    scratch_path: str = "./scratch"
    model_name: str = "model"
    run_id: str = "1"
    n_parts: int = 1
    # Element->part assignment: "rcb" (coordinate bisection), "graph"
    # (native multilevel dual-graph partitioner — the METIS-equivalent
    # path, reference run_metis.py:84-88), or "auto".
    partition_method: str = "rcb"
    speed_test: bool = False
    # In-solve checkpointing: write solver state every N completed time
    # steps (0 = off).  The reference is resumable only at pipeline-stage
    # granularity (SURVEY.md §5); this adds step granularity.
    checkpoint_every: int = 0
    # Resumable snapshots (resilience/), one knob with path-appropriate
    # granularity (CLI: --snapshot-every):
    # * quasi-static chunked dispatch path: persist the resumable
    #   Krylov carry every N CHUNK boundaries (snap_*.npz) — a killed
    #   process or lost device loses at most N chunks, and
    #   `solve(resume=True)` continues MID-SOLVE with bit-identical
    #   history; also the restore point the dispatch guard re-dispatches
    #   from after a device-loss exception.
    # * dynamics/Newmark time histories: persist the full kinematic
    #   state (u, v[, w], histories, probe series, frames) every N
    #   completed TIMESTEPS (step_*.npz, retention-bounded by
    #   PCG_TPU_SNAP_KEEP) — `run(..., resume=True)` continues
    #   MID-TIME-HISTORY, and NaN/Inf rollback restores the last one.
    # 0 = off.
    snapshot_every: int = 0
    # Sharded setup path (ISSUE 14): under multi-process jax.distributed
    # the general/structured partition builders construct ONLY this
    # process's parts (the global layout merges via host allreduce) and
    # the warm cache reads only this process's per-part entries.
    #   "auto" — engage when multi-process with an eligible mesh/backend;
    #   "on"   — like auto, but raise when the mesh layout prevents it;
    #   "off"  — every process builds/loads the full partition (the
    #            historical behavior).
    # Trace-neutral: the engaged sharded build produces bit-identical
    # partition content for this process's rows, so the compiled program
    # and all cache keys are unchanged.
    setup_shard: str = "auto"
    # Preflight gate (validate/ subsystem): sanity-check the ModelData
    # and config cross-constraints BEFORE any partition build or XLA
    # compile.  "" = environment default (PCG_TPU_PREFLIGHT, ultimately
    # "fail"); explicit "fail" | "warn" | "off" overrides.  CLI:
    # --preflight and the `validate` subcommand.
    preflight: str = ""
    # Warm-path cache directory (cache/): when set, partitions are served
    # from a content-addressed on-disk cache, the jitted PCG step is
    # AOT-exported/deserialized (skipping re-tracing), and jax's
    # persistent XLA compilation cache is pointed at <cache_dir>/xla —
    # the second solve of the same model/n_parts/backend performs zero
    # partitioning work and zero step tracing.  CLI: --cache-dir and the
    # `warmup` subcommand (docs/RUNBOOK.md "Warm path").
    cache_dir: str = ""
    # Telemetry (obs/): when set, every structured event (steps, dispatch
    # timings, residual traces, run summary) is appended to this JSONL
    # file, one schema-versioned object per line.  CLI: --telemetry-out.
    # Under multi-process jax.distributed each process writes its OWN
    # shard (path.p<process_index>.jsonl); `pcg-tpu telemetry-merge`
    # aggregates the shards into one time-ordered stream.
    telemetry_path: str = ""
    # Flight recorder (obs/flight.py): when set, every solve dispatch is
    # bracketed by fsync-per-event begin/end records (plus periodic
    # monotonic+wall heartbeats) appended to this JSONL file — a tunnel
    # death or SIGKILL mid-solve leaves a parseable artifact naming the
    # in-flight dispatch and its last heartbeat, instead of a log to
    # hand-reconstruct.  Sharded per process like telemetry_path.
    # "" = environment default (PCG_TPU_FLIGHT), ultimately off.
    # CLI: --flight-out.
    flight_path: str = ""
    # Opt-in jax.profiler.TraceAnnotation around each device dispatch so
    # profiler traces show named pcg-tpu/<dispatch> regions (also
    # PCG_TPU_PROFILE_SPANS=1).  Independent of profile_dir below, which
    # starts/stops an actual trace collection.
    telemetry_profile: bool = False
    # When set, the solve loop runs under a jax.profiler trace written here
    # (open with TensorBoard/XProf).  This is the TPU-native replacement for
    # the reference's hand-rolled calc vs comm-wait bracketing
    # (pcg_solver.py:631-641): collective time shows up as its own ops in
    # the trace instead of host-side timer brackets.
    profile_dir: str = ""
    # Calc vs comm-wait attribution (the reference's primary scaling
    # diagnostic, pcg_solver.py:631-641): after a solve with exports, run
    # this many probe iterations of the PCG body with and without
    # collectives; the measured difference fills TimeData's
    # Mean_CommWaitTime.  0 disables the probe (Mean_CommWaitTime = 0).
    comm_probe_iters: int = 30
    solver: SolverConfig = dataclasses.field(default_factory=SolverConfig)
    time_history: TimeHistoryConfig = dataclasses.field(default_factory=TimeHistoryConfig)

    @property
    def result_path(self) -> str:
        suffix = "_SpeedTest" if self.speed_test else ""
        return f"{self.scratch_path}/Results_Run{self.run_id}{suffix}"

    @property
    def res_vec_path(self) -> str:
        return f"{self.result_path}/ResVecData"

    @property
    def plot_path(self) -> str:
        return f"{self.result_path}/PlotData"

    @property
    def checkpoint_path(self) -> str:
        return f"{self.result_path}/Checkpoints"
