"""Shared matvec-backend selection for the time-integration solvers.

DynamicsSolver (explicit) and NewmarkSolver (implicit) support the same
two backends — the hybrid level-grid path for octree models and the
general node-ELL path for everything else; this is the one copy of that
selection (the quasi-static Solver adds the structured slab path and its
dispatch-chunked machinery on top, driver.py:131-230)."""

from __future__ import annotations

import os

from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS
from pcg_mpi_solver_tpu.parallel.partition import partition_model


def select_time_backend(model: ModelData, n_parts: int, *,
                        partition_method: str, pallas_mode: str, mesh,
                        kernels_f32: bool, backend: str = "auto"):
    """Resolve ``backend`` ("auto" | "hybrid" | "general") for a model.

    ``kernels_f32``: whether this solver will ever run f32 matvecs (the
    only place Pallas kernels dispatch) — gates the compile probe.

    Returns ``(name, pm, mk_ops, mk_data)`` with ``mk_ops(dot_dtype)`` an
    Ops factory and ``mk_data(dtype)`` the device-pytree factory.
    """
    from pcg_mpi_solver_tpu.parallel.hybrid import can_hybrid

    if backend not in ("auto", "hybrid", "general"):
        raise ValueError(f"backend must be 'auto'|'hybrid'|'general', "
                         f"got {backend!r}")
    if backend == "hybrid" and not can_hybrid(model):
        raise ValueError("hybrid backend requested but model has no "
                         "octree/brick metadata")
    if backend == "auto" and can_hybrid(model) \
            and os.environ.get("PCG_TPU_ENABLE_HYBRID") != "1":
        # hybrid demotion gate (ISSUE 14; same policy as the quasi-static
        # driver): AUTO selection needs the explicit opt-in — dry-runs
        # put the hybrid partition at 117-183 s where structured takes
        # 10.5 s, and its stencil compiles cost minutes per
        # instantiation (RUNBOOK "Scaling the setup path").  Loud like
        # the driver's note event — a silent reroute would make octree
        # dynamics perf regressions undiagnosable.
        import warnings

        warnings.warn(
            "model is hybrid-backend eligible but auto-selection is "
            "gated (set PCG_TPU_ENABLE_HYBRID=1 or pass "
            "backend='hybrid'); using the general backend — see "
            "RUNBOOK 'Scaling the setup path'")
        backend = "general"
    if backend in ("auto", "hybrid") and can_hybrid(model):
        from pcg_mpi_solver_tpu.parallel.hybrid import (
            HybridOps, device_data_hybrid, hybrid_pallas_enabled,
            local_parts, partition_hybrid)

        pm = partition_hybrid(model, n_parts, method=partition_method)
        use_pallas = kernels_f32 and hybrid_pallas_enabled(
            pm, pallas_mode, mesh)
        lp = local_parts(n_parts, mesh)
        interp = pallas_mode == "interpret"
        mk_ops = lambda dd: HybridOps.from_hybrid(
            pm, dot_dtype=dd, axis_name=PARTS_AXIS, use_pallas=use_pallas,
            n_local_parts=lp, pallas_interpret=interp)
        return "hybrid", pm, mk_ops, lambda dt: device_data_hybrid(pm, dt)

    pm = partition_model(model, n_parts, method=partition_method)
    mk_ops = lambda dd: Ops.from_model(pm, dot_dtype=dd,
                                       axis_name=PARTS_AXIS)
    return "general", pm, mk_ops, lambda dt: device_data(pm, dt)
