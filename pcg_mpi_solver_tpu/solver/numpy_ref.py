"""Single-process numpy reference backend.

Serves two purposes (BASELINE.json "backend" flag; SURVEY.md §7 design
stance):

1. Parity oracle: same matrix-free type-grouped math as the TPU path, in
   plain float64 numpy, structured like the reference's per-rank compute
   (gather -> sign -> Ke @ (ck*u) -> bincount scatter, pcg_solver.py:277-300)
   but without MPI — a stand-in for the "1-rank mpi4py" reference.
2. Benchmark baseline: per-iteration cost of the CPU implementation the
   reference would run on this machine.

Independent implementation (no jax): do not "fix" it to match the TPU path;
disagreements between the two are signal.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from pcg_mpi_solver_tpu.models.model_data import ModelData


@dataclasses.dataclass
class NumpyRefResult:
    u: np.ndarray
    flag: int
    relres: float
    iters: int
    wall_s: float
    # per-iteration residual norms (oldest -> newest) — the host-side
    # oracle for the TPU path's in-graph convergence trace (obs/trace.py)
    normr_hist: Optional[np.ndarray] = None


class NumpyRefSolver:
    """Matrix-free Jacobi-PCG on the global (unpartitioned) model."""

    def __init__(self, model: ModelData):
        self.model = model
        m = model
        self.groups = []
        for t in sorted(m.elem_lib.keys()):
            e = np.where(m.elem_type == t)[0]
            if len(e) == 0:
                continue
            lib = m.elem_lib[t]
            d = lib["Ke"].shape[0]
            from pcg_mpi_solver_tpu.parallel.partition import _csr_take
            dofs = _csr_take(m.elem_dofs_flat, m.elem_dofs_offset, e).reshape(-1, d).T
            signs = _csr_take(m.elem_sign_flat, m.elem_dofs_offset, e).reshape(-1, d).T
            self.groups.append({
                "Ke": np.asarray(lib["Ke"], float),
                "diagKe": np.asarray(lib["diagKe"], float),
                "dofs": dofs,
                "dofs_flat": dofs.ravel(),
                "signs": signs,
                "ck": np.asarray(m.ck[e], float),
            })
        self.n_dof = m.n_dof
        self.eff = m.dof_eff
        self.springs = m.interface_springs()[:3]   # (dof_a, dof_b, k)

    def matvec(self, x: np.ndarray) -> np.ndarray:
        y = np.zeros(self.n_dof)
        for g in self.groups:
            u = x[g["dofs"]]
            u[g["signs"]] *= -1.0
            v = g["Ke"] @ (g["ck"] * u)
            v[g["signs"]] *= -1.0
            y += np.bincount(g["dofs_flat"], weights=v.ravel(), minlength=self.n_dof)
        sa, sb, sk = self.springs
        if len(sa):
            f = sk * (x[sa] - x[sb])
            np.add.at(y, sa, f)
            np.add.at(y, sb, -f)
        return y

    def diag(self) -> np.ndarray:
        y = np.zeros(self.n_dof)
        for g in self.groups:
            v = g["diagKe"][:, None] * g["ck"][None, :]
            y += np.bincount(g["dofs_flat"], weights=v.ravel(), minlength=self.n_dof)
        sa, sb, sk = self.springs
        if len(sa):
            np.add.at(y, sa, sk)
            np.add.at(y, sb, sk)
        return y

    def solve(self, delta: float = 1.0, tol: float = 1e-7, max_iter: int = 10000,
              x0: Optional[np.ndarray] = None) -> NumpyRefResult:
        """Quasi-static step: Dirichlet lifting + Jacobi-PCG on eff dofs."""
        m = self.model
        t0 = time.perf_counter()
        udi = m.Ud * delta
        fext = (m.F * delta - self.matvec(udi))[self.eff]
        inv_diag = 1.0 / self.diag()[self.eff]

        n2b = np.linalg.norm(fext)
        if n2b == 0:
            return NumpyRefResult(udi, 0, 0.0, 0, time.perf_counter() - t0,
                                  normr_hist=np.zeros(0))
        tolb = tol * n2b

        x = np.zeros(len(self.eff)) if x0 is None else x0[self.eff].copy()
        xg = np.zeros(self.n_dof)

        def amul(v):
            xg[:] = 0.0
            xg[self.eff] = v
            return self.matvec(xg)[self.eff]

        r = fext - amul(x)
        normr = np.linalg.norm(r)
        flag, rho, iters = 1, 1.0, 0
        if normr <= tolb:
            flag, iters = 0, 0
        hist = []
        for i in range(max_iter):
            if flag != 1:
                break
            z = inv_diag * r
            rho_new = float(z @ r)
            if rho_new == 0 or np.isinf(rho_new):
                flag = 4
                break
            p = z if i == 0 else z + (rho_new / rho) * p
            rho = rho_new
            q = amul(p)
            pq = float(p @ q)
            if pq <= 0 or np.isinf(pq):
                flag = 4
                break
            alpha = rho / pq
            x += alpha * p
            r -= alpha * q
            normr = np.linalg.norm(r)
            iters = i + 1
            if normr <= tolb:
                # true-residual confirmation (reference pcg_solver.py:527-533)
                r = fext - amul(x)
                normr = np.linalg.norm(r)
                if normr <= tolb:
                    flag = 0
            hist.append(normr)
            if flag == 0:
                break
        u = udi.copy()
        u[self.eff] += x
        return NumpyRefResult(u, flag, normr / n2b, iters,
                              time.perf_counter() - t0,
                              normr_hist=np.asarray(hist))

    def time_per_iter(self, n_iters: int = 30, delta: float = 1.0) -> float:
        """Measured seconds per PCG iteration (matvec + vector ops)."""
        m = self.model
        udi = m.Ud * delta
        fext = (m.F * delta - self.matvec(udi))[self.eff]
        inv_diag = 1.0 / self.diag()[self.eff]
        x = np.zeros(len(self.eff))
        xg = np.zeros(self.n_dof)

        def amul(v):
            xg[:] = 0.0
            xg[self.eff] = v
            return self.matvec(xg)[self.eff]

        r = fext - amul(x)
        rho = 1.0
        p = None
        t0 = time.perf_counter()
        for i in range(n_iters):
            z = inv_diag * r
            rho_new = float(z @ r)
            p = z if i == 0 else z + (rho_new / rho) * p
            rho = rho_new
            q = amul(p)
            alpha = rho / float(p @ q)
            x += alpha * p
            r -= alpha * q
            np.linalg.norm(r)
        return (time.perf_counter() - t0) / n_iters
