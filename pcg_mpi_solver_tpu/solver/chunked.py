"""Dispatch-chunked PCG execution, shared by solvers.

A single device dispatch that runs for minutes can trip execution
watchdogs on remote/tunneled TPUs (docs/RUNBOOK.md); above ~4M dofs the
solvers split a solve into host-driven dispatches of at most ``cap``
Krylov iterations, with all state resident on device between calls.  The
Krylov recurrence is resumable (solver/pcg.py ``carry_in``), so N capped
dispatches are iteration-for-iteration identical to one long solve in
direct mode, and chunk boundaries align with refinement cycles in mixed
mode.

This module owns everything AFTER the per-solver start step (which
differs: Dirichlet lifting for the quasi-static driver, the Newmark
history term for the implicit dynamics solver): the jitted cycle/refine/
finalize programs and the host-side budget loop.  Used by
``solver/driver.py`` and ``solver/newmark.py``.
"""

from __future__ import annotations

import contextlib
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.obs.trace import trace_host_init, trace_specs
from pcg_mpi_solver_tpu.solver.pcg import (
    LAGGED_VARIANTS, carry_part_specs, cold_carry, pcg, refine_tol,
    select_best)


def _state_kind(state) -> str:
    """The ``kind`` tag of a (possibly npz-round-tripped) snapshot
    state: plain str programmatically, 0-d unicode array from disk."""
    return str(np.asarray(state.get("kind", "")))


class ChunkedEngine:
    """Capped-dispatch budget loop over a resumable PCG.

    ``ops``/``ops32`` follow the Ops protocol (the Newmark solver passes
    mass-shifted wrappers).  In mixed mode ``data`` is the
    ``{"f64": ..., "f32": ...}`` pytree and the preconditioner inverse is
    f32; in direct mode ``data`` is the flat pytree and the inverse
    matches the solve dtype.  The preconditioner is built once per step
    by the caller and passed into :meth:`run`.
    """

    def __init__(self, *, mesh, data_specs, part_spec, rep_spec, ops,
                 scfg, glob_n_dof_eff: int, cap: int, mixed: bool,
                 ops32=None, amul_fn=None, trace_len: int = 0,
                 recorder=None, donate: bool = False, prec_spec=None):
        """``amul_fn``, when given, is a host-level callable
        ``(data, v) -> eff * K.v`` backed by ONE separately-jitted
        program the caller shares across all its out-of-loop f64 matvec
        uses (Dirichlet lifting, r0, refine) — at octree-flagship scale
        every stencil INSTANTIATION costs minutes of compile
        (docs/BENCH_LOG.md 2026-07-31), so the refine step is then
        composed from two tiny elementwise programs around it instead of
        instantiating the stencil a second time in its own program.

        ``trace_len`` > 0 threads the in-graph convergence ring
        (obs/trace.py) through the dispatch carries: in direct mode the
        ring rides the caller-built cold carry (``cold_carry(...,
        trace=...)``), in mixed mode the engine owns it and hands it from
        cycle to cycle.  Either way the ring stays device-resident across
        all dispatches of a solve and is surfaced once, as
        ``self.last_trace``, after :meth:`run` terminates.  ``recorder``
        (obs/metrics.py MetricsRecorder) gets a ``dispatch`` span around
        every jitted call; None disables that instrumentation.

        ``donate`` enables donated-carry dispatch: each capped dispatch
        DONATES its input Krylov carry (and the refine step its previous
        f64 iterate) to XLA, so the multi-vector resumable state is
        aliased in place instead of copied per dispatch.  Numerically a
        no-op (bit-identical on/off, tests/test_cache.py); the budget
        loop in :meth:`run` honors the contract by never touching a
        carry object after passing it to a donating program — every read
        (``final``/``final32``, the trace hand-off) is from the LATEST
        dispatch's freshly-allocated outputs."""
        self.mixed = mixed
        self.scfg = scfg
        self._amul_fn = amul_fn
        self.trace_len = int(trace_len)
        self._rec = recorder
        self.last_trace = None
        self.donate = bool(donate)
        # Loop formulation (SolverConfig.pcg_variant): threads through
        # every resumable pcg() call below and sizes the carry schema —
        # the recurrence variants (fused, pipelined) ride their
        # q/alpha/fresh (+ GV u/w/s/z/init) recurrence state alongside
        # the classic Krylov carry, so capped dispatches stay
        # bit-identical to one long solve of the same variant.
        variant = self.variant = getattr(scfg, "pcg_variant", "classic")
        lagged_v = variant in LAGGED_VARIANTS
        cap = int(cap)
        P, R = part_spec, rep_spec
        # preconditioner-operand spec: the plain part spec for the array
        # inverses (jacobi/block3), or the caller-supplied PYTREE of
        # specs for structured prec operands (the mg dict —
        # driver/newmark pass {"mg_diag": P, "fb": R})
        prec_spec = P if prec_spec is None else prec_spec
        carry_specs = carry_part_specs(P, R, trace=self.trace_len > 0,
                                       variant=variant)

        def smap(f, in_specs, out_specs, donate_argnums=()):
            return jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False),
                donate_argnums=donate_argnums if self.donate else ())

        if mixed:
            # Three jitted pieces so the f32 Krylov state survives dispatch
            # boundaries WITHIN a refinement cycle (restarting CG at every
            # dispatch loses superlinear convergence):
            #   inner_start: normalize the f64 residual, cold f32 carry +
            #                adaptive cycle tolerance;
            #   inner_cycle: resumable capped f32 PCG dispatch;
            #   refine:      f64 solution update + true-residual recompute.
            dd32 = jnp.float32

            traced = self.trace_len > 0

            def _inner_start(data, r, normr, n2b, trace=None):
                tol_cycle = refine_tol(scfg.tol * n2b, normr, scfg.inner_tol)
                rhat32 = (r / normr).astype(dd32)
                # ||rhat||_w = ||r||_w / normr = 1 exactly; no matvec needed.
                one = jnp.asarray(1.0, ops32.dot_dtype)
                carry0 = cold_carry(jnp.zeros_like(rhat32), rhat32, one,
                                    ops32.dot_dtype, trace=trace,
                                    variant=variant)
                return rhat32, tol_cycle, carry0

            in_start = (data_specs, P, R, R) + (
                (trace_specs(R),) if traced else ())
            self._inner_start_fn = smap(
                _inner_start, in_start, (P, R, carry_specs))

            def _inner_cycle(data, rhat32, prec32, tol_cycle, carry32,
                             budget, scale=None):
                if recorder is not None:       # runs at trace time only
                    recorder.inc("trace.inner_cycle")
                res, carry2 = pcg(
                    ops32, data["f32"], rhat32, carry32["x"], prec32,
                    tol=tol_cycle,
                    max_iter=jnp.minimum(cap, budget),
                    glob_n_dof_eff=glob_n_dof_eff,
                    max_stag_steps=scfg.max_stag_steps,
                    max_iter_nominal=scfg.max_iter,
                    carry_in=carry32, return_carry=True,
                    plateau_window=scfg.mixed_plateau_window,
                    progress_window=scfg.mixed_progress_window,
                    progress_ratio=scfg.mixed_progress_ratio,
                    progress_min_gain=scfg.mixed_progress_min_gain,
                    # inner iterations run on r/normr: the ring records
                    # absolute residuals via the cycle's refresh norm
                    trace_scale=scale,
                    variant=variant)
                return res.x, carry2, res.flag

            in_cycle = (data_specs, P, prec_spec, R, carry_specs, R) + (
                (R,) if traced else ())
            # donated f32 carry: each resumable dispatch updates the
            # Krylov state in place instead of copying it
            self._inner_cycle_fn = smap(
                _inner_cycle, in_cycle, (P, carry_specs, R),
                donate_argnums=(4,))

            if amul_fn is None:
                def _refine(data, fext, x, xinc32, scale):
                    data64 = data["f64"]
                    eff = data64["eff"]
                    w = data64["weight"] * eff
                    x2 = x + xinc32.astype(x.dtype) * scale
                    r2 = fext - eff * ops.matvec(data64, x2)
                    normr2 = jnp.sqrt(ops.wdot(w, r2, r2))
                    return x2, r2, normr2

                # donated previous iterate: x2 replaces x 1:1
                self._refine_fn = smap(
                    _refine, (data_specs, P, P, P, R), (P, P, R),
                    donate_argnums=(2,))
            else:
                def _refine_pre(x, xinc32, scale):
                    return x + xinc32.astype(x.dtype) * scale

                # donated previous iterate: x2 replaces x 1:1
                self._refine_pre_fn = smap(_refine_pre, (P, P, R), P,
                                           donate_argnums=(0,))

                def _refine_post(data, fext, kx2):
                    data64 = data["f64"]
                    w = data64["weight"] * data64["eff"]
                    r2 = fext - kx2          # kx2 = eff * K.x2 (amul_fn)
                    normr2 = jnp.sqrt(ops.wdot(w, r2, r2))
                    return r2, normr2

                self._refine_post_fn = smap(
                    _refine_post, (data_specs, P, P), (P, R))

            def _final32(data, rhat32, carry32):
                """f32 min-residual selection when an inner solve fails
                (matches the one-shot pcg_mixed's finalize_bad;
                recurrence-variant carries never evaluated their last
                iterate, so they take the min unconditionally)."""
                x, _ = select_best(ops32, data["f32"], rhat32, carry32,
                                   always_min=lagged_v)
                return x

            self._final32_fn = smap(
                _final32, (data_specs, P, carry_specs), P)
        else:
            def _cycle(data, fext, inv_diag, carry, budget):
                # Resumable call: the Krylov recurrence continues across
                # dispatch boundaries, so N capped dispatches are iteration-
                # for-iteration identical to one long solve.
                if recorder is not None:       # runs at trace time only
                    recorder.inc("trace.cycle")
                res, carry2 = pcg(
                    ops, data, fext, carry["x"], inv_diag,
                    tol=scfg.tol,
                    max_iter=jnp.minimum(cap, budget),
                    glob_n_dof_eff=glob_n_dof_eff,
                    max_stag_steps=scfg.max_stag_steps,
                    max_iter_nominal=scfg.max_iter,
                    carry_in=carry, return_carry=True,
                    variant=variant)
                return res.x, carry2, res.flag, res.relres

            # donated carry: the resumable Krylov state is aliased across
            # dispatch boundaries instead of copied
            self._cycle_fn = smap(
                _cycle, (data_specs, P, prec_spec, carry_specs, R),
                (P, carry_specs, R, R), donate_argnums=(3,))

            def _final(data, fext, carry):
                """Min-residual selection at terminal failure (once/step);
                recurrence-variant carries never evaluated their last
                iterate, so they take the min unconditionally."""
                return select_best(ops, data, fext, carry,
                                   always_min=lagged_v)

            self._final_fn = smap(
                _final, (data_specs, P, carry_specs), (P, R))

    def _disp(self, name: str):
        """Dispatch span (compile/execute attribution + optional profiler
        annotation) when a recorder is attached; free otherwise.

        jax dispatch is asynchronous: a span only measures device
        execution when the blocking scalar fetch sits INSIDE it, so every
        call site keeps its ``int(...)``/``float(...)`` coercions in the
        block.  Spans with no scalar of their own (inner_start, final32)
        time the enqueue; their execution is absorbed by the next fetching
        span (the device serializes programs), so per-CYCLE attribution
        stays correct."""
        if self._rec is None:
            return contextlib.nullcontext()
        return self._rec.dispatch(name)

    def warmup(self, data, fext, carry, normr0, n2b, prec):
        """Compile every budget-loop program by running each ONCE with a
        1-iteration budget: a single Krylov iteration of execution per
        program, negligible next to the minutes-scale XLA compiles this
        front-loads into the persistent compilation cache
        (Solver.warmup / `pcg-tpu warmup`).  CONSUMES ``carry`` when
        donation is on — callers pass a throwaway start state; every
        output is discarded."""
        one = jnp.asarray(1, jnp.int32)
        # Same dispatch names/spans as run(): the warmup call IS the
        # call that pays compile, and booking it cold here keeps the
        # real solve's first dispatch truthfully warm in
        # dispatch_stats() / the run_summary attribution.
        if self.mixed:
            trace = (trace_host_init(self.trace_len)
                     if self.trace_len > 0 else None)
            start_args = (data, carry["r"], normr0, n2b) + (
                (trace,) if trace is not None else ())
            with self._disp("inner_start"):
                rhat32, tol_cycle, c32 = self._inner_start_fn(*start_args)
            cyc_args = (data, rhat32, prec, tol_cycle, c32, one) + (
                (normr0,) if trace is not None else ())
            with self._disp("inner_cycle"):
                _xin, c32, _flag = self._inner_cycle_fn(*cyc_args)
                jax.block_until_ready(c32["exec"])
            with self._disp("final32"):
                xin = self._final32_fn(data, rhat32, c32)
            with self._disp("refine"):
                if self._amul_fn is None:
                    out = self._refine_fn(data, fext, carry["x"], xin,
                                          normr0)
                else:
                    x2 = self._refine_pre_fn(carry["x"], xin, normr0)
                    out = self._refine_post_fn(data, fext,
                                               self._amul_fn(data, x2))
                jax.block_until_ready(out)
        else:
            with self._disp("cycle"):
                _x, c2, _flag, _rel = self._cycle_fn(data, fext, prec,
                                                     carry, one)
                jax.block_until_ready(c2["exec"])
            with self._disp("final"):
                out = self._final_fn(data, fext, c2)
                jax.block_until_ready(out)

    def run(self, data, fext, carry, normr0, n2b, prec,
            vlog: Optional[Callable[[str], None]] = None,
            resilience=None, total0: int = 0):
        """Budget loop from a prepared start state to termination.

        ``carry``: cold carry at the start iterate (``cold_carry``);
        ``prec``: preconditioner inverse (f32 in mixed mode, solve dtype in
        direct mode).  Returns ``(x_fin, flag, relres, total_iters)``.
        The caller handles the ``n2b == 0`` and already-converged early
        exits (they need no dispatches).

        With ``trace_len`` > 0 the convergence ring of the finished solve
        is left (device-resident) on ``self.last_trace`` — unpack it with
        ``obs.trace.unpack_trace`` (that is the single host transfer).

        ``resilience`` (resilience/recovery.ResilienceContext, optional)
        threads the preemption-safety hooks through the loop — all
        no-ops when None.  Healthy-path cost with a context attached:
        the snapshot state thunks are only evaluated at cadence; the
        only unconditional extras are two already-adjacent scalar reads
        per inner dispatch (mixed corruption detection) and, with the
        ladder armed, one device-side copy of the iterate per mixed
        refinement cycle (the restart iterate must survive the refine
        step's buffer donation).  The hooks:

        * chunk boundaries snapshot the resumable state (direct: the
          Krylov carry; mixed: the outer refinement state — chunk
          boundaries align with refinement cycles on this path) and are
          where deterministic faults fire;
        * a device-loss exception from a dispatch re-dispatches from the
          last snapshot via the retry/backoff guard, composing with
          donated-carry dispatch (the snapshot is a HOST copy, so a
          consumed-then-crashed donation cannot orphan the solve);
        * a persisted mid-step snapshot (``--resume`` after a kill)
          replaces the cold start state;
        * a NaN/Inf residual — which trips NO in-graph flag (pcg.py
          BREAKDOWN_FLAGS) — breaks the loop within one chunk so the
          driver's recovery ladder can restart from the min-residual
          iterate instead of burning the whole budget on poison.

        ``total0`` continues the iteration budget across ladder restarts
        and mid-step resumes.  After the loop, ``self.restart_x`` holds
        the iterate a recovery restart should start from (direct: the
        tracked min-residual iterate ``xmin``; mixed: the last iterate
        whose f64 refresh was finite).
        """
        scfg = self.scfg
        vlog = vlog or (lambda s: None)
        self.last_trace = None
        self.restart_x = None
        n2b_f = float(n2b)
        tolb = scfg.tol * n2b_f
        total, flag = int(total0), 1
        cur = float(normr0)
        relres = cur / n2b_f
        x_fin = carry["x"]
        faults = resilience.faults if resilience is not None else None
        resume = (resilience.load_resume_state()
                  if resilience is not None else None)
        if cur <= tolb and resume is None:
            # already converged at entry (a cold start below tol, or a
            # ladder-restart iterate whose true residual already meets
            # it): report the CUMULATIVE iteration count and surface the
            # carry's ring (empty-but-valid) rather than dropping both
            self.last_trace = carry.get("trace")
            self.restart_x = carry.get("xmin")
            return x_fin, 0, relres, total
        if self.mixed:
            x, r, normr = carry["x"], carry["r"], normr0
            stall = 0
            chunk_i = 0
            trace = (trace_host_init(self.trace_len)
                     if self.trace_len > 0 else None)
            def _restore_mixed(st):
                """Snapshot state -> (x, r, normr, stall, total, trace):
                the ONE mixed-state restore, shared by mid-step resume
                and the guard's re-dispatch so the two cannot drift."""
                dev = resilience.restore_device(
                    {k: st[k] for k in ("x", "r")})
                tr = (resilience.restore_device(
                    {"trace": st["trace"]})["trace"]
                    if "trace" in st else None)
                return (dev["x"], dev["r"], np.asarray(st["normr"]),
                        int(np.asarray(st["stall"])),
                        int(np.asarray(st["total"])), tr)

            if resume is not None and _state_kind(resume) == "mixed":
                x, r, normr, stall, total, tr = _restore_mixed(resume)
                if trace is not None and tr is not None:
                    trace = tr
                cur = float(normr)
                relres = cur / n2b_f
            # the restart iterate must survive the refine step's donation
            # of the previous x (a kept alias would die with the buffer);
            # copied only when the driver ladder will actually consume it
            keep_restart = (resilience is not None
                            and resilience.ladder_armed)
            good_x = jnp.copy(x) if keep_restart else None
            while flag == 1 and total < scfg.max_iter:
                # group liveness first, OUTSIDE the dispatch guard: a
                # dead peer surfaces as a named DeadPeerError within the
                # deadline instead of an XLA collective hanging inside
                # the refinement dispatch
                if resilience is not None:
                    resilience.sync_boundary()
                prev = cur
                try:
                    # One refinement cycle: run the f32 inner solve to ITS
                    # convergence via resumable capped dispatches, refine.
                    vlog(f"inner_start dispatch (normr={float(normr):.3e})")
                    start_args = (data, r, normr, n2b) + (
                        (trace,) if trace is not None else ())
                    with self._disp("inner_start"):
                        rhat32, tol_cycle, c32 = self._inner_start_fn(
                            *start_args)
                    inner_flag, xin = 1, None
                    first_dispatch, poisoned = True, False
                    while inner_flag == 1 and total < scfg.max_iter:
                        budget = jnp.asarray(scfg.max_iter - total,
                                             jnp.int32)
                        vlog(f"inner_cycle dispatch (total={total})")
                        if faults is not None:
                            faults.on_dispatch()
                        cyc_args = (data, rhat32, prec, tol_cycle, c32,
                                    budget) + ((normr,) if trace is not None
                                               else ())
                        with self._disp("inner_cycle"):
                            xin, c32, iflag = self._inner_cycle_fn(*cyc_args)
                            # scalar fetches INSIDE the span: jax dispatch
                            # is async, so the span only measures execution
                            # if it contains the blocking host transfer
                            exec_n = int(c32["exec"])
                            total += exec_n
                            inner_flag = int(iflag)
                        if faults is not None:
                            faults.on_dispatch_done()
                        vlog(f"inner_cycle done: +{exec_n} iters "
                             f"flag={inner_flag}")
                        if resilience is not None:
                            # Corruption detection off ALREADY-fetched
                            # scalars (no extra host sync on the healthy
                            # path): (a) flag 0 with 0 iterations on the
                            # cycle's FIRST dispatch is impossible for the
                            # normalized inner rhs (||rhat|| = 1 > any
                            # tol_cycle <= 0.25) unless an Inf rhs faked
                            # tolb = tol * ||Inf|| = Inf; (b) a NaN carry
                            # norm trips no MATLAB flag at all.  Either
                            # way, hand the step to the driver ladder.
                            if (first_dispatch and inner_flag == 0
                                    and exec_n == 0) or not math.isfinite(
                                        float(c32["normr_act"])):
                                vlog("inner state non-finite/corrupt; "
                                     "handing the step to the recovery "
                                     "ladder")
                                poisoned = True
                                break
                        first_dispatch = False
                    if poisoned:
                        if trace is not None:
                            trace = c32["trace"]
                        cur = float("nan")
                        break
                    if trace is not None:
                        # ring hand-off to the next cycle (device-to-device)
                        trace = c32["trace"]
                    if inner_flag != 0:
                        # Failed/exhausted inner solve: min-residual
                        # selection (the resumable path defers it; matches
                        # one-shot pcg_mixed's inner finalize_bad).
                        with self._disp("final32"):
                            xin = self._final32_fn(data, rhat32, c32)
                    vlog("refine dispatch (f64 true-residual matvec)")
                    with self._disp("refine"):
                        if self._amul_fn is None:
                            x, r, normr = self._refine_fn(
                                data, fext, x, xin, normr)
                        else:
                            x = self._refine_pre_fn(x, xin, normr)
                            r, normr = self._refine_post_fn(
                                data, fext, self._amul_fn(data, x))
                        # blocking fetch inside the span (async dispatch) —
                        # this also absorbs any still-running earlier
                        # program (inner_start/final32 have no fetch)
                        cur = float(normr)
                except Exception as e:                  # noqa: BLE001
                    st = (resilience.handle_dispatch_failure(e, "mixed")
                          if resilience is not None else None)
                    if st is None:
                        # no retry budget, or no snapshot of THIS mode's
                        # state (e.g. one predating an escalation
                        # switch): escalate to the driver ladder
                        raise
                    # re-dispatch from the snapshot: lose at most one
                    # snapshot interval, not the step
                    x, r, normr, stall, total, tr = _restore_mixed(st)
                    if trace is not None and tr is not None:
                        trace = tr
                    cur = float(normr)
                    if keep_restart:
                        good_x = jnp.copy(x)
                    continue
                vlog(f"refine done: relres={cur / n2b_f:.3e} total={total}")
                if not math.isfinite(cur):
                    # poisoned carry: break BEFORE the snapshot/stall
                    # bookkeeping (never persist non-finite state); the
                    # driver ladder restarts from self.restart_x
                    break
                if keep_restart:
                    good_x = jnp.copy(x)
                chunk_i += 1
                if cur <= tolb:
                    flag = 0
                elif inner_flag == 2:
                    flag = 2
                elif cur > 0.9 * prev:
                    # no meaningful contraction over a refinement cycle
                    stall += 1
                    if stall >= 2:
                        flag = 3
                else:
                    stall = 0
                if resilience is not None and flag == 1:
                    resilience.after_chunk(lambda: dict(
                        kind="mixed", chunk=chunk_i, total=total,
                        stall=stall, normr=normr, x=x, r=r,
                        **({"trace": trace} if trace is not None else {})))
                    if faults is not None:
                        st = faults.at_boundary({"r": r})
                        r = st["r"]
            x_fin, relres = x, cur / n2b_f
            self.last_trace = trace
            self.restart_x = good_x if good_x is not None else x
        else:
            chunk_i = 0

            def _restore_direct(st):
                """Snapshot state -> (carry, total, relres): the ONE
                direct-state restore, shared by mid-step resume and the
                guard's re-dispatch so the two cannot drift.  Fused
                snapshots written before the drift-guard leaf existed
                resume with its cold value (the legacy-shim precedent of
                CheckpointManager.restore)."""
                sc = dict(st["carry"])
                if self.variant == "fused":
                    sc.setdefault("drift", np.zeros((), np.int32))
                c = resilience.restore_device({"carry": sc})["carry"]
                return (c, int(np.asarray(st["total"])),
                        float(np.asarray(sc["normr_act"])) / n2b_f)

            if resume is not None and _state_kind(resume) == "direct":
                carry, total, relres = _restore_direct(resume)
                x_fin = carry["x"]
            while flag == 1 and total < scfg.max_iter:
                # group liveness first, OUTSIDE the dispatch guard: a
                # dead peer surfaces as a named DeadPeerError within the
                # deadline instead of an XLA collective hanging inside
                # the cycle dispatch being misread as device loss
                if resilience is not None:
                    resilience.sync_boundary()
                budget = jnp.asarray(scfg.max_iter - total, jnp.int32)
                try:
                    if faults is not None:
                        faults.on_dispatch()
                    with self._disp("cycle"):
                        x_fin, carry, cflag, crelres = self._cycle_fn(
                            data, fext, prec, carry, budget)
                        # scalar fetches INSIDE the span (async dispatch):
                        # the span must contain the blocking transfer to
                        # time execution, not enqueue
                        total += int(carry["exec"])
                        flag = int(cflag)
                        relres = float(crelres)
                except Exception as e:                  # noqa: BLE001
                    st = (resilience.handle_dispatch_failure(e, "direct")
                          if resilience is not None else None)
                    if st is None:
                        # no retry budget, or no snapshot of THIS mode's
                        # state (e.g. one predating an escalation
                        # switch): escalate to the driver ladder
                        raise
                    # re-dispatch from the snapshot (the donated carry may
                    # have been consumed by the failed dispatch — the host
                    # snapshot is the one copy that cannot have been)
                    carry, total, relres = _restore_direct(st)
                    flag = 1
                    continue
                if faults is not None:
                    faults.on_dispatch_done()
                chunk_i += 1
                if flag != 1 or not math.isfinite(relres):
                    # terminal, or NaN carry (no in-graph flag trips on
                    # NaN): never snapshot past this point — a persisted
                    # poisoned carry would poison the resume too
                    break
                if resilience is not None:
                    resilience.after_chunk(lambda: dict(
                        kind="direct", chunk=chunk_i, total=total,
                        carry=carry))
                    if faults is not None:
                        carry = faults.at_boundary(carry)
            if flag != 0:
                # Terminal failure: the resumable path defers MATLAB pcg's
                # min-residual fallback to here (once per step).
                with self._disp("final"):
                    x_fin, relres_dev = self._final_fn(data, fext, carry)
                    best = float(relres_dev)
                # a NaN-poisoned carry must stay visible to the ladder's
                # nan_carry trigger: classic's select_best propagates the
                # non-finite normr_act through its NaN-false compare, but
                # the fused always-min selection reports the (finite)
                # recomputed min residual — keep the poison marker either
                # way and let the ladder restart from restart_x
                if math.isfinite(relres):
                    relres = best
            self.last_trace = carry.get("trace")
            # min-residual restart iterate for the recovery ladder (only
            # ever updated by committed finite iterations, so it stays
            # finite through NaN poisoning and flag-2/4 breakdowns)
            self.restart_x = carry["xmin"]
            if self.variant in LAGGED_VARIANTS and self._rec is not None \
                    and "drift" in carry:
                # recurrence-variant residual-drift telemetry
                # (obs/schema `resid_drift`): how many deferred
                # true-residual checks disagreed with the recurrence
                # norm this solve (flag 6 routes sustained drift into
                # the ladder; the count is the observability twin) —
                # one scalar fetch, at termination only
                d = int(carry["drift"])
                if d > 0:
                    self._rec.event("resid_drift", drift=d)
                    self._rec.gauge("resid.drift", d)
        return x_fin, flag, relres, total


def auto_dispatch_cap(scfg, glob_n_dof: int, n_loc_dev: int,
                      force_engage: bool = False) -> int:
    """Resolve SolverConfig.iters_per_dispatch (-1 = auto: engage on large
    problems, sized so one dispatch stays well under a minute).

    ``force_engage`` makes auto engage at ANY size — the hybrid backend
    always prefers the chunked path, whose programs instantiate its
    minutes-to-compile stencil strictly fewer times than the one-shot
    step program (1 shared f64 + 1 f32 loop body vs 2 f64 + 1 f32 in one
    program); chunked dispatches are iteration-identical to one-shot."""
    cap = scfg.iters_per_dispatch
    if cap < 0:
        if glob_n_dof < 4_000_000 and not force_engage:
            cap = 0
        else:
            cap = max(200, int(45.0 / (4e-9 * max(n_loc_dev, 1))))
    return int(cap)
