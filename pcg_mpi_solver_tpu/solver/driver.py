"""Quasi-static solve driver: the reference's main program re-designed.

Reference flow (pcg_solver.py:1002-1008): for each time step —
updateBC (Dirichlet lifting) -> updatePreconditioner (Jacobi rebuild) ->
PCG -> history/exports.  Here the whole step (lifting matvec + diagonal
assembly + the full PCG while_loop) is ONE jitted shard_map'd SPMD program
over the device mesh; only the small per-step scalars (flag/relres/iters)
come back to the host.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.config import RunConfig
from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS, make_mesh
from pcg_mpi_solver_tpu.parallel.partition import PartitionedModel, partition_model
from pcg_mpi_solver_tpu.solver.pcg import pcg


@dataclasses.dataclass
class StepResult:
    flag: int
    relres: float
    iters: int
    wall_s: float


class Solver:
    """Owns the partitioned model on the device mesh and runs time steps."""

    def __init__(
        self,
        model: ModelData,
        config: Optional[RunConfig] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        n_parts: Optional[int] = None,
        elem_part: Optional[np.ndarray] = None,
    ):
        self.config = config or RunConfig()
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        if n_parts is None:
            n_parts = max(self.config.n_parts, n_dev)
        if n_parts < 1:
            raise ValueError(f"n_parts must be >= 1, got {n_parts}")
        if n_parts % n_dev != 0:
            raise ValueError(f"n_parts={n_parts} must be a multiple of device count {n_dev}")

        dtype = jnp.dtype(self.config.solver.dtype)
        dot_dtype = jnp.dtype(self.config.solver.dot_dtype)
        if jnp.float64 in (dtype, dot_dtype) and not jax.config.jax_enable_x64:
            # The config asked for f64 math — honor it rather than silently
            # downgrading (the reference is f64 throughout).
            jax.config.update("jax_enable_x64", True)
        self.dtype = dtype

        self.pm: PartitionedModel = partition_model(model, n_parts, elem_part=elem_part)
        self.ops = Ops.from_model(self.pm, dot_dtype=dot_dtype, axis_name=PARTS_AXIS)

        data = device_data(self.pm, dtype)
        self._specs = _data_specs(data)
        self.data = jax.device_put(
            data, jax.tree.map(lambda s: jax.NamedSharding(self.mesh, s), self._specs,
                               is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        )

        self._part_spec = jax.sharding.PartitionSpec(PARTS_AXIS)
        self._rep_spec = jax.sharding.PartitionSpec()

        solver_cfg = self.config.solver
        glob_n_eff = self.pm.glob_n_dof_eff

        def _step(data, un_prev, delta):
            eff = data["eff"]
            # Dirichlet lifting: Fext = F*delta - K.(Ud*delta)
            # (reference updateBC, pcg_solver.py:226-238)
            udi = data["Ud"] * delta
            fdi = self.ops.matvec(data, udi)
            fext = eff * (data["F"] * delta - fdi)
            # Jacobi preconditioner rebuild (pcg_solver.py:346-352)
            diag_k = self.ops.diag(data)
            inv_diag = jnp.where(eff > 0, 1.0 / diag_k, 0.0)
            x0 = eff * un_prev
            res = pcg(
                self.ops, data, fext, x0, inv_diag,
                tol=solver_cfg.tol, max_iter=solver_cfg.max_iter,
                glob_n_dof_eff=glob_n_eff,
                max_stag_steps=solver_cfg.max_stag_steps,
            )
            un = res.x + udi
            return un, res.flag, res.relres, res.iters

        shard_step = jax.shard_map(
            _step,
            mesh=self.mesh,
            in_specs=(self._specs, self._part_spec, self._rep_spec),
            out_specs=(self._part_spec, self._rep_spec, self._rep_spec, self._rep_spec),
            check_vma=False,
        )
        self._step_fn = jax.jit(shard_step)

        # Initial state: deterministic zeros (the reference seeds Un with
        # unseeded 1e-200*rand, pcg_solver.py:996 — an intentional
        # nondeterminism we do not reproduce).
        self.un = jax.device_put(
            jnp.zeros((self.pm.n_parts, self.pm.n_loc), dtype),
            jax.NamedSharding(self.mesh, self._part_spec),
        )

        # History records (reference TimeList_*, pcg_solver.py:163-165)
        self.flags: List[int] = []
        self.relres: List[float] = []
        self.iters: List[int] = []
        self.step_times: List[float] = []

    # ------------------------------------------------------------------
    def step(self, delta: float) -> StepResult:
        t0 = time.perf_counter()
        un, flag, relres, iters = self._step_fn(
            self.data, self.un, jnp.asarray(delta, self.dtype))
        jax.block_until_ready(un)
        wall = time.perf_counter() - t0
        self.un = un
        res = StepResult(int(flag), float(relres), int(iters), wall)
        self.flags.append(res.flag)
        self.relres.append(res.relres)
        self.iters.append(res.iters)
        self.step_times.append(wall)
        return res

    def solve(self, on_step: Optional[Callable[[int, StepResult], None]] = None):
        """Run the full quasi-static schedule (skips step 0, like the
        reference's ``range(1, RefMaxTimeStepCount)``, pcg_solver.py:1002)."""
        deltas = self.config.time_history.time_step_delta
        results = []
        for t in range(1, len(deltas)):
            res = self.step(deltas[t])
            results.append(res)
            if on_step is not None:
                on_step(t, res)
        return results

    # ------------------------------------------------------------------
    # Host-side views for export
    # ------------------------------------------------------------------
    def owner_mask(self) -> np.ndarray:
        """(P, n_loc) bool — dofs this part owns (reference
        DofWeightVector_Export, pcg_solver.py:198)."""
        return (self.pm.weight > 0) & (self.pm.dof_gid >= 0)

    def export_dof_map(self) -> np.ndarray:
        """Global dof ids in export order (reference writes this once as the
        'Dof' map, pcg_solver.py:201)."""
        m = self.owner_mask()
        return self.pm.dof_gid[m]

    def displacement_owned(self) -> np.ndarray:
        """Owner-masked local solution values, concatenated in part order
        (the per-frame 'U_i' payload, pcg_solver.py:869)."""
        un = np.asarray(jax.device_get(self.un))
        return un[self.owner_mask()]

    def displacement_global(self) -> np.ndarray:
        """Full global solution vector (n_dof,), assembled on host."""
        out = np.zeros(self.pm.glob_n_dof, dtype=np.asarray(self.un).dtype)
        out[self.export_dof_map()] = self.displacement_owned()
        return out


def _data_specs(data: dict):
    """PartitionSpec pytree for the device data: per-type constant matrices
    are replicated, everything else is sharded on the leading parts axis."""
    P = jax.sharding.PartitionSpec
    blocks = [
        {k: (P() if k in ("Ke", "diag_Ke") else P(PARTS_AXIS)) for k in blk}
        for blk in data["blocks"]
    ]
    specs = {k: P(PARTS_AXIS) for k in data if k != "blocks"}
    specs["blocks"] = blocks
    return specs
