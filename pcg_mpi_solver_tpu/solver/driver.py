"""Quasi-static solve driver: the reference's main program re-designed.

Reference flow (pcg_solver.py:1002-1008): for each time step —
updateBC (Dirichlet lifting) -> updatePreconditioner (Jacobi rebuild) ->
PCG -> history/exports.  Here the whole step (lifting matvec + diagonal
assembly + the full PCG while_loop) is ONE jitted shard_map'd SPMD program
over the device mesh; only the small per-step scalars (flag/relres/iters)
come back to the host.
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.config import RunConfig
from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.obs.trace import (
    ConvergenceTrace, clamp_trace_len, empty_trace, trace_init, trace_specs,
    unpack_trace)
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS, make_mesh
from pcg_mpi_solver_tpu.parallel.partition import PartitionedModel, partition_model
from pcg_mpi_solver_tpu.resilience.faultinject import FaultPlan
from pcg_mpi_solver_tpu.solver.pcg import pcg, pcg_mixed

# The old `_vlog` stderr breadcrumb path is gone: dispatch-level
# breadcrumbs (which localize a hung remote compile/execution on tunneled
# TPUs) are now `note`/`dispatch` events through the solver's
# MetricsRecorder (obs/metrics.py).  PCG_TPU_VERBOSE=1 still enables the
# stderr sink on the default recorder — same knob, one logging path.

_PALLAS_PROBE: dict = {}


def _pallas_enabled(mode: str, mesh, shapes=()) -> bool:
    """Resolve the SolverConfig.pallas knob: "auto" enables the fused
    Mosaic kernel only on TPU devices (CPU runs use the interpretable XLA
    path; tests exercise the kernel via interpret=True) — and only after a
    compile probe of the ACTUAL kernel shapes succeeds, so a
    shape-dependent Mosaic lowering failure degrades to the XLA path at
    init instead of crashing the first jitted step."""
    if mode in ("on", "interpret"):
        # "interpret": run the kernel through the Pallas interpreter on
        # any backend — CI's way to exercise the REAL solver->kernel
        # dispatch (layout, batching, reshape order) without TPU hardware
        return True
    if mode == "off":
        return False
    if mode != "auto":
        raise ValueError(f"SolverConfig.pallas must be "
                         f"'auto'|'on'|'off'|'interpret', got {mode!r}")
    d = mesh.devices.flat[0]
    kind = f"{d.platform} {getattr(d, 'device_kind', '')}".lower()
    if "tpu" not in kind:
        return False
    from pcg_mpi_solver_tpu.ops.pallas_matvec import (
        probe_shapes, selected_variant)

    # the planes knob changes what the v3 variant lowers to, so a probe
    # cached under one value must not vouch for another
    key = (d.platform, selected_variant()[0],
           os.environ.get("PCG_TPU_PALLAS_PLANES", "8"), tuple(shapes))
    if key not in _PALLAS_PROBE:
        try:
            probe_shapes(list(shapes) or [((3, 3, 3, 3), (2, 2, 2))])
            ok = True
        except Exception as e:                      # noqa: BLE001
            import warnings

            warnings.warn(f"Pallas matvec unavailable on {kind} "
                          f"({type(e).__name__}: {e}); using the XLA path")
            ok = False
            # A failed remote compile can wedge the device grant for
            # minutes (docs/RUNBOOK.md) — observed wave 3: the flagship's
            # XLA compile died UNAVAILABLE right after ten Mosaic probe
            # failures.  Settle: verify the compile service answers again
            # before handing control to the real compile.
            from pcg_mpi_solver_tpu.utils.backend_probe import settle_compile

            settled, detail = settle_compile()
            if not settled:
                warnings.warn(f"compile service still unsettled after "
                              f"failed Pallas probe ({detail}); the next "
                              f"compile may fail UNAVAILABLE")
        if jax.process_count() > 1:
            # One SPMD program, one kernel: all processes must agree, else
            # hosts would silently run different matvecs (and the resume
            # fingerprint would only record the primary's choice).
            from jax.experimental import multihost_utils

            # consensus-exempt: unconditional data gather reached by
            # every process (the AND below is itself the agreement)
            all_ok = multihost_utils.process_allgather(
                np.asarray([ok], dtype=bool))
            ok = bool(np.all(all_ok))
        _PALLAS_PROBE[key] = ok
    return _PALLAS_PROBE[key]


@dataclasses.dataclass
class StepResult:
    flag: int
    relres: float
    iters: int
    wall_s: float


def normalize_rhs_block(fexts, n_dof: int, dtype=None) -> np.ndarray:
    """ONE authoritative normalization of a solve_many request to the
    (n_dof, nrhs) column contract: a single (n_dof,) vector promotes to
    one column, a stacked (nrhs, n_dof) list transposes when
    unambiguous.  Shared by Solver.solve_many and the CLI front-end so
    the shape heuristic cannot diverge between entry points.  With
    ``dtype=None`` the input dtype is kept (a shape-only pass: no
    full-block copy when the caller just needs the width)."""
    fb = np.asarray(fexts) if dtype is None \
        else np.asarray(fexts, dtype=dtype)
    if fb.ndim == 1:
        fb = fb[:, None]
    elif fb.ndim == 2 and fb.shape[0] != n_dof and fb.shape[1] == n_dof:
        fb = fb.T
    return fb


@dataclasses.dataclass
class ManySolveResult:
    """Per-RHS outcome of a batched :meth:`Solver.solve_many` block:
    flags/relres/iters are (nrhs,) per-column vectors (MATLAB pcg flag
    taxonomy per column, plus flag 5 = quarantined — see
    ``solver/pcg.QUARANTINE_FLAG`` and docs/RUNBOOK.md "Blocked solve
    failure modes & quarantine"), ``x`` the device-resident blocked
    solution (n_parts, n_loc, nrhs) on effective dofs — fetch global
    per-column vectors with :meth:`Solver.displacement_global_many`."""
    flags: np.ndarray
    relres: np.ndarray
    iters: np.ndarray
    wall_s: float
    x: object = None

    # wall of the Krylov work alone (staging — validation, the
    # global->local map, the device upload — excluded): the honest
    # per-iteration denominator for nrhs A/Bs, since the scalar step()
    # baseline derives its rhs in-graph from device-resident data
    solve_wall_s: float = 0.0

    # fault isolation between columns (resilience/): the column indices
    # that ended QUARANTINED (flag 5 — unrecoverable breakdown/poison,
    # reported as their min-residual iterate + true residual), the total
    # per-column recovery-ladder attempts consumed, and the fused
    # residual-drift check count (0 for classic)
    quarantined: tuple = ()
    recoveries: int = 0
    drift: int = 0

    @property
    def nrhs(self) -> int:
        return int(len(self.flags))


class Solver:
    """Owns the partitioned model on the device mesh and runs time steps."""

    def __init__(
        self,
        model: ModelData,
        config: Optional[RunConfig] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        n_parts: Optional[int] = None,
        elem_part: Optional[np.ndarray] = None,
        backend: str = "auto",   # "auto" | "structured" | "hybrid" | "general"
        recorder: Optional[MetricsRecorder] = None,
    ):
        self._t_init0 = time.perf_counter()
        self.config = config or RunConfig()
        # Telemetry: an injected recorder wins; otherwise build the default
        # (stderr sink iff PCG_TPU_VERBOSE=1 — the historical knob — plus a
        # JSONL sink iff config.telemetry_path is set).
        self.recorder = recorder if recorder is not None else (
            MetricsRecorder.default(
                jsonl_path=self.config.telemetry_path or None,
                profile=True if self.config.telemetry_profile else None))
        self._rec = self.recorder
        # ---- flight recorder (obs/flight.py): crash-durable begin/end
        # brackets + heartbeats around every solve dispatch, so a tunnel
        # death / SIGKILL mid-solve leaves a parseable artifact instead
        # of a log to hand-reconstruct (the BENCH_r05 provenance mode).
        from pcg_mpi_solver_tpu.obs.flight import attach_flight

        self._flight = attach_flight(
            self._rec, self.config.flight_path, "solver",
            pcg_variant=self.config.solver.pcg_variant,
            precond=self.config.solver.precond)
        # ---- preflight gate (validate/): reject a pathological model or
        # config BEFORE any partition build or XLA compile is paid (the
        # flagship pays minutes of both).  Policy: config.preflight >
        # PCG_TPU_PREFLIGHT > fail.
        from pcg_mpi_solver_tpu.validate import run_preflight

        # No n_steps in the context: on this path snapshot_every counts
        # CHUNK boundaries, not time steps, so the cadence-vs-schedule
        # cross-check does not apply (it would false-warn on every
        # protected multi-chunk solve).
        run_preflight(model, self.config, recorder=self._rec,
                      context={"kind": "quasi_static"})
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        if n_parts is None:
            n_parts = max(self.config.n_parts, n_dev)
        if n_parts < 1:
            raise ValueError(f"n_parts must be >= 1, got {n_parts}")
        if n_parts % n_dev != 0:
            raise ValueError(f"n_parts={n_parts} must be a multiple of device count {n_dev}")

        solver_cfg = self.config.solver
        from pcg_mpi_solver_tpu.ops.precond import VALID_PRECONDS
        from pcg_mpi_solver_tpu.solver.pcg import VALID_PCG_VARIANTS

        if solver_cfg.precond not in VALID_PRECONDS:
            raise ValueError(f"SolverConfig.precond must be one of "
                             f"{VALID_PRECONDS}, got {solver_cfg.precond!r}")
        if solver_cfg.pcg_variant not in VALID_PCG_VARIANTS:
            raise ValueError(
                f"SolverConfig.pcg_variant must be one of "
                f"{VALID_PCG_VARIANTS}, got {solver_cfg.pcg_variant!r}")
        self.mixed = solver_cfg.precision_mode == "mixed"
        dtype = jnp.dtype(jnp.float64) if self.mixed else jnp.dtype(solver_cfg.dtype)
        dot_dtype = jnp.dtype(solver_cfg.dot_dtype)
        if self.mixed or jnp.float64 in (dtype, dot_dtype):
            if not jax.config.jax_enable_x64:
                # The config asked for f64 math — honor it rather than
                # silently downgrading (the reference is f64 throughout).
                jax.config.update("jax_enable_x64", True)
        self.dtype = dtype

        # ---- warm-path cache + donated-carry dispatch (cache/) -----------
        # cache_dir set => partitions come from the content-addressed
        # on-disk cache, the one-shot PCG step is AOT-exported (zero
        # re-tracing on a warm run), and jax's persistent XLA compilation
        # cache is wired to <cache_dir>/xla (zero re-compile).  The model
        # fingerprint is the content half of every cache key.
        self._donate = bool(solver_cfg.donate_carry)
        self._cache_dir = (self.config.cache_dir or "").strip() or None
        self._model_fp = None
        # Counter baseline for THIS construction: the recorder may be a
        # process-lifetime one (bench._REC) whose cache counters already
        # carry earlier solvers' hits/misses — setup_cache must reflect
        # only the partitions this __init__ resolved.
        self._cache_hm0 = (
            self._rec.counters.get("cache.partition.hit", 0),
            self._rec.counters.get("cache.partition.miss", 0))
        if self._cache_dir:
            from pcg_mpi_solver_tpu.cache.aot import (
                enable_persistent_compilation_cache)
            from pcg_mpi_solver_tpu.cache.keys import model_fingerprint

            enable_persistent_compilation_cache(self._cache_dir)
            with self._rec.span("cache_fingerprint"):
                self._model_fp = model_fingerprint(model)
            self._rec.gauge("cache.dir", self._cache_dir)

        # ---- backend selection: structured slab fast path when possible ----
        # (TPU has no vector gather/scatter; the structured path replaces
        # them with contiguous slice shifts, parallel/structured.py.)
        can_structured = (
            model.grid is not None
            and not model.elem_sign_flat.any()
            and not model.intfc_elems
            and n_parts == n_dev
            # An explicitly requested non-default partitioner (method or an
            # elem_part array) must not be silently replaced by the
            # structured slab partition.
            and self.config.partition_method in ("rcb", "auto")
            and elem_part is None
            and model.grid[0] % n_parts == 0
        )
        if backend not in ("auto", "structured", "hybrid", "general"):
            raise ValueError(f"backend must be 'auto'|'structured'|'hybrid'|"
                             f"'general', got {backend!r}")
        setup_shard = getattr(self.config, "setup_shard", "auto")
        if setup_shard not in ("auto", "on", "off"):
            raise ValueError(f"RunConfig.setup_shard must be "
                             f"'auto'|'on'|'off', got {setup_shard!r}")
        # Kernel variant is FIXED at construction (the env knob is read at
        # trace time); the checkpoint fingerprint must record what this
        # solver actually compiled, not the env at save() time.
        self.pallas_variant = "off"
        # f64-refresh formulation (hybrid+mixed only; see the hybrid
        # branch below).  Recorded in the checkpoint fingerprint: the
        # general form's summation order differs, which can drift
        # refresh residuals in the last bits.
        self.f64_refresh = "stencil"
        self._refresh64_src = None
        if backend == "structured" and not can_structured:
            raise ValueError("structured backend requested but model/partition "
                             "layout does not allow it")
        from pcg_mpi_solver_tpu.parallel.hybrid import can_hybrid as _can_hy

        can_hybrid = _can_hy(model)
        if backend == "hybrid" and not can_hybrid:
            raise ValueError("hybrid backend requested but model has no "
                             "octree/brick metadata")
        # Hybrid demotion gate (ISSUE 14 satellite): dry-runs put the
        # hybrid partition build at 117-183 s where structured takes
        # 10.5 s at the same scale (ROADMAP item 2), and its level-grid
        # stencil compile costs minutes per instantiation — AUTO
        # selection now requires the explicit PCG_TPU_ENABLE_HYBRID=1
        # opt-in (docs/RUNBOOK.md "Scaling the setup path" carries the
        # deprecation note).  An EXPLICIT backend="hybrid" request is
        # honored unchanged — the gate only stops silent auto-routing.
        hybrid_ok = os.environ.get("PCG_TPU_ENABLE_HYBRID") == "1"
        if backend in ("auto", "structured") and can_structured:
            self.backend = "structured"
        elif backend == "hybrid" and can_hybrid:
            self.backend = "hybrid"
        elif backend == "auto" and can_hybrid and hybrid_ok:
            self.backend = "hybrid"
        else:
            if backend == "auto" and can_hybrid and not hybrid_ok:
                self._rec.note(
                    "model is hybrid-backend eligible but auto-selection "
                    "is gated (set PCG_TPU_ENABLE_HYBRID=1 or pass "
                    "backend='hybrid'); using the general backend — see "
                    "RUNBOOK 'Scaling the setup path'")
            self.backend = "general"

        # ---- sharded setup (ISSUE 14): under multi-process
        # jax.distributed, build/load only THIS process's parts of the
        # partition (the general/structured builders take part_range; the
        # global layout merges via HostComm reductions) — the cold path
        # then scales with process count instead of model size.  The
        # hybrid backend keeps the monolithic build (level grids are not
        # part-sharded).
        from pcg_mpi_solver_tpu.parallel.distributed import (
            HostComm, local_part_range)

        self._setup_range = None
        self._setup_comm = None
        self.partition_build_s = 0.0
        if (setup_shard != "off" and jax.process_count() > 1
                and self.backend in ("general", "structured")):
            rng = local_part_range(self.mesh, n_parts)
            # equal contiguous slabs only: the glue exchange allgathers
            # same-shaped blocks from every process
            ok = (rng is not None and rng != (0, n_parts)
                  and (rng[1] - rng[0]) * jax.process_count() == n_parts)
            # The engage decision GATES collective code paths (warmup,
            # the layout exchange, the glue allgathers) — it must be
            # GROUP-AGREED: an exotic device order can make one
            # process's parts non-contiguous while another's pass, and
            # a split decision deadlocks the group on its first
            # unmatched collective.  Every process reaches this reduce
            # (the inputs above are process-invariant).
            from pcg_mpi_solver_tpu.parallel.consensus import agree_flag

            comm = HostComm()
            if agree_flag(comm, ok):
                self._setup_range = rng
                self._setup_comm = comm
                from pcg_mpi_solver_tpu.parallel.partition import (
                    layout_exchange_sizes)

                with self._rec.span("setup_comm_warmup"):
                    self._setup_comm.warmup(layout_exchange_sizes(
                        model.n_dof, model.n_node,
                        len(model.elem_lib), n_parts))
            elif setup_shard == "on":
                raise ValueError(
                    "RunConfig.setup_shard='on' but some process's parts "
                    "are not one contiguous equal block of the mesh (use "
                    "make_global_mesh, or n_parts divisible by the "
                    "device count)")

        interp = solver_cfg.pallas == "interpret"
        if self.backend == "structured":
            from pcg_mpi_solver_tpu.parallel.structured import (
                StructuredOps, device_data_structured, partition_structured)

            self.pm = self._partition_cached(
                "structured",
                lambda part_range=None: partition_structured(
                    model, n_parts, part_range=part_range),
                n_parts=n_parts, shard=True)
            sp = self.pm
            use_pallas = _pallas_enabled(
                solver_cfg.pallas, self.mesh,
                shapes=(((3, sp.nxc + 1, sp.ny + 1, sp.nz + 1),
                         (sp.nxc, sp.ny, sp.nz)),))
            if use_pallas:
                from pcg_mpi_solver_tpu.ops.pallas_matvec import (
                    selected_variant)

                self.pallas_variant = selected_variant()[0]
            self.ops = StructuredOps.from_partition(
                self.pm, dot_dtype=dot_dtype, axis_name=PARTS_AXIS,
                use_pallas=use_pallas, pallas_interpret=interp)
            data = device_data_structured(self.pm, dtype)
            ops32_factory = lambda: StructuredOps.from_partition(
                self.pm, dot_dtype=jnp.float32, axis_name=PARTS_AXIS,
                use_pallas=use_pallas, pallas_interpret=interp)
        elif self.backend == "hybrid":
            from pcg_mpi_solver_tpu.parallel.hybrid import (
                HybridOps, device_data_hybrid, hybrid_pallas_enabled,
                partition_env_knobs, partition_hybrid)

            # PCG_TPU_HYBRID_F64_REFRESH: formulation of the out-of-loop
            # f64 matvecs (Dirichlet lifting, r0, refinement
            # true-residual — ~4 calls/solve).  Default BUCKETED: a full
            # general element partition with the 200+ per-type
            # structures stacked into a few padded batched einsums.
            # Chipless compile at the 5.67M-dof flagship (BENCH_LOG
            # 2026-08-01): stencil 999 s / general 1343 s / bucketed
            # (5 buckets) 425 s — compile cost tracks emitted structure
            # count, and the f64 stencil amul was the flagship's single
            # largest program.  Runtime is per-cycle, so compile
            # dominates the session economics; "stencil" forces the old
            # form (slightly less HBM, fastest execution).  Needs the
            # SAME elem_part so the local dof numbering is identical
            # (partition_model's numbering is block_filter-independent).
            self.f64_refresh = "stencil"
            _knob = os.environ.get("PCG_TPU_HYBRID_F64_REFRESH",
                                   "bucketed")
            if _knob not in ("stencil", "general", "bucketed"):
                # the mode drives checkpoint fingerprints and a 2.35x
                # compile-cost spread — a typo must not silently pick one
                raise ValueError(
                    f"PCG_TPU_HYBRID_F64_REFRESH={_knob!r}: expected "
                    "'bucketed' (default), 'stencil' or 'general'")
            if self.mixed and _knob in ("general", "bucketed"):
                self.f64_refresh = _knob
            method = self.config.partition_method
            self.pm = self._partition_cached(
                "hybrid",
                lambda: partition_hybrid(model, n_parts,
                                         elem_part=elem_part,
                                         method=method),
                n_parts=n_parts, method=method, elem_part=elem_part,
                # every partition-time env knob keys the entry, resolved
                # by the module that owns the defaults (block/merge
                # reshape the level grids, combine/kd shape CombineMaps)
                extra=partition_env_knobs())
            if self.f64_refresh in ("general", "bucketed") \
                    and elem_part is None:
                # The general-refresh partition below must use the SAME
                # element->part map (identical local dof numbering).  A
                # cache hit skipped make_elem_part entirely, so recover
                # the map from the partition itself.
                elem_part = np.asarray(self.pm.elem_part)
            use_pallas = hybrid_pallas_enabled(
                self.pm, solver_cfg.pallas, self.mesh)
            if use_pallas:
                from pcg_mpi_solver_tpu.ops.pallas_matvec import (
                    selected_variant)

                self.pallas_variant = selected_variant()[0]
            from pcg_mpi_solver_tpu.parallel.hybrid import local_parts

            lp = local_parts(n_parts, self.mesh)
            self.ops = HybridOps.from_hybrid(
                self.pm, dot_dtype=dot_dtype, axis_name=PARTS_AXIS,
                use_pallas=use_pallas, n_local_parts=lp,
                pallas_interpret=interp)
            data = device_data_hybrid(self.pm, dtype)
            ops32_factory = lambda: HybridOps.from_hybrid(
                self.pm, dot_dtype=jnp.float32, axis_name=PARTS_AXIS,
                use_pallas=use_pallas, n_local_parts=lp,
                pallas_interpret=interp)
            if self.f64_refresh in ("general", "bucketed"):
                pm_full = self._partition_cached(
                    "general",
                    lambda: partition_model(model, n_parts,
                                            elem_part=elem_part),
                    n_parts=n_parts, method="explicit",
                    elem_part=elem_part)
                if not (pm_full.n_loc == self.pm.n_loc
                        and np.array_equal(pm_full.node_gid,
                                           self.pm.node_gid)):
                    raise RuntimeError(
                        "general-refresh partition numbering diverged "
                        "from the hybrid partition (same elem_part must "
                        "yield identical local dof layouts)")
                if self.f64_refresh == "bucketed" and pm_full.ell is None:
                    # bucketing needs the 3-dof node layout (its gather/
                    # scatter move node rows); models that break it
                    # (e.g. node-less spring dofs, partition.py) degrade
                    # to the unbucketed general form instead of failing
                    # a construction that both older forms handled
                    import warnings

                    warnings.warn(
                        "PCG_TPU_HYBRID_F64_REFRESH=bucketed needs the "
                        "node layout; using 'general' for this model")
                    self.f64_refresh = "general"
                if self.f64_refresh == "bucketed":
                    from pcg_mpi_solver_tpu.ops.matvec import (
                        build_bucketed_blocks)

                    rdata = device_data(pm_full, jnp.float64, blocks=False)
                    rdata["buckets"] = build_bucketed_blocks(
                        pm_full, jnp.float64)
                else:
                    rdata = device_data(pm_full, jnp.float64)
                self._refresh64_src = (
                    Ops.from_model(pm_full, dot_dtype=jnp.float64,
                                   axis_name=PARTS_AXIS),
                    rdata)
        else:
            method = self.config.partition_method
            extra = {}
            if method == "slab2":
                # two-level split: the coarse slab count is structural
                # (a different count = a different partition) — one slab
                # per process so each process refines only its own slab.
                # A function of the PROCESS TOPOLOGY alone, never of
                # whether sharding engaged: toggling setup_shard (a
                # TRACE_NEUTRAL_RUNCONFIG field) must not change the
                # element partition.
                extra["slab2_slabs"] = jax.process_count()
            self.pm = self._partition_cached(
                "general",
                lambda part_range=None: partition_model(
                    model, n_parts, elem_part=elem_part, method=method,
                    part_range=part_range, comm=self._setup_comm,
                    slab2_slabs=extra.get("slab2_slabs", 1)),
                n_parts=n_parts, method=method,
                elem_part=elem_part, extra=extra, shard=True)
            self.ops = Ops.from_model(self.pm, dot_dtype=dot_dtype,
                                      axis_name=PARTS_AXIS)
            data = device_data(self.pm, dtype)
            ops32_factory = lambda: Ops.from_model(
                self.pm, dot_dtype=jnp.float32, axis_name=PARTS_AXIS)

        if self._setup_range is not None:
            # Sharded setup: re-assemble the small host-side EXPORT GLUE
            # (owner masks + global id maps — gather_owned_global,
            # solve_many staging, export maps read ALL parts' rows) from
            # every process's slab.  The heavy per-part structures stay
            # local; this is O(P * n_loc) ids, not O(model).
            self._exchange_export_glue(self.pm)

        # ---- MG hierarchy (precond="mg" — ops/mg.py): host-built level
        # lattice + transfers into the device data tree, the Chebyshev
        # degree pinned on the ops (it shapes the traced V-cycle).  The
        # hybrid backend is out of scope by design: its level-grid
        # stencil costs minutes of compile PER INSTANTIATION and the
        # cycle adds 2*degree more.
        self._mg_meta = None
        self._mg_setup = None
        if solver_cfg.precond == "mg":
            if self.backend == "hybrid":
                raise ValueError(
                    "precond='mg' is not supported on the hybrid "
                    "level-grid backend; use backend='general' or "
                    "'structured' (or precond='jacobi'|'block3')")
            from pcg_mpi_solver_tpu.ops import mg as mgmod

            t_mg0 = time.perf_counter()
            with self._rec.span("mg_setup"):
                mg_setup = self._build_mg_cached(model, solver_cfg)
            # float leaves at the STORAGE dtype (mgmod.cast_tree); the
            # mixed shadow below re-derives its f32 copy
            data["mg"] = mgmod.cast_tree(mg_setup.tree, dtype)
            self._mg_meta = mg_setup.meta
            self._mg_setup = (mg_setup, time.perf_counter() - t_mg0)
            deg = int(solver_cfg.mg_smooth_degree)
            cdofs = mgmod.coarse_dofs(mg_setup.meta)
            self.ops = dataclasses.replace(self.ops, mg_degree=deg,
                                           mg_coarse_dofs=cdofs)
            _base32_factory = ops32_factory
            ops32_factory = lambda: dataclasses.replace(
                _base32_factory(), mg_degree=deg, mg_coarse_dofs=cdofs)

        if self.mixed:
            # f32 shadow of the float leaves; index/bool arrays are shared
            # (same device buffers), so the extra memory is only the f32 floats.
            data = {
                "f64": data,
                "f32": jax.tree.map(
                    lambda x: x.astype(jnp.float32)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, data),
            }
            self.ops32 = ops32_factory()
        self._specs = _data_specs(data)
        # Multi-host aware upload: each process materializes only its
        # addressable shards (parallel/distributed.py).
        from pcg_mpi_solver_tpu.parallel.distributed import put_tree

        self.data = put_tree(data, self.mesh, self._specs)
        self._refresh64 = None
        if self._refresh64_src is not None:
            rops, rdata = self._refresh64_src
            rspecs = _data_specs(rdata)
            self._refresh64 = (rops, put_tree(rdata, self.mesh, rspecs),
                               rspecs)
            self._refresh64_src = None      # free the host copies

        self._part_spec = jax.sharding.PartitionSpec(PARTS_AXIS)
        self._rep_spec = jax.sharding.PartitionSpec()

        if solver_cfg.precond == "mg":
            # fine-level Chebyshev bound: a few power-iteration matvecs
            # on the uploaded operator (cached in the partition cache —
            # warm runs skip the device work), then the per-level lambda
            # vector joins the device tree and the setup telemetry/
            # degenerate-interval warning fire
            self._finish_mg_setup(solver_cfg)

        glob_n_eff = self.pm.glob_n_dof_eff

        # Static telemetry gauges: problem size, backend, and the per-PCG-
        # iteration collective estimate from the ops shapes (psum count /
        # payload bytes) — reported in the run_summary event.
        self._rec.gauge("backend", self.backend)
        self._rec.gauge("n_parts", int(self.pm.n_parts))
        self._rec.gauge("n_dof", int(self.pm.glob_n_dof))
        self._rec.gauge("precision_mode", solver_cfg.precision_mode)
        self._rec.gauge("pcg_variant", solver_cfg.pcg_variant)
        self._rec.gauge("precond", solver_cfg.precond)
        # mixed mode: the Krylov iterations (vectors AND dot reductions)
        # run on the f32 ops, so that is the ops object to size from;
        # the variant sets the per-iteration collective count (fused =
        # one scalar psum, classic = three)
        est_ops = self.ops32 if self.mixed else self.ops
        iter_dtype = jnp.float32 if self.mixed else dtype
        for k, v in est_ops.comm_estimate(
                storage_dtype=iter_dtype,
                variant=solver_cfg.pcg_variant,
                precond=solver_cfg.precond).items():
            self._rec.gauge(f"comm.{k}", v)

        # Analytic per-iteration cost model (obs/perf.py, ISSUE 12):
        # roofline-predicted ms/iter per phase for the engaged
        # (variant, precond, nrhs, backend), emitted as a schema-
        # versioned `cost_model` event + perf.* gauges so every
        # telemetry stream carries the number its measured ms/iter
        # should be judged against.  An unknown variant/precond is a
        # loud KeyError (the single-source-table contract) — kept loud
        # ONLY for the cost_model() table lookup itself; any hiccup in
        # shape derivation, profile resolution or event emission on an
        # exotic model degrades to a note — the model is observability,
        # not a solve dependency.
        from pcg_mpi_solver_tpu.obs import perf as _perf

        self._perf_shape = None
        self._perf_profile = None
        self._cost_model = None
        self._cost_models_by_width: Dict[int, Any] = {}
        try:
            shape = _perf.shape_from_solver(self)
            profile = _perf.resolve_profile(
                self.mesh.devices.flat[0].platform)
        except Exception as e:                          # noqa: BLE001
            self._rec.note(f"cost_model unavailable: "
                           f"{type(e).__name__}: {e}")
        else:
            try:
                cm = _perf.cost_model(
                    shape, solver_cfg.pcg_variant, solver_cfg.precond,
                    max(1, int(solver_cfg.nrhs)), profile)
            except KeyError:
                raise       # unknown variant/precond stays loud
            except Exception as e:                      # noqa: BLE001
                self._rec.note(f"cost_model unavailable: "
                               f"{type(e).__name__}: {e}")
            else:
                self._perf_shape = shape
                self._perf_profile = profile
                self._cost_model = cm
                self._cost_models_by_width[
                    max(1, int(solver_cfg.nrhs))] = cm
                try:
                    _perf.emit_cost_model(self._rec, cm)
                except Exception as e:                  # noqa: BLE001
                    self._rec.note(f"cost_model emission failed: "
                                   f"{type(e).__name__}: {e}")

        # In-graph convergence trace: ring length (0 = off) and its float
        # dtype — the dot dtype of whatever runs the Krylov iterations
        # (f32 for the mixed inner cycles, whose records are rescaled to
        # absolute residuals).
        self.trace_len = (clamp_trace_len(solver_cfg.trace_resid,
                                          solver_cfg.max_iter)
                          if solver_cfg.trace_resid > 0 else 0)
        self._trace_dtype = (jnp.float32 if self.mixed
                             else jnp.dtype(solver_cfg.dot_dtype))
        self.last_trace: Optional[ConvergenceTrace] = None
        trace_len = self.trace_len

        def _step(data, un_prev, delta):
            # Host-side trace counter: runs ONLY while jax traces this
            # function.  The warm-path contract — an AOT-cache hit
            # re-runs the step with ZERO tracing — is asserted against it
            # (tests/test_cache.py).
            self._rec.inc("trace.step")
            data64 = data["f64"] if self.mixed else data
            eff = data64["eff"]
            # Dirichlet lifting: Fext = F*delta - K.(Ud*delta)
            # (reference updateBC, pcg_solver.py:226-238)
            udi = data64["Ud"] * delta
            fdi = self.ops.matvec(data64, udi)
            fext = eff * (data64["F"] * delta - fdi)
            x0 = eff * un_prev
            trace0 = (trace_init(trace_len, self._trace_dtype)
                      if trace_len else None)
            if self.mixed:
                data32 = data["f32"]
                # preconditioner rebuild in f32 (pcg_solver.py:346-352)
                inv_diag32 = self._make_prec(self.ops32, data32)
                res = pcg_mixed(
                    self.ops32, data32, self.ops, data64,
                    fext, x0, inv_diag32,
                    tol=solver_cfg.tol, max_iter=solver_cfg.max_iter,
                    glob_n_dof_eff=glob_n_eff,
                    max_stag_steps=solver_cfg.max_stag_steps,
                    inner_tol=solver_cfg.inner_tol,
                    plateau_window=solver_cfg.mixed_plateau_window,
                    progress_window=solver_cfg.mixed_progress_window,
                    progress_ratio=solver_cfg.mixed_progress_ratio,
                    progress_min_gain=solver_cfg.mixed_progress_min_gain,
                    trace_in=trace0,
                    variant=solver_cfg.pcg_variant,
                )
            else:
                # preconditioner rebuild (pcg_solver.py:346-352)
                inv_diag = self._make_prec(self.ops, data64)
                res = pcg(
                    self.ops, data64, fext, x0, inv_diag,
                    tol=solver_cfg.tol, max_iter=solver_cfg.max_iter,
                    glob_n_dof_eff=glob_n_eff,
                    max_stag_steps=solver_cfg.max_stag_steps,
                    trace_in=trace0,
                    variant=solver_cfg.pcg_variant,
                )
            if trace_len:
                res, trace = res
            un = res.x + udi
            out = (un, res.flag, res.relres, res.iters)
            return out + ((trace,) if trace_len else ())

        R = self._rep_spec
        step_out = (self._part_spec, R, R, R) + (
            (trace_specs(R),) if trace_len else ())
        shard_step = jax.shard_map(
            _step,
            mesh=self.mesh,
            in_specs=(self._specs, self._part_spec, self._rep_spec),
            out_specs=step_out,
            check_vma=False,
        )
        # Donated previous-solution vector: the step's output un replaces
        # its input un_prev 1:1 (same shape/dtype/sharding), so XLA may
        # alias the buffers instead of copying.  The attribute rebinding
        # in step() is the only live reference either way.
        donate_step = (1,) if self._donate else ()
        self._step_fn = jax.jit(shard_step, donate_argnums=donate_step)
        # kept lowerable even when the AOT warm path replaces _step_fn
        # below: obs/profview.scope_map_from_solver re-lowers THIS to
        # read the compiled op_name metadata (named-scope -> phase map)
        self._step_fn_jit = self._step_fn

        # ---- dispatch-chunked solve path (large problems) -----------------
        # (solver/chunked.py; auto-engaged above ~4M dofs)
        from pcg_mpi_solver_tpu.solver.chunked import auto_dispatch_cap

        self._dispatch_cap = auto_dispatch_cap(
            solver_cfg, self.pm.glob_n_dof,
            self.pm.n_loc * (self.pm.n_parts // n_dev),
            force_engage=self.backend == "hybrid")
        # ---- resilience subsystem (resilience/): recovery ladder, mid-
        # Krylov snapshots, dispatch guard, deterministic fault injection.
        # All chunked-path-only; the one-shot path keeps its donated-carry
        # zero-state restore (step(), below).  `fault_plan` is settable
        # (tests inject programmatically; PCG_TPU_FAULTS drives chaos runs).
        self.fault_plan = FaultPlan.from_env(recorder=self._rec)
        self._resume_pending = False     # solve(resume=True) arms mid-step
        #                                  snapshot resume for its steps
        self._snap_store = None          # lazy: fingerprints the model once
        self._group_comm = None          # lazy: guarded multi-proc HostComm
        self._elastic_dir = None         # resume_elastic() arms the named
        #                                  n_procs-mismatch resume path
        self._many_progs = {}            # nrhs -> jitted blocked programs
        self._many_snap = {}             # nrhs -> blocked snapshot store
        self._restart_post_fn = None     # lazy: ladder restart program
        self._fallback_prec_fn = None    # lazy: scalar-Jacobi fallback
        self._esc_engine = None          # lazy: f64 escalation engine
        self._esc_prec_fn = None
        if self._dispatch_cap > 0:
            self._build_chunked(solver_cfg, glob_n_eff)
        elif self._cache_dir:
            # AOT warm path for the one-shot step program (the chunked
            # programs rely on the persistent XLA cache + warmup()): a
            # cache hit deserializes the exported StableHLO — zero
            # re-tracing of _step — and its compile hits <cache_dir>/xla.
            aot_step = self._build_aot_step(shard_step, donate_step)
            if aot_step is not None:
                self._step_fn = aot_step

        # Initial state: deterministic zeros (the reference seeds Un with
        # unseeded 1e-200*rand, pcg_solver.py:996 — an intentional
        # nondeterminism we do not reproduce).
        from pcg_mpi_solver_tpu.parallel.distributed import put_sharded

        self.un = put_sharded(
            np.zeros((self.pm.n_parts, self.pm.n_loc), dtype),
            self.mesh, self._part_spec)

        self._export_fn = None
        self._nu = float(model.mat_prop[0]["Pos"]) if model.mat_prop else 0.2
        self._model = model          # kept for host-side export paths (NS)
        self._nonlocal = None        # lazily built nonlocal weight operator

        # History records (reference TimeList_*, pcg_solver.py:163-165)
        self.flags: List[int] = []
        self.relres: List[float] = []
        self.iters: List[int] = []
        self.step_times: List[float] = []
        self._probe_u: List[np.ndarray] = []
        self._export_wall: float = 0.0
        # Steps timed in THIS process (not checkpoint-restored): the compile
        # estimate must compare a first step that actually paid the compile.
        self._proc_step_times: List[float] = []

        # Setup attribution (bench setup_s / warm-path triage): wall from
        # construction start to ready-to-step, plus whether the partition
        # came cold (built) or warm (cache).
        self.setup_s = time.perf_counter() - self._t_init0
        hits = self._rec.counters.get("cache.partition.hit", 0) \
            - self._cache_hm0[0]
        miss = self._rec.counters.get("cache.partition.miss", 0) \
            - self._cache_hm0[1]
        self.setup_cache = ("off" if not self._cache_dir
                            else "warm" if hits and not miss else "cold")
        self._rec.gauge("setup_s", round(self.setup_s, 3))
        self._rec.gauge("setup.cache", self.setup_cache)
        self._rec.gauge("setup.partition_build_s",
                        round(self.partition_build_s, 3))
        if self._setup_range is not None:
            # setup-phase shard attribution (ISSUE 14): which parts this
            # process built/loaded and whether the partition came warm —
            # the flight-recorder-grade record the setup ladder and the
            # sharded-warm-start tests read
            self._rec.event(
                "setup_shard", parts=list(self._setup_range),
                n_parts=int(self.pm.n_parts),
                cold=self.setup_cache != "warm",
                partition_build_s=round(self.partition_build_s, 6),
                setup_s=round(self.setup_s, 6))

    # ------------------------------------------------------------------
    def _exchange_export_glue(self, pm):
        """Sharded setup: allgather the per-part export-glue rows (owner
        weights + global id maps) so host-side global views
        (gather_owned_global, owner_mask, solve_many staging) keep their
        all-parts contract while everything heavy stays per-process.
        Each process contributes exactly its built slab; slabs tile
        [0, n_parts) by construction (local_part_range).  Packed into
        TWO collectives (one int64 buffer for ranges+id maps, one
        float64 for the weights) — each allgather costs a dispatch and
        a per-shape compile, and this runs on EVERY sharded
        construction, warm starts included."""
        from jax.experimental import multihost_utils as mh

        lo, hi = self._setup_range
        names = ("dof_gid", "node_gid", "weight", "node_weight")
        arrs = {n: np.asarray(getattr(pm, n)) for n in names
                if getattr(pm, n, None) is not None}
        ints = np.concatenate(
            [np.asarray([lo, hi], dtype=np.int64)]
            + [arrs[n][lo:hi].ravel().astype(np.int64)
               for n in ("dof_gid", "node_gid") if n in arrs])
        flts = np.concatenate(
            [arrs[n][lo:hi].ravel().astype(np.float64)
             for n in ("weight", "node_weight") if n in arrs]
            or [np.zeros(0)])
        # consensus-exempt: unconditional layout data exchange — every
        # engaged process reaches both gathers (engage is group-agreed)
        g_int = np.asarray(mh.process_allgather(ints))
        g_flt = np.asarray(mh.process_allgather(flts))
        for proc in range(g_int.shape[0]):
            l, h = int(g_int[proc, 0]), int(g_int[proc, 1])
            pos_i, pos_f = 2, 0
            for n in names:
                if n not in arrs:
                    continue
                full = arrs[n]
                rows = (h - l,) + full.shape[1:]
                cnt = int(np.prod(rows))
                if n in ("dof_gid", "node_gid"):
                    blk = g_int[proc, pos_i:pos_i + cnt]
                    pos_i += cnt
                else:
                    blk = g_flt[proc, pos_f:pos_f + cnt]
                    pos_f += cnt
                full[l:h] = blk.reshape(rows).astype(full.dtype)
        for n, full in arrs.items():
            setattr(pm, n, full)

    def _make_prec(self, ops, d):
        """Preconditioner inverse per config.solver.precond: scalar Jacobi
        (P, n_loc), 3x3 node-block Jacobi (P, n_node_loc, 3, 3), or the
        mg V-cycle prec dict; any of them feeds ops.apply_prec inside
        the PCG body."""
        from pcg_mpi_solver_tpu.ops.precond import make_prec

        return make_prec(ops, d, self.config.solver.precond)

    def _build_mg_cached(self, model, scfg):
        """Host MG hierarchy build, served from the SHARD-ADDRESSED
        partition cache when a cache dir is set (ISSUE 14): the
        replicated coarse hierarchy + meta live in one glue entry, the
        parts-sharded fine transfer arrays in per-part entries — a warm
        start (or an N-host warm start, each host its own parts) skips
        the whole host-side rediscretization.  The structural knobs
        (levels/degree/replication cutoff) key every entry."""
        from pcg_mpi_solver_tpu.ops import mg as mgmod

        def build():
            return mgmod.build_mg_host(
                model, self.pm,
                n_levels=int(scfg.mg_levels),
                degree=int(scfg.mg_smooth_degree),
                max_replicated_dofs=int(scfg.mg_max_replicated_dofs))

        if not self._cache_dir:
            return build()
        from pcg_mpi_solver_tpu.cache import keys as ckeys
        from pcg_mpi_solver_tpu.cache.partition_cache import (
            cached_partition_shards)
        from pcg_mpi_solver_tpu.cache.shards import join_mg, split_mg

        rng = self._setup_range or (0, int(self.pm.n_parts))
        key_kw = dict(
            n_parts=int(self.pm.n_parts), backend=f"mg-{self.backend}",
            dtype=str(np.dtype(self.dtype)),
            extra={"levels": int(scfg.mg_levels),
                   "degree": int(scfg.mg_smooth_degree),
                   "max_replicated_dofs":
                       int(scfg.mg_max_replicated_dofs),
                   # the fine transfers are laid out in the PARTITION's
                   # node order — hierarchies built against different
                   # partitions of the same model must never collide
                   "partition": getattr(self, "_partition_cache_id",
                                        None)})
        part_keys = {p: ckeys.partition_shard_key(
            self._model_fp, part_idx=p, **key_kw)
            for p in range(rng[0], rng[1])}
        return cached_partition_shards(
            self._cache_dir,
            glue_key=ckeys.partition_glue_key(self._model_fp, **key_kw),
            part_keys=part_keys, builder=build,
            split=lambda s: split_mg(s, rng), join=join_mg,
            comm=self._setup_comm, recorder=self._rec, label="mg")

    def _prec_operand_spec(self):
        """shard_map PartitionSpec (pytree) of the preconditioner
        operand the chunked programs thread: the part spec for the array
        inverses, the {mg_diag: parts, fb: replicated} dict for mg."""
        if self.config.solver.precond == "mg":
            return {"mg_diag": self._part_spec, "fb": self._rep_spec}
        return self._part_spec

    def _finish_mg_setup(self, scfg):
        """Post-upload half of the MG setup: estimate the fine-level
        Chebyshev bound via a few power-iteration matvecs on the REAL
        partitioned operator (ops/mg.estimate_fine_lam; served from the
        partition cache on warm runs), then install the per-level lambda
        vector + emit the ``mg_setup`` telemetry and degenerate-interval
        warning through the shared ``mg.install_lam_and_report``."""
        from pcg_mpi_solver_tpu.ops import mg as mgmod

        setup, t_build = self._mg_setup
        data64 = self.data["f64"] if self.mixed else self.data
        specs64 = self._specs["f64"] if self.mixed else self._specs
        t0 = time.perf_counter()
        cached = False
        if self._cache_dir:
            from pcg_mpi_solver_tpu.cache import keys as ckeys
            from pcg_mpi_solver_tpu.cache.partition_cache import (
                cached_partition)

            key = ckeys.partition_cache_key(
                self._model_fp, n_parts=int(self.pm.n_parts),
                backend=f"mglam-{self.backend}",
                dtype=str(np.dtype(self.dtype)),
                extra=dict(setup.meta, iters=mgmod.MG_POWER_ITERS))
            hit0 = self._rec.counters.get("cache.partition.hit", 0)
            entry = cached_partition(
                self._cache_dir, key,
                lambda: {"lam": mgmod.estimate_fine_lam(
                    self.ops, data64, self.mesh, specs64,
                    self._part_spec)},
                recorder=self._rec, label="mg_lam")
            cached = self._rec.counters.get("cache.partition.hit",
                                            0) > hit0
            lam_fine = float(entry["lam"])
        else:
            with self._rec.span("mg_lam"):
                lam_fine = mgmod.estimate_fine_lam(
                    self.ops, data64, self.mesh, specs64,
                    self._part_spec)
        trees = ([self.data["f64"], self.data["f32"]] if self.mixed
                 else [self.data])
        mgmod.install_lam_and_report(
            setup, lam_fine, trees=trees, mesh=self.mesh,
            rep_spec=self._rep_spec, recorder=self._rec,
            wall_s=t_build + time.perf_counter() - t0, cached=cached)

    # ------------------------------------------------------------------
    # Warm-path subsystem (cache/): partition cache, AOT step, warmup
    # ------------------------------------------------------------------
    def _partition_cached(self, backend_label, builder, *, n_parts,
                          method="n/a", elem_part=None, extra=None,
                          shard=False):
        """Serve a partition from the content-addressed cache (cache/),
        falling through to ``builder`` on a miss.  The key covers the
        model content (fingerprint), n_parts, backend, dtype, the
        partition method (resolving 'auto' to whether the native graph
        partitioner is actually available), an explicit elem_part array's
        hash, and backend-specific layout knobs — plus the cache schema
        and package version (cache/keys.py), so a code bump invalidates
        rather than deserializing stale layouts.

        ``shard=True`` (the general/structured backends, ISSUE 14) routes
        through the SHARD-ADDRESSED store: per-part entries + one glue
        entry, so on a warm start each process reads only its own parts'
        entries; the monolithic key stays as the legacy-entry shim.
        ``builder`` then takes ``part_range=`` (None = full build).
        Cold builds are timed into ``self.partition_build_s`` under the
        ``partition_build`` span — the setup ladder's attribution."""
        part_range = self._setup_range if shard else None

        def timed_build(part_range=part_range):
            t0 = time.perf_counter()
            with self._rec.span("partition_build"):
                pm = builder(part_range=part_range) if shard else builder()
            self.partition_build_s += time.perf_counter() - t0
            return pm

        if not self._cache_dir:
            return timed_build()
        from pcg_mpi_solver_tpu.cache import keys as ckeys
        from pcg_mpi_solver_tpu.cache.partition_cache import (
            cached_partition, cached_partition_shards)

        extra = dict(extra or {})
        if method == "auto" and elem_part is None:
            # 'auto' resolves to graph-or-RCB by native availability —
            # the resolved choice must key the entry, not the knob.
            from pcg_mpi_solver_tpu import native

            extra["native"] = bool(native.available())
        key_kw = dict(
            n_parts=int(n_parts), backend=backend_label,
            dtype=str(np.dtype(self.dtype)), method=method,
            elem_part_hash=(ckeys.array_hash(elem_part)
                            if elem_part is not None else None),
            extra=extra)
        legacy_key = ckeys.partition_cache_key(self._model_fp, **key_kw)
        # partition identity for DERIVED per-shard entries (the MG
        # hierarchy): its fine-transfer arrays are laid out in THIS
        # partition's node order, so anything cached against it must
        # re-key when the partition does (method/elem_part/knobs)
        self._partition_cache_id = legacy_key
        if not shard:
            return cached_partition(self._cache_dir, legacy_key,
                                    timed_build, recorder=self._rec,
                                    label=backend_label)
        from pcg_mpi_solver_tpu.cache.shards import (
            join_partition, split_partition)

        lo, hi = part_range if part_range is not None else (0, n_parts)
        part_keys = {p: ckeys.partition_shard_key(
            self._model_fp, part_idx=p, **key_kw) for p in range(lo, hi)}
        return cached_partition_shards(
            self._cache_dir,
            glue_key=ckeys.partition_glue_key(self._model_fp, **key_kw),
            part_keys=part_keys, builder=timed_build,
            split=split_partition, join=join_partition,
            legacy_key=legacy_key, comm=self._setup_comm,
            recorder=self._rec, label=backend_label)

    def _build_aot_step(self, shard_step, donate_step):
        """AOT-export path for the one-shot step program: deserialize the
        exported StableHLO for this abstract signature (warm — zero
        tracing of ``_step``) or export + persist it (cold — the one
        trace every warm run skips).  Returns the dispatchable jit of
        ``exported.call`` (which re-applies carry donation), or None when
        export is unsupported — the caller keeps the plain jit."""
        import dataclasses as _dc

        from pcg_mpi_solver_tpu.cache import aot
        from pcg_mpi_solver_tpu.cache.keys import step_cache_key
        from pcg_mpi_solver_tpu.ops.pallas_matvec import pallas_planes

        data_abs = aot.abstract_like(self.data)
        psh = jax.sharding.NamedSharding(self.mesh, self._part_spec)
        rsh = jax.sharding.NamedSharding(self.mesh, self._rep_spec)
        un_abs = jax.ShapeDtypeStruct(
            (self.pm.n_parts, self.pm.n_loc), self.dtype, sharding=psh)
        delta_abs = jax.ShapeDtypeStruct((), self.dtype, sharding=rsh)
        abstract_args = (data_abs, un_abs, delta_abs)
        key = step_cache_key(
            abstract=aot.signature_repr(abstract_args),
            mesh=(sorted(self.mesh.shape.items()),
                  self.mesh.devices.flat[0].platform),
            backend=self.backend,
            # every SolverConfig scalar is baked into the traced program
            solver=_dc.asdict(self.config.solver),
            # also STRUCTURAL key components (cache/keys.py): the
            # variant reshapes the loop body and the carry pytree, the
            # precond reshapes the body's preconditioner apply (the mg
            # V-cycle), so those programs must never collide even if
            # the solver dict's serialization ever changes
            pcg_variant=self.config.solver.pcg_variant,
            precond=self.config.solver.precond,
            trace_len=self.trace_len,
            glob_n_dof_eff=int(self.pm.glob_n_dof_eff),
            donate=bool(donate_step),
            jax_version=jax.__version__,
            # every trace-time env knob baked into the program must key
            # it: the RESOLVED stencil form (StructuredOps pins it at
            # construction so an env flip cannot silently change what a
            # resume replays — the AOT layer must not reintroduce that
            # substitution) and the pallas kernel shape knobs
            extra={"pallas_variant": self.pallas_variant,
                   "matvec_form": getattr(self.ops, "form", None),
                   "pallas_planes": (pallas_planes()
                                     if self.pallas_variant != "off"
                                     else None),
                   # MG-shape components (level count / smoothing
                   # degree / lattice dims): they shape the traced
                   # V-cycle beyond what the solver dict records
                   "mg": self._mg_meta,
                   "x64": bool(jax.config.jax_enable_x64)})
        exported = aot.cached_step(
            self._cache_dir, key, jax.jit(shard_step), abstract_args,
            recorder=self._rec)
        if exported is None:
            return None
        return jax.jit(exported.call, donate_argnums=donate_step)

    def warmup(self):
        """Compile the engaged solve path WITHOUT running a solve, so a
        later hardware window pays no setup: populates the AOT step cache
        and the persistent XLA compilation cache (both live under
        ``config.cache_dir`` when set — warmup works without it too, but
        then only this process benefits).  One-shot path: AOT
        lower+compile, zero execution.  Chunked path: the start programs
        execute once and each budget-loop program runs a single capped
        Krylov iteration (ChunkedEngine.warmup) — negligible runtime next
        to the minutes-scale compiles this front-loads.  ``self.un`` and
        all solve history are untouched.  CLI: ``pcg-tpu warmup``."""
        delta = jnp.asarray(1.0, self.dtype)
        with self._rec.span("warmup", emit=True):
            if self._dispatch_cap > 0:
                # same dispatch name as _step_chunked: warmup pays the
                # compile, so the real solve's start books warm
                with self._rec.dispatch("start"):
                    udi = self._start_pre_fn(self.data, delta)
                    kudi = self._amul64_fn(self.data, udi)
                    fext, x0 = self._start_mid_fn(self.data, self.un,
                                                  delta, kudi)
                    kx0 = self._amul64_fn(self.data, x0)
                    carry, normr0, n2b, prec = self._start_post_fn(
                        self.data, fext, x0, kx0)
                    jax.block_until_ready(n2b)
                # consumes carry (donated); all outputs are throwaway
                self._engine.warmup(self.data, fext, carry, normr0, n2b,
                                    prec)
                jax.block_until_ready(self._finish_fn(
                    jnp.zeros_like(udi), udi))
            else:
                self._step_fn.lower(self.data, self.un, delta).compile()
        self._rec.note("warmup complete (programs compiled, caches "
                       "populated)")

    # ------------------------------------------------------------------
    def _build_chunked(self, scfg, glob_n_eff):
        """Jitted start step + the shared ChunkedEngine (see __init__)."""
        mixed = self.mixed

        from pcg_mpi_solver_tpu.solver.chunked import ChunkedEngine
        from pcg_mpi_solver_tpu.solver.pcg import carry_part_specs, cold_carry

        P, R = self._part_spec, self._rep_spec
        # Direct mode threads the convergence ring through the dispatch
        # carry built here; in mixed mode the engine owns the ring (it
        # rides the f32 inner carries instead).  The recurrence variants
        # add their extra leaves to the carry schema (pcg.cold_carry).
        variant = scfg.pcg_variant
        trace_direct = self.trace_len > 0 and not mixed
        carry_specs = carry_part_specs(P, R, trace=trace_direct,
                                       variant=variant)

        # The ONE program holding the out-of-loop f64 stencil: Dirichlet
        # lifting, r0, and every refinement's true-residual matvec all
        # dispatch through it.  At octree-flagship scale each stencil
        # INSTANTIATION costs minutes of compile (docs/BENCH_LOG.md
        # 2026-07-31) — the old single _start program alone instantiated
        # it twice.  The cost is a couple of unfused vector round-trips
        # per STEP/cycle (micro-ms at 10M dofs), not per iteration.
        if self._refresh64 is not None:
            # PCG_TPU_HYBRID_F64_REFRESH=general: same contract
            # ((data, v) -> eff * K.v in f64), different operator
            # formulation — element gather/scatter over the full general
            # partition (identical dof layout; asserted at build).  The
            # passed-in data tree is ignored in favor of the refresh
            # tree; callers keep one signature either way.
            rops, rdev, rspecs = self._refresh64
            if self.f64_refresh == "bucketed":
                from pcg_mpi_solver_tpu.ops.matvec import bucketed_matvec

                def _amul64g(rd, v):
                    return rd["eff"] * bucketed_matvec(rops, rd, v)
            else:
                def _amul64g(rd, v):
                    return rd["eff"] * rops.matvec(rd, v)

            amul64g_jit = jax.jit(jax.shard_map(
                _amul64g, mesh=self.mesh, in_specs=(rspecs, P),
                out_specs=P, check_vma=False))
            self._amul64_fn = lambda data, v: amul64g_jit(rdev, v)
        else:
            def _amul64(data, v):
                d = data["f64"] if mixed else data
                return d["eff"] * self.ops.matvec(d, v)

            self._amul64_fn = jax.jit(jax.shard_map(
                _amul64, mesh=self.mesh, in_specs=(self._specs, P),
                out_specs=P, check_vma=False))

        def _start_pre(data, delta):
            data64 = data["f64"] if mixed else data
            return data64["Ud"] * delta

        self._start_pre_fn = jax.jit(jax.shard_map(
            _start_pre, mesh=self.mesh, in_specs=(self._specs, R),
            out_specs=P, check_vma=False))

        def _start_mid(data, un_prev, delta, kudi):
            data64 = data["f64"] if mixed else data
            eff = data64["eff"]
            # eff is idempotent: eff*(F*delta - K.udi) == eff*F*delta - kudi
            fext = eff * data64["F"] * delta - kudi
            x0 = eff * un_prev
            return fext, x0

        self._start_mid_fn = jax.jit(jax.shard_map(
            _start_mid, mesh=self.mesh, in_specs=(self._specs, P, R, P),
            out_specs=(P, P), check_vma=False))

        def _start_post(data, fext, x0, kx0):
            data64 = data["f64"] if mixed else data
            w = data64["weight"] * data64["eff"]
            r0 = fext - kx0
            n2b = jnp.sqrt(self.ops.wdot(w, fext, fext))
            normr0 = jnp.sqrt(self.ops.wdot(w, r0, r0))
            carry0 = cold_carry(
                x0, r0, normr0, self.ops.dot_dtype,
                trace=(trace_init(self.trace_len, self._trace_dtype)
                       if trace_direct else None),
                variant=variant)
            # preconditioner rebuild once per step (not per dispatch /
            # refinement cycle): f32 for the mixed inner solves.
            if mixed:
                prec = self._make_prec(self.ops32, data["f32"])
            else:
                prec = self._make_prec(self.ops, data64)
            return carry0, normr0, n2b, prec

        prec_spec = self._prec_operand_spec()
        self._start_post_fn = jax.jit(jax.shard_map(
            _start_post, mesh=self.mesh,
            in_specs=(self._specs, P, P, P),
            out_specs=(carry_specs, R, R, prec_spec), check_vma=False))

        self._engine = ChunkedEngine(
            mesh=self.mesh, data_specs=self._specs, part_spec=P,
            rep_spec=R, ops=self.ops, scfg=scfg,
            glob_n_dof_eff=glob_n_eff, cap=self._dispatch_cap,
            mixed=mixed, ops32=self.ops32 if mixed else None,
            amul_fn=self._amul64_fn, trace_len=self.trace_len,
            recorder=self._rec, donate=self._donate,
            prec_spec=prec_spec)
        self._finish_fn = jax.jit(lambda x, udi: x + udi)

    def _step_chunked(self, delta):
        """Host-driven solve: repeated capped-iteration dispatches.

        Semantics match the one-shot path (same fext/lifting, same inner
        PCG); the resumable carry makes direct-mode dispatches iteration-
        for-iteration identical to one long solve, and chunk boundaries
        align with refinement cycles in mixed mode.

        The recovery orchestration (breakdown ladder, device-loss
        restart) is the shared :func:`resilience.engine.run_with_recovery`
        harness — this method supplies the recovery PROGRAMS (restart
        carry through the shared out-of-loop amul, fallback
        preconditioner, f64 escalation engine, cold-start rebuild) as
        :class:`~pcg_mpi_solver_tpu.resilience.engine.RecoveryHooks`."""
        from pcg_mpi_solver_tpu.resilience.engine import (
            RecoveryHooks, run_with_recovery)

        rec = self._rec
        scfg = self.config.solver
        rec.note("start dispatch (lifting + r0; first call pays compile)")
        delta_dev = jnp.asarray(delta, self.dtype)
        with rec.dispatch("start"):
            udi = self._start_pre_fn(self.data, delta_dev)
            kudi = self._amul64_fn(self.data, udi)
            fext, x0 = self._start_mid_fn(self.data, self.un, delta_dev,
                                          kudi)
            kx0 = self._amul64_fn(self.data, x0)
            carry, normr0, n2b, prec = self._start_post_fn(
                self.data, fext, x0, kx0)
            n2b_f = float(n2b)
        rec.note(f"start_fn done; ||b||={n2b_f:.3e}")
        if n2b_f == 0.0:
            self.un = self._finish_fn(jnp.zeros_like(carry["x"]), udi)
            self.last_trace = empty_trace() if self.trace_len else None
            return 0, 0.0, 0
        ctx = self._make_resilience()

        def _restart(x):
            # min-residual-iterate restart: a cold Krylov carry at the
            # best iterate seen, through the SHARED out-of-loop amul
            # program (no extra stencil instantiation)
            with rec.dispatch("restart"):
                kx = self._amul64_fn(self.data, x)
                c, nr = self._restart_post()(self.data, fext, x, kx)
                jax.block_until_ready(nr)
            return c, nr

        def _cold_restart():
            # device loss: rebuild the step's cold start state (fext/x0/
            # kx0 are intact: the start programs never donate their
            # operands)
            with rec.dispatch("start"):
                c, nr, _n2b, prec0 = self._start_post_fn(
                    self.data, fext, x0, kx0)
            return c, nr, prec0

        engine, x_fin, flag, relres, total = run_with_recovery(
            self._engine, self.data, fext, carry, normr0, n2b, prec,
            scfg=scfg, mixed=self.mixed, recorder=rec,
            hooks=RecoveryHooks(restart=_restart,
                                cold_restart=_cold_restart,
                                fallback_prec=self._fallback_prec,
                                escalation=self._escalation),
            resilience=ctx)
        if self.trace_len:
            tr = engine.last_trace
            self.last_trace = (unpack_trace(tr) if tr is not None
                               else empty_trace())
        if ctx is not None:
            ctx.discard()               # the step is complete: its mid-
            #                             Krylov snapshot must not outlive it
        self.un = self._finish_fn(x_fin, udi)
        return flag, relres, total

    # ------------------------------------------------------------------
    # Resilience subsystem (resilience/): context + recovery programs
    # ------------------------------------------------------------------
    def _collective_comm(self):
        """Host-collective group for the dispatch path
        (resilience/distributed.GuardedComm), cached; None
        single-process.  Every multi-process run gets a REAL group: the
        consensus agreements (snapshot commit/resume epoch, recovery
        ladder, engage) are correctness-critical regardless of
        configuration, so they must never silently degrade to local
        verdicts.  Only the deadline WATCHDOG stays opt-in
        (PCG_TPU_COLLECTIVE_DEADLINE_S — a watchdog thread per
        collective is pure overhead on a healthy fleet); with no
        deadline armed the wrapper runs collectives inline but still
        classifies transport death as DeadPeerError."""
        if jax.process_count() <= 1:
            return None
        if self._group_comm is None:
            from pcg_mpi_solver_tpu.parallel.distributed import HostComm
            from pcg_mpi_solver_tpu.resilience.distributed import (
                GuardedComm, collective_deadline_s)

            self._group_comm = GuardedComm(
                self._setup_comm or HostComm(),
                deadline_s=collective_deadline_s(),
                recorder=self._rec, index=jax.process_index())
        return self._group_comm

    def _snapshot_store(self):
        """Per-step mid-Krylov snapshot store (lazy).  Multi-process —
        or an armed elastic resume reading a multi-process epoch — gets
        the group-consistent epoch store (two-phase commit markers,
        resilience/distributed.GroupSnapshotStore); single-process keeps
        the plain per-file SnapshotStore."""
        if self._snap_store is None:
            if jax.process_count() > 1 or self._elastic_dir is not None:
                from pcg_mpi_solver_tpu.resilience.distributed import (
                    GroupSnapshotStore)

                self._snap_store = GroupSnapshotStore.for_solver(
                    self, comm=self._collective_comm(),
                    recorder=self._rec,
                    elastic=self._elastic_dir is not None)
                if self._elastic_dir is not None:
                    # re-point at the dead fleet's directory and rescan:
                    # continuation epochs must number past the ones
                    # already committed there, not restart at 0
                    self._snap_store.path = self._elastic_dir
                    self._snap_store._epoch = \
                        self._snap_store._scan_next_epoch()
            else:
                from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

                self._snap_store = SnapshotStore.for_solver(self)
        return self._snap_store

    def _make_resilience(self):
        """Per-step resilience context for the chunked budget loop, or
        None when the subsystem is fully disabled (no ladder budget, no
        snapshot cadence, no fault plan, no collective deadline)."""
        scfg = self.config.solver
        every = int(getattr(self.config, "snapshot_every", 0))
        plan = self.fault_plan
        comm = self._collective_comm()
        if (scfg.max_recoveries <= 0 and every <= 0 and plan is None
                and comm is None):
            return None
        from pcg_mpi_solver_tpu.resilience.recovery import (
            DispatchGuard, ResilienceContext)

        store = self._snapshot_store() if every > 0 else None
        from pcg_mpi_solver_tpu.resilience.recovery import retry_deadline_s

        return ResilienceContext(
            store=store, step=len(self.flags) + 1, snapshot_every=every,
            fetch_state=self._fetch_state, put_state=self._put_state,
            guard=DispatchGuard(retries=scfg.dispatch_retries,
                                deadline_s=retry_deadline_s(),
                                recorder=self._rec),
            faults=plan, recorder=self._rec, resume=self._resume_pending,
            ladder_armed=scfg.max_recoveries > 0, comm=comm)

    def _fetch_state(self, state):
        """Device state pytree -> host numpy (collective on multi-host:
        every process participates in the vector all-gathers; only the
        primary later writes)."""
        from pcg_mpi_solver_tpu.parallel.distributed import fetch_global

        def rec(node):
            if isinstance(node, dict):
                return {k: rec(v) for k, v in node.items()}
            if isinstance(node, (int, float, bool, str)):
                return node
            return fetch_global(node, self.mesh)

        return rec(state)

    def _put_state(self, state):
        """Host numpy state pytree -> device, sharding-faithful: leading-
        axis-(n_parts) arrays go back parts-sharded, everything else
        replicated; non-numeric leaves (the ``kind`` tag) pass through."""
        from pcg_mpi_solver_tpu.parallel.distributed import put_sharded

        n_parts = self.pm.n_parts

        def rec(node):
            if isinstance(node, dict):
                return {k: rec(v) for k, v in node.items()}
            a = np.asarray(node)
            if a.dtype.kind in "OUS":
                return node
            spec = (self._part_spec
                    if a.ndim >= 2 and a.shape[0] == n_parts
                    else self._rep_spec)
            return put_sharded(a, self.mesh, spec)

        return rec(state)

    def _restart_post(self):
        """Lazily-built ladder restart program: ``(data, fext, x, kx) ->
        (cold carry at x, ||r||)`` with ``r = fext - kx`` — the kx matvec
        goes through the shared ``_amul64_fn``, so the restart costs no
        extra stencil instantiation and compiles only if a recovery ever
        fires.  Direct mode with tracing gets a FRESH ring (the poisoned
        solve's partial ring is superseded, not resumed)."""
        if self._restart_post_fn is None:
            from pcg_mpi_solver_tpu.solver.pcg import (
                carry_part_specs, cold_carry)

            mixed = self.mixed
            variant = self.config.solver.pcg_variant
            trace_direct = self.trace_len > 0 and not mixed
            P, R = self._part_spec, self._rep_spec
            carry_specs = carry_part_specs(P, R, trace=trace_direct,
                                           variant=variant)
            trace_len, trace_dtype = self.trace_len, self._trace_dtype

            def _restart(data, fext, x, kx):
                d = data["f64"] if mixed else data
                w = d["weight"] * d["eff"]
                r = fext - kx
                normr = jnp.sqrt(self.ops.wdot(w, r, r))
                tr = (trace_init(trace_len, trace_dtype)
                      if trace_direct else None)
                return cold_carry(x, r, normr, self.ops.dot_dtype,
                                  trace=tr, variant=variant), normr

            self._restart_post_fn = jax.jit(jax.shard_map(
                _restart, mesh=self.mesh,
                in_specs=(self._specs, self._part_spec, self._part_spec,
                          self._part_spec),
                out_specs=(carry_specs, R), check_vma=False))
        return self._restart_post_fn

    def _fallback_prec(self):
        """Scalar-Jacobi fallback preconditioner inverse (ladder rung 2):
        weaker than block3/mg but its inverse is finite wherever the
        assembled diagonal is nonzero, so it cannot re-introduce the Inf
        a near-singular 3x3 block inverse produced — nor depend on an mg
        hierarchy that may itself be the broken ingredient.  Under
        precond='mg' the fallback keeps the mg PREC-OPERAND SHAPE with
        the ``fb`` demotion switch set (the compiled cycle's apply then
        takes the plain scalar-Jacobi branch — ops/mg.mg_apply — so a
        broken hierarchy DEGRADES without recompiling anything).
        Built/compiled only when the rung actually fires."""
        from pcg_mpi_solver_tpu.ops.precond import make_prec

        if self._fallback_prec_fn is None:
            mixed = self.mixed
            mg = self.config.solver.precond == "mg"

            def _fb(data):
                if mixed:
                    inv = make_prec(self.ops32, data["f32"], "jacobi")
                else:
                    inv = make_prec(self.ops, data, "jacobi")
                if mg:
                    from pcg_mpi_solver_tpu.ops.mg import fallback_operand

                    return fallback_operand(inv)
                return inv

            self._fallback_prec_fn = jax.jit(jax.shard_map(
                _fb, mesh=self.mesh, in_specs=(self._specs,),
                out_specs=self._prec_operand_spec(), check_vma=False))
        with self._rec.dispatch("fallback_prec"):
            prec = self._fallback_prec_fn(self.data)
            jax.block_until_ready(prec)
        return prec

    def _escalation(self):
        """f64 escalation (ladder rung 3, mixed mode): finish the solve
        with direct f64 Krylov cycles on the existing f64 ops/data — a
        second ChunkedEngine built lazily, so the extra compile is paid
        only when mixed-precision iteration itself is what keeps breaking
        (the classic case: an f32 preconditioner Inf that the f64
        assembly does not reproduce).  Returns (engine, data, prec)."""
        from pcg_mpi_solver_tpu.ops.precond import make_prec
        from pcg_mpi_solver_tpu.solver.chunked import ChunkedEngine

        if self._esc_engine is None:
            specs64 = self._specs["f64"]
            self._esc_engine = ChunkedEngine(
                mesh=self.mesh, data_specs=specs64,
                part_spec=self._part_spec, rep_spec=self._rep_spec,
                ops=self.ops, scfg=self.config.solver,
                glob_n_dof_eff=self.pm.glob_n_dof_eff,
                cap=self._dispatch_cap, mixed=False, trace_len=0,
                recorder=self._rec, donate=self._donate)

            def _p64(data):
                # scalar Jacobi: the escalation rung sits after the
                # fallback-prec rung, so the safest inverse is the point
                return make_prec(self.ops, data, "jacobi")

            self._esc_prec_fn = jax.jit(jax.shard_map(
                _p64, mesh=self.mesh, in_specs=(specs64,),
                out_specs=self._part_spec, check_vma=False))
        with self._rec.dispatch("esc_prec"):
            prec = self._esc_prec_fn(self.data["f64"])
            jax.block_until_ready(prec)
        return self._esc_engine, self.data["f64"], prec

    def reset_state(self):
        """Zero the solution, preserving its device sharding (avoids a
        silent retrace on the next step)."""
        from pcg_mpi_solver_tpu.parallel.distributed import put_sharded

        self.un = put_sharded(
            np.zeros((self.pm.n_parts, self.pm.n_loc), self.dtype),
            self.mesh, self._part_spec)

    # ------------------------------------------------------------------
    # Batched multi-RHS solves (ISSUE 6): many load cases, ONE operator
    # ------------------------------------------------------------------
    def predicted_ms_per_iter(self, nrhs: int = 1) -> Optional[float]:
        """Cost-model-predicted ms/iter at block width ``nrhs`` — the
        serve/ admission-pricing hook (ISSUE 19).  None when the model
        degraded at construction (exotic platform / shape derivation
        failure): callers must treat None as "cannot price", never as
        zero.  Models are cached per width (one table walk each, all
        pure host arithmetic); an unknown variant/precond stays a loud
        KeyError (the single-source-table contract)."""
        if self._perf_shape is None:
            return None
        nrhs = max(1, int(nrhs))
        cm = self._cost_models_by_width.get(nrhs)
        if cm is None:
            from pcg_mpi_solver_tpu.obs import perf as _perf

            scfg = self.config.solver
            cm = _perf.cost_model(
                self._perf_shape, scfg.pcg_variant, scfg.precond,
                nrhs, self._perf_profile)
            self._cost_models_by_width[nrhs] = cm
        return float(cm["predicted_ms_per_iter"])

    def solve_many(self, fexts, resume: bool = False) -> ManySolveResult:
        """Solve ``K.x_j = fext_j`` for a BLOCK of load cases against the
        one shared partitioned operator — the multi-tenant solve path.

        ``fexts``: global load vectors as an (n_dof, nrhs) array (one
        column per load case; a list of (n_dof,) vectors or a single
        vector also work).  Homogeneous Dirichlet: loads act on the
        effective dofs, constrained dofs solve to 0 (lift prescribed
        displacements into the load columns yourself if needed).

        The block rides one lockstep Krylov loop (solver/pcg.pcg_many —
        per-RHS convergence mask, frozen converged columns, per-column
        flag taxonomy) with the per-type element matmul batched over the
        block and a per-iteration collective count INDEPENDENT of nrhs.
        Reuses every warm-path asset this solver already owns: the
        cached partition, the preconditioner build, and (one-shot path
        with ``cache_dir``) an AOT-exported blocked program keyed by
        nrhs — repeated blocks of the same width do zero partition
        builds and zero step re-traces.  Each request block is validated
        per column first (validate.check_rhs_block — the offending
        column index is named, the PR-4 preflight already vetted the
        model at construction).

        Direct-precision solves above the dispatch cap run as capped
        resumable dispatches with optional mid-solve snapshots
        (``config.snapshot_every`` chunk boundaries, ``many_*.npz``) and
        ``resume=True`` continues a killed blocked solve bit-identically;
        a resume against a different block width fails as a clear
        fingerprint mismatch.  Mixed-precision blocks run as one
        dispatch (the refinement loop is in-graph).

        Returns :class:`ManySolveResult` (per-RHS flags/relres/iters +
        the device-blocked solution)."""
        from pcg_mpi_solver_tpu.validate import PreflightError, check_rhs_block

        t0 = time.perf_counter()
        rdt = np.dtype(np.float64 if self.mixed else self.dtype)
        fb = normalize_rhs_block(fexts, self._model.n_dof, rdt)
        checks = check_rhs_block(fb, self._model.n_dof)
        bad = [c for c in checks if c.status == "fail"]
        if bad:
            raise PreflightError(
                "solve_many rejected the rhs block: " + "; ".join(
                    f"[{c.name}] {c.detail}" for c in bad))
        R = fb.shape[1]
        self._rec.gauge("many.nrhs", R)

        # global columns -> part-local blocked (n_parts, n_loc, nrhs);
        # shared interface dofs replicate their value on every part that
        # carries them (the assembled-operator convention), padded local
        # slots (dof_gid < 0) read 0
        from pcg_mpi_solver_tpu.parallel.distributed import put_sharded

        gid = np.asarray(self.pm.dof_gid)
        loc = fb[np.clip(gid, 0, None), :] * (gid >= 0)[..., None]
        fb_dev = put_sharded(np.ascontiguousarray(loc, dtype=rdt),
                             self.mesh, self._part_spec)

        progs = self._ensure_many_programs(R)
        t_solve0 = time.perf_counter()      # staging done: Krylov wall
        quarantined, recoveries, drift = (), 0, 0
        if "solve" in progs:
            if resume or int(getattr(self.config, "snapshot_every", 0)) > 0:
                # the one-shot blocked path (mixed precision, or below
                # the dispatch cap) has no chunk boundaries to snapshot
                # at — say so instead of silently ignoring the request
                self._rec.note(
                    "solve_many: snapshot/resume requested but this "
                    "blocked solve runs as ONE dispatch (mixed "
                    "precision, or below the dispatch cap) — no "
                    "mid-solve snapshots exist on this path")

            def _one_shot():
                x, flags, relres, iters = progs["solve"](self.data,
                                                         fb_dev)
                # blocking fetches INSIDE the retry guard: a dispatch
                # that dies mid-execution must count as a failed attempt
                return (x, np.asarray(flags),
                        np.asarray(relres, dtype=np.float64),
                        np.asarray(iters))

            # retry-guarded one-shot dispatch: the blocked program
            # donates nothing, so a device-loss failure re-dispatches
            # the identical stateless program instead of failing the
            # whole block request
            x, flags, relres, iters = self._dispatch_with_retry(
                "solve_many", _one_shot)
            # one-shot quarantine semantics (recovery-exempt from the
            # ladder: a single stateless dispatch has no resumable carry
            # to restart columns from — the in-graph finalize already
            # handed failed columns their min-residual iterate): flag
            # 2/4/6 breakdowns, in-graph flag-5 poison, and any residual
            # non-finiteness report as quarantined columns + telemetry
            from pcg_mpi_solver_tpu.solver.pcg import (
                BREAKDOWN_FLAGS, QUARANTINE_FLAG)

            quar = (np.isin(flags, BREAKDOWN_FLAGS + (QUARANTINE_FLAG,))
                    | ~np.isfinite(relres))
            if quar.any():
                for j in np.flatnonzero(quar):
                    trig = ("nan_carry" if not np.isfinite(relres[j])
                            or int(flags[j]) == QUARANTINE_FLAG
                            else f"flag{int(flags[j])}")
                    self._rec.event("rhs_quarantine", rhs=int(j),
                                    trigger=trig,
                                    flag=QUARANTINE_FLAG, attempts=0)
                    self._rec.inc("resilience.rhs_quarantine")
                flags = np.where(quar, QUARANTINE_FLAG, flags)
                quarantined = tuple(int(j) for j in np.flatnonzero(quar))
        else:
            rhs_hash = ""
            if resume or int(getattr(self.config, "snapshot_every", 0)) > 0:
                # the hash exists only to fingerprint snapshots — never
                # scan the (potentially GB-scale) block when neither
                # snapshots nor resume can use it
                from pcg_mpi_solver_tpu.cache.keys import array_hash

                rhs_hash = array_hash(fb)
            (x, flags, relres, iters, quarantined, recoveries,
             drift) = self._solve_many_chunked(
                fb_dev, R, progs, resume, rhs_hash=rhs_hash)
        wall = time.perf_counter() - t0
        res = ManySolveResult(flags=flags, relres=relres, iters=iters,
                              wall_s=wall, x=x,
                              solve_wall_s=time.perf_counter() - t_solve0,
                              quarantined=tuple(quarantined),
                              recoveries=int(recoveries),
                              drift=int(drift))
        self._rec.event("solve_many", nrhs=R, wall_s=round(wall, 6),
                        flags=[int(f) for f in flags],
                        iters_max=int(iters.max()) if R else 0,
                        quarantined=[int(j) for j in res.quarantined],
                        recoveries=int(recoveries))
        for j in range(R):
            # per-RHS telemetry: one event per tenant/load case
            self._rec.event("rhs_solve", rhs=j, flag=int(flags[j]),
                            relres=float(relres[j]), iters=int(iters[j]),
                            quarantined=bool(j in res.quarantined))
        return res

    def _dispatch_with_retry(self, name: str, fn):
        """Retry-with-backoff guard for a NON-DONATING device dispatch
        (resilience/recovery.DispatchGuard): a device-loss-shaped
        failure re-runs ``fn`` after backoff, bounded by
        ``solver.dispatch_retries`` and ``PCG_TPU_RETRY_DEADLINE_S``.
        Only stateless dispatches may pass through here — a program that
        donates an operand must never be re-dispatched with the same
        arguments (the donated buffer may already be consumed); those
        paths re-dispatch from a host snapshot instead
        (ResilienceContext.handle_dispatch_failure)."""
        from pcg_mpi_solver_tpu.resilience.recovery import (
            DispatchGuard, retry_deadline_s)

        plan = self.fault_plan
        guard = None
        while True:
            try:
                if plan is not None:
                    plan.on_dispatch()
                with self._rec.dispatch(name):
                    out = fn()
                if plan is not None:
                    plan.on_dispatch_done()
                return out
            except Exception as e:      # noqa: BLE001 — classified below
                if guard is None:
                    guard = DispatchGuard(
                        retries=self.config.solver.dispatch_retries,
                        deadline_s=retry_deadline_s(),
                        recorder=self._rec)
                if not guard.should_retry(e):
                    raise
                self._rec.event("recovery", action="redispatch",
                                attempt=guard.failures,
                                trigger="device_loss",
                                error=f"{type(e).__name__}: {e}")
                self._rec.inc("resilience.recovery.redispatch")
                guard.backoff()

    def displacement_global_many(self, x) -> np.ndarray:
        """Blocked device solution (n_parts, n_loc, nrhs) -> global host
        (n_dof, nrhs) array: ONE fetch of the whole block (one DCN
        all-gather on multi-host) + one owner-masked scatter, via the
        same :func:`gather_owned_global` every scalar global view uses
        (it carries the trailing block axis natively)."""
        from pcg_mpi_solver_tpu.parallel.distributed import gather_owned_global

        return gather_owned_global(self.pm, x, self.mesh,
                                   np.dtype(self.dtype))

    def _ensure_many_programs(self, R: int) -> dict:
        """Build (once per block width) the jitted blocked programs.
        One-shot: a single ``solve`` program (AOT-cached under cache_dir
        keyed by nrhs).  Chunked direct: start/cycle/final programs
        mirroring the scalar chunked engine, with a donated resumable
        blocked carry."""
        if R in self._many_progs:
            return self._many_progs[R]
        from pcg_mpi_solver_tpu.solver.pcg import (
            LAGGED_VARIANTS, carry_part_specs, cold_carry_many, pcg_many,
            pcg_mixed_many, restart_carry_many, select_best_many)

        scfg = self.config.solver
        mixed = self.mixed
        variant = scfg.pcg_variant
        lagged_v = variant in LAGGED_VARIANTS
        glob_n_eff = self.pm.glob_n_dof_eff
        P, Rsp = self._part_spec, self._rep_spec
        cap = self._dispatch_cap
        chunked = cap > 0 and not mixed
        # per-column ladder rung 2 (fallback preconditioner): wire the
        # scalar-Jacobi inverse as a second cycle operand only when the
        # ladder can use it — with precond already "jacobi" (or the
        # ladder disabled) the selection is compiled out and the cycle
        # program is unchanged
        use_fb = chunked and self._many_use_fb()
        progs = {"has_fallback": use_fb} if chunked else {}

        def smap(f, in_specs, out_specs, donate=()):
            return jax.jit(jax.shard_map(
                f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False),
                donate_argnums=donate if self._donate else ())

        if not chunked:
            def _solve_blk(data, fb):
                # warm-path contract: increments only inside a live
                # trace, like _step (tests assert zero on an AOT hit)
                self._rec.inc("trace.step")
                self._rec.inc("trace.solve_many")
                d64 = data["f64"] if mixed else data
                eff = d64["eff"]
                fext = eff[..., None] * fb
                x0 = jnp.zeros_like(fext)
                if mixed:
                    inv32 = self._make_prec(self.ops32, data["f32"])
                    res = pcg_mixed_many(
                        self.ops32, data["f32"], self.ops, d64, fext, x0,
                        inv32, tol=scfg.tol, max_iter=scfg.max_iter,
                        glob_n_dof_eff=glob_n_eff,
                        max_stag_steps=scfg.max_stag_steps,
                        inner_tol=scfg.inner_tol,
                        plateau_window=scfg.mixed_plateau_window,
                        progress_window=scfg.mixed_progress_window,
                        progress_ratio=scfg.mixed_progress_ratio,
                        progress_min_gain=scfg.mixed_progress_min_gain,
                        variant=variant)
                else:
                    inv = self._make_prec(self.ops, d64)
                    res = pcg_many(
                        self.ops, d64, fext, x0, inv,
                        tol=scfg.tol, max_iter=scfg.max_iter,
                        glob_n_dof_eff=glob_n_eff,
                        max_stag_steps=scfg.max_stag_steps,
                        x0_zero=True, variant=variant)
                return res.x, res.flag, res.relres, res.iters

            shard = jax.shard_map(
                _solve_blk, mesh=self.mesh, in_specs=(self._specs, P),
                out_specs=(P, Rsp, Rsp, Rsp), check_vma=False)
            fn = jax.jit(shard)
            if self._cache_dir:
                aot_fn = self._build_aot_many(shard, R)
                if aot_fn is not None:
                    fn = aot_fn
            progs["solve"] = fn
        else:
            carry_specs = carry_part_specs(P, Rsp, variant=variant,
                                           many=True)
            # prec rides as ONE operand either way: the plain primary
            # inverse (array, or the mg prec dict), or the (primary,
            # scalar-Jacobi fallback) pair the per-column ladder selects
            # from via the carry's prec_sel (the fallback is always the
            # plain scalar array)
            pspec = self._prec_operand_spec()
            prec_specs = (pspec, P) if use_fb else pspec

            def _start(data, fb):
                self._rec.inc("trace.step")
                self._rec.inc("trace.solve_many")
                eff = data["eff"]
                w = data["weight"] * eff
                fext = eff[..., None] * fb
                # x0 = 0: r0 = fext exactly, ||r0|| = ||b|| (one psum)
                normr0 = jnp.sqrt(self.ops.wdot_many(w, fext, fext))
                carry0 = cold_carry_many(
                    jnp.zeros_like(fext), fext, normr0,
                    self.ops.dot_dtype, variant=variant)
                prec = self._make_prec(self.ops, data)
                if use_fb:
                    from pcg_mpi_solver_tpu.ops.precond import (
                        make_fallback_prec)

                    prec = (prec, make_fallback_prec(self.ops, data,
                                                     scfg.precond))
                return fext, carry0, normr0, prec

            progs["start"] = smap(_start, (self._specs, P),
                                  (P, carry_specs, Rsp, prec_specs))

            def _cycle(data, fext, prec, carry, budget):
                inv, inv_fb = prec if use_fb else (prec, None)
                res, carry2 = pcg_many(
                    self.ops, data, fext, carry["x"], inv,
                    tol=scfg.tol,
                    max_iter=jnp.minimum(cap, budget),
                    glob_n_dof_eff=glob_n_eff,
                    max_stag_steps=scfg.max_stag_steps,
                    max_iter_nominal=scfg.max_iter,
                    carry_in=carry, return_carry=True, variant=variant,
                    inv_diag_fb=inv_fb)
                return res.x, carry2

            progs["cycle"] = smap(
                _cycle, (self._specs, P, prec_specs, carry_specs, Rsp),
                (P, carry_specs), donate=(3,))

            def _recover(data, fext, carry, restart_m, fb_m, quar_m):
                # masked per-column ladder surgery (pcg.
                # restart_carry_many): ONE blocked matvec; unmasked
                # columns pass through bit-identically.  Compiled lazily
                # by jit — a healthy solve never pays for it.
                return restart_carry_many(
                    self.ops, data, fext, carry, restart_m, fb_m,
                    quar_m, variant=variant)

            progs["recover"] = smap(
                _recover,
                (self._specs, P, carry_specs, Rsp, Rsp, Rsp),
                carry_specs)

            def _final(data, fext, carry):
                # the ONE terminal per-column selection lives in
                # select_best_many(respect_flags=True): converged
                # columns keep their accepted iterate, zero-rhs columns
                # return zeros, failed columns take the MATLAB
                # min-residual fallback
                return select_best_many(self.ops, data, fext, carry,
                                        always_min=lagged_v,
                                        respect_flags=True)

            progs["final"] = smap(_final, (self._specs, P, carry_specs),
                                  (P, Rsp))
        self._many_progs[R] = progs
        return progs

    def _build_aot_many(self, shard, R: int):
        """AOT-export path for the one-shot blocked program, mirroring
        :meth:`_build_aot_step` with the block width as a structural key
        component: a warm run of the same (model, config, nrhs) block
        shape deserializes StableHLO — zero re-tracing."""
        import dataclasses as _dc

        from pcg_mpi_solver_tpu.cache import aot
        from pcg_mpi_solver_tpu.cache.keys import step_cache_key
        from pcg_mpi_solver_tpu.ops.pallas_matvec import pallas_planes

        data_abs = aot.abstract_like(self.data)
        psh = jax.sharding.NamedSharding(self.mesh, self._part_spec)
        rdt = jnp.float64 if self.mixed else self.dtype
        fb_abs = jax.ShapeDtypeStruct(
            (self.pm.n_parts, self.pm.n_loc, R), rdt, sharding=psh)
        abstract_args = (data_abs, fb_abs)
        key = step_cache_key(
            abstract=aot.signature_repr(abstract_args),
            mesh=(sorted(self.mesh.shape.items()),
                  self.mesh.devices.flat[0].platform),
            backend=self.backend,
            solver=_dc.asdict(self.config.solver),
            pcg_variant=self.config.solver.pcg_variant,
            precond=self.config.solver.precond,
            nrhs=R,
            trace_len=0,
            glob_n_dof_eff=int(self.pm.glob_n_dof_eff),
            donate=False,
            jax_version=jax.__version__,
            extra={"many": True,
                   "pallas_variant": self.pallas_variant,
                   "matvec_form": getattr(self.ops, "form", None),
                   "pallas_planes": (pallas_planes()
                                     if self.pallas_variant != "off"
                                     else None),
                   "mg": self._mg_meta,
                   "x64": bool(jax.config.jax_enable_x64)})
        exported = aot.cached_step(
            self._cache_dir, key, jax.jit(shard), abstract_args,
            recorder=self._rec)
        if exported is None:
            return None
        return jax.jit(exported.call)

    def _many_use_fb(self) -> bool:
        """Whether the blocked cycle programs carry the scalar-Jacobi
        FALLBACK preconditioner operand (per-column ladder rung 2).
        The ONE predicate shared by the program builder and the blocked
        snapshot fingerprint (``SnapshotStore.for_many_solver``): a
        carry whose ``prec_sel`` flipped a column to the fallback must
        never resume into a program compiled without one — that resume
        fails as a clear ``many_fallback`` fingerprint mismatch."""
        from pcg_mpi_solver_tpu.ops.precond import fallback_kind

        scfg = self.config.solver
        return bool(scfg.max_recoveries > 0
                    and fallback_kind(scfg.precond) is not None)

    def _many_snap_store(self, R: int, rhs_hash: str):
        """Blocked-solve snapshot store for one (width, rhs-content)
        request shape (lazy; the fingerprint embeds both, so a resume
        against a different width OR different load cases mismatches
        loudly instead of continuing the wrong Krylov space)."""
        key = (R, rhs_hash)
        if key not in self._many_snap:
            if jax.process_count() > 1 or self._elastic_dir is not None:
                from pcg_mpi_solver_tpu.resilience.distributed import (
                    GroupSnapshotStore)

                store = GroupSnapshotStore.for_many_solver(
                    self, R, rhs_hash=rhs_hash,
                    comm=self._collective_comm(), recorder=self._rec,
                    elastic=self._elastic_dir is not None)
                if self._elastic_dir is not None:
                    store.path = self._elastic_dir
                    store._epoch = store._scan_next_epoch()
                self._many_snap[key] = store
            else:
                from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

                self._many_snap[key] = SnapshotStore.for_many_solver(
                    self, R, rhs_hash=rhs_hash)
        return self._many_snap[key]

    def _make_many_resilience(self, store, resume: bool):
        """Blocked-solve resilience context (``kind="many"`` snapshot
        states at the fixed pseudo-step 1): the dispatch guard, the
        ``many_*.npz`` snapshot cadence, mid-solve resume, and the fault
        plan — the blocked twin of :meth:`_make_resilience`.  None when
        nothing is armed."""
        scfg = self.config.solver
        every = int(getattr(self.config, "snapshot_every", 0))
        plan = self.fault_plan
        comm = self._collective_comm()
        if (store is None and plan is None and scfg.max_recoveries <= 0
                and comm is None):
            return None
        from pcg_mpi_solver_tpu.resilience.recovery import (
            DispatchGuard, ResilienceContext, retry_deadline_s)

        def fetch(state):
            return {k: (self._fetch_state(v) if k == "carry"
                        else np.asarray(v)) for k, v in state.items()}

        return ResilienceContext(
            store=store, step=1, snapshot_every=every,
            fetch_state=fetch, put_state=self._put_state,
            guard=DispatchGuard(retries=scfg.dispatch_retries,
                                deadline_s=retry_deadline_s(),
                                recorder=self._rec),
            faults=plan, recorder=self._rec, resume=resume,
            ladder_armed=scfg.max_recoveries > 0, comm=comm)

    def _solve_many_chunked(self, fb_dev, R: int, progs, resume: bool,
                            rhs_hash: str = ""):
        """Host budget loop for a blocked direct solve: capped resumable
        dispatches of the blocked carry (donated in place), per-column
        flags deciding termination, optional mid-solve snapshots every
        ``config.snapshot_every`` chunk boundaries.  The snapshot is
        discarded only on successful completion — a crashed/killed solve
        leaves it for ``solve_many(..., resume=True)``.

        The loop itself — per-column breakdown/NaN classification, the
        bounded per-column recovery ladder, column quarantine, the
        guarded re-dispatch, snapshots and fault injection — is the
        shared :func:`resilience.engine.run_many_with_recovery` harness;
        this method supplies the blocked device programs (cycle,
        masked recover) as
        :class:`~pcg_mpi_solver_tpu.resilience.engine.ManyRecoveryHooks`."""
        from pcg_mpi_solver_tpu.resilience.engine import (
            ManyRecoveryHooks, run_many_with_recovery)

        scfg = self.config.solver
        rec = self._rec
        from pcg_mpi_solver_tpu.solver.pcg import LAGGED_VARIANTS
        lagged_v = scfg.pcg_variant in LAGGED_VARIANTS
        every = int(getattr(self.config, "snapshot_every", 0))
        store = (self._many_snap_store(R, rhs_hash)
                 if (every > 0 or resume) else None)
        with rec.dispatch("many_start"):
            fext, carry, normr0, prec = progs["start"](self.data, fb_dev)
            jax.block_until_ready(normr0)
        ctx = self._make_many_resilience(store, resume)

        def _cycle(carry, budget):
            with rec.dispatch("many_cycle"):
                x, c2 = progs["cycle"](self.data, fext, prec, carry,
                                       jnp.asarray(budget, jnp.int32))
                # blocking fetch inside the span (async dispatch)
                jax.block_until_ready(c2["exec"])
            return x, c2

        def _recover(carry, restart_m, fb_m, quar_m):
            with rec.dispatch("many_recover"):
                c2 = progs["recover"](self.data, fext, carry,
                                      restart_m, fb_m, quar_m)
                jax.block_until_ready(c2["flag"])
            return c2

        (x_fin, carry, flags, _total, iters_cols, quarantined,
         recoveries, drift_cols) = run_many_with_recovery(
            carry, scfg=scfg, nrhs=R, recorder=rec,
            hooks=ManyRecoveryHooks(cycle=_cycle, recover=_recover,
                                    has_fallback=bool(
                                        progs.get("has_fallback"))),
            resilience=ctx, resume=resume, lagged=lagged_v)
        with rec.dispatch("many_final"):
            x_fin, relres = progs["final"](self.data, fext, carry)
            relres = np.asarray(relres, dtype=np.float64)
        if ctx is not None:
            # the solve completed: its mid-solve snapshot must not
            # outlive it (a store always implies a ctx —
            # _make_many_resilience never returns None with one)
            ctx.discard()
        return (x_fin, flags, relres, iters_cols, quarantined,
                recoveries, int(drift_cols.sum()))

    def step(self, delta: float) -> StepResult:
        # recovery-exempt: the one-shot step DONATES the previous
        # solution vector, so a failed dispatch must never be re-run
        # with the same (possibly consumed) operand, and a single
        # stateless dispatch has no resumable carry for the ladder to
        # restart from — resilience is the chunked path's job
        # (_step_chunked -> run_with_recovery); the except arm below
        # only restores a retryable zero state.
        t0 = time.perf_counter()
        if self._dispatch_cap > 0:
            flag, relres, iters = self._step_chunked(delta)
        else:
            try:
                with self._rec.dispatch("step"):
                    out = self._step_fn(
                        self.data, self.un, jnp.asarray(delta, self.dtype))
                    un, flag, relres, iters = out[:4]
                    # Scalar fetch INSIDE the timed region and the dispatch
                    # span: on tunneled devices block_until_ready can ack
                    # before execution finishes (and async dispatch returns
                    # immediately); fetching the scalars can't.
                    flag, relres, iters = int(flag), float(relres), int(iters)
            except BaseException:
                # The dispatch may have CONSUMED the donated self.un
                # before failing (or before a KeyboardInterrupt landed) —
                # restore a live zero state so the solver stays retryable
                # instead of every later access dying on a deleted
                # buffer.  Only when actually consumed: an error raised
                # before the jitted call ran (bad delta, a sink raising)
                # must keep the intact previous iterate.
                if self._donate and getattr(self.un, "is_deleted",
                                            lambda: False)():
                    self.reset_state()
                    # the divergence from donate_carry=False (which
                    # would have kept the previous iterate) must be
                    # visible to whoever catches and retries
                    self._rec.note(
                        "failed dispatch consumed the donated solution "
                        "vector; state RESET TO ZERO — a retry resumes "
                        "from u=0, not the previous iterate")
                raise
            # trace ring: the solve's ONE device->host trace transfer
            self.last_trace = (unpack_trace(out[4]) if self.trace_len
                               else None)
            self.un = un
        wall = time.perf_counter() - t0
        res = StepResult(flag, relres, iters, wall)
        self.flags.append(res.flag)
        self.relres.append(res.relres)
        self.iters.append(res.iters)
        self.step_times.append(wall)
        self._proc_step_times.append(wall)
        step_i = len(self.flags)
        # time_to_tol_s: the ROADMAP-4 time-to-solution signal — wall to
        # CONVERGED-at-tol, null on any non-0 flag (additive field; the
        # bench stamps the same semantics on its result lines)
        self._rec.event("step", step=step_i, flag=flag, relres=relres,
                        iters=iters, wall_s=round(wall, 6),
                        time_to_tol_s=(round(wall, 6) if flag == 0
                                       else None))
        if self.trace_len and self.last_trace is not None:
            self._rec.event("resid_trace",
                            **self.last_trace.to_event_fields(step_i))
        return res

    def resume_elastic(self, snapshot_dir: Optional[str] = None,
                       **solve_kw):
        """Resume a MULTI-PROCESS run's persisted state on a DIFFERENT
        (typically smaller) process count — the elastic-resume path
        (ISSUE 18).

        Group-consistent snapshot epochs
        (resilience/distributed.GroupSnapshotStore) carry each shard's
        part rows, so a committed N-process epoch re-joins into the full
        global state on any process count; completed-step checkpoints
        are globally-fetched on the primary already.  Both resumes would
        normally refuse on the ``n_procs`` fingerprint mismatch — this
        entry point arms the NAMED elastic path instead: the mismatch
        (confined to ``n_procs``) becomes an ``elastic_resume``
        telemetry event and the solve continues bit-identically.

        ``snapshot_dir`` points at the dead fleet's checkpoint
        directory; None reads this config's ``checkpoint_path``.
        Remaining keywords pass through to :meth:`solve`."""
        self._elastic_dir = snapshot_dir or self.config.checkpoint_path
        # a store built before arming lacks the elastic marker (and, on
        # a shrunk fleet, possibly the epoch protocol entirely): rebuild
        self._snap_store = None
        self._many_snap = {}
        try:
            return self.solve(resume=True, **solve_kw)
        finally:
            self._elastic_dir = None
            self._snap_store = None
            self._many_snap = {}

    def solve(self, on_step: Optional[Callable[[int, StepResult], None]] = None,
              store=None, resume: bool = False):
        """Run the full quasi-static schedule (skips step 0, like the
        reference's ``range(1, RefMaxTimeStepCount)``, pcg_solver.py:1002),
        exporting contour frames / history / timing into ``store`` when
        exports are enabled.

        With ``resume=True``, restores the latest checkpoint under
        ``config.checkpoint_path`` (if any) and continues from the step
        after it; with ``config.checkpoint_every > 0``, writes a checkpoint
        every N completed steps and after the final one."""
        th = self.config.time_history
        deltas = th.time_step_delta
        do_export = store is not None and th.export_flag and not self.config.speed_test
        do_plot = store is not None and th.plot_flag and not self.config.speed_test
        if do_export and self._model.n_dof == self._model.n_node:
            bad = self._nodal_vars()            # includes NS
            if bad:
                # Scalar (Poisson) class: the strain/stress/nonlocal export
                # pipelines statically unpack 6 Voigt components — fail
                # loudly up front, not mid-solve with a shape error.
                raise ValueError(
                    f"export vars {bad} (strain/stress nodal fields) are "
                    "not available for the scalar problem class; export 'U'")

        ckpt_mgr = None
        t_start = 1
        if self.config.checkpoint_every > 0 or resume:
            from pcg_mpi_solver_tpu.utils.checkpoint import CheckpointManager

            ckpt_mgr = CheckpointManager(self._elastic_dir
                                         or self.config.checkpoint_path)
        if resume and ckpt_mgr is not None:
            t_done = ckpt_mgr.restore(
                self, elastic=self._elastic_dir is not None,
                recorder=self._rec)
            if t_done is not None:
                t_start = t_done + 1
        # Mid-Krylov snapshot resume (resilience/): only an EXPLICIT
        # --resume may continue a persisted in-step carry — a fresh solve
        # finding a stale snap_*.npz from a previous generation must
        # start cold (steps discard their snapshot on completion, so the
        # armed window closes as the resumed run advances past it).
        self._resume_pending = bool(resume)

        t_prep = time.perf_counter() - self._t_init0
        if do_export and t_start == 1:
            # On resume the run dir (maps + already-exported frames) must
            # survive; prepare() would rotate it away.
            store.prepare()
            if jax.process_count() > 1:
                # prepare() rotates a pre-existing run dir on the primary;
                # a non-primary shard write racing that rotation would be
                # stranded in the rotated dir.  Barrier before any writes.
                from jax.experimental import multihost_utils

                # consensus-exempt: plain barrier, unconditional on the
                # multi-process export path (no verdict to agree)
                multihost_utils.sync_global_devices("runstore_prepared")
            store.write_map("Dof", self.export_dof_map())
            if self._nodal_vars():
                store.write_map("NodeId", self.export_node_map())
            self._export_count = 0
            self._export_times = []
            self._maybe_export(store, 0)
        if t_start == 1:
            self._probe_u = []
        probe_u = self._probe_u

        profiling = bool(self.config.profile_dir) and not self.config.speed_test
        if self.config.profile_dir and self.config.speed_test:
            import warnings

            warnings.warn("profile_dir is ignored in speed-test mode "
                          "(speed_test disables all I/O)")
        prof_dir = self.config.profile_dir
        if profiling:
            # Multi-process: two hosts must not race one trace directory
            # (the profiler's run-dir naming is second-granular) — each
            # process captures into its own p<idx> subdir, the same
            # sharding rule the telemetry JSONL stream follows.
            if jax.process_count() > 1:
                prof_dir = os.path.join(prof_dir,
                                        f"p{jax.process_index()}")
            jax.profiler.start_trace(prof_dir)

        results = []
        try:
            for t in range(t_start, len(deltas)):
                res = self.step(deltas[t])
                results.append(res)
                if do_export:
                    self._maybe_export(store, t)
                if do_plot and len(th.probe_dofs) > 0:
                    u = self.displacement_global()
                    probe_u.append(u[np.asarray(th.probe_dofs)])
                every = self.config.checkpoint_every
                if ckpt_mgr is not None and every > 0 and (
                        t % every == 0 or t == len(deltas) - 1):
                    ckpt_mgr.save(self, t)
                if on_step is not None:
                    on_step(t, res)
        finally:
            self._resume_pending = False
            if profiling:
                jax.profiler.stop_trace()
                # profile_capture event: the pointer `pcg-tpu summary`
                # and post-mortems follow to the on-disk artifact
                # (obs/profview.newest_profile_artifact resolves the
                # run dir the profiler just wrote; best-effort — a
                # capture that wrote nothing still reports the root)
                try:
                    from pcg_mpi_solver_tpu.obs.profview import (
                        newest_profile_artifact)

                    art = newest_profile_artifact(prof_dir) or prof_dir
                except Exception:                       # noqa: BLE001
                    art = prof_dir
                self._rec.event("profile_capture", path=art,
                                source="solve",
                                steps=len(results))

        if do_export:
            store.write_time_list(self._export_times)
        if do_plot and probe_u:
            times = [i * th.dt for i in range(1, len(deltas))]
            store.write_plot_data(times, np.stack(probe_u, axis=1), th.probe_dofs)
        if store is not None and not self.config.speed_test:
            comm = (self.measure_comm_split()
                    if self.config.comm_probe_iters > 0 else None)
            store.write_time_data(self.pm.n_parts,
                                  self.time_data(t_prep, comm))
        # End-of-run snapshot (counters/gauges/dispatch attribution) as the
        # final JSONL event — also the data behind the CLI --summary table.
        self._rec.emit_run_summary()
        return results

    def _maybe_export(self, store, t: int):
        """Key-frame contour export (reference exportContourData,
        pcg_solver.py:841-896)."""
        th = self.config.time_history
        due = th.export_frame_rate > 0 and t % th.export_frame_rate == 0
        if t in tuple(th.export_frames):
            due = True
        if not due:
            return
        t0 = time.perf_counter()
        k = self._export_count
        if "U" in self._export_vars():
            if jax.process_count() > 1:
                # Parallel I/O: each process writes its own part block —
                # no DCN all-gather, no single-writer bottleneck
                # (reference writeMPIFile_parallel, pcg_solver.py:869).
                vals, p0, p1 = self.displacement_owned_local()
                store.write_frame_shard("U", k, vals, p0, p1,
                                        self.pm.n_parts)
            else:
                store.write_frame("U", k, self.displacement_owned())
        nodal = [v for v in self._nodal_vars() if v != "NS"]
        if nodal:
            fields = self._nodal_fields()
            mask = self.node_owner_mask()
            if jax.process_count() > 1:
                from pcg_mpi_solver_tpu.parallel.distributed import (
                    fetch_addressable)

                for var, arr in fields.items():
                    rows, p0, p1 = fetch_addressable(arr)
                    store.write_frame_shard(var, k, rows[mask[p0:p1]],
                                            p0, p1, self.pm.n_parts)
            else:
                for var, arr in fields.items():
                    store.write_frame(var, k, np.asarray(arr)[mask])
        if "NS" in self._export_vars():
            ns = self._nonlocal_field()
            store.write_frame("NS", k, ns[self.export_node_map()])
        self._export_times.append(t * th.dt)
        self._export_count = k + 1
        self._export_wall += time.perf_counter() - t0

    def _export_vars(self):
        ev = self.config.time_history.export_vars
        return ev.split() if " " in ev else [
            v for v in ("U", "D", "ES", "PS", "PE", "NS") if v in ev]

    def _nodal_vars(self):
        return [v for v in self._export_vars() if v != "U"]

    def _nonlocal_field(self) -> np.ndarray:
        """Nonlocal von-Mises stress, node-averaged, as a global (n_node,)
        field.  Element stresses are smoothed with the Gaussian neighborhood
        operator (reference config_NonlocalNeighbours, partition_mesh.py:
        1000-1299 — built there, never consumed; wired end-to-end here).
        Host-side: it is an export-path op, partition-layout agnostic."""
        from pcg_mpi_solver_tpu.ops.nonlocal_stress import (
            build_nonlocal_weights, elem_stress_host, nodal_average_host,
            von_mises_stress)

        if self._nonlocal is None:
            self._nonlocal = build_nonlocal_weights(self._model)
        sig = elem_stress_host(self._model, self.displacement_global())
        ns = self._nonlocal.apply(von_mises_stress(sig, axis=1))
        return nodal_average_host(self._model, ns)

    def _nodal_fields(self) -> dict:
        """Jitted nodal export fields of the current solution
        ({var: (P, n_node_loc)} split to PS1..3/PE1..3)."""
        if self._export_fn is None:
            from pcg_mpi_solver_tpu.ops.stress import nodal_export_fields

            nodal = tuple(v for v in self._nodal_vars() if v != "NS")
            if self._model.n_dof == self._model.n_node:
                # Scalar (Poisson) class: the strain/stress pipeline
                # statically unpacks 6 Voigt components — fail loudly like
                # the block3 layout guard, not with an IndexError at trace.
                raise ValueError(
                    f"export vars {nodal} (strain/stress nodal fields) are "
                    "not available for the scalar problem class; export 'U'")

            def _fields(data, un):
                data64 = data["f64"] if self.mixed else data
                return nodal_export_fields(self.ops, data64, un, nodal, self._nu)

            self._export_fn = jax.jit(jax.shard_map(
                _fields, mesh=self.mesh,
                in_specs=(self._specs, self._part_spec),
                out_specs=self._part_spec, check_vma=False))
        return self._export_fn(self.data, self.un)

    def measure_comm_split(self, n_iters: Optional[int] = None) -> dict:
        """Measured calc vs comm-wait attribution (the reference brackets
        every MPI call with host timers, pcg_solver.py:631-641; under XLA
        the collectives are compiled into the program, so we measure them
        differentially): time ``n_iters`` of the PCG iteration body — one
        assembled matvec + the iteration's three scalar reductions — once
        with real collectives and once with an ``axis_name=None`` clone of
        the ops (identical local compute, including the interface
        scatter/gather, but no psums).  The difference is collective time.

        Returns {"comm_frac", "full_s_per_iter", "calc_s_per_iter"}."""
        if n_iters is None:
            n_iters = max(self.config.comm_probe_iters, 1)
        if self.mesh.devices.size == 1:
            return {"comm_frac": 0.0, "full_s_per_iter": 0.0,
                    "calc_s_per_iter": 0.0}
        mixed = self.mixed
        ops = self.ops32 if mixed else self.ops
        P, R = self._part_spec, self._rep_spec
        probe_dtype = jnp.float32 if mixed else self.dtype

        def make(ops_):
            def run(data, x, n):
                d = data["f32"] if mixed else data
                eff = d["eff"]
                w = d["weight"] * eff

                def body(i, c):
                    x, acc = c
                    q = eff * ops_.matvec(d, x)           # iface psum
                    rho = ops_.wdot(w, x, q)              # psum 1
                    pq = ops_.wdot(w, q, q)               # psum 2
                    s3 = ops_.wdots(w, [(x, x), (q, q), (x, q)])  # psum 3
                    x2 = (q / jnp.sqrt(jnp.maximum(pq, 1e-30))).astype(x.dtype)
                    # acc consumes every reduction so none is dead code.
                    return x2, acc + rho + s3.sum()

                return jax.lax.fori_loop(0, n, body, (x, jnp.asarray(0.0, ops_.dot_dtype)))

            return jax.jit(jax.shard_map(
                run, mesh=self.mesh,
                in_specs=(self._specs, P, R),
                out_specs=(P, R), check_vma=False))

        import dataclasses as _dc

        from pcg_mpi_solver_tpu.parallel.distributed import put_sharded

        full_fn = make(ops)
        local_fn = make(_dc.replace(ops, axis_name=None))
        x0 = put_sharded(
            np.ones((self.pm.n_parts, self.pm.n_loc), probe_dtype),
            self.mesh, P)
        n = jnp.asarray(n_iters, jnp.int32)

        def timed(fn):
            jax.block_until_ready(fn(self.data, x0, jnp.asarray(2, jnp.int32)))
            t0 = time.perf_counter()
            out = fn(self.data, x0, n)
            # fetch a scalar: on tunneled devices block_until_ready can ack
            # before execution finishes (same caveat as step()).
            float(out[1])
            return (time.perf_counter() - t0) / n_iters

        full_t = timed(full_fn)
        local_t = timed(local_fn)
        comm = max(full_t - local_t, 0.0)
        return {"comm_frac": comm / full_t if full_t > 0 else 0.0,
                "full_s_per_iter": full_t,
                "calc_s_per_iter": full_t - comm}

    def time_data(self, t_prep: float = 0.0,
                  comm_split: Optional[dict] = None) -> dict:
        """Solve metadata in the reference's TimeData schema
        (file_operations.py:72-172, pcg_solver.py:943-961), extended with a
        compile-time estimate, export-time bucket and per-part load-unbalance
        stats (reference LoadUnbalanceData, file_operations.py:118-128).

        ``comm_split`` (from :meth:`measure_comm_split`) apportions the
        measured step time into the reference's two buckets
        (Mean_CalcTime / Mean_CommWaitTime); without it the whole step time
        is reported as calc (per-op detail lives in the profiler trace,
        config.profile_dir)."""
        steps = np.asarray(self.step_times)
        # First step run IN THIS PROCESS pays the XLA compile; checkpoint-
        # restored step times never include this process's compile.
        proc = np.asarray(self._proc_step_times)
        compile_est = float(proc[0] - np.median(proc[1:])) if len(proc) > 1 else 0.0
        type_blocks = getattr(self.pm, "type_blocks", None)
        if type_blocks:
            elems_pp = np.sum([tb.n_elem for tb in type_blocks], axis=0)
        else:   # structured slab partition: identical cell count per part
            elems_pp = np.full(self.pm.n_parts,
                               self.pm.nxc * self.pm.ny * self.pm.nz)
        dofs_pp = np.asarray(self.pm.ndof_p)
        unbalance = {
            "ElemsPerPart": elems_pp,
            "DofsPerPart": dofs_pp,
            "MaxByMeanElems": float(elems_pp.max() / max(elems_pp.mean(), 1))
            if elems_pp.size else 1.0,
            "MaxByMeanDofs": float(dofs_pp.max() / max(dofs_pp.mean(), 1)),
            "IfaceDofFrac": float(self.pm.n_iface / max(self.pm.glob_n_dof, 1)),
        }
        total = float(np.sum(self.step_times))
        comm_frac = comm_split["comm_frac"] if comm_split else 0.0
        return {
            "Mean_FileReadTime": t_prep,
            "Mean_CalcTime": total * (1.0 - comm_frac),
            "Mean_CommWaitTime": total * comm_frac,
            "CommProbe": comm_split or {},
            "Compile_Time_Est": max(compile_est, 0.0),
            "Export_Time": float(self._export_wall),
            "TotalTime": t_prep + float(np.sum(self.step_times)),
            "Flag": np.asarray(self.flags),
            "Iter": np.asarray(self.iters),
            "RelRes": np.asarray(self.relres),
            "StepTimes": steps,
            "LoadUnbalanceData": unbalance,
            "MP_NDOF": self.pm.n_loc,
            "N_Parts": self.pm.n_parts,
        }

    # ------------------------------------------------------------------
    # Host-side views for export
    # ------------------------------------------------------------------
    def owner_mask(self) -> np.ndarray:
        """(P, n_loc) bool — dofs this part owns (reference
        DofWeightVector_Export, pcg_solver.py:198)."""
        return (self.pm.weight > 0) & (self.pm.dof_gid >= 0)

    def node_owner_mask(self) -> np.ndarray:
        """(P, n_node_loc) bool — nodes this part owns."""
        return (self.pm.node_weight > 0) & (self.pm.node_gid >= 0)

    def export_node_map(self) -> np.ndarray:
        """Global node ids in export order (reference 'NodeId' map,
        pcg_solver.py:202)."""
        return self.pm.node_gid[self.node_owner_mask()]

    def export_dof_map(self) -> np.ndarray:
        """Global dof ids in export order (reference writes this once as the
        'Dof' map, pcg_solver.py:201)."""
        m = self.owner_mask()
        return self.pm.dof_gid[m]

    def displacement_owned(self) -> np.ndarray:
        """Owner-masked local solution values, concatenated in part order
        (the per-frame 'U_i' payload, pcg_solver.py:869)."""
        from pcg_mpi_solver_tpu.parallel.distributed import fetch_global

        un = fetch_global(self.un, self.mesh)
        return un[self.owner_mask()]

    def displacement_owned_local(self):
        """This process's slice of :meth:`displacement_owned` without any
        collective: ``(values, p0, p1)`` where values covers parts
        [p0, p1).  Concatenating the slices in part order over all
        processes reproduces displacement_owned() exactly."""
        from pcg_mpi_solver_tpu.parallel.distributed import fetch_addressable

        rows, p0, p1 = fetch_addressable(self.un)
        return rows[self.owner_mask()[p0:p1]], p0, p1

    def displacement_global(self) -> np.ndarray:
        """Full global solution vector (n_dof,), assembled on host."""
        from pcg_mpi_solver_tpu.parallel.distributed import gather_owned_global

        return gather_owned_global(self.pm, self.un, self.mesh,
                                   np.dtype(self.dtype))


_REPLICATED_KEYS = frozenset(
    {"Ke", "diag_Ke", "Me", "Se", "Ke4", "diag_Ke4",
     "brick_Ke", "brick_diag", "brick_Se"})


def _data_specs(data):
    """PartitionSpec pytree for the device data: per-type constant matrices
    are replicated, everything else is sharded on the leading parts axis.
    The ``mg`` subtree (ops/mg.py) is special: only its ``fine``
    transfer arrays carry the parts axis — the whole coarse hierarchy is
    REPLICATED by design (that is what makes the coarse V-cycle
    collective-free)."""
    P = jax.sharding.PartitionSpec

    def const(node):
        if isinstance(node, dict):
            return {k: const(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(const(v) for v in node)
        return P()

    def rec(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if k in _REPLICATED_KEYS:
                    out[k] = P()
                elif k == "mg":
                    out[k] = {kk: (rec(vv) if kk == "fine"
                                   else const(vv))
                              for kk, vv in v.items()}
                else:
                    out[k] = rec(v)
            return out
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v) for v in node)
        return P(PARTS_AXIS)

    return rec(data)
