"""Explicit elasto-dynamics: central-difference time integration.

The reference is quasi-static, but its data model and utilities are from an
explicit-dynamics/damage era it kept vestigially: lumped mass ``DiagM`` and
prescribed-velocity ``Vd`` arrays (partition_mesh.py:324-330), per-element
mass scale ``Cm`` (:172-175), ``Me.mat`` element mass library (:538-599),
``dt`` (run_metis.py:19-43), and offline crack-tip velocity post-processing
(file_operations.py:542-726).  This module makes that capability live,
TPU-first:

    a_n = M^-1 (Fext(t_n) - K u_n - c_m M v_n)        (lumped M, mass damping)
    v_{n+1/2} = v_{n-1/2} + dt a_n
    u_{n+1}  = u_n + dt v_{n+1/2}

with Dirichlet dofs driven as u = Ud*delta(t), v = Vd*delta(t).  The whole
step loop runs as ONE ``lax.scan`` inside a jitted shard_map program over
the device mesh — K u_n is the same node-ELL matvec + psum interface
assembly as the PCG path, probe sampling happens in-scan, and only chunk
boundaries (export frames) surface to the host.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.config import RunConfig
from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS, make_mesh
from pcg_mpi_solver_tpu.resilience.faultinject import FaultPlan
from pcg_mpi_solver_tpu.solver.driver import _data_specs


def stable_dt(model: ModelData, safety: float = 0.9) -> float:
    """CFL estimate: h_min / c_d with c_d = sqrt(E_max/rho_min) the
    dilatational wave speed (conservative for hex elements)."""
    E = np.array([m["E"] for m in model.mat_prop], dtype=float)
    rho = np.array([m.get("Rho", 1.0) for m in model.mat_prop], dtype=float)
    c = float(np.sqrt((E / rho).max()))
    # ck = E*h, ce = 1/h  =>  h = 1/ce
    h_min = float((1.0 / model.ce).min())
    return safety * h_min / c


@dataclasses.dataclass
class DynamicsResult:
    u: np.ndarray                 # final global displacement (n_dof,)
    probe_t: np.ndarray           # (n_steps,)
    probe_u: np.ndarray           # (n_probe, n_steps)
    frames: List[np.ndarray]      # exported global displacement frames
    frame_times: List[float]


class DynamicsSolver:
    """Explicit central-difference solver on the SPMD-partitioned model."""

    def __init__(
        self,
        model: ModelData,
        config: Optional[RunConfig] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        n_parts: Optional[int] = None,
        dt: Optional[float] = None,
        damping: float = 0.0,          # c_m: mass-proportional damping
        probe_dofs: Sequence[int] = (),
        backend: str = "auto",         # "auto" | "hybrid" | "general"
        recorder: Optional[MetricsRecorder] = None,
    ):
        self.config = config or RunConfig()
        # Telemetry registry (obs/metrics.py): same default wiring as the
        # quasi-static Solver — stderr sink iff PCG_TPU_VERBOSE=1, JSONL
        # sink iff config.telemetry_path is set.
        self.recorder = recorder if recorder is not None else (
            MetricsRecorder.default(
                jsonl_path=self.config.telemetry_path or None,
                profile=True if self.config.telemetry_profile else None))
        self._rec = self.recorder
        # Flight recorder (obs/flight.py): the same crash-durable
        # dispatch brackets the quasi-static Solver gets — a long
        # explicit time history is exactly the run a tunnel death
        # orphans mid-chunk.
        from pcg_mpi_solver_tpu.obs.flight import attach_flight

        attach_flight(self._rec, self.config.flight_path, "dynamics")
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        n_parts = n_parts or max(self.config.n_parts, n_dev)
        dt_source = ("arg" if dt is not None else
                     "model" if model.dt and model.dt > 0 else "cfl")
        self.dt = float(dt if dt is not None else
                        (model.dt if model.dt and model.dt > 0 else
                         stable_dt(model)))
        self.damping = float(damping)
        # Preflight gate (validate/): model sanity + the explicit-dt vs
        # stable_dt margin check, before the partition build below.  An
        # explicit caller dt above the CFL bound is rejected; a model-
        # file dt (legacy placeholder) only warns.
        from pcg_mpi_solver_tpu.validate import run_preflight

        run_preflight(model, self.config, recorder=self._rec,
                      context={"kind": "dynamics", "dt": self.dt,
                               "dt_source": dt_source})

        dtype = jnp.dtype(self.config.solver.dtype)
        if dtype == jnp.float64 and not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)
        self.dtype = dtype

        # Backend: the hybrid level-grid path serves octree models' matvec
        # (the per-step hot op) exactly as in the quasi-static driver;
        # everything else stays on the general path.  Pallas only ever
        # dispatches on f32 matvecs; dynamics has no mixed-precision f32
        # shadow, so the probe is skipped in f64 runs.
        from pcg_mpi_solver_tpu.solver.backends import select_time_backend

        self.backend, self.pm, mk_ops, mk_data = select_time_backend(
            model, n_parts,
            partition_method=self.config.partition_method,
            pallas_mode=self.config.solver.pallas, mesh=self.mesh,
            kernels_f32=dtype == jnp.float32, backend=backend)
        self.ops = mk_ops(dtype)
        data = mk_data(dtype)
        # Assembled lumped-mass diagonal: model.diag_M is already the global
        # assembled diagonal, sliced per part (partition extract_NodalVectors
        # analogue) — no cross-part assembly needed.
        inv_m = np.where(self.pm.inv_diag_M > 0, self.pm.inv_diag_M, 0.0)
        data["inv_M"] = jnp.asarray(inv_m, dtype)
        # Prescribed velocity (reference Vd, partition_mesh.py:324-330),
        # sliced per part like F/Ud.
        gid = self.pm.dof_gid
        data["Vd"] = jnp.asarray(
            np.where(gid >= 0, model.Vd[np.maximum(gid, 0)], 0.0), dtype)

        # Probe maps: local index of each probe dof per part + owner mask,
        # so in-scan sampling is a tiny gather + the mesh psum (works under
        # shard_map where each device only sees its local parts).
        self._probe = np.asarray(probe_dofs, dtype=np.int64)
        P_, n_loc_ = gid.shape
        np_ = len(self._probe)
        pidx = np.zeros((P_, max(np_, 1)), dtype=np.int32)
        pmask = np.zeros((P_, max(np_, 1)))
        for j, d in enumerate(self._probe):
            hits = np.argwhere((gid == d) & (self.pm.weight > 0))
            if len(hits) == 0:
                raise ValueError(
                    f"probe dof {int(d)} is not an owned dof of any part "
                    "(out of range or Dirichlet-constrained everywhere)")
            p, i = hits[0]
            pidx[p, j], pmask[p, j] = i, 1.0
        data["probe_idx"] = jnp.asarray(pidx, jnp.int32)
        data["probe_mask"] = jnp.asarray(pmask, dtype)
        self._specs = _data_specs(data)

        from pcg_mpi_solver_tpu.parallel.distributed import put_sharded, put_tree

        self.data = put_tree(data, self.mesh, self._specs)
        self._part_spec = jax.sharding.PartitionSpec(PARTS_AXIS)
        P, n_loc = self.pm.n_parts, self.pm.n_loc
        self.u = put_sharded(np.zeros((P, n_loc), dtype),
                             self.mesh, self._part_spec)
        self.v = put_sharded(np.zeros((P, n_loc), dtype),
                             self.mesh, self._part_spec)

        # ---- resilience (resilience/): timestep-granular snapshots,
        # NaN/Inf chunk-boundary detection with rollback, step-domain
        # fault injection (`kill@s:N` etc.).  `fault_plan` is settable.
        self.fault_plan = FaultPlan.from_env(recorder=self._rec)
        self._finite_fn = jax.jit(lambda a: jnp.isfinite(a).all())
        self.mixed = False           # checkpoint fingerprint contract
        self._model = model          # fingerprint content hash

        ops, dt_, cm = self.ops, self.dt, self.damping

        def _chunk(data, carry, deltas):
            """Scan over a chunk of steps; deltas: (k,) load factors."""
            eff = data["eff"]
            fix = 1.0 - eff

            def body(carry, delta):
                u, v = carry
                fint = ops.matvec(data, u)
                # mass damping: C = c_m M  =>  M^-1 C v = c_m v
                a = data["inv_M"] * (data["F"] * delta - fint) - cm * v
                v2 = v + dt_ * a
                u2 = u + dt_ * v2
                # Dirichlet driving
                u2 = eff * u2 + fix * data["Ud"] * delta
                v2 = eff * v2 + fix * data["Vd"] * delta
                # owner-masked probe sample, combined over the mesh
                vals = jnp.take_along_axis(u2, data["probe_idx"], axis=1)
                probes = ops._psum((vals * data["probe_mask"]).sum(axis=0))
                return (u2, v2), probes

            (u, v), probe = jax.lax.scan(body, carry, deltas)
            return u, v, probe

        shard_chunk = jax.shard_map(
            _chunk, mesh=self.mesh,
            in_specs=(self._specs, (self._part_spec, self._part_spec),
                      jax.sharding.PartitionSpec()),
            out_specs=(self._part_spec, self._part_spec,
                       jax.sharding.PartitionSpec()),
            check_vma=False,
        )
        self._chunk_fn = jax.jit(shard_chunk)

    def _make_guard(self, resume: bool):
        """Timestep-granular resilience harness
        (resilience/engine.TimeHistoryGuard): ``config.snapshot_every``
        checkpoints of the full state (u, v, probe series, export
        frames) into ``step_*.npz``, step-domain fault triggers, NaN/Inf
        rollback bounded by ``config.solver.max_recoveries``."""
        every = int(getattr(self.config, "snapshot_every", 0))
        plan = self.fault_plan
        if every <= 0 and plan is None and not resume:
            return None
        from pcg_mpi_solver_tpu.resilience.engine import (
            TimeHistoryGuard, kinematic_state_io)

        store = None
        if every > 0 or resume:
            from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

            store = SnapshotStore.for_time_solver(self)
        fetch, put = kinematic_state_io(self.mesh, self._part_spec,
                                        self.dtype, ("u", "v"))
        return TimeHistoryGuard(
            store=store, snapshot_every=every, fetch_state=fetch,
            put_state=put, recorder=self._rec, faults=plan,
            max_recoveries=int(self.config.solver.max_recoveries))

    def _next_chunk(self, done: int, n_steps: int, export_every: int,
                    guard) -> int:
        """Steps to integrate in the next device chunk: up to the
        nearest host boundary (export frame, snapshot cadence, pending
        step-domain fault, end of schedule).  Distinct chunk lengths
        compile distinct scan programs, so cadences that divide the
        export rate keep the historical two-program profile."""
        cands = [n_steps]
        if export_every > 0:
            cands.append(done + export_every - done % export_every)
        if guard is not None:
            if guard.snapshot_every > 0:
                cands.append(done + guard.snapshot_every
                             - done % guard.snapshot_every)
            if guard.faults is not None:
                nf = guard.faults.next_step_fault(done)
                if nf is not None:
                    cands.append(nf)
        return min(c for c in cands if c > done) - done

    def run(self, n_steps: int, load_factor=None,
            export_every: int = 0, resume: bool = False) -> DynamicsResult:
        """Integrate n_steps.  ``load_factor``: scalar, (n_steps,) array, or
        None (=1.0).  ``export_every``: displacement frames every k steps.

        Resilience (resilience/engine.TimeHistoryGuard): with
        ``config.snapshot_every > 0`` the full state — kinematic vectors,
        probe series, export frames — is checkpointed every N completed
        TIMESTEPS (``step_*.npz``, retention-bounded by
        ``PCG_TPU_SNAP_KEEP``); ``resume=True`` restores the newest one
        and continues mid-history, reproducing the uninterrupted run's
        probe series and frames bit-identically.  Non-finite state
        detected at a chunk boundary rolls back to the last snapshot
        (bounded by ``config.solver.max_recoveries``) instead of
        silently integrating garbage."""
        if load_factor is None:
            deltas = np.ones(n_steps)
        else:
            deltas = np.broadcast_to(np.asarray(load_factor, dtype=float),
                                     (n_steps,)).copy()
        guard = self._make_guard(resume)
        frames: List[np.ndarray] = []
        frame_steps: List[int] = []
        n_pcols = max(len(self._probe), 1)
        # probe samples accumulate as a list of per-chunk arrays and are
        # concatenated lazily (at snapshot/rollback/return) — a per-chunk
        # concat of the growing history would be O(n^2) over a long run
        probe_chunks: List[np.ndarray] = []

        def _probe_cat() -> np.ndarray:
            return (np.concatenate(probe_chunks, axis=0) if probe_chunks
                    else np.zeros((0, n_pcols)))

        done = 0
        u, v = self.u, self.v
        if resume and guard is not None:
            got = guard.load_resume()
            if got is not None:
                t0, st = got
                if not np.array_equal(np.asarray(st["deltas"])[:t0],
                                      deltas[:t0]):
                    raise ValueError(
                        "resume schedule mismatch: the snapshot was "
                        "written under a different load_factor prefix")
                u, v = st["u"], st["v"]
                done = int(t0)
                probe_chunks = [np.asarray(st["probe"])[:done]]
                frames = [f.copy() for f in np.asarray(st["frames"])]
                frame_steps = [int(s) for s in
                               np.asarray(st["frame_steps"])]
        while done < n_steps:
            k = self._next_chunk(done, n_steps, export_every, guard)
            t0c = time.perf_counter()
            with self._rec.dispatch("dynamics_chunk", emit=False):
                u2, v2, pr = self._chunk_fn(
                    self.data, (u, v),
                    jnp.asarray(deltas[done:done + k], self.dtype))
                # the probe fetch forces the transfer, so the chunk wall
                # time below covers execution, not just dispatch
                pr = np.asarray(pr)
            self._rec.event(
                "dynamics_chunk", steps=int(k),
                wall_s=round(time.perf_counter() - t0c, 6))
            # NaN/Inf detection between chunks: an explicit run has no
            # flags or residuals to report corruption on its own, so
            # poison would otherwise integrate silently to the end
            if not (np.isfinite(pr).all() and bool(self._finite_fn(u2))):
                if guard is None:
                    raise FloatingPointError(
                        f"non-finite state within dynamics steps "
                        f"{done + 1}..{done + k} (dt={self.dt:.3e}; "
                        f"check against stable_dt(); set snapshot_every "
                        "for rollback)")
                t_roll, st = guard.rollback(done + k)
                u, v = st["u"], st["v"]
                done = int(t_roll)
                probe_chunks = [_probe_cat()[:done]]
                n_keep = sum(1 for s in frame_steps if s <= done)
                frames, frame_steps = frames[:n_keep], frame_steps[:n_keep]
                continue
            u, v = u2, v2
            done += k
            if len(self._probe):
                probe_chunks.append(pr)
            if export_every > 0 and (done % export_every == 0
                                     or done == n_steps):
                frames.append(self._global_u(u))
                frame_steps.append(done)
            if guard is not None:
                st = guard.boundary(done, lambda: {
                    "u": u, "v": v, "t": np.int64(done),
                    "probe": _probe_cat(),
                    "frames": (np.stack(frames) if frames
                               else np.zeros((0, self._model.n_dof))),
                    "frame_steps": np.asarray(frame_steps, np.int64),
                    "deltas": deltas})
                if st is not None:
                    u, v = st["u"], st["v"]
        self.u, self.v = u, v
        # End-of-run snapshot, like the quasi-static driver's solve():
        # without it the gauges/dispatch attribution of a JSONL-sinking
        # run would be silently discarded.
        self._rec.emit_run_summary()
        probe_u = (_probe_cat().T[: len(self._probe)]
                   if len(self._probe) else np.zeros((0, n_steps)))
        return DynamicsResult(
            u=self._global_u(u),
            probe_t=(np.arange(n_steps) + 1) * self.dt,
            probe_u=probe_u,
            frames=frames,
            frame_times=[s * self.dt for s in frame_steps],
        )

    def _global_u(self, u) -> np.ndarray:
        from pcg_mpi_solver_tpu.parallel.distributed import gather_owned_global

        return gather_owned_global(self.pm, u, self.mesh, self.dtype)
