"""MATLAB-``pcg``-compatible preconditioned conjugate gradients, fully
in-graph.

Re-implements the reference's PCG (pcg_solver.py:356-598) — itself a
line-for-line port of MATLAB ``pcg`` semantics — as a single
``lax.while_loop``: iterations never leave the device, and every decision the
reference takes on the host (breakdown flags, stagnation, the extra
true-residual matvec on candidate convergence, minimal-residual fallback) is
traced control flow.

Flags (reference pcg_solver.py:399,449,467-469,492-498,560-562):
  0 converged; 1 max-iterations; 2 inf preconditioner; 3 stagnation /
  tolerance too small; 4 rho/pq breakdown.

Per iteration (``variant="classic"``): 3 scalar/fused psums + 1
interface-assembly psum inside the matvec — the same communication count
as the reference's 3 allreduces + 1 halo exchange (SURVEY.md §3.1).

Every loop body (all three variants, scalar and blocked) traces its
phases under ``jax.named_scope`` labels — ``pcg/matvec``,
``pcg/precond``, ``pcg/reduce``, ``pcg/axpy`` — so profiler-trace
events bucket deterministically into the obs/perf.py attribution
phases (obs/profview.py parses them back; the analysis/ fast-tier
``scope-labels`` rule proves the labels exist in every traced variant).

``variant="fused"`` restructures the loop body around the
Chronopoulos–Gear recurrence (the single-reduction CG of arXiv:2105.06176
§2): the matvec runs on the preconditioned residual (w = A.z), the search
direction and its A-image advance by recurrence (p = z + beta*p,
q = w + beta*q), and rho = <r,z>, the p.Ap denominator
(mu - beta*rho/alpha_prev), the residual norm, the stagnation norms and
the inf-preconditioner predicate are all read from ONE fused psum — so a
fused iteration is 1 scalar psum + the interface psum, vs classic's 3+1,
and no axpy is serialized between reductions.  The price: convergence/
stagnation tests see the residual of the iterate committed one trip
earlier (the pipelined lag), so iteration counts differ from classic by
O(1) and the variant is NOT bit-exact with the MATLAB reference (classic
stays the parity default).  The deferred true-residual check (mode 1)
and the flag taxonomy are shared between variants.

``variant="pipelined"`` is Ghysels–Vanroose depth-1 pipelined CG
(arXiv:2105.06176 §3, safeguarded per the communication-reduced survey
arXiv:2501.03743): the fused variant's single psum still READS this
iteration's matvec output (mu = <z, A.z>), so the reduction serializes
after the stencil; pipelining removes that last dependency by keeping
the preconditioned residual u = M^-1.r and its A-image w = A.u in the
carry and advancing BOTH by recurrence.  Per trip the single fused psum
(gamma = <r,u>, delta = <w,u>, the residual/stagnation norms, the
inf-preconditioner flag) consumes ONLY previous-iteration carry leaves,
and the trip's preconditioner apply m = M^-1.w plus stencil matvec
n = A.m consume only carry leaves too — the psum and the matvec are
data-independent in BOTH directions, so the scheduler may run the
reduction concurrently with the stencil and the collective's latency
disappears behind compute (the analysis/ ``psum-overlap`` rule proves
that independence on the traced body jaxpr; classic and fused are its
serialized negative controls).  The price: four more recurrence vectors
(s = A.p, q = M^-1.s, z = A.q ride the carry next to u/w), one priming
trip per cold start (u0/w0 through the body's own precond/matvec — no
extra stencil instantiation), and a residual recurrence that drifts
from truth FASTER than fused's (2501.03743 §4) — the same deferred
true-residual drift guard applies with the LOWER
``PIPELINED_DRIFT_LIMIT``.  Iteration counts differ from classic by
O(1); NOT bit-exact with the reference.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.config import PCG_VARIANTS
from pcg_mpi_solver_tpu.obs.trace import trace_record, trace_specs
from pcg_mpi_solver_tpu.ops.matvec import Ops

# Flag taxonomy for recovery policy (resilience/): flags 2 (Inf
# preconditioner), 4 (rho/pq breakdown) and 6 (sustained fused
# residual drift, below) are RECOVERABLE-by-restart — they mean the
# Krylov recurrence collapsed, not that the system is unsolvable, so
# restarting CG from the tracked min-residual iterate (a fresh
# direction set, possibly with a weaker-but-safer preconditioner)
# routinely completes the solve.  Flags 1 (budget) and 3 (stagnation /
# tolerance floor) are NOT in this set: restarts cannot conjure more
# iterations or a finer floor.  NaN carries trip NO flag at all (every
# breakdown predicate compares false on NaN) — detecting them is the
# host-side budget loop's job (solver/chunked.py).
BREAKDOWN_FLAGS = (2, 4, 6)

# Terminal flag of a QUARANTINED column of a blocked multi-RHS solve
# (resilience/engine.run_many_with_recovery, and pcg_many's one-shot
# finalize for a NaN-poisoned column): the column's recovery budget is
# spent (or there is none) and its reported solution is the tracked
# min-residual iterate with its recomputed true residual — the block
# completes instead of failing on one pathological tenant.  Documented
# in docs/RUNBOOK.md "Blocked solve failure modes & quarantine".
QUARANTINE_FLAG = 5

# Fused-variant residual-drift guard (satellite of ISSUE 9, per the
# communication-reduced CG survey arXiv:2501.03743 §4: recurrence-based
# variants accumulate true-vs-recurrence residual drift).  The deferred
# true-residual check (mode 1) already owns an honest recomputed norm;
# when it exceeds FUSED_DRIFT_FACTOR x the recurrence norm that
# prompted the candidacy (and the check did not converge), the
# iteration is counted as DRIFTED in the carry's ``drift`` leaf.  At
# FUSED_DRIFT_LIMIT drifted checks the loop exits with flag 6
# (DRIFT_FLAG) — a recoverable breakdown: the ladder restarts from the
# min-residual iterate with a fresh recurrence instead of letting the
# solve grind on a residual recurrence that no longer tracks truth.
# Constants, not SolverConfig knobs: they gate a failure diagnostic,
# not a numerics choice, so they must not fork cache keys/fingerprints.
DRIFT_FLAG = 6
FUSED_DRIFT_FACTOR = 2.0
FUSED_DRIFT_LIMIT = 3

# The pipelined recurrence keeps FOUR derived vectors (u/w/s/z on top of
# q) current by axpy instead of recomputation, so its residual
# recurrence drifts from the true residual faster than fused's
# (arXiv:2501.03743 §4 measures roughly one extra digit lost per depth)
# — the same flag-6 guard applies with a LOWER limit: two drifted
# deferred checks, not three, hand the solve to the ladder's
# fresh-recurrence restart.
PIPELINED_DRIFT_LIMIT = 2

# Periodic true-residual replacement cadence (the second 2501.03743
# safeguard, and the one that sets the variant's ATTAINABLE accuracy):
# after this many committed iterations without a deferred check, a
# check trip is FORCED — the true residual replaces the recurrence one
# and the priming bit re-arms, re-synchronizing the u/w chain.  Without
# it the f32 recurrence floors near 5e-3 and breaks down (flag 4: the
# delta - beta*gamma/alpha denominator goes non-positive at ~35-80
# iterations on the golden cube); at 25 the f32 inner solve reaches
# tol 1e-5 in ~105 iterations vs classic's 101 (measured), while 50 is
# already too coarse (breakdown before the first replacement).  Cost:
# ~3 extra matvec-bearing trips per cadence — the pending trip whose
# precond/matvec products are abandoned when forced candidacy fires,
# the check (A.x), and the re-prime (M^-1.r + A.u) — ~12% at 25;
# iteration COUNTS are unaffected (forced checks do not advance i,
# count MoreSteps, touch stagnation, or tick the plateau/progress
# windows).  A constant, not a knob: it
# gates a numerical-safety mechanism, so it must not fork cache
# keys/fingerprints.
PIPELINED_REPLACE_EVERY = 25

# Loop formulations (SolverConfig.pcg_variant): "classic" is the
# MATLAB-compatible 3-reduction body, "fused" the Chronopoulos–Gear
# single-reduction recurrence, "pipelined" the Ghysels–Vanroose depth-1
# overlap form (see module docstring).  Derived from the canonical
# config.PCG_VARIANTS name table — the single source the CLI, config
# validation, cache keys and the ops collective tables share.
VALID_PCG_VARIANTS = PCG_VARIANTS

# Variants whose convergence bookkeeping lags the committed iterate by
# one trip (the recurrence forms): their carry ``x`` is an iterate whose
# residual was never evaluated, so terminal selection must take the
# tracked min-residual iterate unconditionally (``select_best
# always_min``), and a warm resume must never flag-0 off the
# predecessor's stale norm.
LAGGED_VARIANTS = ("fused", "pipelined")


def drift_limit_for(variant: str) -> int:
    """Flag-6 drift budget of a recurrence variant's deferred checks."""
    return (PIPELINED_DRIFT_LIMIT if variant == "pipelined"
            else FUSED_DRIFT_LIMIT)


class PCGResult(NamedTuple):
    x: jnp.ndarray        # (P, n_loc) solution on effective dofs (0 elsewhere)
    flag: jnp.ndarray     # () int32
    relres: jnp.ndarray   # () float
    iters: jnp.ndarray    # () int32  (1-based, MATLAB-compatible)


def cold_carry(x0, r0, normr0, dot_dtype, trace=None,
               variant: str = "classic") -> dict:
    """Cold-start Krylov carry for resumable ``pcg`` calls: with p=0, rho=1
    the resumed beta/p recurrence reduces to the standard first iteration
    p = z.  The single schema shared by every chunked-dispatch call site.
    ``trace`` (obs/trace.py ring dict) rides the carry when convergence
    tracing is on — it resumes across dispatch boundaries like the rest of
    the Krylov state.

    Donation contract (solver/chunked.py donated-carry dispatch): a carry
    dict is a linear resource — once passed to a dispatch compiled with
    ``donate_argnums`` on the carry argument, the caller must never touch
    that dict (or any alias of its leaves) again; the next dispatch's
    carry is the previous dispatch's freshly-returned one.  ``pcg``'s
    ``return_carry`` output satisfies the producer side: every returned
    leaf is an output of the traced computation (never a passed-through
    host reference), so donating the INPUT carry can at most alias
    input->output buffers, exactly as intended."""
    dd = dot_dtype
    zero_i = jnp.asarray(0, jnp.int32)
    out = dict(
        x=x0, r=r0, p=jnp.zeros_like(x0),
        rho=jnp.asarray(1.0, dd),
        stag=zero_i, moresteps=zero_i,
        normrmin=jnp.asarray(normr0, dd), xmin=x0, imin=zero_i,
        since_best=zero_i, best_at_reset=jnp.asarray(normr0, dd),
        win_start=jnp.asarray(normr0, dd), win_count=zero_i,
        normr_act=jnp.asarray(normr0, dd), exec=zero_i)
    if variant in LAGGED_VARIANTS:
        # Chronopoulos–Gear recurrence state (``variant="fused"``, and
        # the base of the pipelined carry): ``q`` tracks an A-chain
        # vector alongside p and ``alpha`` is the previous step size.
        # The cold values make the first recurrence trip reduce to the
        # classic first iteration: with p = q = 0 the direction
        # recurrence collapses to p = z, q = w, and alpha = +inf zeroes
        # the denominator correction exactly (beta*rho/inf == 0 in
        # IEEE), leaving alpha = rho/mu — the textbook first step.
        # ``fresh`` gates candidate true-residual checks on a committed
        # update since the last check (see the fused body in ``pcg``).
        out["q"] = jnp.zeros_like(x0)
        out["alpha"] = jnp.asarray(np.inf, dd)
        out["fresh"] = jnp.asarray(1, jnp.int32)
        # drifted-true-residual-check count (drift_limit_for guard);
        # rides the resumable carry so capped dispatches accumulate it
        out["drift"] = zero_i
    if variant == "pipelined":
        # Ghysels–Vanroose recurrence vectors: u = M^-1.r, w = A.u,
        # s = A.p, z = A.q (q doubles as M^-1.s in GV notation).  All
        # cold-zero; ``init`` = 1 arms the PRIMING trip — the first body
        # trip computes u0 = M^-1.r0, w0 = A.u0 through the body's own
        # preconditioner apply and stencil matvec (no pre-loop stencil
        # instantiation, no budget consumed) and clears the bit.
        out["u"] = jnp.zeros_like(x0)
        out["w"] = jnp.zeros_like(x0)
        out["s"] = jnp.zeros_like(x0)
        out["z"] = jnp.zeros_like(x0)
        out["init"] = jnp.asarray(1, jnp.int32)
        # committed iterations since the last deferred check — the
        # PIPELINED_REPLACE_EVERY forced-replacement cadence counter
        out["sc"] = zero_i
    if trace is not None:
        out["trace"] = trace
    return out


def carry_part_specs(part_spec, rep_spec, trace: bool = False,
                     variant: str = "classic", many: bool = False) -> dict:
    """shard_map PartitionSpecs for the carry dict (vectors on the parts
    axis, bookkeeping scalars replicated; the optional trace ring is
    replicated scalar streams; the recurrence variants add their extra
    leaves — fused the A.p vector and replicated scalars, pipelined the
    four GV recurrence vectors plus the priming bit).  ``many`` is
    the RHS-blocked carry (:func:`pcg_many`): same keys with (R,)
    bookkeeping vectors (still replicated) plus the per-RHS ``flag``
    and ``prec_sel`` leaves — a blocked resume must keep
    already-terminated columns frozen and per-column recovery state
    (which preconditioner each column runs, resilience/) intact across
    dispatch boundaries, which the scalar carry never needed."""
    P, R = part_spec, rep_spec
    out = dict(x=P, r=P, p=P, rho=R, stag=R, moresteps=R,
               normrmin=R, xmin=P, imin=R, since_best=R, best_at_reset=R,
               win_start=R, win_count=R,
               normr_act=R, exec=R)
    if variant in LAGGED_VARIANTS:
        out.update(q=P, alpha=R, fresh=R, drift=R)
    if variant == "pipelined":
        out.update(u=P, w=P, s=P, z=P, init=R, sc=R)
    if many:
        out["flag"] = R
        out["prec_sel"] = R
    if trace:
        out["trace"] = trace_specs(R)
    return out


def refine_tol(tolb, normr, inner_tol):
    """Adaptive inner tolerance for one mixed-precision refinement cycle:
    the final cycle only needs to contract the residual by tolb/normr — a
    fixed inner_tol would overshoot the outer tolerance by orders of
    magnitude (wasted iterations)."""
    return jnp.clip(0.5 * tolb / jnp.maximum(normr, tolb * 1e-30),
                    inner_tol, 0.25).astype(jnp.float32)


def select_best(ops: Ops, data: dict, fext: jnp.ndarray, carry: dict,
                always_min: bool = False):
    """Min-residual fallback for a terminally-failed resumable solve.

    The ``return_carry`` path of ``pcg`` skips MATLAB pcg's min-residual
    finalize (it would cost one matvec + psum per dispatch whose result the
    resuming caller discards); the driver applies this once, at actual
    termination.  Returns (x, relres) matching finalize_bad's semantics.

    ``always_min`` (the fused variant): the carry ``x`` is the
    pipelined-lag fresh iterate whose residual was never evaluated and
    ``normr_act`` belongs to its predecessor, so the MATLAB
    last-vs-min comparison has no honest operand pair — return the
    min-residual iterate with its recomputed true residual
    unconditionally (an internally consistent (x, relres) pair)."""
    eff = data["eff"]
    w = data["weight"] * eff
    n2b = jnp.sqrt(ops.wdot(w, fext, fext))
    r_min = fext - eff * ops.matvec(data, carry["xmin"])
    normr_min = jnp.sqrt(ops.wdot(w, r_min, r_min))
    den = jnp.maximum(n2b, jnp.asarray(np.finfo(np.float32).tiny, n2b.dtype))
    if always_min:
        return carry["xmin"], normr_min / den
    use_min = normr_min < carry["normr_act"]
    x = jnp.where(use_min, carry["xmin"], carry["x"])
    relres = jnp.where(use_min, normr_min, carry["normr_act"]) / den
    return x, relres


def pcg(
    ops: Ops,
    data: dict,
    fext: jnp.ndarray,        # (P, n_loc) rhs, already restricted to eff dofs
    x0: jnp.ndarray,          # (P, n_loc) initial guess (eff-restricted)
    inv_diag,                 # M^-1 on eff dofs (0 elsewhere): (P, n_loc)
                              # scalar Jacobi, (P, n_node_loc, 3, 3)
                              # block-Jacobi, or the mg V-cycle prec
                              # dict (all applied via ops.apply_prec)
    tol,
    max_iter,                 # static int, or traced scalar (then pass
                              # max_iter_nominal for the MoreSteps budget)
    glob_n_dof_eff: int,
    max_stag_steps: int = 3,
    max_iter_nominal: Optional[int] = None,
    carry_in: Optional[dict] = None,
    return_carry: bool = False,
    plateau_window: int = 0,
    x0_zero: bool = False,
    progress_window: int = 0,
    progress_ratio: float = 0.7,
    progress_min_gain: float = 30.0,
    trace_in: Optional[dict] = None,
    trace_scale=None,
    variant: str = "classic",
):
    """Returns PCGResult, or (PCGResult, carry) with ``return_carry``, or
    (PCGResult, trace) when tracing is on without ``return_carry``.

    ``variant`` selects the loop formulation (``VALID_PCG_VARIANTS``):
    "classic" is the MATLAB-compatible 3-reduction body below; "fused"
    the Chronopoulos–Gear single-reduction recurrence; "pipelined" the
    Ghysels–Vanroose depth-1 overlap form (module docstring).  All
    share the carry schema (``cold_carry`` / ``carry_part_specs`` with
    the matching ``variant``), the flag taxonomy, the deferred
    true-residual check, the trace ring and the resumable-dispatch
    contract — a sequence of capped recurrence-variant calls is
    bit-identical to one long solve of that variant, exactly like
    classic.

    ``trace_in`` (an ``obs/trace.py`` ring dict) enables in-graph
    convergence tracing: each committed iteration appends
    (normr, rho, stag, flag) to the device-resident ring inside the
    while_loop — four dynamic-index scalar stores, no extra collectives,
    no host transfers.  With ``return_carry`` the (updated) ring rides the
    returned carry under ``"trace"`` and a subsequent call resumes it via
    ``carry_in`` (so a chunked solve still surfaces ONE ring at the end);
    otherwise the updated ring is returned as a second output.
    ``trace_scale`` rescales recorded residual norms (mixed-precision
    inner cycles iterate on r/||r||; passing ||r|| restores absolute
    residuals in the trace).

    ``progress_window`` > 0 adds a progress-RATE exit for mixed-mode inner
    cycles (flag 3, min-residual iterate — the refinement driver restarts
    in f64): every ``progress_window`` iterations the MONOTONE minimal
    residual ``normrmin`` is compared against its value a window ago; if
    the window contracted it by less than 1/``progress_ratio`` AND the
    cycle has already contracted the rhs norm by ``progress_min_gain``
    (i.e. the cheap early phase is long over and the iterate is plausibly
    near its f32 floor), the remaining grind is worth less than one f64
    restart.  The min-gain gate is what the plateau knob lacked: CG's
    residual is non-monotone and plateaus pre-asymptotically, so a bare
    no-improvement window false-triggers at small scale
    (docs/BENCH_LOG.md 2026-07-31: window 30 DIVERGED at iter 31/255);
    requiring 30x achieved contraction first makes early plateaus
    unreachable.  Keep OFF (0) for direct/f64 solves — the reference's
    iteration-parity contract has no such exit.

    ``x0_zero`` declares (statically) that ``x0`` is all zeros, eliding the
    initial-residual matvec: r0 = fext - A.0 = fext exactly, and
    ||r0|| = ||fext|| = n2b (the same reduction).  One fewer stencil
    instantiation in the compiled program — the hybrid octree stencil
    costs minutes of compile time PER INSTANTIATION (docs/BENCH_LOG.md
    2026-07-31) — and one fewer matvec execution at runtime.

    ``plateau_window`` > 0 adds a plateau exit beyond MATLAB pcg's
    stagnation test: if no meaningfully (0.1%) better minimal residual
    appears for that many consecutive iterations, exit with flag 3 and
    the min-residual iterate.  Off (0) by default and EXPERIMENTAL:
    CG's residual is non-monotone pre-asymptotically, so short windows
    false-trigger during healthy convergence (see SolverConfig.
    mixed_plateau_window).  The counter rides the carry, so chunked
    dispatch resumes it exactly.

    ``carry_in`` resumes the Krylov iteration from a previous call's carry
    (search direction, rho, stagnation/min-residual bookkeeping), making a
    sequence of capped-budget calls mathematically identical to one long
    solve — the dispatch-chunked driver path relies on this.  When given,
    it overrides ``x0`` and the initial-residual matvec.
    """
    if variant not in VALID_PCG_VARIANTS:
        raise ValueError(f"pcg variant must be one of "
                         f"{VALID_PCG_VARIANTS}, got {variant!r}")
    fused = variant == "fused"
    pipelined = variant == "pipelined"
    lagged = variant in LAGGED_VARIANTS
    # flag-6 drift budget of this variant's deferred checks (the ONE
    # variant-to-limit dispatch point; trace-time constant)
    drift_limit = drift_limit_for(variant)
    warm = carry_in is not None
    if warm and "trace" in carry_in:
        # resumable dispatch: the ring continues from the previous call
        trace0 = carry_in["trace"]
    else:
        trace0 = trace_in
    traced = trace0 is not None
    eff = data["eff"]
    w = data["weight"] * eff
    dt = fext.dtype
    eps = jnp.asarray(np.finfo(np.dtype(dt)).eps, ops.dot_dtype)

    # MATLAB: maxmsteps = min([floor(n/50), 5, n-maxit])
    nominal = max_iter_nominal if max_iter_nominal is not None else max_iter
    maxmsteps = min(glob_n_dof_eff // 50, 5, glob_n_dof_eff - nominal)

    n2b = jnp.sqrt(ops.wdot(w, fext, fext))
    tolb = tol * n2b

    def amul(v):
        """Assembled K.v restricted to effective dofs (reference computes the
        full product then slices to LocDofEff, pcg_solver.py:482-484).
        Traced under the ``pcg/matvec`` named scope so profiler-trace
        events bucket deterministically (obs/profview.py; the handful of
        out-of-loop applications — r0, finalize, deferred checks — are
        O(1) per solve and absorbed by the per-iteration division)."""
        with jax.named_scope("pcg/matvec"):
            return eff * ops.matvec(data, v)

    if warm:
        x0 = carry_in["x"]
        r0 = carry_in["r"]
        normr0 = carry_in["normr_act"].astype(ops.dot_dtype)
    elif x0_zero:
        r0 = fext
        normr0 = n2b
    else:
        r0 = fext - amul(x0)
        normr0 = jnp.sqrt(ops.wdot(w, r0, r0))

    zero_rhs = n2b == 0
    if lagged and warm:
        # the warm recurrence-variant normr0 is the PREDECESSOR
        # iterate's norm (the pipelined lag): never flag-0 the
        # unevaluated resumed iterate off it — the first trip reduces
        # the fresh norm and the deferred check gates flag 0 on a true
        # residual as usual
        initial_ok = jnp.asarray(False)
    else:
        initial_ok = normr0 <= tolb

    carry0 = dict(
        x=x0,
        r=r0,
        p=carry_in["p"] if warm else jnp.zeros_like(x0),
        rho=carry_in["rho"] if warm else jnp.asarray(1.0, ops.dot_dtype),
        i=jnp.asarray(0, jnp.int32),
        # zero rhs => skip the loop entirely (reference early-returns,
        # pcg_solver.py:387-395); the outputs are forced to zero below.
        flag=jnp.where(zero_rhs | initial_ok, 0, 1).astype(jnp.int32),
        stag=carry_in["stag"] if warm else jnp.asarray(0, jnp.int32),
        moresteps=carry_in["moresteps"] if warm else jnp.asarray(0, jnp.int32),
        iter_out=jnp.asarray(0, jnp.int32),
        normr_act=normr0.astype(ops.dot_dtype),
        normrmin=carry_in["normrmin"] if warm else normr0.astype(ops.dot_dtype),
        xmin=carry_in["xmin"] if warm else x0,
        imin=carry_in["imin"] if warm else jnp.asarray(0, jnp.int32),
        since_best=(carry_in["since_best"] if warm
                    else jnp.asarray(0, jnp.int32)),
        best_at_reset=(carry_in["best_at_reset"] if warm
                       else normr0.astype(ops.dot_dtype)),
        win_start=(carry_in["win_start"] if warm
                   else normr0.astype(ops.dot_dtype)),
        win_count=(carry_in["win_count"] if warm
                   else jnp.asarray(0, jnp.int32)),
        # mode 1 = the NEXT trip performs the deferred true-residual check
        # of the iteration committed this trip (see body); always 0 at loop
        # exit, so it never rides the exported resume carry
        mode=jnp.asarray(0, jnp.int32),
    )
    if lagged:
        # Chronopoulos–Gear / GV recurrence state (see cold_carry): cold
        # values make the first trip the textbook first CG step; warm
        # values continue the recurrence exactly across dispatch
        # boundaries.
        carry0["q"] = carry_in["q"] if warm else jnp.zeros_like(x0)
        carry0["alpha"] = (carry_in["alpha"] if warm
                           else jnp.asarray(np.inf, ops.dot_dtype))
        carry0["fresh"] = (carry_in["fresh"] if warm
                           else jnp.asarray(1, jnp.int32))
        # residual-drift guard state: cumulative drifted-check count
        # (exported, resumes across dispatches) and the recurrence norm
        # of the pending candidate (internal — mode is always 0 at loop
        # exit, so it never needs to ride the exported carry)
        carry0["drift"] = (carry_in["drift"] if warm
                           else jnp.asarray(0, jnp.int32))
        carry0["chk_normr"] = jnp.asarray(0.0, ops.dot_dtype)
    if pipelined:
        # GV recurrence vectors + the priming bit (see cold_carry): a
        # warm resume continues all five recurrences exactly; a cold
        # start (or a ladder restart that re-armed ``init``) primes
        # u0/w0 on the first trip through the body's own precond/matvec.
        for k in ("u", "w", "s", "z"):
            carry0[k] = carry_in[k] if warm else jnp.zeros_like(x0)
        carry0["init"] = (carry_in["init"] if warm
                          else jnp.asarray(1, jnp.int32))
        carry0["sc"] = (carry_in["sc"] if warm
                        else jnp.asarray(0, jnp.int32))
        # internal: whether the pending mode-1 check was FORCED by the
        # replacement cadence alone (then it must not count MoreSteps /
        # candidacy bookkeeping); mode is always 0 at loop exit, so it
        # never rides the exported carry
        carry0["chk_forced"] = jnp.asarray(0, jnp.int32)
    if traced:
        carry0["trace"] = trace0

    def cond(c):
        return (c["flag"] == 1) & (c["i"] < max_iter)

    def _resolve(c, x, r, p, rho, stag, normr_act, candidate, i,
                 extra=None, record=None, count_windows=None):
        """Shared iteration epilogue (reference pcg_solver.py:536-562):
        stag reset / MoreSteps / min-residual / plateau bookkeeping and
        the flag decision, with ``candidate`` marking a true-residual
        check (then ``normr_act`` is the recomputed actual residual
        norm, else the recurrence norm).  ``extra`` overrides/extends
        the output carry entries AFTER the bookkeeping — the fused body
        uses it to track the min residual against the lagged iterate
        ``x`` while committing the freshly-updated vectors (and its
        q/alpha/fresh recurrence state) to the carry.  ``record`` (a
        traced bool, default always-on) gates the trace-ring append:
        the fused trip after a FAILED true-residual check resolves the
        same iterate a second time and must not write a duplicate
        slot.  ``count_windows`` (a traced bool, default always-on)
        gates the plateau/progress-window counters AND their flag-3
        verdicts: a pipelined CADENCE-forced check resolves no new
        committed iteration, so it must not advance the windows' clocks
        (they would tick ~26x per 25 committed iterations — a silent
        variant-dependent early flag-3 drift)."""
        converged = candidate & (normr_act <= tolb)
        # not converged on candidate: stag reset + MoreSteps bookkeeping
        # (reference pcg_solver.py:544-552)
        stag = jnp.where(candidate & ~converged
                         & (stag >= max_stag_steps) & (c["moresteps"] == 0),
                         0, stag).astype(jnp.int32)
        moresteps = jnp.where(candidate & ~converged,
                              c["moresteps"] + 1,
                              c["moresteps"]).astype(jnp.int32)
        toosmall = candidate & ~converged & (moresteps >= maxmsteps)

        # minimal-residual iterate bookkeeping (pcg_solver.py:554-558)
        better = normr_act < c["normrmin"]
        normrmin = jnp.where(better, normr_act, c["normrmin"])
        xmin = jnp.where(better, x, c["xmin"])
        imin = jnp.where(better, i, c["imin"])
        # the plateau counter demands a MEANINGFUL (0.1%) improvement
        # since the LAST RESET (a snapshot, not the ratcheting
        # normrmin: steady sub-0.1%-per-iteration convergence must
        # accumulate against the snapshot and keep resetting, while
        # hair-thin dips at the f32 floor must not)
        improved = normr_act < c["best_at_reset"] * (1 - 1e-3)
        since_best = jnp.where(improved, 0,
                               c["since_best"] + 1).astype(jnp.int32)
        best_at_reset = jnp.where(improved, normr_act,
                                  c["best_at_reset"])

        stagnated = (stag >= max_stag_steps) & ~converged & ~toosmall
        plateaued = ((since_best > plateau_window) & ~converged
                     & ~toosmall if plateau_window else jnp.asarray(False))

        if progress_window:
            # progress-rate exit (see docstring): evaluated on the
            # MONOTONE normrmin each time a full window elapses
            win_count = c["win_count"] + 1
            at_window = win_count >= progress_window
            weak_window = normrmin > jnp.asarray(
                progress_ratio, normrmin.dtype) * c["win_start"]
            deep_enough = normrmin * jnp.asarray(
                progress_min_gain, normrmin.dtype) < n2b
            no_progress = (at_window & weak_window & deep_enough
                           & ~converged & ~toosmall)
            # window rolls over when it elapses without tripping
            win_start = jnp.where(at_window, normrmin, c["win_start"])
            win_count = jnp.where(at_window, 0, win_count).astype(jnp.int32)
        else:
            no_progress = jnp.asarray(False)
            win_start, win_count = c["win_start"], c["win_count"]

        if count_windows is not None:
            # frozen window clocks (forced checks): keep the carry
            # values and suppress the verdicts those extra ticks alone
            # could have fired — the next committed trip re-derives them
            tick = count_windows
            since_best = jnp.where(tick, since_best,
                                   c["since_best"]).astype(jnp.int32)
            best_at_reset = jnp.where(tick, best_at_reset,
                                      c["best_at_reset"])
            win_start = jnp.where(tick, win_start, c["win_start"])
            win_count = jnp.where(tick, win_count,
                                  c["win_count"]).astype(jnp.int32)
            plateaued = plateaued & tick
            no_progress = no_progress & tick

        flag = jnp.where(converged, 0,
                jnp.where(toosmall | stagnated | plateaued | no_progress, 3,
                          1)).astype(jnp.int32)
        stop = flag != 1
        out = dict(
            x=x, r=r, p=p, rho=rho,
            i=jnp.where(stop, i, i + 1).astype(jnp.int32),
            flag=flag, stag=stag, moresteps=moresteps,
            iter_out=i,
            normr_act=normr_act, normrmin=normrmin, xmin=xmin, imin=imin,
            since_best=since_best, best_at_reset=best_at_reset,
            win_start=win_start, win_count=win_count,
            mode=jnp.asarray(0, jnp.int32),
        )
        if extra:
            out.update(extra)
        if traced:
            # each committed iteration reaches _resolve exactly once
            # (immediately, or via the deferred mode-1 check with the TRUE
            # residual norm) — one ring slot per iteration; the fused
            # body's re-resolve after a failed check sets record=False
            rec_tr = trace_record(
                c["trace"], normr=normr_act, rho=rho, stag=stag, flag=flag,
                scale=trace_scale)
            if record is None:
                out["trace"] = rec_tr
            else:
                out["trace"] = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(record, a, b),
                    rec_tr, c["trace"])
        # carry leaves the epilogue does not own (the fused drift guard
        # state) pass through unchanged unless ``extra`` overrode them —
        # the while carry must stay type-stable across every branch
        for k in c:
            out.setdefault(k, c[k])
        return out

    def body(c):
        """One trip = one CG iteration (mode 0), or the deferred
        true-residual check of the just-committed iteration (mode 1,
        reference pcg_solver.py:527-533; ``i`` does not advance on the
        committing trip, so iteration counts match the reference
        exactly).  The matvec operand is selected BEFORE the single
        ``amul`` below — the stencil is instantiated ONCE in the whole
        loop body, which at octree-flagship scale is minutes of compile
        time per instantiation (docs/BENCH_LOG.md 2026-07-31)."""
        i = c["i"]
        is_check = c["mode"] == 1

        def pre_iterate(c):
            # scalar Jacobi inverse (P, n_loc), block-Jacobi inverse
            # (P, n_node_loc, 3, 3), or the mg V-cycle dict —
            # ops.apply_prec dispatches on type/rank (data carries the
            # mg hierarchy; unused by the array preconditioners)
            with jax.named_scope("pcg/precond"):
                z = ops.apply_prec(inv_diag, c["r"], data=data)
            # The inf-preconditioner predicate must agree across shards or
            # the while_loop exits divergently and collective counts
            # desync; fuse its global reduction into the rho psum (still
            # one collective).
            inf_loc = jnp.any(jnp.isinf(z)).astype(ops.dot_dtype)
            with jax.named_scope("pcg/reduce"):
                red = ops.wdots(w, [(z, c["r"])], extra=[inf_loc])
            rho, flag2 = red[0], red[1] > 0
            bad_rho = (rho == 0) | jnp.isinf(rho)
            beta = (rho / c["rho"]).astype(dt)
            with jax.named_scope("pcg/axpy"):
                if warm:
                    # Resumed iteration: the beta/p recurrence continues
                    # from the previous call's direction on the very
                    # first pass.
                    bad_beta = (beta == 0) | jnp.isinf(beta)
                    p = z + beta * c["p"]
                else:
                    bad_beta = (i > 0) & ((beta == 0) | jnp.isinf(beta))
                    p = jnp.where(i == 0, z, z + beta * c["p"])
            return p, dict(rho=rho, flag2=flag2, bad_pre=bad_rho | bad_beta)

        def pre_check(c):
            false = jnp.asarray(False)
            return c["x"], dict(rho=c["rho"], flag2=false, bad_pre=false)

        operand, aux = jax.lax.cond(is_check, pre_check, pre_iterate, c)
        q = amul(operand)     # the ONE stencil instantiation in the body

        def post_iterate(args):
            c, p, q, aux = args
            rho = aux["rho"]
            with jax.named_scope("pcg/reduce"):
                pq = ops.wdot(w, p, q)
            bad_pq = (pq <= 0) | jnp.isinf(pq)
            alpha = (rho / pq).astype(dt)
            bad_alpha = jnp.isinf(alpha)

            breakdown = aux["bad_pre"] | bad_pq | bad_alpha
            new_flag = jnp.where(aux["flag2"], 2,
                                 jnp.where(breakdown, 4, 1)).astype(jnp.int32)

            def on_break(c):
                out = dict(c)
                out["flag"] = new_flag
                out["iter_out"] = i
                out["rho"] = rho
                if traced:
                    # breakdown exits skip the epilogue; record the flag-2/4
                    # slot here so the trace shows WHY the solve died
                    out["trace"] = trace_record(
                        c["trace"], normr=c["normr_act"], rho=rho,
                        stag=c["stag"], flag=new_flag, scale=trace_scale)
                return out

            def on_continue(c):
                with jax.named_scope("pcg/axpy"):
                    r = c["r"] - alpha * q
                # Fused 3-norm reduction: ||p||, ||x_old||, ||r|| in ONE
                # psum (reference pcg_solver.py:504-507).
                with jax.named_scope("pcg/reduce"):
                    sq = ops.wdots(w, [(p, p), (c["x"], c["x"]), (r, r)])
                normp, normx, normr = (jnp.sqrt(sq[0]), jnp.sqrt(sq[1]),
                                       jnp.sqrt(sq[2]))
                stag = jnp.where(
                    normp * jnp.abs(alpha).astype(ops.dot_dtype)
                    < eps * normx,
                    c["stag"] + 1, 0).astype(jnp.int32)
                with jax.named_scope("pcg/axpy"):
                    x = c["x"] + alpha * p

                candidate = ((normr <= tolb) | (stag >= max_stag_steps)
                             | (c["moresteps"] > 0))

                # Non-candidate epilogue (normr_act := recurrence norm).
                resolved = _resolve(c, x=x, r=r, p=p, rho=rho, stag=stag,
                                    normr_act=normr.astype(ops.dot_dtype),
                                    candidate=jnp.asarray(False), i=i)
                # Candidate: COMMIT the iterate but DEFER the epilogue to
                # the next trip's true-residual check (mode 1); i, flag and
                # all bookkeeping are untouched until then.
                pending = dict(c, x=x, r=r, p=p, rho=rho, stag=stag,
                               iter_out=i, mode=jnp.asarray(1, jnp.int32))
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(candidate, a, b),
                    pending, resolved)

            return jax.lax.cond(aux["flag2"] | breakdown, on_break,
                                on_continue, c)

        def post_check(args):
            c, _x, q, _aux = args
            # q = amul(x): recompute the ACTUAL residual before declaring
            # convergence (reference pcg_solver.py:527-533).
            r_true = fext - q
            with jax.named_scope("pcg/reduce"):
                normr_act = jnp.sqrt(ops.wdot(w, r_true, r_true))
            return _resolve(c, x=c["x"], r=r_true, p=c["p"], rho=c["rho"],
                            stag=c["stag"], normr_act=normr_act,
                            candidate=jnp.asarray(True), i=i)

        return jax.lax.cond(is_check, post_check, post_iterate,
                            (c, operand, q, aux))

    def body_fused(c):
        """One trip of the fused-collective (Chronopoulos–Gear) variant:
        z = M^-1.r, w = A.z (still the ONE stencil instantiation per
        body), then EVERY per-iteration reduction of the classic loop —
        rho = <r,z>, the p.Ap denominator via mu = <z,w>, the residual
        norm, the stagnation norms and the inf-preconditioner predicate
        — in a SINGLE fused psum.  The search direction and its A-image
        advance by recurrence (p = z + beta*p, q = w + beta*q;
        <p,Ap> = mu - beta*rho/alpha_prev in exact arithmetic), so no
        reduction serializes against an axpy.

        Pipelined lag: the reduction reads the residual of the iterate
        committed LAST trip, so the epilogue (stag / min-residual /
        candidate detection) resolves that iterate while this trip's
        update is computed — iteration counts differ from classic by
        O(1).  Mode 1 is the same deferred true-residual check as
        classic, but gated by the ``fresh`` carry bit so a failed check
        always commits an update before re-checking (MATLAB's MoreSteps
        alternation; without the gate the moresteps>0 clause would
        re-check the same iterate forever)."""
        i = c["i"]
        is_check = c["mode"] == 1

        def pre_iterate(c):
            # scalar/block-Jacobi inverse or mg V-cycle (classic
            # pre_iterate's z)
            with jax.named_scope("pcg/precond"):
                return ops.apply_prec(inv_diag, c["r"], data=data)

        def pre_check(c):
            return c["x"]

        operand = jax.lax.cond(is_check, pre_check, pre_iterate, c)
        kop = amul(operand)   # the ONE stencil instantiation in the body

        def post_iterate(args):
            c, z, wz = args
            # the inf-preconditioner predicate rides the same collective
            # (classic fuses it into the rho psum the same way)
            inf_loc = jnp.any(jnp.isinf(z)).astype(ops.dot_dtype)
            with jax.named_scope("pcg/reduce"):
                red = ops.wdots(w, [(c["r"], z), (z, wz),
                                    (c["r"], c["r"]), (c["p"], c["p"]),
                                    (c["x"], c["x"])], extra=[inf_loc])
            rho, mu = red[0], red[1]
            normr = jnp.sqrt(red[2])
            normp, normx = jnp.sqrt(red[3]), jnp.sqrt(red[4])
            flag2 = red[5] > 0

            # lagged stagnation bookkeeping: the update committed LAST
            # trip moved x by alpha_prev * p (both ride the carry).  On
            # a cold start p = 0 and alpha_prev = inf make the product
            # NaN, which compares False — no increment, as there is no
            # update to check yet.  MATLAB compares against ||x_old||;
            # the fused form uses the post-update ||x|| already in the
            # reduction (an eps-scale test — the variant is documented
            # non-bit-exact).
            # fresh == 0 means the CURRENT iterate's epilogue was already
            # resolved by the preceding (failed) true-residual check —
            # the same update must not be stag-checked twice, and the
            # ring must not get a duplicate slot (record below)
            already = c["fresh"] == 0
            small = normp * jnp.abs(c["alpha"]) < eps * normx
            stag = jnp.where(already, c["stag"],
                             jnp.where(small, c["stag"] + 1,
                                       0)).astype(jnp.int32)
            candidate = (((normr <= tolb) | (stag >= max_stag_steps)
                          | (c["moresteps"] > 0)) & ~already)

            # Chronopoulos–Gear scalars; same breakdown taxonomy as
            # classic (bad denominator <=0/Inf <=> classic's bad pq —
            # SPD demands <p,Ap> > 0).  A candidate trip skips them: rho
            # legitimately collapses as r -> 0, and the true-residual
            # check decides before a spurious flag 4 can.
            bad_rho = (rho == 0) | jnp.isinf(rho)
            beta = rho / c["rho"]
            bad_beta = (beta == 0) | jnp.isinf(beta)
            pq = mu - beta * rho / c["alpha"]
            bad_pq = (pq <= 0) | jnp.isinf(pq)
            alpha = rho / pq
            bad_alpha = jnp.isinf(alpha)
            breakdown = bad_rho | bad_beta | bad_pq | bad_alpha
            new_flag = jnp.where(flag2, 2,
                                 jnp.where(breakdown, 4, 1)).astype(jnp.int32)

            def on_break(c):
                out = dict(c)
                out["flag"] = new_flag
                out["iter_out"] = i
                out["rho"] = rho
                if traced:
                    out["trace"] = trace_record(
                        c["trace"], normr=normr, rho=rho,
                        stag=stag, flag=new_flag, scale=trace_scale)
                return out

            def on_continue(c):
                beta_dt = beta.astype(dt)
                alpha_dt = alpha.astype(dt)
                with jax.named_scope("pcg/axpy"):
                    p2 = z + beta_dt * c["p"]    # p = 0 cold => p2 = z
                    q2 = wz + beta_dt * c["q"]   # A.p by recurrence
                    x2 = c["x"] + alpha_dt * p2
                    r2 = c["r"] - alpha_dt * q2
                # Epilogue of the LAGGED iterate (min residual tracked
                # against c["x"], whose norm this trip's reduction
                # computed), while the carry commits the fresh update.
                resolved = _resolve(
                    c, x=c["x"], r=c["r"], p=c["p"], rho=rho, stag=stag,
                    normr_act=normr.astype(ops.dot_dtype),
                    candidate=jnp.asarray(False), i=i,
                    extra=dict(x=x2, r=r2, p=p2, q=q2,
                               alpha=alpha.astype(ops.dot_dtype),
                               fresh=jnp.asarray(1, jnp.int32)),
                    record=~already)
                # Candidate: defer to the next trip's true-residual
                # check of the CURRENT iterate; nothing is committed
                # (``chk_normr`` records the recurrence norm the check
                # will be compared against — the drift guard).
                pending = dict(c, stag=stag, iter_out=i,
                               mode=jnp.asarray(1, jnp.int32),
                               chk_normr=normr)
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(candidate, a, b),
                    pending, resolved)

            return jax.lax.cond((flag2 | breakdown) & ~candidate,
                                on_break, on_continue, c)

        def post_check(args):
            c, _x, kx = args
            # kx = amul(x): recompute the ACTUAL residual before
            # declaring convergence (same contract as classic).  ``i``
            # must not advance (no update was committed on the candidate
            # trip), and ``fresh`` drops so a failed check cannot
            # re-fire without an intervening committed update.
            r_true = fext - kx
            with jax.named_scope("pcg/reduce"):
                normr_act = jnp.sqrt(ops.wdot(w, r_true, r_true))
            # residual-drift guard (arXiv:2501.03743): a non-converged
            # check whose TRUE residual exceeds FUSED_DRIFT_FACTOR x the
            # recurrence norm that prompted the candidacy means the
            # recurrence residual no longer tracks truth
            disagree = ((normr_act > tolb)
                        & (normr_act > jnp.asarray(
                            FUSED_DRIFT_FACTOR, normr_act.dtype)
                           * c["chk_normr"]))
            drift = (c["drift"] + disagree).astype(jnp.int32)
            out = _resolve(c, x=c["x"], r=r_true, p=c["p"], rho=c["rho"],
                           stag=c["stag"], normr_act=normr_act,
                           candidate=jnp.asarray(True), i=i,
                           extra=dict(q=c["q"], alpha=c["alpha"],
                                      fresh=jnp.asarray(0, jnp.int32),
                                      i=i, drift=drift))
            # sustained drift: exit recoverably (flag 6) instead of
            # grinding on a stale recurrence — the ladder restarts from
            # the min-residual iterate with a fresh recurrence
            drift_exit = (out["flag"] == 1) & (drift >= drift_limit)
            out["flag"] = jnp.where(drift_exit, DRIFT_FLAG,
                                    out["flag"]).astype(jnp.int32)
            return out

        return jax.lax.cond(is_check, post_check, post_iterate,
                            (c, operand, kop))

    def body_pipelined(c):
        """One trip of the Ghysels–Vanroose depth-1 pipelined variant.

        The single fused psum is issued FIRST, on previous-iteration
        carry state only — gamma = <r,u>, delta = <w,u> (u = M^-1.r and
        w = A.u ride the carry by recurrence), the residual/stagnation
        norms and the inf-preconditioner flag (read off the carry ``u``,
        where an Inf inverse lands at priming) — and the trip's
        preconditioner apply m = M^-1.w plus stencil matvec n = A.m
        consume only carry state too: neither the psum nor the matvec
        transitively reads the other's output, so the lowered program
        is free to overlap the collective with the stencil (the
        analysis/ psum-overlap rule proves the independence; the psum
        is NOT placed inside the mode conditional precisely so the
        dependence structure stays first-order visible).

        Trip kinds: mode 1 is the shared deferred true-residual check;
        an armed ``init`` bit makes the trip a PRIMING trip (cold start
        or ladder restart) that computes u0 = M^-1.r0, w0 = A.u0
        through the same precond/matvec slots and commits nothing else;
        otherwise the trip advances the x/r/u/w and p/s/q/z recurrences
        (GV: p = u + beta*p, s = w + beta*s, q = m + beta*q,
        z = n + beta*z, then x += alpha*p, r -= alpha*s, u -= alpha*q,
        w -= alpha*z).  Epilogue semantics (pipelined lag, ``fresh``
        gate, drift guard) mirror the fused body, with
        PIPELINED_DRIFT_LIMIT as the flag-6 budget."""
        i = c["i"]
        is_check = c["mode"] == 1

        # ---- the ONE fused psum: carry-state operands only ------------
        inf_loc = jnp.any(jnp.isinf(c["u"])).astype(ops.dot_dtype)
        with jax.named_scope("pcg/reduce"):
            red = ops.wdots(w, [(c["r"], c["u"]), (c["w"], c["u"]),
                                (c["r"], c["r"]), (c["p"], c["p"]),
                                (c["x"], c["x"])], extra=[inf_loc])
        gamma, delta = red[0], red[1]
        normr = jnp.sqrt(red[2])
        normp, normx = jnp.sqrt(red[3]), jnp.sqrt(red[4])
        flag2 = red[5] > 0

        def pre_check(c):
            return c["x"]

        def pre_work(c):
            # priming trips precondition the residual (u0 = M^-1.r0);
            # iterate trips precondition w (m = M^-1.w — the GV overlap
            # operand).  Both sources are carry leaves: the apply never
            # waits on the psum above.
            src = jnp.where(c["init"] > 0, c["r"], c["w"])
            with jax.named_scope("pcg/precond"):
                return ops.apply_prec(inv_diag, src, data=data)

        m = jax.lax.cond(is_check, pre_check, pre_work, c)
        km = amul(m)          # the ONE stencil instantiation in the body

        def post_prime(args):
            c, m, km = args
            # commit u0 = M^-1.r0 and w0 = A.u0; no iteration advances,
            # no budget is consumed — the next trip is the textbook
            # first step (p = s = q = z = 0, alpha_prev = inf)
            return dict(c, u=m, w=km, init=jnp.asarray(0, jnp.int32))

        def post_iterate(args):
            c, m, km = args
            # lagged stagnation bookkeeping: identical contract to the
            # fused body (the update committed LAST trip moved x by
            # alpha_prev * p; cold p = 0 / alpha_prev = inf compare
            # False — nothing to check yet)
            already = c["fresh"] == 0
            small = normp * jnp.abs(c["alpha"]) < eps * normx
            stag = jnp.where(already, c["stag"],
                             jnp.where(small, c["stag"] + 1,
                                       0)).astype(jnp.int32)
            natural = ((normr <= tolb) | (stag >= max_stag_steps)
                       | (c["moresteps"] > 0))
            # forced replacement cadence (PIPELINED_REPLACE_EVERY):
            # a check trip fires even without natural candidacy, purely
            # to re-synchronize the residual chain
            forced = c["sc"] >= PIPELINED_REPLACE_EVERY
            candidate = (natural | forced) & ~already

            # GV scalars; breakdown taxonomy shared with fused (the
            # denominator delta - beta*gamma/alpha_prev is <p,Ap> in
            # exact arithmetic — SPD demands > 0)
            bad_rho = (gamma == 0) | jnp.isinf(gamma)
            beta = gamma / c["rho"]
            bad_beta = (beta == 0) | jnp.isinf(beta)
            pq = delta - beta * gamma / c["alpha"]
            bad_pq = (pq <= 0) | jnp.isinf(pq)
            alpha = gamma / pq
            bad_alpha = jnp.isinf(alpha)
            breakdown = bad_rho | bad_beta | bad_pq | bad_alpha
            new_flag = jnp.where(flag2, 2,
                                 jnp.where(breakdown, 4, 1)).astype(jnp.int32)

            def on_break(c):
                out = dict(c)
                out["flag"] = new_flag
                out["iter_out"] = i
                out["rho"] = gamma
                if traced:
                    out["trace"] = trace_record(
                        c["trace"], normr=normr, rho=gamma,
                        stag=stag, flag=new_flag, scale=trace_scale)
                return out

            def on_continue(c):
                beta_dt = beta.astype(dt)
                alpha_dt = alpha.astype(dt)
                with jax.named_scope("pcg/axpy"):
                    p2 = c["u"] + beta_dt * c["p"]  # p = 0 cold => p2 = u
                    s2 = c["w"] + beta_dt * c["s"]  # A.p by recurrence
                    q2 = m + beta_dt * c["q"]       # M^-1.s by recurrence
                    z2 = km + beta_dt * c["z"]      # A.q by recurrence
                    x2 = c["x"] + alpha_dt * p2
                    r2 = c["r"] - alpha_dt * s2
                    u2 = c["u"] - alpha_dt * q2     # M^-1.r by recurrence
                    w2 = c["w"] - alpha_dt * z2     # A.u by recurrence
                resolved = _resolve(
                    c, x=c["x"], r=c["r"], p=c["p"], rho=gamma, stag=stag,
                    normr_act=normr.astype(ops.dot_dtype),
                    candidate=jnp.asarray(False), i=i,
                    extra=dict(x=x2, r=r2, p=p2, u=u2, w=w2, s=s2, q=q2,
                               z=z2, alpha=alpha.astype(ops.dot_dtype),
                               fresh=jnp.asarray(1, jnp.int32),
                               sc=(c["sc"] + 1).astype(jnp.int32)),
                    record=~already)
                pending = dict(c, stag=stag, iter_out=i,
                               mode=jnp.asarray(1, jnp.int32),
                               chk_normr=normr,
                               chk_forced=(forced & ~natural
                                           ).astype(jnp.int32))
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(candidate, a, b),
                    pending, resolved)

            return jax.lax.cond((flag2 | breakdown) & ~candidate,
                                on_break, on_continue, c)

        def post_work(args):
            c = args[0]
            return jax.lax.cond(c["init"] > 0, post_prime, post_iterate,
                                args)

        def post_check(args):
            c, _x, kx = args
            # kx = amul(x): the shared deferred true-residual check,
            # plus the 2501.03743 TRUE-RESIDUAL REPLACEMENT the
            # pipelined recurrence needs: the carry residual is replaced
            # with the recomputed one (like classic/fused), and because
            # u = M^-1.r / w = A.u advance by recurrence against the OLD
            # r, the priming bit is RE-ARMED — the next trip rebuilds
            # u/w from the honest residual through the body's own
            # precond/matvec (one extra trip per check, no budget
            # consumed), re-synchronizing the residual chain instead of
            # letting f32 recurrence drift degrade the search.  The
            # p/s and q/z direction chains are exact recurrence PAIRS
            # (s mirrors p under A, z mirrors q), so they stay.
            # Sustained disagreement still exits via flag 6 at the
            # TIGHTER pipelined budget — replacement bounds drift per
            # check; the counter catches a recurrence that keeps lying.
            r_true = fext - kx
            with jax.named_scope("pcg/reduce"):
                normr_act = jnp.sqrt(ops.wdot(w, r_true, r_true))
            disagree = ((normr_act > tolb)
                        & (normr_act > jnp.asarray(
                            FUSED_DRIFT_FACTOR, normr_act.dtype)
                           * c["chk_normr"]))
            drift = (c["drift"] + disagree).astype(jnp.int32)
            # a CADENCE-forced check must not act as convergence
            # candidacy (no MoreSteps/stagnation bookkeeping) nor tick
            # the plateau/progress-window clocks (count_windows) — it
            # only replaces the residual and re-primes; a natural check
            # runs the full shared candidate epilogue
            natural = c["chk_forced"] == 0
            out = _resolve(c, x=c["x"], r=r_true, p=c["p"], rho=c["rho"],
                           stag=c["stag"], normr_act=normr_act,
                           candidate=natural, i=i,
                           extra=dict(fresh=jnp.asarray(0, jnp.int32),
                                      i=i, drift=drift,
                                      init=jnp.asarray(1, jnp.int32),
                                      sc=jnp.asarray(0, jnp.int32),
                                      chk_forced=jnp.asarray(
                                          0, jnp.int32)),
                           count_windows=natural)
            drift_exit = ((out["flag"] == 1)
                          & (drift >= drift_limit))
            out["flag"] = jnp.where(drift_exit, DRIFT_FLAG,
                                    out["flag"]).astype(jnp.int32)
            return out

        return jax.lax.cond(is_check, post_check, post_work, (c, m, km))

    loop_body = (body_pipelined if pipelined
                 else body_fused if fused else body)
    c = jax.lax.while_loop(cond, loop_body, carry0)

    # ---- finalize (reference pcg_solver.py:566-584): on any non-converged
    # exit return the minimal-residual iterate (MATLAB pcg semantics).
    def finalize_ok(c):
        relres = c["normr_act"] / n2b
        return c["x"], relres, c["iter_out"]

    def finalize_bad(c):
        # MATLAB pcg: on failure return whichever of (last iterate, minimal-
        # residual iterate) actually has the smaller true residual, with
        # matching relres/iters.  (The reference accidentally always returns
        # XMin while reporting the better residual, pcg_solver.py:566-582 —
        # we keep x consistent with the reported numbers instead.)
        r_min = fext - amul(c["xmin"])
        normr_min = jnp.sqrt(ops.wdot(w, r_min, r_min))
        if lagged:
            # pipelined lag: the carry x is the fresh iterate whose
            # residual was never evaluated, and normr_act belongs to its
            # predecessor — the min-residual iterate is the only
            # candidate with an honest (recomputed) residual, so return
            # it unconditionally (x/relres/iters stay consistent)
            return c["xmin"], normr_min / n2b, c["imin"]
        use_min = normr_min < c["normr_act"]
        relres = jnp.where(use_min, normr_min, c["normr_act"]) / n2b
        iters = jnp.where(use_min, c["imin"], c["iter_out"])
        x = jnp.where(use_min, c["xmin"], c["x"])
        return x, relres, iters

    if return_carry:
        # Resumable call: skip the min-residual finalize (one matvec + psum
        # per dispatch the resuming caller would discard) — the caller runs
        # select_best() once at actual termination.
        x, relres, iters = finalize_ok(c)
    else:
        x, relres, iters = jax.lax.cond(
            c["flag"] == 0, finalize_ok, finalize_bad, c)

    # all-zero rhs => all-zero solution (reference pcg_solver.py:387-395)
    x = jnp.where(zero_rhs, jnp.zeros_like(x), x)
    relres = jnp.where(zero_rhs, 0.0, relres)
    # +1 makes the count 1-based (MATLAB-compatible, pcg_solver.py:584);
    # the two pre-loop early exits report 0 (pcg_solver.py:392,424).
    iters = jnp.where(zero_rhs | initial_ok, 0, iters + 1)
    flag = jnp.where(zero_rhs, 0, c["flag"]).astype(jnp.int32)

    result = PCGResult(x=x, flag=flag, relres=relres.astype(jnp.float32), iters=iters)
    if return_carry:
        # Raw (non-finalized) continuation state: x is the LAST iterate, not
        # the min-residual fallback — resuming must continue the recurrence.
        # Every entry comes out of the while_loop carry (fresh outputs of
        # the traced program), which is what makes the chunked engine's
        # donated-carry dispatch safe (see cold_carry's donation contract).
        keys = ["x", "r", "p", "rho", "stag", "moresteps",
                "normrmin", "xmin", "imin", "since_best",
                "best_at_reset", "win_start", "win_count", "normr_act"]
        if lagged:
            # the recurrence state resumes like the rest of the Krylov
            # carry (the A-chain vector q, the previous alpha, the
            # update-since-check gate, and the drift-guard count)
            keys += ["q", "alpha", "fresh", "drift"]
        if pipelined:
            # the GV vectors, the priming bit (a dispatch that exits
            # before its priming trip ran must hand the armed bit to
            # its successor) and the replacement-cadence counter
            keys += ["u", "w", "s", "z", "init", "sc"]
        carry = {k: c[k] for k in keys}
        # Executed body-iteration count for host-side budget accounting
        # (result.iters reports the min-residual index on failure, which
        # would undercount).
        carry["exec"] = jnp.where(zero_rhs | initial_ok, 0,
                                  c["iter_out"] + 1).astype(jnp.int32)
        if traced:
            carry["trace"] = c["trace"]
        return result, carry
    if traced:
        return result, c["trace"]
    return result


def pcg_mixed(
    ops32: Ops,
    data32: dict,
    ops64: Ops,
    data64: dict,
    fext: jnp.ndarray,        # (P, n_loc) f64 rhs on eff dofs
    x0: jnp.ndarray,          # (P, n_loc) f64 initial guess
    inv_diag32: jnp.ndarray,  # f32 preconditioner inverse (scalar Jacobi
                              # (P, n_loc) or block-Jacobi (P, n, 3, 3))
    tol: float,
    max_iter: int,
    glob_n_dof_eff: int,
    max_stag_steps: int = 3,
    inner_tol: float = 1e-5,
    max_outer: int = 12,
    plateau_window: int = 0,
    progress_window: int = 0,
    progress_ratio: float = 0.7,
    progress_min_gain: float = 30.0,
    trace_in: Optional[dict] = None,
    variant: str = "classic",
) -> PCGResult:
    """Mixed-precision PCG by iterative refinement (TPU performance path).

    ``variant`` selects the inner f32 Krylov loop's formulation
    (``pcg``'s classic 3-reduction body or the fused Chronopoulos–Gear
    single-reduction recurrence); the f64 refinement shell is identical
    either way.

    ``trace_in`` (f32 ring dict, obs/trace.py) threads in-graph convergence
    tracing through the f32 inner cycles: recorded norms are rescaled by
    the cycle's f64 refresh norm, so the trace reads as ABSOLUTE residuals
    across the whole refinement sequence.  Returns (PCGResult, trace) when
    given.

    Finite-precision CG can only reach a relative residual of roughly
    eps*kappa; in f32 that is far above the reference's tol=1e-7 (SURVEY.md §7
    "hard parts (a)").  Classic fix: run the Krylov iterations in fast f32 on
    a NORMALIZED residual (so f32's dynamic range is centered), and
    periodically recompute the true residual and accumulate the solution in
    f64.  Each outer cycle costs one f64 matvec (emulated on TPU but rare);
    all hot iterations run at f32/MXU speed.  Total inner-iteration count is
    comparable to a pure-f64 solve.
    """
    eff64 = data64["eff"]
    w64 = data64["weight"] * eff64

    def amul64(v):
        return eff64 * ops64.matvec(data64, v)

    n2b = jnp.sqrt(ops64.wdot(w64, fext, fext))
    tolb = tol * n2b

    # The f64 residual is refreshed at the TOP of the loop body (for the
    # CURRENT x) instead of pre-loop + bottom: the numerical sequence
    # r0, inner, r1, inner, ..., rN is identical, but the f64 stencil is
    # instantiated ONCE in the whole program instead of twice — at octree
    # flagship scale each instantiation is minutes of compile time
    # (docs/BENCH_LOG.md 2026-07-31).  Internal flag -1 = still running
    # (the final residual evaluation happens in-body, so the loop cond
    # only tests the flag).
    carry0 = dict(
        x=x0,
        normr=jnp.asarray(np.inf, ops64.dot_dtype),   # last refreshed norm
        outer=jnp.asarray(0, jnp.int32),
        total=jnp.asarray(0, jnp.int32),
        flag=jnp.where(n2b == 0, 0, -1).astype(jnp.int32),
        # inner inf-preconditioner exit last cycle: terminal flag 2, but
        # only AFTER this trip's refresh so the reported residual is the
        # post-cycle one (matches the refresh-at-bottom formulation)
        fatal2=jnp.asarray(False),
    )
    traced = trace_in is not None
    if traced:
        carry0["trace"] = trace_in

    def cond(c):
        return c["flag"] == -1

    def body(c):
        r = fext - amul64(c["x"])
        normr = jnp.sqrt(ops64.wdot(w64, r, r))
        converged = normr <= tolb
        # no-progress guard: refinement must contract the residual
        # (first trip: normr_prev = inf, never trips)
        stalled = normr > 0.5 * c["normr"]
        exhausted = (c["outer"] >= max_outer) | (c["total"] >= max_iter)
        run_inner = ~(converged | stalled | c["fatal2"] | exhausted)

        def do_inner(args):
            r, normr = args
            rhat32 = (r / normr).astype(jnp.float32)
            remaining = jnp.maximum(max_iter - c["total"], 1)
            tol_cycle = refine_tol(tolb, normr, inner_tol)
            # return_carry gives the EXECUTED body-iteration count: on
            # flag-3 exits inner.iters is the min-residual index, which
            # would both undercount the reported work and let the budget
            # run past max_iter.
            inner, icarry = pcg(
                ops32, data32,
                fext=rhat32,
                x0=jnp.zeros_like(rhat32),
                inv_diag=inv_diag32,
                tol=tol_cycle,
                max_iter=remaining,
                glob_n_dof_eff=glob_n_dof_eff,
                max_stag_steps=max_stag_steps,
                max_iter_nominal=max_iter,
                plateau_window=plateau_window,
                return_carry=True,
                x0_zero=True,
                progress_window=progress_window,
                progress_ratio=progress_ratio,
                progress_min_gain=progress_min_gain,
                trace_in=c["trace"] if traced else None,
                # inner iterations run on r/normr: rescale recorded norms
                # to absolute residuals
                trace_scale=normr if traced else None,
                variant=variant,
            )
            # return_carry skips the min-residual finalize, so inner.x is
            # the LAST iterate.  CG's residual is non-monotone: on a
            # non-converged exit (flag 3 from the progress/plateau exits,
            # or budget flag 1) a spiked last iterate hands the f64
            # refresh a worse restart and can spuriously trip the 0.5x
            # stalled guard.  Select the tracked min-residual iterate
            # in-graph — normrmin/xmin ride the carry.  Unlike
            # select_best (the chunked path's finalize, which recomputes
            # xmin's TRUE residual), this trusts the recurrence-tracked
            # norms: one more stencil instantiation here would cost
            # minutes of compile at octree scale for a tie-break that
            # the outer loop immediately re-evaluates anyway — the next
            # trip's f64 refresh computes the true residual of whichever
            # iterate wins, and the 0.5x stalled guard bounds the damage
            # of a drift-optimistic pick.
            use_min = (inner.flag != 0) & (
                icarry["normrmin"] < icarry["normr_act"])
            xbest = jnp.where(use_min, icarry["xmin"], inner.x)
            out = (xbest.astype(fext.dtype) * normr,
                   jnp.maximum(icarry["exec"], 1), inner.flag)
            return out + ((icarry["trace"],) if traced else ())

        def skip_inner(args):
            r, _ = args
            out = (jnp.zeros_like(fext), jnp.asarray(0, jnp.int32),
                   jnp.asarray(1, jnp.int32))
            return out + ((c["trace"],) if traced else ())

        inner_out = jax.lax.cond(
            run_inner, do_inner, skip_inner, (r, normr))
        xinc, exec_n, inner_flag = inner_out[:3]

        flag = jnp.where(
            converged, 0,
            jnp.where(stalled, 3,
             jnp.where(c["fatal2"], 2,
              jnp.where(exhausted, 1, -1)))).astype(jnp.int32)
        out = dict(x=c["x"] + xinc, normr=normr,
                   outer=c["outer"] + run_inner.astype(jnp.int32),
                   total=c["total"] + exec_n, flag=flag,
                   fatal2=inner_flag == 2)
        if traced:
            out["trace"] = inner_out[3]
        return out

    c = jax.lax.while_loop(cond, body, carry0)
    zero_rhs = n2b == 0
    relres = jnp.where(zero_rhs, 0.0, c["normr"] / n2b)
    x = jnp.where(zero_rhs, jnp.zeros_like(c["x"]), c["x"])
    # flag 1 if budget exhausted without convergence
    result = PCGResult(x=x, flag=c["flag"], relres=relres.astype(jnp.float32),
                       iters=c["total"])
    if traced:
        return result, c["trace"]
    return result


# ---------------------------------------------------------------------------
# Batched multi-RHS PCG (ISSUE 6): one Krylov loop over an RHS block.
#
# The block rides a TRAILING axis through every vector (x/r/p/q are
# (P, n_loc, nrhs)) and every bookkeeping scalar becomes an (nrhs,)
# vector.  The loop is LOCKSTEP: one blocked matvec per trip (the
# per-type element matmul batches to (d x d) @ (d x N x nrhs) — the MXU
# amortization the ISSUE targets), and every per-RHS scalar reduction of
# a trip folds into the same psums the single-RHS body runs — the psum
# COUNT is independent of nrhs (classic 5 / fused 3 body psums, proven
# statically by tools/check_collectives.py); only payloads widen.
#
# Per-column semantics mirror solver/pcg.pcg exactly: each column runs
# its own mode-0/mode-1 (deferred true-residual check) sequence, its own
# stagnation/MoreSteps/min-residual bookkeeping and flag taxonomy, and a
# CONVERGED (or broken-down) column FREEZES — every state update is
# gated by a per-column mask, so the remaining columns iterate while
# finished ones hold their accepted iterate.  On CPU a blocked classic
# solve reproduces each column of the equivalent single-RHS solves
# bit-identically (tests/test_pcg_many.py): the blocked gathers/matmuls/
# reductions keep per-column operation order (verified for the general
# element path), and the lockstep merge only reorders WHICH trip a
# column's arithmetic runs on, never the arithmetic itself.
#
# Not supported on the blocked path (by design, documented in
# docs/RUNBOOK.md "Many right-hand sides"): the in-graph trace ring
# (per-solve, not per-column) — telemetry instead carries per-RHS
# `rhs_solve` events from the driver.
# ---------------------------------------------------------------------------


def _colsel(mask, a, b):
    """Per-column select: ``mask`` (R,) over blocked vectors (P, n, R)."""
    return jnp.where(mask[None, None, :], a, b)


def cold_carry_many(x0, r0, normr0, dot_dtype,
                    variant: str = "classic") -> dict:
    """Blocked twin of :func:`cold_carry`: x0/r0 are (P, n_loc, R), the
    bookkeeping rides as (R,) vectors, and the per-RHS ``flag`` and
    ``prec_sel`` leaves (all-1 = running, all-0 = primary
    preconditioner) join the carry so a resumed dispatch keeps
    already-terminated columns frozen and per-column recovery state
    intact.  Same donation contract."""
    dd = dot_dtype
    R = x0.shape[-1]
    zi = jnp.zeros((R,), jnp.int32)
    n0 = jnp.asarray(normr0, dd)
    out = dict(
        x=x0, r=r0, p=jnp.zeros_like(x0),
        rho=jnp.ones((R,), dd),
        stag=zi, moresteps=zi,
        normrmin=n0, xmin=x0, imin=zi,
        since_best=zi, best_at_reset=n0,
        win_start=n0, win_count=zi,
        normr_act=n0, exec=zi,
        flag=jnp.ones((R,), jnp.int32),
        prec_sel=zi)
    if variant in LAGGED_VARIANTS:
        out["q"] = jnp.zeros_like(x0)
        out["alpha"] = jnp.full((R,), np.inf, dd)
        out["fresh"] = jnp.ones((R,), jnp.int32)
        out["drift"] = zi
    if variant == "pipelined":
        # GV recurrence vectors + per-COLUMN priming bits (a ladder-
        # restarted column re-primes independently of its neighbors)
        out["u"] = jnp.zeros_like(x0)
        out["w"] = jnp.zeros_like(x0)
        out["s"] = jnp.zeros_like(x0)
        out["z"] = jnp.zeros_like(x0)
        out["init"] = jnp.ones((R,), jnp.int32)
        out["sc"] = zi
    return out


def select_best_many(ops: Ops, data: dict, fext: jnp.ndarray, carry: dict,
                     always_min: bool = False,
                     respect_flags: bool = False):
    """Per-column min-residual fallback for a terminally-failed blocked
    resumable solve (the blocked twin of :func:`select_best`): one
    blocked matvec and two R-wide dot psums for the WHOLE block — once
    per solve, never per iteration.

    ``respect_flags`` makes this the ONE terminal per-column selection
    (the chunked driver's finalize): converged columns (carry flag 0)
    keep their accepted iterate and true residual, zero-rhs columns
    return exact zeros, and only failed columns take the min-residual
    fallback — MATLAB pcg's taxonomy, in one place."""
    eff = data["eff"]
    w = data["weight"] * eff
    n2b = jnp.sqrt(ops.wdot_many(w, fext, fext))
    r_min = fext - eff[..., None] * ops.matvec(data, carry["xmin"])
    normr_min = jnp.sqrt(ops.wdot_many(w, r_min, r_min))
    den = jnp.maximum(n2b, jnp.asarray(np.finfo(np.float32).tiny, n2b.dtype))
    if always_min:
        x, relres = carry["xmin"], normr_min / den
    else:
        # a NaN/Inf-poisoned column compares False everywhere: force the
        # min-residual fallback so a quarantined column still returns an
        # internally-consistent finite (x, relres) pair (xmin is only
        # ever updated by committed finite iterations)
        use_min = ((normr_min < carry["normr_act"])
                   | ~jnp.isfinite(carry["normr_act"]))
        x = _colsel(use_min, carry["xmin"], carry["x"])
        relres = jnp.where(use_min, normr_min, carry["normr_act"]) / den
    if respect_flags:
        ok = carry["flag"] == 0
        x = _colsel(ok, carry["x"], x)
        relres = jnp.where(ok, carry["normr_act"] / den, relres)
        zero = n2b == 0
        x = jnp.where(zero[None, None, :], jnp.zeros_like(x), x)
        relres = jnp.where(zero, 0.0, relres)
    return x, relres


def restart_carry_many(ops: Ops, data: dict, fext: jnp.ndarray,
                       carry: dict, restart_mask, fallback_mask,
                       quarantine_mask, variant: str = "classic") -> dict:
    """Per-column recovery surgery on a blocked resumable carry (the
    masked twin of the scalar ladder's min-residual restart,
    resilience/engine.run_many_with_recovery):

    * ``restart_mask`` columns get a COLD Krylov carry at their tracked
      min-residual iterate ``xmin`` — residual recomputed by ONE blocked
      matvec for the whole block, flag back to 1 (running), recurrence/
      bookkeeping/drift state reset;
    * ``fallback_mask`` (a subset of restart) columns additionally flip
      their ``prec_sel`` to the scalar-Jacobi fallback preconditioner
      (the per-column rung-2 escalation);
    * ``quarantine_mask`` columns get the terminal ``QUARANTINE_FLAG``
      and are otherwise frozen (their min-residual fallback happens once,
      in :func:`select_best_many`).

    Every UNMASKED column's leaves pass through bit-identically
    (``jnp.where`` selects, never rescales), which is what keeps healthy
    columns' solutions bit-identical to a fault-free block run — the
    fault-isolation contract of tests/test_pcg_many.py."""
    eff = data["eff"]
    w = data["weight"] * eff
    dd = carry["rho"].dtype
    R = fext.shape[-1]
    m = restart_mask
    xmin = carry["xmin"]
    r_new = fext - eff[..., None] * ops.matvec(data, xmin)
    normr_new = jnp.sqrt(ops.wdot_many(w, r_new, r_new))
    zi = jnp.zeros((R,), jnp.int32)
    out = dict(carry)
    out["x"] = _colsel(m, xmin, carry["x"])
    out["r"] = _colsel(m, r_new, carry["r"])
    out["p"] = _colsel(m, jnp.zeros_like(xmin), carry["p"])
    out["rho"] = jnp.where(m, jnp.ones((R,), dd), carry["rho"])
    for k in ("stag", "moresteps", "imin", "since_best", "win_count",
              "exec"):
        out[k] = jnp.where(m, zi, carry[k]).astype(jnp.int32)
    for k in ("normrmin", "best_at_reset", "win_start", "normr_act"):
        out[k] = jnp.where(m, normr_new, carry[k])
    out["prec_sel"] = jnp.where(fallback_mask, 1,
                                carry["prec_sel"]).astype(jnp.int32)
    out["flag"] = jnp.where(
        quarantine_mask, QUARANTINE_FLAG,
        jnp.where(m, 1, carry["flag"])).astype(jnp.int32)
    if variant in LAGGED_VARIANTS:
        out["q"] = _colsel(m, jnp.zeros_like(xmin), carry["q"])
        out["alpha"] = jnp.where(m, jnp.full((R,), np.inf, dd),
                                 carry["alpha"])
        out["fresh"] = jnp.where(m, 1, carry["fresh"]).astype(jnp.int32)
        out["drift"] = jnp.where(m, zi, carry["drift"]).astype(jnp.int32)
    if variant == "pipelined":
        # restarted columns drop their whole GV recurrence and re-ARM
        # the priming bit: the column's next trip recomputes u/w from
        # the restarted residual through the body's own precond/matvec
        # (unmasked columns' chains pass through bitwise, as ever)
        for k in ("u", "w", "s", "z"):
            out[k] = _colsel(m, jnp.zeros_like(xmin), carry[k])
        out["init"] = jnp.where(m, 1, carry["init"]).astype(jnp.int32)
        out["sc"] = jnp.where(m, zi, carry["sc"]).astype(jnp.int32)
    return out


def pcg_many(
    ops: Ops,
    data: dict,
    fext: jnp.ndarray,        # (P, n_loc, R) rhs block on eff dofs
    x0: jnp.ndarray,          # (P, n_loc, R) initial guess block
    inv_diag: jnp.ndarray,    # preconditioner inverse (shared by columns)
    tol,                      # scalar or (R,) per-column tolerance
    max_iter,                 # static int or traced scalar budget
    glob_n_dof_eff: int,
    max_stag_steps: int = 3,
    max_iter_nominal: Optional[int] = None,
    carry_in: Optional[dict] = None,
    return_carry: bool = False,
    plateau_window: int = 0,
    x0_zero: bool = False,
    progress_window: int = 0,
    progress_ratio: float = 0.7,
    progress_min_gain: float = 30.0,
    variant: str = "classic",
    inv_diag_fb: Optional[jnp.ndarray] = None,
):
    """Blocked multi-RHS ``pcg``: solves K.x_j = fext_j for every column
    j of the RHS block in ONE lockstep while-loop with a per-RHS
    convergence mask in the predicate.  Returns a :class:`PCGResult`
    whose ``x`` is (P, n_loc, R) and whose flag/relres/iters are (R,)
    per-column vectors, or ``(result, carry)`` with ``return_carry``
    (the resumable-dispatch contract of :func:`pcg`, per column).

    ``inv_diag_fb`` (optional) is the scalar-Jacobi FALLBACK
    preconditioner inverse for per-column recovery: the carry's
    ``prec_sel`` leaf selects, per column, whether the primary or the
    fallback inverse preconditions that column's residual — the
    recovery ladder flips one broken column to the safe inverse while
    every other column's arithmetic stays bit-identical (both applies
    are collective-free elementwise/small-matmul work, so the body psum
    count is untouched).  Without it the selection is compiled out.

    See the module-level "Batched multi-RHS PCG" note for the exact
    per-column semantics and the collective-count contract."""
    if variant not in VALID_PCG_VARIANTS:
        raise ValueError(f"pcg variant must be one of "
                         f"{VALID_PCG_VARIANTS}, got {variant!r}")
    fused = variant == "fused"
    pipelined = variant == "pipelined"
    lagged = variant in LAGGED_VARIANTS
    drift_limit = drift_limit_for(variant)
    warm = carry_in is not None
    eff = data["eff"]
    w = data["weight"] * eff
    dt = fext.dtype
    dd = ops.dot_dtype
    R = fext.shape[-1]
    eps = jnp.asarray(np.finfo(np.dtype(dt)).eps, dd)

    nominal = max_iter_nominal if max_iter_nominal is not None else max_iter
    maxmsteps = min(glob_n_dof_eff // 50, 5, glob_n_dof_eff - nominal)

    n2b = jnp.sqrt(ops.wdot_many(w, fext, fext))       # (R,)
    tolb = jnp.asarray(tol, dd) * n2b                  # (R,)

    def amul(v):
        # named pcg/matvec: blocked trace events bucket like the scalar
        # loop's (obs/profview.py)
        with jax.named_scope("pcg/matvec"):
            return eff[..., None] * ops.matvec(data, v)

    if warm:
        x0 = carry_in["x"]
        r0 = carry_in["r"]
        normr0 = carry_in["normr_act"].astype(dd)
        frozen0 = carry_in["flag"] != 1
    else:
        frozen0 = jnp.zeros((R,), bool)
        if x0_zero:
            r0 = fext
            normr0 = n2b
        else:
            r0 = fext - amul(x0)
            normr0 = jnp.sqrt(ops.wdot_many(w, r0, r0))

    zero_rhs = n2b == 0
    if lagged and warm:
        # warm recurrence-variant normr0 is the predecessor iterate's
        # norm (pipelined lag) — never flag-0 the unevaluated resumed
        # column off it
        initial_ok = jnp.zeros((R,), bool)
    else:
        initial_ok = normr0 <= tolb

    zi = jnp.zeros((R,), jnp.int32)
    flag0 = carry_in["flag"] if warm else jnp.ones((R,), jnp.int32)
    carry0 = dict(
        x=x0,
        r=r0,
        p=carry_in["p"] if warm else jnp.zeros_like(x0),
        rho=carry_in["rho"] if warm else jnp.ones((R,), dd),
        i=zi,
        flag=jnp.where(zero_rhs | initial_ok,
                       0, flag0).astype(jnp.int32),
        stag=carry_in["stag"] if warm else zi,
        moresteps=carry_in["moresteps"] if warm else zi,
        iter_out=zi,
        normr_act=normr0.astype(dd),
        normrmin=carry_in["normrmin"] if warm else normr0.astype(dd),
        xmin=carry_in["xmin"] if warm else x0,
        imin=carry_in["imin"] if warm else zi,
        since_best=carry_in["since_best"] if warm else zi,
        best_at_reset=(carry_in["best_at_reset"] if warm
                       else normr0.astype(dd)),
        win_start=(carry_in["win_start"] if warm
                   else normr0.astype(dd)),
        win_count=carry_in["win_count"] if warm else zi,
        mode=zi,
        # per-column preconditioner selector (0 = primary, 1 = fallback):
        # recovery state that must resume with the rest of the carry
        prec_sel=(carry_in["prec_sel"] if warm else zi),
    )
    if lagged:
        carry0["q"] = carry_in["q"] if warm else jnp.zeros_like(x0)
        carry0["alpha"] = (carry_in["alpha"] if warm
                           else jnp.full((R,), np.inf, dd))
        carry0["fresh"] = (carry_in["fresh"] if warm
                           else jnp.ones((R,), jnp.int32))
        carry0["drift"] = carry_in["drift"] if warm else zi
        carry0["chk_normr"] = jnp.zeros((R,), dd)
    if pipelined:
        for k in ("u", "w", "s", "z"):
            carry0[k] = carry_in[k] if warm else jnp.zeros_like(x0)
        carry0["init"] = (carry_in["init"] if warm
                          else jnp.ones((R,), jnp.int32))
        carry0["sc"] = carry_in["sc"] if warm else zi
        # internal forced-check marker (see the scalar carry0)
        carry0["chk_forced"] = zi

    def _prec_apply(c, src=None):
        """Per-column preconditioner apply: the primary inverse, with
        ``prec_sel`` columns flipped to the fallback inverse when one is
        wired (collective-free — the psum budget is untouched).
        ``src`` overrides the preconditioned vector (default the carry
        residual; the pipelined body passes its per-column r/w
        select)."""
        src = c["r"] if src is None else src
        with jax.named_scope("pcg/precond"):
            z = ops.apply_prec(inv_diag, src, data=data)
            if inv_diag_fb is not None:
                z = _colsel(c["prec_sel"] > 0,
                            ops.apply_prec(inv_diag_fb, src), z)
        return z

    def cond(c):
        return jnp.any((c["flag"] == 1) & (c["i"] < max_iter))

    def _resolve_many(c, x, r, p, rho, stag, normr_act, candidate, i,
                      extra=None, count_windows=None):
        """Elementwise (per-column) twin of ``pcg``'s ``_resolve``: the
        shared iteration epilogue, with every scalar decision an (R,)
        vector.  ``extra`` overrides output entries AFTER the
        bookkeeping (the fused body commits fresh vectors while the
        epilogue resolves the lagged iterate).  ``count_windows`` (an
        (R,) bool, default always-on) gates the per-column plateau/
        progress-window clocks exactly like the scalar ``_resolve``'s:
        a cadence-forced pipelined column check must not tick them."""
        converged = candidate & (normr_act <= tolb)
        stag = jnp.where(candidate & ~converged
                         & (stag >= max_stag_steps) & (c["moresteps"] == 0),
                         0, stag).astype(jnp.int32)
        moresteps = jnp.where(candidate & ~converged,
                              c["moresteps"] + 1,
                              c["moresteps"]).astype(jnp.int32)
        toosmall = candidate & ~converged & (moresteps >= maxmsteps)

        better = normr_act < c["normrmin"]
        normrmin = jnp.where(better, normr_act, c["normrmin"])
        xmin = _colsel(better, x, c["xmin"])
        imin = jnp.where(better, i, c["imin"])
        improved = normr_act < c["best_at_reset"] * (1 - 1e-3)
        since_best = jnp.where(improved, 0,
                               c["since_best"] + 1).astype(jnp.int32)
        best_at_reset = jnp.where(improved, normr_act, c["best_at_reset"])

        stagnated = (stag >= max_stag_steps) & ~converged & ~toosmall
        plateaued = ((since_best > plateau_window) & ~converged
                     & ~toosmall if plateau_window
                     else jnp.zeros((R,), bool))

        if progress_window:
            win_count = c["win_count"] + 1
            at_window = win_count >= progress_window
            weak_window = normrmin > jnp.asarray(
                progress_ratio, normrmin.dtype) * c["win_start"]
            deep_enough = normrmin * jnp.asarray(
                progress_min_gain, normrmin.dtype) < n2b
            no_progress = (at_window & weak_window & deep_enough
                           & ~converged & ~toosmall)
            win_start = jnp.where(at_window, normrmin, c["win_start"])
            win_count = jnp.where(at_window, 0, win_count).astype(jnp.int32)
        else:
            no_progress = jnp.zeros((R,), bool)
            win_start, win_count = c["win_start"], c["win_count"]

        if count_windows is not None:
            # frozen per-column window clocks (forced checks) — see the
            # scalar _resolve
            tick = count_windows
            since_best = jnp.where(tick, since_best,
                                   c["since_best"]).astype(jnp.int32)
            best_at_reset = jnp.where(tick, best_at_reset,
                                      c["best_at_reset"])
            win_start = jnp.where(tick, win_start, c["win_start"])
            win_count = jnp.where(tick, win_count,
                                  c["win_count"]).astype(jnp.int32)
            plateaued = plateaued & tick
            no_progress = no_progress & tick

        flag = jnp.where(converged, 0,
                jnp.where(toosmall | stagnated | plateaued | no_progress, 3,
                          1)).astype(jnp.int32)
        stop = flag != 1
        out = dict(
            x=x, r=r, p=p, rho=rho,
            i=jnp.where(stop, i, i + 1).astype(jnp.int32),
            flag=flag, stag=stag, moresteps=moresteps,
            iter_out=i,
            normr_act=normr_act, normrmin=normrmin, xmin=xmin, imin=imin,
            since_best=since_best, best_at_reset=best_at_reset,
            win_start=win_start, win_count=win_count,
            mode=jnp.zeros_like(i),
        )
        if extra:
            out.update(extra)
        # recovery/drift leaves the epilogue does not own pass through
        # unchanged (prec_sel, and the fused drift-guard state) — the
        # while carry must stay type-stable across every branch
        for k in c:
            out.setdefault(k, c[k])
        return out

    def _merge_cases(c, cases):
        """Per-column merge of branch outcomes: ``cases`` is a list of
        (mask (R,), state dict) with DISJOINT masks; columns matching no
        mask keep their old state ``c`` (frozen/inactive columns)."""
        out = {}
        for k in c:
            v = c[k]
            for m, d in cases:
                nv = d[k]
                mv = m[None, None, :] if nv.ndim == 3 else m
                v = jnp.where(mv, nv, v)
            out[k] = v
        return out

    def body(c):
        """Classic blocked body: the per-column merge of ``pcg``'s
        mode-0 iterate / mode-1 deferred-check / breakdown branches.
        Psums: rho+inf (1) + interface assembly inside the one blocked
        matvec (1) + p.q (1) + fused 3-norm (1) + the check's
        true-residual norm (1) = 5, independent of nrhs."""
        i = c["i"]
        active = (c["flag"] == 1) & (i < max_iter)
        is_check = (c["mode"] == 1) & active
        it_m = active & ~is_check

        # -- pre (mode 0): z, rho, beta, direction recurrence ----------
        z = _prec_apply(c)
        inf_col = jnp.isinf(z).any(axis=(0, 1)).astype(dd)
        with jax.named_scope("pcg/reduce"):
            red = ops.wdots_many(w, [(z, c["r"])], extra=[inf_col])
        rho_new, flag2 = red[0], red[1] > 0
        bad_rho = (rho_new == 0) | jnp.isinf(rho_new)
        beta = (rho_new / c["rho"]).astype(dt)
        with jax.named_scope("pcg/axpy"):
            if warm:
                bad_beta = (beta == 0) | jnp.isinf(beta)
                p_new = z + beta[None, None, :] * c["p"]
            else:
                bad_beta = (i > 0) & ((beta == 0) | jnp.isinf(beta))
                p_new = jnp.where((i == 0)[None, None, :], z,
                                  z + beta[None, None, :] * c["p"])

        # the ONE blocked stencil application: check columns ride their
        # committed iterate through the same matvec (q_j = A.x_j there)
        operand = _colsel(is_check, c["x"], p_new)
        q = amul(operand)

        # -- iterate path ----------------------------------------------
        with jax.named_scope("pcg/reduce"):
            pq = ops.wdot_many(w, p_new, q)
        bad_pq = (pq <= 0) | jnp.isinf(pq)
        alpha = (rho_new / pq).astype(dt)
        bad_alpha = jnp.isinf(alpha)
        breakdown = bad_rho | bad_beta | bad_pq | bad_alpha
        new_flag = jnp.where(flag2, 2,
                             jnp.where(breakdown, 4, 1)).astype(jnp.int32)

        with jax.named_scope("pcg/axpy"):
            r_upd = c["r"] - alpha[None, None, :] * q
        with jax.named_scope("pcg/reduce"):
            sq = ops.wdots_many(w, [(p_new, p_new), (c["x"], c["x"]),
                                    (r_upd, r_upd)])
        normp, normx = jnp.sqrt(sq[0]), jnp.sqrt(sq[1])
        normr = jnp.sqrt(sq[2])
        stag_upd = jnp.where(
            normp * jnp.abs(alpha).astype(dd) < eps * normx,
            c["stag"] + 1, 0).astype(jnp.int32)
        with jax.named_scope("pcg/axpy"):
            x_upd = c["x"] + alpha[None, None, :] * p_new
        cand_new = ((normr <= tolb) | (stag_upd >= max_stag_steps)
                    | (c["moresteps"] > 0))

        # -- check path: true residual of the committed iterate --------
        r_true = fext - q
        with jax.named_scope("pcg/reduce"):
            normr_chk = jnp.sqrt(ops.wdot_many(w, r_true, r_true))

        chk = _resolve_many(c, x=c["x"], r=r_true, p=c["p"], rho=c["rho"],
                            stag=c["stag"], normr_act=normr_chk,
                            candidate=jnp.ones((R,), bool), i=i)
        brk = dict(c, flag=new_flag, iter_out=i, rho=rho_new)
        pend = dict(c, x=x_upd, r=r_upd, p=p_new, rho=rho_new,
                    stag=stag_upd, iter_out=i,
                    mode=jnp.ones((R,), jnp.int32))
        res = _resolve_many(c, x=x_upd, r=r_upd, p=p_new, rho=rho_new,
                            stag=stag_upd,
                            normr_act=normr.astype(dd),
                            candidate=jnp.zeros((R,), bool), i=i)

        m_brk = it_m & (flag2 | breakdown)
        m_pend = it_m & ~(flag2 | breakdown) & cand_new
        m_res = it_m & ~(flag2 | breakdown) & ~cand_new
        return _merge_cases(c, [(is_check, chk), (m_brk, brk),
                                (m_pend, pend), (m_res, res)])

    def body_fused(c):
        """Fused (Chronopoulos–Gear) blocked body: ONE fused psum
        carries every per-RHS reduction (rho, mu, ||r||, ||p||, ||x||,
        inf flag — a (6, R) payload) + the interface psum + the check's
        true-residual norm = 3 body psums, independent of nrhs.  Same
        pipelined-lag semantics per column as ``pcg``'s fused body."""
        i = c["i"]
        active = (c["flag"] == 1) & (i < max_iter)
        is_check = (c["mode"] == 1) & active
        it_m = active & ~is_check

        z = _prec_apply(c)
        operand = _colsel(is_check, c["x"], z)
        kop = amul(operand)          # A.z (iterate cols) / A.x (check cols)

        inf_col = jnp.isinf(z).any(axis=(0, 1)).astype(dd)
        with jax.named_scope("pcg/reduce"):
            red = ops.wdots_many(w, [(c["r"], z), (z, kop),
                                     (c["r"], c["r"]), (c["p"], c["p"]),
                                     (c["x"], c["x"])], extra=[inf_col])
        rho, mu = red[0], red[1]
        normr = jnp.sqrt(red[2])
        normp, normx = jnp.sqrt(red[3]), jnp.sqrt(red[4])
        flag2 = red[5] > 0

        already = c["fresh"] == 0
        small = normp * jnp.abs(c["alpha"]) < eps * normx
        stag = jnp.where(already, c["stag"],
                         jnp.where(small, c["stag"] + 1,
                                   0)).astype(jnp.int32)
        candidate = (((normr <= tolb) | (stag >= max_stag_steps)
                      | (c["moresteps"] > 0)) & ~already)

        bad_rho = (rho == 0) | jnp.isinf(rho)
        beta = rho / c["rho"]
        bad_beta = (beta == 0) | jnp.isinf(beta)
        pq = mu - beta * rho / c["alpha"]
        bad_pq = (pq <= 0) | jnp.isinf(pq)
        alpha = rho / pq
        bad_alpha = jnp.isinf(alpha)
        breakdown = bad_rho | bad_beta | bad_pq | bad_alpha
        new_flag = jnp.where(flag2, 2,
                             jnp.where(breakdown, 4, 1)).astype(jnp.int32)

        beta_dt = beta.astype(dt)[None, None, :]
        alpha_dt = alpha.astype(dt)[None, None, :]
        with jax.named_scope("pcg/axpy"):
            p2 = z + beta_dt * c["p"]
            q2 = kop + beta_dt * c["q"]
            x2 = c["x"] + alpha_dt * p2
            r2 = c["r"] - alpha_dt * q2

        res = _resolve_many(
            c, x=c["x"], r=c["r"], p=c["p"], rho=rho, stag=stag,
            normr_act=normr.astype(dd),
            candidate=jnp.zeros((R,), bool), i=i,
            extra=dict(x=x2, r=r2, p=p2, q=q2,
                       alpha=alpha.astype(dd),
                       fresh=jnp.ones((R,), jnp.int32)))
        pend = dict(c, stag=stag, iter_out=i,
                    mode=jnp.ones((R,), jnp.int32),
                    chk_normr=jnp.where(candidate, normr.astype(dd),
                                        c["chk_normr"]))
        brk = dict(c, flag=new_flag, iter_out=i, rho=rho)

        r_true = fext - kop
        with jax.named_scope("pcg/reduce"):
            normr_chk = jnp.sqrt(ops.wdot_many(w, r_true, r_true))
        # per-column residual-drift guard (same contract as the scalar
        # fused post_check): a non-converged check whose true residual
        # exceeds FUSED_DRIFT_FACTOR x the recurrence norm counts as
        # drifted; at FUSED_DRIFT_LIMIT the column exits with flag 6
        disagree = ((normr_chk > tolb)
                    & (normr_chk > jnp.asarray(FUSED_DRIFT_FACTOR, dd)
                       * c["chk_normr"]))
        drift = (c["drift"] + disagree).astype(jnp.int32)
        chk = _resolve_many(c, x=c["x"], r=r_true, p=c["p"], rho=c["rho"],
                            stag=c["stag"], normr_act=normr_chk,
                            candidate=jnp.ones((R,), bool), i=i,
                            extra=dict(q=c["q"], alpha=c["alpha"],
                                       fresh=jnp.zeros((R,), jnp.int32),
                                       i=i, drift=drift))
        drift_exit = (chk["flag"] == 1) & (drift >= drift_limit)
        chk["flag"] = jnp.where(drift_exit, DRIFT_FLAG,
                                chk["flag"]).astype(jnp.int32)

        m_brk = it_m & (flag2 | breakdown) & ~candidate
        m_pend = it_m & candidate
        m_res = it_m & ~candidate & ~(flag2 | breakdown)
        return _merge_cases(c, [(is_check, chk), (m_brk, brk),
                                (m_pend, pend), (m_res, res)])

    def body_pipelined(c):
        """Ghysels–Vanroose depth-1 pipelined blocked body: the ONE
        fused psum (a (6, R) payload) consumes ONLY previous-iteration
        carry leaves — gamma = <r,u>, delta = <w,u>, the residual/
        stagnation norms, the inf-prec flag off ``u`` — and the blocked
        precond apply + stencil matvec consume only carry leaves too,
        so the psum and the matvec are data-independent both ways
        (the analysis/ psum-overlap rule's contract; see the scalar
        ``body_pipelined``).  3 body psums (fused + iface + deferred
        check), independent of nrhs.  Per-column trip kinds: mode-1
        deferred check, per-column PRIMING (armed ``init`` bits:
        u0 = M^-1.r0, w0 = A.u0 — a ladder-restarted column re-primes
        alone), and the GV recurrence advance."""
        i = c["i"]
        active = (c["flag"] == 1) & (i < max_iter)
        is_check = (c["mode"] == 1) & active
        is_prime = (c["init"] > 0) & active & ~is_check
        it_m = active & ~is_check & ~is_prime

        # ---- the ONE fused psum: carry-state operands only ------------
        inf_col = jnp.isinf(c["u"]).any(axis=(0, 1)).astype(dd)
        with jax.named_scope("pcg/reduce"):
            red = ops.wdots_many(w, [(c["r"], c["u"]), (c["w"], c["u"]),
                                     (c["r"], c["r"]), (c["p"], c["p"]),
                                     (c["x"], c["x"])], extra=[inf_col])
        gamma, delta = red[0], red[1]
        normr = jnp.sqrt(red[2])
        normp, normx = jnp.sqrt(red[3]), jnp.sqrt(red[4])
        flag2 = red[5] > 0

        # per-column precond source: priming columns precondition their
        # residual, iterating columns their w; check columns' apply is
        # discarded by the operand select below.  All carry leaves — the
        # apply/matvec chain never waits on the psum above.
        m = _prec_apply(c, src=_colsel(c["init"] > 0, c["r"], c["w"]))
        operand = _colsel(is_check, c["x"], m)
        kop = amul(operand)

        already = c["fresh"] == 0
        small = normp * jnp.abs(c["alpha"]) < eps * normx
        stag = jnp.where(already, c["stag"],
                         jnp.where(small, c["stag"] + 1,
                                   0)).astype(jnp.int32)
        natural = ((normr <= tolb) | (stag >= max_stag_steps)
                   | (c["moresteps"] > 0))
        # per-column forced replacement cadence (see the scalar body)
        forced = c["sc"] >= PIPELINED_REPLACE_EVERY
        candidate = (natural | forced) & ~already

        bad_rho = (gamma == 0) | jnp.isinf(gamma)
        beta = gamma / c["rho"]
        bad_beta = (beta == 0) | jnp.isinf(beta)
        pq = delta - beta * gamma / c["alpha"]
        bad_pq = (pq <= 0) | jnp.isinf(pq)
        alpha = gamma / pq
        bad_alpha = jnp.isinf(alpha)
        breakdown = bad_rho | bad_beta | bad_pq | bad_alpha
        new_flag = jnp.where(flag2, 2,
                             jnp.where(breakdown, 4, 1)).astype(jnp.int32)

        beta_dt = beta.astype(dt)[None, None, :]
        alpha_dt = alpha.astype(dt)[None, None, :]
        with jax.named_scope("pcg/axpy"):
            p2 = c["u"] + beta_dt * c["p"]   # p = 0 cold => p2 = u
            s2 = c["w"] + beta_dt * c["s"]   # A.p by recurrence
            q2 = m + beta_dt * c["q"]        # M^-1.s by recurrence
            z2 = kop + beta_dt * c["z"]      # A.q by recurrence
            x2 = c["x"] + alpha_dt * p2
            r2 = c["r"] - alpha_dt * s2
            u2 = c["u"] - alpha_dt * q2      # M^-1.r by recurrence
            w2 = c["w"] - alpha_dt * z2      # A.u by recurrence

        res = _resolve_many(
            c, x=c["x"], r=c["r"], p=c["p"], rho=gamma, stag=stag,
            normr_act=normr.astype(dd),
            candidate=jnp.zeros((R,), bool), i=i,
            extra=dict(x=x2, r=r2, p=p2, u=u2, w=w2, s=s2, q=q2, z=z2,
                       alpha=alpha.astype(dd),
                       fresh=jnp.ones((R,), jnp.int32),
                       sc=(c["sc"] + 1).astype(jnp.int32)))
        pend = dict(c, stag=stag, iter_out=i,
                    mode=jnp.ones((R,), jnp.int32),
                    chk_normr=jnp.where(candidate, normr.astype(dd),
                                        c["chk_normr"]),
                    chk_forced=(forced & ~natural).astype(jnp.int32))
        brk = dict(c, flag=new_flag, iter_out=i, rho=gamma)
        # priming commit: u0/w0 land, the bit clears, nothing advances
        prime = dict(c, u=m, w=kop,
                     init=jnp.zeros((R,), jnp.int32))

        # deferred check (kop = A.x for check columns) with per-column
        # TRUE-RESIDUAL REPLACEMENT (see the scalar post_check): the
        # column's residual is replaced with the honest one and its
        # priming bit re-armed so u/w re-sync next trip; the TIGHTER
        # pipelined drift budget still gates flag 6
        r_true = fext - kop
        with jax.named_scope("pcg/reduce"):
            normr_chk = jnp.sqrt(ops.wdot_many(w, r_true, r_true))
        disagree = ((normr_chk > tolb)
                    & (normr_chk > jnp.asarray(FUSED_DRIFT_FACTOR, dd)
                       * c["chk_normr"]))
        drift = (c["drift"] + disagree).astype(jnp.int32)
        # a cadence-forced column check replaces/re-primes only — no
        # MoreSteps/candidacy bookkeeping, no plateau/progress-window
        # ticks (count_windows; see the scalar post_check)
        chk_nat = c["chk_forced"] == 0
        chk = _resolve_many(c, x=c["x"], r=r_true, p=c["p"], rho=c["rho"],
                            stag=c["stag"], normr_act=normr_chk,
                            candidate=chk_nat, i=i,
                            extra=dict(fresh=jnp.zeros((R,), jnp.int32),
                                       i=i, drift=drift,
                                       init=jnp.ones((R,), jnp.int32),
                                       sc=jnp.zeros((R,), jnp.int32),
                                       chk_forced=jnp.zeros(
                                           (R,), jnp.int32)),
                            count_windows=chk_nat)
        drift_exit = (chk["flag"] == 1) & (drift >= drift_limit)
        chk["flag"] = jnp.where(drift_exit, DRIFT_FLAG,
                                chk["flag"]).astype(jnp.int32)

        m_brk = it_m & (flag2 | breakdown) & ~candidate
        m_pend = it_m & candidate
        m_res = it_m & ~candidate & ~(flag2 | breakdown)
        return _merge_cases(c, [(is_check, chk), (is_prime, prime),
                                (m_brk, brk), (m_pend, pend),
                                (m_res, res)])

    loop_body = (body_pipelined if pipelined
                 else body_fused if fused else body)
    c = jax.lax.while_loop(cond, loop_body, carry0)

    skip_mask = zero_rhs | initial_ok | frozen0

    def finalize():
        ok = c["flag"] == 0
        relres_ok = c["normr_act"] / n2b
        # per-column min-residual fallback (MATLAB pcg semantics); ONE
        # blocked matvec for the whole block
        r_min = fext - amul(c["xmin"])
        normr_min = jnp.sqrt(ops.wdot_many(w, r_min, r_min))
        if lagged:
            x_bad, relres_bad = c["xmin"], normr_min / n2b
            iters_bad = c["imin"]
        else:
            # NaN-poisoned columns compare False: force the min-residual
            # fallback so a poisoned column still reports a finite,
            # internally-consistent (x, relres) pair (quarantine
            # semantics — the host has no ladder on the one-shot path)
            use_min = ((normr_min < c["normr_act"])
                       | ~jnp.isfinite(c["normr_act"]))
            x_bad = _colsel(use_min, c["xmin"], c["x"])
            relres_bad = jnp.where(use_min, normr_min,
                                   c["normr_act"]) / n2b
            iters_bad = jnp.where(use_min, c["imin"], c["iter_out"])
        x = _colsel(ok, c["x"], x_bad)
        relres = jnp.where(ok, relres_ok, relres_bad)
        iters = jnp.where(ok, c["iter_out"], iters_bad)
        return x, relres, iters

    if return_carry:
        x, relres, iters = c["x"], c["normr_act"] / n2b, c["iter_out"]
    else:
        x, relres, iters = finalize()

    x = jnp.where(zero_rhs[None, None, :], jnp.zeros_like(x), x)
    relres = jnp.where(zero_rhs, 0.0, relres)
    iters = jnp.where(skip_mask, 0, iters + 1)
    flag = jnp.where(zero_rhs, 0, c["flag"]).astype(jnp.int32)
    if not return_carry:
        # One-shot terminal reporting: a NaN/Inf-poisoned column trips
        # NO MATLAB flag, but finalize() already handed it the finite
        # min-residual fallback — surface the poisoning as the terminal
        # QUARANTINE_FLAG instead of a flag that reads like an honest
        # budget/stagnation exit.  The resumable path must NOT do this:
        # the host-side per-column ladder reads flag 1 + a non-finite
        # carry norm as its nan_carry trigger.
        poisoned = ~jnp.isfinite(c["normr_act"]) & (flag != 0) & ~zero_rhs
        flag = jnp.where(poisoned, QUARANTINE_FLAG, flag).astype(jnp.int32)

    result = PCGResult(x=x, flag=flag, relres=relres.astype(jnp.float32),
                       iters=iters)
    if return_carry:
        keys = ["x", "r", "p", "rho", "stag", "moresteps",
                "normrmin", "xmin", "imin", "since_best",
                "best_at_reset", "win_start", "win_count", "normr_act",
                "prec_sel"]
        if lagged:
            keys += ["q", "alpha", "fresh", "drift"]
        if pipelined:
            keys += ["u", "w", "s", "z", "init", "sc"]
        carry = {k: c[k] for k in keys}
        carry["flag"] = flag
        # executed body-iteration count per column; columns that never
        # ran this dispatch (frozen at entry / converged at entry /
        # zero rhs) report 0
        carry["exec"] = jnp.where(skip_mask, 0,
                                  c["iter_out"] + 1).astype(jnp.int32)
        return result, carry
    return result


def pcg_mixed_many(
    ops32: Ops,
    data32: dict,
    ops64: Ops,
    data64: dict,
    fext: jnp.ndarray,        # (P, n_loc, R) f64 rhs block on eff dofs
    x0: jnp.ndarray,          # (P, n_loc, R) f64 initial guess block
    inv_diag32: jnp.ndarray,  # f32 preconditioner inverse (shared)
    tol: float,
    max_iter: int,
    glob_n_dof_eff: int,
    max_stag_steps: int = 3,
    inner_tol: float = 1e-5,
    max_outer: int = 12,
    plateau_window: int = 0,
    progress_window: int = 0,
    progress_ratio: float = 0.7,
    progress_min_gain: float = 30.0,
    variant: str = "classic",
) -> PCGResult:
    """Blocked mixed-precision PCG by iterative refinement: the blocked
    twin of :func:`pcg_mixed`.  The f32 inner Krylov cycles run
    :func:`pcg_many` on the per-column normalized residuals (a finished
    column's inner rhs is zeroed, so its inner solve early-exits and
    costs nothing but a masked lane), and the f64 refresh is one blocked
    matvec per cycle.  Per-column flags follow pcg_mixed's taxonomy."""
    eff64 = data64["eff"]
    w64 = data64["weight"] * eff64
    R = fext.shape[-1]
    dd = ops64.dot_dtype

    def amul64(v):
        return eff64[..., None] * ops64.matvec(data64, v)

    n2b = jnp.sqrt(ops64.wdot_many(w64, fext, fext))   # (R,)
    tolb = tol * n2b

    carry0 = dict(
        x=x0,
        normr=jnp.full((R,), np.inf, dd),
        outer=jnp.zeros((R,), jnp.int32),
        total=jnp.zeros((R,), jnp.int32),
        flag=jnp.where(n2b == 0, 0, -1).astype(jnp.int32),
        fatal2=jnp.zeros((R,), bool),
    )

    def cond(c):
        return jnp.any(c["flag"] == -1)

    def body(c):
        r = fext - amul64(c["x"])
        normr = jnp.sqrt(ops64.wdot_many(w64, r, r))
        live = c["flag"] == -1
        converged = normr <= tolb
        stalled = normr > 0.5 * c["normr"]
        exhausted = (c["outer"] >= max_outer) | (c["total"] >= max_iter)
        run_inner = live & ~(converged | stalled | c["fatal2"] | exhausted)

        # normalized inner rhs per column; columns NOT running this
        # cycle get a zero rhs, which pcg_many's per-column zero-rhs
        # early exit freezes at flag 0 / 0 iterations immediately
        denom = jnp.where(normr > 0, normr, jnp.ones_like(normr))
        rhat32 = jnp.where(run_inner[None, None, :],
                           r / denom[None, None, :], 0.0
                           ).astype(jnp.float32)
        # PER-COLUMN inner budget, exactly the scalar path's
        # max_iter - total per solve: a lightly-spent column must not be
        # clamped by the most-spent column's remaining budget (pcg_many
        # takes an (R,) max_iter — its budget test is elementwise)
        remaining = jnp.maximum(max_iter - c["total"], 1)
        tol_cycle = refine_tol(tolb, normr, inner_tol)
        inner, icarry = pcg_many(
            ops32, data32,
            fext=rhat32,
            x0=jnp.zeros_like(rhat32),
            inv_diag=inv_diag32,
            tol=tol_cycle,
            max_iter=remaining,
            glob_n_dof_eff=glob_n_dof_eff,
            max_stag_steps=max_stag_steps,
            max_iter_nominal=max_iter,
            plateau_window=plateau_window,
            return_carry=True,
            x0_zero=True,
            progress_window=progress_window,
            progress_ratio=progress_ratio,
            progress_min_gain=progress_min_gain,
            variant=variant,
        )
        use_min = (inner.flag != 0) & (icarry["normrmin"]
                                       < icarry["normr_act"])
        xbest = _colsel(use_min, icarry["xmin"], inner.x)
        xinc = xbest.astype(fext.dtype) * normr[None, None, :]
        xinc = jnp.where(run_inner[None, None, :], xinc,
                         jnp.zeros_like(xinc))
        exec_n = jnp.where(run_inner, jnp.maximum(icarry["exec"], 1), 0)
        inner_flag = jnp.where(run_inner, inner.flag, 1)

        flag = jnp.where(
            ~live, c["flag"],
            jnp.where(converged, 0,
             jnp.where(stalled, 3,
              jnp.where(c["fatal2"], 2,
               jnp.where(exhausted, 1, -1))))).astype(jnp.int32)
        return dict(x=c["x"] + xinc,
                    normr=jnp.where(live, normr, c["normr"]),
                    outer=c["outer"] + run_inner.astype(jnp.int32),
                    total=c["total"] + exec_n,
                    flag=flag,
                    fatal2=inner_flag == 2)

    c = jax.lax.while_loop(cond, body, carry0)
    zero_rhs = n2b == 0
    relres = jnp.where(zero_rhs, 0.0, c["normr"] / n2b)
    x = jnp.where(zero_rhs[None, None, :], jnp.zeros_like(c["x"]), c["x"])
    return PCGResult(x=x, flag=c["flag"], relres=relres.astype(jnp.float32),
                     iters=c["total"])
