from pcg_mpi_solver_tpu.solver.pcg import pcg, pcg_many, PCGResult
from pcg_mpi_solver_tpu.solver.driver import (ManySolveResult, Solver,
                                              StepResult)
from pcg_mpi_solver_tpu.solver.newmark import NewmarkSolver

__all__ = ["pcg", "pcg_many", "PCGResult", "Solver", "StepResult",
           "ManySolveResult", "NewmarkSolver"]
