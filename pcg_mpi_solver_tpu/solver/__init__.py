from pcg_mpi_solver_tpu.solver.pcg import pcg, PCGResult
from pcg_mpi_solver_tpu.solver.driver import Solver, StepResult
from pcg_mpi_solver_tpu.solver.newmark import NewmarkSolver

__all__ = ["pcg", "PCGResult", "Solver", "StepResult", "NewmarkSolver"]
