from pcg_mpi_solver_tpu.solver.pcg import pcg, PCGResult
from pcg_mpi_solver_tpu.solver.driver import Solver, StepResult

__all__ = ["pcg", "PCGResult", "Solver", "StepResult"]
