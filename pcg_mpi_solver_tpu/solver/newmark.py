"""Implicit elasto-dynamics: Newmark-beta time integration with a PCG
solve per step.

The reference's dynamics era was explicit-only (vestigial ``DiagM``/``Vd``/
``Cm`` arrays, partition_mesh.py:324-330; no implicit integrator exists
anywhere in it).  This module adds the implicit path (BASELINE.json
config 5: "elasto-dynamic (implicit Newmark), repeated PCG solves per
timestep"), TPU-first: each step is ONE jitted shard_map program — the
effective-force build, the full PCG ``lax.while_loop`` on the shifted
operator, and the kinematic updates never leave the device.

Discretization (a-form, lumped mass M, mass-proportional damping C=c_m M):

    A u_{n+1} = F(t_{n+1}) + M (a0 u_n + a2 v_n + a3 w_n)
                           + C (a1 u_n + a4 v_n + a5 w_n)
    w_{n+1}   = a0 (u_{n+1} - u_n) - a2 v_n - a3 w_n
    v_{n+1}   = v_n + dt ((1-gamma) w_n + gamma w_{n+1})

with A = K + a0 M + a1 C,  a0 = 1/(beta dt^2),  a1 = gamma/(beta dt),
a2 = 1/(beta dt), a3 = 1/(2 beta) - 1, a4 = gamma/beta - 1,
a5 = dt (gamma/(2 beta) - 1); (w = acceleration).  Default
beta=1/4, gamma=1/2 (average acceleration: unconditionally stable, no
algorithmic damping) — dt is a resolution choice, not a CFL bound, unlike
the explicit solver (solver/dynamics.py).

Because M is lumped (diagonal) and assembled, the shifted operator is the
stock matrix-free K matvec plus an elementwise axpy; its Jacobi diagonal
and 3x3 node blocks shift the same way, so both preconditioners and the
mixed-precision refinement path work unchanged.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.config import RunConfig
from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.obs.trace import (
    ConvergenceTrace, clamp_trace_len, empty_trace, trace_init,
    unpack_trace)
from pcg_mpi_solver_tpu.ops.matvec import Ops
from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS, make_mesh
from pcg_mpi_solver_tpu.resilience.faultinject import FaultPlan
from pcg_mpi_solver_tpu.solver.driver import StepResult, _data_specs
from pcg_mpi_solver_tpu.solver.pcg import pcg, pcg_mixed


@dataclasses.dataclass(frozen=True)
class MassShiftedOps:
    """A + c*M wrapper over any backend's Ops: matvec/diag/node blocks gain
    the (assembled, diagonal) mass term; everything else delegates."""

    base: Ops
    c: float

    def matvec(self, data, x):
        return self.base.matvec(data, x) + self.c * data["diag_M"] * x

    def matvec_local(self, data, x):
        # diag_M holds ASSEMBLED values on every copy of a shared dof, so
        # the shift must ride on the assembled product only (matvec above);
        # a local partial sum plus the full mass term would double-count
        # after assembly.
        raise NotImplementedError("MassShiftedOps only exposes the "
                                  "assembled matvec")

    def diag_local(self, data):
        # same double-count trap as matvec_local: partial K sums must not
        # carry the assembled mass term
        raise NotImplementedError("MassShiftedOps only exposes the "
                                  "assembled diag")

    def _node_block_local(self, data):
        raise NotImplementedError("MassShiftedOps only exposes the "
                                  "assembled node_block_diag")

    def diag(self, data):
        return self.base.diag(data) + self.c * data["diag_M"]

    def node_block_diag(self, data):
        B = self.base.node_block_diag(data)
        m3 = self.base._as_node3(self.c * data["diag_M"])
        return B + m3[..., :, None] * jnp.eye(3, dtype=B.dtype)

    def block_precond(self, data):
        from pcg_mpi_solver_tpu.ops.precond import invert_node_blocks

        return invert_node_blocks(self.node_block_diag(data),
                                  self.base._as_node3(data["eff"]))

    def apply_prec(self, m, r, data=None):
        # the mg V-cycle must run on THIS (shifted) operator — the
        # __getattr__ delegation below would bind mg_apply's ops to the
        # unshifted base, whose defect matvecs would precondition K
        # instead of A = K + c*M
        if isinstance(m, dict):
            from pcg_mpi_solver_tpu.ops.mg import mg_apply

            return mg_apply(self, data, m, r)
        return self.base.apply_prec(m, r)

    def __getattr__(self, name):
        if name in ("base", "c") or name.startswith("__"):
            raise AttributeError(name)
        return getattr(self.base, name)


class NewmarkSolver:
    """Implicit Newmark-beta on the SPMD-partitioned model.

    Shares the quasi-static driver's backend selection (general node-ELL or
    hybrid level-grid; the structured slab path has no mass data), its
    precision/preconditioner config (``config.solver.precision_mode``,
    ``config.solver.precond``), and its dispatch-chunked solve machinery
    (``config.solver.iters_per_dispatch``, auto-engaged above ~4M dofs —
    solver/chunked.py)."""

    def __init__(
        self,
        model: ModelData,
        config: Optional[RunConfig] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        n_parts: Optional[int] = None,
        dt: float = 1.0,
        beta: float = 0.25,
        gamma: float = 0.5,
        damping: float = 0.0,          # c_m: C = c_m * M
        backend: str = "auto",         # "auto" | "hybrid" | "general"
        recorder: Optional[MetricsRecorder] = None,
    ):
        self.config = config or RunConfig()
        scfg = self.config.solver
        # Telemetry: same default wiring as the quasi-static driver
        # (stderr sink iff PCG_TPU_VERBOSE=1, JSONL sink iff
        # config.telemetry_path is set).
        self.recorder = recorder if recorder is not None else (
            MetricsRecorder.default(
                jsonl_path=self.config.telemetry_path or None,
                profile=True if self.config.telemetry_profile else None))
        self._rec = self.recorder
        # Flight recorder: same crash-durable dispatch brackets as the
        # quasi-static and explicit-dynamics drivers (obs/flight.py).
        from pcg_mpi_solver_tpu.obs.flight import attach_flight

        attach_flight(self._rec, self.config.flight_path, "newmark",
                      pcg_variant=scfg.pcg_variant, precond=scfg.precond)
        from pcg_mpi_solver_tpu.ops.precond import VALID_PRECONDS
        from pcg_mpi_solver_tpu.solver.pcg import VALID_PCG_VARIANTS

        if scfg.precond not in VALID_PRECONDS:
            raise ValueError(f"SolverConfig.precond must be one of "
                             f"{VALID_PRECONDS}, got {scfg.precond!r}")
        if scfg.pcg_variant not in VALID_PCG_VARIANTS:
            raise ValueError(
                f"SolverConfig.pcg_variant must be one of "
                f"{VALID_PCG_VARIANTS}, got {scfg.pcg_variant!r}")
        self._rec.gauge("pcg_variant", scfg.pcg_variant)
        self._rec.gauge("precond", scfg.precond)
        # Preflight gate (validate/): reject a pathological model/config
        # before the partition build below is paid.
        from pcg_mpi_solver_tpu.validate import run_preflight

        run_preflight(model, self.config, recorder=self._rec,
                      context={"kind": "newmark"})
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        n_parts = n_parts or max(self.config.n_parts, n_dev)
        if n_parts % n_dev != 0:
            raise ValueError(f"n_parts={n_parts} must be a multiple of "
                             f"device count {n_dev}")
        if beta <= 0:
            raise ValueError("NewmarkSolver requires beta > 0 (beta == 0 is "
                             "the explicit path: solver/dynamics.py)")
        if dt <= 0:
            raise ValueError(f"NewmarkSolver requires dt > 0, got {dt}")
        if gamma <= 0:
            raise ValueError(f"NewmarkSolver requires gamma > 0, got {gamma}")
        if gamma < 0.5:
            import warnings

            # gamma < 1/2 gives NEGATIVE algorithmic damping: each step
            # returns flag=0 while the integration grows without bound
            warnings.warn(
                f"Newmark gamma={gamma} < 0.5 is numerically unstable "
                "(negative algorithmic damping); unconditional stability "
                "requires gamma >= 1/2 with beta >= gamma/2", stacklevel=2)
        elif 2.0 * beta < gamma:
            import warnings

            warnings.warn(
                f"Newmark beta={beta} < gamma/2={gamma/2}: only "
                "conditionally stable — the integration diverges for dt "
                "above the stability bound while each step reports flag=0",
                stacklevel=2)
        self.dt, self.beta, self.gamma = float(dt), float(beta), float(gamma)
        self.damping = float(damping)

        self.mixed = scfg.precision_mode == "mixed"
        dtype = jnp.dtype(jnp.float64) if self.mixed else jnp.dtype(scfg.dtype)
        dot_dtype = jnp.dtype(scfg.dot_dtype)
        if self.mixed or jnp.float64 in (dtype, dot_dtype):
            if not jax.config.jax_enable_x64:
                # honor requested f64 math (same rule as the quasi-static
                # driver) — f32 storage still gets f64-accumulated dots
                jax.config.update("jax_enable_x64", True)
        self.dtype = dtype

        from pcg_mpi_solver_tpu.solver.backends import select_time_backend

        self.backend, self.pm, mk_ops, mk_data = select_time_backend(
            model, n_parts,
            partition_method=self.config.partition_method,
            pallas_mode=scfg.pallas, mesh=self.mesh,
            kernels_f32=self.mixed or dtype == jnp.float32,
            backend=backend)
        self._mg_meta = None
        self._mg_setup = None
        if scfg.precond == "mg":
            if self.backend != "general":
                raise ValueError(
                    "precond='mg' on the Newmark path is supported on "
                    "the general backend only (the hybrid level-grid "
                    "stencil costs minutes of compile per "
                    "instantiation); use backend='general' or "
                    "precond='jacobi'|'block3'")
            # MG hierarchy (ops/mg.py): the level lattice preconditions
            # the K part; the mass shift rides the fine level through
            # this solver's shifted matvec/diag (MassShiftedOps.
            # apply_prec) — coarse levels on K alone keep M^-1 SPD
            from pcg_mpi_solver_tpu.ops import mg as mgmod

            t_mg0 = time.perf_counter()
            with self._rec.span("mg_setup"):
                mg_setup = mgmod.build_mg_host(
                    model, self.pm, n_levels=int(scfg.mg_levels),
                    degree=int(scfg.mg_smooth_degree),
                    max_replicated_dofs=int(
                        scfg.mg_max_replicated_dofs))
            self._mg_meta = mg_setup.meta
            self._mg_setup = (mg_setup, time.perf_counter() - t_mg0)
        data = mk_data(dtype)

        # Newmark coefficients (a-form)
        dt_, b, g = self.dt, self.beta, self.gamma
        self.a0 = 1.0 / (b * dt_ * dt_)
        self.a1 = g / (b * dt_)
        self.a2 = 1.0 / (b * dt_)
        self.a3 = 1.0 / (2.0 * b) - 1.0
        self.a4 = g / b - 1.0
        self.a5 = dt_ * (g / (2.0 * b) - 1.0)
        cshift = self.a0 + self.a1 * self.damping

        base_ops = mk_ops(dot_dtype)
        if scfg.precond == "mg":
            from pcg_mpi_solver_tpu.ops import mg as mgmod

            base_ops = dataclasses.replace(
                base_ops, mg_degree=int(scfg.mg_smooth_degree),
                mg_coarse_dofs=mgmod.coarse_dofs(self._mg_meta))
        self.ops = MassShiftedOps(base_ops, cshift)

        # Assembled lumped-mass diagonal, per-part (reference DiagM,
        # partition_mesh.py:324-330), gathered exactly — bitwise equal to
        # the model's M (zero-mass dofs stay 0: A = K there, still SPD).
        gid = self.pm.dof_gid
        data["diag_M"] = jnp.asarray(
            np.where(gid >= 0, model.diag_M[np.maximum(gid, 0)], 0.0), dtype)
        data["Vd"] = jnp.asarray(
            np.where(gid >= 0, model.Vd[np.maximum(gid, 0)], 0.0), dtype)

        if scfg.precond == "mg":
            from pcg_mpi_solver_tpu.ops import mg as mgmod

            # float leaves at the storage dtype (same rule as driver.py)
            data["mg"] = mgmod.cast_tree(self._mg_setup[0].tree, dtype)

        if self.mixed:
            data = {
                "f64": data,
                "f32": jax.tree.map(
                    lambda x: x.astype(jnp.float32)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, data),
            }
            ops32_base = mk_ops(jnp.float32)
            if scfg.precond == "mg":
                from pcg_mpi_solver_tpu.ops import mg as mgmod

                ops32_base = dataclasses.replace(
                    ops32_base, mg_degree=int(scfg.mg_smooth_degree),
                    mg_coarse_dofs=mgmod.coarse_dofs(self._mg_meta))
            self.ops32 = MassShiftedOps(ops32_base, cshift)
        self._specs = _data_specs(data)

        from pcg_mpi_solver_tpu.parallel.distributed import put_sharded, put_tree

        self.data = put_tree(data, self.mesh, self._specs)
        self._part_spec = jax.sharding.PartitionSpec(PARTS_AXIS)
        self._rep_spec = jax.sharding.PartitionSpec()
        if scfg.precond == "mg":
            self._finish_mg_setup()
        P, n_loc = self.pm.n_parts, self.pm.n_loc
        zeros = lambda: put_sharded(np.zeros((P, n_loc), dtype),
                                    self.mesh, self._part_spec)
        self.u, self.v, self.w = zeros(), zeros(), zeros()

        glob_n_eff = self.pm.glob_n_dof_eff
        a0, a2_, a3_ = self.a0, self.a2, self.a3
        a1_, a4_, a5_ = self.a1, self.a4, self.a5
        cm = self.damping

        def _effective_force(data64, u, v, w, delta_next):
            """History term + Dirichlet lifting at t_{n+1} (the quasi-static
            driver's updateBC shape, pcg_solver.py:226-238, with A in place
            of K) — the ONE copy of the Newmark rhs physics, shared by the
            one-shot and chunked paths."""
            eff = data64["eff"]
            fix = 1.0 - eff
            M = data64["diag_M"]
            hist = M * ((a0 * u + a2_ * v + a3_ * w)
                        + cm * (a1_ * u + a4_ * v + a5_ * w))
            rhs = data64["F"] * delta_next + hist
            udi = fix * data64["Ud"] * delta_next
            fext = eff * (rhs - self.ops.matvec(data64, udi))
            return udi, fext

        def _kinematics(data64, x, udi, u, v, w, delta_next):
            """u/v/w updates from the solved increment; on fixed dofs u2
            carries the prescribed motion, so w2 is its finite-difference-
            consistent acceleration.  Shared by both paths."""
            eff = data64["eff"]
            fix = 1.0 - eff
            u2 = x + udi
            w2 = a0 * (u2 - u) - a2_ * v - a3_ * w
            v2 = v + dt_ * ((1.0 - g) * w + g * w2)
            v2 = eff * v2 + fix * data64["Vd"] * delta_next
            return u2, v2, w2

        def _step(data, prec, u, v, w, delta_next):
            data64 = data["f64"] if self.mixed else data
            eff = data64["eff"]
            udi, fext = _effective_force(data64, u, v, w, delta_next)
            x0 = eff * u
            if self.mixed:
                res = pcg_mixed(
                    self.ops32, data["f32"], self.ops, data64, fext, x0,
                    prec,
                    tol=scfg.tol, max_iter=scfg.max_iter,
                    glob_n_dof_eff=glob_n_eff,
                    max_stag_steps=scfg.max_stag_steps,
                    inner_tol=scfg.inner_tol,
                    variant=scfg.pcg_variant)
            else:
                res = pcg(
                    self.ops, data64, fext, x0, prec,
                    tol=scfg.tol, max_iter=scfg.max_iter,
                    glob_n_dof_eff=glob_n_eff,
                    max_stag_steps=scfg.max_stag_steps,
                    variant=scfg.pcg_variant)
            u2, v2, w2 = _kinematics(data64, res.x, udi, u, v, w, delta_next)
            return u2, v2, w2, res.flag, res.relres, res.iters

        P_, R_ = self._part_spec, self._rep_spec
        self._step_fn = jax.jit(jax.shard_map(
            _step, mesh=self.mesh,
            in_specs=(self._specs, self._prec_operand_spec(),
                      P_, P_, P_, R_),
            out_specs=(P_, P_, P_, R_, R_, R_), check_vma=False))

        # In-graph convergence trace (obs/trace.py), chunked path only:
        # the one-shot step program keeps its pre-telemetry shape.
        self.trace_len = (clamp_trace_len(scfg.trace_resid, scfg.max_iter)
                          if scfg.trace_resid > 0 else 0)
        self._trace_dtype = (jnp.float32 if self.mixed
                             else jnp.dtype(scfg.dot_dtype))
        self.last_trace: Optional[ConvergenceTrace] = None

        # ---- dispatch-chunked step path (large problems) ------------------
        # Same machinery as the quasi-static driver (solver/chunked.py):
        # the Newmark start step swaps Dirichlet lifting for the history
        # term; the engine's cycles are untouched.
        from pcg_mpi_solver_tpu.solver.chunked import (
            ChunkedEngine, auto_dispatch_cap)

        self._dispatch_cap = auto_dispatch_cap(
            scfg, self.pm.glob_n_dof,
            self.pm.n_loc * (self.pm.n_parts // n_dev))
        # donation-safe here too: the carry is built fresh by
        # _start_ch_fn each step and never read after run()
        self._donate = bool(getattr(scfg, "donate_carry", False))
        if self._dispatch_cap > 0:
            from pcg_mpi_solver_tpu.solver.pcg import (
                carry_part_specs, cold_carry)

            variant = scfg.pcg_variant
            trace_direct = self.trace_len > 0 and not self.mixed
            carry_specs = carry_part_specs(P_, R_, trace=trace_direct,
                                           variant=variant)
            trace_len, trace_dtype = self.trace_len, self._trace_dtype

            def _start_ch(data, u, v, w, delta_next):
                data64 = data["f64"] if self.mixed else data
                eff = data64["eff"]
                wts = data64["weight"] * eff
                udi, fext = _effective_force(data64, u, v, w, delta_next)
                x0 = eff * u
                r0 = fext - eff * self.ops.matvec(data64, x0)
                n2b = jnp.sqrt(self.ops.wdot(wts, fext, fext))
                normr0 = jnp.sqrt(self.ops.wdot(wts, r0, r0))
                carry0 = cold_carry(
                    x0, r0, normr0, self.ops.dot_dtype,
                    trace=(trace_init(trace_len, trace_dtype)
                           if trace_direct else None),
                    variant=variant)
                return udi, fext, carry0, normr0, n2b

            self._start_ch_fn = jax.jit(jax.shard_map(
                _start_ch, mesh=self.mesh,
                in_specs=(self._specs, P_, P_, P_, R_),
                out_specs=(P_, P_, carry_specs, R_, R_), check_vma=False))

            def _finish_ch(data, x, udi, u, v, w, delta_next):
                data64 = data["f64"] if self.mixed else data
                return _kinematics(data64, x, udi, u, v, w, delta_next)

            self._finish_ch_fn = jax.jit(jax.shard_map(
                _finish_ch, mesh=self.mesh,
                in_specs=(self._specs, P_, P_, P_, P_, P_, R_),
                out_specs=(P_, P_, P_), check_vma=False))

            self._engine = ChunkedEngine(
                mesh=self.mesh, data_specs=self._specs, part_spec=P_,
                rep_spec=R_, ops=self.ops, scfg=scfg,
                glob_n_dof_eff=glob_n_eff, cap=self._dispatch_cap,
                mixed=self.mixed,
                ops32=self.ops32 if self.mixed else None,
                trace_len=self.trace_len, recorder=self._rec,
                donate=self._donate,
                prec_spec=self._prec_operand_spec())

        # A = K + c*M is CONSTANT over the run (unlike the quasi-static
        # driver, whose per-step Jacobi rebuild is reference parity):
        # build + invert the preconditioner ONCE, device-resident.
        from pcg_mpi_solver_tpu.ops.precond import make_prec

        def _prec(data):
            if self.mixed:
                return make_prec(self.ops32, data["f32"], scfg.precond)
            return make_prec(self.ops, data, scfg.precond)

        self._prec = jax.jit(jax.shard_map(
            _prec, mesh=self.mesh,
            in_specs=(self._specs,), out_specs=self._prec_operand_spec(),
            check_vma=False))(self.data)

        def _init_accel(data, u, v, delta0):
            """w = M^-1 (F(t)*delta0 - K u - C v) at the CURRENT state:
            lumped M makes the solve elementwise (one K matvec)."""
            data64 = data["f64"] if self.mixed else data
            M = data64["diag_M"]
            inv_m = jnp.where(M > 0, 1.0 / jnp.where(M > 0, M, 1.0), 0.0)
            fint = base_ops.matvec(data64, u)      # K u (unshifted)
            return data64["eff"] * (
                inv_m * (data64["F"] * delta0 - fint) - cm * v)

        self._init_fn = jax.jit(jax.shard_map(
            _init_accel, mesh=self.mesh,
            in_specs=(self._specs, P_, P_, R_), out_specs=P_,
            check_vma=False))

        # ---- resilience (resilience/): per-step recovery ladder on the
        # chunked path + timestep-granular snapshots/rollback in run().
        # `fault_plan` is settable (tests inject programmatically;
        # PCG_TPU_FAULTS drives chaos runs — incl. the step domain
        # `kill@s:N`).
        self.fault_plan = FaultPlan.from_env(recorder=self._rec)
        self._amulA_fn = None           # lazy: shifted-operator amul
        self._restart_post_fn = None    # lazy: ladder restart program
        self._fallback_prec_fn = None   # lazy: scalar-Jacobi fallback
        self._esc_engine = None         # lazy: f64 escalation engine
        self._esc_prec_fn = None
        self._finite_fn = jax.jit(lambda a: jnp.isfinite(a).all())
        self._model = model             # checkpoint fingerprint content

        self.flags: List[int] = []
        self.relres: List[float] = []
        self.iters: List[int] = []

    # ------------------------------------------------------------------
    def _prec_operand_spec(self):
        """shard_map spec (pytree) of the preconditioner operand: the
        part spec for array inverses, the mg dict spec for precond='mg'
        (mirrors driver.Solver._prec_operand_spec)."""
        if self.config.solver.precond == "mg":
            return {"mg_diag": self._part_spec, "fb": self._rep_spec}
        return self._part_spec

    def _finish_mg_setup(self):
        """Post-upload MG setup (the Newmark twin of
        driver.Solver._finish_mg_setup, without the partition-cache
        shortcut — Newmark has no cache_dir wiring): estimate the fine
        Chebyshev bound ON THE SHIFTED OPERATOR, then install the
        per-level lambda vector + telemetry/warning through the shared
        ``mg.install_lam_and_report``."""
        from pcg_mpi_solver_tpu.ops import mg as mgmod

        setup, t_build = self._mg_setup
        data64 = self.data["f64"] if self.mixed else self.data
        specs64 = self._specs["f64"] if self.mixed else self._specs
        t0 = time.perf_counter()
        with self._rec.span("mg_lam"):
            lam_fine = mgmod.estimate_fine_lam(
                self.ops, data64, self.mesh, specs64, self._part_spec)
        trees = ([self.data["f64"], self.data["f32"]] if self.mixed
                 else [self.data])
        mgmod.install_lam_and_report(
            setup, lam_fine, trees=trees, mesh=self.mesh,
            rep_spec=self._rep_spec, recorder=self._rec,
            wall_s=t_build + time.perf_counter() - t0, cached=False)

    # ------------------------------------------------------------------
    # Resilience (resilience/): recovery programs + step harness
    # ------------------------------------------------------------------
    def _build_restart(self):
        """Lazily-built ladder restart programs on the SHIFTED operator:
        one amul program ``(data, v) -> eff * A.v`` shared by every
        restart, plus ``(data, fext, x, kx) -> (cold carry at x, ||r||)``
        — compiled only if a recovery ever fires (mirrors
        driver._restart_post)."""
        if self._restart_post_fn is not None:
            return
        from pcg_mpi_solver_tpu.solver.pcg import carry_part_specs, cold_carry

        mixed = self.mixed
        variant = self.config.solver.pcg_variant
        trace_direct = self.trace_len > 0 and not mixed
        P, R = self._part_spec, self._rep_spec
        carry_specs = carry_part_specs(P, R, trace=trace_direct,
                                       variant=variant)
        trace_len, trace_dtype = self.trace_len, self._trace_dtype

        def _amulA(data, v):
            d = data["f64"] if mixed else data
            return d["eff"] * self.ops.matvec(d, v)

        self._amulA_fn = jax.jit(jax.shard_map(
            _amulA, mesh=self.mesh, in_specs=(self._specs, P),
            out_specs=P, check_vma=False))

        def _restart(data, fext, x, kx):
            d = data["f64"] if mixed else data
            w = d["weight"] * d["eff"]
            r = fext - kx
            normr = jnp.sqrt(self.ops.wdot(w, r, r))
            tr = (trace_init(trace_len, trace_dtype)
                  if trace_direct else None)
            return cold_carry(x, r, normr, self.ops.dot_dtype,
                              trace=tr, variant=variant), normr

        self._restart_post_fn = jax.jit(jax.shard_map(
            _restart, mesh=self.mesh, in_specs=(self._specs, P, P, P),
            out_specs=(carry_specs, R), check_vma=False))

    def _fallback_prec(self):
        """Scalar-Jacobi fallback inverse on the shifted operator
        (ladder rung 2; the mass shift rides ops.diag, so the fallback
        is still a preconditioner of A, not of K)."""
        from pcg_mpi_solver_tpu.ops.precond import make_prec

        if self._fallback_prec_fn is None:
            mixed = self.mixed
            mg = self.config.solver.precond == "mg"

            def _fb(data):
                if mixed:
                    inv = make_prec(self.ops32, data["f32"], "jacobi")
                else:
                    inv = make_prec(self.ops, data, "jacobi")
                if mg:
                    # mg demotion: keep the compiled prec-operand shape,
                    # flip the apply to the plain scalar branch
                    from pcg_mpi_solver_tpu.ops.mg import fallback_operand

                    return fallback_operand(inv)
                return inv

            self._fallback_prec_fn = jax.jit(jax.shard_map(
                _fb, mesh=self.mesh, in_specs=(self._specs,),
                out_specs=self._prec_operand_spec(), check_vma=False))
        with self._rec.dispatch("fallback_prec"):
            prec = self._fallback_prec_fn(self.data)
            jax.block_until_ready(prec)
        return prec

    def _escalation(self):
        """f64 escalation (ladder rung 3, mixed mode): finish the step
        with direct f64 Krylov cycles on the shifted f64 ops/data — a
        second ChunkedEngine built lazily, exactly like the quasi-static
        driver's."""
        from pcg_mpi_solver_tpu.ops.precond import make_prec
        from pcg_mpi_solver_tpu.solver.chunked import ChunkedEngine

        if self._esc_engine is None:
            specs64 = self._specs["f64"]
            self._esc_engine = ChunkedEngine(
                mesh=self.mesh, data_specs=specs64,
                part_spec=self._part_spec, rep_spec=self._rep_spec,
                ops=self.ops, scfg=self.config.solver,
                glob_n_dof_eff=self.pm.glob_n_dof_eff,
                cap=self._dispatch_cap, mixed=False, trace_len=0,
                recorder=self._rec, donate=self._donate)

            def _p64(data):
                return make_prec(self.ops, data, "jacobi")

            self._esc_prec_fn = jax.jit(jax.shard_map(
                _p64, mesh=self.mesh, in_specs=(specs64,),
                out_specs=self._part_spec, check_vma=False))
        with self._rec.dispatch("esc_prec"):
            prec = self._esc_prec_fn(self.data["f64"])
            jax.block_until_ready(prec)
        return self._esc_engine, self.data["f64"], prec

    def _make_resilience(self):
        """Chunk-level resilience context for one step's budget loop
        (fault hooks + dispatch guard), or None when idle.  Timestep-
        granular snapshots live one level up (the TimeHistoryGuard in
        :meth:`run`); mid-Krylov snapshot cadence stays a quasi-static-
        path feature."""
        scfg = self.config.solver
        plan = self.fault_plan
        if scfg.max_recoveries <= 0 and plan is None:
            return None
        from pcg_mpi_solver_tpu.resilience.recovery import (
            DispatchGuard, ResilienceContext)

        return ResilienceContext(
            step=len(self.flags) + 1,
            guard=DispatchGuard(retries=scfg.dispatch_retries,
                                recorder=self._rec),
            faults=plan, recorder=self._rec,
            ladder_armed=scfg.max_recoveries > 0)

    def _make_guard(self, resume: bool):
        """Timestep-granular resilience harness for :meth:`run`
        (resilience/engine.TimeHistoryGuard): step snapshots at
        ``config.snapshot_every`` completed steps, step-domain fault
        triggers, NaN/Inf rollback bounded by ``max_recoveries``."""
        every = int(getattr(self.config, "snapshot_every", 0))
        plan = self.fault_plan
        if every <= 0 and plan is None and not resume:
            return None
        from pcg_mpi_solver_tpu.resilience.engine import (
            TimeHistoryGuard, kinematic_state_io)

        store = None
        if every > 0 or resume:
            from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

            store = SnapshotStore.for_time_solver(self)
        fetch, put = kinematic_state_io(self.mesh, self._part_spec,
                                        self.dtype, ("u", "v", "w"))
        return TimeHistoryGuard(
            store=store, snapshot_every=every, fetch_state=fetch,
            put_state=put, recorder=self._rec, faults=plan,
            max_recoveries=int(self.config.solver.max_recoveries))

    def _history_state(self, t: int, deltas) -> dict:
        """Full resumable state after completed step ``t``: kinematic
        vectors (device) + solve histories + the schedule prefix guard."""
        return {"u": self.u, "v": self.v, "w": self.w,
                "t": np.int64(t),
                "flags": np.asarray(self.flags, np.int64),
                "relres": np.asarray(self.relres, np.float64),
                "iters": np.asarray(self.iters, np.int64),
                "deltas": np.asarray(deltas, np.float64)}

    def _step_chunked(self, delta_next):
        """Chunked step through the shared recovery harness
        (resilience/engine.run_with_recovery): flag-2/4 breakdowns,
        NaN/Inf carries and device-loss dispatch failures restart from
        the min-residual iterate through the bounded ladder — restart ->
        scalar-Jacobi fallback prec -> f64 escalation (mixed)."""
        from pcg_mpi_solver_tpu.resilience.engine import (
            RecoveryHooks, run_with_recovery)

        rec = self._rec
        d = jnp.asarray(delta_next, self.dtype)
        with rec.dispatch("start"):
            udi, fext, carry, normr0, n2b = self._start_ch_fn(
                self.data, self.u, self.v, self.w, d)
            n2b_f = float(n2b)
        if n2b_f == 0.0:
            x_fin, flag, relres, total = (jnp.zeros_like(carry["x"]),
                                          0, 0.0, 0)
            if self.trace_len:
                self.last_trace = empty_trace()
        else:
            def _restart(x):
                self._build_restart()
                with rec.dispatch("restart"):
                    kx = self._amulA_fn(self.data, x)
                    c, nr = self._restart_post_fn(self.data, fext, x, kx)
                    jax.block_until_ready(nr)
                return c, nr

            def _cold_restart():
                # device loss: rebuild the step's cold start state (the
                # kinematic vectors are intact — the start program never
                # donates them); the constant prec is always live
                with rec.dispatch("start"):
                    _u2, _f2, c, nr, _n = self._start_ch_fn(
                        self.data, self.u, self.v, self.w, d)
                    jax.block_until_ready(nr)
                return c, nr, self._prec

            engine, x_fin, flag, relres, total = run_with_recovery(
                self._engine, self.data, fext, carry, normr0, n2b,
                self._prec,
                scfg=self.config.solver, mixed=self.mixed, recorder=rec,
                hooks=RecoveryHooks(restart=_restart,
                                    cold_restart=_cold_restart,
                                    fallback_prec=self._fallback_prec,
                                    escalation=self._escalation),
                resilience=self._make_resilience())
            if self.trace_len:
                tr = engine.last_trace
                self.last_trace = (unpack_trace(tr) if tr is not None
                                   else empty_trace())
        self.u, self.v, self.w = self._finish_ch_fn(
            self.data, x_fin, udi, self.u, self.v, self.w, d)
        return flag, relres, total

    def step(self, delta_next: float) -> StepResult:
        # recovery-exempt: the one-shot Newmark step is a single
        # stateless dispatch with no resumable carry to restart from —
        # resilience is the chunked path's job (_step_chunked ->
        # run_with_recovery), and the time-history level already has the
        # TimeHistoryGuard rollback/resume harness around run().
        t0 = time.perf_counter()
        if self._dispatch_cap > 0:
            flag, relres, iters = self._step_chunked(delta_next)
        else:
            u, v, w, flag, relres, iters = self._step_fn(
                self.data, self._prec, self.u, self.v, self.w,
                jnp.asarray(delta_next, self.dtype))
            self.u, self.v, self.w = u, v, w
        wall = time.perf_counter() - t0
        res = StepResult(int(flag), float(relres), int(iters), wall)
        self.flags.append(res.flag)
        self.relres.append(res.relres)
        self.iters.append(res.iters)
        step_i = len(self.flags)
        self._rec.event("step", step=step_i, flag=res.flag,
                        relres=res.relres, iters=res.iters,
                        wall_s=round(wall, 6))
        if self.trace_len and self.last_trace is not None:
            self._rec.event("resid_trace",
                            **self.last_trace.to_event_fields(step_i))
        return res

    def run(self, load_factor: Sequence[float],
            init_accel_delta: Optional[float] = None,
            resume: bool = False) -> List[StepResult]:
        """Integrate one step per load factor (load_factor[t] scales F, Ud
        and Vd at t_{t+1}, like the quasi-static schedule).  With
        ``init_accel_delta`` set, w is (re)initialized consistently from
        the CURRENT state, w = M^-1 (F*delta - K u - C v) — standard when
        F(t_0) != 0, and also correct for continuing a run.

        Resilience (resilience/engine.TimeHistoryGuard): with
        ``config.snapshot_every > 0`` the full kinematic state
        ``(u, v, w, histories)`` is checkpointed every N completed steps
        (``step_*.npz``, retention-bounded by ``PCG_TPU_SNAP_KEEP``);
        ``resume=True`` restores the newest one and continues
        MID-TIME-HISTORY with bit-identical histories.  A non-finite
        state after a step rolls back to the last snapshot (bounded by
        ``config.solver.max_recoveries``) instead of integrating
        garbage.  Returns results for the steps run in THIS call."""
        deltas = [float(d) for d in load_factor]
        guard = self._make_guard(resume)
        t = 0
        if resume and guard is not None:
            got = guard.load_resume()
            if got is not None:
                t0, st = got
                saved = np.asarray(st["deltas"])
                if not np.array_equal(saved[:t0],
                                      np.asarray(deltas)[:t0]):
                    raise ValueError(
                        "resume schedule mismatch: the snapshot was "
                        "written under a different load_factor prefix")
                self.u, self.v, self.w = st["u"], st["v"], st["w"]
                self.flags = [int(x) for x in np.asarray(st["flags"])]
                self.relres = [float(x) for x in np.asarray(st["relres"])]
                self.iters = [int(x) for x in np.asarray(st["iters"])]
                t = int(t0)
        if init_accel_delta is not None and t == 0:
            self.w = self._init_fn(self.data, self.u, self.v,
                                   jnp.asarray(init_accel_delta, self.dtype))
        t_start = t
        results: List[StepResult] = []
        while t < len(deltas):
            res = self.step(deltas[t])
            t += 1
            results.append(res)
            finite = (math.isfinite(res.relres)
                      and bool(self._finite_fn(self.u)))
            if not finite:
                if guard is None:
                    raise FloatingPointError(
                        f"non-finite state after Newmark step {t} and no "
                        "snapshot to roll back to (set snapshot_every)")
                t0, st = guard.rollback(t)
                self.u, self.v, self.w = st["u"], st["v"], st["w"]
                self.flags = self.flags[:t0]
                self.relres = self.relres[:t0]
                self.iters = self.iters[:t0]
                del results[max(t0 - t_start, 0):]
                t = t0
                continue
            if guard is not None:
                st = guard.boundary(
                    t, lambda: self._history_state(t, deltas))
                if st is not None:
                    self.u, self.v, self.w = st["u"], st["v"], st["w"]
        # End-of-run counter/gauge snapshot, like the quasi-static
        # driver's solve() and the explicit dynamics run().
        self._rec.emit_run_summary()
        return results

    def displacement_global(self) -> np.ndarray:
        from pcg_mpi_solver_tpu.parallel.distributed import gather_owned_global

        return gather_owned_global(self.pm, self.u, self.mesh,
                                   np.dtype(self.dtype))

    def state_global(self):
        """(u, v, w) global vectors (for tests/restarts)."""
        from pcg_mpi_solver_tpu.parallel.distributed import gather_owned_global

        return tuple(gather_owned_global(self.pm, arr, self.mesh,
                                         np.dtype(self.dtype))
                     for arr in (self.u, self.v, self.w))
