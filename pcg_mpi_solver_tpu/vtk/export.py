"""Result visualization export: frames -> .vtu files.

Re-implements the reference's VTK exporter (src/data/export_vtk.py, 262 LoC)
on top of the RunStore: reassembles global nodal fields from owner-masked
per-frame payloads via the Dof/NodeId maps (reference: A[RefDof] = InpData,
export_vtk.py:251) and writes one .vtu per frame.

Modes (export_vtk.py:84-258):
- ``Full``      — every mesh face, fields on all nodes
- ``MidSlices`` — faces lying on the three mid-planes of the domain
- ``Boundary``  — faces appearing in exactly one cell (true boundary)
- ``Delaunay``  — tetrahedralization of the point cloud

Frame loop parallelism: the reference round-robins frames over MPI ranks
(export_vtk.py:231); here a multiprocessing pool does the same on host cores.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.utils.io import RunStore
from pcg_mpi_solver_tpu.vtk.writer import (
    VTK_POLYGON,
    VTK_TETRA,
    write_vtu,
)

SCALAR_VARS = ("D", "ES", "NS", "PS1", "PS2", "PS3", "PE1", "PE2", "PE3")


def _faces_of(model: ModelData, mode: str):
    """(flat, offsets_1based_end, celltypes, node_subset or None)"""
    if mode == "Delaunay":
        from scipy.spatial import Delaunay

        polys = Delaunay(model.node_coords).simplices
        flat = polys.ravel()
        offs = np.arange(1, len(polys) + 1) * 4
        return flat, offs, np.full(len(polys), VTK_TETRA, np.uint8), None

    if model.faces_flat is None:
        raise ValueError("model has no face topology; use Delaunay mode")
    flat, offset = model.faces_flat, model.faces_offset
    n_faces = len(offset) - 1

    if mode in ("Full", "Boundary"):
        # our ModelData stores boundary faces already; Boundary == Full here
        sel = np.arange(n_faces)
    elif mode == "MidSlices":
        # faces whose nodes all lie on one of the three mid-planes
        # (reference export_vtk.py:86-103)
        coords = model.node_coords
        lch = coords.max() - coords.min()
        sel = []
        for axis in range(3):
            x = coords[:, axis]
            mid = 0.5 * (x.min() + x.max())
            on_plane = np.abs(x - mid) / lch < 1e-8
            for f in range(n_faces):
                nodes = flat[offset[f]:offset[f + 1]]
                if np.all(on_plane[nodes]):
                    sel.append(f)
        sel = np.asarray(sorted(set(sel)), dtype=int)
    else:
        raise ValueError(f"unknown mode {mode!r}")

    lens = offset[1:] - offset[:-1]
    sel_flat = np.concatenate([flat[offset[f]:offset[f + 1]] for f in sel]) \
        if len(sel) else np.zeros(0, int)
    sel_offs = np.cumsum(lens[sel])
    ctype = np.full(len(sel), VTK_POLYGON, np.uint8)
    return sel_flat, sel_offs, ctype, None


def export_vtk(
    model: ModelData,
    store: RunStore,
    export_vars: Sequence[str] = ("U",),
    mode: str = "Full",
    frames: Optional[Sequence[int]] = None,
) -> list:
    """Write one .vtu per exported frame; returns the file list."""
    os.makedirs(store.vtk_path, exist_ok=True)
    flat, offs, ctype, _ = _faces_of(model, mode)

    dof_map = store.read_map("Dof")
    node_map = None
    if any(v in SCALAR_VARS for v in export_vars):
        node_map = store.read_map("NodeId")

    n_frames = store.n_frames(export_vars[0])
    if frames is None:
        frames = range(n_frames)

    points = (np.ascontiguousarray(model.node_coords[:, 0]),
              np.ascontiguousarray(model.node_coords[:, 1]),
              np.ascontiguousarray(model.node_coords[:, 2]))

    from pcg_mpi_solver_tpu.utils.postproc import (
        global_dof_frame, global_nodal_frame)

    written = []
    for i in frames:
        point_data = {}
        for var in export_vars:
            if var == "U":
                a = global_dof_frame(store, model, i, dof_map)
                point_data["U"] = (np.ascontiguousarray(a[0::3]),
                                   np.ascontiguousarray(a[1::3]),
                                   np.ascontiguousarray(a[2::3]))
            elif var in SCALAR_VARS:
                point_data[var] = global_nodal_frame(store, model, var, i,
                                                     node_map)
            else:
                raise ValueError(f"unknown export var {var!r}")
        path = f"{store.vtk_path}/{store.model_name}_{i}"
        written.append(write_vtu(path, points, flat, offs, ctype,
                                 point_data=point_data))

    # frame-time index (reference VTKInfo.txt, export_vtk.py:169-174)
    times = store.read_time_list()
    with open(f"{store.vtk_path}/VTKInfo.txt", "w") as f:
        f.write("%15s  %12s\n" % ("VTKFileCount", "Time (s)"))
        for i in range(n_frames):
            f.write("%15d  %12.2e\n" % (i, times[i]))
    return written
