"""Result visualization export: frames -> .vtu files.

Re-implements the reference's VTK exporter (src/data/export_vtk.py, 262 LoC)
on top of the RunStore: reassembles global nodal fields from owner-masked
per-frame payloads via the Dof/NodeId maps (reference: A[RefDof] = InpData,
export_vtk.py:251) and writes one .vtu per frame.

Modes (export_vtk.py:84-258):
- ``Full``      — every stored mesh face, fields on all nodes
- ``MidSlices`` — faces lying on the three mid-planes of the domain
- ``Boundary``  — faces with incidence exactly 1 over the stored face list
  (reference bincounts PolysFlat and keeps count==1 faces,
  export_vtk.py:105-113).  Models that store every element face (octree
  generator) get the true boundary; models that pre-store only boundary
  faces (structured cube) see every face count 1, which is already the
  boundary.
- ``Delaunay``  — tetrahedralization of the point cloud

All face selections are vectorized (length-grouped gathers — no per-face
Python loop), and the frame loop can fan out over a process pool
(``n_workers``), the host-side analogue of the reference round-robining
frames over MPI ranks (export_vtk.py:231).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.utils.io import RunStore
from pcg_mpi_solver_tpu.vtk.writer import (
    VTK_POLYGON,
    VTK_TETRA,
    write_vtu,
)

SCALAR_VARS = ("D", "ES", "NS", "PS1", "PS2", "PS3", "PE1", "PE2", "PE3")


def _face_table(flat, offset):
    """Ragged faces -> list of (face_ids, (n, L) node array) per length."""
    lens = offset[1:] - offset[:-1]
    out = []
    for L in np.unique(lens):
        idx = np.where(lens == L)[0]
        cols = offset[idx][:, None] + np.arange(L)[None, :]
        out.append((idx, flat[cols]))
    return out


def _select_faces(model: ModelData, mode: str) -> np.ndarray:
    """Face ids (into model.faces_offset) selected by the export mode."""
    flat, offset = model.faces_flat, model.faces_offset
    n_faces = len(offset) - 1
    if mode == "Full":
        return np.arange(n_faces)

    if mode == "Boundary":
        # Face-incidence counting: interior faces are stored by both of
        # their cells, boundary faces once (export_vtk.py:105-113).
        keep = []
        for idx, arr in _face_table(flat, offset):
            key = np.sort(arr, axis=1)
            _, inv, counts = np.unique(key, axis=0, return_inverse=True,
                                       return_counts=True)
            keep.append(idx[counts[inv] == 1])
        return np.sort(np.concatenate(keep)) if keep else np.zeros(0, int)

    if mode == "MidSlices":
        # Faces whose nodes all lie on one of the three mid-planes
        # (reference export_vtk.py:86-103), fully vectorized.
        coords = model.node_coords
        lch = float(coords.max() - coords.min()) or 1.0
        table = _face_table(flat, offset)
        sel = []
        for axis in range(3):
            x = coords[:, axis]
            mid = 0.5 * (x.min() + x.max())
            on_plane = np.abs(x - mid) / lch < 1e-8
            for idx, arr in table:
                sel.append(idx[np.all(on_plane[arr], axis=1)])
        return np.unique(np.concatenate(sel)) if sel else np.zeros(0, int)

    raise ValueError(f"unknown mode {mode!r}")


def _faces_of(model: ModelData, mode: str):
    """(flat, offsets_1based_end, celltypes)"""
    if mode == "Delaunay":
        from scipy.spatial import Delaunay

        polys = Delaunay(model.node_coords).simplices
        flat = polys.ravel()
        offs = np.arange(1, len(polys) + 1) * 4
        return flat, offs, np.full(len(polys), VTK_TETRA, np.uint8)

    if model.faces_flat is None:
        raise ValueError("model has no face topology; use Delaunay mode")
    flat, offset = model.faces_flat, model.faces_offset
    sel = _select_faces(model, mode)

    lens = offset[1:] - offset[:-1]
    starts = offset[sel]
    sel_lens = lens[sel]
    if len(sel):
        # vectorized ragged gather
        reps = np.repeat(starts, sel_lens)
        within = np.arange(int(sel_lens.sum())) - np.repeat(
            np.cumsum(sel_lens) - sel_lens, sel_lens)
        sel_flat = flat[reps + within]
        sel_offs = np.cumsum(sel_lens)
    else:
        sel_flat, sel_offs = np.zeros(0, int), np.zeros(0, int)
    ctype = np.full(len(sel), VTK_POLYGON, np.uint8)
    return sel_flat, sel_offs, ctype


# Per-worker shared context: the model/points/face arrays are shipped ONCE
# per worker via the pool initializer (several hundred MB at bench scale —
# re-pickling them per frame would swamp the pool with IPC).
_FRAME_CTX = None


def _init_frame_ctx(ctx):
    global _FRAME_CTX
    _FRAME_CTX = ctx


def _write_frame_idx(i):
    return _write_frame((i,) + _FRAME_CTX)


def _write_frame(args):
    """One frame -> one .vtu (top-level function: picklable for the pool)."""
    (i, store, model, export_vars, dof_map, node_map,
     points, flat, offs, ctype) = args
    from pcg_mpi_solver_tpu.utils.postproc import (
        global_dof_frame, global_nodal_frame)

    point_data = {}
    for var in export_vars:
        if var == "U":
            a = global_dof_frame(store, model, i, dof_map)
            if model.n_dof == model.n_node:
                # scalar problem class (Poisson): U is one value per node
                point_data["U"] = a
            else:
                point_data["U"] = (np.ascontiguousarray(a[0::3]),
                                   np.ascontiguousarray(a[1::3]),
                                   np.ascontiguousarray(a[2::3]))
        elif var in SCALAR_VARS:
            point_data[var] = global_nodal_frame(store, model, var, i,
                                                 node_map)
        else:
            raise ValueError(f"unknown export var {var!r}")
    path = f"{store.vtk_path}/{store.model_name}_{i}"
    return write_vtu(path, points, flat, offs, ctype, point_data=point_data)


def export_vtk(
    model: ModelData,
    store: RunStore,
    export_vars: Sequence[str] = ("U",),
    mode: str = "Full",
    frames: Optional[Sequence[int]] = None,
    n_workers: int = 0,
) -> list:
    """Write one .vtu per exported frame; returns the file list.

    ``n_workers > 1`` fans frames out over a spawn-based process pool
    (frames are independent; the reference uses ``i % N_Workers == Rank``
    round-robin over MPI ranks, export_vtk.py:231)."""
    os.makedirs(store.vtk_path, exist_ok=True)
    flat, offs, ctype = _faces_of(model, mode)

    dof_map = store.read_map("Dof")
    node_map = None
    if any(v in SCALAR_VARS for v in export_vars):
        node_map = store.read_map("NodeId")

    n_frames = store.n_frames(export_vars[0])
    if frames is None:
        frames = range(n_frames)

    points = (np.ascontiguousarray(model.node_coords[:, 0]),
              np.ascontiguousarray(model.node_coords[:, 1]),
              np.ascontiguousarray(model.node_coords[:, 2]))

    ctx = (store, model, tuple(export_vars), dof_map, node_map,
           points, flat, offs, ctype)
    frames = list(frames)
    if n_workers > 1 and len(frames) > 1:
        import multiprocessing as mp

        # spawn, not fork: the parent typically holds a multithreaded JAX
        # runtime (fork would risk deadlock).  The worker import chain is
        # numpy-only (no jax), so spawn startup is cheap.  The big shared
        # arrays go through the initializer once per worker; per-frame IPC
        # is just the frame index.
        with mp.get_context("spawn").Pool(
                min(n_workers, len(frames)),
                initializer=_init_frame_ctx, initargs=(ctx,)) as pool:
            written = pool.map(_write_frame_idx, frames)
    else:
        written = [_write_frame((i,) + ctx) for i in frames]

    # frame-time index (reference VTKInfo.txt, export_vtk.py:169-174)
    times = store.read_time_list()
    with open(f"{store.vtk_path}/VTKInfo.txt", "w") as f:
        f.write("%15s  %12s\n" % ("VTKFileCount", "Time (s)"))
        for i in range(n_frames):
            f.write("%15d  %12.2e\n" % (i, times[i]))
    return written
