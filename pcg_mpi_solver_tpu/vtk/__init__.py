from pcg_mpi_solver_tpu.vtk.writer import write_vtu, VTK_HEXAHEDRON, VTK_POLYGON, VTK_QUAD, VTK_TETRA

__all__ = ["write_vtu", "VTK_HEXAHEDRON", "VTK_POLYGON", "VTK_QUAD", "VTK_TETRA"]
