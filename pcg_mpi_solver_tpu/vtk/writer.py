"""Minimal VTK XML UnstructuredGrid (.vtu) writer, raw-appended binary.

Fills the role of the reference's vendored pyevtk (src/data/evtk/, ~1480 LoC:
``unstructuredGridToVTK`` hl.py:587-653, ``VtkFile`` vtk.py:181-491) with a
fresh ~130-line implementation of exactly the subset the exporter needs:
points + connectivity/offsets/types + scalar/vector point and cell data,
binary appended encoding readable by ParaView.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Union

import numpy as np

# VTK cell type ids (subset; full table in VTK spec)
VTK_VERTEX = 1
VTK_LINE = 3
VTK_TRIANGLE = 5
VTK_POLYGON = 7
VTK_QUAD = 9
VTK_TETRA = 10
VTK_HEXAHEDRON = 12

_VTK_TYPE_NAMES = {
    np.dtype(np.float32): "Float32",
    np.dtype(np.float64): "Float64",
    np.dtype(np.int8): "Int8",
    np.dtype(np.uint8): "UInt8",
    np.dtype(np.int16): "Int16",
    np.dtype(np.int32): "Int32",
    np.dtype(np.int64): "Int64",
    np.dtype(np.uint64): "UInt64",
}

FieldValue = Union[np.ndarray, Sequence[np.ndarray]]


def _as_components(val: FieldValue):
    """Normalize a field to (ncomp, data2d) with data2d shape (n, ncomp)."""
    if isinstance(val, (tuple, list)):
        comps = [np.ascontiguousarray(v) for v in val]
        data = np.stack(comps, axis=1)
        return len(comps), data
    arr = np.ascontiguousarray(val)
    if arr.ndim == 1:
        return 1, arr[:, None]
    return arr.shape[1], arr


def write_vtu(
    path: str,
    points: np.ndarray,                      # (n_pts, 3) or (x, y, z) tuple
    connectivity: np.ndarray,                # flat node ids
    offsets: np.ndarray,                     # 1-based end offsets per cell
    cell_types: np.ndarray,                  # VTK type id per cell
    point_data: Optional[Dict[str, FieldValue]] = None,
    cell_data: Optional[Dict[str, FieldValue]] = None,
) -> str:
    if isinstance(points, (tuple, list)):
        points = np.stack([np.asarray(p) for p in points], axis=1)
    points = np.ascontiguousarray(points, dtype=np.float64)
    n_pts = len(points)
    n_cells = len(cell_types)

    conn = np.ascontiguousarray(connectivity, dtype=np.int64)
    offs = np.ascontiguousarray(offsets, dtype=np.int64)
    ctys = np.ascontiguousarray(cell_types, dtype=np.uint8)

    blocks = []   # (xml descriptor, raw bytes)
    offset = 0
    xml_arrays = {}

    def add_array(section, name, arr, ncomp):
        nonlocal offset
        raw = arr.tobytes()
        dtype_name = _VTK_TYPE_NAMES[arr.dtype]
        xml_arrays.setdefault(section, []).append(
            f'<DataArray type="{dtype_name}" Name="{name}" '
            f'NumberOfComponents="{ncomp}" format="appended" offset="{offset}"/>'
        )
        blocks.append(raw)
        offset += 8 + len(raw)  # 8-byte UInt64 size header per block

    add_array("points", "Points", points, 3)
    add_array("cells", "connectivity", conn, 1)
    add_array("cells", "offsets", offs, 1)
    add_array("cells", "types", ctys, 1)
    for section, fields in (("pdata", point_data or {}), ("cdata", cell_data or {})):
        n_expected = n_pts if section == "pdata" else n_cells
        for name, val in fields.items():
            ncomp, data = _as_components(val)
            if len(data) != n_expected:
                raise ValueError(
                    f"field {name!r}: {len(data)} values for {n_expected} "
                    f"{'points' if section == 'pdata' else 'cells'}")
            add_array(section, name, np.ascontiguousarray(data), ncomp)

    if not path.endswith(".vtu"):
        path += ".vtu"
    with open(path, "wb") as f:
        f.write(b'<?xml version="1.0"?>\n')
        f.write(
            b'<VTKFile type="UnstructuredGrid" version="1.0" '
            b'byte_order="LittleEndian" header_type="UInt64">\n'
        )
        f.write(b"<UnstructuredGrid>\n")
        f.write(f'<Piece NumberOfPoints="{n_pts}" NumberOfCells="{n_cells}">\n'.encode())
        f.write(b"<Points>\n")
        f.write((xml_arrays["points"][0] + "\n").encode())
        f.write(b"</Points>\n<Cells>\n")
        for x in xml_arrays["cells"]:
            f.write((x + "\n").encode())
        f.write(b"</Cells>\n")
        if xml_arrays.get("pdata"):
            f.write(b"<PointData>\n")
            for x in xml_arrays["pdata"]:
                f.write((x + "\n").encode())
            f.write(b"</PointData>\n")
        if xml_arrays.get("cdata"):
            f.write(b"<CellData>\n")
            for x in xml_arrays["cdata"]:
                f.write((x + "\n").encode())
            f.write(b"</CellData>\n")
        f.write(b"</Piece>\n</UnstructuredGrid>\n")
        f.write(b'<AppendedData encoding="raw">\n_')
        for raw in blocks:
            f.write(np.uint64(len(raw)).tobytes())
            f.write(raw)
        f.write(b"\n</AppendedData>\n</VTKFile>\n")
    return path


def read_vtu_arrays(path: str) -> dict:
    """Parse a .vtu written by write_vtu back into arrays (for tests)."""
    import re

    with open(path, "rb") as f:
        content = f.read()
    header, _, appended = content.partition(b'<AppendedData encoding="raw">')
    appended = appended.split(b"_", 1)[1]
    inv_types = {v: k for k, v in _VTK_TYPE_NAMES.items()}
    out = {}
    for m in re.finditer(
        rb'<DataArray type="(\w+)" Name="(\w+)" NumberOfComponents="(\d+)" '
        rb'format="appended" offset="(\d+)"/>', header
    ):
        tname, name, ncomp, off = m.groups()
        dt = inv_types[tname.decode()]
        off = int(off)
        nbytes = int(np.frombuffer(appended[off:off + 8], np.uint64)[0])
        arr = np.frombuffer(appended[off + 8:off + 8 + nbytes], dt)
        ncomp = int(ncomp)
        if ncomp > 1:
            arr = arr.reshape(-1, ncomp)
        out[name.decode()] = arr
    return out
