"""Content-addressed cache keys for the warm-path subsystem.

A cache entry is valid iff EVERYTHING that shaped its content hashes the
same: the model bundle, the partition/solver knobs, and the code
generation that produced it.  The last part is covered by embedding
``CACHE_SCHEMA`` (bumped on any serialization-layout change in cache/)
and the package version in every key — a version bump invalidates the
whole cache rather than risking a stale entry deserialized into new code.

Import contract: jax-free at module load (numpy/hashlib only).  The CLI
and bench consult keys before the accelerator environment is configured.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Optional

import numpy as np

from pcg_mpi_solver_tpu import __version__
from pcg_mpi_solver_tpu.config import PCG_VARIANTS

# Bump on ANY change to what cache entries contain or how they are
# serialized (partition pickle layout, AOT export calling convention
# expectations, key payload shape).  Additive key fields need no bump —
# they change the key hash by themselves.
# 2: ISSUE 9 — the blocked (pcg_many) and fused loop bodies gained
#    per-column recovery / drift-guard carry leaves and the
#    quarantine-flag finalize; AOT entries exported from the old
#    programs must not be deserialized into the new semantics.
# 3: ISSUE 14 — PartitionedModel gained the layout/part_range fields and
#    the partition cache became shard-addressed (glue + per-part
#    entries, cache/shards.py); monolithic entries pickled by older code
#    lack the new fields and must re-key rather than deserialize.
CACHE_SCHEMA = 3

# Monkeypatchable in tests to simulate a package-version bump without
# editing the package.
PACKAGE_VERSION = __version__


def _hash_update(h, obj: Any) -> None:
    """Deterministic recursive hash of numpy arrays / builtins /
    dataclasses (dict keys canonicalized by repr sort)."""
    if obj is None:
        h.update(b"\x00none")
    elif isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        h.update(f"nd:{a.shape}:{a.dtype}".encode())
        h.update(a.tobytes())
    elif hasattr(obj, "ids") and hasattr(obj, "vals") \
            and hasattr(obj, "fill"):
        # models/model_data.SparseVec (slab-ingest nodal restriction):
        # its CONTENT must hash — falling through to repr() would hash
        # only n/nnz/dtype, making models that differ solely in nodal
        # data (loads, coordinates) collide in the partition cache
        h.update(f"sparsevec:{len(obj)}:{obj.fill!r}".encode())
        _hash_update(h, np.asarray(obj.ids))
        _hash_update(h, np.asarray(obj.vals))
    elif isinstance(obj, (bool, int, float, str, bytes, complex,
                          np.integer, np.floating, np.bool_)):
        h.update(f"{type(obj).__name__}:{obj!r}".encode())
    elif isinstance(obj, dict):
        h.update(f"dict:{len(obj)}".encode())
        for k in sorted(obj, key=repr):
            h.update(repr(k).encode())
            _hash_update(h, obj[k])
    elif isinstance(obj, (list, tuple)):
        h.update(f"seq:{len(obj)}".encode())
        for v in obj:
            _hash_update(h, v)
    elif dataclasses.is_dataclass(obj):
        h.update(type(obj).__name__.encode())
        for f in dataclasses.fields(obj):
            h.update(f.name.encode())
            _hash_update(h, getattr(obj, f.name))
    else:
        h.update(repr(obj).encode())


def model_fingerprint(model) -> str:
    """Content hash of a full ModelData bundle (every dataclass field:
    topology, loads, BCs, element library, materials, octree/grid
    metadata).  ~GB/s sha256 — sub-second even at flagship scale, and the
    ONE thing that makes the partition cache safe against silently-edited
    models (the reference's zpkl bundles carry no integrity check)."""
    h = hashlib.sha256()
    _hash_update(h, model)
    return h.hexdigest()


def array_hash(arr) -> str:
    """Short content hash of one array (e.g. an explicit elem_part map)."""
    a = np.ascontiguousarray(np.asarray(arr))
    h = hashlib.sha256(f"{a.shape}:{a.dtype}".encode())
    h.update(a.tobytes())
    return h.hexdigest()[:16]


def _digest(payload: Dict[str, Any]) -> str:
    payload = dict(payload)
    payload["cache_schema"] = CACHE_SCHEMA
    payload["version"] = PACKAGE_VERSION
    blob = json.dumps(payload, sort_keys=True, default=repr).encode()
    return hashlib.sha256(blob).hexdigest()[:32]


def partition_cache_key(model_fp: str, *, n_parts: int, backend: str,
                        dtype: str, method: str = "n/a",
                        elem_part_hash: Optional[str] = None,
                        pad_multiple: int = 8,
                        extra: Optional[Dict[str, Any]] = None) -> str:
    """Key for one serialized partition: model content + every knob that
    shapes the partition arrays.  ``extra`` carries backend-specific knobs
    (hybrid block size / merge, native-partitioner availability for
    method='auto', ...)."""
    return _digest({
        "kind": "partition",
        "model": model_fp,
        "n_parts": int(n_parts),
        "backend": backend,
        "dtype": dtype,
        "method": method,
        "elem_part": elem_part_hash,
        "pad_multiple": int(pad_multiple),
        "extra": extra or {},
    })


def partition_shard_key(model_fp: str, *, n_parts: int, part_idx: int,
                        backend: str, dtype: str, method: str = "n/a",
                        elem_part_hash: Optional[str] = None,
                        pad_multiple: int = 8,
                        extra: Optional[Dict[str, Any]] = None) -> str:
    """Key for ONE part's rows of a shard-addressed partition entry
    (ISSUE 14): the monolithic :func:`partition_cache_key` payload plus
    the STRUCTURAL ``part_idx`` component, so N hosts each read only
    their own parts' entries on a warm start.  ``part_idx`` must bite on
    its own (proven by the analysis/ partition-key-components rule):
    two parts of one partition must never collide on one entry."""
    if not (0 <= int(part_idx) < int(n_parts)):
        raise KeyError(
            f"partition_shard_key: part_idx {part_idx} outside "
            f"[0, {n_parts})")
    return _digest({
        "kind": "partition-shard",
        "model": model_fp,
        "n_parts": int(n_parts),
        "part_idx": int(part_idx),
        "backend": backend,
        "dtype": dtype,
        "method": method,
        "elem_part": elem_part_hash,
        "pad_multiple": int(pad_multiple),
        "extra": extra or {},
    })


def partition_glue_key(model_fp: str, *, n_parts: int, backend: str,
                       dtype: str, method: str = "n/a",
                       elem_part_hash: Optional[str] = None,
                       pad_multiple: int = 8,
                       extra: Optional[Dict[str, Any]] = None) -> str:
    """Key for the GLUE entry of a shard-addressed partition: the global
    layout (PartitionLayout, scalars, shared element matrices) every
    process loads alongside its own part entries.  Same payload as the
    per-part keys minus ``part_idx`` — distinct ``kind`` so glue can
    never collide with a part entry or a legacy monolithic one."""
    return _digest({
        "kind": "partition-glue",
        "model": model_fp,
        "n_parts": int(n_parts),
        "backend": backend,
        "dtype": dtype,
        "method": method,
        "elem_part": elem_part_hash,
        "pad_multiple": int(pad_multiple),
        "extra": extra or {},
    })


def mdf_fingerprint(mdf_path: str, chunk_bytes: int = 1 << 24) -> str:
    """Content hash of an on-disk MDF bundle, STREAMED file-by-file in
    bounded chunks — the slab-ingest twin of :func:`model_fingerprint`:
    a process that never materializes the full model (models/mdf.
    read_mdf_slab) still needs the one content hash every shard key
    shares, and every process must derive the identical hash from the
    identical bundle."""
    import os

    h = hashlib.sha256()
    try:
        names = sorted(os.listdir(mdf_path))
    except OSError as e:
        raise FileNotFoundError(f"mdf_fingerprint: {mdf_path}: {e}")
    for name in names:
        p = os.path.join(mdf_path, name)
        if not os.path.isfile(p):
            continue
        h.update(f"file:{name}:{os.path.getsize(p)}".encode())
        with open(p, "rb") as f:
            while True:
                chunk = f.read(chunk_bytes)
                if not chunk:
                    break
                h.update(chunk)
    return h.hexdigest()


def step_cache_key(*, abstract: Any, mesh: Any, backend: str,
                   solver: Dict[str, Any], trace_len: int,
                   glob_n_dof_eff: int, donate: bool,
                   jax_version: str,
                   pcg_variant: str = "classic",
                   precond: str = "jacobi",
                   nrhs: int = 1,
                   extra: Optional[Dict[str, Any]] = None) -> str:
    """Key for one AOT-exported PCG step program: the ABSTRACT signature
    (shapes/dtypes/shardings repr), the mesh layout, and every scalar the
    step closure bakes in as a compile-time constant (solver config,
    effective dof count, trace ring length, donation).

    ``pcg_variant`` (SolverConfig.pcg_variant) is carried as its own
    structural component on top of the solver dict: the classic and
    fused loop bodies are different programs with different carry
    pytrees, and an AOT/compile-cache hit across variants would
    deserialize the wrong one.  ``nrhs`` is the same kind of structural
    component for the batched multi-RHS programs (solve_many): the
    blocked body's carry pytree and every vector shape differ per block
    width, so programs of different nrhs must never collide (the
    abstract signature already separates them — the explicit key field
    makes the invariant survive any signature-repr change).
    ``precond`` is the same kind of structural component (ISSUE 10):
    the mg V-cycle reshapes the loop body's preconditioner apply and
    its operand pytree, so jacobi/block3/mg programs must never collide
    even if the solver dict's serialization changes; the MG-shape knobs
    (levels/degree/dims) ride ``extra["mg"]`` from the driver."""
    if pcg_variant not in PCG_VARIANTS:
        # single-source variant discipline (config.PCG_VARIANTS): a key
        # for a variant no loop builder knows would cache a program that
        # can never be rebuilt — fail here, loudly, like the gauges and
        # the collective-budget table do
        raise KeyError(
            f"step_cache_key: unknown pcg_variant {pcg_variant!r} "
            f"(valid: {PCG_VARIANTS})")
    return _digest({
        "kind": "aot-step",
        "abstract": abstract,
        "mesh": mesh,
        "backend": backend,
        "solver": solver,
        "pcg_variant": str(pcg_variant),
        "precond": str(precond),
        "nrhs": int(nrhs),
        "trace_len": int(trace_len),
        "glob_n_dof_eff": int(glob_n_dof_eff),
        "donate": bool(donate),
        "jax": jax_version,
        "extra": extra or {},
    })
