"""On-disk partition cache: content-addressed serialized partitions.

A cache hit turns the 58.5 s flagship partition (BENCH_r05.json
``partition_s``) into a multi-second zlib-pickle load.  Entries are
written atomically (unique tmp + ``os.replace``, the same publish
discipline as bench.py's model cache) so concurrent solvers — e.g. a
warmup queue racing the bench — can share one directory; corrupt or
unreadable entries are treated as misses and removed.

Layout under a cache dir (shared with ``cache/aot.py``)::

    <cache_dir>/partition/<key>.zpkl    serialized partitions (this module)
    <cache_dir>/aot/<key>.jaxexport     AOT-exported step programs
    <cache_dir>/xla/...                 persistent XLA compilation cache

Import contract: jax-free at module load (utils/io.py only imports jax
lazily inside ``is_primary``).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional

from pcg_mpi_solver_tpu.utils import io as uio

SUBDIRS = ("partition", "aot", "xla")


def _entry_path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, "partition", f"{key}.zpkl")


def load_partition(cache_dir: str, key: str):
    """Deserialize the entry for ``key``; None on miss.  A corrupt entry
    (failed unpickle — e.g. written by an incompatible code state that
    predates the key's version fields) is removed and treated as a miss."""
    path = _entry_path(cache_dir, key)
    if not os.path.exists(path):
        return None
    try:
        pm = uio.importz(path)
    except Exception:                                   # noqa: BLE001
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    try:
        os.utime(path)                                  # LRU touch
    except OSError:
        pass
    return pm


def store_partition(cache_dir: str, key: str, pm,
                    cap_bytes: Optional[float] = None) -> bool:
    """Atomically publish ``pm`` under ``key``; best-effort (a full disk
    must not fail the solve that built the partition).  LRU-evicts old
    entries past PCG_TPU_CACHE_GB (default 8)."""
    path = _entry_path(cache_dir, key)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        uio.exportz_atomic(path, pm)
        evict_lru(os.path.dirname(path), keep=path, cap_bytes=cap_bytes)
        return True
    except Exception:                                   # noqa: BLE001
        return False


def evict_lru(entry_dir: str, keep: str,
              cap_bytes: Optional[float] = None,
              suffix: str = ".zpkl", prefix: str = "") -> None:
    """LRU-evict ``prefix*suffix`` entries until the directory fits the
    size cap — the ONE copy of the eviction protocol, shared by this
    module, cache/aot.py (*.jaxexport) and bench.py's model cache
    (model_*.pkl).  Model or code edits re-key every entry, permanently
    orphaning the old generation — without eviction the
    multi-hundred-MB flagship entries accumulate unboundedly."""
    if cap_bytes is None:
        cap_bytes = float(os.environ.get("PCG_TPU_CACHE_GB", 8)) * 2**30
    try:
        entries = []
        for fn in os.listdir(entry_dir):
            p = os.path.join(entry_dir, fn)
            # Per-file tolerance (ISSUE 14 bugfix): with N processes
            # writing shard entries into ONE cache dir, another
            # process's eviction can delete a file between our listdir
            # and stat/remove — that is a fait accompli, not a reason to
            # abort THIS process's whole eviction pass (the old
            # dir-level try/except did exactly that, leaving the cache
            # over cap whenever evictions raced).
            try:
                if fn.startswith(prefix) and fn.endswith(suffix):
                    st = os.stat(p)
                    entries.append((st.st_mtime, st.st_size, p))
                elif fn.endswith(".tmp") and \
                        time.time() - os.stat(p).st_mtime > 3600:
                    os.remove(p)        # SIGKILL-orphaned half-write
            except OSError:
                continue                # concurrently deleted: move on
        total = sum(s for _, s, _ in entries)
        for _, size, p in sorted(entries):              # oldest first
            if total <= cap_bytes:
                break
            if os.path.abspath(p) == os.path.abspath(keep):
                continue                                # never the new entry
            try:
                os.remove(p)
            except OSError:
                pass        # another process evicted it first — same goal
            total -= size
    except OSError:
        pass                                            # best-effort


def cached_partition(cache_dir: str, key: str, builder: Callable[[], Any],
                     recorder=None, label: str = "partition"):
    """Load-or-build with cold/warm attribution through obs/metrics.py:
    a hit emits a ``cache`` event and bumps ``cache.partition.hit``
    (zero partitioning work — the builder is never invoked); a miss
    builds, publishes, and bumps ``cache.partition.miss``."""
    t0 = time.perf_counter()
    pm = load_partition(cache_dir, key)
    if pm is not None:
        if recorder is not None:
            recorder.inc("cache.partition.hit")
            recorder.event("cache", name=f"partition.{label}", hit=True,
                           key=key,
                           wall_s=round(time.perf_counter() - t0, 6))
        return pm
    pm = builder()
    stored = store_partition(cache_dir, key, pm)
    if recorder is not None:
        recorder.inc("cache.partition.miss")
        recorder.event("cache", name=f"partition.{label}", hit=False,
                       key=key, stored=stored,
                       wall_s=round(time.perf_counter() - t0, 6))
    return pm


def cached_partition_shards(cache_dir: str, *, glue_key: str,
                            part_keys: Dict[int, str], builder,
                            split, join,
                            legacy_key: Optional[str] = None,
                            comm=None, recorder=None,
                            label: str = "partition"):
    """Shard-addressed load-or-build (ISSUE 14).

    Warm path: load the glue entry + ONLY the entries named in
    ``part_keys`` (this process's parts) and ``join`` them — zero build
    work, and the bytes read scale with parts-per-process, not model
    size.  Legacy shim: when any shard entry misses but ``legacy_key``
    (the monolithic :func:`partition_cache_key`) hits, the monolithic
    object is served as-is — pre-shard caches stay warm.  Cold path:
    ``builder()`` builds (possibly only this process's part range), then
    ``split`` publishes the glue + one entry per key in ``part_keys``
    (each process persists exactly the parts it built; under a
    multi-process cold start the processes collectively tile the whole
    partition).

    ``comm`` (a SetupComm under multi-process jax.distributed): the
    warm-vs-cold decision GATES a collective code path (the cold
    builder runs the layout exchange), so it must be AGREED across the
    group — a process whose entries were concurrently evicted (or whose
    store failed on a full disk) must not build-and-exchange while its
    peers skip ahead to later collectives (mispaired allgathers hang
    the group).  With ``comm`` set, one small reduce decides: warm only
    if EVERY process can serve warm (shard entries or the legacy
    monolithic); otherwise every process builds.

    Emits ONE ``cache`` event (hit = fully-warm) with the per-entry read
    accounting the sharded-warm-start tests assert on; counters follow
    :func:`cached_partition` (`cache.partition.hit`/`miss`)."""
    import numpy as np

    t0 = time.perf_counter()
    glue = load_partition(cache_dir, glue_key)
    shards, missing = {}, []
    if glue is not None:
        for p, key in part_keys.items():
            sh = load_partition(cache_dir, key)
            if sh is None:
                missing.append(p)
                break
            shards[p] = sh
    shard_warm = glue is not None and not missing
    legacy_pm = None
    if not shard_warm and legacy_key is not None:
        legacy_pm = load_partition(cache_dir, legacy_key)
    can_serve = shard_warm or legacy_pm is not None
    if comm is not None and getattr(comm, "n_procs", 1) > 1:
        # warm/cold GATES collective code paths — group-agreed (min:
        # all ranks must be able to serve warm) via the shared
        # consensus primitive (parallel/consensus, ISSUE 18)
        from pcg_mpi_solver_tpu.parallel.consensus import agree_flag

        can_serve = agree_flag(comm, can_serve)
    if can_serve and shard_warm:
        pm = join(glue, shards)
        if recorder is not None:
            recorder.inc("cache.partition.hit")
            recorder.event("cache", name=f"partition.{label}", hit=True,
                           key=glue_key, shard=True,
                           entries=1 + len(shards),
                           parts=sorted(part_keys),
                           wall_s=round(time.perf_counter() - t0, 6))
        return pm
    if can_serve and legacy_pm is not None:
        if recorder is not None:
            recorder.inc("cache.partition.hit")
            recorder.event("cache", name=f"partition.{label}",
                           hit=True, key=legacy_key, shard=False,
                           legacy=True,
                           wall_s=round(time.perf_counter() - t0, 6))
        return legacy_pm
    pm = builder()
    glue, built = split(pm)
    keys = dict(part_keys)
    stored = store_partition(cache_dir, glue_key, glue)
    for p, key in keys.items():
        if p in built:
            stored = store_partition(cache_dir, key, built[p]) and stored
    if recorder is not None:
        recorder.inc("cache.partition.miss")
        recorder.event("cache", name=f"partition.{label}", hit=False,
                       key=glue_key, shard=True, stored=stored,
                       parts=sorted(keys),
                       wall_s=round(time.perf_counter() - t0, 6))
    return pm


# ----------------------------------------------------------------------
# Stats (the CLI `cache-stats` / `warmup` surfaces)
# ----------------------------------------------------------------------

def cache_stats(cache_dir: str) -> Dict[str, Dict[str, Any]]:
    """{section: {entries, bytes, newest_age_s}} for each cache subdir
    (xla entries are whatever the persistent compilation cache wrote)."""
    out: Dict[str, Dict[str, Any]] = {}
    now = time.time()
    for sub in SUBDIRS:
        d = os.path.join(cache_dir, sub)
        entries, size, newest = 0, 0, None
        if os.path.isdir(d):
            for root, _dirs, files in os.walk(d):
                for fn in files:
                    if fn.endswith(".tmp"):
                        continue
                    try:
                        st = os.stat(os.path.join(root, fn))
                    except OSError:
                        continue
                    entries += 1
                    size += st.st_size
                    age = now - st.st_mtime
                    newest = age if newest is None else min(newest, age)
        out[sub] = {"entries": entries, "bytes": size,
                    "newest_age_s": None if newest is None
                    else round(newest, 1)}
    return out


def format_stats(cache_dir: str) -> str:
    """Human-readable cache table (CLI `cache-stats` output)."""
    stats = cache_stats(cache_dir)
    lines = [f"cache dir: {cache_dir}",
             f"{'section':<12} {'entries':>8} {'size':>10} {'newest':>10}"]
    for sub in SUBDIRS:
        st = stats[sub]
        mb = st["bytes"] / 2**20
        age = ("-" if st["newest_age_s"] is None
               else f"{st['newest_age_s']:.0f}s ago")
        lines.append(f"{sub:<12} {st['entries']:>8} {mb:>9.1f}M {age:>10}")
    return "\n".join(lines)
