"""Ahead-of-time compile path: persistent XLA cache + jax.export'd steps.

Two layers, both keyed/invalidated by ``cache/keys.py``:

* ``enable_persistent_compilation_cache`` points jax's persistent
  compilation cache at ``<cache_dir>/xla`` on accelerator backends (CPU
  executables don't round-trip through it on jax 0.4.x — see the
  function docstring) — a re-compile of an identical program becomes a
  cache read.  This alone cuts the 400+ s flagship octree compiles
  (docs/BENCH_LOG.md) to a load on re-runs.
* ``export_step``/``store_step``/``load_step`` serialize the jitted PCG
  step via ``jax.export`` keyed by its ABSTRACT signature (shapes /
  dtypes / shardings): a warm session deserializes StableHLO instead of
  re-tracing the solver's Python, so a same-shape-class re-run skips
  tracing entirely and its (deserialized-module) compile hits the
  persistent XLA cache.  Critical when a hardware window is 9 minutes.

Import contract: jax is imported lazily inside functions — this module
may be imported before the accelerator environment is configured.
"""

from __future__ import annotations

import os
import time
from typing import Optional


def enable_persistent_compilation_cache(cache_dir: str) -> str:
    """Wire jax's persistent compilation cache to ``<cache_dir>/xla``.
    Safe to call repeatedly; returns the XLA cache dir.

    ACCELERATOR BACKENDS ONLY: on jax 0.4.x CPU, executables written to
    the persistent cache do not deserialize reliably — a later
    same-signature compile loads the entry and crashes the process
    (segfault, flaky) at dispatch.  Empirically reproduced on the
    8-device virtual CPU mesh; the cache module is also sticky (a later
    ``jax_compilation_cache_dir`` config change does not re-point an
    initialized cache), so one enable poisons every later solve in the
    process.  CPU compiles are seconds, not the 400+ s flagship pain
    this exists for — the partition + AOT layers alone already give CPU
    the warm path."""
    import jax

    d = os.path.join(cache_dir, "xla")
    os.makedirs(d, exist_ok=True)
    if jax.default_backend() != "cpu":
        jax.config.update("jax_compilation_cache_dir", d)
    return d


def _entry_path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, "aot", f"{key}.jaxexport")


def abstract_like(tree):
    """Concrete (committed) array pytree -> ShapeDtypeStruct pytree with
    the SAME shardings, for sharding-faithful .lower()/export calls."""
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=x.sharding), tree)


def signature_repr(abstract_args) -> str:
    """Stable repr of an abstract signature (shapes/dtypes/shardings) for
    key derivation."""
    import jax

    leaves, treedef = jax.tree.flatten(abstract_args)
    parts = [f"{tuple(l.shape)}:{l.dtype}:"
             f"{getattr(l, 'sharding', None)}" for l in leaves]
    return f"{treedef}|" + ";".join(parts)


def export_step(jit_fn, abstract_args):
    """Trace + lower ``jit_fn`` at the abstract signature and return the
    serializable ``jax.export.Exported``.  The one trace this costs on a
    COLD run is what every warm run skips."""
    from jax import export as jexport

    return jexport.export(jit_fn)(*abstract_args)


def load_step(cache_dir: str, key: str, recorder=None):
    """Deserialize the exported step for ``key``; None on miss.

    Corrupt, truncated, or version-incompatible blobs (jax.export
    enforces its own calling-convention versioning; a killed writer
    predating the atomic-publish discipline, or a torn disk, leaves
    truncated ones) are QUARANTINED — renamed to ``<entry>.corrupt``,
    overwriting any previous quarantine for the key so at most one is
    kept — and treated as a cache miss, exactly matching the
    corruption handling ``cache/partition_cache.load_partition``
    already has (there the entry is removed; here the blob is kept for
    forensics since a bad AOT entry usually means a toolchain-version
    skew worth diagnosing).  The caller then re-exports and the fresh
    entry replaces the bad one: a corrupt cache can cost one re-trace,
    never a failed solve."""
    path = _entry_path(cache_dir, key)
    if not os.path.exists(path):
        return None
    from jax import export as jexport

    try:
        with open(path, "rb") as f:
            exported = jexport.deserialize(bytearray(f.read()))
    except Exception as e:                              # noqa: BLE001
        try:
            os.replace(path, path + ".corrupt")
            action = "quarantined"
        except OSError:
            try:
                os.remove(path)
            except OSError:
                pass
            action = "removed"
        if recorder is not None:
            recorder.inc("cache.aot.corrupt")
            recorder.event("cache", name="aot.step.corrupt", hit=False,
                           key=key, wall_s=0.0, action=action,
                           error=f"{type(e).__name__}: {e}")
        return None
    try:
        os.utime(path)                                  # LRU touch
    except OSError:
        pass
    return exported


def store_step(cache_dir: str, key: str, exported) -> bool:
    """Atomically publish a serialized exported step; best-effort.  The
    half-written tmp of a failed write is removed, and the aot dir is
    LRU-evicted to the same PCG_TPU_CACHE_GB cap as the partition
    entries (code/version re-keys orphan old generations here too)."""
    from pcg_mpi_solver_tpu.cache.partition_cache import evict_lru
    from pcg_mpi_solver_tpu.utils.io import write_atomic

    path = _entry_path(cache_dir, key)
    try:
        blob = bytes(exported.serialize())
        os.makedirs(os.path.dirname(path), exist_ok=True)
        write_atomic(path, blob)
    except Exception:                                   # noqa: BLE001
        return False
    evict_lru(os.path.dirname(path), keep=path,
              suffix=".jaxexport")
    # quarantined corrupt blobs (load_step) are forensics, not cache
    # entries — they get the same LRU discipline under their own suffix
    # so they can never grow the shared dir unboundedly (every version
    # bump re-keys entries, so per-key overwrite alone does not bound
    # them)
    evict_lru(os.path.dirname(path), keep=path,
              suffix=".jaxexport.corrupt")
    return True


def cached_step(cache_dir: str, key: str, jit_fn, abstract_args,
                recorder=None) -> Optional[object]:
    """Load-or-export the step program; returns the ``Exported`` (from
    disk on a hit — zero tracing — or freshly exported on a miss), or
    None when export is unsupported for this program/jax version (the
    caller keeps its plain jit).  Cold/warm attribution mirrors
    ``cached_partition``."""
    t0 = time.perf_counter()
    exported = load_step(cache_dir, key, recorder=recorder)
    if exported is not None:
        if recorder is not None:
            recorder.inc("cache.aot.hit")
            recorder.event("cache", name="aot.step", hit=True, key=key,
                           wall_s=round(time.perf_counter() - t0, 6))
        return exported
    try:
        exported = export_step(jit_fn, abstract_args)
        stored = store_step(cache_dir, key, exported)
        err = None
    except Exception as e:                              # noqa: BLE001
        exported, stored = None, False
        err = f"{type(e).__name__}: {e}"
    if recorder is not None:
        recorder.inc("cache.aot.miss" if err is None
                     else "cache.aot.unsupported")
        recorder.event("cache", name="aot.step", hit=False, key=key,
                       stored=stored, error=err,
                       wall_s=round(time.perf_counter() - t0, 6))
    return exported
