"""Warm-path subsystem: persistent compile + partition caches.

Round-5 hardware data showed wall time dominated by SETUP, not iteration:
58.5 s of partitioning at 10.33M dofs and 400+ s XLA compiles for the
bucketed octree variant (BENCH_r05.json, VERDICT.md r5).  This package
makes the SECOND solve of a given model/mesh shape cost near-zero setup —
the warm-start discipline production inference stacks apply to compiled
programs and KV caches:

* ``keys``            — content-addressed cache keys: model fingerprint +
                        (n_parts, backend, dtype, padding/partition knobs),
                        versioned by ``CACHE_SCHEMA`` and the package
                        version so a code bump invalidates cleanly.
* ``partition_cache`` — on-disk store for ``PartitionedModel`` /
                        ``HybridPartition`` / ``StructuredPartition``
                        (atomic zlib-pickled writes via ``utils/io.py``,
                        LRU eviction, stats).
* ``aot``             — persistent XLA compilation-cache wiring
                        (``jax_compilation_cache_dir``) plus ahead-of-time
                        ``jax.export`` serialization of the jitted PCG
                        step, so a warm re-run of the same shape class
                        skips tracing AND compile.

Import contract: this ``__init__`` and ``keys`` / ``partition_cache`` are
jax-free at module load (``aot`` imports jax lazily inside functions) —
``bench.py`` and the CLI consult cache keys/stats before the accelerator
environment is configured, and the package ``__init__`` must stay jax-free
for the wedged-tunnel CPU pin (see ``pcg_mpi_solver_tpu/__init__.py``).
"""

from pcg_mpi_solver_tpu.cache.keys import (
    CACHE_SCHEMA, array_hash, model_fingerprint, partition_cache_key,
    step_cache_key)
from pcg_mpi_solver_tpu.cache.partition_cache import (
    cache_stats, cached_partition, format_stats, load_partition,
    store_partition)

__all__ = [
    "CACHE_SCHEMA",
    "array_hash",
    "model_fingerprint",
    "partition_cache_key",
    "step_cache_key",
    "cache_stats",
    "cached_partition",
    "format_stats",
    "load_partition",
    "store_partition",
]
