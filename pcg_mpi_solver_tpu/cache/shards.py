"""Shard-addressed partition serialization (ISSUE 14).

Splits a built partition into one small GLUE entry (global scalars, the
:class:`~pcg_mpi_solver_tpu.parallel.partition.PartitionLayout`, the
shared per-type element matrices) plus one entry PER PART holding only
that part's rows of every ``(P, ...)`` array — so N hosts of a
``jax.distributed`` run each read ONLY their own parts' entries (plus
the glue) on a warm start, instead of every host deserializing one
monolithic multi-hundred-MB blob.  ``join_partition`` reassembles a
partition object from the glue + any subset of part entries; rows of
absent parts are reconstructed at their padding values (weight 0,
dof_gid -1, index maps at their out-of-range sentinels) — exactly what
``partition_model(part_range=...)`` leaves there, so a warm shard load
is bit-identical to a cold shard build.

The classification below is EXPLICIT (not shape-sniffed): an array field
whose leading dim happens to equal ``n_parts`` (e.g. ``elem_part`` on a
tiny model) must not silently become per-part.  A new array field on
``PartitionedModel``/``TypeBlock``/``StructuredPartition`` that is
neither listed per-part nor global fails loudly in ``split_partition``
— the forcing function that keeps the cache layout complete.

Import contract: jax-free at module load (like the rest of cache/).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

#: schema tag embedded in every glue/shard entry payload; bump on any
#: layout change here (CACHE_SCHEMA in cache/keys.py already re-keys all
#: entries on serialization changes — this tag is the belt to that
#: suspenders for hand-inspected entries)
SHARD_LAYOUT = "pcg-tpu-partition-shard/1"

# ---- PartitionedModel classification ---------------------------------
_PM_PER_PART = (
    "scat_perm", "scat_ids", "ell", "iface_local", "iface_slot",
    "niface_local", "niface_slot", "weight", "node_weight", "eff", "F",
    "Ud", "inv_diag_M", "dof_gid", "node_gid", "spr_a", "spr_b", "spr_k",
)
_PM_GLOBAL = (
    "n_parts", "n_loc", "n_node_loc", "n_iface", "n_node_iface",
    "glob_n_dof", "glob_n_dof_eff", "glob_n_node", "node_layout",
    "ndof_p", "nnode_p", "layout",
)
#: fields deliberately NOT persisted in the shard layout: ``elem_part``
#: is O(n_elem) (the glue must stay surface-scale — at 1B dofs a
#: model-sized map would make every host's warm read O(model) again)
#: AND process-dependent under the slab2 refine-local fast path (other
#: slabs keep coarse labels), so concurrent glue writers would race on
#: different content.  Its identity already keys every entry
#: (elem_part_hash / method / slab2_slabs); consumers needing the map
#: (only the hybrid backend's refresh path, which uses the monolithic
#: store) never read shard entries.  Joined partitions carry None.
#: ``part_range`` is process-dependent under a sharded cold build for
#: the same reason (each writer's glue would race on ITS range) —
#: ``join_partition`` re-derives it from the loaded shard set instead.
_PM_DROPPED = ("elem_part", "part_range")
_TB_PER_PART = ("dof", "sign", "node", "ck", "ce", "e_mod", "valid",
                "n_elem")
_TB_GLOBAL = ("type_id", "d", "n_nodes", "Ke", "diag_Ke", "Se", "Me")

# ---- StructuredPartition classification ------------------------------
_SP_PER_PART = ("ck", "ce", "weight", "node_weight", "eff", "F", "Ud",
                "dof_gid", "node_gid")
_SP_GLOBAL = (
    "n_parts", "n_loc", "n_iface", "n_node_loc", "glob_n_dof",
    "glob_n_dof_eff", "glob_n_node", "nxc", "ny", "nz", "Ke", "diag_Ke",
    "Se", "ndof_p",
)


def _check_classified(obj, per_part, global_, label: str,
                      special=("type_blocks",) + _PM_DROPPED) -> None:
    names = {f.name for f in dataclasses.fields(obj)}
    missing = names - set(per_part) - set(global_) - set(special)
    if missing:
        raise TypeError(
            f"cache/shards.py: unclassified {label} field(s) {sorted(missing)}"
            " — add them to the per-part or global table so the shard "
            "cache layout stays complete")


def _is_structured(pm) -> bool:
    return hasattr(pm, "nxc") and not hasattr(pm, "type_blocks")


def split_partition(pm, part_range: Optional[Tuple[int, int]] = None):
    """Split a built partition into ``(glue, {part_idx: shard})``.

    ``part_range`` limits which parts get shard entries (a sharded cold
    build only has its own rows populated); default = the partition's
    own ``part_range`` (full build: every part)."""
    if part_range is None:
        part_range = getattr(pm, "part_range", None) or (0, pm.n_parts)
    lo, hi = part_range
    if _is_structured(pm):
        per_part, global_, blocks = _SP_PER_PART, _SP_GLOBAL, None
        _check_classified(pm, per_part, global_, "StructuredPartition")
    else:
        per_part, global_, blocks = _PM_PER_PART, _PM_GLOBAL, pm.type_blocks
        _check_classified(pm, per_part, global_, "PartitionedModel")
        for tb in blocks:
            _check_classified(tb, _TB_PER_PART, _TB_GLOBAL, "TypeBlock")

    glue = {"schema": SHARD_LAYOUT,
            "kind": "structured" if blocks is None else "general",
            "fields": {n: getattr(pm, n) for n in global_}}
    if blocks is not None:
        glue["blocks"] = [{n: getattr(tb, n) for n in _TB_GLOBAL}
                         for tb in blocks]
        # ROW shapes (shape[1:]): join re-adds the parts axis
        glue["block_shapes"] = [
            {n: (getattr(tb, n).shape[1:], str(getattr(tb, n).dtype))
             for n in _TB_PER_PART} for tb in blocks]
    glue["shapes"] = {n: (None if getattr(pm, n) is None
                          else (getattr(pm, n).shape[1:],
                                str(getattr(pm, n).dtype)))
                      for n in per_part}
    shards: Dict[int, dict] = {}
    for p in range(lo, hi):
        sh = {"schema": SHARD_LAYOUT, "part_idx": p,
              "fields": {n: (None if getattr(pm, n) is None
                             else np.ascontiguousarray(getattr(pm, n)[p]))
                         for n in per_part}}
        if blocks is not None:
            sh["blocks"] = [{n: np.ascontiguousarray(getattr(tb, n)[p])
                             for n in _TB_PER_PART} for tb in blocks]
        shards[p] = sh
    return glue, shards


def _row_fill(name: str, shape, dtype, glue_fields) -> np.ndarray:
    """Padding row for a part whose shard entry was not loaded — must
    match what ``partition_model(part_range=...)`` leaves in unbuilt
    rows (the bit-identity contract of warm vs cold sharded setup)."""
    n_loc = glue_fields["n_loc"]
    fills = {"dof_gid": -1, "node_gid": -1, "spr_a": n_loc, "spr_b": n_loc,
             "iface_local": n_loc, "iface_slot": glue_fields["n_iface"],
             "niface_local": glue_fields["n_node_loc"],
             "niface_slot": glue_fields.get("n_node_iface", 0),
             "ell": glue_fields.get("_ell_fill", 0)}
    return np.full(shape, fills.get(name, 0), dtype=np.dtype(dtype))


def join_partition(glue: dict, shards: Dict[int, dict]):
    """Reassemble a partition object from the glue entry + any subset of
    part entries (absent parts' rows take their padding values).  The
    result is bit-identical to a ``partition_model(part_range=...)``
    build covering the same parts."""
    fields = dict(glue["fields"])
    P = int(fields["n_parts"])
    out = dict(fields)
    # the loaded shard set defines the populated range (part_range is
    # deliberately NOT in the glue — see _PM_DROPPED)
    ps = sorted(shards)
    out["part_range"] = (ps[0], ps[-1] + 1) if ps else None
    structured = glue.get("kind") == "structured"
    per_part = _SP_PER_PART if structured else _PM_PER_PART
    if not structured:
        # ell's padding value is the out-of-range slot id n_slots (the
        # total element-node slot count across type blocks)
        fields["_ell_fill"] = sum(
            int(np.prod(bs["node"][0]))
            for bs in glue.get("block_shapes", ()))
    for n in per_part:
        spec = glue["shapes"][n]
        if spec is None:
            out[n] = None
            continue
        shape, dtype = spec
        full = _row_fill(n, (P,) + tuple(shape), dtype, fields)
        for p, sh in shards.items():
            row = sh["fields"][n]
            if row is not None:
                full[p] = row
        out[n] = full
    if structured:
        from pcg_mpi_solver_tpu.parallel.structured import (
            StructuredPartition)

        return StructuredPartition(**out)
    from pcg_mpi_solver_tpu.parallel.partition import (
        PartitionedModel, TypeBlock)

    out.setdefault("elem_part", None)     # _PM_DROPPED — see above
    type_blocks = []
    for bi, bglob in enumerate(glue["blocks"]):
        tb = dict(bglob)
        for n, (shape, dtype) in glue["block_shapes"][bi].items():
            if n == "n_elem":
                full = np.zeros((P,), dtype=np.dtype(dtype))
            elif n in ("dof",):
                full = np.full((P,) + tuple(shape), fields["n_loc"],
                               dtype=np.dtype(dtype))
            elif n in ("node",):
                full = np.full((P,) + tuple(shape), fields["n_node_loc"],
                               dtype=np.dtype(dtype))
            else:
                full = np.zeros((P,) + tuple(shape), dtype=np.dtype(dtype))
            for p, sh in shards.items():
                # ascontiguousarray promoted scalar rows to (1,)
                full[p] = np.asarray(sh["blocks"][bi][n]).reshape(
                    np.shape(full[p]))
            tb[n] = full
        type_blocks.append(TypeBlock(**tb))
    out["type_blocks"] = type_blocks
    return PartitionedModel(**out)


# ----------------------------------------------------------------------
# MG hierarchy (ops/mg.py MGSetup): the ``fine`` transfer arrays are the
# only parts-sharded leaves — everything else (the replicated coarse
# hierarchy, Ke, lam, meta) is global by design and lives in the glue.
# ----------------------------------------------------------------------

def split_mg(setup, part_range: Tuple[int, int]):
    """``MGSetup`` -> (glue, {part_idx: shard}) for the shard cache."""
    lo, hi = part_range
    fine = setup.tree["fine"]
    glue = {"schema": SHARD_LAYOUT, "kind": "mg",
            "tree": {k: v for k, v in setup.tree.items() if k != "fine"},
            "fine_shapes": {k: (v.shape, str(v.dtype))
                            for k, v in fine.items()},
            "meta": setup.meta, "coarse_lams": setup.coarse_lams,
            "lam_min_coarse": setup.lam_min_coarse}
    shards = {p: {"schema": SHARD_LAYOUT, "part_idx": p,
                  "fine": {k: np.ascontiguousarray(v[p])
                           for k, v in fine.items()}}
              for p in range(lo, hi)}
    return glue, shards


def join_mg(glue: dict, shards: Dict[int, dict]):
    """Reassemble an ``MGSetup`` from glue + any subset of part entries
    (absent parts' fine-transfer rows are zero-weight — never read by a
    process that does not own them)."""
    from pcg_mpi_solver_tpu.ops.mg import MGSetup

    fine = {}
    for k, (shape, dtype) in glue["fine_shapes"].items():
        P = shape[0]
        full = np.zeros(shape, dtype=np.dtype(dtype))
        for p, sh in shards.items():
            full[p] = sh["fine"][k]
        fine[k] = full
    tree = dict(glue["tree"])
    tree["fine"] = fine
    return MGSetup(tree=tree, meta=dict(glue["meta"]),
                   coarse_lams=list(glue["coarse_lams"]),
                   lam_min_coarse=float(glue["lam_min_coarse"]))
