"""Weak-scaling setup ladder (ISSUE 14, ``BENCH_SETUP_LADDER``).

Measures the COLD SETUP PATH — partition build, model ingest, warm-cache
reload — as a weak-scaling ladder over process counts: each rung runs a
real N-process ``jax.distributed`` group on CPU with a FIXED per-process
problem size (the model grows with N along x), so the numbers answer the
ROADMAP-2 question directly: does setup cost scale with process count
instead of model size?

Per rung the harness records, as one BENCH-schema line (and into the
``BENCH_SETUP_OUT`` artifact):

* ``partition_build_s``  — max per-process SHARDED partition build wall
  (each process builds only its own parts; ``Solver.partition_build_s``);
* ``partition_serial_s`` — the monolithic full build of the SAME model,
  measured once in the parent: what every process would pay without the
  sharded path.  ``vs_baseline`` = serial/parallel — the acceptance
  criterion (>= 2x at 4 processes);
* ``cold_setup_s`` / ``warm_setup_s`` — solver construction wall on the
  cold build vs the shard-addressed warm cache (every process reads ONLY
  its own per-part entries — asserted in-child via the recorder's cache
  event);
* ``ingest_peak_bytes``  — peak host memory of the streamed slab ingest
  (models/mdf.read_mdf_slab) of the rung's model, per process.

Run via ``BENCH_SETUP_LADDER=1,2,4 python bench.py`` (bench.py delegates
here before touching any accelerator — the ladder is CPU-only by
design) or ``python -m pcg_mpi_solver_tpu.setup_ladder``.  Knobs:
``BENCH_SETUP_LADDER`` (comma process counts), ``BENCH_SETUP_NX``
(per-process cells/axis, default 40 — big enough that per-part build
work dominates the layout-exchange dispatches), ``BENCH_SETUP_PPP``
(parts per
process, default 2), ``BENCH_SETUP_OUT`` (artifact path, default
``setup_ladder.json``), ``BENCH_SETUP_TIMEOUT_S`` (per-rung child
timeout).  The hardware queue runs it as the ``setup ladder`` step
(tools/hw_session.py --preset priority), sharing the warm cache dir.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

# Child process body: one rank of a rung.  Builds the (deterministic)
# synthetic model itself, constructs a COLD sharded Solver against the
# shared cache dir, then a WARM one, asserting the warm start read only
# this process's shard entries; finally measures the streamed slab
# ingest of the rung's MDF bundle.  Prints one "LADDER {json}" line.
_CHILD = r"""
import json, os, sys, time
import numpy as np
N_PROCS = int(sys.argv[3]); PPP = int(sys.argv[4]); NX = int(sys.argv[5])
CACHE = sys.argv[6]; MDF = sys.argv[7]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + f" --xla_force_host_platform_device_count={PPP}")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from pcg_mpi_solver_tpu.parallel.distributed import (fetch_global,
                                                     init_distributed,
                                                     make_global_mesh)
if N_PROCS > 1:
    pid = init_distributed(coordinator_address=sys.argv[1],
                           num_processes=N_PROCS, process_id=int(sys.argv[2]))
else:
    pid = 0
from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.solver.driver import Solver
from pcg_mpi_solver_tpu.parallel.partition import BUILD_CALLS

class CapSink:
    def __init__(self): self.events = []
    def emit(self, ev): self.events.append(ev)
    def close(self): pass

model = make_cube_model(NX * N_PROCS, NX, NX, heterogeneous=True)
n_parts = N_PROCS * PPP
def cfg():
    return RunConfig(cache_dir=CACHE, partition_method="slab2",
                     solver=SolverConfig(tol=1e-6, max_iter=60),
                     time_history=TimeHistoryConfig(
                         time_step_delta=[0.0, 1.0], export_flag=False))
mesh = make_global_mesh()
s_cold = Solver(model, cfg(), mesh=mesh, n_parts=n_parts,
                backend="general")
# the acceptance denominator MUST be a real build: a pre-warmed cache
# dir would record partition_build_s ~ 0 and fabricate the ratio
assert s_cold.setup_cache == "cold", \
    f"ladder cold rung warm-hit the cache ({s_cold.setup_cache}) — " \
    "the rung cache dir must be fresh"
cold = {"setup_s": s_cold.setup_s,
        "partition_build_s": s_cold.partition_build_s,
        "cache": s_cold.setup_cache}
r = s_cold.step(1.0)
checksum = float(np.abs(fetch_global(s_cold.un, mesh)).sum())
b0 = dict(BUILD_CALLS)
cap = CapSink()
s_warm = Solver(model, cfg(), mesh=mesh, n_parts=n_parts,
                backend="general", recorder=MetricsRecorder(sinks=(cap,)))
assert s_warm.setup_cache == "warm", s_warm.setup_cache
assert BUILD_CALLS == b0, "warm start performed partition work"
ev = [e for e in cap.events if e.get("kind") == "cache"
      and e.get("shard")]
rng = s_warm._setup_range or (0, n_parts)
expect = list(range(rng[0], rng[1]))
assert ev and ev[0]["parts"] == expect, (ev, expect)
r2 = s_warm.step(1.0)
checksum2 = float(np.abs(fetch_global(s_warm.un, mesh)).sum())
assert checksum == checksum2, (checksum, checksum2)
warm = {"setup_s": s_warm.setup_s, "cache": s_warm.setup_cache,
        "entries": ev[0]["entries"], "parts": ev[0]["parts"]}
ingest = None
if MDF and os.path.isdir(MDF):
    from pcg_mpi_solver_tpu.models.mdf import IngestStats, read_mdf_slab

    st = IngestStats()
    t0 = time.perf_counter()
    read_mdf_slab(MDF, pid, N_PROCS, stats=st)
    ingest = {"peak_bytes": st.peak_bytes,
              "wall_s": time.perf_counter() - t0}
print("LADDER " + json.dumps({
    "pid": pid, "n_dof": int(model.n_dof), "flag": int(r.flag),
    "cold": cold, "warm": warm, "ingest": ingest,
    "checksum": checksum}), flush=True)
"""


def _log(msg: str) -> None:
    print(f"# setup_ladder: {msg}", file=sys.stderr, flush=True)


def _ensure(d: str) -> str:
    os.makedirs(d, exist_ok=True)
    return d


def _run_rung(n_procs: int, ppp: int, nx: int, cache_dir: str,
              mdf_dir: str, timeout_s: float):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "child.py")
        with open(script, "w") as f:
            f.write(_CHILD)
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = os.pathsep.join(
            [repo] + env.get("PYTHONPATH", "").split(os.pathsep))
        # child stdout goes to FILES, not pipes: the children form one
        # collective group, and a later child blocking on a full 64KB
        # pipe while the parent drains an earlier child's would wedge
        # the whole rung mid-collective
        logs = [open(os.path.join(td, f"child{i}.log"), "w+")
                for i in range(n_procs)]
        procs = [subprocess.Popen(
            [sys.executable, script, coord, str(i), str(n_procs),
             str(ppp), str(nx), cache_dir, mdf_dir],
            stdout=logs[i], stderr=subprocess.STDOUT, text=True,
            env=env) for i in range(n_procs)]
        outs = []
        try:
            deadline = time.monotonic() + timeout_s
            for p in procs:
                p.wait(timeout=max(1.0, deadline - time.monotonic()))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
            for f in logs:
                f.seek(0)
                outs.append(f.read())
                f.close()
    results = []
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(f"setup_ladder rung {n_procs}: child {i} "
                               f"failed:\n{out[-4000:]}")
        lines = [ln for ln in out.splitlines() if ln.startswith("LADDER ")]
        results.append(json.loads(lines[-1][len("LADDER "):]))
    return results


def run_ladder(rungs, *, nx: int, ppp: int, cache_dir: str,
               out_path: str, timeout_s: float = 900.0):
    """Run the ladder; returns the list of per-rung BENCH-schema lines
    (also printed to stdout and written to ``out_path``)."""
    # unique per-invocation subdir: rungs must COLD-build (the in-child
    # assert), then warm from their own entries; a previous session's
    # entries in a shared BENCH_CACHE_DIR must not pre-warm the
    # acceptance measurement.  Removed on exit — the rung models/MDF
    # bundles are measurement scratch (hundreds of MB at default sizes)
    # that evict_lru's flat-file scan would never reclaim.
    cache_dir = tempfile.mkdtemp(prefix="run_", dir=_ensure(cache_dir))
    lines = []
    try:
        return _run_rungs(rungs, nx, ppp, cache_dir, out_path,
                          timeout_s, lines)
    finally:
        import shutil

        shutil.rmtree(cache_dir, ignore_errors=True)


def _run_rungs(rungs, nx, ppp, cache_dir, out_path, timeout_s, lines):
    from pcg_mpi_solver_tpu.models.mdf import write_mdf
    from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
    from pcg_mpi_solver_tpu.obs.schema import BENCH_SCHEMA
    from pcg_mpi_solver_tpu.parallel.partition import partition_model

    for n in rungs:
        n_parts = n * ppp
        _log(f"rung {n}: {nx * n}x{nx}x{nx} cells, {n_parts} parts")
        model = make_cube_model(nx * n, nx, nx, heterogeneous=True)
        # serial reference: the monolithic full build of the SAME model
        # and the SAME two-level method — what every process pays today
        t0 = time.perf_counter()
        partition_model(model, n_parts, method="slab2", slab2_slabs=n)
        serial_s = time.perf_counter() - t0
        mdf_dir = os.path.join(cache_dir, f"ladder_mdf_{n}")
        if not os.path.isdir(mdf_dir):
            write_mdf(model, mdf_dir)
        res = _run_rung(n, ppp, nx, cache_dir, mdf_dir,
                        timeout_s=timeout_s)
        par_s = max(r["cold"]["partition_build_s"] for r in res)
        line = {
            "schema": BENCH_SCHEMA,
            "metric": "setup_partition_build",
            "value": round(par_s, 4),
            "unit": "s",
            "vs_baseline": round(serial_s / max(par_s, 1e-9), 3),
            "detail": {
                "procs": n,
                "n_parts": n_parts,
                "n_dof": res[0]["n_dof"],
                "partition_build_s": round(par_s, 4),
                "partition_serial_s": round(serial_s, 4),
                "cold_setup_s": round(
                    max(r["cold"]["setup_s"] for r in res), 4),
                "warm_setup_s": round(
                    max(r["warm"]["setup_s"] for r in res), 4),
                "ingest_peak_bytes": max(
                    (r["ingest"] or {}).get("peak_bytes", 0)
                    for r in res),
                "setup_cache": "warm",
                "pcg_variant": "classic",
            },
        }
        print(json.dumps(line), flush=True)
        lines.append(line)
    artifact = {"schema": BENCH_SCHEMA, "metric": "setup_ladder",
                "value": lines[-1]["vs_baseline"] if lines else 0.0,
                "unit": "x_vs_serial",
                "vs_baseline": lines[-1]["vs_baseline"] if lines else 0.0,
                "rungs": lines}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
        _log(f"artifact written: {out_path}")
    return lines


def main() -> int:
    rungs = [int(v) for v in
             os.environ.get("BENCH_SETUP_LADDER", "1,2,4").split(",")
             if v.strip()]
    nx = int(os.environ.get("BENCH_SETUP_NX", 40))
    ppp = int(os.environ.get("BENCH_SETUP_PPP", 2))
    cache = os.environ.get("BENCH_CACHE_DIR", "")
    own_tmp = None
    if not cache:
        cache = own_tmp = tempfile.mkdtemp(prefix="pcg_setup_ladder_")
    out = os.environ.get("BENCH_SETUP_OUT", "setup_ladder.json")
    timeout_s = float(os.environ.get("BENCH_SETUP_TIMEOUT_S", 900))
    try:
        run_ladder(rungs, nx=nx, ppp=ppp, cache_dir=cache, out_path=out,
                   timeout_s=timeout_s)
    finally:
        if own_tmp is not None:     # run_ladder removes only its run_
            import shutil           # subdir; the parent we made is ours

            shutil.rmtree(own_tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
