"""Telemetry schemas: the versioned contracts of every JSON artifact the
framework emits, plus the validators `tools/check_telemetry_schema.py` and
the tests run against committed artifacts.

Two families:

* **Telemetry events** (`TELEMETRY_SCHEMA`): one JSON object per line in a
  ``--telemetry-out`` JSONL stream, produced by
  :class:`pcg_mpi_solver_tpu.obs.metrics.MetricsRecorder`.  Every event
  carries ``schema`` / ``t`` (unix seconds) / ``kind``; the per-kind
  required fields are in :data:`EVENT_KINDS`.  Unknown kinds and extra
  fields are ALLOWED (forward compatibility) — consumers must ignore what
  they don't know; validators only reject missing required fields or a
  schema version they don't speak.

* **Bench result lines** (`BENCH_SCHEMA`): the one-line JSON contract of
  ``bench.py`` (`{"metric", "value", "unit", "vs_baseline", ...}`).  The
  ``schema`` key is new; committed pre-schema artifacts (BENCH_r0*.json)
  stay valid as *legacy* lines — required keys are checked either way.

This module must stay import-light (no jax, no numpy): bench.py imports it
before configuring the accelerator environment.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from pcg_mpi_solver_tpu.config import PCG_VARIANTS

# Bump the integer suffix on any BREAKING change (key removal/retyping);
# additive fields do not bump.
TELEMETRY_SCHEMA = "pcg-tpu-telemetry/1"
BENCH_SCHEMA = "pcg-tpu-bench/1"

KNOWN_TELEMETRY_SCHEMAS = (TELEMETRY_SCHEMA,)
KNOWN_BENCH_SCHEMAS = (BENCH_SCHEMA,)

# kind -> required field names (beyond the base schema/t/kind triplet).
EVENT_KINDS: Dict[str, tuple] = {
    # one line per completed solve step (quasi-static or Newmark)
    "step": ("step", "flag", "relres", "iters", "wall_s"),
    # one jitted device dispatch (cold = first call of this program,
    # i.e. the call that paid compile)
    "dispatch": ("name", "wall_s", "cold"),
    # per-iteration residual ring buffer, one host transfer per solve
    "resid_trace": ("step", "n_recorded", "truncated", "normr"),
    # free-form breadcrumb (the PCG_TPU_VERBOSE lineage)
    "note": ("msg",),
    # explicit-dynamics scan chunk
    "dynamics_chunk": ("steps", "wall_s"),
    # bench harness phase timing
    "bench_phase": ("name", "wall_s"),
    # one warm-path cache probe (cache/: partition load-or-build, AOT
    # step load-or-export); `hit` is the cold/warm attribution bit
    "cache": ("name", "hit", "key", "wall_s"),
    # one recovery-ladder attempt or guarded re-dispatch (resilience/):
    # action = restart_minres | fallback_prec | escalate_f64 |
    # redispatch; trigger = flag2 | flag4 | nan_carry | device_loss
    "recovery": ("action", "attempt", "trigger"),
    # one injected fault (resilience/faultinject.py — deterministic
    # chaos): mode = kill|exc|nan|inf|rho0, point = dispatch|boundary
    "fault": ("mode", "point", "at"),
    # one mid-Krylov snapshot operation (op = save | restore)
    "snapshot": ("op", "step"),
    # one timestep-granular snapshot operation of a dynamics/Newmark
    # time history (op = save | restore; resilience/engine.py)
    "step_snapshot": ("op", "step"),
    # one preflight gate run (validate/): the policy applied, the
    # fail/warn counts, and the full per-check results list
    "preflight": ("policy", "failed", "checks"),
    # end-of-step ladder summary (emitted only when recoveries happened)
    "recovery_done": ("flag", "attempts", "actions"),
    # one batched multi-RHS solve (Solver.solve_many): block width,
    # wall, per-column flags
    "solve_many": ("nrhs", "wall_s", "flags"),
    # per-RHS outcome of a batched solve — one event per column/tenant,
    # carrying the rhs (column) index
    "rhs_solve": ("rhs", "flag", "relres", "iters"),
    # one QUARANTINED column of a batched solve (resilience/): the
    # column's recovery budget was spent (or absent) on `trigger`; the
    # block completed anyway and the column reports flag 5 with its
    # min-residual iterate — the billing/ops signal for a pathological
    # tenant load case
    "rhs_quarantine": ("rhs", "trigger", "flag", "attempts"),
    # fused-variant residual drift (arXiv:2501.03743): deferred
    # true-residual checks that disagreed with the recurrence norm this
    # solve (`drift` = count; blocked solves add per-column `cols`) —
    # sustained drift also routes into the ladder as flag 6
    "resid_drift": ("drift",),
    # one MG-preconditioner setup (ops/mg.py, precond="mg"): hierarchy
    # shape (levels/degree/dims), the estimated per-level Chebyshev
    # bounds, whether the fine bound came from the partition cache, and
    # the setup wall — the cost side of the iteration-count win
    "mg_setup": ("levels", "degree", "wall_s"),
    # analytic per-iteration cost model (obs/perf.py): per-phase
    # FLOPs/HBM-bytes/collective resources + roofline-predicted ms/iter
    # for the engaged (pcg_variant, precond, nrhs, backend) — emitted at
    # solver construction so every telemetry stream carries the number
    # its measured ms/iter should be judged against
    "cost_model": ("pcg_variant", "precond", "nrhs", "backend", "phases",
                   "predicted_ms_per_iter"),
    # one measured phase-attribution probe run (obs/phases.py /
    # `pcg-tpu perf-report`): per-phase measured ms/iter (matvec /
    # precond / reduction / axpy), their sum, and the whole-iteration
    # anchor from the real solve program
    "phase_probe": ("pcg_variant", "precond", "phases",
                    "sum_ms_per_iter", "whole_ms_per_iter"),
    # one bounded profiler-trace capture (obs/profview.py
    # capture_solve_profile, or the driver's profile_dir bracket): the
    # on-disk artifact path — the pointer `summary` and post-mortems
    # follow to the trace a run left behind
    "profile_capture": ("path",),
    # one parsed device-trace report (obs/profview.py profile_report /
    # `pcg-tpu prof-report`): per-phase bucketed device-op wall time,
    # the measured collective-overlap fraction (null when the trace
    # carries no collectives), and the tolerant reader's verdict
    # ("ok" or "degraded: <named reason>" — a truncated artifact still
    # emits, it never crashes)
    "prof_report": ("source", "phases", "overlap_frac", "verdict"),
    # one crash-durable flight record (obs/flight.py — fsync-per-event):
    # op = meta | begin | heartbeat | end | fail; begin/end/fail carry
    # name+seq, every record carries the monotonic clock next to the
    # base wall `t` so a dead run's artifact says what was in flight and
    # when it last breathed, across host clock jumps
    "flight": ("op", "mono"),
    # sharded setup attribution (ISSUE 14): which contiguous part range
    # THIS process built/loaded (`parts` = [lo, hi)), whether the
    # partition came cold (built) or warm (shard cache), and the
    # partition-build wall — the per-process record the setup ladder
    # aggregates and the sharded-warm-start tests assert on
    "setup_shard": ("parts", "n_parts", "cold", "partition_build_s"),
    # one cross-process collective-skew attribution report (obs/fleet.py
    # fleet_report / `pcg-tpu fleet-report`, ISSUE 16): per-process
    # transport-vs-wait split over clock-aligned matched collectives,
    # the fleet-wide skew fraction (null when the capture carried no
    # cross-process skew — single process, no matched collectives), the
    # named straggler, and the tolerant verdict
    "fleet_report": ("source", "n_processes", "matched_collectives",
                     "skew_frac", "verdict"),
    # one live-monitor snapshot (obs/watch.py / `pcg-tpu watch`): the
    # run's liveness status (running | stalled | done | empty), shard
    # count, fleet-wide newest-record age, and the cost-model x
    # observed-rate ETA (null with a named reason in the rendering)
    "watch": ("path", "status", "n_shards", "silent_s", "eta_s"),
    # the monitor's stall alarm: ALL shards' heartbeats silent past the
    # threshold — `silent_s` is the newest record's age at detection,
    # `in_flight` the union of unclosed flight brackets (what the run
    # was doing when it wedged)
    "stall": ("path", "silent_s", "threshold_s", "in_flight"),
    # a deadline-guarded host collective expired
    # (resilience/distributed.GuardedComm, ISSUE 18): which labelled
    # round stalled, the configured deadline, and the most
    # flight-silent peer rank (-1 when no peer shard was readable) —
    # the record a DeadPeerError post-mortem starts from
    "collective_timeout": ("label", "deadline_s", "suspect"),
    # one group-consistent snapshot epoch
    # (resilience/distributed.GroupSnapshotStore two-phase commit):
    # epoch number, in-flight step, shard count, and whether the commit
    # marker was (or will be) published; op="restore" on the read side
    "snapshot_epoch": ("epoch", "step", "shards", "committed"),
    # an armed elastic resume accepted an ``n_procs`` fingerprint
    # mismatch (Solver.resume_elastic): the writing fleet's process
    # count, this fleet's, and which store took it (snap | many | ckpt)
    "elastic_resume": ("from_procs", "to_procs", "prefix"),
    # one ADMITTED solve-service job (serve/admission.py): its absolute
    # admission ordinal, the PR 12 cost-model price the admission was
    # judged against (predicted block seconds; null when the model is
    # unavailable — the pricing degrades to admit, never to a crash)
    # and the job's relative deadline
    "job_admit": ("job", "ordinal", "predicted_s", "deadline_s"),
    # one REJECTED admission with its NAMED reason
    # (deadline_infeasible | queue_full | draining | bad_spec) — the
    # no-silent-drops contract: a job the service will not run always
    # says why, in the stream and in its result file
    "job_reject": ("job", "reason"),
    # one load-SHED job (bounded-queue backpressure, serve/): the queue
    # was full and this already-admitted job was past its deadline, so
    # it was dropped — oldest first — with a named reason, never
    # silently
    "job_shed": ("job", "reason"),
    # one FINISHED solve-service job: ok = converged (flag 0); failed
    # jobs carry the named verdict ("injected: ..." for a chaos-
    # injected failure, "flagN" for a solver flag, "quarantined" for a
    # PR 8 column quarantine)
    "job_done": ("job", "ok", "verdict"),
    # a tenant's request quarantined without failing its co-batched
    # block: either the PR 8 per-column quarantine fired in-solve (the
    # event adds `rhs`, the column index) or the service boundary
    # caught a poisoned/non-finite RHS before dispatch
    "job_quarantine": ("job", "verdict"),
    # solve-service daemon drain/exit record (reason = sigterm | idle |
    # max_blocks): in-flight blocks finished, new admissions rejected,
    # journal closed clean — the graceful twin of the SIGKILL the job
    # journal replays through
    "serve_drain": ("reason",),
    # end-of-run counter/gauge/span snapshot
    "run_summary": ("counters", "gauges"),
}

BENCH_REQUIRED = ("metric", "value", "unit", "vs_baseline")

# Optional ``detail`` fields with a typed contract WHEN present (absent in
# pre-warm-path lines — committed BENCH_r0*.json stay valid).  Numeric-or-
# null: ``time_to_first_iter_s`` is null when no device dispatch happened
# (e.g. a solve that failed before its first jitted call).  ``nrhs`` /
# ``dof_iter_rhs_per_s`` are the batched multi-RHS A/B fields
# (BENCH_NRHS): the MEASURED block width of the line's numbers and the
# dof*iter*rhs/s throughput.  Scalar-solve lines (warm insurance,
# salvage) report nrhs=1 with the configured sweep width preserved under
# ``nrhs_planned`` — a line must never fabricate batched throughput that
# was not run.
#  ``time_to_tol_s`` (ROADMAP item 4) is the time-to-solution signal of
#  a leg: wall to CONVERGED-at-tol, null when the solve did not reach
#  tol — with ``iters`` it makes a preconditioner A/B (BENCH_PRECOND)
#  read as time-to-solution, not just dof*iter/s.  Both are emitted on
#  every leg, insurance/salvage lines included.
#  ``predicted_ms_per_iter`` / ``model_ratio`` (ISSUE 12) are the
#  analytic cost model's verdict on the line (obs/perf.py): the
#  roofline-predicted ms/iter for the line's engaged
#  (variant, precond, nrhs, platform) and measured/predicted — emitted
#  on EVERY leg, insurance/salvage included, so an interrupted window
#  still records how far off the model was.  Null when the model could
#  not be built (e.g. the zero-value error sentinel).
#  The ``setup_ladder`` leg (ISSUE 14, BENCH_SETUP_LADDER) stamps the
#  weak-scaling setup fields: ``procs`` (rung process count),
#  ``partition_build_s`` (max per-process sharded build wall),
#  ``partition_serial_s`` (the monolithic full build of the SAME model —
#  what every process would pay without the sharded path; the ratio is
#  the acceptance number), ``cold_setup_s``/``warm_setup_s`` (solver
#  setup wall on the cold vs shard-cache-warm start), and
#  ``ingest_peak_bytes`` (streamed slab ingest's peak host memory).
#  ``measured_ms_per_iter_matvec`` / ``overlap_frac`` (ISSUE 15,
#  obs/profview.py) are the PROFILED-leg fields (BENCH_PROFILE=1): the
#  trace-measured matvec ms/iter and the measured collective-overlap
#  fraction of the profiled warm solve.  ABSENT (not null) on
#  unprofiled legs, and on insurance/salvage lines emitted only when
#  the capture actually ran before the failure — a line must never
#  carry a measurement that was not taken.
#  ``skew_frac`` / ``straggler_rank`` (ISSUE 16, obs/fleet.py) are the
#  multi-controller PROFILED-leg fields: the fleet-wide fraction of
#  collective time spent blocked on stragglers and THIS process's rank
#  in the caused-wait ordering (0 = the straggler).  ABSENT (not null)
#  on single-process captures and whenever the fleet report carried no
#  matched collectives — same never-fabricate contract as the ISSUE 15
#  fields above.
#  ``jobs_per_s`` / ``jobs_per_s_serial`` / ``queue_depth_max`` /
#  ``jobs_shed`` (ISSUE 19, serve/) are the BENCH_SERVE=1 sustained-
#  throughput fields: completed jobs per second with the saturated
#  queue packing nrhs blocks, the one-at-a-time (width-1) dispatch
#  baseline the ratio is judged against, the deepest the bounded queue
#  got, and how many jobs backpressure shed.  ABSENT (not null) on
#  every other leg — a line must never fabricate service throughput
#  that was not served.
BENCH_DETAIL_NUMERIC = ("setup_s", "time_to_first_iter_s", "nrhs",
                        "nrhs_planned", "dof_iter_rhs_per_s",
                        "nrhs_quarantined", "nrhs_recoveries",
                        "time_to_tol_s", "iters",
                        "predicted_ms_per_iter", "model_ratio",
                        "procs", "partition_build_s",
                        "partition_serial_s", "cold_setup_s",
                        "warm_setup_s", "ingest_peak_bytes",
                        "measured_ms_per_iter_matvec", "overlap_frac",
                        "skew_frac", "straggler_rank",
                        "jobs_per_s", "jobs_per_s_serial",
                        "queue_depth_max", "jobs_shed")
# ``setup_cache``: warm-path partition attribution (cache/ subsystem).
BENCH_SETUP_CACHE_VALUES = ("off", "cold", "warm")
# ``pcg_variant``: the engaged PCG loop formulation of the line's
# numbers — the classic/fused/pipelined A/B axis (BENCH_PCG_VARIANT).
# Derived from the canonical config.PCG_VARIANTS name table (config.py
# is jax/numpy-free, so this module's import-light contract holds): a
# line claiming a variant no loop builder knows is a schema error, on
# measured AND insurance/salvage lines alike.
BENCH_PCG_VARIANT_VALUES = PCG_VARIANTS


def validate_event(ev: Any) -> List[str]:
    """Validate one telemetry event dict; returns a list of error strings
    (empty = valid)."""
    errs: List[str] = []
    if not isinstance(ev, dict):
        return [f"event is not an object: {type(ev).__name__}"]
    schema = ev.get("schema")
    if schema is None:
        errs.append("missing 'schema'")
    elif schema not in KNOWN_TELEMETRY_SCHEMAS:
        errs.append(f"unknown telemetry schema {schema!r}")
    if not isinstance(ev.get("t"), (int, float)):
        errs.append("missing/non-numeric 't'")
    kind = ev.get("kind")
    if not isinstance(kind, str) or not kind:
        errs.append("missing 'kind'")
        return errs
    for field in EVENT_KINDS.get(kind, ()):
        if field not in ev:
            errs.append(f"kind={kind}: missing required field {field!r}")
    return errs


def validate_bench_line(d: Any) -> List[str]:
    """Validate one bench result object (the parsed one-line JSON)."""
    errs: List[str] = []
    if not isinstance(d, dict):
        return [f"bench line is not an object: {type(d).__name__}"]
    for field in BENCH_REQUIRED:
        if field not in d:
            errs.append(f"missing required key {field!r}")
    if "value" in d and not isinstance(d["value"], (int, float)):
        errs.append(f"'value' is not numeric: {d['value']!r}")
    schema = d.get("schema")
    if schema is not None and schema not in KNOWN_BENCH_SCHEMAS:
        errs.append(f"unknown bench schema {schema!r}")
    detail = d.get("detail")
    if isinstance(detail, dict):
        for field in BENCH_DETAIL_NUMERIC:
            if field in detail and detail[field] is not None \
                    and not isinstance(detail[field], (int, float)):
                errs.append(f"detail.{field} is not numeric/null: "
                            f"{detail[field]!r}")
        sc = detail.get("setup_cache")
        if sc is not None and sc not in BENCH_SETUP_CACHE_VALUES:
            errs.append(f"detail.setup_cache not in "
                        f"{BENCH_SETUP_CACHE_VALUES}: {sc!r}")
        pv = detail.get("pcg_variant")
        if pv is not None and pv not in BENCH_PCG_VARIANT_VALUES:
            errs.append(f"detail.pcg_variant not in "
                        f"{BENCH_PCG_VARIANT_VALUES}: {pv!r}")
    # schema-less lines are legacy (pre-schema artifacts) — still valid.
    return errs


def validate_jsonl_text(text: str) -> List[str]:
    """Validate a telemetry JSONL payload line by line."""
    errs: List[str] = []
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        try:
            ev = json.loads(line)
        except ValueError as e:
            errs.append(f"line {ln}: not JSON ({e})")
            continue
        errs.extend(f"line {ln}: {e}" for e in validate_event(ev))
    return errs


def _find_bench_payload(doc: Any) -> Any:
    """Locate the metric object inside a committed BENCH_*.json artifact:
    either the raw one-line dict, or the round wrapper
    ``{"n", "cmd", "rc", "tail", "parsed": {...}}``."""
    if isinstance(doc, dict) and "metric" in doc:
        return doc
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return None


def validate_bench_text(text: str) -> List[str]:
    """Validate a BENCH_*.json artifact (raw line or round wrapper).

    A round wrapper whose bench run failed (``rc`` != 0, ``parsed`` null —
    BENCH_r01..r03 are committed examples) is a legitimate artifact: the
    driver captured a crash, not a malformed metric.  Only a wrapper that
    CLAIMS success (rc == 0) must carry a valid payload."""
    try:
        doc = json.loads(text)
    except ValueError as e:
        return [f"not JSON ({e})"]
    payload = _find_bench_payload(doc)
    if payload is None:
        if (isinstance(doc, dict) and "rc" in doc and "parsed" in doc
                and doc.get("parsed") is None and doc.get("rc") != 0):
            return []       # failed-round wrapper: no metric to validate
        return ["no bench metric object found (neither top-level nor "
                "under 'parsed')"]
    return validate_bench_line(payload)
