"""Cross-process collective-skew attribution over per-process profile
captures (ISSUE 16 tentpole) — the multi-host twin of
:mod:`~pcg_mpi_solver_tpu.obs.profview`'s single-capture report.

:func:`~pcg_mpi_solver_tpu.obs.profview.capture_solve_profile` writes one
subdir per controller (``p<idx>/…``) when ``jax.process_count() > 1``.
Each process's trace clock is local — the profiler timestamps carry an
arbitrary per-host origin — so the per-process timelines cannot be
compared directly.  But a collective is a synchronization point: every
participant leaves it at (physically) the same instant, so matched
collective END events are cross-process clock anchors.  The per-process
clock offset is the median end-time difference against process 0 over
every matched collective (median: robust to the handful of collectives a
profiler clips at a trace boundary).

With the timelines aligned, each matched collective's duration splits
into

* **transport** — the minimum duration across processes.  The process
  that arrived LAST did not wait for anyone; its duration is the pure
  wire/reduction cost.
* **wait** — each process's excess over transport: the time it sat
  blocked at the rendezvous because a straggler arrived late.

The straggler of a collective is therefore the process with the
*minimum* duration (it arrived last and waited least); the wait it
caused is the sum of every other process's excess.  Summed per phase
(``pcg/matvec`` vs ``pcg/reduce`` scope labels, same bucketing as
profview) this names WHICH host the weak-scaling latency comes from —
the number the pipelined variant exists to hide (arXiv:2105.06176).

Import-light on purpose (no jax/numpy): ``pcg-tpu fleet-report`` must
run on a laptop against a copied capture dir.  The clock-alignment
helper (:func:`align_offsets`) is shared with ``telemetry-merge
--align collectives`` (obs/flight.py), which applies the same
matched-anchor median to telemetry ``dispatch`` completions.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Mapping, Optional, Tuple

from pcg_mpi_solver_tpu.obs.profview import (
    _base_scope_map, device_ops, find_trace_files, is_collective,
    load_meta, phase_of, read_trace_events)

FLEET_SCHEMA = "pcg-tpu-fleet/1"

_PDIR_RE = re.compile(r"^p(\d+)$")


# ----------------------------------------------------------------------
# Generic matched-anchor clock alignment (shared with telemetry-merge)
# ----------------------------------------------------------------------

def align_offsets(anchors: Mapping[Any, Mapping[Any, float]]
                  ) -> Tuple[Dict[Any, float], int]:
    """Per-stream clock offsets from matched synchronization anchors.

    ``anchors`` maps stream id -> {anchor key: completion time}; an
    anchor key identifies the SAME synchronization event across streams
    (e.g. ``(collective base name, occurrence index)``).  Completion
    times share a unit but not an origin.  Returns ``(offsets,
    n_matched)`` where ``offsets[s]`` is the median of ``t_s - t_ref``
    over every anchor present in ALL streams (ref = lowest stream id,
    offset 0.0 by construction).  Subtracting ``offsets[s]`` from stream
    ``s`` timestamps puts every stream on the reference clock.  A stream
    is given offset 0.0 (unaligned) when fewer than one anchor matches.
    """
    ids = sorted(anchors)
    offsets: Dict[Any, float] = {s: 0.0 for s in ids}
    if len(ids) < 2:
        return offsets, 0
    ref = ids[0]
    shared = set(anchors[ref])
    for s in ids[1:]:
        shared &= set(anchors[s])
    for s in ids[1:]:
        deltas = sorted(anchors[s][k] - anchors[ref][k] for k in shared)
        if deltas:
            m = len(deltas) // 2
            offsets[s] = (deltas[m] if len(deltas) % 2
                          else 0.5 * (deltas[m - 1] + deltas[m]))
    return offsets, len(shared)


# ----------------------------------------------------------------------
# Capture discovery + per-process collective sequences
# ----------------------------------------------------------------------

def discover_process_dirs(root: str) -> List[Tuple[int, str]]:
    """``(process index, dir)`` pairs under a capture root: the
    ``p<idx>/`` subdirs capture_solve_profile writes on multi-controller
    runs, or ``[(0, root)]`` when the root itself holds a single
    process's trace (degraded single-process mode)."""
    out: List[Tuple[int, str]] = []
    if os.path.isdir(root):
        for name in sorted(os.listdir(root)):
            m = _PDIR_RE.match(name)
            d = os.path.join(root, name)
            if m and os.path.isdir(d) and find_trace_files(d):
                out.append((int(m.group(1)), d))
    if out:
        return sorted(out)
    if find_trace_files(root):
        return [(0, root)]
    return []


def collective_occurrences(ops: List[dict]) -> Dict[Tuple[str, int], dict]:
    """One representative per (collective base, occurrence index) for a
    single process's device ops.

    On a real TPU pod slice each local device is a trace lane
    (``pid``/``tid``) and the SAME program collective appears once per
    lane; on the forced-host CPU mesh all virtual devices usually share
    one lane.  Occurrences are counted per lane in timestamp order, then
    the k-th occurrences are aggregated across lanes: ``end`` = max end
    (the process leaves the rendezvous when its slowest lane does),
    ``dur`` = max duration, ``ts`` = min start.  The representative op
    dict keeps the max-duration lane's ``text`` for phase attribution.
    """
    lanes: Dict[Tuple[Any, Any], Dict[str, List[dict]]] = {}
    for op in ops:
        if not is_collective(op["base"]):
            continue
        lane = lanes.setdefault((op.get("pid"), op.get("tid")), {})
        lane.setdefault(op["base"], []).append(op)
    reps: Dict[Tuple[str, int], dict] = {}
    for lane in lanes.values():
        for base, evs in lane.items():
            evs.sort(key=lambda o: o["ts"])
            for k, op in enumerate(evs):
                key = (base, k)
                rep = reps.get(key)
                end = op["ts"] + op["dur"]
                if rep is None:
                    reps[key] = {"base": base, "name": op["name"],
                                 "text": op.get("text", ""),
                                 "ts": op["ts"], "dur": op["dur"],
                                 "end": end, "lanes": 1}
                else:
                    rep["ts"] = min(rep["ts"], op["ts"])
                    rep["end"] = max(rep["end"], end)
                    if op["dur"] > rep["dur"]:
                        rep["dur"] = op["dur"]
                        rep["name"] = op["name"]
                        rep["text"] = op.get("text", "")
                    rep["lanes"] += 1
    return reps


def _load_process(pdir: str) -> Tuple[Optional[dict], List[str]]:
    """Parse one process's newest trace: ``{"colls", "meta", "n_ops"}``
    plus the tolerant reader's problem list (never raises)."""
    files = find_trace_files(pdir)
    if not files:
        return None, [f"{pdir}: no trace files"]
    events, problems = read_trace_events(files[0])
    ops = device_ops(events)
    colls = collective_occurrences(ops)
    return ({"dir": pdir, "trace": files[0], "colls": colls,
             "meta": load_meta(files[0]), "n_ops": len(ops)},
            [f"{os.path.basename(pdir)}: {p}" for p in problems])


# ----------------------------------------------------------------------
# The fleet report
# ----------------------------------------------------------------------

def fleet_report(root: str) -> Dict[str, Any]:
    """Cross-process skew attribution over a capture root (see module
    docstring for the alignment + transport/wait model).  Tolerant: a
    missing process dir, an unreadable trace, or a collective-free
    capture degrades the verdict by name — it never raises."""
    problems: List[str] = []
    pdirs = discover_process_dirs(root)
    procs: Dict[int, dict] = {}
    for idx, pdir in pdirs:
        info, probs = _load_process(pdir)
        problems.extend(probs)
        if info is not None:
            procs[idx] = info
    report: Dict[str, Any] = {
        "schema": FLEET_SCHEMA, "source": root,
        "n_processes": len(procs),
        "processes": {}, "phases": {},
        "matched_collectives": 0, "skew_frac": None,
        "transport_ms": 0.0, "wait_ms": 0.0,
        "straggler": None, "clock_offsets_ms": {},
        "iters": None, "verdict": "ok",
    }
    if not procs:
        problems.append("no per-process captures found")
        report["verdict"] = "degraded: " + "; ".join(problems)
        return report
    meta0 = next((procs[i]["meta"] for i in sorted(procs)
                  if procs[i]["meta"]), None)
    iters = None
    if meta0:
        try:
            iters = int(meta0.get("iters") or 0) or None
        except (TypeError, ValueError):
            iters = None
    report["iters"] = iters
    if len(procs) == 1:
        idx = next(iter(procs))
        report["processes"][str(idx)] = {
            "dir": procs[idx]["dir"], "coll_ms": round(sum(
                c["dur"] for c in procs[idx]["colls"].values()) / 1e3, 3),
            "wait_ms": None, "transport_ms": None, "skew_frac": None,
            "wait_ms_per_iter": None, "caused_wait_ms": None,
            "straggler_rank": None}
        problems.append("single-process capture (no cross-process skew)")
        report["verdict"] = "degraded: " + "; ".join(problems)
        return report

    # -- clock alignment over matched collective END anchors -----------
    ids = sorted(procs)
    anchors = {i: {k: c["end"] for k, c in procs[i]["colls"].items()}
               for i in ids}
    offsets_us, n_matched = align_offsets(anchors)
    report["clock_offsets_ms"] = {
        str(i): round(offsets_us[i] / 1e3, 3) for i in ids}
    report["matched_collectives"] = n_matched
    if n_matched == 0:
        problems.append("no matched collectives across processes")
        report["verdict"] = "degraded: " + "; ".join(problems)
        return report

    shared = set(anchors[ids[0]])
    for i in ids[1:]:
        shared &= set(anchors[i])

    # per-process phase maps for attribution
    base_maps = {}
    scope_maps = {}
    for i in ids:
        sm = (procs[i]["meta"] or {}).get("scope_map") or {}
        scope_maps[i] = sm
        base_maps[i] = _base_scope_map(sm) if sm else {}

    per_proc = {i: {"coll_us": 0.0, "wait_us": 0.0,
                    "caused_wait_us": 0.0, "straggler_hits": 0}
                for i in ids}
    phases: Dict[str, dict] = {}
    transport_us_total = 0.0
    for key in sorted(shared):
        durs = {i: procs[i]["colls"][key]["dur"] for i in ids}
        transport = min(durs.values())
        transport_us_total += transport
        waits = {i: durs[i] - transport for i in ids}
        slow = min(ids, key=lambda i: durs[i])   # arrived last, waited least
        caused = sum(waits.values())
        phase = None
        for i in ids:
            phase = phase_of(procs[i]["colls"][key], scope_maps[i],
                             base_maps[i])
            if phase is not None:
                break
        ph = phases.setdefault(phase or "other", {
            "matched": 0, "wait_ms": 0.0,
            "caused_wait_us": {i: 0.0 for i in ids}})
        ph["matched"] += 1
        ph["wait_ms"] += caused / 1e3
        ph["caused_wait_us"][slow] += caused
        for i in ids:
            per_proc[i]["coll_us"] += durs[i]
            per_proc[i]["wait_us"] += waits[i]
        per_proc[slow]["caused_wait_us"] += caused
        per_proc[slow]["straggler_hits"] += 1

    coll_us_total = sum(p["coll_us"] for p in per_proc.values())
    wait_us_total = sum(p["wait_us"] for p in per_proc.values())
    ranking = sorted(ids, key=lambda i: (-per_proc[i]["caused_wait_us"],
                                         i))
    report["transport_ms"] = round(transport_us_total / 1e3, 3)
    report["wait_ms"] = round(wait_us_total / 1e3, 3)
    report["skew_frac"] = round(wait_us_total / coll_us_total, 4) \
        if coll_us_total > 0 else None
    if per_proc[ranking[0]]["caused_wait_us"] > 0:
        report["straggler"] = str(ranking[0])
    for rank, i in enumerate(ranking):
        pp = per_proc[i]
        report["processes"][str(i)] = {
            "dir": procs[i]["dir"],
            "coll_ms": round(pp["coll_us"] / 1e3, 3),
            "wait_ms": round(pp["wait_us"] / 1e3, 3),
            "transport_ms": round(transport_us_total / 1e3, 3),
            "skew_frac": round(pp["wait_us"] / pp["coll_us"], 4)
            if pp["coll_us"] > 0 else None,
            "wait_ms_per_iter": round(pp["wait_us"] / 1e3 / iters, 4)
            if iters else None,
            "caused_wait_ms": round(pp["caused_wait_us"] / 1e3, 3),
            "straggler_hits": pp["straggler_hits"],
            "straggler_rank": rank,
        }
    for name, ph in phases.items():
        prank = sorted(ids, key=lambda i: (-ph["caused_wait_us"][i], i))
        report["phases"][name] = {
            "matched": ph["matched"],
            "wait_ms": round(ph["wait_ms"], 3),
            "straggler": str(prank[0])
            if ph["caused_wait_us"][prank[0]] > 0 else None,
            "ranking": [str(i) for i in prank],
        }
    if problems:
        report["verdict"] = "degraded: " + "; ".join(problems)
    return report


# ----------------------------------------------------------------------
# Rendering + telemetry emission
# ----------------------------------------------------------------------

def format_fleet_report(report: Dict[str, Any]) -> str:
    """Human-readable fleet report (``pcg-tpu fleet-report``)."""
    lines = [f"fleet report: {report['source']}",
             f"  processes: {report['n_processes']}   "
             f"matched collectives: {report['matched_collectives']}   "
             f"iters: {report['iters'] if report['iters'] else '?'}"]
    offs = report.get("clock_offsets_ms") or {}
    if offs:
        lines.append("  clock offsets vs p0 (ms): "
                     + "  ".join(f"p{i}={offs[i]:+.3f}"
                                 for i in sorted(offs, key=int)))
    if report.get("skew_frac") is not None:
        lines.append(f"  transport {report['transport_ms']:.3f} ms   "
                     f"wait {report['wait_ms']:.3f} ms   "
                     f"skew_frac {report['skew_frac']:.4f}")
    procs = report.get("processes") or {}
    if procs:
        lines.append("  proc   coll_ms    wait_ms  skew_frac  "
                     "wait_ms/iter  caused_ms  rank")
        for i in sorted(procs, key=int):
            p = procs[i]

            def _f(v, fmt):
                return format(v, fmt) if v is not None else "-"

            lines.append(
                f"  p{i:<4} {_f(p['coll_ms'], '9.3f')}  "
                f"{_f(p['wait_ms'], '9.3f')}  {_f(p['skew_frac'], '9.4f')}  "
                f"{_f(p.get('wait_ms_per_iter'), '12.4f')}  "
                f"{_f(p.get('caused_wait_ms'), '9.3f')}  "
                f"{_f(p.get('straggler_rank'), 'd')}")
    for name in sorted(report.get("phases") or {}):
        ph = report["phases"][name]
        who = f"p{ph['straggler']}" if ph["straggler"] is not None \
            else "none (balanced)"
        lines.append(f"  phase {name:<10} matched {ph['matched']:>4}  "
                     f"wait {ph['wait_ms']:9.3f} ms  straggler {who}")
    if report.get("straggler") is not None:
        lines.append(f"  straggler: p{report['straggler']}")
    lines.append(f"  verdict: {report['verdict']}")
    return "\n".join(lines)


def emit_fleet_report(recorder, report: Dict[str, Any]) -> None:
    """One schema-versioned ``fleet_report`` telemetry event + gauges."""
    recorder.event(
        "fleet_report", source=report["source"],
        n_processes=report["n_processes"],
        matched_collectives=report["matched_collectives"],
        skew_frac=report["skew_frac"], straggler=report["straggler"],
        processes=report["processes"], phases=report["phases"],
        clock_offsets_ms=report["clock_offsets_ms"],
        verdict=report["verdict"])
    if report["skew_frac"] is not None:
        recorder.gauge("fleet.skew_frac", report["skew_frac"])
    for i, p in (report.get("processes") or {}).items():
        if p.get("wait_ms_per_iter") is not None:
            recorder.gauge(f"fleet.wait_ms_per_iter.p{i}",
                           p["wait_ms_per_iter"])


def bench_detail_fields(report: Dict[str, Any],
                        process_index: int = 0) -> Dict[str, Any]:
    """The ``detail.skew_frac`` / ``detail.straggler_rank`` bench fields
    for THIS process, or ``{}`` when the capture carried no cross-process
    skew (single process, no matched collectives) — a bench line must
    never carry a measurement that was not taken."""
    if report.get("skew_frac") is None:
        return {}
    p = (report.get("processes") or {}).get(str(process_index))
    if p is None or p.get("straggler_rank") is None:
        return {}
    return {"skew_frac": report["skew_frac"],
            "straggler_rank": p["straggler_rank"]}


def load_fleet_report(path: str) -> Optional[Dict[str, Any]]:
    """Read a previously saved fleet report JSON; None when absent or
    not a fleet report."""
    try:
        with open(path, encoding="utf-8") as f:
            rep = json.load(f)
    except (OSError, ValueError):
        return None
    return rep if isinstance(rep, dict) \
        and rep.get("schema") == FLEET_SCHEMA else None
