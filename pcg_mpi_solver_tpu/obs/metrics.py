"""Step metrics registry: counters, gauges, wall-time spans and structured
events, fanned out to pluggable sinks (stderr, JSONL, and — opt-in — a
``jax.profiler`` trace annotation around each device dispatch).

This is the ONE logging path of the framework: the old ``_vlog`` stderr
breadcrumbs of ``solver/driver.py`` are now ``note`` events through a
:class:`MetricsRecorder`, with ``PCG_TPU_VERBOSE=1`` kept as the alias
that enables the stderr sink on the default recorder.

Design constraints:

* Host-side only.  Nothing here touches device buffers; enabling telemetry
  adds zero device<->host transfers per PCG iteration (the in-graph
  residual trace lives in ``obs/trace.py`` and is fetched once per solve).
* Import-light.  ``bench.py`` imports this module before configuring the
  accelerator environment, so jax is imported lazily and only when the
  opt-in profiler spans are enabled.
* A recorder with no sinks is a cheap null object: counters/spans still
  accumulate (for the ``--summary`` table) but nothing is formatted or
  written.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from pcg_mpi_solver_tpu.obs.schema import TELEMETRY_SCHEMA


def _jsonable(v):
    """Best-effort coercion for numpy scalars/arrays without importing
    numpy: anything with .item()/.tolist() degrades to builtins."""
    if hasattr(v, "tolist"):
        return v.tolist()
    if hasattr(v, "item"):
        return v.item()
    return str(v)


class StderrSink:
    """Human breadcrumbs on stderr.

    Every event gets the historical ``[pcg-tpu HH:MM:SS]`` prefix so
    dispatch-hang forensics on tunneled TPUs keep working (the original
    ``_vlog`` contract); note events print their message body verbatim
    after it (bench.py's ``# ...`` lines keep their shape).
    """

    def __init__(self, stream=None):
        self._stream = stream

    def emit(self, ev: Dict[str, Any]) -> None:
        stream = self._stream if self._stream is not None else sys.stderr
        kind = ev.get("kind", "?")
        if kind == "note":
            body = str(ev.get("msg", ""))
        else:
            skip = ("schema", "t", "kind")
            parts = []
            for k, v in ev.items():
                if k in skip:
                    continue
                if isinstance(v, (list, dict)):
                    v = f"<{len(v)} entries>"
                elif isinstance(v, float):
                    v = f"{v:.6g}"
                parts.append(f"{k}={v}")
            body = f"{kind}: " + " ".join(parts)
        print(f"[pcg-tpu {time.strftime('%H:%M:%S')}] {body}",
              file=stream, flush=True)

    def close(self) -> None:
        pass


class EnvGatedStderrSink(StderrSink):
    """StderrSink active only while ``PCG_TPU_VERBOSE=1``, sampled PER
    EVENT — matching the removed ``_vlog``'s per-call env check, so a
    long-lived process can turn breadcrumbs on after the Solver was
    constructed (the hung-dispatch forensics workflow)."""

    def emit(self, ev: Dict[str, Any]) -> None:
        if os.environ.get("PCG_TPU_VERBOSE") == "1":
            super().emit(ev)


class JsonlSink:
    """Schema-versioned JSONL event stream: one JSON object per line,
    flushed per event so a killed run still leaves a parseable file."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def emit(self, ev: Dict[str, Any]) -> None:
        self._f.write(json.dumps(ev, default=_jsonable) + "\n")
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except ValueError:
            pass


class MetricsRecorder:
    """Counters + gauges + monotonic wall-time spans + structured events.

    All mutation goes through a lock: the solver may be driven from a
    thread while exports run elsewhere.  Events are dicts with the base
    triplet ``schema``/``t``/``kind`` (see ``obs/schema.py``).
    """

    def __init__(self, sinks=(), profile_spans: bool = False,
                 clock=time.monotonic):
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, Any] = {}
        self.sinks: List[Any] = list(sinks)
        # Optional crash-durable flight recorder (obs/flight.py): when
        # attached, every dispatch span is bracketed by fsync'd
        # begin/end flight records so a SIGKILL mid-dispatch leaves a
        # parseable artifact naming the in-flight program.
        self.flight = None
        self.profile_spans = bool(profile_spans)
        self._clock = clock
        self._spans: Dict[str, List[float]] = {}    # name -> [count, total_s]
        # per-dispatch-name: [calls, cold_s, warm_s] — the first call of a
        # jitted program pays XLA compile, so cold vs warm IS the
        # compile-time vs execute-time split per dispatch.
        self._dispatch: Dict[str, List[float]] = {}
        self.step_events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    # -- construction ---------------------------------------------------
    @classmethod
    def default(cls, jsonl_path: Optional[str] = None,
                profile: Optional[bool] = None) -> "MetricsRecorder":
        """The solver-facing factory: stderr breadcrumbs gated on
        ``PCG_TPU_VERBOSE=1`` per event (the historical knob, checked at
        every emit like the old ``_vlog`` so it can be flipped on a LIVE
        process), JSONL sink iff a path is given, profiler spans iff
        requested (or ``PCG_TPU_PROFILE_SPANS=1``)."""
        sinks: List[Any] = [EnvGatedStderrSink()]
        if jsonl_path:
            # Multi-process jax.distributed: each process appends to its
            # OWN shard (run.jsonl -> run.p<idx>.jsonl) — interleaved
            # appends from N processes would corrupt a shared file.
            # Single-process paths are untouched; `pcg-tpu
            # telemetry-merge` reassembles the shards.  Lazy import, no
            # jax side effects (shard_jsonl_path only consults an
            # already-imported jax).
            from pcg_mpi_solver_tpu.obs.flight import shard_jsonl_path

            sinks.append(JsonlSink(shard_jsonl_path(jsonl_path)))
        if profile is None:
            profile = os.environ.get("PCG_TPU_PROFILE_SPANS") == "1"
        return cls(sinks=sinks, profile_spans=bool(profile))

    def add_sink(self, sink) -> None:
        with self._lock:
            self.sinks.append(sink)

    def remove_sink(self, sink) -> None:
        """Detach a sink added with :meth:`add_sink`; idempotent (the
        bench's one-shot first-dispatch sink detaches best-effort on
        every exit path)."""
        with self._lock:
            try:
                self.sinks.remove(sink)
            except ValueError:
                pass

    def close(self) -> None:
        fl = self.flight
        if fl is not None:
            fl.close()
        for s in self.sinks:
            close = getattr(s, "close", None)
            if close:
                close()

    # -- registry -------------------------------------------------------
    def inc(self, name: str, delta: float = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def gauge(self, name: str, value) -> None:
        with self._lock:
            self.gauges[name] = value

    # -- events ---------------------------------------------------------
    def event(self, kind: str, **fields) -> Dict[str, Any]:
        ev = {"schema": TELEMETRY_SCHEMA, "t": time.time(), "kind": kind}
        ev.update(fields)
        # sink emission stays UNDER the lock: concurrent emitters (solver
        # thread + a watchdog note) must not interleave mid-line in a
        # shared JSONL stream
        with self._lock:
            if kind == "step":
                self.step_events.append(ev)
            for s in self.sinks:
                s.emit(ev)
        return ev

    def note(self, msg: str) -> None:
        self.event("note", msg=msg)

    # -- timing ---------------------------------------------------------
    @contextmanager
    def span(self, name: str, emit: bool = False):
        """Accumulate monotonic wall time under ``name``; ``emit=True``
        additionally emits a ``bench_phase`` event on exit."""
        t0 = self._clock()
        try:
            yield
        finally:
            dt = self._clock() - t0
            with self._lock:
                st = self._spans.setdefault(name, [0, 0.0])
                st[0] += 1
                st[1] += dt
            if emit:
                self.event("bench_phase", name=name, wall_s=round(dt, 6))

    @contextmanager
    def dispatch(self, name: str, emit: bool = True):
        """Wrap one jitted device dispatch: cold/warm attribution (first
        call of a program = the call that paid XLA compile) and, when
        ``profile_spans`` is on, a ``jax.profiler.TraceAnnotation`` so the
        dispatch shows up as a named region in profiler traces.

        Caller contract: jax dispatch is ASYNC — keep a blocking
        device->host fetch (``int(scalar)``, ``float(scalar)``,
        ``block_until_ready``) inside the span, otherwise wall_s measures
        enqueue time, not execution."""
        with self._lock:
            st = self._dispatch.setdefault(name, [0, 0.0, 0.0])
            cold = st[0] == 0
            st[0] += 1
        if self.profile_spans:
            import jax  # deferred: bench configures env before jax init

            ann = jax.profiler.TraceAnnotation(f"pcg-tpu/{name}")
        else:
            ann = None
        # Flight bracket (obs/flight.py): the begin record is fsync'd
        # BEFORE the dispatch runs, so a tunnel death / SIGKILL inside
        # the device call leaves "dispatch:<name> in flight" on disk —
        # the round-5 artifact an operator used to reconstruct by hand.
        flight = self.flight
        seq = (flight.begin(f"dispatch:{name}", cold=cold)
               if flight is not None else None)
        t0 = self._clock()
        ok = True
        err = None
        try:
            if ann is not None:
                with ann:
                    yield
            else:
                yield
        except BaseException as e:
            ok = False
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            dt = self._clock() - t0
            with self._lock:
                st = self._dispatch[name]
                st[1 if cold else 2] += dt
            self.inc(f"dispatch.{name}.calls")
            if flight is not None:
                flight.end(seq, f"dispatch:{name}", ok=ok,
                           wall_s=round(dt, 6),
                           **({"error": err} if err else {}))
            if emit:
                self.event("dispatch", name=name, wall_s=round(dt, 6),
                           cold=cold)

    # -- snapshots ------------------------------------------------------
    def dispatch_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-program compile vs execute attribution: ``cold_s`` is the
        first call (compile + one execution), ``warm_s`` the rest."""
        with self._lock:
            return {k: {"calls": int(v[0]), "cold_s": v[1], "warm_s": v[2]}
                    for k, v in self._dispatch.items()}

    def span_stats(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: {"calls": int(v[0]), "total_s": v[1]}
                    for k, v in self._spans.items()}

    def reset_dispatch_attribution(self) -> None:
        """Forget per-program cold/warm state.  Call when the programs
        behind the dispatch names are REBUILT (e.g. a solver
        reconstruction after a failed kernel path): the next call of each
        name pays XLA compile again and must be booked as cold.  The
        ``dispatch.<name>.calls`` counters reset too, so snapshot() stays
        internally consistent."""
        with self._lock:
            self._dispatch.clear()
            for k in [k for k in self.counters
                      if k.startswith("dispatch.")]:
                del self.counters[k]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
        return {"counters": counters, "gauges": gauges,
                "spans": self.span_stats(),
                "dispatches": self.dispatch_stats()}

    def emit_run_summary(self) -> Dict[str, Any]:
        return self.event("run_summary", **self.snapshot())

    def summary(self) -> str:
        """Human-readable end-of-run table (the CLI ``--summary`` output)."""
        lines = []
        if self.step_events:
            lines.append(f"{'step':>5} {'flag':>4} {'iters':>7} "
                         f"{'relres':>10} {'wall_s':>9}")
            for ev in self.step_events:
                lines.append(
                    f"{ev.get('step', '?'):>5} {ev.get('flag', '?'):>4} "
                    f"{ev.get('iters', '?'):>7} "
                    f"{ev.get('relres', float('nan')):>10.3e} "
                    f"{ev.get('wall_s', float('nan')):>9.3f}")
        ds = self.dispatch_stats()
        if ds:
            lines.append("")
            lines.append(f"{'dispatch':<24} {'calls':>6} {'cold_s':>9} "
                         f"{'warm_s':>9}")
            for name in sorted(ds):
                d = ds[name]
                lines.append(f"{name:<24} {d['calls']:>6} "
                             f"{d['cold_s']:>9.3f} {d['warm_s']:>9.3f}")
        with self._lock:
            gauges = dict(self.gauges)
            counters = dict(self.counters)
        extra = {k: v for k, v in counters.items()
                 if not k.startswith("dispatch.")}
        if gauges:
            lines.append("")
            lines.extend(f"gauge {k} = {gauges[k]}" for k in sorted(gauges))
        if extra:
            lines.extend(f"counter {k} = {extra[k]}" for k in sorted(extra))
        return "\n".join(lines) if lines else "(no telemetry recorded)"


def summarize_jsonl(path: str) -> str:
    """Offline ``--summary`` of an on-disk telemetry/flight JSONL
    artifact — INCLUDING the exact artifact a dead tunnel produces: a
    truncated trailing line is skipped and counted (``truncated_lines``),
    never raised on (obs/flight.read_jsonl_tolerant).

    Rebuilds the live summary's tables from the event stream: the
    per-step table, per-dispatch cold/warm aggregation, per-kind event
    counts, the last run_summary's gauges, and — when flight records are
    present — the mechanical verdict (clean / failed / died-in-flight
    with the unclosed record names and last heartbeat)."""
    from pcg_mpi_solver_tpu.obs.flight import (
        flight_verdict_path, read_jsonl_tolerant)

    events, truncated = read_jsonl_tolerant(path)
    lines = [f"{path}: {len(events)} event(s), "
             f"truncated_lines = {truncated}"]
    kinds: Dict[str, int] = {}
    for ev in events:
        k = str(ev.get("kind", "?"))
        kinds[k] = kinds.get(k, 0) + 1
    if kinds:
        lines.append("  " + "  ".join(f"{k}={kinds[k]}"
                                      for k in sorted(kinds)))
    steps = [ev for ev in events if ev.get("kind") == "step"]
    if steps:
        lines.append("")
        lines.append(f"{'step':>5} {'flag':>4} {'iters':>7} "
                     f"{'relres':>10} {'wall_s':>9}")
        for ev in steps:
            try:
                relres = float(ev.get("relres", float("nan")))
                wall = float(ev.get("wall_s", float("nan")))
            except (TypeError, ValueError):
                relres = wall = float("nan")
            lines.append(
                f"{ev.get('step', '?'):>5} {ev.get('flag', '?'):>4} "
                f"{ev.get('iters', '?'):>7} {relres:>10.3e} "
                f"{wall:>9.3f}")
    disp: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("kind") != "dispatch":
            continue
        st = disp.setdefault(str(ev.get("name", "?")), [0, 0.0, 0.0])
        st[0] += 1
        try:
            w = float(ev.get("wall_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            w = 0.0
        st[1 if ev.get("cold") else 2] += w
    if disp:
        lines.append("")
        lines.append(f"{'dispatch':<24} {'calls':>6} {'cold_s':>9} "
                     f"{'warm_s':>9}")
        for name in sorted(disp):
            d = disp[name]
            lines.append(f"{name:<24} {int(d[0]):>6} {d[1]:>9.3f} "
                         f"{d[2]:>9.3f}")
    caps = [ev for ev in events if ev.get("kind") == "profile_capture"]
    if caps:
        lines.append("")
        for ev in caps:
            lines.append(f"profile artifact: {ev.get('path')} "
                         f"(read it back with `pcg-tpu prof-report`)")
    summaries = [ev for ev in events if ev.get("kind") == "run_summary"]
    if summaries:
        gauges = summaries[-1].get("gauges") or {}
        if isinstance(gauges, dict) and gauges:
            lines.append("")
            lines.extend(f"gauge {k} = {gauges[k]}"
                         for k in sorted(gauges))
    if any(ev.get("kind") == "flight" for ev in events):
        # flight_verdict_path folds a final heartbeat cut mid-write back
        # into last_wall/last_mono (salvaged_tail): a shard killed while
        # writing its newest beat must read as alive until then, not as
        # having died a heartbeat interval earlier
        v = flight_verdict_path(path)
        lines.append("")
        lines.append(f"flight verdict: {v['verdict']} "
                     f"({v['records']} record(s))")
        if v["in_flight"]:
            lines.append("  in flight at death: "
                         + ", ".join(v["in_flight"]))
        for msg in v["fails"]:
            lines.append(f"  fail: {msg}")
        for msg in v.get("expected_fails", []):
            lines.append(f"  expected descent: {msg}")
        if v["last_wall"] is not None:
            lines.append(f"  last record at t={v['last_wall']:.3f} "
                         f"(mono {v['last_mono']})"
                         + (" [salvaged from the truncated final line]"
                            if v.get("salvaged_tail") else ""))
    if truncated:
        lines.append(f"({truncated} truncated line(s) skipped — the "
                     "partial write of a killed process)")
    return "\n".join(lines)
