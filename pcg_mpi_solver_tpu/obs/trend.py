"""Bench-trend regression sentinel: mechanical before/after verdicts
over the committed ``BENCH_r*.json`` series.

Every hardware round commits one artifact (the round wrapper
``{"n", "cmd", "rc", "tail", "parsed"}`` — ``parsed`` is the final
metric line, the ``tail`` often carries additional insurance/salvage
lines), but until this module nothing ever COMPARED them: whether a
round regressed against its predecessor was log archaeology.  The
sentinel:

* tolerantly extracts every bench line from each artifact (round
  wrapper ``parsed``, JSON lines embedded in ``tail``, or a raw
  one-line artifact like ``bench_provisional.json``), skipping the
  zero-value error sentinels and failed-round wrappers (rc != 0,
  parsed null — themselves legitimate artifacts, per obs/schema.py);

* matches legs across rounds by SHAPE AND CONFIGURATION — (metric,
  model, n_dof, mode, backend, pcg_variant, precond, nrhs) — so a
  144^3 mg leg never compares against the 150^3 jacobi flagship, and
  pre-schema lines (no pcg_variant/precond fields) match under the
  historical defaults (classic/jacobi/nrhs=1);

* prints per-leg deltas with threshold-based verdicts — ``regressed``
  (new value < old * (1 - threshold)), ``improved``, ``flat`` — plus
  the unmatched singletons, and reports an exit code that reflects
  regressions, so every future hardware window and CI run gets a
  mechanical answer (``pcg-tpu trend``; the hw_session priority queue
  logs the verdict line after its profiled rung).

Higher-is-better is the contract of every ``value`` the bench emits
(dof*iter/s throughput); a future lower-is-better metric must be added
to :data:`LOWER_IS_BETTER` or its verdicts would invert silently.

Import-light by contract (no jax, no numpy): the hw_session queue and
CI call this before any accelerator environment exists.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

#: default relative-change threshold separating flat from
#: regressed/improved (10%: past rounds show single-digit-% run-to-run
#: noise on the 1-core host; override with --threshold / threshold=).
DEFAULT_THRESHOLD = 0.10

#: metrics where a SMALLER value is the better one.  Everything the
#: bench emits today is a throughput (higher-better); the set exists so
#: adding a latency metric is a one-line change, not a silent inversion.
LOWER_IS_BETTER = frozenset()


def iter_bench_lines(path: str) -> List[dict]:
    """Every parseable bench metric line in one artifact, deduplicated.
    Tolerates every committed artifact shape: the round wrapper (parsed
    + tail-embedded lines), a raw one-line metric file, and the failed
    rounds (rc != 0, parsed null) which simply contribute nothing."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return []
    lines: List[dict] = []

    def add(obj):
        if not isinstance(obj, dict) or "metric" not in obj:
            return
        try:
            value = float(obj.get("value", 0))
        except (TypeError, ValueError):
            return
        if value <= 0:
            return                  # the zero-value error sentinel
        if any(o is obj or (o.get("metric"), o.get("value")) ==
               (obj.get("metric"), obj.get("value")) for o in lines):
            return                  # tail often repeats the parsed line
        lines.append(obj)

    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        add(doc)                                    # raw one-line artifact
        if isinstance(doc.get("parsed"), dict):
            add(doc["parsed"])                      # round wrapper
        # a FAILED round's tail (rc != 0) may still carry provisional/
        # insurance lines emitted before the death — they are not that
        # round's measurement and must not become the leg's newest
        # value (the failed-round-contributes-nothing contract)
        tail = doc.get("tail", "") if doc.get("rc", 0) == 0 else ""
    else:
        tail = text                                 # JSONL-ish fallback
    for ln in str(tail).splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            add(json.loads(ln))
        except ValueError:
            continue
    return lines


def leg_key(line: dict) -> Tuple:
    """The cross-round matching identity of one bench line: shape +
    configuration.  Pre-schema lines (no variant/precond/nrhs fields)
    match under the historical defaults — BENCH_r01..r05 predate those
    knobs and all measured classic/jacobi/nrhs=1."""
    d = line.get("detail") or {}
    return (
        str(line.get("metric", "?")),
        str(d.get("model", "?")),
        int(d.get("n_dof", 0) or 0),
        str(d.get("mode", "?")),
        str(d.get("backend", "?")),
        str(d.get("pcg_variant") or "classic"),
        str(d.get("precond") or "jacobi"),
        int(d.get("nrhs", 1) or 1),
    )


def _key_label(key: Tuple) -> str:
    metric, model, n_dof, mode, backend, variant, precond, nrhs = key
    return (f"{model}/{n_dof} {mode} {backend} {variant}+{precond}"
            + (f" nrhs={nrhs}" if nrhs != 1 else ""))


def default_series(root: str = ".") -> List[str]:
    """The committed round artifacts, in round order."""
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def trend_report(paths: List[str], fresh: Optional[str] = None,
                 threshold: float = DEFAULT_THRESHOLD) -> Dict[str, Any]:
    """Match legs across the artifact series (plus an optional fresh
    artifact appended as the newest round) and compute per-leg deltas
    and verdicts.  Returns the report dict ``format_report`` renders;
    ``regressed`` > 0 is the non-zero-exit condition."""
    series: List[Tuple[str, dict]] = []
    sources: List[Dict[str, Any]] = []
    for p in list(paths) + ([fresh] if fresh else []):
        label = os.path.basename(p)
        lines = iter_bench_lines(p)
        sources.append({"path": p, "label": label, "lines": len(lines)})
        for ln in lines:
            series.append((label, ln))

    by_key: Dict[Tuple, List[Tuple[str, dict]]] = {}
    for label, ln in series:
        by_key.setdefault(leg_key(ln), []).append((label, ln))

    legs: List[Dict[str, Any]] = []
    counts = {"regressed": 0, "improved": 0, "flat": 0, "single": 0}
    for key in sorted(by_key):
        entries = by_key[key]
        # ONE representative per round: a round's artifact often carries
        # the final line NEXT TO insurance/salvage near-duplicates of
        # the same leg — comparing two lines of the same round would
        # shadow (and silently mask) the cross-round regression.  The
        # representative is the round's best value: the round's real
        # measurement, with its conservative insurance twins below it.
        per_round: Dict[str, dict] = {}
        order: List[str] = []
        for label, ln in entries:
            if label not in per_round:
                order.append(label)
                per_round[label] = ln
            elif float(ln["value"]) > float(per_round[label]["value"]):
                per_round[label] = ln
        if len(order) < 2:
            counts["single"] += 1
            label = order[0]
            legs.append({"leg": _key_label(key), "verdict": "single",
                         "old_round": None, "old_value": None,
                         "new_round": label,
                         "new_value": float(per_round[label]["value"]),
                         "delta_pct": None})
            continue
        old_label, new_label = order[-2], order[-1]
        old, new = per_round[old_label], per_round[new_label]
        ov, nv = float(old["value"]), float(new["value"])
        delta = (nv - ov) / ov if ov else 0.0
        better = -delta if key[0] in LOWER_IS_BETTER else delta
        verdict = ("regressed" if better < -threshold
                   else "improved" if better > threshold else "flat")
        counts[verdict] += 1
        legs.append({"leg": _key_label(key), "verdict": verdict,
                     "old_round": old_label, "old_value": ov,
                     "new_round": new_label, "new_value": nv,
                     "delta_pct": round(delta * 100.0, 2),
                     "rounds_seen": len(order)})
    return {"schema": "pcg-tpu-trend/1", "threshold": threshold,
            "sources": sources, "legs": legs, **counts}


def verdict_line(report: Dict[str, Any]) -> str:
    """One-line summary (the hw_session log line).  A zero-matched-leg
    series says so by NAME — a gate must be able to tell a vacuous pass
    from a genuinely flat comparison."""
    matched = (report["regressed"] + report["improved"] + report["flat"])
    head = ("REGRESSED" if report["regressed"]
            else "improved" if report["improved"]
            else "flat" if matched else "no matched legs")
    return (f"{head} — {matched} matched leg(s): "
            f"{report['regressed']} regressed, "
            f"{report['improved']} improved, {report['flat']} flat "
            f"({report['single']} unmatched singleton(s); "
            f"threshold {report['threshold']:.0%})")


def format_report(report: Dict[str, Any]) -> str:
    lines = []
    for s in report["sources"]:
        lines.append(f">{s['label']}: {s['lines']} bench line(s)")
    lines.append("")
    lines.append(f"{'leg':<48} {'old':>12} {'new':>12} {'delta':>8} "
                 f"verdict")
    for leg in report["legs"]:
        old = (f"{leg['old_value']:.3g}" if leg["old_value"] is not None
               else "-")
        delta = (f"{leg['delta_pct']:+.1f}%"
                 if leg["delta_pct"] is not None else "-")
        mark = {"regressed": " <-- REGRESSION", "improved": " (better)",
                }.get(leg["verdict"], "")
        lines.append(f"{leg['leg']:<48} {old:>12} "
                     f"{leg['new_value']:>12.3g} {delta:>8} "
                     f"{leg['verdict']}{mark}")
    lines.append("")
    lines.append("trend verdict: " + verdict_line(report))
    return "\n".join(lines)


def main_cli(paths: List[str], fresh: Optional[str] = None,
             threshold: float = DEFAULT_THRESHOLD) -> int:
    """The ``pcg-tpu trend`` body: print the table, return the exit
    code — 1 = at least one regressed matched leg; 2 = nothing to
    compare at all (no artifacts, or no artifact carried a single
    bench line); 0 otherwise (including a series of unmatched
    singletons, which the verdict line names as 'no matched legs'
    rather than 'flat')."""
    if not paths:
        paths = default_series()
    if not paths:
        print("trend: no BENCH_r*.json artifacts found (pass paths, or "
              "run from the repo root)")
        return 2
    report = trend_report(paths, fresh=fresh, threshold=threshold)
    print(format_report(report))
    if all(s["lines"] == 0 for s in report["sources"]):
        print("trend: no bench lines in any artifact — nothing to "
              "compare")
        return 2
    return 1 if report["regressed"] else 0
