"""Solver telemetry: in-graph convergence traces (``obs.trace``), a step
metrics registry (``obs.metrics``) and the versioned artifact schemas
(``obs.schema``).

``obs.schema`` and ``obs.metrics`` are import-light (no jax/numpy) so
``bench.py`` can use them before configuring the accelerator environment;
``obs.trace`` imports jax and is loaded lazily here.
"""

from pcg_mpi_solver_tpu.obs.flight import (
    FlightRecorder, flight_verdict, merge_shards, read_jsonl_tolerant,
    shard_jsonl_path)
from pcg_mpi_solver_tpu.obs.metrics import (
    JsonlSink, MetricsRecorder, StderrSink, summarize_jsonl)
from pcg_mpi_solver_tpu.obs.schema import BENCH_SCHEMA, TELEMETRY_SCHEMA

_TRACE_NAMES = ("ConvergenceTrace", "clamp_trace_len", "empty_trace",
                "trace_host_init", "trace_init", "trace_record",
                "trace_specs", "unpack_trace")

__all__ = ["BENCH_SCHEMA", "TELEMETRY_SCHEMA", "FlightRecorder",
           "JsonlSink", "MetricsRecorder", "StderrSink",
           "flight_verdict", "merge_shards", "read_jsonl_tolerant",
           "shard_jsonl_path", "summarize_jsonl", *_TRACE_NAMES]


def __getattr__(name):
    if name in _TRACE_NAMES:
        from pcg_mpi_solver_tpu.obs import trace

        return getattr(trace, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
