"""Live run monitor over flight/telemetry JSONL shards (ISSUE 16
tentpole, operator side): ``pcg-tpu watch PATH`` tails the stream a
RUNNING solve is writing and answers the three questions an operator of
a multi-hour flagship run actually has — is it alive, how far along is
it, and when will it finish.

* **alive** — every shard's newest record timestamp (heartbeats
  included, and a final heartbeat cut mid-write still counts via
  :func:`~pcg_mpi_solver_tpu.obs.flight.salvage_truncated_tail`).  A
  single silent shard is a per-shard warning; a **stall** is flagged
  only when ALL shards have gone silent past the threshold — on a
  multi-controller run one slow host is skew (obs/fleet.py's job), but
  everyone silent means the run is wedged (dead tunnel, hung
  collective, SIGSTOP'd process).
* **progress** — per-dispatch counters and the completed-step residual
  table from ``step`` / ``dispatch`` / ``resid_trace`` events, plus the
  newest note (the driver narrates chunk boundaries through notes).
* **ETA** — the PR 12 analytic cost model's ``predicted_ms_per_iter``
  (the ``cost_model`` event every stream carries) × the iterations the
  OBSERVED convergence rate says remain: the residual decay is fit
  log-linearly over the newest residual series (``resid_trace`` when
  present, else the completed steps' ``relres``), so the estimate is
  model-paced but data-rated.  Every input is optional; a missing one
  degrades the ETA to a named reason, never a crash.

Pointed at a solve-service journal (``pcg-tpu watch
spool/journal.jsonl``, ISSUE 19) the same snapshot additionally folds
the job lifecycle: per-op counts, the in-flight job set, and the
graceful-drain marker (a drained journal reads DONE — silence after the
drain record is the expected end state, while a SIGKILLed daemon's
journal keeps its ``serve`` bracket open and trips the same stall alarm
over the missing heartbeats).

Import-light by contract (no jax/numpy): watching must work from a
laptop over an rsync'd artifact dir, and from ``tools/hw_session.py``
before any accelerator env is configured.  Read-side only — the monitor
NEVER writes to the watched stream (its own telemetry goes to a
separate ``--telemetry-out`` sink).
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Dict, List, Optional

from pcg_mpi_solver_tpu.obs.flight import (
    DEFAULT_HEARTBEAT_S, find_shards, flight_verdict,
    read_jsonl_tolerant, salvage_truncated_tail)

#: Default stall threshold = this many heartbeat intervals of
#: fleet-wide silence (the heartbeat cadence is the stream's liveness
#: contract; 3x tolerates one lost-to-truncation beat plus scheduler
#: jitter without crying wolf).
STALL_HEARTBEATS = 3.0


def stall_threshold_s(stall_after_s: Optional[float] = None) -> float:
    """Resolve the stall threshold: an explicit ``--stall-after`` wins,
    else ``STALL_HEARTBEATS`` x the configured heartbeat cadence (same
    env override the writer honors)."""
    if stall_after_s is not None and stall_after_s > 0:
        return float(stall_after_s)
    try:
        hb = float(os.environ.get("PCG_TPU_FLIGHT_HEARTBEAT_S",
                                  DEFAULT_HEARTBEAT_S))
    except ValueError:
        hb = DEFAULT_HEARTBEAT_S
    return STALL_HEARTBEATS * max(hb, 0.05)


def _shard_status(path: str, now: float) -> Dict[str, Any]:
    """One shard's liveness + flight state (tolerant, never raises)."""
    events, truncated = read_jsonl_tolerant(path)
    last_t = None
    done = False
    for ev in events:
        t = ev.get("t")
        if isinstance(t, (int, float)):
            last_t = t if last_t is None else max(last_t, t)
        if ev.get("kind") == "run_summary":
            done = True
    tail = salvage_truncated_tail(path)
    if tail and isinstance(tail.get("t"), (int, float)):
        if last_t is None or tail["t"] > last_t:
            last_t = tail["t"]
    fv = flight_verdict(events)
    return {"path": path, "events": events, "truncated": truncated,
            "last_t": last_t,
            "silent_s": (now - last_t) if last_t is not None else None,
            "in_flight": fv["in_flight"], "done": done,
            "salvaged_tail": bool(tail)}


def _residual_series(events: List[Dict[str, Any]]
                     ) -> List[float]:
    """Newest residual decay series (relative, monotone index = one CG
    iteration): the last ``resid_trace`` event's ``normr`` ring when
    present, else the completed steps' ``relres`` (one entry per step —
    coarser, but the same decades-per-iteration fit applies with the
    per-step iteration counts)."""
    for ev in reversed(events):
        if ev.get("kind") == "resid_trace":
            normr = ev.get("normr")
            if isinstance(normr, list):
                vals = [float(v) for v in normr
                        if isinstance(v, (int, float)) and v > 0]
                if len(vals) >= 2:
                    return vals
    return []


def _rate_decades_per_iter(events: List[Dict[str, Any]]
                           ) -> Optional[float]:
    """Observed convergence rate in residual decades per iteration
    (negative = converging); None when the stream carries no usable
    series."""
    vals = _residual_series(events)
    if len(vals) >= 2 and vals[0] > 0 and vals[-1] > 0:
        return (math.log10(vals[-1]) - math.log10(vals[0])) \
            / (len(vals) - 1)
    # fall back to completed steps: relres over cumulative iters
    pts = []
    iters_cum = 0
    for ev in events:
        if ev.get("kind") != "step":
            continue
        it = ev.get("iters")
        rr = ev.get("relres")
        if isinstance(it, (int, float)) and isinstance(rr, (int, float)) \
                and rr > 0 and it > 0:
            iters_cum += int(it)
            pts.append((iters_cum, math.log10(rr)))
    if len(pts) >= 2 and pts[-1][0] > pts[0][0]:
        return (pts[-1][1] - pts[0][1]) / (pts[-1][0] - pts[0][0])
    return None


def _serve_section(events: List[Dict[str, Any]]
                   ) -> Optional[Dict[str, Any]]:
    """Fold serve-journal records (ISSUE 19: job-lifecycle ops tagged
    with the ``journal`` schema field) into per-op counts + the in-
    flight job set; None when the stream is not a serve journal.  The
    daemon's liveness rides the same heartbeats the stall detector
    already watches — this section adds the per-job progress."""
    from pcg_mpi_solver_tpu.serve.journal import (
        DRAIN_OP, JOB_OPS, TERMINAL_OPS)

    counts: Dict[str, int] = {}
    in_flight: Dict[str, str] = {}
    drained = False
    drain_reason = None
    for ev in events:
        if ev.get("kind") != "flight" or not ev.get("journal"):
            continue
        op = ev.get("op")
        if op == DRAIN_OP:
            drained = True
            drain_reason = ev.get("reason")
            continue
        if op not in JOB_OPS:
            continue
        counts[op] = counts.get(op, 0) + 1
        jobs = ev.get("jobs") if isinstance(ev.get("jobs"), list) \
            else [ev.get("job")]
        for job in jobs:
            if not isinstance(job, str):
                continue
            if op in TERMINAL_OPS:
                in_flight.pop(job, None)
            else:
                in_flight[job] = op
    if not counts and not drained:
        return None
    return {"jobs": counts, "in_flight": sorted(in_flight),
            "drained": drained, "drain_reason": drain_reason}


def watch_snapshot(path: str, now: Optional[float] = None,
                   stall_after_s: Optional[float] = None,
                   tol: float = 1e-8) -> Dict[str, Any]:
    """One monitor snapshot of a (possibly running) run's JSONL stream.

    ``path`` is the base telemetry/flight path; all on-disk ``.pN``
    shards are tailed (multi-shard, truncation-tolerant — the `summary`
    contract).  Status: ``empty`` (no shards / no events), ``done`` (a
    ``run_summary`` landed and nothing is in flight), ``stalled`` (ALL
    shards silent past the threshold), else ``running``.  ``tol`` is the
    convergence target the ETA aims the observed rate at (the stream
    does not carry the run's tol; the default matches SolverConfig's and
    the rendering names the assumption)."""
    now = time.time() if now is None else now
    threshold = stall_threshold_s(stall_after_s)
    paths = find_shards(path)
    shards = [_shard_status(p, now) for p in paths]
    all_events: List[Dict[str, Any]] = []
    for sh in shards:
        all_events.extend(sh["events"])
    all_events.sort(key=lambda ev: ev["t"]
                    if isinstance(ev.get("t"), (int, float)) else -math.inf)

    dispatches: Dict[str, int] = {}
    steps: List[Dict[str, Any]] = []
    last_note = None
    predicted_ms = None
    last_relres = None
    for ev in all_events:
        kind = ev.get("kind")
        if kind == "dispatch":
            name = str(ev.get("name"))
            dispatches[name] = dispatches.get(name, 0) + 1
        elif kind == "step":
            steps.append({k: ev.get(k) for k in
                          ("step", "flag", "relres", "iters", "wall_s")})
            if isinstance(ev.get("relres"), (int, float)):
                last_relres = float(ev["relres"])
        elif kind == "note":
            last_note = str(ev.get("msg"))
        elif kind == "cost_model":
            pm = ev.get("predicted_ms_per_iter")
            if isinstance(pm, (int, float)):
                predicted_ms = float(pm)

    vals = _residual_series(all_events)
    if vals:
        last_relres = vals[-1] / vals[0]
    rate = _rate_decades_per_iter(all_events)
    eta_s = None
    eta_reason = None
    if predicted_ms is None:
        eta_reason = "no cost_model event in stream"
    elif rate is None:
        eta_reason = "no residual series yet (rate unknown)"
    elif rate >= 0:
        eta_reason = "residual not converging (rate >= 0)"
    elif last_relres is None or last_relres <= tol:
        eta_reason = "already at tol" if last_relres is not None \
            else "no residual observed"
    else:
        iters_left = math.log10(last_relres / tol) / (-rate)
        eta_s = round(iters_left * predicted_ms / 1e3, 3)

    serve = _serve_section(all_events)
    live = [sh for sh in shards if sh["last_t"] is not None]
    silent = [sh for sh in shards
              if sh["silent_s"] is None or sh["silent_s"] > threshold]
    done = bool(live) and all(sh["done"] for sh in live) \
        and not any(sh["in_flight"] for sh in live)
    # a gracefully-drained serve journal is DONE, not stalled: the
    # daemon stamped its drain record and closed the bracket — silence
    # after that is the expected end state, not a wedged run
    if serve is not None and serve["drained"] \
            and not any(sh["in_flight"] for sh in live):
        done = bool(live)
    if not live:
        status = "empty"
    elif done:
        status = "done"
    elif len(silent) == len(shards):
        status = "stalled"
    else:
        status = "running"
    min_silent = min((sh["silent_s"] for sh in live
                      if sh["silent_s"] is not None), default=None)
    return {
        "path": path, "status": status, "now": now,
        "stall_after_s": threshold, "tol": tol,
        "n_shards": len(shards),
        "silent_s": round(min_silent, 3) if min_silent is not None
        else None,
        "shards": [{k: sh[k] for k in
                    ("path", "truncated", "last_t", "silent_s",
                     "in_flight", "done", "salvaged_tail")}
                   for sh in shards],
        "serve": serve,
        "dispatches": dispatches, "steps": steps,
        "last_note": last_note, "last_relres": last_relres,
        "rate_decades_per_iter": round(rate, 5) if rate is not None
        else None,
        "predicted_ms_per_iter": predicted_ms,
        "eta_s": eta_s, "eta_reason": eta_reason,
    }


def format_watch(snap: Dict[str, Any]) -> str:
    """Human rendering of one :func:`watch_snapshot`."""
    lines = [f"watch: {snap['path']}   status: {snap['status'].upper()}"
             f"   shards: {snap['n_shards']}"
             f"   stall threshold: {snap['stall_after_s']:.1f}s"]
    for sh in snap["shards"]:
        age = f"{sh['silent_s']:.1f}s ago" if sh["silent_s"] is not None \
            else "never"
        extra = ""
        if sh["in_flight"]:
            extra += "  in flight: " + ", ".join(sh["in_flight"])
        if sh["salvaged_tail"]:
            extra += "  (tail salvaged from truncated line)"
        elif sh["truncated"]:
            extra += f"  ({sh['truncated']} truncated line(s))"
        if sh["done"]:
            extra += "  done"
        lines.append(f"  shard {os.path.basename(sh['path'])}: "
                     f"last record {age}{extra}")
    srv = snap.get("serve")
    if srv is not None:
        ops = "  ".join(f"{k}={v}" for k, v in sorted(srv["jobs"].items()))
        lines.append(f"  serve jobs: {ops}" if ops else "  serve jobs: -")
        if srv["in_flight"]:
            lines.append("  in-flight jobs: "
                         + ", ".join(srv["in_flight"]))
        if srv["drained"]:
            lines.append(f"  serve drained ({srv['drain_reason']})")
    if snap["dispatches"]:
        disp = "  ".join(f"{k}x{v}"
                         for k, v in sorted(snap["dispatches"].items()))
        lines.append(f"  dispatches: {disp}")
    for st in snap["steps"][-5:]:
        rr = st.get("relres")
        rr = f"{rr:.3e}" if isinstance(rr, (int, float)) else "?"
        lines.append(f"  step {st.get('step')}: flag={st.get('flag')} "
                     f"relres={rr} iters={st.get('iters')} "
                     f"wall={st.get('wall_s')}s")
    if snap["last_note"]:
        lines.append(f"  last note: {snap['last_note']}")
    rr = snap["last_relres"]
    if rr is not None:
        rate = snap["rate_decades_per_iter"]
        lines.append(f"  residual: {rr:.3e}"
                     + (f"   rate: {rate:+.4f} decades/iter"
                        if rate is not None else ""))
    if snap["eta_s"] is not None:
        lines.append(f"  ETA to tol={snap['tol']:.0e} (assumed): "
                     f"~{snap['eta_s']:.1f}s "
                     f"(cost model {snap['predicted_ms_per_iter']:.3f} "
                     f"ms/iter x observed rate)")
    else:
        lines.append(f"  ETA: n/a ({snap['eta_reason']})")
    if snap["status"] == "stalled":
        lines.append(f"  STALL: all {snap['n_shards']} shard(s) silent "
                     f"> {snap['stall_after_s']:.1f}s "
                     f"(newest record {snap['silent_s']:.1f}s ago)")
    return "\n".join(lines)


def emit_watch_events(recorder, snap: Dict[str, Any]) -> None:
    """Monitor telemetry: one ``watch`` event per snapshot, plus a
    ``stall`` event when the fleet has gone silent."""
    recorder.event("watch", path=snap["path"], status=snap["status"],
                   n_shards=snap["n_shards"], silent_s=snap["silent_s"],
                   eta_s=snap["eta_s"])
    if snap["status"] == "stalled":
        recorder.event("stall", path=snap["path"],
                       silent_s=snap["silent_s"],
                       threshold_s=snap["stall_after_s"],
                       in_flight=sorted({n for sh in snap["shards"]
                                         for n in sh["in_flight"]}))
