"""In-graph convergence tracing: a fixed-size ring buffer threaded through
the PCG carry, recording per-iteration ``normr`` / ``rho`` / ``stag`` /
``flag`` ON DEVICE inside the ``lax.while_loop``.

The whole point is cost profile: with tracing ON the per-iteration cost is
four dynamic-index scalar stores into device-resident arrays (no psum —
the recorded scalars are already replicated reduction results), and the
buffer crosses to the host ONCE per solve (it rides the resumable carry
across dispatch chunks, so even a billion-DOF chunked solve makes one
transfer).  With tracing OFF nothing is threaded at all: the carry pytree
is unchanged and the compiled program is bit-identical to pre-telemetry.

The ring length is static (shapes must be); when a solve runs longer than
the ring, the oldest entries are overwritten and :func:`unpack_trace`
returns the LAST ``length`` iterations in order, flagged ``truncated``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

TRACE_FIELDS = ("normr", "rho", "stag", "flag")


def clamp_trace_len(length: int, max_iter: int) -> int:
    """Ring sizes are clamped to [1, max_iter]: a ring longer than the
    iteration budget only wastes HBM, and zero/negative lengths are the
    caller's 'off' encoding (callers gate on > 0 before init)."""
    return max(1, min(int(length), max(int(max_iter), 1)))


def trace_init(length: int, dtype=jnp.float32) -> dict:
    """Empty device ring buffer.  ``dtype`` is the float dtype for
    normr/rho (use the solve's dot dtype; f32 for mixed-mode inner
    iterations, whose recorded values are rescaled to absolute residuals
    via ``trace_scale``)."""
    length = max(1, int(length))
    return dict(
        normr=jnp.zeros((length,), dtype),
        rho=jnp.zeros((length,), dtype),
        stag=jnp.zeros((length,), jnp.int32),
        flag=jnp.zeros((length,), jnp.int32),
        n=jnp.asarray(0, jnp.int32),
    )


def trace_host_init(length: int, dtype=np.float32) -> dict:
    """Host (numpy) twin of :func:`trace_init` for call sites that feed a
    jitted program its initial trace from the host (chunked mixed path)."""
    length = max(1, int(length))
    return dict(
        normr=np.zeros((length,), dtype),
        rho=np.zeros((length,), dtype),
        stag=np.zeros((length,), np.int32),
        flag=np.zeros((length,), np.int32),
        n=np.asarray(0, np.int32),
    )


def trace_specs(rep_spec) -> dict:
    """shard_map PartitionSpecs: every ring field is a replicated scalar
    stream (the recorded values are post-psum reduction results)."""
    return dict(normr=rep_spec, rho=rep_spec, stag=rep_spec, flag=rep_spec,
                n=rep_spec)


def trace_record(tr: dict, *, normr, rho, stag, flag, scale=None) -> dict:
    """Functional ring-buffer append (one slot per committed iteration).
    ``scale`` rescales the recorded residual norm (mixed-mode inner solves
    iterate on r/||r||; scale=||r|| restores absolute residuals)."""
    length = tr["normr"].shape[0]
    idx = jnp.mod(tr["n"], length)
    v = normr if scale is None else normr * scale
    return dict(
        normr=tr["normr"].at[idx].set(v.astype(tr["normr"].dtype)),
        rho=tr["rho"].at[idx].set(rho.astype(tr["rho"].dtype)),
        stag=tr["stag"].at[idx].set(stag.astype(jnp.int32)),
        flag=tr["flag"].at[idx].set(flag.astype(jnp.int32)),
        n=tr["n"] + 1,
    )


class ConvergenceTrace(NamedTuple):
    """Host-side unpacked trace, oldest -> newest."""

    normr: np.ndarray          # per-iteration residual norm (absolute)
    rho: np.ndarray            # per-iteration z.r inner product
    stag: np.ndarray           # stagnation counter
    flag: np.ndarray           # flag decided AT that iteration (1 = running)
    n_recorded: int            # total iterations recorded (>= len(normr)
    #                            when the ring wrapped)
    truncated: bool            # True when older entries were overwritten

    def to_event_fields(self, step: int) -> dict:
        """The ``resid_trace`` telemetry event payload for this trace."""
        return dict(step=step, n_recorded=int(self.n_recorded),
                    truncated=bool(self.truncated),
                    normr=[float(v) for v in self.normr],
                    rho=[float(v) for v in self.rho],
                    stag=[int(v) for v in self.stag],
                    flag=[int(v) for v in self.flag])


def empty_trace() -> ConvergenceTrace:
    z = np.zeros((0,))
    zi = np.zeros((0,), np.int32)
    return ConvergenceTrace(z, z.copy(), zi, zi.copy(), 0, False)


def unpack_trace(tr: dict) -> ConvergenceTrace:
    """Device/host ring dict -> ordered :class:`ConvergenceTrace`.  Call
    once per solve (this is THE host transfer when given device arrays)."""
    n = int(np.asarray(tr["n"]))
    arrs = {k: np.asarray(tr[k]) for k in TRACE_FIELDS}
    length = arrs["normr"].shape[0]
    if n <= length:
        sel = np.arange(n)
    else:
        sel = (np.arange(length) + n) % length
    return ConvergenceTrace(
        normr=arrs["normr"][sel], rho=arrs["rho"][sel],
        stag=arrs["stag"][sel], flag=arrs["flag"][sel],
        n_recorded=n, truncated=n > length)
