"""Crash-durable performance flight recorder + tolerant JSONL ingest.

The round-5 post-mortem (docs/HW_SESSION.log, BENCH_r05.json provenance
note) is the design brief: a tunnel died mid-timed-dispatch and a HUMAN
reconstructed the round's numbers out of the session log by hand.  This
module makes that artifact mechanical in both directions:

* **Writing** (:class:`FlightRecorder`): an append-only JSONL stream
  where every record is ``flush`` + ``os.fsync``'d the moment it is
  written, so a SIGKILL / tunnel death / power loss can lose AT MOST the
  record being written — never a completed one.  Records bracket work
  (``begin`` / ``end`` / ``fail``) and a daemon thread emits periodic
  ``heartbeat`` records carrying BOTH the monotonic and the wall clock
  while any bracket is open, so a dead run's artifact says *what* was in
  flight and *when* it was last alive — even across a host clock jump.

* **Reading** (:func:`read_jsonl_tolerant`, :func:`flight_verdict`):
  the exact artifact a dead tunnel produces is a JSONL file whose LAST
  line may be cut mid-object.  The tolerant reader skips unparseable
  lines and reports their count instead of raising; the verdict
  classifier turns the event list into the mechanical answer the
  operator used to dig out by hand: ``clean`` (every bracket closed),
  ``failed`` (a bracket closed with an error), or ``died`` (a bracket
  never closed — the process was killed mid-flight), with the in-flight
  record names and last-heartbeat timestamps attached.

Import-light by contract (no jax, no numpy): ``bench.py`` and
``tools/hw_session.py`` use this module before the accelerator
environment is configured.  Flight records are ordinary telemetry
events (``kind="flight"``, obs/schema.py) so every existing JSONL
consumer can ingest them.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from pcg_mpi_solver_tpu.obs.metrics import _jsonable
from pcg_mpi_solver_tpu.obs.schema import TELEMETRY_SCHEMA

#: default seconds between heartbeat records while a bracket is open
#: (env override: PCG_TPU_FLIGHT_HEARTBEAT_S).
DEFAULT_HEARTBEAT_S = 5.0


class FlightRecorder:
    """fsync-per-event JSONL flight recorder.

    Thread-safe; cheap when idle (the heartbeat thread runs only while a
    bracket is open).  ``fsync=False`` (or PCG_TPU_FLIGHT_FSYNC=0)
    downgrades to flush-only for tests/hot paths where durability
    against OS crash is not needed — a SIGKILL still loses nothing,
    only a kernel panic could.
    """

    def __init__(self, path: str, meta: Optional[Dict[str, Any]] = None,
                 heartbeat_s: Optional[float] = None,
                 fsync: Optional[bool] = None):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        if heartbeat_s is None:
            try:
                heartbeat_s = float(os.environ.get(
                    "PCG_TPU_FLIGHT_HEARTBEAT_S", DEFAULT_HEARTBEAT_S))
            except ValueError:      # a typo'd knob must not cost the run
                heartbeat_s = DEFAULT_HEARTBEAT_S
        if fsync is None:
            fsync = os.environ.get("PCG_TPU_FLIGHT_FSYNC", "1") != "0"
        self.heartbeat_s = max(0.05, float(heartbeat_s))
        self._fsync = bool(fsync)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self._seq = 0
        self._open: Dict[int, str] = {}     # seq -> record name
        self._hb_stop: Optional[threading.Event] = None
        self._closed = False
        if meta:
            self.emit("meta", **meta)

    # -- low-level ------------------------------------------------------
    def emit(self, op: str, **fields) -> Dict[str, Any]:
        """Write ONE durable flight record: a telemetry event of
        ``kind="flight"`` carrying the op, a monotonic timestamp (crash
        forensics must survive wall-clock jumps) and the caller's
        fields."""
        ev = {"schema": TELEMETRY_SCHEMA, "t": time.time(),
              "kind": "flight", "op": op,
              "mono": round(time.monotonic(), 6)}
        ev.update(fields)
        with self._lock:
            if self._closed:
                return ev
            try:
                self._f.write(json.dumps(ev, default=_jsonable) + "\n")
                self._f.flush()
                if self._fsync:
                    try:
                        os.fsync(self._f.fileno())
                    except OSError:
                        pass    # fs without fsync (pipes): flush stands
            except (OSError, ValueError):
                # disk full / handle gone mid-run: observability must
                # never cost the run itself — the record is lost, the
                # solve (and every other bracket) continues
                pass
        return ev

    # -- brackets -------------------------------------------------------
    def begin(self, name: str, **fields) -> int:
        """Open a bracket; returns the sequence token ``end`` needs.
        Heartbeats run while at least one bracket is open."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._open[seq] = name
            start_hb = self._hb_stop is None and not self._closed
            if start_hb:
                self._hb_stop = threading.Event()
                stop = self._hb_stop
        if start_hb:
            threading.Thread(target=self._heartbeat_loop, args=(stop,),
                             daemon=True).start()
        self.emit("begin", name=name, seq=seq, **fields)
        return seq

    def end(self, seq: int, name: str, ok: bool = True, **fields) -> None:
        """Close a bracket (op = ``end`` or ``fail``)."""
        with self._lock:
            self._open.pop(seq, None)
            if not self._open and self._hb_stop is not None:
                self._hb_stop.set()
                self._hb_stop = None
        self.emit("end" if ok else "fail", name=name, seq=seq, **fields)

    @contextmanager
    def record(self, name: str, **fields):
        """Bracket a block of work: ``begin`` on entry, ``end`` on clean
        exit, ``fail`` (with the exception named) when it raises — and
        nothing at all if the process is killed, which is exactly the
        parseable absence :func:`flight_verdict` classifies as
        ``died``."""
        seq = self.begin(name, **fields)
        t0 = time.monotonic()
        try:
            yield self
        except BaseException as e:
            self.end(seq, name, ok=False,
                     error=f"{type(e).__name__}: {e}",
                     wall_s=round(time.monotonic() - t0, 6))
            raise
        self.end(seq, name, ok=True,
                 wall_s=round(time.monotonic() - t0, 6))

    def _heartbeat_loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.heartbeat_s):
            with self._lock:
                names = list(self._open.values())
                if not names or self._closed:
                    return
            self.emit("heartbeat", in_flight=names)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._hb_stop is not None:
                self._hb_stop.set()
                self._hb_stop = None
            try:
                self._f.close()
            except ValueError:
                pass


# ---------------------------------------------------------------------------
# Tolerant ingest — the read side every dead-tunnel artifact needs.
# ---------------------------------------------------------------------------

def read_jsonl_tolerant(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Parse a JSONL file, skipping unparseable lines instead of raising.

    Returns ``(events, truncated_lines)``.  A process killed mid-write
    leaves exactly one cut line (usually the last); any JSONL consumer of
    crash artifacts must survive it — this is the ONE reader the CLI
    summary, the telemetry-merge aggregator and the bench salvage path
    share.  Blank lines are ignored (not counted)."""
    events: List[Dict[str, Any]] = []
    truncated = 0
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                truncated += 1
                continue
            if isinstance(ev, dict):
                events.append(ev)
            else:
                truncated += 1
    return events, truncated


def flight_verdict(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Classify a flight-record event stream mechanically.

    verdict: ``clean``  — every begin has a matching end;
             ``failed`` — at least one bracket closed with op=fail;
             ``died``   — at least one bracket never closed (the process
             was killed in flight);
             ``empty``  — no flight records at all.
    ``in_flight`` names the unclosed brackets, ``last_wall`` /
    ``last_mono`` the newest timestamp of ANY flight record (the
    heartbeat cadence bounds how stale they can be), and ``fails`` the
    collected failure messages.  A fail record carrying
    ``expected=True`` (the bench ladder descending to a smaller rung BY
    DESIGN) is collected separately in ``expected_fails`` and does NOT
    make the verdict ``failed`` — and neither do fails whose bracket is
    NESTED inside an expected one (the Solver's dispatch bracket closes
    op=fail when the rung's solve raises, before bench closes the rung
    expected): the verdict must keep pointing operators at work to
    re-queue, not at descents that already succeeded."""
    open_recs: Dict[Any, str] = {}
    fails: List[str] = []
    expected_fails: List[str] = []
    begin_at: Dict[Any, int] = {}       # key -> flight-record index
    # (shard, begin_i, close_i, expected, msg) per op=fail bracket
    fail_spans: List[Tuple[Any, int, int, bool, str]] = []
    last_wall = last_mono = None
    n = 0
    for ev in events:
        if ev.get("kind") != "flight":
            continue
        n += 1
        if isinstance(ev.get("t"), (int, float)):
            last_wall = ev["t"] if last_wall is None \
                else max(last_wall, ev["t"])
        if isinstance(ev.get("mono"), (int, float)):
            last_mono = ev["mono"] if last_mono is None \
                else max(last_mono, ev["mono"])
        op = ev.get("op")
        # brackets pair per SOURCE STREAM: a telemetry-merge'd stream
        # carries per-shard seq counters that all start at 1, and one
        # process's end must never close another's begin (a died shard
        # would read clean).  Unmerged files have no shard field — the
        # key degrades to the plain seq.
        key = (ev.get("shard"), ev.get("seq"))
        if op == "begin":
            open_recs[key] = str(ev.get("name"))
            begin_at[key] = n
        elif op in ("end", "fail"):
            open_recs.pop(key, None)
            b = begin_at.pop(key, n)
            if op == "fail":
                why = ev.get("error") or ev.get("status") or "?"
                fail_spans.append((ev.get("shard"), b, n,
                                   bool(ev.get("expected")),
                                   f"{ev.get('name')}: {why}"))
    exp_spans = [(sh, b, c) for sh, b, c, exp, _ in fail_spans if exp]
    for sh, b, c, exp, msg in fail_spans:
        covered = exp or any(s == sh and eb < b and c < ec
                             for s, eb, ec in exp_spans)
        (expected_fails if covered else fails).append(msg)
    if n == 0:
        verdict = "empty"
    elif open_recs:
        verdict = "died"
    elif fails:
        verdict = "failed"
    else:
        verdict = "clean"
    return {"verdict": verdict, "records": n,
            "in_flight": sorted(open_recs.values()),
            "fails": fails, "expected_fails": expected_fails,
            "last_wall": last_wall, "last_mono": last_mono}


_SALVAGE_NUM_RE = {
    k: re.compile(r'"%s"\s*:\s*(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)' % k)
    for k in ("t", "mono")}
_SALVAGE_STR_RE = {
    k: re.compile(r'"%s"\s*:\s*"([^"]*)"' % k) for k in ("kind", "op")}


def salvage_truncated_tail(path: str) -> Optional[Dict[str, Any]]:
    """Best-effort fields of a FINAL line that was cut mid-write.

    A process killed mid-``write()`` leaves one truncated trailing line;
    :func:`read_jsonl_tolerant` rightly skips it as unparseable — but
    when that line is the stream's last heartbeat, dropping it makes the
    shard look dead ``(write interval + heartbeat cadence)`` earlier
    than it really was, and a stall monitor would flag a live run.  The
    JSONL writers emit ``schema``/``t``/``kind`` first (metrics.event,
    FlightRecorder.emit), so even a badly cut line usually still carries
    the timestamp.  Returns ``{"t", "mono", "kind", "op", "salvaged":
    True}`` (fields present only when recovered) for a trailing line
    that starts like a record but does not parse; None when the file
    ends with a complete line (or cannot be read)."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 65536))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    lines = tail.splitlines()
    if not lines:
        return None
    last = lines[-1].strip()
    if not last or not last.startswith("{"):
        return None
    try:
        json.loads(last)
        return None                     # complete final line: nothing cut
    except ValueError:
        pass
    out: Dict[str, Any] = {"salvaged": True}
    for k, rx in _SALVAGE_NUM_RE.items():
        m = rx.search(last)
        if m:
            out[k] = float(m.group(1))
    for k, rx in _SALVAGE_STR_RE.items():
        m = rx.search(last)
        if m:
            out[k] = m.group(1)
    return out if len(out) > 1 else None


def flight_verdict_path(path: str) -> Dict[str, Any]:
    """:func:`flight_verdict` of a file, tolerant of truncation; the
    skipped-line count rides along as ``truncated_lines``.

    A final heartbeat cut mid-write still counts as the stream's last
    breath: its salvaged ``t``/``mono`` advance ``last_wall`` /
    ``last_mono`` (flagged ``salvaged_tail``) so a shard killed while
    writing its newest heartbeat is not read as having died a heartbeat
    interval earlier than it did."""
    events, truncated = read_jsonl_tolerant(path)
    out = flight_verdict(events)
    out["truncated_lines"] = truncated
    tail = salvage_truncated_tail(path)
    if tail and tail.get("kind") == "flight":
        t, mono = tail.get("t"), tail.get("mono")
        if t is not None and (out["last_wall"] is None
                              or t > out["last_wall"]):
            out["last_wall"] = t
            out["salvaged_tail"] = True
        if mono is not None and (out["last_mono"] is None
                                 or mono > out["last_mono"]):
            out["last_mono"] = mono
            out["salvaged_tail"] = True
    return out


def ingest_and_rotate(path: str, log_fn,
                      label: str = "previous flight record") -> str:
    """Mechanically ingest a LEFTOVER flight artifact before starting a
    fresh stream at the same path: log its verdict (in-flight names +
    truncated-line count included) and rotate it to ``path + ".prev"``.

    The startup discipline every flight writer shares (bench.py,
    tools/hw_session.py): a new run's verdict must not inherit a dead
    run's unclosed brackets, and a dead run's verdict must not be closed
    by the new run's reused seq numbers reading as matching end records.
    Returns the path the new stream must write to: ``path`` itself when
    it was rotated away (or never existed), or a unique ``path.<pid>``
    sibling when the leftover artifact could not be read/rotated —
    appending to the old stream would silently close the dead run's
    brackets, so a fallback path is the only safe degrade.  Ingest
    trouble never raises: it must not cost the run itself."""
    if not os.path.exists(path):
        return path
    try:
        v = flight_verdict_path(path)
        os.replace(path, path + ".prev")
        log_fn(f"{label} ({path}): verdict={v['verdict']}, "
               f"{v['records']} record(s)"
               + (", in flight at death: " + ", ".join(v["in_flight"])
                  if v["in_flight"] else "")
               + (f", {v['truncated_lines']} truncated line(s) skipped"
                  if v.get("truncated_lines") else "")
               + "; rotated to .prev")
        return path
    except OSError as e:
        fallback = f"{path}.{os.getpid()}"
        log_fn(f"{label} ({path}) could not be read/rotated ({e}); "
               f"new flight records go to {fallback}")
        return fallback


def attach_flight(recorder, path: Optional[str], component: str,
                  **meta) -> Optional[FlightRecorder]:
    """Attach a crash-durable FlightRecorder to a ``MetricsRecorder`` —
    the ONE wiring every solve driver shares (Solver, DynamicsSolver,
    NewmarkSolver): resolve the path (config value, else the
    ``PCG_TPU_FLIGHT`` env default), shard it per process, ingest +
    rotate a dead previous run's artifact, and hang the recorder on
    ``recorder.flight`` so the dispatch spans bracket themselves.

    Best-effort throughout: an unwritable path degrades to a
    ``recorder.note`` — observability must never cost the run itself.
    Returns the attached FlightRecorder (an already-attached one is
    returned untouched) or None."""
    existing = getattr(recorder, "flight", None)
    if existing is not None:
        return existing
    fp = (path or os.environ.get("PCG_TPU_FLIGHT", "")).strip()
    if not fp:
        return None
    try:
        shard = shard_jsonl_path(fp)
        shard = ingest_and_rotate(shard, recorder.note)
        fl = FlightRecorder(shard, meta={"component": component, **meta})
        recorder.flight = fl
        return fl
    except (OSError, ValueError) as e:
        recorder.note(f"flight recorder unavailable ({e}); "
                      "continuing without")
        return None


# ---------------------------------------------------------------------------
# Per-process telemetry shards + the merge aggregator.
# ---------------------------------------------------------------------------

def shard_jsonl_path(path: str, process_index: Optional[int] = None,
                     process_count: Optional[int] = None) -> str:
    """Per-process shard name for a JSONL path under multi-process
    jax.distributed: ``run.jsonl`` -> ``run.p3.jsonl`` on process 3 of a
    multi-process run; unchanged single-process (so every existing
    single-host workflow keeps its exact filenames).

    With index/count omitted they are read from an ALREADY-IMPORTED jax
    (never importing it here: this module is import-light by contract,
    and a recorder built before the accelerator env is configured must
    not initialize a backend as a side effect)."""
    if process_index is None or process_count is None:
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return path
        try:
            process_index = jax.process_index()
            process_count = jax.process_count()
        except Exception:                               # noqa: BLE001
            return path     # backend not initializable: single-process
    if int(process_count) <= 1:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.p{int(process_index)}{ext or '.jsonl'}"


def dispatch_anchors(events: List[Dict[str, Any]]
                     ) -> Dict[Tuple[str, int], float]:
    """Matched-anchor completion times of one telemetry/flight shard for
    clock alignment: every jitted dispatch is an SPMD program all
    processes block on together, so the k-th completion of dispatch
    ``name`` is the telemetry-granularity analogue of a collective end
    event (the anchors obs/fleet.py aligns trace clocks with).  Keys are
    ``(name, occurrence)`` over telemetry ``dispatch`` events and flight
    ``end`` records of ``dispatch:*`` brackets; values are the wall
    ``t``."""
    anchors: Dict[Tuple[str, int], float] = {}
    counts: Dict[str, int] = {}
    for ev in events:
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            continue
        kind = ev.get("kind")
        name = None
        if kind == "dispatch":
            name = str(ev.get("name"))
        elif kind == "flight" and ev.get("op") == "end" \
                and str(ev.get("name", "")).startswith("dispatch:"):
            name = str(ev.get("name"))
        if name is None:
            continue
        k = counts.get(name, 0)
        counts[name] = k + 1
        anchors[(name, k)] = float(t)
    return anchors


def merge_shards(paths: List[str], out_path: str,
                 align: Optional[str] = None) -> Dict[str, Any]:
    """Aggregate per-process telemetry/flight shards into ONE
    time-ordered JSONL stream.

    Every event gains a ``shard`` field (the source basename; the full
    given path when two inputs share a basename — e.g. per-host
    collection dirs both holding ``run.p0.jsonl`` — so stats can't
    silently collapse and :func:`flight_verdict`'s per-``(shard, seq)``
    bracket pairing can't close one stream's death with another's end)
    so per-process attribution survives the merge; ordering is by the
    wall timestamp ``t`` with the per-shard order as the stable tiebreak
    (events without a numeric ``t`` sort to the front of their shard's
    position).  Truncated lines — the dead-tunnel signature — are
    SKIPPED and counted per shard, never raised on.

    ``align="collectives"`` reuses the fleet clock-alignment
    (obs/fleet.py :func:`~pcg_mpi_solver_tpu.obs.fleet.align_offsets`)
    over matched dispatch completions (:func:`dispatch_anchors`): hosts
    with skewed wall clocks would otherwise interleave out of true
    order.  Each shard's median offset against shard 0 is subtracted
    from its ordering key and stamped on its events as ``t_aligned``
    (``t`` itself is never rewritten — provenance keeps the raw clock);
    the offsets and matched-anchor count ride along in the returned
    stats under ``align``.  With no matched anchors the mode degrades to
    the plain ``t`` ordering (offsets 0) and says so.

    Returns ``{"events", "shards": {name: {"events", "truncated"}},
    "truncated_lines"[, "align"]}``."""
    base_counts: Dict[str, int] = {}
    for p in paths:
        b = os.path.basename(p)
        base_counts[b] = base_counts.get(b, 0) + 1
    names: List[str] = []
    name_counts: Dict[str, int] = {}
    for p in paths:
        name = p if base_counts[os.path.basename(p)] > 1 \
            else os.path.basename(p)
        n = name_counts.get(name, 0)
        name_counts[name] = n + 1
        names.append(f"{name}#{n}" if n else name)
    per_shard: List[List[Dict[str, Any]]] = []
    stats: Dict[str, Dict[str, int]] = {}
    total_trunc = 0
    for si, p in enumerate(paths):
        events, truncated = read_jsonl_tolerant(p)
        per_shard.append(events)
        stats[names[si]] = {"events": len(events), "truncated": truncated}
        total_trunc += truncated
    offsets = {si: 0.0 for si in range(len(paths))}
    align_stats = None
    if align == "collectives":
        from pcg_mpi_solver_tpu.obs.fleet import align_offsets

        offsets, matched = align_offsets(
            {si: dispatch_anchors(evs)
             for si, evs in enumerate(per_shard)})
        align_stats = {"mode": align, "matched_anchors": matched,
                       "offsets_s": {names[si]: round(offsets[si], 6)
                                     for si in range(len(paths))}}
    merged: List[Tuple[float, int, int, Dict[str, Any]]] = []
    for si, events in enumerate(per_shard):
        name = names[si]
        for ei, ev in enumerate(events):
            t = ev.get("t")
            key = float(t) - offsets[si] \
                if isinstance(t, (int, float)) else float("-inf")
            ev = dict(ev)
            ev.setdefault("shard", name)
            if align_stats is not None and key != float("-inf"):
                ev["t_aligned"] = round(key, 6)
            merged.append((key, si, ei, ev))
    merged.sort(key=lambda r: (r[0], r[1], r[2]))
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        for _, _, _, ev in merged:
            f.write(json.dumps(ev, default=_jsonable) + "\n")
    os.replace(tmp, out_path)
    out = {"events": len(merged), "shards": stats,
           "truncated_lines": total_trunc}
    if align_stats is not None:
        out["align"] = align_stats
    return out


def find_shards(path: str) -> List[str]:
    """Every on-disk shard of a telemetry path: the base file (if
    written — single-process runs) plus any ``.pN`` siblings, sorted by
    process index."""
    out = []
    if os.path.exists(path):
        out.append(path)
    root, ext = os.path.splitext(path)
    ext = ext or ".jsonl"       # the same fallback shard_jsonl_path uses
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(root)
    try:
        names = os.listdir(d)
    except OSError:
        return out
    shards = []
    for n in names:
        r, e = os.path.splitext(n)
        if e == ext and r.startswith(base + ".p") \
                and r[len(base) + 2:].isdigit():
            shards.append((int(r[len(base) + 2:]), os.path.join(d, n)))
    out.extend(p for _, p in sorted(shards))
    return out
