"""Phase-attribution probes: MEASURE where each ms/iter goes.

The analytic cost model (obs/perf.py) predicts per-phase ms/iter from
the ops tables; this module measures the same four phases — ``matvec``
/ ``precond`` / ``reduction`` / ``axpy`` — by compiling each
sub-program ONCE from the live solver's own ops/data (identical gather/
einsum/scatter code paths, identical sharding) and timing it with
``block_until_ready`` around an inner ``fori_loop`` that amortizes
dispatch overhead.  The whole-iteration anchor comes from the REAL
solve program: a warm capped-iteration solve divided by its committed
iteration count.  measured-vs-model is then the attribution table that
explains gaps like round 5's 24.994 ms/iter vs 13.741 ms/matvec — and
it runs chiplessly on CPU (the probes are ordinary jitted programs), so
``pcg-tpu perf-report`` can sanity the attribution before a hardware
window ever opens.

Probe fidelity notes:

* every probe normalizes its carry by a LOCAL (collective-free) max so
  repeated applications of K (growth ~||K||) or M^-1 (shrink ~1/||K||)
  cannot overflow/underflow across the inner reps — a light extra pass
  whose cost is part of the quoted number;
* the reduction/axpy probes execute the VARIANT's declared per-iteration
  counts (``PCG_SCALAR_PSUMS`` worth of psums carrying the 6 reduced
  scalars, ``PCG_VECTOR_AXPYS`` vector updates), so the per-phase
  numbers line up 1:1 with the cost model's rows;
* timings take the best of ``reps`` outer rounds (min, not mean: host
  jitter only ever adds), and each round times every phase AND the
  whole-iteration anchor back to back — both sides of the attribution
  ratio see the same machine weather.

jax is imported lazily: the module is import-light until a probe is
actually built.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from pcg_mpi_solver_tpu.obs.perf import PHASES

#: inner fori_loop applications per timed dispatch (amortizes the
#: per-dispatch host overhead the real while-loop also amortizes).
DEFAULT_INNER = 16


class PhaseProbe:
    """Compiled-once phase timing programs for a live (direct-mode)
    Solver.  Construction is cheap; programs compile on first
    :meth:`measure`."""

    def __init__(self, solver, nrhs: int = 1, inner: int = DEFAULT_INNER):
        if getattr(solver, "mixed", False):
            raise ValueError(
                "phase probes need a direct-mode solver (one dtype, one "
                "loop); precision_mode='mixed' interleaves f32 cycles "
                "with f64 refreshes and has no single per-iteration "
                "phase split")
        self.solver = solver
        self.nrhs = max(1, int(nrhs))
        self.inner = max(1, int(inner))
        self._progs: Optional[Dict[str, Any]] = None
        self._prec = None

    # -- program construction ------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp

        from pcg_mpi_solver_tpu.ops.precond import make_prec
        from pcg_mpi_solver_tpu.ops.matvec import (
            PCG_SCALAR_PSUMS, PCG_VECTOR_AXPYS)
        from pcg_mpi_solver_tpu.utils.compat import ensure_shard_map

        ensure_shard_map()
        s = self.solver
        ops = s.ops
        mesh = s.mesh
        specs = s._specs
        P = s._part_spec
        prec_spec = s._prec_operand_spec()
        variant = s.config.solver.pcg_variant
        precond = s.config.solver.precond
        M = self.inner
        R = self.nrhs
        n_psums = PCG_SCALAR_PSUMS[variant]     # KeyError = the contract
        n_axpys = PCG_VECTOR_AXPYS[variant]

        def _seed(data):
            """A bounded, fully-populated start vector from the device
            data (no host staging): |F| + eff, locally normalized."""
            x = jnp.abs(data["F"]) + data["eff"] + 1e-3
            x = x / jnp.max(x)
            if R > 1:
                x = jnp.repeat(x[..., None], R, axis=-1)
            return x

        def _norm(v):
            # LOCAL max normalization (no collective): keeps repeated
            # operator applications bounded without touching the
            # phase's collective count
            m = jnp.max(jnp.abs(v))
            return v / jnp.where(m > 0, m, 1.0)

        def _out(v):
            # per-part scalar: a tiny fetch that still forces the loop
            return jnp.sum(jnp.abs(v),
                           axis=tuple(range(1, v.ndim)))

        def matvec_prog(data):
            x = _seed(data)

            def body(_, v):
                return _norm(ops.matvec(data, v))

            return _out(jax.lax.fori_loop(0, M, body, x))

        def precond_prog(data, prec):
            x = _seed(data)

            def body(_, v):
                return _norm(ops.apply_prec(prec, v, data=data))

            return _out(jax.lax.fori_loop(0, M, body, x))

        def reduction_prog(data):
            x = _seed(data)
            w = data["weight"] * data["eff"]
            r, z, p, q = x, x * 0.5, x * 2.0, x * 0.25
            if R > 1:
                one_dot, many_dots = ops.wdot_many, ops.wdots_many
            else:
                one_dot, many_dots = ops.wdot, ops.wdots

            def body(_, v):
                if n_psums >= 3:    # classic: three serialized psums
                    s1 = one_dot(w, v, z)
                    s2 = one_dot(w, p, q)
                    s3 = many_dots(w, [(p, p), (v, v), (z, z)],
                                   extra=(jnp.zeros(
                                       (R,) if R > 1 else (),
                                       ops.dot_dtype),))
                    tot = jnp.sum(s1) + jnp.sum(s2) + jnp.sum(s3)
                else:               # fused/pipelined: ONE fused psum
                    red = many_dots(
                        w, [(v, z), (z, q), (v, v), (p, p), (q, q)],
                        extra=(jnp.zeros((R,) if R > 1 else (),
                                         ops.dot_dtype),))
                    tot = jnp.sum(red)
                # fold the reduced scalar back so the loop is sequential
                # without perturbing the operand magnitudes (cast keeps
                # the carry dtype stable — tot is dot_dtype, v may not be)
                return v + (tot * 1e-300).astype(v.dtype)

            return _out(jax.lax.fori_loop(0, M, body, r))

        def axpy_prog(data):
            x = _seed(data)
            a, b, c = x, x * 0.5, x * 0.25

            def body(_, carry):
                va, vb, vc = carry
                bufs = [va, vb, vc]
                for k in range(n_axpys):
                    dst, src = k % 3, (k + 1) % 3
                    bufs[dst] = bufs[src] + 0.5 * bufs[dst]
                va, vb, vc = bufs
                return _norm(va), _norm(vb), _norm(vc)

            out = jax.lax.fori_loop(0, M, body, (a, b, c))
            return _out(out[0])

        sm = jax.shard_map
        self._prec_builder = jax.jit(sm(
            lambda data: make_prec(ops, data, precond),
            mesh=mesh, in_specs=(specs,), out_specs=prec_spec,
            check_vma=False))
        self._progs = {
            "matvec": jax.jit(sm(matvec_prog, mesh=mesh, in_specs=(specs,),
                                 out_specs=P, check_vma=False)),
            "precond": jax.jit(sm(precond_prog, mesh=mesh,
                                  in_specs=(specs, prec_spec),
                                  out_specs=P, check_vma=False)),
            "reduction": jax.jit(sm(reduction_prog, mesh=mesh,
                                    in_specs=(specs,), out_specs=P,
                                    check_vma=False)),
            "axpy": jax.jit(sm(axpy_prog, mesh=mesh, in_specs=(specs,),
                               out_specs=P, check_vma=False)),
        }

    # -- timing --------------------------------------------------------
    #
    # Noise discipline: the phases and the whole-iteration anchor are
    # timed INTERLEAVED — each round measures every phase once and runs
    # one anchor solve, and the final numbers are per-quantity minima
    # across rounds.  Timing them in separate blocks (all phase reps,
    # then all anchor reps) lets a background-load swing land entirely
    # on one side and move the attribution ratio by tens of percent;
    # interleaved rounds put both sides of the ratio inside the same
    # ~second of machine weather, and min-of-rounds picks the quietest.

    def _time_once(self, fn, args) -> float:
        import jax

        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        return (time.perf_counter() - t0) / self.inner

    def _phase_args(self, ph):
        data = self.solver.data
        return (data, self._prec) if ph == "precond" else (data,)

    def warm(self) -> None:
        """Compile + warm every probe program (and build the prec
        operand) so the timed rounds never pay a trace."""
        if self._progs is None:
            self._build()
        import jax

        self._prec = self._prec_builder(self.solver.data)
        jax.block_until_ready(self._prec)
        for ph in PHASES:
            jax.block_until_ready(
                self._progs[ph](*self._phase_args(ph)))

    def measure_phases_once(self) -> Dict[str, float]:
        """One timed application of every phase program: per-phase
        seconds per ITERATION-EQUIVALENT (one matvec, one precond apply,
        the variant's reduction set, the variant's axpy set), measured
        on the live device data.  Call :meth:`warm` first."""
        return {ph: self._time_once(self._progs[ph], self._phase_args(ph))
                for ph in PHASES}

    def measure_whole_once(self) -> Dict[str, float]:
        """One whole-iteration anchor from the REAL solve program: a
        warm capped solve (the solver's configured max_iter bounds it),
        wall divided by committed iterations.  The solver's state is
        reset around the measurement."""
        s = self.solver
        if self.nrhs > 1:
            import numpy as np

            F = np.repeat(
                np.asarray(s._model.F)[:, None], self.nrhs, axis=1)
            res = s.solve_many(F)
            iters = int(max(1, int(res.iters.max(initial=1))))
            wall = float(res.solve_wall_s)
        else:
            r = s.step(1.0)
            s.reset_state()
            iters = max(1, int(r.iters))
            wall = float(r.wall_s)
        return {"wall_s": wall, "iters": iters,
                "s_per_iter": wall / iters}

    def measure(self, reps: int = 3,
                whole: bool = False) -> Dict[str, Any]:
        """``reps`` interleaved rounds; returns the per-phase minima,
        with ``whole=True`` the best anchor under ``"whole"`` plus the
        MEDIAN of the per-round sum/whole ratios under
        ``"attribution"``.  The ratio is quoted round-wise because both
        of its sides then sat in the same second of machine weather — a
        load swing inflates them together and cancels, where a ratio of
        independently-taken minima needs BOTH sides to have caught a
        quiet window."""
        self.warm()
        if whole:
            self.measure_whole_once()           # warm the solve program
        best: Dict[str, float] = {}
        best_whole = None
        ratios = []
        for _ in range(max(1, reps)):
            round_a = self.measure_phases_once()
            for ph, v in round_a.items():
                best[ph] = min(best.get(ph, float("inf")), v)
            if whole:
                w = self.measure_whole_once()
                if best_whole is None or \
                        w["s_per_iter"] < best_whole["s_per_iter"]:
                    best_whole = w
                # bracket the anchor: a second phase pass AFTER it, the
                # round ratio from the mean of the two — a load ramp
                # across the round inflates the anchor like the average
                # of its brackets and cancels to first order
                round_b = self.measure_phases_once()
                for ph, v in round_b.items():
                    best[ph] = min(best[ph], v)
                if w["s_per_iter"] > 0:
                    ratios.append(
                        0.5 * (sum(round_a.values())
                               + sum(round_b.values()))
                        / w["s_per_iter"])
        out: Dict[str, Any] = dict(best)
        if whole:
            out["whole"] = best_whole
            ratios.sort()
            out["attribution"] = (
                ratios[len(ratios) // 2] if len(ratios) % 2 else
                0.5 * (ratios[len(ratios) // 2 - 1]
                       + ratios[len(ratios) // 2])) if ratios else None
        return out


def run_phase_probe(solver, recorder=None, reps: int = 3,
                    nrhs: int = 1, inner: int = DEFAULT_INNER,
                    whole: bool = True) -> Dict[str, Any]:
    """Measure the phases (and optionally the whole-iteration anchor) on
    a live solver, emit the ``phase_probe`` telemetry event, and return
    the payload: per-phase ms, their sum, the whole-iteration ms and the
    sum/whole attribution ratio."""
    probe = PhaseProbe(solver, nrhs=nrhs, inner=inner)
    measured = probe.measure(reps=reps, whole=whole)
    w = measured.pop("whole", None)
    attribution = measured.pop("attribution", None)
    phases_ms = {ph: round(v * 1e3, 6) for ph, v in measured.items()}
    total_ms = round(sum(phases_ms.values()), 6)
    payload: Dict[str, Any] = {
        "pcg_variant": solver.config.solver.pcg_variant,
        "precond": solver.config.solver.precond,
        "nrhs": int(nrhs),
        "backend": solver.backend,
        "inner": int(inner),
        "phases": phases_ms,
        "sum_ms_per_iter": total_ms,
        "whole_ms_per_iter": None,
        "attribution": None,
    }
    if w is not None:
        payload["whole_ms_per_iter"] = round(w["s_per_iter"] * 1e3, 6)
        payload["whole_iters"] = w["iters"]
        # round-wise median, NOT min-sum/min-whole: each round's ratio
        # compares numbers taken in the same second of machine weather
        if attribution is not None:
            payload["attribution"] = round(attribution, 4)
        elif payload["whole_ms_per_iter"]:
            payload["attribution"] = round(
                total_ms / payload["whole_ms_per_iter"], 4)
    rec = recorder if recorder is not None else getattr(
        solver, "recorder", None)
    if rec is not None:
        rec.event("phase_probe", **payload)
        for ph, v in phases_ms.items():
            rec.gauge(f"perf.measured.{ph}_ms", v)
        if payload["whole_ms_per_iter"] is not None:
            rec.gauge("perf.measured.whole_ms", payload["whole_ms_per_iter"])
    return payload
