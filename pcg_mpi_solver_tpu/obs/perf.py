"""Analytic per-iteration cost model: attribute every ms/iter BEFORE a
hardware window opens.

Round-5 hardware data left ~45% of each iteration unattributed (24.994
ms/iter against 13.741 ms/matvec at 10.33M dofs) — the data-locality CG
literature (arXiv:2205.08909) shows those gaps are memory-bound phase
costs predictable from bytes moved, and the communication-reduced survey
(arXiv:2501.03743) does the same for collective payloads.  This module
turns the repo's existing single-source ops tables into that prediction:

* ``ops/matvec.PCG_SCALAR_PSUMS``    — per-variant reduction collectives,
* ``ops/matvec.PCG_VECTOR_AXPYS``    — per-variant vector updates,
* ``ops/matvec.precond_cycle_cost``  — per-precond extra matvecs/psums,
* ``parallel/structured.STENCIL_HALO_PPERMUTES`` — halo exchanges.

Per ``(pcg_variant, precond, nrhs, backend)`` combination the model
produces FLOPs, HBM bytes and collective count/payload for the four
phases of one PCG iteration — ``matvec`` / ``precond`` / ``reduction``
/ ``axpy`` — and converts them to predicted ms/iter through a hardware
roofline profile.  An UNKNOWN variant or preconditioner is a loud
``KeyError`` (the same contract as the source tables; the analysis/
``cost-model-completeness`` rule proves the enumeration is total).

The model is emitted as a schema-versioned ``cost_model`` telemetry
event plus ``perf.*`` gauges at solver construction, stamped on every
bench line as ``detail.predicted_ms_per_iter`` (with
``detail.model_ratio`` = measured/predicted), and compared against the
MEASURED phase probes (obs/phases.py) by ``pcg-tpu perf-report``.

Import-light by contract (no jax, no numpy at import): the ops tables
are imported lazily inside the functions, so bench.py and the analysis
rules can import this module before the accelerator environment is
configured.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional, Tuple

from pcg_mpi_solver_tpu.config import PCG_VARIANTS, PRECONDS

#: the four attribution phases of one PCG iteration — the rows of the
#: measured-vs-model table (obs/phases.py measures the same four).
PHASES = ("matvec", "precond", "reduction", "axpy")

#: reduced scalars per iteration (rho, the p.Ap denominator, ||r||, the
#: two stagnation norms, the inf-prec flag) — every variant reduces the
#: same six, the variants differ only in how many psums carry them.
REDUCED_SCALARS = 6


@dataclasses.dataclass(frozen=True)
class ProblemShape:
    """The pure-python geometry the cost model consumes — derivable from
    a live Solver (:func:`shape_from_solver`) or constructed synthetically
    (the analysis rule, tests)."""

    n_dof: int                       # global effective-ish dof count
    n_parts: int = 1
    n_iface: int = 0                 # global interface dof count (psum payload)
    #: per pattern-type group: (element dof count d, total element count)
    elem_groups: Tuple[Tuple[int, int], ...] = ()
    backend: str = "general"         # general | structured | hybrid
    itemsize: int = 8                # iteration storage dtype bytes
    dot_itemsize: int = 8            # reduction accumulation dtype bytes
    mg_degree: int = 2
    mg_coarse_dofs: int = 0

    def matvec_flops(self) -> float:
        """One assembled matvec, nrhs=1: the per-type dense
        ``Ke @ (ck*u)`` einsums (2*d*d*N each).  Structured/hybrid
        backends report an equivalent-stencil group."""
        if self.elem_groups:
            return float(sum(2.0 * d * d * n for d, n in self.elem_groups))
        # fallback: brick elasticity, ~1 element per 3 dofs, d=24
        return 2.0 * 24 * 24 * (self.n_dof / 3.0)

    def matvec_bytes(self) -> float:
        """One assembled matvec, nrhs=1: element gather + scatter traffic
        (d values in, d values out per element) plus the in/out nodal
        vectors."""
        if self.elem_groups:
            elem = sum(2.0 * d * n for d, n in self.elem_groups)
        else:
            elem = 2.0 * 24 * (self.n_dof / 3.0)
        return (elem + 2.0 * self.n_dof) * self.itemsize


@dataclasses.dataclass(frozen=True)
class PhaseCost:
    """Resource cost of one phase of one iteration (already nrhs-wide)."""

    flops: float = 0.0
    hbm_bytes: float = 0.0
    coll_count: int = 0
    coll_bytes: float = 0.0

    def to_dict(self) -> dict:
        return {"flops": round(self.flops, 1),
                "hbm_bytes": round(self.hbm_bytes, 1),
                "coll_count": int(self.coll_count),
                "coll_bytes": round(self.coll_bytes, 1)}


@dataclasses.dataclass(frozen=True)
class HwProfile:
    """Roofline constants of the execution platform.  Deliberately
    conservative EFFECTIVE rates (the matvec's d x d einsums and
    gather/scatter never hit datasheet peaks), overridable per run via
    PCG_TPU_ROOFLINE_{FLOPS,HBM_GBS,ICI_GBS,COLL_LAT_US}."""

    name: str
    flops_per_s: float
    hbm_bytes_per_s: float
    ici_bytes_per_s: float
    coll_latency_s: float


#: baked-in profiles; "tpu" is calibrated loosely against the round-5
#: flagship (13.741 ms/matvec at 10.33M dofs ~ 0.9 TB/s effective HBM on
#: the matvec's ~12 GB of gather/scatter traffic), "cpu" against the
#: 1-core container this repo's golden models run on.
HW_PROFILES: Dict[str, HwProfile] = {
    "tpu": HwProfile("tpu", flops_per_s=2.0e13, hbm_bytes_per_s=9.0e11,
                     ici_bytes_per_s=9.0e10, coll_latency_s=8e-6),
    "cpu": HwProfile("cpu", flops_per_s=6.0e9, hbm_bytes_per_s=1.5e10,
                     ici_bytes_per_s=1.5e10, coll_latency_s=2e-6),
}


def resolve_profile(platform: str) -> HwProfile:
    """The HwProfile for a platform string ("cpu", "tpu",
    "TPU v4" ... — anything not starting with "cpu" is the accelerator),
    with the PCG_TPU_ROOFLINE_* env overrides applied."""
    key = "cpu" if str(platform).lower().startswith("cpu") else "tpu"
    p = HW_PROFILES[key]

    def env(name, default, scale=1.0):
        raw = os.environ.get(name)
        return default if raw is None else float(raw) * scale

    return HwProfile(
        name=p.name,
        flops_per_s=env("PCG_TPU_ROOFLINE_FLOPS", p.flops_per_s),
        hbm_bytes_per_s=env("PCG_TPU_ROOFLINE_HBM_GBS",
                            p.hbm_bytes_per_s, 1e9),
        ici_bytes_per_s=env("PCG_TPU_ROOFLINE_ICI_GBS",
                            p.ici_bytes_per_s, 1e9),
        coll_latency_s=env("PCG_TPU_ROOFLINE_COLL_LAT_US",
                           p.coll_latency_s, 1e-6),
    )


def _iface_collective(shape: ProblemShape, nrhs: int) -> Tuple[int, float]:
    """(count, payload bytes) of ONE assembled matvec's cross-part
    collective: the interface psum (general/hybrid) or the
    STENCIL_HALO_PPERMUTES halo exchange (structured)."""
    if shape.n_parts <= 1:
        return 0, 0.0
    if shape.backend == "structured":
        from pcg_mpi_solver_tpu.parallel.structured import (
            STENCIL_HALO_PPERMUTES)

        # halo payload: one boundary plane each way ~ n_dof^(2/3) rows
        plane = max(1.0, float(shape.n_dof) ** (2.0 / 3.0))
        return STENCIL_HALO_PPERMUTES, (STENCIL_HALO_PPERMUTES * plane
                                        * shape.itemsize * nrhs)
    if shape.n_iface <= 0:
        return 0, 0.0
    return 1, float(shape.n_iface) * shape.itemsize * nrhs


def phase_costs(shape: ProblemShape, variant: str, precond: str,
                nrhs: int = 1) -> Dict[str, PhaseCost]:
    """The per-phase resource model of ONE iteration of the
    ``(variant, precond)`` loop at block width ``nrhs``.

    Derived from the single-source ops tables — an unknown variant or
    preconditioner raises the same loud ``KeyError`` the tables
    themselves raise, never a silent default row (the
    cost-model-completeness rule and tests/test_perf_model.py hold this
    contract)."""
    from pcg_mpi_solver_tpu.ops.matvec import (
        PCG_SCALAR_PSUMS, PCG_VECTOR_AXPYS, precond_cycle_cost)

    R = max(1, int(nrhs))
    scalar_psums = PCG_SCALAR_PSUMS[variant]    # KeyError = the contract
    axpys = PCG_VECTOR_AXPYS[variant]
    mv_extra, ps_extra = precond_cycle_cost(precond, shape.mg_degree)

    mv_coll, mv_coll_bytes = _iface_collective(shape, R)
    matvec = PhaseCost(
        flops=shape.matvec_flops() * R,
        hbm_bytes=shape.matvec_bytes() * R,
        coll_count=mv_coll, coll_bytes=mv_coll_bytes)

    # -- preconditioner apply ------------------------------------------
    n = float(shape.n_dof)
    if precond == "jacobi":
        prec = PhaseCost(flops=n * R,
                         hbm_bytes=3.0 * n * shape.itemsize * R)
    elif precond == "block3":
        # batched (n/3) 3x3 block multiplies: 2*9 flops per node, block
        # operand ~3x the vector traffic
        prec = PhaseCost(flops=6.0 * n * R,
                         hbm_bytes=6.0 * n * shape.itemsize * R)
    elif precond == "mg":
        # 2*degree assembled FINE matvecs (each with its own interface
        # collective) + the replicated coarse cycle (geometric series of
        # 8x-coarser levels ~ 1/7 of one fine sweep, collective-free) +
        # the one restriction psum into the replicated coarse vector.
        fine = PhaseCost(flops=shape.matvec_flops() * R,
                         hbm_bytes=shape.matvec_bytes() * R)
        coarse_factor = 1.0 / 7.0
        smooth_bytes = (2 * shape.mg_degree + 2) * 3.0 * n \
            * shape.itemsize * R
        prec = PhaseCost(
            flops=fine.flops * mv_extra * (1.0 + coarse_factor),
            hbm_bytes=(fine.hbm_bytes * mv_extra * (1.0 + coarse_factor)
                       + smooth_bytes),
            coll_count=mv_coll * mv_extra
            + (ps_extra if shape.n_parts > 1 else 0),
            coll_bytes=mv_coll_bytes * mv_extra
            + (float(shape.mg_coarse_dofs) * shape.itemsize * R
               if shape.n_parts > 1 else 0.0))
    else:
        # same loudness as the source tables: a precond no table row
        # covers must never silently model as free
        raise KeyError(precond)

    reduction = PhaseCost(
        flops=2.0 * n * REDUCED_SCALARS * R,
        hbm_bytes=REDUCED_SCALARS * n * shape.itemsize * R,
        coll_count=scalar_psums if shape.n_parts > 1 else 0,
        # the SAME six scalars cross the wire whether one fused psum or
        # classic's three carry them — the variants differ in coll_count
        # (latency), not payload
        coll_bytes=(REDUCED_SCALARS * shape.dot_itemsize * R
                    if shape.n_parts > 1 else 0.0))

    axpy = PhaseCost(
        flops=2.0 * n * axpys * R,
        hbm_bytes=3.0 * n * shape.itemsize * axpys * R)

    return {"matvec": matvec, "precond": prec,
            "reduction": reduction, "axpy": axpy}


def predict_phase_ms(cost: PhaseCost, profile: HwProfile) -> float:
    """Roofline time of one phase: max(compute, HBM) + collective
    latency + collective payload wire time, in milliseconds."""
    t = max(cost.flops / profile.flops_per_s,
            cost.hbm_bytes / profile.hbm_bytes_per_s)
    t += cost.coll_count * profile.coll_latency_s
    t += cost.coll_bytes / profile.ici_bytes_per_s
    return t * 1e3


def cost_model(shape: ProblemShape, variant: str, precond: str,
               nrhs: int = 1,
               profile: Optional[HwProfile] = None) -> Dict[str, Any]:
    """The full model of one combination: per-phase resources + per-phase
    predicted ms + their total — the payload of the ``cost_model``
    telemetry event and the model column of ``pcg-tpu perf-report``."""
    profile = profile or resolve_profile("cpu")
    costs = phase_costs(shape, variant, precond, nrhs)
    phases = {}
    total = 0.0
    for ph in PHASES:
        ms = predict_phase_ms(costs[ph], profile)
        total += ms
        d = costs[ph].to_dict()
        d["model_ms"] = round(ms, 6)
        phases[ph] = d
    return {
        "pcg_variant": variant,
        "precond": precond,
        "nrhs": int(nrhs),
        "backend": shape.backend,
        "n_dof": int(shape.n_dof),
        "n_parts": int(shape.n_parts),
        "profile": profile.name,
        "phases": phases,
        "predicted_ms_per_iter": round(total, 6),
    }


def cost_model_table(shape: ProblemShape, nrhs_set=(1, 8),
                     profile: Optional[HwProfile] = None,
                     variants=PCG_VARIANTS,
                     preconds=PRECONDS) -> Dict[tuple, Dict[str, Any]]:
    """Models for EVERY ``variant x precond x nrhs`` combination — the
    enumeration the analysis/ cost-model-completeness rule proves total
    against the canonical name tables."""
    return {(v, p, int(r)): cost_model(shape, v, p, r, profile)
            for v in variants for p in preconds for r in nrhs_set}


def shape_from_detail(detail) -> Optional[ProblemShape]:
    """The cost-model geometry from a bench line's ``detail`` dict —
    a salvage/insurance line must be self-describing without a live
    solver in hand.  Returns None when the line carries no dof count
    (e.g. the zero-value error sentinel)."""
    n_dof = int(detail.get("n_dof", 0) or 0)
    if n_dof <= 0:
        return None
    mode = str(detail.get("mode", "direct"))
    dtype = str(detail.get("dtype", "float64"))
    return ProblemShape(
        n_dof=n_dof,
        n_parts=int(detail.get("n_parts", 1) or 1),
        # interface payload estimate: one boundary plane ~ n_dof^(2/3)
        # rows — the same heuristic _iface_collective's structured-halo
        # payload model uses (the general iface psum is comparable)
        n_iface=int(max(0.0, float(n_dof) ** (2.0 / 3.0))),
        backend=str(detail.get("backend", "general")),
        itemsize=4 if (mode == "mixed" or dtype == "float32") else 8,
        dot_itemsize=8)


def shape_from_solver(solver) -> ProblemShape:
    """Derive the cost-model geometry from a live Solver (any backend).
    Reads only host-side partition metadata — no device traffic."""
    pm = solver.pm
    scfg = solver.config.solver
    mixed = getattr(solver, "mixed", False)
    itemsize = 4 if (mixed or str(scfg.dtype) == "float32") else 8
    dot_itemsize = 4 if str(scfg.dot_dtype) == "float32" else 8
    groups = []
    for tb in getattr(pm, "type_blocks", None) or ():
        d = int(getattr(tb, "d", 0) or 0)
        node = getattr(tb, "node", None)
        if d and node is not None and getattr(node, "ndim", 0) >= 2:
            # (P, nn, N): total element slots across parts (padding
            # included — it is computed and moved like real elements)
            n_elem = int(node.shape[0]) * int(node.shape[-1])
        elif d:
            n_elem = int(getattr(pm, "glob_n_dof", 0)) // max(1, d // 8)
        else:
            continue
        if d and n_elem:
            groups.append((d, n_elem))
    ops = solver.ops
    return ProblemShape(
        n_dof=int(pm.glob_n_dof),
        n_parts=int(pm.n_parts),
        n_iface=int(getattr(ops, "n_iface", getattr(pm, "n_iface", 0))
                    or 0),
        elem_groups=tuple(groups),
        backend=str(solver.backend),
        itemsize=itemsize,
        dot_itemsize=dot_itemsize,
        mg_degree=int(getattr(ops, "mg_degree", scfg.mg_smooth_degree)),
        mg_coarse_dofs=int(getattr(ops, "mg_coarse_dofs", 0)),
    )


def emit_cost_model(recorder, model: Dict[str, Any]) -> None:
    """Emit one model as the schema-versioned ``cost_model`` event plus
    the ``perf.*`` gauges the run_summary snapshot carries."""
    recorder.event("cost_model", **model)
    recorder.gauge("perf.predicted_ms_per_iter",
                   model["predicted_ms_per_iter"])
    recorder.gauge("perf.model_profile", model["profile"])
    for ph in PHASES:
        recorder.gauge(f"perf.model.{ph}_ms",
                       model["phases"][ph]["model_ms"])
