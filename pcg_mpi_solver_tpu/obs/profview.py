"""Profiler-trace consumer: parse device traces into per-phase
attribution and a MEASURED collective-overlap verdict.

The repo has predicted per-iteration phase costs since ISSUE 12
(obs/perf.py roofline model) and recorded them with compiled probe
programs (obs/phases.py) — but until this module nothing ever READ a
captured ``jax.profiler`` trace back, so the round-5 question ("where do
the unattributed ~45% of 24.994 ms/iter go?") and the pipelined-CG
overlap claim (PR 10 proved it STATICALLY on the jaxpr; the premise of
arXiv:2105.06176 is that the psum *measurably* hides behind the stencil)
had no mechanical answer.  This closes the loop:

* :func:`capture_solve_profile` — a bounded one-shot capture around any
  warm solver dispatch (``jax.profiler.start_trace``/``stop_trace``),
  multi-process-safe (per-process dir suffix, like the telemetry
  shards), writing a ``profview_meta.json`` sidecar next to the trace
  so the artifact is SELF-DESCRIBING offline: committed iterations, the
  whole-solve anchor, the engaged variant/precond/nrhs/backend shape,
  and the HLO-instruction -> phase ``scope_map`` derived from the
  compiled program's ``op_name`` metadata.

* a TOLERANT reader for the trace-viewer JSON(.gz) the profiler emits:
  gz or plain, a truncated/unreadable file or a trace with no device-op
  events degrades to a NAMED verdict (``degraded: <reason>``), never a
  crash — the artifact a dead tunnel leaves behind must still parse.

* :func:`bucket_phases` — buckets device-op wall time per phase via the
  ``pcg/*`` ``jax.named_scope`` labels threaded through the
  solver/pcg.py loop bodies (all three variants, scalar + blocked).  On
  TPU the labels ride the event metadata directly; on CPU the events
  carry bare HLO instruction names (``dot.1``, ``multiply_add_fusion``)
  and the sidecar scope_map restores the mapping.  Events matching no
  phase are COUNTED and their time reported (``other``); a ``pcg/<x>``
  label that is not one of the four known phases is counted under
  ``unknown_scopes`` — never silently dropped (the analysis/
  ``scope-labels`` rule holds both contracts).

* :func:`collective_overlap` — the measured twin of PR 10's static
  psum-overlap rule: per device lane, the wall-clock intersection of
  collective-op spans with concurrent compute-op spans on OTHER
  threads of the same lane, as a fraction of total collective time.
  Contract: the traced pipelined program must compute a fraction where
  classic's serialized reductions report ~0 (on a 1-core host both may
  be ~0 — the parse/bucket/reconcile pipeline is what CPU proves; the
  number is the hardware window's to confirm).

The report is emitted as a schema-versioned ``prof_report`` event +
``prof.*`` gauges, reconciled against ``obs/perf.cost_model()`` by the
extended ``pcg-tpu perf-report`` (predicted | recorded | measured) and
readable offline from any artifact via ``pcg-tpu prof-report PATH``.

Import-light by contract (no jax, no numpy at import): jax is imported
only inside :func:`capture_solve_profile` / :func:`scope_map_from_solver`.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

#: named-scope label -> attribution phase (the obs/perf.PHASES rows).
#: solver/pcg.py threads exactly these labels through every loop body;
#: the analysis/ ``scope-labels`` rule proves each appears in the traced
#: hot loop of every variant (scalar + blocked).
PHASE_SCOPES: Dict[str, str] = {
    "pcg/matvec": "matvec",
    "pcg/precond": "precond",
    "pcg/reduce": "reduction",
    "pcg/axpy": "axpy",
}

#: substrings identifying a collective device op (XLA instruction
#: naming; the -start/-done halves of async collectives match too).
COLLECTIVE_MARKERS = ("all-reduce", "all-gather", "reduce-scatter",
                      "collective-permute", "all-to-all")

#: container ops whose span WRAPS other ops on the same thread — they
#: must join neither the phase buckets nor the overlap compute set (a
#: ``while`` span intersecting its own body's collective would read as
#: fake 100% overlap).
CONTAINER_OPS = frozenset({"while", "call", "conditional", "tuple",
                           "parameter", "get-tuple-element"})

#: sidecar filename written next to the trace by capture_solve_profile.
PROFVIEW_META = "profview_meta.json"
PROFVIEW_META_SCHEMA = "pcg-tpu-profview-meta/1"

_SCOPE_RE = re.compile(r"pcg/([A-Za-z0-9_]+)")


# ----------------------------------------------------------------------
# interval math (unit-tested on synthetic timelines)
# ----------------------------------------------------------------------

def merge_intervals(spans: List[Tuple[float, float]]
                    ) -> List[Tuple[float, float]]:
    """Sorted union of half-open [s, e) intervals (degenerate/negative
    spans dropped)."""
    spans = sorted((s, e) for s, e in spans if e > s)
    out: List[Tuple[float, float]] = []
    for s, e in spans:
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def intersect_len(span: Tuple[float, float],
                  merged: List[Tuple[float, float]]) -> float:
    """Length of ``span``'s intersection with a merged interval union."""
    s, e = span
    total = 0.0
    for ms, me in merged:
        if me <= s:
            continue
        if ms >= e:
            break
        total += min(e, me) - max(s, ms)
    return total


# ----------------------------------------------------------------------
# tolerant trace reading
# ----------------------------------------------------------------------

def find_trace_files(path: str) -> List[str]:
    """Every ``*.trace.json(.gz)`` under ``path`` (a file, a profile run
    dir, or a capture root containing ``plugins/profile/<run>/``),
    newest run first."""
    if os.path.isfile(path):
        return [path]
    if not os.path.isdir(path):
        return []
    hits: List[str] = []
    for root, _dirs, files in os.walk(path):
        for fn in files:
            if fn.endswith((".trace.json", ".trace.json.gz")):
                hits.append(os.path.join(root, fn))
    hits.sort(key=lambda p: os.path.getmtime(p), reverse=True)
    return hits


def read_trace_events(path: str) -> Tuple[List[dict], List[str]]:
    """(traceEvents, problems) of one trace-viewer JSON(.gz) file.
    A truncated/unreadable file returns ([], [named reason]) — the
    dead-tunnel artifact must degrade, never crash."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8",
                           errors="replace") as f:
                text = f.read()
        else:
            with open(path, encoding="utf-8", errors="replace") as f:
                text = f.read()
    except (OSError, EOFError) as e:
        return [], [f"unreadable trace file ({type(e).__name__}: {e})"]
    try:
        doc = json.loads(text)
    except ValueError as e:
        return [], [f"truncated/invalid trace JSON ({e})"]
    evs = doc.get("traceEvents") if isinstance(doc, dict) else None
    if not isinstance(evs, list):
        return [], ["no traceEvents array in trace JSON"]
    return evs, []


def _base_name(name: str) -> str:
    """HLO instruction base name: strip ``.clone``/``.N`` suffixes
    (``multiply_add_fusion.clone`` -> ``multiply_add_fusion``,
    ``all-reduce.0`` -> ``all-reduce``)."""
    while True:
        if name.endswith(".clone"):
            name = name[:-6]
            continue
        head, dot, tail = name.rpartition(".")
        if dot and tail.isdigit():
            name = head
            continue
        return name


def device_ops(events: List[dict]) -> List[dict]:
    """Normalized device-op records from raw trace events.

    A device op is a complete ("ph" == "X") event that names an XLA op:
    its args carry hlo metadata (``hlo_op``/``hlo_module``/
    ``hlo_category``/``tf_op``/``long_name`` — the CPU and TPU trace
    flavors between them), and it is not a container op.  Host-side
    python/runtime events (``$builtins ...``, ``TfrtCpuExecutable::*``)
    never qualify."""
    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args")
        if not isinstance(args, dict):
            continue
        if not any(k in args for k in ("hlo_op", "hlo_module",
                                       "hlo_category", "tf_op",
                                       "long_name")):
            continue
        name = str(e.get("name", ""))
        base = _base_name(name)
        if base in CONTAINER_OPS:
            continue
        try:
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        # searchable text: the name plus every string arg (TPU events
        # carry the full op_name stack in tf_op/long_name)
        text = " ".join([name] + [str(v) for v in args.values()
                                  if isinstance(v, str)])
        out.append({"name": name, "base": base, "ts": ts, "dur": dur,
                    "pid": e.get("pid", 0), "tid": e.get("tid", 0),
                    "text": text})
    return out


def is_collective(base: str) -> bool:
    return any(m in base for m in COLLECTIVE_MARKERS)


# ----------------------------------------------------------------------
# scope map (HLO instruction name -> phase)
# ----------------------------------------------------------------------

_METADATA_RE = re.compile(
    r"%?([A-Za-z0-9_.\-]+)\s*=\s*.*?op_name=\"([^\"]+)\"")


def scope_map_from_hlo_text(text: str) -> Dict[str, str]:
    """{instruction name: phase} for every instruction whose ``op_name``
    metadata carries a ``pcg/*`` named-scope label (the optimized-HLO
    ``as_text()`` of the profiled executable).  A label OUTSIDE the
    known phase set maps to the marker ``"?<label>"`` — the parser then
    counts it into ``unknown_scopes`` instead of silently folding a
    future phase into 'other' (the scope-labels loudness contract must
    hold on the sidecar path too, not just on TPU event text)."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        m = _METADATA_RE.search(line)
        if m is None:
            continue
        sm = _SCOPE_RE.search(m.group(2))
        if sm is None:
            continue
        phase = PHASE_SCOPES.get(f"pcg/{sm.group(1)}")
        out[m.group(1)] = (phase if phase is not None
                           else "?" + sm.group(1))
    return out


def scope_map_from_solver(solver, nrhs: int = 1) -> Dict[str, str]:
    """Best-effort scope map from the live solver's own compiled solve
    program (the one-shot step, or the blocked ``solve`` program at
    ``nrhs`` > 1).  Returns {} when the program cannot be re-lowered
    (e.g. an AOT-deserialized executable) — the parser then degrades to
    metadata-only bucketing and says so."""
    import jax
    import jax.numpy as jnp

    texts: List[str] = []
    try:
        if nrhs > 1:
            progs = solver._ensure_many_programs(int(nrhs))
            rdt = jnp.float64 if solver.mixed else solver.dtype
            fb = jax.ShapeDtypeStruct(
                (solver.pm.n_parts, solver.pm.n_loc, int(nrhs)), rdt)
            texts.append(
                progs["solve"].lower(solver.data, fb).compile().as_text())
        else:
            # _step_fn_jit is the plain jitted step kept lowerable even
            # when the AOT warm path replaced _step_fn (driver.py)
            fn = getattr(solver, "_step_fn_jit", None) or solver._step_fn
            delta = jnp.asarray(1.0, solver.dtype)
            texts.append(
                fn.lower(solver.data, solver.un, delta).compile().as_text())
    except Exception:                                   # noqa: BLE001
        return {}
    out: Dict[str, str] = {}
    for t in texts:
        out.update(scope_map_from_hlo_text(t))
    return out


def _base_scope_map(scope_map: Dict[str, str]) -> Dict[str, Optional[str]]:
    """Base-name fallback: a trace event name whose numeric suffix
    differs from the compiled text's (two lowerings of one program) maps
    through its base WHEN the base is unambiguous; an ambiguous base
    (two phases share it) maps to None — never a guess."""
    out: Dict[str, Optional[str]] = {}
    for name, phase in scope_map.items():
        b = _base_name(name)
        if b in out and out[b] != phase:
            out[b] = None
        else:
            out[b] = phase
    return out


# ----------------------------------------------------------------------
# bucketing + overlap
# ----------------------------------------------------------------------

def phase_of(op: dict, scope_map: Dict[str, str],
             base_map: Optional[Dict[str, Optional[str]]] = None,
             unknown_scopes: Optional[Dict[str, int]] = None,
             ) -> Optional[str]:
    """Phase of one device op: (1) a ``pcg/<label>`` substring in the
    event text (TPU metadata flavor) — an unrecognized label is COUNTED
    into ``unknown_scopes``; (2) the sidecar scope map by exact
    instruction name, then by unambiguous base name.  None = no phase
    (the ``other`` bucket)."""
    sm = _SCOPE_RE.search(op["text"])
    if sm is not None:
        label = f"pcg/{sm.group(1)}"
        phase = PHASE_SCOPES.get(label)
        if phase is not None:
            return phase
        if unknown_scopes is not None:
            unknown_scopes[sm.group(1)] = \
                unknown_scopes.get(sm.group(1), 0) + 1
    if scope_map:
        phase = scope_map.get(op["name"])
        if phase is None:
            if base_map is None:
                base_map = _base_scope_map(scope_map)
            phase = base_map.get(op["base"])
        if isinstance(phase, str) and phase.startswith("?"):
            # a sidecar-mapped label outside the known phase set:
            # counted, never silently dropped (see scope_map_from_hlo_text)
            if unknown_scopes is not None:
                label = phase[1:]
                unknown_scopes[label] = unknown_scopes.get(label, 0) + 1
            return None
        return phase
    return None


def bucket_phases(ops: List[dict], scope_map: Dict[str, str]
                  ) -> Dict[str, Any]:
    """Bucket device-op wall time per phase.  Nothing is dropped: time
    that matches no phase lands in ``other_ms``/``other_events``, and
    ``pcg/<x>`` labels outside the known four are counted in
    ``unknown_scopes`` — the scope-labels rule's loudness contract."""
    from pcg_mpi_solver_tpu.obs.perf import PHASES

    phases = {ph: {"us": 0.0, "events": 0} for ph in PHASES}
    other_us = 0.0
    other_events = 0
    unknown_scopes: Dict[str, int] = {}
    base_map = _base_scope_map(scope_map) if scope_map else {}
    for op in ops:
        ph = phase_of(op, scope_map, base_map, unknown_scopes)
        if ph in phases:
            phases[ph]["us"] += op["dur"]
            phases[ph]["events"] += 1
        else:
            other_us += op["dur"]
            other_events += 1
    return {"phases": phases, "other_us": other_us,
            "other_events": other_events,
            "unknown_scopes": unknown_scopes}


def collective_overlap(ops: List[dict]) -> Dict[str, Any]:
    """Measured collective-overlap: per device lane (trace pid), the
    wall-clock intersection of each collective op's span with the union
    of compute-op spans on OTHER threads of the same lane, as a
    fraction of total collective time.  Same-thread events are excluded
    (they are serialized with the collective by construction, and a
    parent span would fake overlap).  ``overlap_frac`` is None when the
    trace carries no collectives (single-device capture)."""
    colls = [o for o in ops if is_collective(o["base"])]
    if not colls:
        return {"n_collectives": 0, "coll_us": 0.0, "overlap_us": 0.0,
                "overlap_frac": None}
    computes = [o for o in ops if not is_collective(o["base"])]
    by_pid: Dict[Any, List[dict]] = {}
    for o in computes:
        by_pid.setdefault(o["pid"], []).append(o)
    coll_us = 0.0
    overlap_us = 0.0
    merged_cache: Dict[Tuple[Any, Any], List[Tuple[float, float]]] = {}
    for c in colls:
        span = (c["ts"], c["ts"] + c["dur"])
        coll_us += c["dur"]
        key = (c["pid"], c["tid"])
        if key not in merged_cache:
            merged_cache[key] = merge_intervals(
                [(o["ts"], o["ts"] + o["dur"])
                 for o in by_pid.get(c["pid"], ())
                 if o["tid"] != c["tid"]])
        overlap_us += intersect_len(span, merged_cache[key])
    return {"n_collectives": len(colls), "coll_us": coll_us,
            "overlap_us": overlap_us,
            "overlap_frac": (overlap_us / coll_us) if coll_us else None}


# ----------------------------------------------------------------------
# meta sidecar + capture
# ----------------------------------------------------------------------

def load_meta(trace_file: str) -> Optional[dict]:
    """The ``profview_meta.json`` sidecar next to (or up to two levels
    above) a trace file; None when absent/unreadable."""
    d = os.path.dirname(os.path.abspath(trace_file))
    for _ in range(3):
        p = os.path.join(d, PROFVIEW_META)
        if os.path.exists(p):
            try:
                with open(p, encoding="utf-8") as f:
                    return json.load(f)
            except (OSError, ValueError):
                return None
        nd = os.path.dirname(d)
        if nd == d:
            break
        d = nd
    return None


def newest_profile_artifact(root: str) -> Optional[str]:
    """The newest ``plugins/profile/<run>`` dir under a capture root (or
    the root itself when it directly holds trace files)."""
    files = find_trace_files(root)
    return os.path.dirname(files[0]) if files else None


def capture_solve_profile(solver, out_dir: str, nrhs: int = 1,
                          recorder=None, fn=None,
                          scope_map: Optional[Dict[str, str]] = None,
                          ) -> Dict[str, Any]:
    """Bounded one-shot profile capture around a warm solver dispatch.

    Runs one UNPROFILED dispatch first (compile + warm), then brackets a
    second one with ``jax.profiler.start_trace``/``stop_trace``, and
    writes the ``profview_meta.json`` sidecar (shape, committed
    iterations, whole-solve anchor, HLO scope map) into the run dir so
    the artifact parses offline.  Multi-process safe: each process
    captures into ``out_dir/p<idx>`` (two hosts must not race one trace
    directory — the same rule the telemetry shards follow).

    ``fn``: optional override dispatch, returning ``(iters, wall_s)``
    (default: ``solver.step(1.0)`` scalar / ``solver.solve_many`` at
    ``nrhs`` > 1, state reset around the measurement).  Emits a
    ``profile_capture`` telemetry event with the artifact path."""
    import jax

    pdir = out_dir
    if jax.process_count() > 1:
        pdir = os.path.join(out_dir, f"p{jax.process_index()}")
    os.makedirs(pdir, exist_ok=True)

    if fn is None:
        if nrhs > 1:
            import numpy as np

            F = np.repeat(np.asarray(solver._model.F)[:, None],
                          int(nrhs), axis=1)

            def fn():
                res = solver.solve_many(F)
                return int(res.iters.max(initial=1)), \
                    float(res.solve_wall_s)
        else:
            def fn():
                r = solver.step(1.0)
                solver.reset_state()
                return int(r.iters), float(r.wall_s)

    fn()                                    # warm: compile outside the trace
    jax.profiler.start_trace(pdir)
    try:
        iters, wall_s = fn()
    finally:
        jax.profiler.stop_trace()
    iters = max(1, int(iters))

    run_dir = newest_profile_artifact(pdir) or pdir
    if scope_map is None:
        scope_map = scope_map_from_solver(solver, nrhs=nrhs)
    scfg = solver.config.solver
    # lane count for per-iteration normalization: the mesh devices LOCAL
    # to this process — this process's trace carries only their events
    # (a multi-process capture divided by the GLOBAL device count would
    # undercount every phase by process_count)
    local_lanes = sum(
        1 for d in solver.mesh.devices.flat
        if getattr(d, "process_index", 0) == jax.process_index())
    meta = {
        "schema": PROFVIEW_META_SCHEMA,
        "pcg_variant": scfg.pcg_variant,
        "precond": scfg.precond,
        "nrhs": int(nrhs),
        "backend": str(solver.backend),
        "n_dof": int(solver.pm.glob_n_dof),
        "n_parts": int(solver.pm.n_parts),
        "n_devices": max(1, int(local_lanes)),
        "n_devices_global": int(solver.mesh.devices.size),
        "dtype": str(scfg.dtype),
        "mode": str(scfg.precision_mode),
        "platform": str(solver.mesh.devices.flat[0].platform),
        "iters": iters,
        "anchor_ms_per_iter": round(wall_s / iters * 1e3, 6),
        "wall_s": round(wall_s, 6),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "scope_map": scope_map,
    }
    meta_path = os.path.join(run_dir, PROFVIEW_META)
    try:
        with open(meta_path, "w", encoding="utf-8") as f:
            json.dump(meta, f, indent=1)
    except OSError:
        meta_path = None                    # artifact still parses degraded
    rec = recorder if recorder is not None else getattr(
        solver, "recorder", None)
    if rec is not None:
        rec.event("profile_capture", path=run_dir, source="capture",
                  iters=iters, wall_s=round(wall_s, 6),
                  scope_map_ops=len(scope_map))
    return {"artifact": run_dir, "meta": meta, "meta_path": meta_path,
            "iters": iters, "wall_s": wall_s}


# ----------------------------------------------------------------------
# the report
# ----------------------------------------------------------------------

def profile_report(path: str, meta: Optional[dict] = None,
                   iters: Optional[int] = None) -> Dict[str, Any]:
    """Parse a captured trace artifact into the ``prof_report`` payload:
    per-phase device-op wall time (ms, and ms/iter when the iteration
    count is known), the unbucketed remainder, unknown-scope counts, and
    the measured collective-overlap verdict.  Degrades to a NAMED
    verdict on every tolerated failure mode (missing file, truncated
    JSON, no device lanes, no sidecar) — never a crash."""
    problems: List[str] = []
    files = find_trace_files(path)
    events: List[dict] = []
    src = str(path)
    if not files:
        problems.append(f"no trace artifact under {path}")
    else:
        src = files[0]
        events, probs = read_trace_events(src)
        problems.extend(probs)
    if meta is None and files:
        meta = load_meta(src)
    meta = meta or {}
    if iters is None:
        iters = meta.get("iters")
    n_devices = int(meta.get("n_devices", 1) or 1)
    scope_map = meta.get("scope_map") or {}

    ops = device_ops(events)
    if events and not ops:
        problems.append("no device-op events in trace (device lanes "
                        "missing — host-only capture?)")
    buckets = bucket_phases(ops, scope_map)
    overlap = collective_overlap(ops)

    phases: Dict[str, Any] = {}
    sum_ms = 0.0
    sum_ms_per_iter = 0.0
    denom = (int(iters) * n_devices) if iters else None
    for ph, b in buckets["phases"].items():
        ms = b["us"] / 1e3
        sum_ms += ms
        per = round(ms / denom, 6) if denom else None
        if per is not None:
            sum_ms_per_iter += per
        phases[ph] = {"ms": round(ms, 6), "ms_per_iter": per,
                      "events": b["events"]}
    anchor = meta.get("anchor_ms_per_iter")
    attribution = (round(sum_ms_per_iter / anchor, 4)
                   if denom and anchor else None)
    # the trace-derived anchor: total device-op time per iteration —
    # what the trace can possibly attribute.  The wall anchor minus
    # this is the RUNTIME GAP (thunk scheduling, host dispatch,
    # transfers): reported explicitly, never silently absorbed into a
    # phase.  device_attribution is the four phases' share of it.
    other_per_iter = (round(buckets["other_us"] / 1e3 / denom, 6)
                      if denom else None)
    device_ms_per_iter = (round(sum_ms_per_iter + other_per_iter, 6)
                          if denom else None)
    device_attribution = (round(sum_ms_per_iter / device_ms_per_iter, 4)
                          if device_ms_per_iter else None)
    if not meta:
        problems.append("no profview_meta.json sidecar (per-iteration "
                        "normalization and the predicted column are "
                        "unavailable)")
    elif not scope_map and ops and buckets["other_events"] == len(ops):
        problems.append("empty scope map and no pcg/* labels in event "
                        "metadata — attribution is all 'other'")

    verdict = "ok" if not problems else "degraded: " + "; ".join(problems)
    return {
        "source": src,
        "verdict": verdict,
        "n_events": len(events),
        "n_device_ops": len(ops),
        "phases": phases,
        "sum_ms": round(sum_ms, 6),
        "sum_ms_per_iter": (round(sum_ms_per_iter, 6) if denom else None),
        "other_ms": round(buckets["other_us"] / 1e3, 6),
        "other_events": buckets["other_events"],
        "other_ms_per_iter": other_per_iter,
        "unknown_scopes": buckets["unknown_scopes"],
        "iters": iters,
        "n_devices": n_devices,
        "anchor_ms_per_iter": anchor,
        "attribution": attribution,
        "device_ms_per_iter": device_ms_per_iter,
        "device_attribution": device_attribution,
        "overlap_frac": overlap["overlap_frac"],
        "overlap": {k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in overlap.items()},
        "pcg_variant": meta.get("pcg_variant"),
        "precond": meta.get("precond"),
        "nrhs": meta.get("nrhs"),
        "backend": meta.get("backend"),
        "n_dof": meta.get("n_dof"),
        "platform": meta.get("platform"),
    }


def emit_prof_report(recorder, report: Dict[str, Any]) -> None:
    """Emit one parsed report as the schema-versioned ``prof_report``
    event plus the ``prof.*`` gauges."""
    recorder.event("prof_report", **report)
    for ph, d in report["phases"].items():
        if d.get("ms_per_iter") is not None:
            recorder.gauge(f"prof.{ph}_ms_per_iter", d["ms_per_iter"])
    if report.get("overlap_frac") is not None:
        recorder.gauge("prof.overlap_frac",
                       round(report["overlap_frac"], 6))
    if report.get("attribution") is not None:
        recorder.gauge("prof.attribution", report["attribution"])
    recorder.gauge("prof.other_ms", report["other_ms"])


def predicted_from_meta(meta: dict) -> Optional[dict]:
    """The obs/perf.py cost model rebuilt from a capture sidecar (the
    predicted column of the offline report); None when the meta carries
    no usable shape.  Unknown variant/precond names stay loud
    (KeyError — the single-source-table contract)."""
    from pcg_mpi_solver_tpu.obs import perf as _perf

    if not meta:
        return None
    try:
        shape = _perf.shape_from_detail(meta)
        if shape is None:
            return None
        return _perf.cost_model(
            shape, str(meta.get("pcg_variant", "classic")),
            str(meta.get("precond", "jacobi")),
            int(meta.get("nrhs", 1) or 1),
            _perf.resolve_profile(str(meta.get("platform", "cpu"))))
    except KeyError:
        raise
    except Exception:                                   # noqa: BLE001
        return None


def format_report(report: Dict[str, Any],
                  predicted: Optional[dict] = None,
                  recorded: Optional[dict] = None) -> str:
    """Human table of one parsed report: per-phase rows with the
    predicted (cost model) and recorded (phase probes) columns when
    available next to the trace-measured ms/iter, then the overlap
    verdict and the degraded-mode notes."""
    from pcg_mpi_solver_tpu.obs.perf import PHASES

    per_iter = report.get("sum_ms_per_iter") is not None
    lines = []
    lines.append(f"{'phase':<10} {'predicted':>10} {'recorded':>10} "
                 + (f"{'measured':>10} {'share':>7}" if per_iter
                    else f"{'measured_ms':>12} {'share':>7}"))
    total = report["sum_ms"] or 0.0
    pred_sum = 0.0
    for ph in PHASES:
        d = report["phases"].get(ph, {})
        meas = d.get("ms_per_iter") if per_iter else d.get("ms", 0.0)
        share = (d.get("ms", 0.0) / total) if total else 0.0
        pm = (predicted["phases"][ph]["model_ms"]
              if predicted is not None else None)
        pred_sum += pm or 0.0
        rm = (recorded or {}).get(ph)
        pm_s = f"{pm:>10.4f}" if pm is not None else f"{'-':>10}"
        rm_s = f"{rm:>10.4f}" if rm is not None else f"{'-':>10}"
        ms_s = (f"{meas:>10.4f}" if per_iter
                else f"{meas:>12.3f}")
        lines.append(f"{ph:<10} {pm_s} {rm_s} {ms_s} {share:>6.0%}")
    sum_meas = (report["sum_ms_per_iter"] if per_iter
                else report["sum_ms"])
    ps = f"{pred_sum:>10.4f}" if predicted is not None else f"{'-':>10}"
    lines.append(f"{'sum':<10} {ps} {'':>10} "
                 + (f"{sum_meas:>10.4f}" if per_iter
                    else f"{sum_meas:>12.3f}"))
    lines.append(f"other (unbucketed): {report['other_ms']:.3f} ms over "
                 f"{report['other_events']} event(s)")
    if report.get("unknown_scopes"):
        lines.append("UNKNOWN pcg/* scope labels (counted, not "
                     f"dropped): {report['unknown_scopes']}")
    if report.get("device_ms_per_iter") is not None:
        lines.append(
            f"device-op anchor: {report['device_ms_per_iter']:.4f} "
            f"ms/iter ({report.get('iters')} iters, "
            f"{report.get('n_devices')} device(s)); phase share of "
            f"device-op time: {report.get('device_attribution')}")
    if report.get("anchor_ms_per_iter"):
        gap = None
        if report.get("device_ms_per_iter") is not None:
            gap = (report["anchor_ms_per_iter"]
                   - report["device_ms_per_iter"])
        lines.append(
            f"wall anchor: {report['anchor_ms_per_iter']:.4f} ms/iter; "
            f"attribution (phase sum / wall): "
            f"{report.get('attribution')}"
            + (f"; runtime gap (scheduling/dispatch, outside every "
               f"device op): {gap:.4f} ms/iter" if gap is not None
               else ""))
    ov = report["overlap"]
    if report.get("overlap_frac") is not None:
        lines.append(
            f"collective overlap: {report['overlap_frac']:.3f} "
            f"({ov['overlap_us'] / 1e3:.3f} of {ov['coll_us'] / 1e3:.3f}"
            f" ms across {ov['n_collectives']} collective op(s) hidden "
            "behind concurrent compute)")
    elif ov["n_collectives"]:
        # collectives present but zero total duration (e.g. bare async
        # -start markers): a fraction of nothing is n/a, not a crash
        lines.append(f"collective overlap: n/a ({ov['n_collectives']} "
                     "collective op(s) carry zero duration)")
    else:
        lines.append("collective overlap: n/a (no collective ops in "
                     "trace — single-device capture?)")
    lines.append(f"verdict: {report['verdict']}")
    return "\n".join(lines)
