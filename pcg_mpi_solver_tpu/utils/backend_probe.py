"""Fail-fast accelerator-backend probe.

On a tunneled TPU a dead relay makes the first backend touch
(``jax.devices()``) block forever in a native retry loop that Python
cannot interrupt — a caller would then eat its supervisor's whole timeout
with zero diagnostics.  Probing in a subprocess turns that into a quick,
explained failure.  The probe is skipped when it cannot add information:
when the env pins the CPU backend (cannot hang on a tunnel), or when a
backend is already live in this process (first touch already happened —
and on process-exclusive TPUs a subprocess probe would falsely fail
against our own device lock).
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Tuple


def pin_cpu_backend_if_requested() -> None:
    """Apply the JAX_PLATFORMS=cpu env request as an IN-PROCESS config pin.

    The env var alone does NOT stop a sitecustomize-registered TPU plugin
    (axon) from initializing on the first jax.devices()/jit touch — and
    that init HANGS uninterruptibly when the accelerator tunnel is wedged
    (observed 2026-07-31).  Only the explicit config.update pins the
    backend for real (jax pre-populates the config from the env var, so
    the value can look set already — update unconditionally, it is
    idempotent).  Call BEFORE any device touch; no-op unless the env
    requests cpu."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def backend_live() -> bool:
    """True when a JAX backend is already initialized in this process."""
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:                                  # noqa: BLE001
        return False


def probe_backend(timeout_s: float = 180.0) -> Tuple[bool, str]:
    """Returns (ok, detail).  detail explains a failure for the operator."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return True, "cpu backend pinned; probe skipped"
    if backend_live():
        return True, "backend already live in this process; probe skipped"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return False, (
            f"backend init did not complete within {timeout_s:.0f}s — "
            "accelerator tunnel/relay is unreachable (dead relay process, "
            "or the device is held by a wedged session)")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-8:]
        return False, (
            f"backend init failed (rc={proc.returncode}):\n"
            + "\n".join(tail))
    return True, "ok"


def settle_compile(max_attempts: int = 4,
                   timeout_s: float = 180.0) -> Tuple[bool, str]:
    """Verify the (possibly remote) compile service answers by compiling
    a trivial jitted function, retrying with backoff.

    A failed remote compile (e.g. a Mosaic probe rejection) can wedge the
    tunnel's device grant for minutes (docs/RUNBOOK.md); unlike
    :func:`probe_backend` this exercises the COMPILE path specifically.
    With a LIVE in-process backend the probe compiles in-process through
    our own client (a subprocess would contend with our own exclusive
    device grant — the false-failure mode probe_backend's backend_live()
    skip exists for), but inside a worker thread with a timeout, because
    a wedged backend can hang (not error) in a native retry loop Python
    cannot interrupt.  Without a live backend it probes in a SUBPROCESS
    with a timeout for the same reason.  The probe shape is pid/time-
    derived so a persistent compile-cache hit cannot fake health on
    repeat invocations."""
    import time

    live = backend_live()
    detail = "no attempt ran"
    for attempt in range(max_attempts):
        # odd sublane count -> unlikely to collide with real programs
        n = 8 * (attempt + 3) + 123 + 8 * ((os.getpid()
                                            + int(time.time())) % 1024)
        if live:
            import threading

            # a DAEMON thread, not a ThreadPoolExecutor worker:
            # concurrent.futures joins its (non-daemon) workers at
            # interpreter shutdown, so a native-hung compile probe would
            # hang process EXIT — the exact wedged-tunnel hang this
            # helper exists to bound
            result = {}
            done = threading.Event()

            def _probe():
                try:
                    import jax
                    import jax.numpy as jnp

                    jax.jit(lambda x: (x * 3 + 1).sum()).lower(
                        jax.ShapeDtypeStruct((n, 128), jnp.float32)).compile()
                    result["ok"] = True
                except Exception as e:                  # noqa: BLE001
                    result["err"] = e
                done.set()

            threading.Thread(target=_probe, daemon=True).start()
            if done.wait(timeout=timeout_s):
                if result.get("ok"):
                    return True, f"compile service ok (attempt {attempt + 1})"
                e = result["err"]
                detail = (f"compile probe failed "
                          f"({type(e).__name__}: {e})")
            else:
                # native-hung thread: daemon, so it cannot block exit
                detail = f"compile probe hung past {timeout_s:.0f}s"
        else:
            code = (f"import jax, jax.numpy as jnp; "
                    f"jax.jit(lambda x: (x * 3 + 1).sum()).lower("
                    f"jax.ShapeDtypeStruct(({n}, 128), "
                    f"jnp.float32)).compile()")
            try:
                proc = subprocess.run([sys.executable, "-c", code],
                                      timeout=timeout_s, capture_output=True,
                                      text=True)
            except subprocess.TimeoutExpired:
                detail = f"compile probe hung past {timeout_s:.0f}s"
            else:
                if proc.returncode == 0:
                    return True, f"compile service ok (attempt {attempt + 1})"
                tail = (proc.stderr or "").strip().splitlines()[-4:]
                detail = (f"compile probe rc={proc.returncode}: "
                          + " | ".join(tail))
        if attempt + 1 < max_attempts:
            time.sleep(30.0 * (attempt + 1))
    return False, (f"compile service still failing after "
                   f"{max_attempts} attempts ({detail})")


def probe_or_exit(timeout_s: float = 180.0) -> None:
    """Probe-or-die preamble for accelerator-targeting example scripts:
    fail in ~3 min (exit 2) instead of hanging until a queue step's
    timeout when the tunnel is down (wave-5 burned ~50 min of queue
    budget on two probe-less examples hanging on a dead backend)."""
    import sys

    ok, detail = probe_backend(timeout_s=timeout_s)
    if not ok:
        print(f"accelerator unreachable: {detail}", flush=True)
        sys.exit(2)
