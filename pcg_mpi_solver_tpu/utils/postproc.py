"""Offline result post-processing: crack-tip tracking and probe time
histories.

Re-designs the reference's dynamics/damage-era offline tools
(file_operations.py:542-787):

- ``calcCrackTipVelocity_TensileBranching`` / ``_Shear`` /
  ``calcCrackTipCoord_CrkArrest`` (:542-726): per frame, rebuild the global
  damage field, select nodes with D >= threshold inside a geometric window,
  take the extremal node along a tracking axis; double-pass moving-average
  smoothing; cumulative crack length; 3-point least-squares slope as the tip
  velocity.
- ``getTimeHistoryData`` (:728-787): locate nodes at given coordinates and
  sample U / nodal-field frames over all time steps, saved as a .mat.

Here they are generic (no hardcoded geometry windows) functions over a
RunStore + ModelData.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.utils.io import RunStore


def global_nodal_frame(store: RunStore, model: ModelData, var: str, k: int,
                       node_map: Optional[np.ndarray] = None) -> np.ndarray:
    """Rebuild a global (n_node,) nodal field from an owner-masked frame
    (reference: A[ResNodeId] = InpData, file_operations.py:569-571)."""
    if node_map is None:
        node_map = store.read_map("NodeId")
    data = store.read_frame(var, k)
    a = np.zeros(model.n_node, dtype=data.dtype)
    a[node_map] = data
    return a


def global_dof_frame(store: RunStore, model: ModelData, k: int,
                     dof_map: Optional[np.ndarray] = None) -> np.ndarray:
    """Rebuild the global (n_dof,) displacement from a 'U' frame."""
    if dof_map is None:
        dof_map = store.read_map("Dof")
    data = store.read_frame("U", k)
    a = np.zeros(model.n_dof, dtype=data.dtype)
    a[dof_map] = data
    return a


def smooth_moving_average(x: np.ndarray, half_window: int = 25,
                          passes: int = 2) -> np.ndarray:
    """Reference smoothing (file_operations.py:581-590): centered moving
    average of width 2*half_window+1 applied ``passes`` times; entries within
    half_window of either end are zeroed (exact reference semantics)."""
    out = np.asarray(x, dtype=float)
    n = len(out)
    for _ in range(passes):
        sm = np.zeros_like(out)
        for q in range(half_window, n - half_window):
            sm[q] = np.mean(out[q - half_window:q + half_window + 1], axis=0)
        out = sm
    return out


def crack_tip_history(
    store: RunStore,
    model: ModelData,
    threshold: float = 0.9,
    window: Optional[np.ndarray] = None,
    track_axis: int = 0,
    damage_var: str = "D",
    n_frames: Optional[int] = None,
) -> np.ndarray:
    """Per-frame crack-tip coordinates (n_frames, 3).

    Frame loop of the reference trackers (file_operations.py:565-576): nodes
    with damage >= ``threshold`` and ``window`` True (a boolean node mask
    replacing the hardcoded ``Nodes[:,1] < 0.02``-style selections), tip =
    the one maximal along ``track_axis``.  Frames with no damaged node keep
    (0, 0, 0), like the reference's zero-initialized array."""
    node_map = store.read_map("NodeId")
    if n_frames is None:
        n_frames = store.n_frames(damage_var)
    if window is None:
        window = np.ones(model.n_node, dtype=bool)
    tips = np.zeros((n_frames, 3))
    for k in range(n_frames):
        D = global_nodal_frame(store, model, damage_var, k, node_map)
        sel = (D >= threshold) & window
        if np.any(sel):
            coords = model.node_coords[sel]
            tips[k] = coords[np.argmax(coords[:, track_axis])]
    return tips


def crack_length_and_velocity(times: np.ndarray, tips: np.ndarray):
    """Cumulative crack length + tip velocity (file_operations.py:595-605):
    length increments are Euclidean tip displacements; velocity at q is the
    slope of a 3-point linear fit of length vs time."""
    n = len(times)
    crk_len = np.zeros(n)
    for q in range(1, n):
        crk_len[q] = crk_len[q - 1] + np.linalg.norm(tips[q] - tips[q - 1])
    vel = np.zeros(n)
    for q in range(1, n - 1):
        vel[q] = np.polyfit(times[q - 1:q + 2], crk_len[q - 1:q + 2], 1)[0]
    return crk_len, vel


def calc_crack_tip_velocity(
    store: RunStore,
    model: ModelData,
    threshold: float = 0.9,
    window: Optional[np.ndarray] = None,
    track_axis: int = 0,
    smooth_half_window: int = 25,
    drop_last: int = 10,
) -> Dict:
    """Full reference pipeline (calcCrackTipVelocity_*, :542-677): track ->
    double smooth -> length -> velocity; saves ``CrackTipVelData.npy`` beside
    the run's ResVecData like the reference (:608)."""
    times = store.read_time_list()
    n_frames = max(len(times) - drop_last, 0)
    tips = crack_tip_history(store, model, threshold, window, track_axis,
                             n_frames=n_frames)
    tips = smooth_moving_average(tips, smooth_half_window, passes=2)
    crk_len, vel = crack_length_and_velocity(times[:n_frames], tips)
    out = {"CTVel": vel, "DmgNodeCoord": tips, "CrkLen": crk_len,
           "Time_T": times[:n_frames]}
    payload = np.empty(4, dtype=object)
    payload[:] = [vel, tips, crk_len, times[:n_frames]]
    np.save(f"{store.result_path}/CrackTipVelData", payload, allow_pickle=True)
    return out


def find_nodes_at(model: ModelData, ref_coords: np.ndarray,
                  tol: float = 1e-12) -> np.ndarray:
    """Node ids at exact coordinates (reference getTimeHistoryData
    coordinate lookup, file_operations.py:755-765); raises if any is
    missing, like the reference."""
    ids = []
    for c in np.atleast_2d(ref_coords):
        hit = np.where(np.all(np.abs(model.node_coords - c) < tol, axis=1))[0]
        if len(hit) == 0:
            raise ValueError(f"no node at coordinates {c}")
        ids.append(hit[0])
    return np.asarray(ids)


def get_time_history_data(
    store: RunStore,
    model: ModelData,
    ref_coords: np.ndarray,
    nodal_vars: Sequence[str] = ("PS1",),
    dof_component: int = 0,
    tol: float = 1e-12,
    save_mat: bool = True,
) -> Dict:
    """Sample displacement component + nodal fields at probe coordinates over
    every frame (reference getTimeHistoryData, file_operations.py:728-787);
    optionally saves ``TimeHistoryData.mat`` like the reference (:787)."""
    node_ids = find_nodes_at(model, ref_coords, tol)
    dof_map = store.read_map("Dof")
    node_map = store.read_map("NodeId") if nodal_vars else None
    times = store.read_time_list()
    out: Dict = {"T": times, "U": []}
    for v in nodal_vars:
        out[v] = []
    for k in range(len(times)):
        u = global_dof_frame(store, model, k, dof_map)
        out["U"].append(u[dof_component::3][node_ids])
        for v in nodal_vars:
            a = global_nodal_frame(store, model, v, k, node_map)
            out[v].append(a[node_ids])
    out["U"] = np.asarray(out["U"])
    for v in nodal_vars:
        out[v] = np.asarray(out[v])
    if save_mat:
        import scipy.io

        scipy.io.savemat(f"{store.result_path}/TimeHistoryData.mat", out)
    return out
