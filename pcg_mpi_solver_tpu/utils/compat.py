"""JAX version compatibility shims.

The framework targets the modern ``jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=...)`` entry point.  Older jax releases (< 0.5)
ship the same machinery as ``jax.experimental.shard_map.shard_map`` with
the replication check spelled ``check_rep``.  ``ensure_shard_map()``
installs a signature-adapting alias at ``jax.shard_map`` so every call
site (and downstream user code written against the new spelling) runs
unchanged on both.

Called once from the package ``__init__`` — importing any part of the
framework guarantees the alias exists.
"""

from __future__ import annotations

import functools


def ensure_shard_map() -> None:
    import jax

    try:
        if getattr(jax, "shard_map", None) is not None:
            return                          # modern jax: nothing to do
    except Exception:                       # noqa: BLE001 — deprecation
        pass                                # __getattr__ may raise; shim it
    from jax.experimental.shard_map import shard_map as _legacy

    @functools.wraps(_legacy)
    def shard_map(f=None, /, **kwargs):
        # new-API spelling of the replication check -> legacy keyword
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:                       # decorator-style partial use
            return functools.partial(shard_map, **kwargs)
        return _legacy(f, **kwargs)

    jax.shard_map = shard_map
