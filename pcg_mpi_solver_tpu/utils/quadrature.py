"""Numerical integration tables (reference GaussIntegrationTable /
GaussLobattoIntegrationTable, file_operations.py:177-247).

The reference hardcodes closed-form Gauss-Legendre nodes for 1-4 points and
Gauss-Lobatto for 2-5; here arbitrary orders come from
``numpy.polynomial.legendre`` with the same (nodes, weights) convention on
[-1, 1], plus a tensor-product helper for hexahedral elements.
"""

from __future__ import annotations

import numpy as np


def gauss_table(n_points: int):
    """Gauss-Legendre nodes/weights on [-1, 1]; exact for degree 2n-1."""
    if n_points < 1:
        raise ValueError("n_points must be >= 1")
    ni, wi = np.polynomial.legendre.leggauss(n_points)
    return ni, wi


def gauss_lobatto_table(n_points: int):
    """Gauss-Lobatto nodes/weights on [-1, 1] (endpoints included); exact for
    degree 2n-3.  Nodes are the roots of P'_{n-1} plus the endpoints;
    weights w_i = 2 / (n(n-1) P_{n-1}(x_i)^2)."""
    if n_points < 2:
        raise ValueError("n_points must be >= 2")
    n = n_points
    Pn1 = np.polynomial.legendre.Legendre.basis(n - 1)
    interior = Pn1.deriv().roots()
    ni = np.concatenate([[-1.0], np.sort(np.real(interior)), [1.0]])
    wi = 2.0 / (n * (n - 1) * Pn1(ni) ** 2)
    return ni, wi


def gauss_points_3d(n_points: int):
    """Tensor-product Gauss rule on the reference cube [-1,1]^3.

    Returns (points (n^3, 3), weights (n^3,)) — the integration layout for
    hexahedral pattern elements."""
    ni, wi = gauss_table(n_points)
    X, Y, Z = np.meshgrid(ni, ni, ni, indexing="ij")
    WX, WY, WZ = np.meshgrid(wi, wi, wi, indexing="ij")
    pts = np.stack([X.ravel(), Y.ravel(), Z.ravel()], axis=1)
    w = (WX * WY * WZ).ravel()
    return pts, w
