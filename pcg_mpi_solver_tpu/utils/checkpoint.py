"""In-solve checkpoint/resume.

The reference has NO in-solve checkpointing: its pipeline is resumable only
at stage granularity because every stage persists its output to disk, and
per-frame exports double as (unexploited) restart data (reference:
SURVEY.md §5; pcg_solver.py:891-894 persists per-frame ResVecData).  This
module closes that gap for the multi-step quasi-static schedule: after any
completed time step the full solver state (solution vector, convergence
histories, export counters) can be written and a later run continues from
the next step, producing byte-identical histories and export frames.

Two record granularities share the directory and the fingerprint guard:

* ``ckpt_{t:06d}.npz`` — full solver state after COMPLETED step ``t``
  (:class:`CheckpointManager`), plus the atomically-published ``latest``
  pointer.  When the pointer references a missing/corrupt file, resume
  falls back to the newest valid checkpoint instead of failing.
* ``snap_{t:06d}.npz`` — mid-Krylov snapshot INSIDE step ``t``
  (:class:`SnapshotStore`, resilience subsystem): the resumable dispatch
  carry of the chunked budget loop, persisted every N chunks so a killed
  process or lost device loses at most one snapshot interval and
  ``--resume`` continues mid-solve with bit-identical history.
* ``step_{t:06d}.npz`` — full kinematic state after COMPLETED timestep
  ``t`` of a dynamics/Newmark time history (:class:`SnapshotStore` with
  ``prefix="step"``, driven by ``resilience/engine.TimeHistoryGuard``):
  kill-and-resume continues MID-TIME-HISTORY with bit-identical
  probe/frame history, and on-disk retention is bounded to the newest K
  files (``PCG_TPU_SNAP_KEEP``).
* ``many_{t:06d}.npz`` — mid-solve blocked carry of a batched multi-RHS
  solve (:class:`SnapshotStore.for_many_solver`, driven by
  ``resilience/engine.run_many_with_recovery``): the fingerprint embeds
  the block width AND the rhs content hash.  Retention pruning and the
  corrupt-tolerant :meth:`SnapshotStore.latest` pointer are
  PREFIX-SCOPED, so they govern this namespace exactly like
  ``snap_*``/``step_*`` (asserted in tests/test_pcg_many.py).

A fingerprint of the model and solver configuration guards all of them
against resuming with mismatched state.
"""

from __future__ import annotations

import glob as _glob
import hashlib
import json
import os
import warnings
from typing import Any, Dict, Optional, Tuple

import numpy as np


def _model_hash(solver) -> str:
    """Content hash of the model the solver was built from: resuming a
    checkpoint against a model with identical shapes but different material
    fields / loads / partitioning would silently produce garbage."""
    h = hashlib.sha256()
    m = getattr(solver, "_model", None)
    if m is not None:
        for arr in (m.ck, m.cm, m.ce, m.F, m.Ud, m.fixed_dof,
                    m.elem_type, m.elem_dofs_flat, m.elem_sign_flat,
                    m.node_coords):
            a = np.ascontiguousarray(arr)
            h.update(str(a.dtype).encode())
            h.update(a.tobytes())
        # The material law enters only via the per-type element matrices
        # (e.g. a different Poisson ratio changes Ke but none of the arrays
        # above) and mat_prop — hash them too.
        for t in sorted(m.elem_lib):
            h.update(np.ascontiguousarray(m.elem_lib[t]["Ke"]).tobytes())
        # Material identity is POSITIONAL (poly_mat indexes mat_prop, e.g.
        # nonlocal_stress.py groups by poly_mat==m) — keep list order and
        # canonicalize key order recursively (incl. nested param dicts).
        h.update(json.dumps(m.mat_prop, sort_keys=True,
                            default=repr).encode())
    ep = getattr(solver.pm, "elem_part", None)
    if ep is not None:
        h.update(np.ascontiguousarray(ep).tobytes())
    return h.hexdigest()


def _process_count() -> int:
    """Process-group size for the fingerprint, without importing jax as
    a side effect of loading this module (a backend not initialized yet
    reads as single-process — the value every legacy record implies)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 1
    try:
        return int(jax.process_count())
    except Exception:                                   # noqa: BLE001
        return 1


def _fingerprint(solver) -> dict:
    """Everything that must not drift between checkpoint and resume: the
    model content, the numerics (precision/tol), the schedule values, and
    the export/plot config (counters in the state refer to them)."""
    cfg = solver.config
    th = cfg.time_history
    return {
        "model_hash": _model_hash(solver),
        "glob_n_dof": int(solver.pm.glob_n_dof),
        "n_parts": int(solver.pm.n_parts),
        "n_loc": int(solver.pm.n_loc),
        "dtype": str(np.dtype(solver.dtype)),
        "precision_mode": cfg.solver.precision_mode,
        "precond": cfg.solver.precond,
        # MG-shape components (ISSUE 10): the V-cycle's level count /
        # smoothing degree / lattice dims reshape both the traced apply
        # and its numerical sequence — a resume across any of them must
        # fail as a named mismatch.  "n/a" for non-mg solvers (and for
        # every pre-mg record via the restore/load legacy shims).
        "mg_shape": _mg_shape(solver),
        # the PCG loop formulation reshapes the resumable carry pytree
        # itself (the fused variant rides q/alpha/fresh recurrence
        # leaves) and changes the iteration sequence — a cross-variant
        # resume must fail HERE, as a clear fingerprint mismatch, not as
        # a pytree/in_specs error deep in the shard_map dispatch
        "pcg_variant": getattr(cfg.solver, "pcg_variant", "classic"),
        # RHS-block width: the quasi-static/dynamics solve paths are
        # always width 1; solve_many snapshots override this with the
        # actual block width (SnapshotStore.for_many_solver), so a
        # blocked resume against a different-width block fails HERE as a
        # clear fingerprint mismatch instead of a pytree shape error
        # deep in the shard_map dispatch
        "nrhs": 1,
        "tol": float(cfg.solver.tol),
        "max_iter": int(cfg.solver.max_iter),
        # every remaining trace-affecting numerics knob (found
        # mechanically by the analysis/ fingerprint-completeness rule —
        # the PR-5/PR-6 bug class, closed wholesale): the reduction
        # accumulation dtype, the MATLAB stagnation window, the mixed
        # engine's cycle tolerance + exit knobs, and the in-graph trace
        # ring length (the ring rides the resumable carry pytree, so a
        # different length is a different carry shape).
        "dot_dtype": str(np.dtype(cfg.solver.dot_dtype)),
        "max_stag_steps": int(cfg.solver.max_stag_steps),
        "inner_tol": float(cfg.solver.inner_tol),
        "mixed_knobs": [int(cfg.solver.mixed_plateau_window),
                        int(cfg.solver.mixed_progress_window),
                        float(cfg.solver.mixed_progress_ratio),
                        float(cfg.solver.mixed_progress_min_gain)],
        "trace_len": int(getattr(solver, "trace_len", 0)),
        # process-group shape: shard-per-rank snapshot epochs
        # (resilience/distributed.GroupSnapshotStore) are written by N
        # cooperating processes; a same-count resume must match, and a
        # different-count restore is only legal through the NAMED
        # elastic path (Solver.resume_elastic), never silently.
        "n_procs": _process_count(),
        "deltas": [float(d) for d in th.time_step_delta],
        "export": [bool(th.export_flag), int(th.export_frame_rate),
                   [int(f) for f in th.export_frames], th.export_vars],
        "plot": [bool(th.plot_flag), [int(d) for d in th.probe_dofs]],
        "backend": solver.backend,
        # EFFECTIVE kernel choice, not the "auto" knob: each Pallas matvec
        # variant has its own summation order (changes iteration counts,
        # breaking exact resume), but kernels only ever execute on f32
        # matvecs — a pure-f64 direct run is byte-identical either way.
        "pallas": _effective_kernel(solver),
        # same summation-order hazard for the stencil backends: the XLA
        # formulation (gse vs corner) and the hybrid level-grid block
        # layout both reorder the pad-accumulate sums.  Both are PINNED
        # on the ops at construction (ops.form / ops.level_dims — the
        # env knobs cannot drift between trace and save), and ops
        # without a form attribute (general backend) never read the
        # knob.
        "matvec_form": getattr(solver.ops, "form", "n/a"),
        "level_dims": [list(d) for d in getattr(solver.ops, "level_dims",
                                                ())],
        # the hybrid level combine (gather vs scatter) also reorders the
        # slot accumulation — pinned on the ops at construction; KD (the
        # dense/heavy split of the gather maps) reorders it too and is
        # frozen in the partition's built maps
        "combine": getattr(solver.ops, "combine", "n/a"),
        "combine_kd": _combine_kd(solver),
        # the general-form f64 refresh (hybrid+mixed) reorders the
        # refresh-residual summation — pinned on the solver at
        # construction like the kernel variant
        "f64_refresh": getattr(solver, "f64_refresh", "stencil"),
    }


def _mg_shape(solver):
    """The structural MG components of a solver configured with
    precond='mg' (driver/newmark stamp ``_mg_meta`` at setup), else
    "n/a" — JSON-stable for the fingerprint compare."""
    meta = getattr(solver, "_mg_meta", None)
    if not meta:
        return "n/a"
    return [int(meta["levels"]), int(meta["degree"]),
            [int(v) for v in meta["dims"]]]


def _combine_kd(solver) -> int | str:
    # only meaningful when the gather combine is the engaged path (KD
    # does not touch scatter-mode numerics)
    if getattr(solver.ops, "combine", "n/a") != "gather":
        return "n/a"
    cm = getattr(getattr(solver, "pm", None), "combine", None)
    return int(cm.gidx.shape[-1]) if cm is not None else "n/a"


def _effective_kernel(solver) -> str:
    """The variant this solver COMPILED (pinned at construction — the env
    knob is read at trace time, so the env at save() time is irrelevant),
    gated on an f32 matvec path actually existing."""
    if not (getattr(solver.ops, "use_pallas", False)
            and (solver.mixed or np.dtype(solver.dtype) == np.float32)):
        return "off"
    return getattr(solver, "pallas_variant", "off")


def state_dict(solver) -> dict:
    """Everything needed to continue ``solve()`` after step ``t``."""
    from pcg_mpi_solver_tpu.parallel.distributed import fetch_global

    return {
        "un": fetch_global(solver.un, solver.mesh),
        "flags": np.asarray(solver.flags, dtype=np.int64),
        "relres": np.asarray(solver.relres, dtype=np.float64),
        "iters": np.asarray(solver.iters, dtype=np.int64),
        "step_times": np.asarray(solver.step_times, dtype=np.float64),
        "export_count": np.int64(getattr(solver, "_export_count", 0)),
        "export_times": np.asarray(getattr(solver, "_export_times", []),
                                   dtype=np.float64),
        "export_wall": np.float64(solver._export_wall),
        "probe_u": (np.stack(solver._probe_u)
                    if getattr(solver, "_probe_u", [])
                    else np.zeros((0, 0))),
    }


def load_state_dict(solver, state: dict) -> None:
    from pcg_mpi_solver_tpu.parallel.distributed import put_sharded

    solver.un = put_sharded(
        np.asarray(state["un"], dtype=solver.dtype),
        solver.mesh, solver._part_spec)
    solver.flags = [int(v) for v in state["flags"]]
    solver.relres = [float(v) for v in state["relres"]]
    solver.iters = [int(v) for v in state["iters"]]
    solver.step_times = [float(v) for v in state["step_times"]]
    solver._export_count = int(state["export_count"])
    solver._export_times = [float(v) for v in state["export_times"]]
    solver._export_wall = float(state.get("export_wall", 0.0))
    probe = np.asarray(state["probe_u"])
    solver._probe_u = [] if probe.size == 0 else [row for row in probe]


class CheckpointManager:
    """Writes/reads per-step solver checkpoints under one directory."""

    def __init__(self, path: str):
        self.path = path

    def _ckpt_file(self, t: int) -> str:
        return os.path.join(self.path, f"ckpt_{t:06d}.npz")

    def save(self, solver, t: int) -> str:
        """Checkpoint solver state after completed step ``t``.

        Multi-host safe: state_dict's device fetch is collective and runs on
        every process; only process 0 touches the filesystem (the analogue
        of the reference's rank-0-gated writes, file_operations.py:348-396)."""
        payload = dict(state_dict(solver))
        from pcg_mpi_solver_tpu.utils.io import is_primary

        out = self._ckpt_file(t)
        if not is_primary():
            return out
        os.makedirs(self.path, exist_ok=True)
        tmp = out + ".tmp"
        payload["t"] = np.int64(t)
        payload["fingerprint"] = np.frombuffer(
            json.dumps(_fingerprint(solver), sort_keys=True).encode(),
            dtype=np.uint8).copy()
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **payload)
        os.replace(tmp, out)
        ptr = os.path.join(self.path, "latest")
        with open(ptr + ".tmp", "w") as f:
            f.write(os.path.basename(out))
        os.replace(ptr + ".tmp", ptr)
        return out

    @staticmethod
    def _valid_step(path: str) -> Optional[int]:
        """The step index of a readable checkpoint file, else None (a
        truncated/corrupt npz — e.g. the write was killed before the
        atomic publish discipline existed, or the disk filled — must
        read as absent, not crash the resume)."""
        try:
            with np.load(path) as z:
                return int(z["t"])
        except Exception:                               # noqa: BLE001
            return None

    def latest_step(self) -> Optional[int]:
        """Newest restorable step: the ``latest`` pointer's target when
        it exists and loads, else the newest VALID ``ckpt_*.npz`` in the
        directory — a dangling/corrupt pointer target costs one
        checkpoint interval, not the whole resume."""
        candidates = []
        ptr = os.path.join(self.path, "latest")
        ptr_name = None
        if os.path.exists(ptr):
            with open(ptr) as f:
                ptr_name = f.read().strip()
            candidates.append(ptr_name)
        candidates += sorted(
            (os.path.basename(p) for p in
             _glob.glob(os.path.join(self.path, "ckpt_*.npz"))
             if os.path.basename(p) != ptr_name),
            reverse=True)
        for name in candidates:
            p = os.path.join(self.path, name)
            if not os.path.exists(p):
                continue
            t = self._valid_step(p)
            if t is None:
                continue
            if name != ptr_name and ptr_name is not None:
                warnings.warn(
                    f"checkpoint 'latest' pointer references "
                    f"{ptr_name!r} (missing or corrupt); falling back "
                    f"to {name!r}")
            return t
        return None

    def restore(self, solver, t: Optional[int] = None, *,
                elastic: bool = False,
                recorder=None) -> Optional[int]:
        """Load the checkpoint for step ``t`` (default: latest) into
        ``solver``.  Returns the restored step index, or None when no
        checkpoint exists.  Raises on fingerprint mismatch — except a
        mismatch confined to ``n_procs`` under ``elastic=True``: step
        checkpoints hold the globally-fetched state, so restoring onto
        a different process count is exact, and the NAMED elastic path
        records an ``elastic_resume`` event instead of refusing."""
        if t is None:
            t = self.latest_step()
            if t is None:
                return None
        with np.load(self._ckpt_file(t)) as z:
            saved = json.loads(bytes(z["fingerprint"]).decode())
            # Checkpoints written before the pallas field existed can only
            # have come from the XLA matvec path; a bool False predates
            # the variant-name format and also means the XLA path.  (A
            # bool True is left as-is: the variant it ran is unknown, so
            # the mismatch error is the correct outcome.)
            if saved.get("pallas", False) is False:
                saved["pallas"] = "off"
            # Checkpoints written before the precond field existed can only
            # have come from the scalar-Jacobi path.
            saved.setdefault("precond", "jacobi")
            # Checkpoints written before the mg_shape field existed can
            # only have come from a non-mg preconditioner.
            saved.setdefault("mg_shape", "n/a")
            # Checkpoints written before the pcg_variant field existed
            # can only have come from the classic loop.
            saved.setdefault("pcg_variant", "classic")
            # Checkpoints written before the nrhs field existed can only
            # have come from the single-RHS paths.
            saved.setdefault("nrhs", 1)
            want = _fingerprint(solver)
            # Checkpoints that predate the stencil-form/level-dims fields
            # did not record which formulation/layout produced them (the
            # corner form and block tiling existed briefly before the
            # fields did), so their historical values are unknowable —
            # skip BOTH checks for legacy checkpoints rather than guess.
            saved.setdefault("matvec_form", want["matvec_form"])
            saved.setdefault("level_dims", want["level_dims"])
            # pre-combine checkpoints are NOT ambiguous: only the scatter
            # path existed, so a gather-mode resume must mismatch loudly
            if "combine" not in saved:
                saved["combine"] = ("scatter" if want["combine"] != "n/a"
                                    else "n/a")
                saved["combine_kd"] = "n/a" if saved["combine"] == "n/a" \
                    else want["combine_kd"]
            # pre-f64_refresh checkpoints can only have come from the
            # stencil formulation (the general form did not exist)
            saved.setdefault("f64_refresh", "stencil")
            # Checkpoints written before the fingerprint-completeness
            # sweep (analysis/) did not record the remaining numerics
            # knobs although the knobs themselves already existed —
            # their historical values are unknowable, so skip the new
            # checks for legacy checkpoints rather than guess (the
            # matvec_form precedent above).
            for k in ("dot_dtype", "max_stag_steps", "inner_tol",
                      "mixed_knobs", "trace_len", "n_procs"):
                saved.setdefault(k, want[k])
            if saved != want:
                diffs = {k: (saved.get(k), want[k]) for k in want
                         if saved.get(k) != want[k]}
                if elastic and set(diffs) == {"n_procs"}:
                    if recorder is not None:
                        recorder.event(
                            "elastic_resume",
                            from_procs=int(saved.get("n_procs", -1)),
                            to_procs=int(want["n_procs"]),
                            prefix="ckpt")
                        recorder.inc("resilience.elastic_resume")
                else:
                    raise ValueError(
                        f"checkpoint/solver mismatch (saved, current): "
                        f"{diffs}")
            load_state_dict(solver, {k: z[k] for k in z.files
                                     if k not in ("t", "fingerprint")})
        return t


# ----------------------------------------------------------------------
# Mid-Krylov snapshots (resilience subsystem)
# ----------------------------------------------------------------------

def _flatten(tree: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


class SnapshotStore:
    """Mid-solve (intra-step) Krylov snapshots under the checkpoint dir.

    One ``snap_{t:06d}.npz`` per in-flight step, published through
    ``utils/io.write_atomic`` (readers never see a half-write — exactly
    the failure window a snapshot exists to survive) and guarded by the
    same solver fingerprint as the step checkpoints: resuming a Krylov
    carry against different numerics would silently produce garbage.
    The payload is an arbitrary numpy pytree (the chunked engine's
    resumable state — direct-mode carry or mixed-mode outer-cycle
    state) flattened with ``/``-joined keys.

    The record is a mid-STEP artifact on the quasi-static path: the
    owning step deletes it on completion (:meth:`discard`), so a later
    resume can never replay a snapshot past the state it belongs to.
    The time-history drivers (dynamics/Newmark) reuse the store with
    ``prefix="step"`` for their timestep-granular checkpoints
    (``step_*.npz``), where records deliberately outlive their step —
    they are the resume points — and on-disk retention is bounded
    instead: after each successful write only the newest K files of the
    store's prefix are kept (``PCG_TPU_SNAP_KEEP``, default 2), so a
    week-long time history cannot fill the disk.
    """

    def __init__(self, path: str, fingerprint: Optional[dict] = None,
                 prefix: str = "snap"):
        self.path = path
        self.fingerprint = fingerprint
        self.prefix = prefix

    @classmethod
    def for_solver(cls, solver) -> "SnapshotStore":
        return cls(solver.config.checkpoint_path, _fingerprint(solver))

    @classmethod
    def for_many_solver(cls, solver, nrhs: int,
                        rhs_hash: str = "") -> "SnapshotStore":
        """Blocked-solve store (``Solver.solve_many``): same fingerprint
        guard with the ACTUAL block width AND a content hash of the rhs
        block, distinct ``many_*.npz`` namespace.  Resuming a width-R
        blocked carry under a width-R' request — or under a same-width
        block of DIFFERENT load cases (the scalar paths derive their rhs
        from the fingerprinted model/schedule; solve_many's rhs is a
        per-request input, so it must be fingerprinted itself) — fails
        as a clear mismatch naming the field, never as a silently-wrong
        Krylov continuation or a shape error deep in the dispatch."""
        fp = dict(_fingerprint(solver))
        fp["nrhs"] = int(nrhs)
        fp["rhs_hash"] = str(rhs_hash)
        # whether the blocked cycle programs carry the fallback-
        # preconditioner operand (driver._many_use_fb): a carry whose
        # ``prec_sel`` flipped a column to the fallback must never
        # resume into a program compiled without one — the selection
        # would be silently compiled out
        fp["many_fallback"] = bool(
            getattr(solver, "_many_use_fb", lambda: False)())
        return cls(solver.config.checkpoint_path, fp, prefix="many")

    @classmethod
    def for_time_solver(cls, solver) -> "SnapshotStore":
        """Timestep-granular store for the dynamics/Newmark drivers:
        same fingerprint guard, distinct ``step_*.npz`` namespace so a
        quasi-static mid-Krylov snapshot in the same checkpoint dir can
        never be mistaken for a completed-timestep state."""
        return cls(solver.config.checkpoint_path, _fingerprint(solver),
                   prefix="step")

    def _file(self, t: int) -> str:
        return os.path.join(self.path, f"{self.prefix}_{t:06d}.npz")

    @staticmethod
    def retention() -> int:
        """On-disk retention bound: keep the newest K files per prefix
        (``PCG_TPU_SNAP_KEEP``, default 2 — the newest plus one spare in
        case the newest write raced a kill).  A malformed value must not
        disable the bound it configures."""
        raw = os.environ.get("PCG_TPU_SNAP_KEEP", "").strip()
        if not raw:
            return 2
        try:
            k = int(raw)
        except ValueError:
            warnings.warn(f"PCG_TPU_SNAP_KEEP={raw!r} is not an integer; "
                          "keeping the default 2 snapshots")
            return 2
        return max(k, 1)

    def _prune(self) -> None:
        """Drop all but the newest K snapshots of this prefix.  Runs
        only after a successful atomic publish, so the newest file is
        always a complete record; zero-padded names sort by step.  Only
        files of THIS store's ``<prefix>_<step>.npz`` naming count:
        the epoch shards/markers a GroupSnapshotStore keeps under the
        same prefix (``<prefix>_e<E>.p<idx>.npz``) have their own
        committed-epoch retention, and a per-file prune racing across
        rank shards is exactly how retention used to split a group's
        ``latest()`` resolution."""
        files = sorted(
            p for p in _glob.glob(
                os.path.join(self.path, f"{self.prefix}_*.npz"))
            if os.path.basename(p)[len(self.prefix) + 1:-4].isdigit())
        for p in files[:-self.retention()]:
            try:
                os.remove(p)
            except OSError:
                pass        # a racing reader/cleaner already has it

    def latest(self) -> Optional[int]:
        """Newest restorable step index of this prefix, or None.  A
        corrupt/truncated newest file costs one retention slot, not the
        resume (same posture as CheckpointManager.latest_step)."""
        steps = []
        for p in _glob.glob(os.path.join(self.path,
                                         f"{self.prefix}_*.npz")):
            stem = os.path.basename(p)[len(self.prefix) + 1:-4]
            try:
                steps.append(int(stem))
            except ValueError:
                continue
        for t in sorted(steps, reverse=True):
            try:
                with np.load(self._file(t)) as z:
                    if "__t" in z.files:
                        return t
            except Exception:                           # noqa: BLE001
                continue        # corrupt reads as absent; older file next
        return None

    def save(self, t: int, state: Dict[str, Any]) -> str:
        """Persist the (host numpy) state pytree for in-flight step
        ``t``.  Multi-host safe like :meth:`CheckpointManager.save`: the
        caller's state fetch is collective, only process 0 writes."""
        from pcg_mpi_solver_tpu.utils.io import is_primary, write_atomic

        out = self._file(t)
        if not is_primary():
            return out
        os.makedirs(self.path, exist_ok=True)
        flat = _flatten(state)
        flat["__t"] = np.int64(t)
        flat["__fingerprint"] = np.frombuffer(
            json.dumps(self.fingerprint or {}, sort_keys=True).encode(),
            dtype=np.uint8).copy()
        write_atomic(out, lambda f: np.savez_compressed(f, **flat))
        self._prune()
        return out

    def load(self, t: int) -> Optional[Dict[str, Any]]:
        """The state pytree snapshotted inside step ``t``, or None.
        Raises on a fingerprint mismatch (resuming a carry under drifted
        numerics must fail loudly, like the step checkpoints); a
        corrupt/truncated snapshot reads as absent — the step then
        simply restarts cold from its start state."""
        path = self._file(t)
        if not os.path.exists(path):
            return None
        try:
            with np.load(path) as z:
                flat = {k: z[k] for k in z.files}
            # a structurally-loadable npz with a missing/garbled
            # fingerprint entry is just as corrupt as a torn zip — same
            # reads-as-absent outcome, not a KeyError mid-resume
            saved = json.loads(bytes(flat.pop("__fingerprint")).decode())
        except Exception as e:                          # noqa: BLE001
            warnings.warn(f"mid-solve snapshot {path} unreadable "
                          f"({type(e).__name__}: {e}); restarting the "
                          "step from its start state")
            return None
        flat.pop("__t", None)
        self._reconcile_fingerprint(saved)
        return _unflatten(flat)

    def _reconcile_fingerprint(self, saved: dict) -> None:
        """Apply the legacy shims to a record's saved fingerprint, then
        compare against this store's live fingerprint, dispatching any
        mismatch to :meth:`_fingerprint_mismatch` (shared by the base
        per-file reads and the epoch-shard joins of
        ``resilience.distributed.GroupSnapshotStore``)."""
        if self.fingerprint is None:
            return
        # snapshots written before the nrhs field existed can only have
        # come from the width-1 scalar paths (same back-compat shim as
        # CheckpointManager.restore — without it every pre-existing
        # snap_*/step_* resume point would mismatch on upgrade).  Only
        # when THIS store's fingerprint carries the field: a custom
        # fingerprint without it must keep comparing equal to itself.
        if "nrhs" in self.fingerprint:
            saved.setdefault("nrhs", 1)
        if "many_fallback" in self.fingerprint:
            # blocked snapshots written before the per-column fallback
            # wiring existed can only have come from programs without
            # the fallback operand
            saved.setdefault("many_fallback", False)
        if "mg_shape" in self.fingerprint:
            # snapshots written before the mg_shape field existed can
            # only have come from a non-mg preconditioner — resuming
            # them under precond='mg' still mismatches (on precond AND
            # on "n/a" != the live shape), loudly
            saved.setdefault("mg_shape", "n/a")
        # snapshots written before the fingerprint-completeness sweep
        # (analysis/) did not record these numerics knobs; their
        # historical values are unknowable — skip the new checks for
        # legacy snapshots rather than guess (same rationale and guard
        # as the nrhs shim above)
        for k in ("dot_dtype", "max_stag_steps", "inner_tol",
                  "mixed_knobs", "trace_len", "n_procs"):
            if k in self.fingerprint:
                saved.setdefault(k, self.fingerprint[k])
        if saved != self.fingerprint:
            diffs = {k: (saved.get(k), self.fingerprint[k])
                     for k in self.fingerprint
                     if saved.get(k) != self.fingerprint[k]}
            self._fingerprint_mismatch(saved, diffs)

    def _fingerprint_mismatch(self, saved: dict, diffs: dict) -> None:
        """Mismatch outcome hook: the base store always refuses; the
        group store's elastic path tolerates an ``n_procs``-only diff
        as a named event."""
        raise ValueError(
            f"mid-solve snapshot/solver mismatch (saved, current): "
            f"{diffs}")

    def discard(self, t: int) -> None:
        from pcg_mpi_solver_tpu.utils.io import is_primary

        if not is_primary():
            return
        try:
            os.remove(self._file(t))
        except OSError:
            pass
