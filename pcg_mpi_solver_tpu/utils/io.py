"""Run-directory store and small serialization helpers.

Replaces the reference's file plumbing (src/utils/file_operations.py:
exportz/importz zlib-pickles :32-42, MPI-IO shared-file writes with sidecar
metadata :348-531) with plain .npy/.npz per-array files — no MPI-IO needed
since the host assembles owner-masked arrays directly.  Keeps the reference's
results layout and .mat co-exports so downstream tooling carries over:

    <scratch>/Results_Run<id>[_SpeedTest]/
        ResVecData/   Dof.npy NodeId.npy U_<k>.npy D_<k>.npy ... Time_T.npy
        PlotData/     <model>_PlotData.npz/.mat  <model>_MP<P>_TimeData.npz/.mat
        VTKs/         <model>_<k>.vtu  VTKInfo.txt
"""

from __future__ import annotations

import os
import pickle
import shutil
import zlib
from datetime import datetime
from typing import Dict

import numpy as np


def is_primary() -> bool:
    """True on the one process that performs result-file writes (the
    reference gates shared-file writes on rank 0 / uses MPI-IO offsets,
    file_operations.py:348-396; here process 0 writes, everyone computes).
    Local import so io stays importable without initializing jax."""
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def exportz(filename: str, data) -> None:
    """zlib-compressed pickle (reference file_operations.py:32-38)."""
    with open(filename, "wb") as f:
        f.write(zlib.compress(pickle.dumps(data, pickle.HIGHEST_PROTOCOL)))


def importz(filename: str):
    with open(filename, "rb") as f:
        return pickle.loads(zlib.decompress(f.read()))


def write_atomic(filename: str, blob) -> None:
    """Atomic-publish discipline for shared directories (cache/,
    concurrent warmup queues): write to a unique per-process tmp, then
    ``os.replace`` — readers only ever see complete files, concurrent
    writers cannot truncate each other's half-write, and a failed write
    leaves no tmp residue.  The ONE copy of this protocol; layer
    serialization on top (``exportz_atomic``, cache/aot.py).

    ``blob``: bytes, or a ``callable(fileobj)`` that STREAMS the payload
    (bench.py's flagship model pickles are multi-hundred-MB — streaming
    avoids materializing the serialized blob on top of the live model)."""
    import threading

    # pid alone is not unique: two threads of one process storing the
    # same cache key would interleave into a single tmp file
    tmp = f"{filename}.{os.getpid()}.{threading.get_ident()}.tmp"
    try:
        with open(tmp, "wb") as f:
            if callable(blob):
                blob(f)
            else:
                f.write(blob)
        os.replace(tmp, filename)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def exportz_atomic(filename: str, data) -> None:
    """``exportz`` published via :func:`write_atomic`."""
    write_atomic(filename,
                 zlib.compress(pickle.dumps(data, pickle.HIGHEST_PROTOCOL)))


class RunStore:
    """Owns one Results_Run directory.

    Multi-host safe: every write method is a no-op on non-primary processes
    (callers still evaluate their — possibly collective — arguments on all
    processes, so device fetches stay in sync; only the file I/O is gated,
    matching the reference's rank-0 write gating)."""

    def __init__(self, result_path: str, model_name: str = "model",
                 primary: bool = None):
        self.result_path = result_path.rstrip("/")
        self.model_name = model_name
        self.res_vec_path = f"{self.result_path}/ResVecData"
        self.plot_path = f"{self.result_path}/PlotData"
        self.vtk_path = f"{self.result_path}/VTKs"
        # Lazily resolved at first write: is_primary() touches the JAX
        # backend, and a RunStore may be constructed before
        # jax.distributed.initialize().
        self._primary = primary

    @property
    def primary(self) -> bool:
        if self._primary is None:
            self._primary = is_primary()
        return self._primary

    def prepare(self) -> None:
        """Create result dirs; an existing run dir is renamed with a
        timestamp (crude run protection, reference pcg_solver.py:67-70)."""
        if not self.primary:
            return
        if os.path.exists(self.result_path):
            stamp = datetime.now().strftime("%d%m%Y_%H%M%S")
            os.rename(self.result_path, f"{self.result_path}_{stamp}")
        os.makedirs(self.res_vec_path)
        os.makedirs(self.plot_path)

    # -- maps and frames ------------------------------------------------
    def write_map(self, name: str, ids: np.ndarray) -> None:
        if not self.primary:
            return
        np.save(f"{self.res_vec_path}/{name}.npy", ids)

    def read_map(self, name: str) -> np.ndarray:
        return np.load(f"{self.res_vec_path}/{name}.npy")

    def write_frame(self, var: str, k: int, values: np.ndarray) -> None:
        if not self.primary:
            return
        np.save(f"{self.res_vec_path}/{var}_{k}.npy", values)

    def write_frame_shard(self, var: str, k: int, values: np.ndarray,
                          p0: int, p1: int, n_parts: int) -> None:
        """Parallel I/O: EVERY process writes the slice of the frame its
        devices own, named by part range + total (the analogue of the
        reference's MPI-IO writes at computed offsets + sidecar metadata,
        file_operations.py:348-531).  ``read_frame`` reassembles in part
        order.  Not primary-gated by design."""
        os.makedirs(self.res_vec_path, exist_ok=True)
        np.save(f"{self.res_vec_path}/{var}_{k}"
                f".part{p0:05d}-{p1:05d}of{n_parts:05d}.npy", values)

    def read_frame(self, var: str, k: int) -> np.ndarray:
        mono = f"{self.res_vec_path}/{var}_{k}.npy"
        if os.path.exists(mono):
            return np.load(mono)
        import glob
        import re

        shards = glob.glob(f"{self.res_vec_path}/{var}_{k}.part*.npy")
        if not shards:
            raise FileNotFoundError(mono)
        ranged, totals = [], set()
        for s in shards:
            m = re.search(r"\.part(\d+)-(\d+)of(\d+)\.npy$", s)
            if m is None:
                raise ValueError(f"unrecognized frame shard name: {s}")
            ranged.append((int(m.group(1)), int(m.group(2)), s))
            totals.add(int(m.group(3)))
        ranged.sort()
        # The ranges must tile [0, n_parts) exactly — stale shards from an
        # earlier run with a different process layout, or a not-yet-flushed
        # writer, must fail loudly rather than merge into a garbled frame.
        names = [os.path.basename(r[2]) for r in ranged]
        if len(totals) != 1:
            raise ValueError(f"mixed-generation frame shards for {var}_{k}: "
                             f"{names}")
        pos = 0
        for p0, p1, s in ranged:
            if p0 != pos:
                raise ValueError(
                    f"frame shards for {var}_{k} do not tile contiguously "
                    f"(at part {pos}): {names}")
            pos = p1
        if pos != totals.pop():
            raise ValueError(
                f"incomplete frame shards for {var}_{k} (cover {pos} parts): "
                f"{names}")
        return np.concatenate([np.load(s) for _, _, s in ranged])

    def n_frames(self, var: str) -> int:
        import glob
        import re

        ks = set()
        for f in glob.glob(f"{self.res_vec_path}/{var}_*.npy"):
            m = re.match(
                rf"{re.escape(var)}_(\d+)(\.part\d+-\d+of\d+)?\.npy$",
                os.path.basename(f))
            if m:
                ks.add(int(m.group(1)))
        return len(ks)

    def write_time_list(self, times) -> None:
        if not self.primary:
            return
        np.save(f"{self.res_vec_path}/Time_T.npy", np.asarray(times))

    def read_time_list(self) -> np.ndarray:
        return np.load(f"{self.res_vec_path}/Time_T.npy")

    # -- history / timing ----------------------------------------------
    def write_plot_data(self, plot_t, plot_u, plot_dofs) -> None:
        """Probe-dof displacement history: .npz + .mat + rendered PNG
        (reference exportHistoryPlotData + TestPlot PNG,
        pcg_solver.py:817-838, 899-940)."""
        if not self.primary:
            return
        data = {"Plot_T": np.asarray(plot_t), "Plot_U": np.asarray(plot_u),
                "Plot_Dof": np.asarray(plot_dofs) + 1}
        np.savez_compressed(f"{self.plot_path}/{self.model_name}_PlotData",
                            PlotData=np.array(data, dtype=object))
        _savemat(f"{self.plot_path}/{self.model_name}_PlotData.mat", data)
        self._plot_png(data)

    def _plot_png(self, data) -> None:
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:                        # matplotlib is optional
            return
        fig, ax = plt.subplots(figsize=(7, 4.5))
        t, u = data["Plot_T"], np.atleast_2d(data["Plot_U"])
        for i, dof in enumerate(np.atleast_1d(data["Plot_Dof"])):
            ax.plot(t, u[i], label=f"dof {int(dof)}")
        ax.set_xlabel("time")
        ax.set_ylabel("displacement")
        ax.legend(loc="best", fontsize=8)
        ax.grid(True, alpha=0.3)
        fig.tight_layout()
        fig.savefig(f"{self.plot_path}/{self.model_name}_PlotData.png", dpi=110)
        plt.close(fig)

    def write_time_data(self, n_parts: int, time_data: Dict) -> None:
        """Solve metadata: per-step Flag/RelRes/Iter + timing buckets
        (reference exportTimeData, pcg_solver.py:943-961)."""
        if not self.primary:
            return
        name = f"{self.plot_path}/{self.model_name}_MP{n_parts}_TimeData"
        np.savez_compressed(name, TimeData=np.array(time_data, dtype=object))
        _savemat(name + ".mat", time_data)

    def read_time_data(self, n_parts: int) -> Dict:
        name = f"{self.plot_path}/{self.model_name}_MP{n_parts}_TimeData.npz"
        return np.load(name, allow_pickle=True)["TimeData"].item()


def _savemat(path: str, data: Dict) -> None:
    import scipy.io

    scipy.io.savemat(path, {k: (v if isinstance(v, dict) else np.asarray(v))
                            for k, v in data.items()})
