"""Resilience subsystem: preemption-safe solves on scarce hardware.

The paper's billion-DOF regime runs on preemptible, tunneled TPUs where
a failed dispatch throws away minutes of compile and thousands of Krylov
iterations (round 5 lost its only timed flagship measurement exactly
this way).  This package makes the solver SURVIVE those failures rather
than report them:

* mid-Krylov snapshots (``utils/checkpoint.SnapshotStore`` + the
  per-step :class:`~pcg_mpi_solver_tpu.resilience.recovery.ResilienceContext`)
  — a killed process or lost device loses at most one snapshot interval,
  and ``--resume`` continues MID-SOLVE with bit-identical history;
* a bounded recovery ladder for flag-2/4 breakdowns and NaN/Inf carries
  (:class:`~pcg_mpi_solver_tpu.resilience.recovery.RecoveryLadder`);
* a retry-with-backoff dispatch guard for XLA/device-loss exceptions
  (:class:`~pcg_mpi_solver_tpu.resilience.recovery.DispatchGuard`);
* the shared recovery orchestration + timestep-granular time-history
  harness (:mod:`pcg_mpi_solver_tpu.resilience.engine`:
  :func:`~pcg_mpi_solver_tpu.resilience.engine.run_with_recovery`,
  :class:`~pcg_mpi_solver_tpu.resilience.engine.TimeHistoryGuard`) —
  one copy of the machinery, consumed by the quasi-static driver, the
  implicit Newmark stepper and the explicit dynamics driver;
* deterministic fault injection so every path above is exercised in
  tier-1 on CPU (:mod:`pcg_mpi_solver_tpu.resilience.faultinject`),
  including the step domain (``kill@s:N``) for time histories and the
  rank domain (``kill@rank:R:N``) for multi-process chaos runs;
* multi-process fault tolerance (ISSUE 18,
  :mod:`pcg_mpi_solver_tpu.resilience.distributed`): deadline-guarded
  host collectives that turn a dead peer into a named
  :class:`~pcg_mpi_solver_tpu.resilience.distributed.DeadPeerError`
  in bounded time, group-consistent two-phase snapshot epochs, and
  elastic resume of an N-process run onto M processes
  (``Solver.resume_elastic``).

Import contract: jax-free at module load (the fault poisoners and the
state put/fetch closures import jax lazily), matching ``cache/`` and
``obs/``.
"""

from pcg_mpi_solver_tpu.resilience.distributed import (
    DeadPeerError, GroupSnapshotStore, GuardedComm,
    collective_deadline_s, suspect_dead_rank)
from pcg_mpi_solver_tpu.resilience.engine import (
    ManyRecoveryHooks, RecoveryHooks, TimeHistoryGuard,
    kinematic_state_io, run_many_with_recovery, run_with_recovery)
from pcg_mpi_solver_tpu.resilience.faultinject import (
    FaultPlan, InjectedDispatchError, SimulatedKill)
from pcg_mpi_solver_tpu.resilience.recovery import (
    DispatchGuard, RecoveryLadder, ResilienceContext, breakdown_trigger,
    column_trigger, is_device_loss, retry_deadline_s)

__all__ = [
    "FaultPlan",
    "InjectedDispatchError",
    "SimulatedKill",
    "DeadPeerError",
    "DispatchGuard",
    "GroupSnapshotStore",
    "GuardedComm",
    "collective_deadline_s",
    "suspect_dead_rank",
    "ManyRecoveryHooks",
    "RecoveryHooks",
    "RecoveryLadder",
    "ResilienceContext",
    "TimeHistoryGuard",
    "breakdown_trigger",
    "column_trigger",
    "is_device_loss",
    "kinematic_state_io",
    "retry_deadline_s",
    "run_many_with_recovery",
    "run_with_recovery",
]
