"""Multi-process fault tolerance: deadline-guarded collectives and
group-consistent snapshot epochs.

At fleet scale the dominant failure mode is no longer "a column breaks
down" (the PR 8 quarantine regime) but "a process dies or wedges
mid-collective".  Under multi-controller JAX that failure is silent and
total: every surviving peer blocks forever inside the next gloo/psum
round, and the per-process ``snap_*.npz`` files the single-host
resilience path writes carry no cross-process consistency guarantee —
a crash between two ranks' writes leaves a torn, unresumable mix.
This module supplies the two missing pieces (the
communication-avoiding-CG safeguard posture of arXiv:2501.03743 —
detect cheaply, recover from the last consistent state):

* :class:`GuardedComm` — a deadline watchdog around every host-side
  collective on the dispatch path (``PCG_TPU_COLLECTIVE_DEADLINE_S``).
  A wedged round becomes a named :class:`DeadPeerError` in bounded
  time, carrying the most heartbeat-silent peer rank read from the
  PR 16 flight shards, plus a ``collective_timeout`` telemetry/flight
  event for post-mortem triage.
* :class:`GroupSnapshotStore` — a two-phase epoch protocol over the
  existing :class:`~pcg_mpi_solver_tpu.utils.checkpoint.SnapshotStore`
  layout: every rank atomically writes its own
  ``<prefix>_e<E>.p<idx>.npz`` shard, an allreduce confirms all shards
  landed, and only then does rank 0 publish the ``COMMIT_e<E>`` marker.
  Readers resolve the newest *committed* epoch (group-agreed), so a
  crash mid-epoch falls back cleanly to epoch E-1, never a torn mix —
  and retention is routed through the commit markers so pruning can
  never split the group.  Because shards are written as axis-0 slices
  of the globally-fetched part arrays, a committed N-process epoch can
  be re-joined and restored onto M != N processes (elastic resume).

Import-light like the rest of ``resilience/``: jax and the obs readers
are imported lazily inside the functions that need them.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import warnings
import glob as _glob
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pcg_mpi_solver_tpu.utils.checkpoint import (
    SnapshotStore, _flatten, _unflatten)
from pcg_mpi_solver_tpu.utils.io import write_atomic

__all__ = ["DeadPeerError", "GuardedComm", "GroupSnapshotStore",
           "collective_deadline_s", "suspect_dead_rank"]


class DeadPeerError(RuntimeError):
    """A host-side collective got no reply within the configured
    deadline — some peer process is dead or wedged.

    Deliberately NOT device-loss shaped (the message avoids every
    ``resilience.recovery._DEVICE_ERROR_MARKERS`` substring and the
    type name is not in ``_DEVICE_ERROR_NAMES``): a dead peer does not
    come back on redispatch, so the dispatch guard must propagate this
    instead of burning its retry budget re-entering the same stuck
    round.  Recovery is a relaunch with ``--resume`` (same process
    count) or :meth:`Solver.resume_elastic` (fewer processes)."""


def collective_deadline_s() -> Optional[float]:
    """The host-collective watchdog deadline
    (``PCG_TPU_COLLECTIVE_DEADLINE_S`` seconds, env-only; unset or
    non-positive disables the guard).  A malformed value must not kill
    the solve the knob protects — it disables the guard with a
    warning."""
    raw = os.environ.get("PCG_TPU_COLLECTIVE_DEADLINE_S", "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        warnings.warn(f"PCG_TPU_COLLECTIVE_DEADLINE_S={raw!r} is not a "
                      "number; collective deadline guard disabled")
        return None
    return v if v > 0 else None


# ---------------------------------------------------------------------------
# Dead-peer attribution via the per-process flight shards (PR 16).
# ---------------------------------------------------------------------------

_RANK_RE = re.compile(r"\.p(\d+)$")


def _shard_rank(path: str) -> Optional[int]:
    """Process index encoded in a flight-shard filename
    (``run.p3.jsonl`` -> 3), or None for the unsharded base file."""
    root, _ = os.path.splitext(path)
    m = _RANK_RE.search(root)
    return int(m.group(1)) if m else None


def flight_base_path(shard_path: str) -> str:
    """Invert ``obs.flight.shard_jsonl_path``: this process's shard
    path back to the base telemetry path every process shards from."""
    root, ext = os.path.splitext(shard_path)
    m = _RANK_RE.search(root)
    return (root[:m.start()] + (ext or ".jsonl")) if m else shard_path


def suspect_dead_rank(flight_base: Optional[str],
                      self_index: Optional[int] = None
                      ) -> Tuple[Optional[int], Optional[float]]:
    """The most heartbeat-silent PEER rank of a flight-shard set:
    ``(rank, silent_s)``, or ``(None, None)`` when no peer shard can be
    read.  This is the shard-tail liveness read ``pcg-tpu watch`` does
    fleet-wide, pointed at the single question a stuck collective
    poses: which peer stopped writing first?"""
    if not flight_base:
        return None, None
    from pcg_mpi_solver_tpu.obs.flight import find_shards
    from pcg_mpi_solver_tpu.obs.watch import _shard_status

    now = time.time()
    best: Tuple[Optional[int], Optional[float]] = (None, None)
    for p in find_shards(flight_base):
        rank = _shard_rank(p)
        if rank is None or (self_index is not None and rank == self_index):
            continue
        st = _shard_status(p, now)
        silent = st.get("silent_s")
        if silent is None or st.get("done"):
            continue        # no timestamps / finished cleanly: not stuck
        if best[1] is None or silent > best[1]:
            best = (rank, float(silent))
    return best


#: Substrings marking a collective failure as TRANSPORT death (a peer's
#: sockets closed under the collective) rather than a wrong computation.
#: gloo surfaces a killed peer as a fast connection error, not a hang —
#: the verdict is the same as a deadline expiry and must be named the
#: same way (matched case-insensitively).
_TRANSPORT_MARKERS = (
    "gloo", "connection reset", "connection closed", "connection refused",
    "socket closed", "heartbeat timeout", "coordination service",
    "peer closed",
)


def is_transport_failure(exc: BaseException) -> bool:
    """Does this collective error mean a peer's transport died (same
    dead-peer verdict as a deadline expiry)?"""
    msg = str(exc).lower()
    return any(m in msg for m in _TRANSPORT_MARKERS)


class GuardedComm:
    """Deadline watchdog around a HostComm-shaped collective group.

    Each collective runs on a worker thread while the caller waits at
    most ``deadline_s`` (monotonic).  On expiry the caller raises
    :class:`DeadPeerError` naming the most flight-silent peer — the
    worker thread itself stays parked inside gloo (there is no portable
    way to cancel it), which is why it is a daemon: the process is
    expected to exit/relaunch after a dead-peer verdict, not to retry.

    With no deadline configured every call runs inline (no watchdog
    thread, no flight span), but on a multi-process group the
    transport-failure classification still applies — a killed gloo
    peer's fast connection-reset error becomes the same named
    :class:`DeadPeerError` verdict with or without the knob.  The
    wrapper is therefore installed unconditionally on the multi-process
    dispatch path (``Solver._collective_comm``): the consensus
    agreements it carries are correctness-critical; only the watchdog
    is opt-in.
    """

    def __init__(self, comm, *, deadline_s: Optional[float] = None,
                 recorder=None, flight_base: Optional[str] = None,
                 index: int = 0):
        self.comm = comm
        self.n_procs = int(getattr(comm, "n_procs", 1))
        self.deadline_s = deadline_s
        self.recorder = recorder
        self.index = int(index)
        self._flight_base = flight_base

    def flight_base(self) -> Optional[str]:
        """The base flight path (for peer-shard reads), from the
        constructor or derived from the recorder's attached shard."""
        if self._flight_base:
            return self._flight_base
        fl = getattr(self.recorder, "flight", None)
        path = getattr(fl, "path", None)
        return flight_base_path(path) if path else None

    # -- guarded collectives -------------------------------------------
    def allreduce(self, arr, op: str):
        return self._guarded("allreduce",
                             lambda: self.comm.allreduce(arr, op))

    def allreduce_many(self, arrs, op: str):
        return self._guarded("allreduce_many",
                             lambda: self.comm.allreduce_many(arrs, op))

    def allreduce_groups(self, groups):
        return self._guarded("allreduce_groups",
                             lambda: self.comm.allreduce_groups(groups))

    def warmup(self, sizes=(1,)):
        return self._guarded("warmup", lambda: self.comm.warmup(sizes))

    def barrier(self, label: str = "barrier") -> None:
        """A named group sync (the chunk-boundary liveness probe): one
        tiny guarded allreduce — the cheapest round that still proves
        every peer reached this point within the deadline."""
        self._guarded(label, lambda: self.comm.allreduce(
            np.ones(1, dtype=np.int64), "min"))

    def _suspect(self) -> Tuple[Optional[int], str]:
        """``(rank, description)`` of the most flight-silent peer."""
        rank, silent = suspect_dead_rank(self.flight_base(), self.index)
        who = (f"process {rank} (flight-silent {silent:.1f}s)"
               if rank is not None else
               "unknown (no peer flight shard readable)")
        return rank, who

    def _raise_transport_death(self, label: str, err: BaseException,
                               waited: float, flight, seq) -> None:
        """Record + raise the dead-peer transport verdict: a killed peer
        usually surfaces as a FAST gloo connection error, not a hang —
        same verdict as a deadline expiry, same named error (the
        original rides along as ``__cause__`` — its XlaRuntimeError
        shape would otherwise read as a retryable device loss and burn
        the dispatch-guard budget re-entering the same dead group).
        Shared by the watchdog path and the no-deadline inline path."""
        rank, who = self._suspect()
        if self.recorder is not None:
            self.recorder.event(
                "collective_timeout", label=label,
                deadline_s=float(self.deadline_s or 0.0),
                suspect=(-1 if rank is None else int(rank)))
            self.recorder.inc("resilience.collective_timeout")
        if flight is not None:
            flight.end(seq, f"collective:{label}", ok=False,
                       error="collective transport failure",
                       waited_s=round(waited, 3),
                       suspect=(-1 if rank is None else int(rank)))
        raise DeadPeerError(
            f"collective '{label}' failed on the transport after "
            f"{waited:.1f}s ({type(err).__name__}: a peer's "
            f"connection dropped mid-round, {self.n_procs} "
            f"processes); suspected dead peer: {who}") from err

    def _guarded(self, label: str, fn):
        deadline = self.deadline_s
        if self.n_procs <= 1:
            return fn()
        if deadline is None:
            # no watchdog armed: run inline (no thread, no flight span)
            # — but the transport classification is a correctness
            # concern, not a watchdog concern, so it applies to every
            # multi-process group regardless of the deadline knob
            t0 = time.monotonic()
            try:
                return fn()
            except DeadPeerError:
                raise
            except BaseException as err:    # noqa: BLE001 — classified below
                if is_transport_failure(err):
                    self._raise_transport_death(
                        label, err, time.monotonic() - t0, None, None)
                raise
        box: Dict[str, Any] = {}
        done = threading.Event()

        def work():
            try:
                box["out"] = fn()
            except BaseException as e:      # noqa: BLE001 — re-raised on the caller thread below
                box["err"] = e
            finally:
                done.set()

        flight = getattr(self.recorder, "flight", None) \
            if self.recorder is not None else None
        seq = (flight.begin(f"collective:{label}")
               if flight is not None else None)
        t0 = time.monotonic()
        threading.Thread(target=work, daemon=True,
                         name=f"collective:{label}").start()
        done.wait(deadline)
        if not done.is_set():
            waited = time.monotonic() - t0
            rank, who = self._suspect()
            if self.recorder is not None:
                self.recorder.event(
                    "collective_timeout", label=label,
                    deadline_s=float(deadline),
                    suspect=(-1 if rank is None else int(rank)))
                self.recorder.inc("resilience.collective_timeout")
            if flight is not None:
                flight.end(seq, f"collective:{label}", ok=False,
                           error="collective stalled",
                           waited_s=round(waited, 3),
                           suspect=(-1 if rank is None else int(rank)))
            # NB: phrased to stay outside is_device_loss()'s marker set —
            # a dead peer must propagate, not burn dispatch retries.
            raise DeadPeerError(
                f"collective '{label}' got no reply from the group within "
                f"{deadline:.1f}s (waited {waited:.1f}s, "
                f"{self.n_procs} processes); suspected dead peer: {who}")
        err = box.get("err")
        if err is not None and is_transport_failure(err):
            self._raise_transport_death(label, err,
                                        time.monotonic() - t0, flight, seq)
        if flight is not None:
            flight.end(seq, f"collective:{label}",
                       ok=err is None,
                       **({} if err is None
                          else {"error": type(err).__name__}))
        if err is not None:
            raise err
        return box.get("out")


# ---------------------------------------------------------------------------
# Two-phase group-consistent snapshot epochs.
# ---------------------------------------------------------------------------

class GroupSnapshotStore(SnapshotStore):
    """Group-consistent snapshot epochs over the SnapshotStore layout.

    Two-phase protocol per :meth:`save`:

    1. every rank atomically writes its shard
       ``<prefix>_e<E:06d>.p<idx>.npz`` — the axis-0 slice
       ``[part_lo:part_hi]`` of each part-sharded array in the state
       pytree (replicated leaves are written whole by every rank; the
       joiner takes rank 0's copy);
    2. a min-allreduce confirms every shard landed, and only then does
       rank 0 publish the ``<prefix>_COMMIT_e<E:06d>.json`` marker
       (epoch, step, shard count).

    Readers (:meth:`load`, :meth:`latest`) resolve the newest committed
    epoch — group-agreed with a min-reduce, so a rank whose directory
    view lags (NFS) pulls the whole group back to an epoch everyone can
    see — and re-join the shards by concatenation.  An uncommitted
    (torn) epoch is invisible: a crash between two ranks' writes costs
    one snapshot interval, never a mixed resume.  Retention
    (``PCG_TPU_SNAP_KEEP``) keeps the newest K *committed* epochs plus
    any newer in-flight epoch; each rank prunes only its own shards
    (rank 0 also sweeps markers and leftover shards of dropped epochs),
    so pruning can never make two ranks resolve different newest
    snapshots.

    Elastic resume: shards carry their part ranges, so :meth:`load`
    re-joins a committed N-process epoch into the full global state on
    ANY process count; with ``elastic=True`` a fingerprint mismatch
    confined to ``n_procs`` becomes a named ``elastic_resume`` event
    instead of an error.
    """

    def __init__(self, path: str, fingerprint: Optional[dict] = None,
                 prefix: str = "snap", *, comm=None, index: int = 0,
                 n_shards: int = 1,
                 part_range: Optional[Tuple[int, int]] = None,
                 n_parts: Optional[int] = None, recorder=None,
                 elastic: bool = False):
        super().__init__(path, fingerprint, prefix)
        self.comm = comm
        self.index = int(index)
        self.n_shards = int(n_shards)
        self.part_range = part_range
        self.n_parts = n_parts
        self.recorder = recorder
        self.elastic = bool(elastic)
        # next epoch number, scanned once at construction (every rank
        # builds its store before the first collective save, so the
        # scans see the same directory generation; save() max-agrees
        # the result anyway)
        self._epoch = self._scan_next_epoch()

    # -- construction ---------------------------------------------------
    @classmethod
    def for_solver(cls, solver, *, comm=None, recorder=None,
                   elastic: bool = False) -> "GroupSnapshotStore":
        base = SnapshotStore.for_solver(solver)
        return cls._from_base(base, solver, comm, recorder, elastic)

    @classmethod
    def for_many_solver(cls, solver, nrhs: int, rhs_hash: str = "", *,
                        comm=None, recorder=None,
                        elastic: bool = False) -> "GroupSnapshotStore":
        base = SnapshotStore.for_many_solver(solver, nrhs, rhs_hash)
        return cls._from_base(base, solver, comm, recorder, elastic)

    @classmethod
    def _from_base(cls, base: SnapshotStore, solver, comm, recorder,
                   elastic: bool) -> "GroupSnapshotStore":
        import jax
        from pcg_mpi_solver_tpu.parallel.distributed import local_part_range

        n_parts = int(solver.pm.n_parts)
        return cls(base.path, base.fingerprint, base.prefix, comm=comm,
                   index=int(jax.process_index()),
                   n_shards=int(jax.process_count()),
                   part_range=local_part_range(solver.mesh, n_parts),
                   n_parts=n_parts, recorder=recorder, elastic=elastic)

    # -- naming ---------------------------------------------------------
    def _shard_file(self, epoch: int, idx: int) -> str:
        return os.path.join(self.path,
                            f"{self.prefix}_e{epoch:06d}.p{idx}.npz")

    def _marker_file(self, epoch: int) -> str:
        return os.path.join(self.path,
                            f"{self.prefix}_COMMIT_e{epoch:06d}.json")

    _EPOCH_SHARD_RE = re.compile(r"_e(\d{6})\.p(\d+)\.npz$")
    _EPOCH_MARKER_RE = re.compile(r"_COMMIT_e(\d{6})\.json$")

    def _scan_next_epoch(self) -> int:
        """First unused epoch number in the directory (fresh store) —
        every rank scans the same files, and :meth:`save` max-agrees the
        result so a racing first scan cannot diverge the group."""
        newest = -1
        for p in _glob.glob(os.path.join(self.path, f"{self.prefix}_*")):
            name = os.path.basename(p)
            m = self._EPOCH_SHARD_RE.search(name) \
                or self._EPOCH_MARKER_RE.search(name)
            if m and name.startswith(self.prefix + "_"):
                newest = max(newest, int(m.group(1)))
        return newest + 1

    def committed_epochs(self) -> List[Tuple[int, Dict[str, Any]]]:
        """``(epoch, marker)`` of every readable commit marker,
        ascending.  Unreadable markers read as absent (same tolerant
        posture as the snapshot reads): the epoch is simply not
        committed from this rank's view, and the group min-agreement
        handles the divergence."""
        out = []
        for p in _glob.glob(os.path.join(
                self.path, f"{self.prefix}_COMMIT_e*.json")):
            m = self._EPOCH_MARKER_RE.search(os.path.basename(p))
            if not m:
                continue
            try:
                with open(p, encoding="utf-8") as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue
            out.append((int(m.group(1)), meta))
        return sorted(out)

    # -- write path -----------------------------------------------------
    def _sharded_keys(self, flat: Dict[str, Any]) -> List[str]:
        """The flattened keys this store splits by part rows: the same
        heuristic driver._put_state reshards by — axis 0 of an ndim>=2
        numeric array equals n_parts."""
        if self.part_range is None or not self.n_parts:
            return []
        return sorted(
            k for k, v in flat.items()
            if v.ndim >= 2 and v.shape[0] == self.n_parts
            and v.dtype.kind not in "OUS")

    def save(self, t: int, state: Dict[str, Any]) -> str:
        """Two-phase epoch write (see class docstring).  Every rank
        calls this collectively — unlike the base store there is no
        primary gate: each rank persists its own slice."""
        from pcg_mpi_solver_tpu.parallel.consensus import agree

        os.makedirs(self.path, exist_ok=True)
        # phase 0: agree the epoch number (max — ranks are lockstep, but
        # a first-save directory scan racing a peer's publish must not
        # split the numbering)
        epoch = int(agree(self.comm, [self._epoch], "max")[0])
        flat = _flatten(state)
        sharded = self._sharded_keys(flat)
        lo, hi = self.part_range if self.part_range is not None else (-1, -1)
        for k in sharded:
            flat[k] = flat[k][lo:hi]
        flat["__t"] = np.int64(t)
        flat["__epoch"] = np.int64(epoch)
        flat["__shard"] = np.asarray([self.index, self.n_shards], np.int64)
        flat["__part_range"] = np.asarray([lo, hi], np.int64)
        flat["__sharded"] = np.asarray(sharded)
        flat["__fingerprint"] = np.frombuffer(
            json.dumps(self.fingerprint or {}, sort_keys=True).encode(),
            dtype=np.uint8).copy()
        out = self._shard_file(epoch, self.index)
        ok = 1
        try:
            write_atomic(out, lambda f: np.savez_compressed(f, **flat))
        except OSError as e:
            warnings.warn(f"snapshot shard {out} failed to write "
                          f"({type(e).__name__}: {e}); epoch {epoch} "
                          "will not commit")
            ok = 0
        # phase 1 -> 2: the marker is published only after every rank
        # confirms its shard landed
        committed = bool(int(agree(self.comm, [ok], "min")[0]))
        if committed and self.index == 0:
            marker = {"epoch": int(epoch), "step": int(t),
                      "n_shards": int(self.n_shards),
                      "n_parts": int(self.n_parts or 0)}
            blob = json.dumps(marker, sort_keys=True).encode()
            try:
                write_atomic(self._marker_file(epoch), blob)
            except OSError as e:
                warnings.warn(f"commit marker for epoch {epoch} failed "
                              f"({type(e).__name__}: {e}); the epoch "
                              "stays uncommitted")
                committed = False
        if self.recorder is not None:
            self.recorder.event("snapshot_epoch", epoch=int(epoch),
                                step=int(t), shards=int(self.n_shards),
                                committed=bool(committed))
        self._epoch = epoch + 1
        self._prune()
        return out

    # -- read path ------------------------------------------------------
    def _newest_committed(self, step: Optional[int] = None,
                          below: Optional[int] = None) -> int:
        """Newest locally-visible committed epoch (optionally for one
        step, optionally strictly below an epoch), or -1."""
        newest = -1
        for epoch, meta in self.committed_epochs():
            if step is not None and int(meta.get("step", -1)) != int(step):
                continue
            if below is not None and epoch >= below:
                continue
            newest = max(newest, epoch)
        return newest

    def _read_shard(self, epoch: int, idx: int
                    ) -> Optional[Dict[str, Any]]:
        path = self._shard_file(epoch, idx)
        try:
            with np.load(path) as z:
                return {k: z[k] for k in z.files}
        except Exception as e:                          # noqa: BLE001
            warnings.warn(f"snapshot shard {path} unreadable "
                          f"({type(e).__name__}: {e}); falling back to "
                          "an older committed epoch")
            return None

    def _join_epoch(self, epoch: int, t: int
                    ) -> Optional[Dict[str, Any]]:
        """Re-join one committed epoch's shards into the full state
        pytree, or None when any shard is missing/corrupt/torn."""
        meta = dict(next((m for e, m in self.committed_epochs()
                          if e == epoch), {}))
        n_shards = int(meta.get("n_shards", 0))
        if n_shards <= 0:
            return None
        shards = []
        for idx in range(n_shards):
            flat = self._read_shard(epoch, idx)
            if flat is None or int(flat.get("__t", -1)) != int(t):
                return None
            shards.append(flat)
        try:
            saved = json.loads(
                bytes(shards[0]["__fingerprint"]).decode())
        except (KeyError, ValueError):
            return None
        self._reconcile_fingerprint(saved)
        sharded = [str(k) for k in shards[0].get(
            "__sharded", np.asarray([], dtype=str))]
        ranged = sorted(
            ((tuple(int(v) for v in flat["__part_range"]), flat)
             for flat in shards), key=lambda pair: pair[0])
        n_parts = int(meta.get("n_parts", 0)) or int(self.n_parts or 0)
        joined: Dict[str, Any] = {}
        for k in shards[0]:
            if k.startswith("__"):
                continue
            if k in sharded:
                pos, pieces = 0, []
                for (p0, p1), flat in ranged:
                    if p0 != pos:       # stale/mixed-generation shards
                        warnings.warn(
                            f"epoch {epoch} shards do not tile part "
                            f"rows contiguously at part {pos}; falling "
                            "back to an older committed epoch")
                        return None
                    pieces.append(flat[k])
                    pos = p1
                if n_parts and pos != n_parts:
                    # contiguous but short: e.g. leftover shards of a
                    # shrunk fleet matching an old marker's n_shards —
                    # a truncated global array must not restore
                    warnings.warn(
                        f"epoch {epoch} shards tile only {pos} of "
                        f"{n_parts} part rows; falling back to an "
                        "older committed epoch")
                    return None
                joined[k] = np.concatenate(pieces, axis=0)
            else:
                joined[k] = shards[0][k]
        return _unflatten(joined)

    def load(self, t: int) -> Optional[Dict[str, Any]]:
        """The newest committed epoch of in-flight step ``t``, joined —
        group-agreed: every rank restores the SAME epoch or none.  A
        locally-unreadable epoch pulls the whole group back to the next
        older committed one (bounded retries: one agreement round per
        candidate epoch)."""
        from pcg_mpi_solver_tpu.parallel.consensus import agree, agree_flag

        below: Optional[int] = None
        while True:
            local = self._newest_committed(step=t, below=below)
            epoch = int(agree(self.comm, [local], "min")[0])
            if epoch < 0:
                return None
            state = self._join_epoch(epoch, t)
            if agree_flag(self.comm, state is not None):
                if self.recorder is not None:
                    self.recorder.event(
                        "snapshot_epoch", epoch=int(epoch), step=int(t),
                        shards=int(self.n_shards), committed=True,
                        op="restore")
                return state
            below = epoch       # someone failed the join: fall back

    def latest(self) -> Optional[int]:
        """Step index of the newest committed epoch (group-agreed), or
        None — the committed-epoch twin of the base store's newest
        readable file."""
        from pcg_mpi_solver_tpu.parallel.consensus import agree

        epoch = int(agree(self.comm, [self._newest_committed()], "min")[0])
        if epoch < 0:
            return None
        meta = next((m for e, m in self.committed_epochs()
                     if e == epoch), None)
        return int(meta["step"]) if meta and "step" in meta else None

    def _fingerprint_mismatch(self, saved: dict, diffs: dict) -> None:
        if self.elastic and set(diffs) == {"n_procs"}:
            # the NAMED elastic path: restoring an N-process epoch onto
            # M processes is exact for the dof-indexed CG carry — record
            # it loudly instead of refusing
            if self.recorder is not None:
                self.recorder.event(
                    "elastic_resume",
                    from_procs=int(saved.get("n_procs", -1)),
                    to_procs=int((self.fingerprint or {}).get(
                        "n_procs", -1)),
                    prefix=self.prefix)
                self.recorder.inc("resilience.elastic_resume")
            return
        super()._fingerprint_mismatch(saved, diffs)

    # -- retention ------------------------------------------------------
    def _prune(self) -> None:
        """Committed-epoch retention: keep the newest K committed epochs
        (``PCG_TPU_SNAP_KEEP``) plus anything newer than the newest
        committed epoch (it may still commit).  Each rank removes only
        its own shards; rank 0 additionally sweeps dropped markers and
        any leftover shards (e.g. of a rank count that shrank).  Races
        with a peer's prune are benign — the loser's remove is a no-op
        and readers fall back by construction."""
        committed = [e for e, _ in self.committed_epochs()]
        keep = set(committed[-self.retention():])
        newest = committed[-1] if committed else -1

        def droppable(epoch: int) -> bool:
            return epoch not in keep and epoch <= newest

        own = _glob.glob(os.path.join(
            self.path, f"{self.prefix}_e*.p{self.index}.npz"))
        sweep = list(own)
        if self.index == 0:
            sweep = _glob.glob(os.path.join(
                self.path, f"{self.prefix}_e*.p*.npz"))
        for p in sweep:
            m = self._EPOCH_SHARD_RE.search(os.path.basename(p))
            if m and droppable(int(m.group(1))):
                try:
                    os.remove(p)
                except OSError:
                    pass        # a racing peer's prune already has it
        if self.index == 0:
            for epoch in committed:
                if droppable(epoch):
                    try:
                        os.remove(self._marker_file(epoch))
                    except OSError:
                        pass
        # the base-store files of this prefix (snap_000001.npz style)
        # are a different namespace — never touched here

    def discard(self, t: int) -> None:
        """Drop every committed epoch of completed step ``t`` (markers
        first, so a reader racing the removal sees a consistent
        absent-epoch view, then each rank's own shards)."""
        for epoch, meta in self.committed_epochs():
            if int(meta.get("step", -1)) != int(t):
                continue
            if self.index == 0:
                try:
                    os.remove(self._marker_file(epoch))
                except OSError:
                    pass
            for idx in ([self.index] if self.index != 0
                        else range(max(self.n_shards,
                                       int(meta.get("n_shards", 1))))):
                try:
                    os.remove(self._shard_file(epoch, idx))
                except OSError:
                    pass
