"""Reusable resilience harness.

PR 3 inlined the recovery orchestration in ``solver/driver.py``; this
module is that machinery extracted so the quasi-static driver, the
implicit Newmark stepper and the explicit dynamics driver share ONE copy
of each behavior:

* :func:`run_with_recovery` — the ladder budget loop around
  :meth:`ChunkedEngine.run` (breakdown classification, bounded
  escalation through :class:`RecoveryHooks`, device-loss restarts, the
  ``recovery_done`` event).  Ex ``driver._step_chunked``.
* :func:`kinematic_state_io` — sharding-faithful device<->host transfer
  closures for a named-leaf state dict (the snapshot payloads).
* :class:`TimeHistoryGuard` — timestep-granular checkpoints for the
  time-history drivers: snapshot cadence into a
  ``utils/checkpoint.SnapshotStore`` (``step_*.npz``), kill-and-resume
  that continues MID-TIME-HISTORY, step-domain fault injection, and
  NaN/Inf rollback-to-last-checkpoint instead of silently integrating
  garbage.

Import contract: jax-free at module load, like the rest of
``resilience/`` (the transfer closures import jax lazily).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from pcg_mpi_solver_tpu.parallel.consensus import (
    agree_trigger, agree_triggers)
from pcg_mpi_solver_tpu.resilience.recovery import (
    RecoveryLadder, breakdown_trigger, column_trigger, is_device_loss)


# ----------------------------------------------------------------------
# Per-step recovery ladder around a ChunkedEngine
# ----------------------------------------------------------------------

@dataclasses.dataclass
class RecoveryHooks:
    """Driver-supplied recovery programs for :func:`run_with_recovery`.

    ``restart(x) -> (carry, normr)``: a cold Krylov carry at the ladder's
    restart iterate (the driver routes the matvec through its shared
    out-of-loop amul program so the restart costs no extra stencil
    instantiation).

    ``cold_restart() -> (carry, normr, prec)``: rebuild the step's cold
    start state after a device loss (the in-flight carry may be gone
    with the failed dispatch); the returned prec replaces the original
    when the loop was still using it.

    ``fallback_prec() -> prec``: the weaker-but-safer preconditioner
    inverse (ladder rung 2, ``ops/precond.fallback_kind``).

    ``escalation() -> (engine, data, prec)``: the f64 escalation engine
    (ladder rung 3, mixed mode).
    """

    restart: Callable[[Any], Tuple[Any, Any]]
    cold_restart: Optional[Callable[[], Tuple[Any, Any, Any]]] = None
    fallback_prec: Optional[Callable[[], Any]] = None
    escalation: Optional[Callable[[], Tuple[Any, Any, Any]]] = None


def run_with_recovery(engine, data, fext, carry, normr0, n2b, prec, *,
                      scfg, mixed: bool, recorder, hooks: RecoveryHooks,
                      resilience=None, total0: int = 0):
    """Run a chunked solve to termination through the bounded recovery
    ladder (resilience posture: ISSUE 3 / arXiv:2501.03743).

    When the budget loop terminates on a flag-2/4 breakdown, a NaN/Inf
    carry, or a device-loss exception, the solve restarts from the
    engine's tracked min-residual iterate through a bounded escalation —
    plain restart -> fallback preconditioner -> f64 escalation — instead
    of reporting the failure and discarding thousands of Krylov
    iterations.  The total iteration budget (``scfg.max_iter``) spans
    all attempts.

    Returns ``(engine_used, x_fin, flag, relres, total)`` — the engine
    that ran the final attempt (its ``last_trace`` holds the ring).
    """
    rec = recorder
    note = rec.note if rec is not None else (lambda s: None)
    comm = getattr(resilience, "comm", None)
    eng, eng_data, eng_prec = engine, data, prec
    ladder = None
    total = int(total0)
    while True:
        err = None
        try:
            x_fin, flag, relres, total = eng.run(
                eng_data, fext, carry, normr0, n2b, eng_prec,
                vlog=note, resilience=resilience, total0=total)
            trigger = breakdown_trigger(flag, relres)
            restart_x = eng.restart_x
        except Exception as e:          # noqa: BLE001 — classified below
            # the engine's guard already retried from the snapshot;
            # reaching here means the guard budget is spent (or there
            # was no snapshot to re-dispatch from)
            if scfg.max_recoveries <= 0 or not is_device_loss(e):
                raise
            trigger, restart_x, err = "device_loss", None, e
        # group consensus: every rank must take the SAME ladder branch
        # (a divergent branch pairs a live collective against a missing
        # one and wedges the fleet) — max-reduce the encoded triggers so
        # one rank's breakdown drives every rank's ladder in lockstep
        trigger = agree_trigger(comm, trigger)
        if trigger == "device_loss" and err is None:
            # another rank lost a device: this rank's carry is fine but
            # the group restart must be identical everywhere, and only
            # the cold start state is rank-independently reconstructible
            restart_x = None
        if trigger is None:
            break
        if ladder is None:
            ladder = RecoveryLadder(
                precond=scfg.precond, mixed=mixed,
                max_recoveries=scfg.max_recoveries, recorder=rec)
        action = ladder.next_action(trigger)
        if action is None:              # recovery budget spent
            if err is not None:
                raise err
            if trigger == "device_loss":
                # group-agreed loss seen on ANOTHER rank: this rank has
                # no local exception to re-raise, but returning normally
                # while the failing rank raises would diverge the fleet
                raise RuntimeError(
                    "group-agreed device loss with the recovery budget "
                    f"spent ({ladder.attempt} attempts); the failing "
                    "rank carries the original error")
            note(f"recovery budget exhausted ({ladder.attempt} "
                 f"attempts); reporting flag={flag} relres={relres:.3e}")
            break
        note(f"recovery attempt {ladder.attempt}/{scfg.max_recoveries}: "
             f"{action} after {trigger} (total={total})")
        if action == "fallback_prec" and hooks.fallback_prec is not None:
            eng_prec = hooks.fallback_prec()
        elif action == "escalate_f64" and hooks.escalation is not None:
            eng, eng_data, eng_prec = hooks.escalation()
        if restart_x is None:
            # device loss: the in-flight carry may be gone with the
            # failed dispatch — rebuild the step's cold start state
            if hooks.cold_restart is None:
                raise err if err is not None else RuntimeError(
                    "device_loss recovery without a cold_restart hook")
            carry, normr0, prec0 = hooks.cold_restart()
            if eng_prec is prec:
                eng_prec = prec0
            prec = prec0
        else:
            # min-residual-iterate restart: a cold Krylov carry at the
            # best iterate seen
            carry, normr0 = hooks.restart(restart_x)
    if ladder is not None and ladder.attempt and rec is not None:
        rec.event("recovery_done", flag=flag, relres=relres,
                  attempts=ladder.attempt,
                  actions=list(ladder.actions_taken))
    return eng, x_fin, flag, relres, total


# ----------------------------------------------------------------------
# Per-column recovery for blocked multi-RHS solves (ISSUE 9 tentpole)
# ----------------------------------------------------------------------

@dataclasses.dataclass
class ManyRecoveryHooks:
    """Driver-supplied blocked-solve programs for
    :func:`run_many_with_recovery`.

    ``cycle(carry, budget) -> (x, carry)``: one capped resumable blocked
    dispatch (driver ``many_cycle`` program; budget = remaining
    iteration allowance).

    ``recover(carry, restart_mask, fallback_mask, quarantine_mask) ->
    carry``: the masked per-column surgery program
    (``solver/pcg.restart_carry_many`` behind one jitted dispatch) —
    cold-restarts masked columns at their min-residual iterate, flips
    ``fallback_mask`` columns to the scalar-Jacobi fallback
    preconditioner, stamps ``quarantine_mask`` columns terminal.

    ``has_fallback``: whether the cycle program was built with a
    fallback preconditioner operand (``ops/precond.fallback_kind`` of
    the configured precond is not None) — without one the ladder's
    fallback rung repeats the plain restart instead.
    """

    cycle: Callable[[Any, int], Tuple[Any, Any]]
    recover: Callable[[Any, Any, Any, Any], Any]
    has_fallback: bool = False


def _upgrade_many_carry(carry: Dict[str, Any], nrhs: int,
                        lagged: bool) -> Dict[str, Any]:
    """Back-compat shim for blocked snapshots written before the
    per-column recovery state existed: fill the ``prec_sel`` (and the
    recurrence variants' ``drift``) leaves with their cold values —
    zeros, i.e. exactly the pre-upgrade behavior — so pre-existing
    ``many_*.npz`` resume points still resume instead of failing a
    pytree mismatch (the ``CheckpointManager.restore`` legacy-shim
    precedent).  Only fused snapshots can actually predate the drift
    leaf; pipelined carries always carried it, and their GV vector
    leaves need no shim (the variant postdates every legacy format)."""
    carry = dict(carry)
    carry.setdefault("prec_sel", np.zeros(nrhs, np.int32))
    if lagged:
        carry.setdefault("drift", np.zeros(nrhs, np.int32))
    return carry


def run_many_with_recovery(carry, *, scfg, nrhs: int, hooks, recorder,
                           resilience=None, resume: bool = False,
                           lagged: bool = False, total0: int = 0,
                           iters_cols0=None):
    """Run a blocked (multi-RHS) chunked solve to termination with
    FAULT ISOLATION BETWEEN COLUMNS — the blocked twin of
    :func:`run_with_recovery`.

    Per capped dispatch, every column's carry flag and residual norm are
    classified (:func:`~pcg_mpi_solver_tpu.resilience.recovery.column_trigger`):
    a flag-2/4/6 breakdown or NaN/Inf carry in column *k* consumes one
    attempt of column *k*'s OWN bounded
    :class:`~pcg_mpi_solver_tpu.resilience.recovery.RecoveryLadder`
    (masked min-residual restart -> per-column scalar-Jacobi fallback)
    while healthy columns keep iterating — or stay frozen — with
    bit-identical arithmetic; a column whose budget is spent (or absent,
    ``scfg.max_recoveries <= 0``) is QUARANTINED: terminal
    ``QUARANTINE_FLAG``, one ``rhs_quarantine`` telemetry event naming
    the column, the block completes regardless.  The dispatch guard,
    mid-solve ``many_*.npz`` snapshots, resume, and deterministic
    faults all thread through ``resilience``
    (:class:`~pcg_mpi_solver_tpu.resilience.recovery.ResilienceContext`,
    optional), exactly like the scalar path.

    Returns ``(x, carry, flags, total, iters_cols, quarantined,
    recoveries, drift_cols)``.
    """
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.solver.pcg import QUARANTINE_FLAG

    rec = recorder
    note = rec.note if rec is not None else (lambda s: None)
    R = int(nrhs)
    total = int(total0)
    iters_cols = (np.zeros(R, np.int64) if iters_cols0 is None
                  else np.asarray(iters_cols0, np.int64).copy())
    faults = resilience.faults if resilience is not None else None
    max_iter = int(scfg.max_iter)
    ladders: Dict[int, RecoveryLadder] = {}
    actions_taken: list = []

    # ---- mid-solve resume (``many_*.npz``) ---------------------------
    st = resilience.load_resume_state() if resilience is not None else None
    if st is not None and str(np.asarray(st.get("kind", ""))) == "many":
        carry = resilience.restore_device(
            {"carry": _upgrade_many_carry(st["carry"], R, lagged)})["carry"]
        total = int(np.asarray(st["total"]))
        iters_cols = np.asarray(st["iters_cols"], np.int64).copy()
        note(f"resumed blocked solve (nrhs={R}) at {total} iterations")
    elif resume:
        # the negative signal matters operationally: a pruned/corrupt/
        # absent snapshot must leave a breadcrumb that this run started
        # COLD, not a stream indistinguishable from a successful resume
        note(f"solve_many resume requested but no usable blocked "
             f"snapshot found (nrhs={R}); starting cold")

    flags = np.asarray(carry["flag"])
    quarantined = {k for k in range(R) if flags[k] == QUARANTINE_FLAG}
    # drift accounting ACCUMULATES per-dispatch increments: the carry's
    # drift leaf resets to 0 on every ladder restart (restart_carry_many
    # cold state), so reading it once at the end would report 0 exactly
    # on the solves where drift triggered a recovery
    drift_cols = np.zeros(R, np.int64)
    drift_prev = np.zeros(R, np.int64)
    x_fin = carry["x"]
    while np.any(flags == 1) and total < max_iter:
        # group liveness first, OUTSIDE the dispatch guard: a dead peer
        # must surface as DeadPeerError (named, bounded by the deadline)
        # rather than as an XLA collective hanging inside the dispatch
        # and being misread as a retryable device loss
        if resilience is not None:
            resilience.sync_boundary()
        try:
            if faults is not None:
                faults.on_dispatch()
            x_fin, carry = hooks.cycle(carry, max_iter - total)
            execv = np.asarray(carry["exec"])
            flags = np.asarray(carry["flag"])
            normr = np.asarray(carry["normr_act"], dtype=np.float64)
        except Exception as e:          # noqa: BLE001 — classified below
            st = (resilience.handle_dispatch_failure(e, "many")
                  if resilience is not None else None)
            if st is None:
                raise
            # re-dispatch from the snapshot (the donated blocked carry
            # may have been consumed by the failed dispatch — the host
            # snapshot is the one copy that cannot have been)
            carry = resilience.restore_device(
                {"carry": _upgrade_many_carry(st["carry"], R,
                                              lagged)})["carry"]
            total = int(np.asarray(st["total"]))
            iters_cols = np.asarray(st["iters_cols"], np.int64).copy()
            flags = np.asarray(carry["flag"])
            # the restored snapshot predates any later quarantine/drift:
            # re-derive BOTH from the restored carry so a column
            # quarantined after the snapshot is re-classified (and
            # re-recovered or re-quarantined) instead of being skipped
            # forever in its restored poisoned state
            quarantined = {k for k in range(R)
                           if flags[k] == QUARANTINE_FLAG}
            if lagged and "drift" in carry:
                drift_prev = np.asarray(carry["drift"], dtype=np.int64)
            continue
        if faults is not None:
            faults.on_dispatch_done()
        iters_cols += execv.astype(np.int64)
        total += int(execv.max()) if execv.size else 0
        if lagged and "drift" in carry:
            cur = np.asarray(carry["drift"], dtype=np.int64)
            drift_cols += np.maximum(cur - drift_prev, 0)
            drift_prev = cur

        # ---- per-column trigger classification + ladder --------------
        triggers = {}
        for k in range(R):
            if k in quarantined:
                continue
            t = column_trigger(int(flags[k]), float(normr[k]))
            if t is not None:
                triggers[k] = t
        # group consensus: one packed max-reduce so every rank drives
        # the SAME per-column ladders (divergent restart/quarantine
        # masks would change the jitted recover dispatch shape on one
        # rank only and wedge the next collective)
        comm = getattr(resilience, "comm", None)
        if comm is not None and getattr(comm, "n_procs", 1) > 1:
            triggers = {k: t
                        for k, t in agree_triggers(comm, triggers,
                                                   R).items()
                        if k not in quarantined}
        if triggers:
            restart_m = np.zeros(R, bool)
            fb_m = np.zeros(R, bool)
            quar_m = np.zeros(R, bool)
            for k, trig in sorted(triggers.items()):
                lad = ladders.get(k)
                if lad is None and scfg.max_recoveries > 0:
                    # the ladder's fallback rung must match what the
                    # COMPILED cycle program can actually do: without a
                    # wired fallback inverse (hooks.has_fallback — the
                    # programs are built once per width), advertising
                    # the rung would emit `fallback_prec` events for
                    # what is really a second plain restart
                    lad = ladders[k] = RecoveryLadder(
                        precond=(scfg.precond if hooks.has_fallback
                                 else "jacobi"), mixed=False,
                        max_recoveries=scfg.max_recoveries,
                        recorder=rec, extra={"rhs": k})
                action = lad.next_action(trig) if lad is not None else None
                if action is None:
                    quar_m[k] = True
                    quarantined.add(k)
                    if rec is not None:
                        rec.event("rhs_quarantine", rhs=k, trigger=trig,
                                  flag=QUARANTINE_FLAG,
                                  attempts=lad.attempt if lad else 0)
                        rec.inc("resilience.rhs_quarantine")
                    note(f"solve_many: column {k} quarantined "
                         f"({trig}, attempts="
                         f"{lad.attempt if lad else 0})")
                else:
                    actions_taken.append(action)
                    restart_m[k] = True
                    if action == "fallback_prec" and hooks.has_fallback:
                        fb_m[k] = True
                    note(f"solve_many recovery: column {k} {action} "
                         f"after {trig} (total={total})")
            carry = hooks.recover(carry, jnp.asarray(restart_m),
                                  jnp.asarray(fb_m), jnp.asarray(quar_m))
            flags = np.asarray(carry["flag"])
            if lagged and "drift" in carry:
                # restarted columns come back with a zeroed drift leaf;
                # re-baseline so the next dispatch's increment is honest
                drift_prev = np.asarray(carry["drift"], dtype=np.int64)
        if not np.any(flags == 1):
            break
        if resilience is not None:
            resilience.after_chunk(lambda: dict(
                kind="many", total=total, iters_cols=iters_cols,
                carry=carry))
            if faults is not None:
                carry = faults.at_boundary(carry, blocked=True)
    recoveries = sum(l.attempt for l in ladders.values())
    if recoveries and rec is not None:
        rec.event("recovery_done", flag=[int(f) for f in flags],
                  relres=None, attempts=recoveries,
                  actions=actions_taken)
    if rec is not None and int(drift_cols.sum()) > 0:
        # the recurrence-variant residual-drift telemetry twin (obs/schema
        # `resid_drift`): cumulative drifted true-residual checks per
        # column, surfaced once per blocked solve
        rec.event("resid_drift", drift=int(drift_cols.sum()),
                  cols=[int(v) for v in drift_cols])
        rec.gauge("resid.drift", int(drift_cols.sum()))
    return (x_fin, carry, flags, total, iters_cols,
            sorted(quarantined), recoveries, drift_cols)


# ----------------------------------------------------------------------
# Snapshot state transfer
# ----------------------------------------------------------------------

def kinematic_state_io(mesh, part_spec, dtype, device_keys):
    """``(fetch, put)`` closures for a flat state dict whose
    ``device_keys`` leaves are parts-sharded ``(n_parts, n_loc)`` device
    vectors (the kinematic state) and whose remaining leaves are host
    numpy (histories, counters, schedules).

    ``fetch`` is collective on multi-host (every process participates in
    the all-gathers; only the primary later writes); ``put`` restores
    the device leaves sharding-faithfully and passes host leaves
    through unchanged."""
    device_keys = frozenset(device_keys)

    def fetch(state: Dict[str, Any]) -> Dict[str, Any]:
        from pcg_mpi_solver_tpu.parallel.distributed import fetch_global

        return {k: (fetch_global(v, mesh) if k in device_keys
                    else np.asarray(v))
                for k, v in state.items()}

    def put(state: Dict[str, Any]) -> Dict[str, Any]:
        from pcg_mpi_solver_tpu.parallel.distributed import put_sharded

        return {k: (put_sharded(np.asarray(v, dtype), mesh, part_spec)
                    if k in device_keys else v)
                for k, v in state.items()}

    return fetch, put


# ----------------------------------------------------------------------
# Timestep-granular checkpoint / rollback / fault harness
# ----------------------------------------------------------------------

class TimeHistoryGuard:
    """Resilience harness for the time-history drivers (explicit
    ``solver/dynamics.py`` and implicit ``solver/newmark.py``).

    Three hooks, all driven by the host time loop:

    * :meth:`load_resume` — restore the newest persisted step snapshot
      (``step_*.npz`` under the checkpoint dir) so ``--resume``
      continues MID-TIME-HISTORY with bit-identical probe/frame/trace
      history;
    * :meth:`boundary` — after each completed timestep: snapshot the
      full kinematic state at cadence (clean state FIRST), then let
      step-domain faults fire (``kill`` raises after the snapshot, like
      a real preemption; poisons corrupt the live state the snapshot
      just protected);
    * :meth:`rollback` — a NaN/Inf state detected after a step restores
      the last good snapshot (memory-first, so no disk round-trip)
      instead of silently integrating garbage; bounded by
      ``max_recoveries`` like the Krylov ladder.
    """

    def __init__(self, *, store=None, snapshot_every: int = 0,
                 fetch_state=None, put_state=None, recorder=None,
                 faults=None, max_recoveries: int = 0):
        self.store = store
        self.snapshot_every = int(snapshot_every)
        self.fetch_state = fetch_state or (lambda s: s)
        self.put_state = put_state or (lambda s: s)
        self.recorder = recorder
        self.faults = faults
        self.max_recoveries = int(max_recoveries)
        self.recoveries = 0
        self._mem: Optional[Tuple[int, Dict[str, Any]]] = None

    # -- resume ---------------------------------------------------------
    def load_resume(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        """Newest persisted step snapshot as ``(t, device_state)``, or
        None when there is nothing to resume from.  The restored host
        copy doubles as the first rollback point."""
        if self.store is None:
            return None
        t = self.store.latest()
        if t is None:
            return None
        state = self.store.load(t)
        if state is None:
            return None
        self._mem = (t, state)
        if self.recorder is not None:
            self.recorder.event("step_snapshot", op="restore", step=t)
            self.recorder.inc("resilience.step_snapshot.restore")
        return t, self.put_state(state)

    # -- per-step boundary ----------------------------------------------
    def boundary(self, t: int, state_fn: Callable[[], Dict[str, Any]]) \
            -> Optional[Dict[str, Any]]:
        """Completed-timestep hook.  ``state_fn`` builds the full device
        state dict lazily (with snapshots and faults both idle this
        costs nothing).  Returns the possibly-poisoned device state the
        caller must continue with, or None when untouched."""
        state = None
        if self.snapshot_every > 0 and t % self.snapshot_every == 0:
            state = state_fn()
            host = self.fetch_state(state)
            self._mem = (t, host)
            if self.store is not None:
                self.store.save(t, host)
                if self.recorder is not None:
                    self.recorder.event("step_snapshot", op="save",
                                        step=t)
                    self.recorder.inc("resilience.step_snapshot.save")
        if self.faults is not None and self.faults.step_armed:
            if state is None:
                state = state_fn()
            state = self.faults.at_step(t, state)
        return state

    # -- poison rollback ------------------------------------------------
    def rollback(self, t: int) -> Tuple[int, Dict[str, Any]]:
        """Non-finite state detected after timestep ``t``: the state to
        roll back to as ``(t0, device_state)``.  Consumes one recovery;
        raises :class:`FloatingPointError` when there is no snapshot or
        the budget is spent (an honest failure beats looping on a
        deterministic instability)."""
        if self._mem is None or self.recoveries >= self.max_recoveries:
            raise FloatingPointError(
                f"non-finite state after timestep {t} and no rollback "
                f"available (snapshot={'yes' if self._mem else 'no'}, "
                f"recoveries={self.recoveries}/{self.max_recoveries}); "
                "for explicit dynamics check dt against stable_dt()")
        self.recoveries += 1
        t0, host = self._mem
        if self.recorder is not None:
            self.recorder.event("recovery", action="rollback",
                                attempt=self.recoveries,
                                trigger="nan_carry", step=t, to_step=t0)
            self.recorder.inc("resilience.recovery.rollback")
        return t0, self.put_state(host)
