"""Reusable resilience harness.

PR 3 inlined the recovery orchestration in ``solver/driver.py``; this
module is that machinery extracted so the quasi-static driver, the
implicit Newmark stepper and the explicit dynamics driver share ONE copy
of each behavior:

* :func:`run_with_recovery` — the ladder budget loop around
  :meth:`ChunkedEngine.run` (breakdown classification, bounded
  escalation through :class:`RecoveryHooks`, device-loss restarts, the
  ``recovery_done`` event).  Ex ``driver._step_chunked``.
* :func:`kinematic_state_io` — sharding-faithful device<->host transfer
  closures for a named-leaf state dict (the snapshot payloads).
* :class:`TimeHistoryGuard` — timestep-granular checkpoints for the
  time-history drivers: snapshot cadence into a
  ``utils/checkpoint.SnapshotStore`` (``step_*.npz``), kill-and-resume
  that continues MID-TIME-HISTORY, step-domain fault injection, and
  NaN/Inf rollback-to-last-checkpoint instead of silently integrating
  garbage.

Import contract: jax-free at module load, like the rest of
``resilience/`` (the transfer closures import jax lazily).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from pcg_mpi_solver_tpu.resilience.recovery import (
    RecoveryLadder, breakdown_trigger, is_device_loss)


# ----------------------------------------------------------------------
# Per-step recovery ladder around a ChunkedEngine
# ----------------------------------------------------------------------

@dataclasses.dataclass
class RecoveryHooks:
    """Driver-supplied recovery programs for :func:`run_with_recovery`.

    ``restart(x) -> (carry, normr)``: a cold Krylov carry at the ladder's
    restart iterate (the driver routes the matvec through its shared
    out-of-loop amul program so the restart costs no extra stencil
    instantiation).

    ``cold_restart() -> (carry, normr, prec)``: rebuild the step's cold
    start state after a device loss (the in-flight carry may be gone
    with the failed dispatch); the returned prec replaces the original
    when the loop was still using it.

    ``fallback_prec() -> prec``: the weaker-but-safer preconditioner
    inverse (ladder rung 2, ``ops/precond.fallback_kind``).

    ``escalation() -> (engine, data, prec)``: the f64 escalation engine
    (ladder rung 3, mixed mode).
    """

    restart: Callable[[Any], Tuple[Any, Any]]
    cold_restart: Optional[Callable[[], Tuple[Any, Any, Any]]] = None
    fallback_prec: Optional[Callable[[], Any]] = None
    escalation: Optional[Callable[[], Tuple[Any, Any, Any]]] = None


def run_with_recovery(engine, data, fext, carry, normr0, n2b, prec, *,
                      scfg, mixed: bool, recorder, hooks: RecoveryHooks,
                      resilience=None, total0: int = 0):
    """Run a chunked solve to termination through the bounded recovery
    ladder (resilience posture: ISSUE 3 / arXiv:2501.03743).

    When the budget loop terminates on a flag-2/4 breakdown, a NaN/Inf
    carry, or a device-loss exception, the solve restarts from the
    engine's tracked min-residual iterate through a bounded escalation —
    plain restart -> fallback preconditioner -> f64 escalation — instead
    of reporting the failure and discarding thousands of Krylov
    iterations.  The total iteration budget (``scfg.max_iter``) spans
    all attempts.

    Returns ``(engine_used, x_fin, flag, relres, total)`` — the engine
    that ran the final attempt (its ``last_trace`` holds the ring).
    """
    rec = recorder
    note = rec.note if rec is not None else (lambda s: None)
    eng, eng_data, eng_prec = engine, data, prec
    ladder = None
    total = int(total0)
    while True:
        err = None
        try:
            x_fin, flag, relres, total = eng.run(
                eng_data, fext, carry, normr0, n2b, eng_prec,
                vlog=note, resilience=resilience, total0=total)
            trigger = breakdown_trigger(flag, relres)
            restart_x = eng.restart_x
        except Exception as e:          # noqa: BLE001 — classified below
            # the engine's guard already retried from the snapshot;
            # reaching here means the guard budget is spent (or there
            # was no snapshot to re-dispatch from)
            if scfg.max_recoveries <= 0 or not is_device_loss(e):
                raise
            trigger, restart_x, err = "device_loss", None, e
        if trigger is None:
            break
        if ladder is None:
            ladder = RecoveryLadder(
                precond=scfg.precond, mixed=mixed,
                max_recoveries=scfg.max_recoveries, recorder=rec)
        action = ladder.next_action(trigger)
        if action is None:              # recovery budget spent
            if err is not None:
                raise err
            note(f"recovery budget exhausted ({ladder.attempt} "
                 f"attempts); reporting flag={flag} relres={relres:.3e}")
            break
        note(f"recovery attempt {ladder.attempt}/{scfg.max_recoveries}: "
             f"{action} after {trigger} (total={total})")
        if action == "fallback_prec" and hooks.fallback_prec is not None:
            eng_prec = hooks.fallback_prec()
        elif action == "escalate_f64" and hooks.escalation is not None:
            eng, eng_data, eng_prec = hooks.escalation()
        if restart_x is None:
            # device loss: the in-flight carry may be gone with the
            # failed dispatch — rebuild the step's cold start state
            if hooks.cold_restart is None:
                raise err if err is not None else RuntimeError(
                    "device_loss recovery without a cold_restart hook")
            carry, normr0, prec0 = hooks.cold_restart()
            if eng_prec is prec:
                eng_prec = prec0
            prec = prec0
        else:
            # min-residual-iterate restart: a cold Krylov carry at the
            # best iterate seen
            carry, normr0 = hooks.restart(restart_x)
    if ladder is not None and ladder.attempt and rec is not None:
        rec.event("recovery_done", flag=flag, relres=relres,
                  attempts=ladder.attempt,
                  actions=list(ladder.actions_taken))
    return eng, x_fin, flag, relres, total


# ----------------------------------------------------------------------
# Snapshot state transfer
# ----------------------------------------------------------------------

def kinematic_state_io(mesh, part_spec, dtype, device_keys):
    """``(fetch, put)`` closures for a flat state dict whose
    ``device_keys`` leaves are parts-sharded ``(n_parts, n_loc)`` device
    vectors (the kinematic state) and whose remaining leaves are host
    numpy (histories, counters, schedules).

    ``fetch`` is collective on multi-host (every process participates in
    the all-gathers; only the primary later writes); ``put`` restores
    the device leaves sharding-faithfully and passes host leaves
    through unchanged."""
    device_keys = frozenset(device_keys)

    def fetch(state: Dict[str, Any]) -> Dict[str, Any]:
        from pcg_mpi_solver_tpu.parallel.distributed import fetch_global

        return {k: (fetch_global(v, mesh) if k in device_keys
                    else np.asarray(v))
                for k, v in state.items()}

    def put(state: Dict[str, Any]) -> Dict[str, Any]:
        from pcg_mpi_solver_tpu.parallel.distributed import put_sharded

        return {k: (put_sharded(np.asarray(v, dtype), mesh, part_spec)
                    if k in device_keys else v)
                for k, v in state.items()}

    return fetch, put


# ----------------------------------------------------------------------
# Timestep-granular checkpoint / rollback / fault harness
# ----------------------------------------------------------------------

class TimeHistoryGuard:
    """Resilience harness for the time-history drivers (explicit
    ``solver/dynamics.py`` and implicit ``solver/newmark.py``).

    Three hooks, all driven by the host time loop:

    * :meth:`load_resume` — restore the newest persisted step snapshot
      (``step_*.npz`` under the checkpoint dir) so ``--resume``
      continues MID-TIME-HISTORY with bit-identical probe/frame/trace
      history;
    * :meth:`boundary` — after each completed timestep: snapshot the
      full kinematic state at cadence (clean state FIRST), then let
      step-domain faults fire (``kill`` raises after the snapshot, like
      a real preemption; poisons corrupt the live state the snapshot
      just protected);
    * :meth:`rollback` — a NaN/Inf state detected after a step restores
      the last good snapshot (memory-first, so no disk round-trip)
      instead of silently integrating garbage; bounded by
      ``max_recoveries`` like the Krylov ladder.
    """

    def __init__(self, *, store=None, snapshot_every: int = 0,
                 fetch_state=None, put_state=None, recorder=None,
                 faults=None, max_recoveries: int = 0):
        self.store = store
        self.snapshot_every = int(snapshot_every)
        self.fetch_state = fetch_state or (lambda s: s)
        self.put_state = put_state or (lambda s: s)
        self.recorder = recorder
        self.faults = faults
        self.max_recoveries = int(max_recoveries)
        self.recoveries = 0
        self._mem: Optional[Tuple[int, Dict[str, Any]]] = None

    # -- resume ---------------------------------------------------------
    def load_resume(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        """Newest persisted step snapshot as ``(t, device_state)``, or
        None when there is nothing to resume from.  The restored host
        copy doubles as the first rollback point."""
        if self.store is None:
            return None
        t = self.store.latest()
        if t is None:
            return None
        state = self.store.load(t)
        if state is None:
            return None
        self._mem = (t, state)
        if self.recorder is not None:
            self.recorder.event("step_snapshot", op="restore", step=t)
            self.recorder.inc("resilience.step_snapshot.restore")
        return t, self.put_state(state)

    # -- per-step boundary ----------------------------------------------
    def boundary(self, t: int, state_fn: Callable[[], Dict[str, Any]]) \
            -> Optional[Dict[str, Any]]:
        """Completed-timestep hook.  ``state_fn`` builds the full device
        state dict lazily (with snapshots and faults both idle this
        costs nothing).  Returns the possibly-poisoned device state the
        caller must continue with, or None when untouched."""
        state = None
        if self.snapshot_every > 0 and t % self.snapshot_every == 0:
            state = state_fn()
            host = self.fetch_state(state)
            self._mem = (t, host)
            if self.store is not None:
                self.store.save(t, host)
                if self.recorder is not None:
                    self.recorder.event("step_snapshot", op="save",
                                        step=t)
                    self.recorder.inc("resilience.step_snapshot.save")
        if self.faults is not None and self.faults.step_armed:
            if state is None:
                state = state_fn()
            state = self.faults.at_step(t, state)
        return state

    # -- poison rollback ------------------------------------------------
    def rollback(self, t: int) -> Tuple[int, Dict[str, Any]]:
        """Non-finite state detected after timestep ``t``: the state to
        roll back to as ``(t0, device_state)``.  Consumes one recovery;
        raises :class:`FloatingPointError` when there is no snapshot or
        the budget is spent (an honest failure beats looping on a
        deterministic instability)."""
        if self._mem is None or self.recoveries >= self.max_recoveries:
            raise FloatingPointError(
                f"non-finite state after timestep {t} and no rollback "
                f"available (snapshot={'yes' if self._mem else 'no'}, "
                f"recoveries={self.recoveries}/{self.max_recoveries}); "
                "for explicit dynamics check dt against stable_dt()")
        self.recoveries += 1
        t0, host = self._mem
        if self.recorder is not None:
            self.recorder.event("recovery", action="rollback",
                                attempt=self.recoveries,
                                trigger="nan_carry", step=t, to_step=t0)
            self.recorder.inc("resilience.recovery.rollback")
        return t0, self.put_state(host)
