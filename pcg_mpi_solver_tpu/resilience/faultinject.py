"""Deterministic fault injection for the resilience subsystem.

Every recovery path in the framework (breakdown ladder, dispatch guard,
mid-Krylov snapshot/resume) must be exercisable in tier-1 on CPU, without
hardware and without flaky timing: faults fire at exact, configured
positions in the chunked dispatch sequence, so a test (or a chaos run on
real hardware) is bit-reproducible.

A :class:`FaultPlan` is parsed from a spec string (env ``PCG_TPU_FAULTS``
or passed programmatically, e.g. ``Solver.fault_plan = FaultPlan(...)``):

    spec     := term ("," term)*
    term     := mode "@" ["s:" | "col:" | "job:" | "rank:" rank ":"]
                index ["*" count]
    mode     := "kill" | "exc" | "nan" | "inf" | "rho0" | "sleep"
    index    := 0-based position in the mode's counter (see below);
                with the "s:" prefix, the ABSOLUTE timestep number of a
                time-history run; with the "col:" prefix, the COLUMN
                index of a blocked multi-RHS solve; with the "job:"
                prefix, the ABSOLUTE admission ordinal of a solve-
                service job (serve/); with the "rank:" prefix, the
                dispatch/boundary counter index on process ``rank``
                only (omitted index = 0: ``kill@rank:1`` ==
                ``kill@rank:1:0``)
    count    := consecutive firings (default 1; "exc@3*2" also fails the
                first retry of dispatch 3)

Six counter domains.  The first two are monotone over the life of the
plan (they keep running across recovery restarts, so a second fault can
be aimed at a later ladder rung):

* the DISPATCH counter advances once per successfully completed Krylov
  dispatch ("exc" fires *before* the dispatch with that index runs);
* the BOUNDARY counter advances once per chunk boundary — after a direct
  chunk / mixed refinement cycle completes and any due snapshot is taken
  ("kill" / "nan" / "inf" / "rho0" / "sleep" fire *at* that boundary);
* the STEP domain ("s:" prefix — ``kill@s:3``, ``nan@s:5``) is indexed
  by the absolute completed-timestep number of a dynamics/Newmark time
  history (:meth:`FaultPlan.at_step`, driven by
  ``resilience/engine.TimeHistoryGuard``): the fault fires at EXACTLY
  timestep N, after any due step snapshot — so a rollback/resume that
  replays past N does not re-fire a consumed fault, while ``*count``
  deliberately re-fires it to exercise budget exhaustion.  Step-domain
  modes are ``kill``/``nan``/``inf`` (poison lands on the kinematic
  state leaf ``u``);
* the COLUMN domain ("col:" prefix — ``nan@col:2``, ``rho0@col:0``) is
  indexed by the RHS-block COLUMN of a blocked multi-RHS solve
  (``Solver.solve_many``): the fault fires at the next blocked chunk
  boundary (after any due snapshot, like the boundary domain) and
  poisons ONLY that column of the carry — ``nan``/``inf`` land on the
  column's residual, ``rho0`` zeroes the column's rho — so the
  per-column recovery ladder and quarantine paths run deterministically
  in tier-1 while every other column stays bit-identical (the poison is
  a ``jnp.where`` column select, never a whole-block rescale).
  ``*count`` re-fires it at that many consecutive boundaries to defeat
  a bounded per-column recovery budget;
* the JOB domain ("job:" prefix — ``exc@job:1``, ``nan@job:0``,
  ``sleep@job:2``) is indexed by the ABSOLUTE admission ordinal of a
  solve-service job (``serve/``, :meth:`FaultPlan.at_job`): the fault
  fires at the SERVICE BOUNDARY, when the daemon is about to dispatch
  the block containing the k-th admitted job — ``exc`` raises
  :class:`InjectedDispatchError` (the job fails with a named verdict,
  its co-batched tenants dispatch unharmed), ``nan`` asks the daemon
  to poison THAT job's RHS column (the service-boundary quarantine
  drill), ``sleep`` delays the whole block on the host (the
  deterministic window the SIGKILL chaos test fires inside).  Ordinals
  never reset: a restarted daemon continues the journal's admission
  numbering, and replay pre-consumes the ordinals the journal proves
  already passed the boundary (:meth:`FaultPlan.replay_consume_job`) —
  same never-re-fire contract as the step domain's absolute indexing;
* the RANK domain ("rank:" prefix — ``kill@rank:1``, ``exc@rank:0``,
  ``sleep@rank:1:3``) gates a dispatch/boundary-counter fault on ONE
  process of a multi-controller run, so distributed chaos drills are
  deterministic: every process parses the same spec
  (``PCG_TPU_FAULTS`` is shared), but the fault fires only where
  ``jax.process_index()`` matches.  ``exc`` rides the dispatch
  counter, the other modes the boundary counter, exactly like their
  unprefixed twins.  A rank at/past ``jax.process_count()`` follows
  the cannot-land contract (neither consumed nor recorded), same as a
  column fault aimed past the block width.

Modes and the recovery path each one exercises:

``exc``   raise :class:`InjectedDispatchError` (walks/talks like an XLA
          device-loss error) before the dispatch -> dispatch guard
          (snapshot re-dispatch) or, with the guard exhausted, the
          driver ladder's ``device_loss`` restart.
``kill``  raise :class:`SimulatedKill` at the chunk boundary, after the
          snapshot -> kill-and-resume (``BaseException`` on purpose:
          like a real SIGKILL it must not be swallowable by any
          ``except Exception`` on the way out).
``inf``   overwrite the nonzero entries of the carry residual with Inf
          -> the next preconditioner apply goes Inf -> flag 2.
``rho0``  zero the carry ``rho`` -> the resumed beta recurrence divides
          by zero -> flag 4 (rho/pq breakdown).
``nan``   multiply the carry residual by NaN — the silent-corruption
          case: NO MATLAB flag trips on NaN (every breakdown predicate
          compares false), so this exercises the host-side NaN-carry
          detection, not the in-graph flags.
``sleep`` ``time.sleep`` on the HOST at the chunk boundary (duration
          ``FaultPlan.sleep_s``, env ``PCG_TPU_FAULT_SLEEP_S``, default
          0.25 s) — the straggler simulator: not a failure at all, so
          no recovery path fires, but on a multi-controller run every
          OTHER process blocks at the next collective until this one
          arrives.  The deterministic delayed-rank injection the
          obs/fleet.py skew-attribution tests are built on.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

MODES = ("kill", "exc", "nan", "inf", "rho0", "sleep")
_DISPATCH_MODES = ("exc",)
_BOUNDARY_MODES = ("kill", "nan", "inf", "rho0", "sleep")
_STEP_MODES = ("kill", "nan", "inf")
_COL_MODES = ("nan", "inf", "rho0")
_JOB_MODES = ("exc", "nan", "sleep")


class SimulatedKill(BaseException):
    """Simulated process death at a chunk boundary.

    Derives from ``BaseException`` (like ``KeyboardInterrupt``) so no
    recovery handler can catch it: a killed process does not get to run
    its ladder — only a NEW process's ``--resume`` does.
    """


class InjectedDispatchError(RuntimeError):
    """Synthetic device-loss exception (stands in for XlaRuntimeError/
    UNAVAILABLE from a dropped tunnel or preempted device)."""


def _parse(spec: str):
    """spec string -> ({mode: {index: count}}, {mode: {step: count}},
    {mode: {col: count}}, {mode: {job: count}},
    {mode: {(rank, index): count}}) for the dispatch/boundary domains,
    the step domain, the per-column domain of blocked multi-RHS solves,
    the per-job domain of the solve service, and the per-process rank
    domain."""
    out: Dict[str, Dict[int, int]] = {}
    steps: Dict[str, Dict[int, int]] = {}
    cols: Dict[str, Dict[int, int]] = {}
    jobs: Dict[str, Dict[int, int]] = {}
    ranks: Dict[str, Dict[tuple, int]] = {}
    for term in (t.strip() for t in spec.split(",")):
        if not term:
            continue
        try:
            mode, rest = term.split("@", 1)
            count = 1
            if "*" in rest:
                rest, c = rest.split("*", 1)
                count = int(c)
            rest = rest.strip()
            step_domain = rest.startswith("s:")
            col_domain = rest.startswith("col:")
            job_domain = rest.startswith("job:")
            rank_domain = rest.startswith("rank:")
            rank = None
            if rank_domain:
                bits = rest[len("rank:"):].split(":")
                if len(bits) > 2:
                    raise ValueError(rest)
                rank = int(bits[0])
                idx = int(bits[1]) if len(bits) > 1 else 0
            else:
                idx = int(rest[4:] if col_domain or job_domain
                          else rest[2:] if step_domain else rest)
        except ValueError:
            raise ValueError(
                f"bad fault term {term!r} "
                "(want mode@[s:|col:|job:|rank:R:]index[*count])")
        mode = mode.strip()
        if mode not in MODES:
            raise ValueError(f"unknown fault mode {mode!r} "
                             f"(valid: {', '.join(MODES)})")
        if idx < 0 or count < 1 or (rank is not None and rank < 0):
            raise ValueError(f"bad fault term {term!r}: rank >= 0, "
                             f"index >= 0, count >= 1")
        if rank_domain:
            ranks.setdefault(mode, {})[(rank, idx)] = count
        elif step_domain:
            if mode not in _STEP_MODES:
                raise ValueError(
                    f"fault mode {mode!r} has no step-domain trigger "
                    f"(valid at s: indices: {', '.join(_STEP_MODES)})")
            steps.setdefault(mode, {})[idx] = count
        elif col_domain:
            if mode not in _COL_MODES:
                raise ValueError(
                    f"fault mode {mode!r} has no column-domain trigger "
                    f"(valid at col: indices: {', '.join(_COL_MODES)})")
            cols.setdefault(mode, {})[idx] = count
        elif job_domain:
            if mode not in _JOB_MODES:
                raise ValueError(
                    f"fault mode {mode!r} has no job-domain trigger "
                    f"(valid at job: indices: {', '.join(_JOB_MODES)})")
            jobs.setdefault(mode, {})[idx] = count
        else:
            out.setdefault(mode, {})[idx] = count
    return out, steps, cols, jobs, ranks


class FaultPlan:
    """One deterministic injection schedule (see module docstring).

    Stateful and single-use by design: counters and remaining fire-counts
    advance as the solve runs, so a plan instance describes one process
    lifetime, exactly like the failures it simulates.
    """

    def __init__(self, spec: str, recorder=None):
        (self._faults, self._step_faults, self._col_faults,
         self._job_faults, self._rank_faults) = _parse(spec)
        self.recorder = recorder
        self.dispatches = 0         # completed Krylov dispatches
        self.boundaries = 0         # completed chunk boundaries
        self.fired: List[dict] = []  # (mode, point, index) audit trail
        try:
            self.sleep_s = float(
                os.environ.get("PCG_TPU_FAULT_SLEEP_S", 0.25))
        except ValueError:
            self.sleep_s = 0.25     # straggler-delay duration ("sleep")

    @classmethod
    def from_env(cls, recorder=None) -> Optional["FaultPlan"]:
        """Plan from ``PCG_TPU_FAULTS``; None when unset/empty."""
        spec = os.environ.get("PCG_TPU_FAULTS", "").strip()
        return cls(spec, recorder=recorder) if spec else None

    @property
    def armed(self) -> bool:
        return (any(self._faults.values()) or self.step_armed
                or self.col_armed or self.job_armed
                or any(self._rank_faults.values()))

    @property
    def step_armed(self) -> bool:
        """Any step-domain fault still pending."""
        return any(self._step_faults.values())

    @property
    def col_armed(self) -> bool:
        """Any column-domain fault still pending."""
        return any(self._col_faults.values())

    @property
    def job_armed(self) -> bool:
        """Any job-domain (service-boundary) fault still pending."""
        return any(self._job_faults.values())

    def next_step_fault(self, after: int) -> Optional[int]:
        """Smallest pending step-domain index > ``after``, or None — the
        time loop splits its device chunks there so the fault's timestep
        is an actual host boundary."""
        pending = [i for m in self._step_faults.values() for i in m
                   if i > after]
        return min(pending) if pending else None

    def _take(self, mode: str, idx: int) -> bool:
        pending = self._faults.get(mode, {})
        if pending.get(idx, 0) <= 0:
            return False
        pending[idx] -= 1
        if pending[idx] <= 0:
            del pending[idx]
        return True

    @staticmethod
    def _process_slot():
        """``(process_index, process_count)`` of an ALREADY-IMPORTED
        jax (never importing it here — faultinject stays import-light),
        defaulting to the single-process identity."""
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return 0, 1
        try:
            return int(jax.process_index()), int(jax.process_count())
        except Exception:                               # noqa: BLE001
            return 0, 1     # backend not initialized: single-process

    def _take_rank(self, mode: str, idx: int) -> bool:
        """Consume a pending rank-domain fault of ``mode`` at counter
        position ``idx`` aimed at THIS process; True when it fires
        here.  A rank at/past the process count cannot land — neither
        consumed nor recorded (cannot-land contract); a fault aimed at
        a DIFFERENT live rank stays pending on this process (its plan
        never fires it, but `armed` must keep every process's
        resilience context engaged for the collective snapshot/resume
        protocol)."""
        pending = self._rank_faults.get(mode, {})
        here, n_procs = self._process_slot()
        for rank, at in sorted(pending):
            if at != idx or pending[(rank, at)] <= 0:
                continue
            if rank >= n_procs or rank != here:
                continue
            pending[(rank, at)] -= 1
            if pending[(rank, at)] <= 0:
                del pending[(rank, at)]
            return True
        return False

    def _fire(self, mode: str, point: str, idx: int) -> None:
        self.fired.append({"mode": mode, "point": point, "at": idx})
        if self.recorder is not None:
            self.recorder.event("fault", mode=mode, point=point, at=idx)

    # -- engine hooks ---------------------------------------------------
    def on_dispatch(self) -> None:
        """Called immediately before a Krylov dispatch.  May raise
        :class:`InjectedDispatchError` (the count is consumed, so a
        guarded retry of the same dispatch succeeds unless the spec asked
        for consecutive failures with ``*count``)."""
        idx = self.dispatches
        if self._take("exc", idx):
            self._fire("exc", "dispatch", idx)
            raise InjectedDispatchError(
                f"injected device loss before dispatch {idx} "
                "(PCG_TPU_FAULTS)")
        if self._take_rank("exc", idx):
            self._fire("exc", "rank-dispatch", idx)
            raise InjectedDispatchError(
                f"injected device loss before dispatch {idx} on this "
                "process (PCG_TPU_FAULTS rank domain)")

    def on_dispatch_done(self) -> None:
        """Called after a dispatch completes successfully."""
        self.dispatches += 1

    def at_boundary(self, carry: dict, blocked: bool = False) -> dict:
        """Called at a chunk boundary AFTER any snapshot was taken (the
        snapshot must hold the clean state — corruption happens to the
        live carry, as it would on real hardware).  Returns the
        (possibly poisoned) carry; may raise :class:`SimulatedKill`.

        ``blocked`` marks a blocked multi-RHS boundary: pending
        column-domain faults (``mode@col:k``) then fire too, poisoning
        ONLY column ``k`` of the blocked carry (nan/inf on the column's
        residual, rho0 on the column's rho) — every other column's
        leaves stay bitwise untouched.

        A poison mode whose target leaf is absent from this path's carry
        (``rho0`` needs ``rho`` — the mixed outer state has none) is NOT
        consumed and NOT recorded as fired: a chaos drill must never
        read "recovery path exercised" off an injection that could not
        land."""
        idx = self.boundaries
        self.boundaries += 1
        if self._take("sleep", idx):
            # host-side straggler delay: fires BEFORE any poison/kill at
            # this boundary — a delayed process still runs its chunk, it
            # just arrives late at the next collective
            self._fire("sleep", "boundary", idx)
            time.sleep(self.sleep_s)
        if self._take_rank("sleep", idx):
            self._fire("sleep", "rank-boundary", idx)
            time.sleep(self.sleep_s)
        for mode, leaf in (("nan", "r"), ("inf", "r"), ("rho0", "rho")):
            if leaf in carry and self._take(mode, idx):
                self._fire(mode, "boundary", idx)
                carry = _poison(carry, mode)
            if leaf in carry and self._take_rank(mode, idx):
                self._fire(mode, "rank-boundary", idx)
                carry = _poison(carry, mode)
        if blocked:
            # block width from the carry itself: a column fault aimed
            # past the actual width cannot land — like the absent-leaf
            # case above it must be neither consumed nor recorded
            r, rho = carry.get("r"), carry.get("rho")
            width = (r.shape[-1] if getattr(r, "ndim", 0) == 3
                     else rho.shape[0]
                     if getattr(rho, "ndim", 0) == 1 else 0)
            for mode, leaf in (("nan", "r"), ("inf", "r"),
                               ("rho0", "rho")):
                pend = self._col_faults.get(mode, {})
                for col in sorted(pend):
                    if col < width and leaf in carry \
                            and self._take_col(mode, col):
                        self._fire(mode, "col", col)
                        carry = _poison_col(carry, mode, col, leaf)
        if self._take("kill", idx):
            self._fire("kill", "boundary", idx)
            raise SimulatedKill(
                f"injected kill at chunk boundary {idx} (PCG_TPU_FAULTS)")
        if self._take_rank("kill", idx):
            self._fire("kill", "rank-boundary", idx)
            raise SimulatedKill(
                f"injected kill at chunk boundary {idx} on this process "
                "(PCG_TPU_FAULTS rank domain)")
        return carry

    def _take_col(self, mode: str, col: int) -> bool:
        pending = self._col_faults.get(mode, {})
        if pending.get(col, 0) <= 0:
            return False
        pending[col] -= 1
        if pending[col] <= 0:
            del pending[col]
        return True

    def _take_job(self, mode: str, job: int) -> bool:
        pending = self._job_faults.get(mode, {})
        if pending.get(job, 0) <= 0:
            return False
        pending[job] -= 1
        if pending[job] <= 0:
            del pending[job]
        return True

    def at_job(self, ordinal: int) -> Optional[str]:
        """Called by the solve service at the SERVICE BOUNDARY — the
        daemon is about to dispatch the block containing the job with
        ABSOLUTE admission ordinal ``ordinal`` (serve/daemon.py).
        Fires in straggler-first order, like :meth:`at_boundary`:
        ``sleep`` delays the host (the whole block arrives late — the
        deterministic window the SIGKILL chaos test fires inside), then
        ``nan`` returns ``"nan"`` asking the caller to poison THAT
        job's RHS column, then ``exc`` raises
        :class:`InjectedDispatchError` (the job fails with a named
        verdict while its co-batched tenants dispatch unharmed).  A job
        ordinal never admitted simply never reaches this hook —
        the cannot-land contract needs no width check here."""
        poison = None
        if self._take_job("sleep", ordinal):
            self._fire("sleep", "job", ordinal)
            time.sleep(self.sleep_s)
        if self._take_job("nan", ordinal):
            self._fire("nan", "job", ordinal)
            poison = "nan"
        if self._take_job("exc", ordinal):
            self._fire("exc", "job", ordinal)
            raise InjectedDispatchError(
                f"injected service-boundary failure for job ordinal "
                f"{ordinal} (PCG_TPU_FAULTS job domain)")
        return poison

    def replay_consume_job(self, ordinal: int) -> None:
        """Journal-replay pre-consumption: drop every pending job-domain
        fault aimed at ``ordinal`` WITHOUT firing or recording it.  A
        restarted daemon re-parses ``PCG_TPU_FAULTS`` into a fresh plan,
        but the journal proves ordinal ``ordinal`` already passed the
        service boundary (a ``dispatched`` or terminal record) — its
        fault was consumed by the dead process, and the absolute-
        indexing contract (step-domain precedent) says replay must
        never re-fire it."""
        for pending in self._job_faults.values():
            pending.pop(ordinal, None)

    def _take_step(self, mode: str, t: int) -> bool:
        pending = self._step_faults.get(mode, {})
        if pending.get(t, 0) <= 0:
            return False
        pending[t] -= 1
        if pending[t] <= 0:
            del pending[t]
        return True

    def at_step(self, t: int, state: dict) -> dict:
        """Called after completed timestep ``t`` of a time history,
        AFTER any due step snapshot (the snapshot must hold the clean
        state — corruption happens to the live run, as it would on real
        hardware).  Poison lands on the kinematic leaf ``u``; ``kill``
        raises :class:`SimulatedKill` last, so a poison+kill at the same
        step persists the poison-free snapshot first.  Indexed by the
        ABSOLUTE timestep number: a rollback or resume that replays past
        ``t`` does not re-fire a consumed fault."""
        for mode in ("nan", "inf"):
            if "u" in state and self._take_step(mode, t):
                self._fire(mode, "step", t)
                state = _poison(state, mode, leaf="u")
        if self._take_step("kill", t):
            self._fire("kill", "step", t)
            raise SimulatedKill(
                f"injected kill at timestep {t} (PCG_TPU_FAULTS)")
        return state


def _poison(carry: dict, mode: str, leaf: str = "r") -> dict:
    """Corrupt a device-resident carry dict (new leaves, never in-place:
    the donated-carry contract means the input dict's leaves may be the
    fresh outputs of the previous dispatch — poisoning builds replacement
    arrays and leaves the originals to the garbage collector).  ``leaf``
    is the poison target: the Krylov residual ``r`` at chunk boundaries,
    the kinematic state ``u`` at timestep boundaries."""
    import jax.numpy as jnp

    out = dict(carry)
    if mode == "rho0":
        if "rho" in out:
            out["rho"] = jnp.zeros_like(out["rho"])
        return out
    r = out.get(leaf)
    if r is None:
        return out
    if mode == "nan":
        out[leaf] = r * jnp.asarray(float("nan"), r.dtype)
    elif mode == "inf":
        # only the nonzero entries: constrained dofs stay exactly 0, so
        # the Inf lands where the preconditioner inverse is > 0 and the
        # next apply_prec trips the flag-2 Inf-preconditioner exit
        out[leaf] = jnp.where(r != 0, jnp.asarray(float("inf"), r.dtype),
                              r)
    return out


def _poison_col(carry: dict, mode: str, col: int, leaf: str) -> dict:
    """Column-domain poisoner for a blocked multi-RHS carry: corrupt
    ONLY column ``col`` (trailing RHS axis of the (P, n_loc, R) vectors,
    index ``col`` of the (R,) scalars).  Built from ``jnp.where`` column
    selects so every other column's values stay bitwise identical — the
    fault-isolation tests compare them bit for bit.  Same new-leaves
    discipline as :func:`_poison` (donated-carry contract)."""
    import jax.numpy as jnp

    out = dict(carry)
    if mode == "rho0":
        rho = out.get("rho")
        if rho is not None and getattr(rho, "ndim", 0) == 1:
            mask = jnp.arange(rho.shape[0]) == col
            out["rho"] = jnp.where(mask, jnp.zeros((), rho.dtype), rho)
        return out
    r = out.get(leaf)
    if r is None or getattr(r, "ndim", 0) != 3:
        return out
    mask = (jnp.arange(r.shape[-1]) == col)[None, None, :]
    if mode == "nan":
        out[leaf] = jnp.where(mask, r * jnp.asarray(float("nan"),
                                                    r.dtype), r)
    elif mode == "inf":
        out[leaf] = jnp.where(mask & (r != 0),
                              jnp.asarray(float("inf"), r.dtype), r)
    return out
