"""Recovery policy: dispatch guard, breakdown ladder, and the per-step
resilience context threaded through the chunked budget loop.

Division of labor (one failure taxonomy, three handlers):

* **Device loss** (XLA runtime errors, dropped tunnels, injected
  ``exc`` faults): the :class:`DispatchGuard` retries the dispatch with
  backoff, re-dispatching from the last mid-Krylov snapshot — losing at
  most one snapshot interval of iterations instead of the whole step.
  With no snapshot in memory (or the retry budget spent) the exception
  propagates to the driver, whose ladder restarts the step from its
  start state (the ``device_loss`` trigger).
* **In-graph breakdown** (flag 2 Inf-preconditioner, flag 4 rho/pq —
  ``solver/pcg.py`` BREAKDOWN_FLAGS) and **NaN/Inf carry** (silent
  corruption no MATLAB flag catches): the driver-level
  :class:`RecoveryLadder` restarts from the tracked min-residual
  iterate through a bounded escalation — plain restart -> scalar-Jacobi
  fallback preconditioner -> f64 escalation (mixed mode) — each attempt
  an ``obs/metrics`` ``recovery`` event.
* **Process death** (SIGKILL, preemption, injected ``kill`` faults):
  nothing in-process — the next run's ``--resume`` restores the last
  mid-Krylov snapshot (``utils/checkpoint.SnapshotStore``) and
  continues bit-identically.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from pcg_mpi_solver_tpu.resilience.faultinject import (
    FaultPlan, InjectedDispatchError)

# Exception type names that indicate the DEVICE (not the math) failed —
# matched by name so no jaxlib import is needed at module load, and the
# set survives jax moving its error types between releases.
_DEVICE_ERROR_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "InternalError",
    "UnavailableError", "FailedPreconditionError", "AbortedError",
})
_DEVICE_ERROR_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "INTERNAL:",
                         "ABORTED", "device loss", "Device loss")


def is_device_loss(exc: BaseException) -> bool:
    """Does this exception mean the device/dispatch died (retryable),
    rather than the computation being wrong (not retryable)?"""
    if isinstance(exc, InjectedDispatchError):
        return True
    if type(exc).__name__ in _DEVICE_ERROR_NAMES:
        return True
    msg = str(exc)
    return any(m in msg for m in _DEVICE_ERROR_MARKERS)


def breakdown_trigger(flag: int, relres: float) -> Optional[str]:
    """Classify a terminal chunked-solve outcome into a ladder trigger
    (None = no recovery warranted: converged, budget, or stagnation)."""
    from pcg_mpi_solver_tpu.solver.pcg import BREAKDOWN_FLAGS

    if not math.isfinite(relres):
        return "nan_carry"
    if flag in BREAKDOWN_FLAGS:
        return f"flag{flag}"
    return None


def column_trigger(flag: int, normr: float) -> Optional[str]:
    """Per-column ladder trigger of a blocked multi-RHS carry
    (:func:`resilience.engine.run_many_with_recovery`): the blocked
    twin of :func:`breakdown_trigger`, reading the column's carry flag
    and carry residual norm.  A flag-1 (still running) column with a
    non-finite norm is the NaN-carry case — no MATLAB flag ever trips
    on NaN, so the host must intervene before the column burns the
    whole lockstep budget on poison."""
    from pcg_mpi_solver_tpu.solver.pcg import BREAKDOWN_FLAGS

    if flag in BREAKDOWN_FLAGS:
        return f"flag{flag}"
    if flag == 1 and not math.isfinite(normr):
        return "nan_carry"
    return None


def retry_deadline_s() -> Optional[float]:
    """Optional wall clamp on retry storms (``PCG_TPU_RETRY_DEADLINE_S``
    seconds, env-only): a scarce hardware window must not be eaten by
    backoff loops.  A malformed value must not kill the solve the knob
    protects — it disables the deadline with a warning instead."""
    raw = os.environ.get("PCG_TPU_RETRY_DEADLINE_S", "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        import warnings

        warnings.warn(f"PCG_TPU_RETRY_DEADLINE_S={raw!r} is not a "
                      "number; retry deadline disabled")
        return None


class DispatchGuard:
    """Retry-with-backoff + deadline budget for device dispatches.

    One instance per solve step: the retry budget is a per-step total
    (a flapping tunnel must not retry forever), the deadline an absolute
    wall clamp.  Backoff is exponential from
    ``PCG_TPU_RETRY_BACKOFF_S`` (default 0.5 s; tests set it near 0).
    """

    def __init__(self, retries: int = 2, deadline_s: Optional[float] = None,
                 recorder=None):
        self.retries = int(retries)
        self.failures = 0
        self.recorder = recorder
        self._deadline = (time.monotonic() + deadline_s
                          if deadline_s else None)
        self._backoff0 = float(os.environ.get("PCG_TPU_RETRY_BACKOFF_S",
                                              "0.5"))

    def should_retry(self, exc: BaseException) -> bool:
        """Account one dispatch failure; True when a retry is allowed
        (device-loss shaped, budget left, deadline not passed)."""
        if not is_device_loss(exc):
            return False
        self.failures += 1
        if self.failures > self.retries:
            return False
        if self._deadline is not None and time.monotonic() > self._deadline:
            return False
        return True

    def backoff(self) -> None:
        time.sleep(min(self._backoff0 * (2 ** (self.failures - 1)), 30.0))


class RecoveryLadder:
    """Bounded escalation ladder for breakdown/NaN/device-loss triggers.

    Rung order (ISSUE 3 / arXiv:2501.03743's recoverable-breakdown
    posture): restart from the min-residual iterate -> same restart with
    the scalar-Jacobi fallback preconditioner (when the configured one
    is stronger, ``ops/precond.fallback_kind``) -> f64 escalation (mixed
    mode: finish the solve with direct f64 Krylov cycles).  Attempts
    past the last applicable rung repeat it; ``max_recoveries`` bounds
    the total.
    """

    def __init__(self, *, precond: str, mixed: bool, max_recoveries: int,
                 recorder=None, extra: Optional[Dict[str, Any]] = None):
        from pcg_mpi_solver_tpu.ops.precond import fallback_kind

        self.max_recoveries = int(max_recoveries)
        self.attempt = 0
        self.recorder = recorder
        # extra fields stamped on every `recovery` event this ladder
        # emits (the per-column ladders of a blocked solve tag theirs
        # with the column index: extra={"rhs": k})
        self.extra = dict(extra or {})
        self.actions_taken: List[str] = []
        rungs = ["restart_minres"]
        if fallback_kind(precond) is not None:
            rungs.append("fallback_prec")
        if mixed:
            rungs.append("escalate_f64")
        self._rungs = rungs

    @property
    def exhausted(self) -> bool:
        return self.attempt >= self.max_recoveries

    def next_action(self, trigger: str) -> Optional[str]:
        """Consume one attempt; returns the rung action (None when the
        budget is spent) and records the ``recovery`` telemetry event
        that makes every attempt visible in the JSONL stream."""
        if self.exhausted:
            return None
        self.attempt += 1
        action = self._rungs[min(self.attempt - 1, len(self._rungs) - 1)]
        self.actions_taken.append(action)
        if self.recorder is not None:
            self.recorder.event("recovery", action=action,
                                attempt=self.attempt, trigger=trigger,
                                **self.extra)
            self.recorder.inc(f"resilience.recovery.{action}")
        return action


class ResilienceContext:
    """Everything the chunked budget loop needs per solve step: the
    mid-Krylov snapshot cadence (disk via ``SnapshotStore`` + the
    in-memory restore point the dispatch guard re-dispatches from), the
    guard itself, and the optional fault plan.

    ``fetch_state`` / ``put_state`` are driver-supplied closures mapping
    a device pytree to host numpy and back (sharding-aware) — the
    context itself stays jax-free.
    """

    def __init__(self, *, store=None, step: int = 0, snapshot_every: int = 0,
                 fetch_state: Callable[[Any], Any] = None,
                 put_state: Callable[[Any], Any] = None,
                 guard: Optional[DispatchGuard] = None,
                 faults: Optional[FaultPlan] = None,
                 recorder=None, resume: bool = False,
                 ladder_armed: bool = False, comm=None):
        self.store = store
        self.step = int(step)
        self.snapshot_every = int(snapshot_every)
        self.fetch_state = fetch_state
        self.put_state = put_state
        self.guard = guard
        self.faults = faults
        self.recorder = recorder
        # host-collective group of a multi-process run
        # (resilience.distributed.GuardedComm; watchdog armed only when
        # PCG_TPU_COLLECTIVE_DEADLINE_S is set), or None single-process:
        # drives the chunk-boundary liveness sync and the consensus
        # agreements of the recovery engine
        self.comm = comm
        # whether the driver will actually consume engine.restart_x — the
        # engine skips the per-cycle restart-iterate copy otherwise
        self.ladder_armed = bool(ladder_armed)
        self._allow_resume = bool(resume)
        self._mem: Optional[Dict[str, Any]] = None   # last good host state
        self._since_snapshot = 0

    # -- group liveness -------------------------------------------------
    def sync_boundary(self) -> None:
        """Chunk-boundary liveness probe of a multi-process run: one
        tiny deadline-guarded collective at the TOP of each chunk
        iteration, OUTSIDE the dispatch try/except — a dead peer
        surfaces as a named DeadPeerError in bounded time (never an
        infinite psum hang, never a dispatch-guard retry), before any
        device work of the next chunk is enqueued.  No-op without a
        multi-process comm."""
        comm = self.comm
        if comm is None or getattr(comm, "n_procs", 1) <= 1:
            return
        if hasattr(comm, "barrier"):
            comm.barrier("chunk_boundary")
        else:
            comm.allreduce(np.ones(1, dtype=np.int64), "min")

    # -- snapshots ------------------------------------------------------
    def load_resume_state(self) -> Optional[Dict[str, Any]]:
        """The persisted mid-step state to resume from, or None.  Only
        honored when the caller asked for --resume (a FRESH solve must
        never silently continue a stale snapshot from a previous
        generation of the same run directory)."""
        if not (self._allow_resume and self.store is not None):
            return None
        self._allow_resume = False
        state = self.store.load(self.step)
        if state is None:
            return None
        self._mem = state           # also the guard's restore point
        if self.recorder is not None:
            self.recorder.event("snapshot", op="restore", step=self.step,
                                chunk=int(state.get("chunk", -1)))
        return state

    def after_chunk(self, state_fn: Callable[[], Dict[str, Any]]) -> None:
        """Chunk-boundary hook: every ``snapshot_every`` completed chunks,
        fetch the resumable state to host (``state_fn`` builds the device
        pytree lazily — with snapshots off this costs nothing), keep it
        as the guard's restore point, and persist it atomically."""
        if self.snapshot_every <= 0:
            return
        self._since_snapshot += 1
        if self._since_snapshot < self.snapshot_every:
            return
        self._since_snapshot = 0
        state = state_fn()
        state = self.fetch_state(state) if self.fetch_state else state
        self._mem = state
        if self.store is not None:
            self.store.save(self.step, state)
            if self.recorder is not None:
                self.recorder.event("snapshot", op="save", step=self.step,
                                    chunk=int(state.get("chunk", -1)))

    def discard(self) -> None:
        """Drop the step's snapshot (the step completed — the record
        must not outlive the state it describes)."""
        self._mem = None
        if self.store is not None:
            self.store.discard(self.step)

    # -- dispatch guard -------------------------------------------------
    def handle_dispatch_failure(self, exc: BaseException,
                                kind: Optional[str] = None) \
            -> Optional[Dict[str, Any]]:
        """Guard decision for a failed dispatch: the host state to
        re-dispatch from (after backoff), or None to propagate.  Needs
        BOTH a retry budget and an in-memory restore point — without a
        snapshot there is nothing safe to re-dispatch (the donated carry
        may be gone), so the driver-level ladder handles it instead.
        ``kind`` (``"direct"``/``"mixed"``) rejects a restore point of
        the wrong schema (e.g. one predating an escalation switch)
        WITHOUT consuming a retry."""
        if self.guard is None or self._mem is None:
            return None
        if kind is not None and str(
                np.asarray(self._mem.get("kind", ""))) != kind:
            return None
        if not self.guard.should_retry(exc):
            return None
        if self.recorder is not None:
            self.recorder.event(
                "recovery", action="redispatch",
                attempt=self.guard.failures, trigger="device_loss",
                error=f"{type(exc).__name__}: {exc}")
            self.recorder.inc("resilience.recovery.redispatch")
        self.guard.backoff()
        return self._mem

    def restore_device(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Host snapshot state -> device pytree (sharding-faithful)."""
        return self.put_state(state) if self.put_state else state
