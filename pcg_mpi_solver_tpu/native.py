"""ctypes binding for the native (C++) runtime library.

The reference depends on external native code for its offline prep: METIS via
mgmetis for dual-graph mesh partitioning (reference: src/solver/run_metis.py:
84-88) and wished-for Cython element loops (partition_mesh.py:244,271,280).
This framework ships its own native layer (``native/src/*.cpp``), built into
``pcg_mpi_solver_tpu/_libpcgnative.so`` and loaded here lazily.  Every entry
point has a pure-numpy fallback, so the package works without a compiler; the
native path is used automatically when the library is present or buildable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_LIB_NAME = "_libpcgnative.so"
_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_PKG_DIR, _LIB_NAME)
_NATIVE_DIR = os.path.join(os.path.dirname(_PKG_DIR), "native")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def build(force: bool = False) -> bool:
    """Compile the native library with make (g++).  Returns success.

    Always runs make when the source tree is present — make's own
    dependency tracking makes this a cheap no-op when the .so is current,
    and it keeps edited native/src/*.cpp from being silently ignored.
    """
    if os.environ.get("PCG_TPU_NO_NATIVE"):
        return False
    if not os.path.isdir(_NATIVE_DIR):
        return os.path.exists(_LIB_PATH)
    try:
        res = subprocess.run(
            ["make", "-s"] + (["-B"] if force else []),
            cwd=_NATIVE_DIR, capture_output=True, text=True, timeout=300)
        if res.returncode != 0:
            return False
    except (OSError, subprocess.TimeoutExpired):
        return False
    return os.path.exists(_LIB_PATH)


def _declare(lib: ctypes.CDLL) -> None:
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    lib.pcgn_part_graph.restype = ctypes.c_int
    lib.pcgn_part_graph.argtypes = [
        ctypes.c_int64, i64p, i64p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_uint64, i32p]
    lib.pcgn_part_mesh_dual.restype = ctypes.c_int
    lib.pcgn_part_mesh_dual.argtypes = [
        ctypes.c_int64, ctypes.c_int64, i64p, i64p,
        ctypes.c_int, ctypes.c_int, ctypes.c_uint64, i32p]
    lib.pcgn_edge_cut.restype = ctypes.c_int64
    lib.pcgn_edge_cut.argtypes = [ctypes.c_int64, i64p, i64p, ctypes.c_void_p, i32p]
    lib.pcgn_csr_take.restype = ctypes.c_int64
    lib.pcgn_csr_take.argtypes = [i64p, i64p, i64p, ctypes.c_int64, i64p]
    lib.pcgn_unique_renumber.restype = ctypes.c_int64
    lib.pcgn_unique_renumber.argtypes = [i64p, ctypes.c_int64, i64p,
                                         ctypes.c_void_p]  # loc nullable
    lib.pcgn_sort_i32.restype = None
    lib.pcgn_sort_i32.argtypes = [i32p, ctypes.c_int64, i32p, i32p]


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_attempted
    if _lib is not None:
        return _lib
    if _load_attempted:
        return None
    _load_attempted = True
    if os.environ.get("PCG_TPU_NO_NATIVE"):
        return None
    if not build():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        _declare(lib)
        _lib = lib
    except OSError:
        return None
    return _lib


def available() -> bool:
    return load() is not None


# ---------------------------------------------------------------------------
# Partitioning entry points
# ---------------------------------------------------------------------------

def part_mesh_dual(eptr: np.ndarray, eind: np.ndarray, n_node: int,
                   n_parts: int, ncommon: int = 1,
                   seed: int = 0) -> Optional[np.ndarray]:
    """Partition a mesh by its dual graph (elements sharing >= ncommon nodes
    are adjacent) — the call shape of the reference's METIS use
    (run_metis.py:88).  Returns an (n_elem,) int32 part map, or None when the
    native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    eptr = np.ascontiguousarray(eptr, dtype=np.int64)
    eind = np.ascontiguousarray(eind, dtype=np.int64)
    n_elem = len(eptr) - 1
    part = np.empty(n_elem, dtype=np.int32)
    rc = lib.pcgn_part_mesh_dual(n_elem, int(n_node), eptr, eind,
                                 int(ncommon), int(n_parts), int(seed), part)
    if rc != 0:
        return None
    return part


def part_graph(xadj: np.ndarray, adjncy: np.ndarray, n_parts: int,
               adjwgt: Optional[np.ndarray] = None,
               vwgt: Optional[np.ndarray] = None,
               seed: int = 0) -> Optional[np.ndarray]:
    """k-way partition of a CSR graph; None when native lib unavailable."""
    lib = load()
    if lib is None:
        return None
    xadj = np.ascontiguousarray(xadj, dtype=np.int64)
    adjncy = np.ascontiguousarray(adjncy, dtype=np.int64)
    n = len(xadj) - 1
    part = np.empty(n, dtype=np.int32)
    # Keep converted arrays alive in locals for the duration of the C call
    # (.ctypes.data of an unnamed temporary would dangle).
    aw_arr = (np.ascontiguousarray(adjwgt, dtype=np.int64)
              if adjwgt is not None else None)
    vw_arr = (np.ascontiguousarray(vwgt, dtype=np.int64)
              if vwgt is not None else None)
    rc = lib.pcgn_part_graph(n, xadj, adjncy,
                             aw_arr.ctypes.data if aw_arr is not None else None,
                             vw_arr.ctypes.data if vw_arr is not None else None,
                             int(n_parts), int(seed), part)
    if rc != 0:
        return None
    return part


def edge_cut(xadj: np.ndarray, adjncy: np.ndarray, part: np.ndarray) -> int:
    """Edge cut of a partition (unit edge weights).  Numpy fallback."""
    lib = load()
    xadj = np.ascontiguousarray(xadj, dtype=np.int64)
    adjncy = np.ascontiguousarray(adjncy, dtype=np.int64)
    part = np.ascontiguousarray(part, dtype=np.int32)
    if lib is not None:
        return int(lib.pcgn_edge_cut(len(xadj) - 1, xadj, adjncy, None, part))
    src = np.repeat(np.arange(len(xadj) - 1), np.diff(xadj))
    return int((part[src] != part[adjncy]).sum() // 2)


_PREP_THRESHOLD = 4096  # below this, numpy's C loops win on call overhead


def csr_take(flat: np.ndarray, offset: np.ndarray,
             elems: np.ndarray) -> Optional[np.ndarray]:
    """Ragged gather flat[offset[e]:offset[e+1]] for e in elems; None when
    the native library is unavailable (caller falls back to numpy)."""
    lib = load()
    if lib is None or len(elems) < _PREP_THRESHOLD:
        return None
    orig_dtype = np.asarray(flat).dtype
    flat = np.ascontiguousarray(flat, dtype=np.int64)
    offset = np.ascontiguousarray(offset, dtype=np.int64)
    elems = np.ascontiguousarray(elems, dtype=np.int64)
    total = int((offset[elems + 1] - offset[elems]).sum())
    out = np.empty(total, dtype=np.int64)
    lib.pcgn_csr_take(flat, offset, elems, len(elems), out)
    # Preserve the caller's dtype — a bool mask must stay a bool mask.
    return out if orig_dtype == np.int64 else out.astype(orig_dtype)


def unique_renumber(ids: np.ndarray, renumber: bool = True):
    """(sorted unique ids, int32 local index of each input id); None when
    the native library is unavailable.  With ``renumber=False`` the second
    element is None and the renumbering pass is skipped."""
    lib = load()
    if lib is None or len(ids) < _PREP_THRESHOLD:
        return None
    ids = np.ascontiguousarray(ids, dtype=np.int64)
    uniq = np.empty(len(ids), dtype=np.int64)
    loc = np.empty(len(ids), dtype=np.int32) if renumber else None
    nu = lib.pcgn_unique_renumber(
        ids, len(ids), uniq, loc.ctypes.data if loc is not None else None)
    return uniq[:nu].copy(), loc


def sort_i32(keys: np.ndarray):
    """(stable argsort perm, sorted keys) of int32 keys; None when the
    native library is unavailable."""
    lib = load()
    if lib is None or len(keys) < _PREP_THRESHOLD:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int32)
    perm = np.empty(len(keys), dtype=np.int32)
    skeys = np.empty(len(keys), dtype=np.int32)
    lib.pcgn_sort_i32(keys, len(keys), perm, skeys)
    return perm, skeys


def build_dual_graph_np(eptr: np.ndarray, eind: np.ndarray, n_node: int,
                        ncommon: int = 1):
    """Pure-numpy dual-graph builder (fallback + test oracle): returns
    (xadj, adjncy) CSR of element adjacency."""
    n_elem = len(eptr) - 1
    src = np.repeat(np.arange(n_elem, dtype=np.int64), np.diff(eptr))
    order = np.argsort(eind, kind="stable")
    by_node_elem = src[order]
    node_cnt = np.bincount(eind, minlength=n_node)
    node_off = np.concatenate([[0], np.cumsum(node_cnt)])
    pairs = []
    for nd in range(n_node):
        es = by_node_elem[node_off[nd]:node_off[nd + 1]]
        if len(es) > 1:
            a, b = np.meshgrid(es, es, indexing="ij")
            m = a != b
            pairs.append(np.stack([a[m], b[m]], axis=1))
    if not pairs:
        return np.zeros(n_elem + 1, dtype=np.int64), np.zeros(0, dtype=np.int64)
    pr = np.concatenate(pairs)
    key = pr[:, 0] * n_elem + pr[:, 1]
    uniq, counts = np.unique(key, return_counts=True)
    keep = counts >= ncommon
    uniq = uniq[keep]
    a = uniq // n_elem
    b = uniq % n_elem
    xadj = np.concatenate([[0], np.cumsum(np.bincount(a, minlength=n_elem))]).astype(np.int64)
    return xadj, b.astype(np.int64)
