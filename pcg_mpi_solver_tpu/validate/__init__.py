"""Model/config preflight subsystem: reject pathological inputs BEFORE
any partition build or XLA compile is paid.

The flagship workloads burn minutes of partitioning and 100s-of-seconds
XLA compiles per solver construction; a ``ModelData`` with NaN loads, a
zero-volume element, or no Dirichlet constraint at all would happily
consume all of it and then fail (or worse, converge to garbage) deep in
the solve.  ``run_preflight`` is wired into ``Solver.__init__``, both
dynamics drivers, ``cli.py`` (the ``validate`` subcommand and
``--preflight=``) and ``bench.py``; the policy is
``PCG_TPU_PREFLIGHT=fail|warn|off`` (default ``fail``).

Import contract: jax-free at module load (numpy only), matching
``obs/`` and ``resilience/``.
"""

from pcg_mpi_solver_tpu.validate.preflight import (
    CheckResult, PreflightError, check_mg_interval, check_rhs_block,
    preflight_checks, resolve_policy, run_preflight)

__all__ = [
    "CheckResult",
    "PreflightError",
    "check_mg_interval",
    "check_rhs_block",
    "preflight_checks",
    "resolve_policy",
    "run_preflight",
]
