"""Preflight checks: ModelData sanity + config cross-checks.

Each check returns a :class:`CheckResult` with a severity the policy
acts on:

* ``fail`` — the input is unusable (NaN loads, zero-volume elements, a
  fully-unconstrained rigid-body system, a broken connectivity table):
  under the default ``fail`` policy construction raises
  :class:`PreflightError` before any partition build or compile.
* ``warn`` — the input is usable but suspicious (a tolerance below the
  precision mode's attainable floor, a snapshot cadence that never
  fires): recorded in the ``preflight`` telemetry event and surfaced by
  the ``validate`` CLI subcommand, never raised.
* ``ok`` — the check passed.

Policy (:func:`resolve_policy`): explicit argument > the caller's
``RunConfig.preflight`` > ``PCG_TPU_PREFLIGHT`` env > ``"fail"``.
``off`` skips the scans entirely (zero cost — the historical behavior).

Every check is O(model size) numpy; no jax, no partitioning.
"""

from __future__ import annotations

import dataclasses
import math
import os
import warnings
from typing import Any, Dict, List, Optional

import numpy as np

POLICIES = ("fail", "warn", "off")


class PreflightError(ValueError):
    """A fail-severity preflight check rejected the model/config."""


@dataclasses.dataclass
class CheckResult:
    name: str
    status: str            # "ok" | "warn" | "fail"
    detail: str = ""

    def to_event(self) -> dict:
        return {"name": self.name, "status": self.status,
                "detail": self.detail}


def resolve_policy(policy: Optional[str] = None) -> str:
    """The effective policy: argument > ``PCG_TPU_PREFLIGHT`` > fail.
    A malformed value must not silently disable the gate it configures."""
    p = (policy or "").strip() or \
        os.environ.get("PCG_TPU_PREFLIGHT", "").strip() or "fail"
    if p not in POLICIES:
        raise ValueError(f"preflight policy must be one of {POLICIES}, "
                         f"got {p!r} (PCG_TPU_PREFLIGHT / --preflight)")
    return p


# ----------------------------------------------------------------------
# Individual checks (each returns one CheckResult)
# ----------------------------------------------------------------------

def _finite(name: str, arrs: Dict[str, np.ndarray]) -> CheckResult:
    bad = []
    for label, a in arrs.items():
        a = np.asarray(a)
        if a.size and not np.isfinite(a).all():
            n = int(np.count_nonzero(~np.isfinite(a)))
            bad.append(f"{label} ({n} non-finite)")
    if bad:
        return CheckResult(name, "fail", "NaN/Inf in " + ", ".join(bad))
    return CheckResult(name, "ok")


def _check_shapes(model) -> CheckResult:
    n_dof, n_node, n_elem = model.n_dof, model.n_node, model.n_elem
    probs = []
    for label in ("F", "Ud", "Vd", "diag_M"):
        a = np.asarray(getattr(model, label))
        if a.shape != (n_dof,):
            probs.append(f"{label}.shape={a.shape} != ({n_dof},)")
        elif a.dtype.kind != "f":
            probs.append(f"{label}.dtype={a.dtype} is not floating")
    coords = np.asarray(model.node_coords)
    if coords.shape != (n_node, 3):
        probs.append(f"node_coords.shape={coords.shape} != ({n_node}, 3)")
    for label in ("elem_type", "ck", "cm", "ce", "level", "poly_mat"):
        a = np.asarray(getattr(model, label))
        if a.shape[:1] != (n_elem,):
            probs.append(f"{label}.shape={a.shape} != ({n_elem}, ...)")
    for label in ("fixed_dof", "dof_eff", "elem_dofs_flat"):
        if np.asarray(getattr(model, label)).dtype.kind not in "iu":
            probs.append(f"{label} is not integer-typed")
    if probs:
        return CheckResult("shapes_dtypes", "fail", "; ".join(probs))
    return CheckResult("shapes_dtypes", "ok")


def _check_connectivity(model) -> CheckResult:
    probs = []
    for flat_l, off_l in (("elem_dofs_flat", "elem_dofs_offset"),
                          ("elem_nodes_flat", "elem_nodes_offset")):
        flat = np.asarray(getattr(model, flat_l))
        off = np.asarray(getattr(model, off_l))
        if off.shape != (model.n_elem + 1,):
            probs.append(f"{off_l}.shape={off.shape} != "
                         f"({model.n_elem + 1},)")
            continue
        if off.size and (np.any(np.diff(off) < 0) or off[0] != 0
                         or off[-1] != flat.size):
            probs.append(f"{off_l} is not a monotone 0..len({flat_l}) "
                         "offset table")
    dofs = np.asarray(model.elem_dofs_flat)
    if dofs.size and (dofs.min() < 0 or dofs.max() >= model.n_dof):
        probs.append(f"elem_dofs_flat ids outside [0, {model.n_dof})")
    nodes = np.asarray(model.elem_nodes_flat)
    if nodes.size and (nodes.min() < 0 or nodes.max() >= model.n_node):
        probs.append(f"elem_nodes_flat ids outside [0, {model.n_node})")
    types = np.asarray(model.elem_type)
    known = set(int(t) for t in model.elem_lib)
    if types.size and not set(np.unique(types).tolist()) <= known:
        probs.append("elem_type references types missing from elem_lib")
    if probs:
        return CheckResult("connectivity", "fail", "; ".join(probs))
    return CheckResult("connectivity", "ok")


def _check_elements(model) -> CheckResult:
    level = np.asarray(model.level, dtype=float)
    ce = np.asarray(model.ce, dtype=float)
    ck = np.asarray(model.ck, dtype=float)
    n_degen = int(np.count_nonzero((level <= 0) | (ce <= 0)))
    if n_degen:
        return CheckResult(
            "element_volume", "fail",
            f"{n_degen} zero/negative-volume element(s) "
            "(level/ce <= 0)")
    n_neg = int(np.count_nonzero(ck < 0))
    if n_neg:
        return CheckResult("element_volume", "fail",
                           f"{n_neg} element(s) with negative stiffness "
                           "scale ck")
    n_zero = int(np.count_nonzero(ck == 0))
    if n_zero:
        return CheckResult("element_volume", "warn",
                           f"{n_zero} element(s) with zero stiffness "
                           "scale ck (contribute nothing to K)")
    return CheckResult("element_volume", "ok")


def _check_constraints(model) -> CheckResult:
    fixed = np.asarray(model.fixed_dof)
    if fixed.size == 0:
        return CheckResult(
            "constraints", "fail",
            "no Dirichlet-constrained dofs: the system is a fully-"
            "unconstrained rigid body (K is singular; PCG on it "
            "diverges or converges to an arbitrary translation)")
    if fixed.min() < 0 or fixed.max() >= model.n_dof:
        return CheckResult("constraints", "fail",
                           f"fixed_dof ids outside [0, {model.n_dof})")
    return CheckResult("constraints", "ok")


def _check_dof_partition(model) -> CheckResult:
    fixed = np.asarray(model.fixed_dof)
    eff = np.asarray(model.dof_eff)
    if np.intersect1d(fixed, eff).size:
        return CheckResult("dof_partition", "fail",
                           "fixed_dof and dof_eff overlap")
    if fixed.size + eff.size != model.n_dof or \
            np.union1d(fixed, eff).size != model.n_dof:
        return CheckResult(
            "dof_partition", "fail",
            f"fixed_dof ({fixed.size}) + dof_eff ({eff.size}) do not "
            f"partition the {model.n_dof} dofs")
    return CheckResult("dof_partition", "ok")


def _check_materials(model) -> CheckResult:
    probs = []
    for i, m in enumerate(model.mat_prop or []):
        for key in ("E", "Pos", "Rho"):
            if key in m:
                v = float(m[key])
                if not math.isfinite(v):
                    probs.append(f"mat_prop[{i}].{key} non-finite")
        if "E" in m and float(m["E"]) <= 0:
            probs.append(f"mat_prop[{i}].E <= 0")
        if "Rho" in m and float(m["Rho"]) < 0:
            probs.append(f"mat_prop[{i}].Rho < 0")
    if probs:
        return CheckResult("materials", "fail", "; ".join(probs))
    return CheckResult("materials", "ok")


def _check_solver_params(scfg) -> CheckResult:
    probs = []
    if not (math.isfinite(scfg.tol) and scfg.tol > 0):
        probs.append(f"tol={scfg.tol} must be a finite positive number")
    if scfg.max_iter < 1:
        probs.append(f"max_iter={scfg.max_iter} must be >= 1")
    if int(getattr(scfg, "nrhs", 1)) < 1:
        probs.append(f"nrhs={scfg.nrhs} must be >= 1")
    if probs:
        return CheckResult("solver_params", "fail", "; ".join(probs))
    return CheckResult("solver_params", "ok")


def _check_tol_floor(scfg) -> CheckResult:
    """Mixed-precision / f32 tolerance floor: a tol the precision mode
    cannot reach grinds the full iteration budget every step."""
    if scfg.precision_mode == "mixed" and scfg.tol < 1e-13:
        return CheckResult(
            "tol_floor", "warn",
            f"tol={scfg.tol:.1e} is below the mixed-precision refinement "
            "floor (~1e-13 relative); the solve will burn max_iter "
            "without converging")
    if scfg.precision_mode == "direct" and \
            str(scfg.dtype) == "float32" and scfg.tol < 1e-6:
        return CheckResult(
            "tol_floor", "warn",
            f"tol={scfg.tol:.1e} with direct float32 storage is below "
            "the f32 residual floor (~1e-6 relative)")
    return CheckResult("tol_floor", "ok")


def _check_snapshot_cadence(config, context) -> CheckResult:
    """``n_steps`` is only meaningful on paths where snapshot_every
    counts TIMESTEPS (dynamics/Newmark); the quasi-static driver counts
    chunk boundaries and must not put n_steps in its context."""
    every = int(getattr(config, "snapshot_every", 0))
    if every < 0:
        return CheckResult("snapshot_cadence", "fail",
                           f"snapshot_every={every} must be >= 0")
    n_steps = (context or {}).get("n_steps")
    if every > 0 and n_steps is not None and every > int(n_steps):
        return CheckResult(
            "snapshot_cadence", "warn",
            f"snapshot_every={every} exceeds the {n_steps}-step "
            "schedule: no snapshot will ever be written")
    return CheckResult("snapshot_cadence", "ok")


def _check_explicit_dt(model, context) -> CheckResult:
    """Explicit central-difference stability: dt against the CFL
    estimate (solver/dynamics.stable_dt with safety=1).  Severity keys
    off ``dt_source``: an EXPLICIT caller dt above the bound is a
    fail-class config error; a dt inherited from a model file is only
    warned about (legacy MDF bundles carry dt=1.0 placeholders); the
    CFL default is the estimate itself and always passes."""
    ctx = context or {}
    dt = ctx.get("dt")
    src = ctx.get("dt_source", "arg")
    if dt is None or src == "cfl":
        return CheckResult("explicit_dt", "ok")
    if not (math.isfinite(dt) and dt > 0):
        return CheckResult("explicit_dt", "fail",
                           f"explicit dt={dt} must be a finite positive "
                           "number")
    from pcg_mpi_solver_tpu.solver.dynamics import stable_dt

    try:
        bound = stable_dt(model, safety=1.0)
    except (ValueError, ZeroDivisionError, KeyError) as e:
        return CheckResult("explicit_dt", "warn",
                           f"stable_dt estimate unavailable "
                           f"({type(e).__name__}: {e})")
    if not (math.isfinite(bound) and bound > 0):
        return CheckResult("explicit_dt", "warn",
                           f"stable_dt estimate non-finite ({bound})")
    if dt > bound:
        severity = "fail" if src == "arg" else "warn"
        return CheckResult(
            "explicit_dt", severity,
            f"dt={dt:.3e} ({src}) exceeds the CFL stability estimate "
            f"{bound:.3e}: the integration diverges within a few steps")
    if dt > 0.95 * bound:
        return CheckResult(
            "explicit_dt", "warn",
            f"dt={dt:.3e} is within 5% of the CFL estimate "
            f"{bound:.3e} (the estimate is conservative for hexes but "
            "not exact)")
    return CheckResult("explicit_dt", "ok")


def _check_mg_hierarchy(model, scfg) -> CheckResult:
    """precond='mg' eligibility (ISSUE 10): the model must expose a
    coarsenable cell lattice BEFORE the partition build / minutes-scale
    compile is paid — a non-power-of-two structured lattice, a scalar
    (Poisson-class) model, or a model with no lattice metadata at all
    would otherwise die mid-setup with a shape error.  Mirrors the named
    reasons ``ops/mg.build_mg_host`` raises."""
    if getattr(scfg, "precond", "jacobi") != "mg":
        return CheckResult("mg_hierarchy", "ok")
    if int(model.n_dof) != 3 * int(model.n_node):
        return CheckResult(
            "mg_hierarchy", "fail",
            "precond='mg' needs the vector (3-dof/node) problem class; "
            f"this model has n_dof={model.n_dof}, n_node={model.n_node}")
    from pcg_mpi_solver_tpu.ops.mg import (
        MGSetupError, fine_lattice, plan_levels)

    dims, _lat = fine_lattice(model)
    if dims is None:
        return CheckResult(
            "mg_hierarchy", "fail",
            "precond='mg' needs lattice metadata (ModelData.grid or "
            ".octree); this model has neither — use precond='jacobi'")
    try:
        plan_levels(dims, int(getattr(scfg, "mg_levels", 0)))
    except MGSetupError as e:
        return CheckResult("mg_hierarchy", "fail", str(e))
    return CheckResult("mg_hierarchy", "ok")


def _check_mg_replication(model, scfg) -> CheckResult:
    """MG replication scale audit (ISSUE 14): every coarse level is
    REPLICATED on every device (PR 9's zero-collective-coarse-cycle
    design), so the planned hierarchy's replicated dof total must fit
    the ``SolverConfig.mg_max_replicated_dofs`` cutoff.  Mirrors the
    named reasons ``ops/mg.apply_replication_cutoff`` raises — here the
    arithmetic runs BEFORE any partition build, and a hierarchy the
    cutoff will silently TRUNCATE (auto depth) warns so the shallower-
    than-expected cycle is no surprise."""
    if getattr(scfg, "precond", "jacobi") != "mg":
        return CheckResult("mg_replication", "ok")
    cap = int(getattr(scfg, "mg_max_replicated_dofs", 0))
    if cap <= 0:
        return CheckResult("mg_replication", "ok")
    from pcg_mpi_solver_tpu.ops.mg import (
        MGSetupError, apply_replication_cutoff, fine_lattice,
        level_replicated_dofs, plan_levels)

    dims, _lat = fine_lattice(model)
    if dims is None:
        return CheckResult("mg_replication", "ok")   # mg_hierarchy fails
    n_levels = int(getattr(scfg, "mg_levels", 0))
    try:
        planned = plan_levels(dims, n_levels)
    except MGSetupError:
        return CheckResult("mg_replication", "ok")   # mg_hierarchy fails
    try:
        kept = apply_replication_cutoff(planned, n_levels, cap)
    except MGSetupError as e:
        return CheckResult("mg_replication", "fail", str(e))
    if len(kept) < len(planned):
        total = sum(level_replicated_dofs(planned))
        return CheckResult(
            "mg_replication", "warn",
            f"mg hierarchy will be truncated from {len(planned)} to "
            f"{len(kept)} coarse level(s): the full hierarchy needs "
            f"{total} replicated dofs per device, over the "
            f"mg_max_replicated_dofs={cap} cutoff")
    return CheckResult("mg_replication", "ok")


def check_mg_interval(lmin: float, lmax: float) -> CheckResult:
    """Degenerate Chebyshev interval diagnostic for the MG smoother
    (ISSUE 10 satellite): the setup-time eigenvalue estimates
    [lambda_min, lambda_max] of the coarsest level's D^-1 A.  A ratio
    under 1.05 means the level operator is numerically a multiple of
    its diagonal — the Chebyshev polynomial degenerates and the coarse
    correction adds nothing (usually a sign the hierarchy coarsened
    into triviality or the estimates failed).  Warn, never fail: the
    V-cycle is still a valid SPD preconditioner, just a weak one."""
    if not (math.isfinite(lmax) and lmax > 0):
        return CheckResult(
            "mg_cheb_interval", "warn",
            f"estimated lambda_max={lmax!r} is not a positive finite "
            "number; the Chebyshev smoother interval is meaningless")
    lo = max(float(lmin), 0.0)
    if lo > 0 and lmax / lo < 1.05:
        return CheckResult(
            "mg_cheb_interval", "warn",
            f"estimated Chebyshev interval is degenerate "
            f"(lambda_max/lambda_min = {lmax / lo:.4f} < 1.05): the "
            "level operator is numerically a multiple of its diagonal "
            "— the mg coarse correction adds ~nothing over Jacobi")
    return CheckResult("mg_cheb_interval", "ok")


def check_rhs_block(fexts: Any, n_dof: int) -> List[CheckResult]:
    """Per-column validation of a blocked right-hand side (the
    ``Solver.solve_many`` request gate): shape contract per RHS and a
    NaN/Inf scan that names the OFFENDING COLUMN INDEX — a multi-tenant
    block must reject the one bad load case comprehensibly, not report
    a whole-array failure.  Also applied by ``cli.py solve-many``.

    ``fexts``: (n_dof, nrhs) array (one column per load case)."""
    a = np.asarray(fexts)
    if a.ndim != 2:
        return [CheckResult(
            "rhs_block_shape", "fail",
            f"fext block must be 2-D (n_dof, nrhs), got shape {a.shape}")]
    if a.shape[0] != n_dof:
        return [CheckResult(
            "rhs_block_shape", "fail",
            f"fext block rows {a.shape[0]} != n_dof {n_dof} "
            f"(columns are load cases)")]
    if a.shape[1] < 1:
        return [CheckResult("rhs_block_shape", "fail",
                            "fext block has zero columns")]
    if a.dtype.kind != "f":
        return [CheckResult(
            "rhs_block_shape", "fail",
            f"fext block dtype {a.dtype} is not floating")]
    results = [CheckResult("rhs_block_shape", "ok")]
    finite_cols = np.isfinite(a).all(axis=0)
    if not finite_cols.all():
        bad = np.flatnonzero(~finite_cols)
        per_col = ", ".join(
            f"rhs {int(j)} ({int(np.count_nonzero(~np.isfinite(a[:, j])))} "
            "non-finite)" for j in bad[:8])
        more = f" (+{bad.size - 8} more)" if bad.size > 8 else ""
        results.append(CheckResult(
            "rhs_block_finite", "fail",
            f"NaN/Inf in column(s): {per_col}{more}"))
    else:
        results.append(CheckResult("rhs_block_finite", "ok"))
    zero_cols = ~np.any(a, axis=0) if a.size else np.zeros(0, bool)
    if zero_cols.any():
        results.append(CheckResult(
            "rhs_block_zero", "warn",
            f"all-zero column(s) {np.flatnonzero(zero_cols).tolist()}: "
            "they solve to x = 0 but still ride every blocked matvec"))
    else:
        results.append(CheckResult("rhs_block_zero", "ok"))
    # norm spread across the block: per-column tolerances are RELATIVE
    # (tolb_j = tol * ||b_j||), so a column whose load norm is many
    # orders below its block-mates chases an absolute residual near the
    # working-precision floor of the SHARED lockstep arithmetic — the
    # classic way one tenant column ends flag 3 (stagnation) or enters
    # the recovery ladder while the rest of the block converges.  Warn,
    # don't fail: the solve is still well-defined.
    if finite_cols.all() and not zero_cols.any() and a.shape[1] > 1:
        norms = np.linalg.norm(a, axis=0)
        lo, hi = float(norms.min()), float(norms.max())
        if lo > 0 and hi / lo > 1e10:
            results.append(CheckResult(
                "rhs_block_spread", "warn",
                f"column load norms span {hi / lo:.1e}x (min rhs "
                f"{int(np.argmin(norms))}, max rhs "
                f"{int(np.argmax(norms))}): the small-norm column may "
                "stagnate/quarantine near the precision floor of the "
                "blocked solve — consider solving it separately"))
        else:
            results.append(CheckResult("rhs_block_spread", "ok"))
    return results


# ----------------------------------------------------------------------
# Runner
# ----------------------------------------------------------------------

def preflight_checks(model, config=None,
                     context: Optional[Dict[str, Any]] = None) \
        -> List[CheckResult]:
    """Run every applicable check; returns all results (never raises)."""
    results = [
        _check_shapes(model),
        _finite("finite_coords", {"node_coords": model.node_coords}),
        _finite("finite_loads", {"F": model.F, "Ud": model.Ud,
                                 "Vd": model.Vd}),
        _finite("finite_mass", {"diag_M": model.diag_M}),
        _finite("finite_scales", {"ck": model.ck, "cm": model.cm,
                                  "ce": model.ce, "level": model.level}),
        _check_materials(model),
        _check_elements(model),
        _check_constraints(model),
        _check_dof_partition(model),
        _check_connectivity(model),
    ]
    if config is not None:
        scfg = config.solver
        results.append(_check_solver_params(scfg))
        results.append(_check_tol_floor(scfg))
        results.append(_check_snapshot_cadence(config, context))
        results.append(_check_mg_hierarchy(model, scfg))
        results.append(_check_mg_replication(model, scfg))
    if (context or {}).get("kind") == "dynamics":
        results.append(_check_explicit_dt(model, context))
    return results


def run_preflight(model, config=None, *, policy: Optional[str] = None,
                  recorder=None,
                  context: Optional[Dict[str, Any]] = None) \
        -> List[CheckResult]:
    """Run the preflight gate: scan, emit ONE ``preflight`` telemetry
    event, and enforce the policy on fail-severity findings.

    Returns the check results (empty under ``off`` — nothing was
    scanned).  Raises :class:`PreflightError` under ``fail`` when any
    check failed; under ``warn`` the same findings become a
    ``warnings.warn`` and construction proceeds at the caller's risk.
    """
    pol = resolve_policy(policy if policy is not None
                         else getattr(config, "preflight", None))
    if pol == "off":
        return []
    results = preflight_checks(model, config, context)
    failed = [r for r in results if r.status == "fail"]
    warned = [r for r in results if r.status == "warn"]
    if recorder is not None:
        recorder.event("preflight", policy=pol,
                       context=(context or {}).get("kind", ""),
                       failed=len(failed), warned=len(warned),
                       checks=[r.to_event() for r in results])
        recorder.inc("preflight.runs")
        if failed:
            recorder.inc("preflight.failed")
    if failed:
        msg = "preflight rejected the model/config: " + "; ".join(
            f"[{r.name}] {r.detail}" for r in failed) + \
            "  (set PCG_TPU_PREFLIGHT=warn/off or --preflight= to bypass)"
        if pol == "fail":
            raise PreflightError(msg)
        warnings.warn(msg, stacklevel=3)
    return results
