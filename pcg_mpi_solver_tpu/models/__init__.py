from pcg_mpi_solver_tpu.models.model_data import ModelData
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model, make_poisson_model

__all__ = ["ModelData", "make_cube_model", "make_poisson_model"]
