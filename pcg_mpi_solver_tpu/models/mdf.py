"""Reader/writer for the reference's Model Definition Files (MDF) bundle.

A user of the reference brings models as a zip of binary arrays + .mat files
(produced by its offline MATLAB meshing pipeline).  Schema, with reference
citations:

- ``GlobN.mat`` Data[0..8] = [NElem, NDof, NDofGlbFlat, NNodeGlbFlat,
  NDofEff, NFacesFlat, NFaces, NPolysFlat, NFixedDof] (run_metis.py:19-34)
- per-element CSR-ish arrays with INCLUSIVE [start, end] offset pairs
  (partition_mesh.py:172-175, slices ``flat[o[i,0]:o[i,1]+1]`` :246-254):
  ``NodeGlbFlat.bin`` int32 + ``NodeGlbOffset.bin`` int64 (N,2) F-order;
  ``DofGlbFlat``/``DofGlbOffset``; ``SignFlat`` int8 + ``SignOffset``;
  ``Type`` int32, ``Level/Ck/Cm/Ce`` f64, ``PolyMat`` int32,
  ``sctrs`` f64 (N,3) F-order, ``StrsGlb``/``StrsSign`` int8 (N,6)
- nodal arrays (partition_mesh.py:324-330): ``DiagM/F/Ud/Vd/NodeCoordVec``
  f64 (NDof,) — NodeCoordVec holds each dof's node coordinate for that
  dof's axis (x for dof 3n, y for 3n+1, z for 3n+2; interleaved ravel of
  node coords, see identify_PotentialNeighbours partition_mesh.py:688-690);
  ``DofEff``/``FixedDof`` int32 id lists
- element library ``Ke.mat``/``Me.mat`` Data = per-type dense matrices
  (partition_mesh.py:543-547); ``MatProp.mat`` struct array E/Pos/Rho
  (partition_mesh.py:503-512); ``dt.mat`` scalar
- visualization topology: ``nodes.bin`` f64 (NNode,3), ``FacesFlat.bin``
  int32 + ``FacesOffset.bin`` int64 (NFaces,2), ``PolysFlat.bin`` int32
  (export_vtk.py:55-70,108-112)
- ``Intfc.npz`` (OUR schema extension, absent from the reference): cohesive
  interface elements — the reference keeps these only inside its partition
  pickles (partition_mesh.py:603-650), so they have no MDF representation
  to mirror
- ``Grid.npz`` / ``Octree.npz`` (OUR schema extensions): structured-grid /
  octree-lattice fast-path metadata (ModelData.grid / .octree), so a
  re-ingested model keeps its structured/hybrid backend eligibility;
  readers of the reference schema can ignore both

The writer emits the same schema from a ModelData (round-trip tested), so
synthetic models can feed the reference and vice versa.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
from typing import Optional

import numpy as np
import scipy.io

from pcg_mpi_solver_tpu.models.element import unit_element_library
from pcg_mpi_solver_tpu.models.model_data import ModelData, SparseVec


def _offsets_to_csr(flat, offset2):
    """Inclusive [start,end] pairs -> (contiguous flat, n+1 exclusive offsets)."""
    starts = offset2[:, 0]
    ends = offset2[:, 1] + 1
    lens = ends - starts
    csr_offset = np.concatenate([[0], np.cumsum(lens)])
    # re-pack (slices may in principle be non-contiguous in the source)
    if np.array_equal(starts, csr_offset[:-1]):
        packed = flat[: csr_offset[-1]]
    else:
        packed = np.concatenate([flat[s:e] for s, e in zip(starts, ends)])
    return packed, csr_offset


def _csr_to_offsets(offset):
    """n+1 exclusive offsets -> inclusive [start, end] int64 pairs."""
    return np.stack([offset[:-1], offset[1:] - 1], axis=1).astype(np.int64)


def read_mdf(mdf_path: str) -> ModelData:
    p = lambda name: os.path.join(mdf_path, name)
    glob_n = scipy.io.loadmat(p("GlobN.mat"))["Data"][0]
    n_elem = int(glob_n[0])
    n_dof = int(glob_n[1])
    n_node = n_dof // 3
    n_dof_flat = int(glob_n[2])
    n_node_flat = int(glob_n[3])
    n_dof_eff = int(glob_n[4])
    n_fixed = int(glob_n[8])

    def bin_(name, dtype, shape=None, order="C"):
        a = np.fromfile(p(name + ".bin"), dtype=dtype)
        if shape is not None:
            a = a.reshape(shape, order=order)
        return a

    node_flat = bin_("NodeGlbFlat", np.int32)[:n_node_flat].astype(np.int64)
    node_off2 = bin_("NodeGlbOffset", np.int64, (n_elem, 2), "F")
    dof_flat = bin_("DofGlbFlat", np.int32)[:n_dof_flat].astype(np.int64)
    dof_off2 = bin_("DofGlbOffset", np.int64, (n_elem, 2), "F")
    sign_flat = bin_("SignFlat", np.int8)[:n_dof_flat].astype(bool)
    sign_off2 = bin_("SignOffset", np.int64, (n_elem, 2), "F")

    nodes_flat, nodes_offset = _offsets_to_csr(node_flat, node_off2)
    dofs_flat, dofs_offset = _offsets_to_csr(dof_flat, dof_off2)
    signs_flat, signs_offset = _offsets_to_csr(sign_flat, sign_off2)
    if not np.array_equal(signs_offset, dofs_offset):
        raise ValueError("SignOffset inconsistent with DofGlbOffset")

    elem_type = bin_("Type", np.int32)[:n_elem]
    level = bin_("Level", np.float64)[:n_elem]
    ck = bin_("Ck", np.float64)[:n_elem]
    cm = bin_("Cm", np.float64)[:n_elem]
    ce = bin_("Ce", np.float64)[:n_elem]
    poly_mat = bin_("PolyMat", np.int32)[:n_elem]
    sctrs = bin_("sctrs", np.float64, (n_elem, 3), "F")

    diag_m = bin_("DiagM", np.float64)[:n_dof]
    F = bin_("F", np.float64)[:n_dof]
    Ud = bin_("Ud", np.float64)[:n_dof]
    Vd = bin_("Vd", np.float64)[:n_dof]
    dof_eff = bin_("DofEff", np.int32)[:n_dof_eff].astype(np.int64)
    fixed_dof = bin_("FixedDof", np.int32)[:n_fixed].astype(np.int64)

    if os.path.exists(p("nodes.bin")):
        # column-major on disk: the reference reads (NNode, 3) with
        # order='F' (export_vtk.py:70 via loadBinDataInSharedMem)
        raw_nodes = bin_("nodes", np.float64)
        node_coords = raw_nodes.reshape((n_node, 3), order="F")
        if os.path.exists(p("NodeCoordVec.bin")):
            # NodeCoordVec is dof-interleaved (= C-order ravel of the
            # coords) in both layouts — use it to detect legacy bundles
            # written row-major by pre-fix write_mdf, instead of silently
            # scrambling their geometry.
            ncv = bin_("NodeCoordVec", np.float64)[:n_dof]
            if not np.array_equal(node_coords.ravel(), ncv):
                legacy = raw_nodes.reshape(n_node, 3)
                if np.array_equal(legacy.ravel(), ncv):
                    node_coords = legacy
                else:
                    raise ValueError(
                        "nodes.bin matches neither the reference's "
                        "column-major layout nor the legacy row-major "
                        "layout (cross-checked against NodeCoordVec.bin)")
    else:
        node_coords = bin_("NodeCoordVec", np.float64)[:n_dof].reshape(n_node, 3)

    # element library
    Ke = scipy.io.loadmat(p("Ke.mat"))["Data"][0]
    Me = scipy.io.loadmat(p("Me.mat"))["Data"][0] if os.path.exists(p("Me.mat")) else None
    Se = scipy.io.loadmat(p("Se.mat"))["Data"][0] if os.path.exists(p("Se.mat")) else None
    elem_lib = {}
    for t in range(len(Ke)):
        Ket = np.asarray(Ke[t], float)
        elem_lib[t] = {
            "Ke": Ket,
            "diagKe": np.diag(Ket).copy(),
            "Me": np.asarray(Me[t], float) if Me is not None else None,
            "Se": np.asarray(Se[t], float) if Se is not None else None,
            "n_nodes": Ket.shape[0] // 3,
        }

    mat_raw = scipy.io.loadmat(p("MatProp.mat"), struct_as_record=False)["Data"][0]
    mat_prop = []
    for m in mat_raw:
        d = m.__dict__
        entry = {"E": float(d["E"][0][0]), "Pos": float(d["Pos"][0][0]),
                 "Rho": float(d["Rho"][0][0])}
        if "NonLocStressParam" in d:
            # alternating [key, value, ...] cell array, exactly the layout the
            # reference parses (partition_mesh.py:515-520)
            raw = d["NonLocStressParam"][0]
            nl = {str(raw[2 * i][0]): float(raw[2 * i + 1][0][0])
                  for i in range(len(raw) // 2)}
            if nl:
                entry["NonLocStressParam"] = nl
        mat_prop.append(entry)

    dt = float(scipy.io.loadmat(p("dt.mat"))["Data"][0][0]) \
        if os.path.exists(p("dt.mat")) else 1.0

    faces_flat = faces_offset = None
    if os.path.exists(p("FacesFlat.bin")):
        n_faces = int(glob_n[6])
        ff = bin_("FacesFlat", np.int32)[: int(glob_n[5])].astype(np.int64)
        fo2 = bin_("FacesOffset", np.int64, (n_faces, 2), "F")
        faces_flat, faces_offset = _offsets_to_csr(ff, fo2)

    # fast-path metadata sidecars (not part of the reference schema;
    # re-ingested models keep their structured/hybrid backend eligibility)
    grid = None
    octree = None
    if os.path.exists(p("Grid.npz")):
        with np.load(p("Grid.npz")) as z:
            grid = (int(z["nx"]), int(z["ny"]), int(z["nz"]),
                    float(z["h"]))
    if os.path.exists(p("Octree.npz")):
        with np.load(p("Octree.npz")) as z:
            octree = {
                "leaves": z["leaves"],
                "dims": tuple(int(d) for d in z["dims"]),
                "node_keys": z["node_keys"],
                "strides": tuple(int(s) for s in z["strides"]),
                "brick_type": (int(z["brick_type"])
                               if int(z["brick_type"]) >= 0 else None),
                "brick_corners": (z["brick_corners"]
                                  if z["brick_corners"].size else None),
            }

    intfc_elems = None
    if os.path.exists(p("Intfc.npz")):
        with np.load(p("Intfc.npz")) as z:
            # bind each member once: NpzFile re-reads the whole array per access
            nid, adj = z["node_id_list"], z["adj_elem"]
            kn, kt, area, nax = z["kn"], z["kt"], z["area"], z["normal_axis"]
        intfc_elems = [
            {"NodeIdList": nid[i], "adj_elem": int(adj[i]),
             "kn": float(kn[i]), "kt": float(kt[i]),
             "area": float(area[i]), "normal_axis": int(nax[i])}
            for i in range(len(adj))
        ]

    md = ModelData(
        n_elem=n_elem, n_node=n_node, n_dof=n_dof,
        node_coords=node_coords, F=F, Ud=Ud, Vd=Vd, diag_M=diag_m,
        fixed_dof=fixed_dof, dof_eff=dof_eff,
        elem_type=elem_type,
        elem_nodes_flat=nodes_flat, elem_nodes_offset=nodes_offset,
        elem_dofs_flat=dofs_flat, elem_dofs_offset=dofs_offset,
        elem_sign_flat=signs_flat,
        ck=ck, cm=cm, ce=ce, level=level, poly_mat=poly_mat, sctrs=sctrs,
        elem_lib=elem_lib, mat_prop=mat_prop, dt=dt,
        faces_flat=faces_flat, faces_offset=faces_offset,
        grid=grid, octree=octree,
        intfc_elems=intfc_elems,
    )
    # grid-only bundles skip the rebuild: backend selection picks
    # 'structured' anyway, so the multi-pass geometry scan buys nothing
    if (octree is None and grid is None
            and os.environ.get("PCG_TPU_RECONSTRUCT", "1") == "1"):
        # A GENUINE reference bundle has no fast-path sidecars (they are
        # our schema extension); rebuild the octree-lattice metadata from
        # the schema's own geometry so it routes to the hybrid backend
        # (reconstruct_lattice_meta engages only on exact lattice fits).
        from pcg_mpi_solver_tpu.models.octree import reconstruct_lattice_meta

        reconstruct_lattice_meta(md)
    return md


def write_mdf(model: ModelData, mdf_path: str) -> str:
    """Write a ModelData in the reference's MDF schema."""
    if model.n_dof != 3 * model.n_node:
        # The MDF schema is the reference's 3-dof elasticity format
        # (NodeCoordVec etc. interleave 3 components per node,
        # partition_mesh.py:172-175) — it cannot carry the scalar class.
        raise ValueError(
            "the MDF schema is 3-dof-per-node (reference elasticity "
            "format); scalar (Poisson) models cannot be written — keep "
            "them as in-memory/synthetic models")
    os.makedirs(mdf_path, exist_ok=True)
    p = lambda name: os.path.join(mdf_path, name)

    n_faces = 0 if model.faces_offset is None else len(model.faces_offset) - 1
    n_faces_flat = 0 if model.faces_flat is None else len(model.faces_flat)
    glob_n = np.array([
        model.n_elem, model.n_dof, len(model.elem_dofs_flat),
        len(model.elem_nodes_flat), len(model.dof_eff), n_faces_flat,
        n_faces, n_faces, len(model.fixed_dof),
    ], dtype=np.float64)
    scipy.io.savemat(p("GlobN.mat"), {"Data": glob_n})
    scipy.io.savemat(p("dt.mat"), {"Data": np.array([model.dt])})

    model.elem_nodes_flat.astype(np.int32).tofile(p("NodeGlbFlat.bin"))
    _csr_to_offsets(model.elem_nodes_offset).ravel(order="F").tofile(p("NodeGlbOffset.bin"))
    model.elem_dofs_flat.astype(np.int32).tofile(p("DofGlbFlat.bin"))
    _csr_to_offsets(model.elem_dofs_offset).ravel(order="F").tofile(p("DofGlbOffset.bin"))
    model.elem_sign_flat.astype(np.int8).tofile(p("SignFlat.bin"))
    _csr_to_offsets(model.elem_dofs_offset).ravel(order="F").tofile(p("SignOffset.bin"))

    model.elem_type.astype(np.int32).tofile(p("Type.bin"))
    model.level.astype(np.float64).tofile(p("Level.bin"))
    model.ck.astype(np.float64).tofile(p("Ck.bin"))
    model.cm.astype(np.float64).tofile(p("Cm.bin"))
    model.ce.astype(np.float64).tofile(p("Ce.bin"))
    model.poly_mat.astype(np.int32).tofile(p("PolyMat.bin"))
    np.asfortranarray(model.sctrs).ravel(order="F").tofile(p("sctrs.bin"))
    np.zeros((model.n_elem, 6), np.int8).ravel(order="F").tofile(p("StrsGlb.bin"))
    np.zeros((model.n_elem, 6), np.int8).ravel(order="F").tofile(p("StrsSign.bin"))

    model.diag_M.astype(np.float64).tofile(p("DiagM.bin"))
    model.F.astype(np.float64).tofile(p("F.bin"))
    model.Ud.astype(np.float64).tofile(p("Ud.bin"))
    model.Vd.astype(np.float64).tofile(p("Vd.bin"))
    model.node_coords.astype(np.float64).ravel().tofile(p("NodeCoordVec.bin"))
    model.dof_eff.astype(np.int32).tofile(p("DofEff.bin"))
    model.fixed_dof.astype(np.int32).tofile(p("FixedDof.bin"))
    # column-major to match the reference's order='F' read (see read_mdf)
    model.node_coords.astype(np.float64).ravel(order="F").tofile(
        p("nodes.bin"))

    type_ids = sorted(model.elem_lib.keys())
    ke_arr = np.empty(len(type_ids), dtype=object)
    me_arr = np.empty(len(type_ids), dtype=object)
    se_arr = np.empty(len(type_ids), dtype=object)
    for i, t in enumerate(type_ids):
        lib = model.elem_lib[t]
        ke_arr[i] = np.asarray(lib["Ke"], float)
        me_arr[i] = np.asarray(lib["Me"] if lib.get("Me") is not None
                               else np.zeros_like(lib["Ke"]), float)
        se_arr[i] = np.asarray(lib["Se"] if lib.get("Se") is not None
                               else np.zeros((6, lib["Ke"].shape[0])), float)
    scipy.io.savemat(p("Ke.mat"), {"Data": ke_arr.reshape(1, -1)})
    scipy.io.savemat(p("Me.mat"), {"Data": me_arr.reshape(1, -1)})
    scipy.io.savemat(p("Se.mat"), {"Data": se_arr.reshape(1, -1)})

    dtype = [("E", object), ("Pos", object), ("Rho", object),
             ("NonLocStressParam", object)]
    rec = np.zeros((1, len(model.mat_prop)), dtype=dtype)
    for i, m in enumerate(model.mat_prop):
        nl = m.get("NonLocStressParam", {})
        nl_arr = np.empty((1, 2 * len(nl)), dtype=object)
        for j, (key, val) in enumerate(nl.items()):
            nl_arr[0, 2 * j] = np.array([key])
            nl_arr[0, 2 * j + 1] = np.array([[val]])
        rec[0, i] = (np.array([[m["E"]]]), np.array([[m["Pos"]]]),
                     np.array([[m["Rho"]]]), nl_arr)
    scipy.io.savemat(p("MatProp.mat"), {"Data": rec})

    if model.faces_flat is not None:
        model.faces_flat.astype(np.int32).tofile(p("FacesFlat.bin"))
        _csr_to_offsets(model.faces_offset).ravel(order="F").tofile(p("FacesOffset.bin"))
        # PolysFlat carries face-id incidence: the reference's Boundary mode
        # keeps ids with bincount == 1 (export_vtk.py:112).  Our face list
        # stores interior faces TWICE (one record per adjacent cell), so we
        # emit each record's CANONICAL id (first record with the same node
        # set): canonical interior ids then count 2, their duplicates 0,
        # boundary ids 1 — exactly the reference's semantics.  For models
        # that store only boundary faces this reduces to arange.
        from pcg_mpi_solver_tpu.vtk.export import _face_table

        canon = np.arange(n_faces, dtype=np.int64)
        for idx, arr in _face_table(model.faces_flat, model.faces_offset):
            key = np.sort(arr, axis=1)
            _, first, inv = np.unique(key, axis=0, return_index=True,
                                      return_inverse=True)
            canon[idx] = idx[first[inv]]
        canon.astype(np.int32).tofile(p("PolysFlat.bin"))

    for name, present in (("Grid.npz", model.grid is not None),
                          ("Octree.npz", model.octree is not None)):
        if not present and os.path.exists(p(name)):
            os.remove(p(name))      # never leave stale sidecars behind
    if model.grid is not None:
        nx_, ny_, nz_, h_ = model.grid
        np.savez(p("Grid.npz"), nx=nx_, ny=ny_, nz=nz_, h=h_)
    if model.octree is not None:
        ot = model.octree
        bt = ot.get("brick_type")
        bc = ot.get("brick_corners")
        np.savez(
            p("Octree.npz"),
            leaves=np.asarray(ot["leaves"], np.int64),
            dims=np.asarray(ot["dims"], np.int64),
            node_keys=np.asarray(ot["node_keys"], np.int64),
            strides=np.asarray(ot["strides"], np.int64),
            brick_type=np.int64(-1 if bt is None else bt),
            brick_corners=(np.zeros((0, 3), np.int64) if bc is None
                           else np.asarray(bc, np.int64)),
        )

    if not model.intfc_elems and os.path.exists(p("Intfc.npz")):
        os.remove(p("Intfc.npz"))   # never leave stale interfaces behind
    if model.intfc_elems:
        ie = model.intfc_elems
        np.savez(
            p("Intfc.npz"),
            node_id_list=np.stack([np.asarray(e["NodeIdList"]) for e in ie]),
            adj_elem=np.array([e["adj_elem"] for e in ie], dtype=np.int64),
            kn=np.array([e["kn"] for e in ie]),
            kt=np.array([e["kt"] for e in ie]),
            area=np.array([e["area"] for e in ie]),
            normal_axis=np.array([e["normal_axis"] for e in ie], dtype=np.int32),
        )
    return mdf_path


# ----------------------------------------------------------------------
# Streamed slab ingest (ISSUE 14): a process of an N-way sharded setup
# reads ONLY its slab's elements + the nodal entries they reference —
# peak host memory is bounded by slab size + one chunk, never by the
# model (the full reader materializes every array; at 1B dofs that is
# the wall ROADMAP item 2 names).
# ----------------------------------------------------------------------

@dataclasses.dataclass
class IngestStats:
    """Peak-host-memory accounting of one streamed ingest: ``retained``
    bytes live in the returned model, ``transient`` bytes existed only
    during a chunked pass.  ``peak_bytes`` is the asserted bound in
    tests and the ``ingest_peak_bytes`` field of the setup-ladder
    artifact."""

    retained_bytes: int = 0
    peak_bytes: int = 0
    _transient: int = 0

    def retain(self, *arrays) -> None:
        for a in arrays:
            if a is not None:
                self.retained_bytes += int(np.asarray(a).nbytes)
        self._bump()

    def transient(self, nbytes: int) -> None:
        self._transient = int(nbytes)
        self._bump()
        self._transient = 0

    def _bump(self) -> None:
        self.peak_bytes = max(self.peak_bytes,
                              self.retained_bytes + self._transient)


def _mm(path, dtype, shape=None, order="C"):
    """Read-only memmap of one MDF .bin array: fancy-indexed gathers
    touch only the selected pages — the mechanism that keeps slab
    ingest's peak memory at slab size."""
    mm = np.memmap(path, dtype=dtype, mode="r")
    if shape is not None:
        mm = mm[: int(np.prod(shape))].reshape(shape, order=order)
    return mm


def slab_elem_ids(mdf_path: str, slab_idx: int, n_slabs: int,
                  chunk_elems: int = 250_000,
                  stats: Optional[IngestStats] = None) -> np.ndarray:
    """Element ids of one coarse slab: the SAME cut as
    ``parallel/partition.coarse_slab_cut`` (dominant centroid axis,
    stable sort, balanced contiguous chunks), with the axis extents
    found by a CHUNKED pass over ``sctrs`` so full coordinates are never
    materialized.  The one O(n_elem) transient is the chosen axis's
    scalar column (the sort key).  Returns SORTED ascending global ids
    (gather locality)."""
    glob_n = scipy.io.loadmat(os.path.join(mdf_path, "GlobN.mat"))["Data"][0]
    n_elem = int(glob_n[0])
    if not (0 <= slab_idx < n_slabs):
        raise ValueError(f"slab_idx {slab_idx} outside [0, {n_slabs})")
    if n_slabs == 1:
        return np.arange(n_elem, dtype=np.int64)
    # sctrs is F-order (n_elem, 3): each axis is one contiguous column
    sc = _mm(os.path.join(mdf_path, "sctrs.bin"), np.float64,
             (n_elem, 3), "F")
    ext = np.zeros(3)
    for a in range(3):
        amin, amax = np.inf, -np.inf
        for i in range(0, n_elem, chunk_elems):
            col = np.asarray(sc[i:i + chunk_elems, a])
            if stats is not None:
                stats.transient(col.nbytes)
            amin = min(amin, float(col.min()))
            amax = max(amax, float(col.max()))
        ext[a] = amax - amin
    axis = int(np.argmax(ext))
    coord = np.asarray(sc[:, axis])          # ONE scalar column, transient
    if stats is not None:
        stats.transient(coord.nbytes)
    order = np.argsort(coord, kind="stable")
    if stats is not None:
        stats.transient(coord.nbytes + order.nbytes)
    lo = int(round(n_elem * slab_idx / n_slabs))
    hi = int(round(n_elem * (slab_idx + 1) / n_slabs))
    return np.sort(order[lo:hi]).astype(np.int64)


def read_mdf_slab(mdf_path: str, slab_idx: int, n_slabs: int,
                  chunk_elems: int = 250_000,
                  stats: Optional[IngestStats] = None) -> ModelData:
    """Streamed slab ingest of an MDF bundle: a ModelData VIEW holding
    only slab ``slab_idx`` of ``n_slabs`` — per-element arrays cover the
    slab's elements (``elem_ids`` maps to global ids, ``n_elem`` is the
    slab count), nodal arrays are :class:`SparseVec` restrictions to the
    dofs/nodes the slab references.  Global counts/ids are untouched, so
    ``partition_model(part_range=..., comm=...)`` consumes the view
    directly (elem_part slab-positional) and the interface reduction
    still runs on global ids.  Bundles with cohesive interface elements
    or octree sidecars need the full reader (their structures are not
    slab-separable); ``Grid.npz`` passes through.

    Peak host memory: O(slab + chunk) for connectivity/coordinates (the
    asserted bound — ``stats.peak_bytes``), plus three O(n) transients:
    the coarse-cut sort key, its argsort, and the effective-dof id list
    it intersects."""
    stats = stats if stats is not None else IngestStats()
    p = lambda name: os.path.join(mdf_path, name)
    if os.path.exists(p("Intfc.npz")):
        raise NotImplementedError(
            "read_mdf_slab: cohesive interface elements are not "
            "slab-separable (their anchor elements cross slabs); use "
            "read_mdf")
    if os.path.exists(p("Octree.npz")):
        raise NotImplementedError(
            "read_mdf_slab: octree-lattice models route to the hybrid "
            "backend, which needs the full model; use read_mdf")
    glob_n = scipy.io.loadmat(p("GlobN.mat"))["Data"][0]
    n_elem = int(glob_n[0])
    n_dof = int(glob_n[1])
    n_node = n_dof // 3
    n_dof_flat = int(glob_n[2])
    n_node_flat = int(glob_n[3])
    n_dof_eff = int(glob_n[4])
    n_fixed = int(glob_n[8])

    e = slab_elem_ids(mdf_path, slab_idx, n_slabs, chunk_elems, stats)
    ne = len(e)

    # ---- per-element scalars (memmap row gathers) ---------------------
    elem_type = np.asarray(_mm(p("Type.bin"), np.int32)[:n_elem][e])
    level = np.asarray(_mm(p("Level.bin"), np.float64)[:n_elem][e])
    ck = np.asarray(_mm(p("Ck.bin"), np.float64)[:n_elem][e])
    cm = np.asarray(_mm(p("Cm.bin"), np.float64)[:n_elem][e])
    ce = np.asarray(_mm(p("Ce.bin"), np.float64)[:n_elem][e])
    poly_mat = np.asarray(_mm(p("PolyMat.bin"), np.int32)[:n_elem][e])
    sctrs = np.asarray(_mm(p("sctrs.bin"), np.float64,
                           (n_elem, 3), "F")[e])
    stats.retain(elem_type, level, ck, cm, ce, poly_mat, sctrs, e)

    # ---- slab CSR connectivity (chunked ragged gather) ----------------
    def slab_csr(flat_name, off_name, dtype, n_flat):
        off2 = _mm(p(off_name), np.int64, (n_elem, 2), "F")
        starts = np.asarray(off2[e, 0])
        ends = np.asarray(off2[e, 1]) + 1
        lens = ends - starts
        offset = np.concatenate([[0], np.cumsum(lens)])
        flat_mm = _mm(p(flat_name), dtype)
        out = np.empty(int(offset[-1]), dtype=dtype)
        for i in range(0, ne, chunk_elems):
            j = min(i + chunk_elems, ne)
            idx = _ragged_index(starts[i:j], lens[i:j])
            stats.transient(idx.nbytes)
            out[offset[i]:offset[j]] = flat_mm[idx]
        return out, offset

    nodes_flat_raw, nodes_offset = slab_csr(
        "NodeGlbFlat.bin", "NodeGlbOffset.bin", np.int32, n_node_flat)
    dofs_flat_raw, dofs_offset = slab_csr(
        "DofGlbFlat.bin", "DofGlbOffset.bin", np.int32, n_dof_flat)
    signs_flat, signs_offset = slab_csr(
        "SignFlat.bin", "SignOffset.bin", np.int8, n_dof_flat)
    if not np.array_equal(signs_offset, dofs_offset):
        raise ValueError("SignOffset inconsistent with DofGlbOffset")
    nodes_flat = nodes_flat_raw.astype(np.int64)
    dofs_flat = dofs_flat_raw.astype(np.int64)
    stats.retain(nodes_flat, nodes_offset, dofs_flat, dofs_offset,
                 signs_flat)

    # ---- referenced nodal entries (sparse restriction) ----------------
    ref_dofs = np.unique(dofs_flat)
    ref_nodes = np.unique(nodes_flat)

    def sparse(name, ids):
        mm = _mm(p(name + ".bin"), np.float64)
        vals = np.asarray(mm[:n_dof][ids])
        stats.retain(vals)
        return SparseVec(ids, vals, n_dof, strict=False)

    F = sparse("F", ref_dofs)
    Ud = sparse("Ud", ref_dofs)
    Vd = sparse("Vd", ref_dofs)
    diag_m = sparse("DiagM", ref_dofs)
    if os.path.exists(p("nodes.bin")):
        nc = _mm(p("nodes.bin"), np.float64, (n_node, 3), "F")
        nc_vals = np.asarray(nc[ref_nodes])
        if os.path.exists(p("NodeCoordVec.bin")):
            # same legacy-layout cross-check as read_mdf, on the slab's
            # rows only: NodeCoordVec is the C-order ravel of the
            # coords in BOTH layouts — a pre-fix row-major nodes.bin
            # must be detected, not silently transposed
            ncv = _mm(p("NodeCoordVec.bin"), np.float64)
            ref = np.asarray(ncv[(3 * ref_nodes[:, None]
                                  + np.arange(3)).ravel()]).reshape(-1, 3)
            if not np.array_equal(nc_vals, ref):
                legacy = np.asarray(
                    _mm(p("nodes.bin"), np.float64,
                        (n_node, 3))[ref_nodes])
                if np.array_equal(legacy, ref):
                    nc_vals = legacy
                else:
                    raise ValueError(
                        "nodes.bin matches neither the reference's "
                        "column-major layout nor the legacy row-major "
                        "layout (cross-checked against "
                        "NodeCoordVec.bin on the slab's nodes)")
    else:
        nc = _mm(p("NodeCoordVec.bin"), np.float64).reshape(n_node, 3)
        nc_vals = np.asarray(nc[ref_nodes])
    stats.retain(nc_vals)
    node_coords = SparseVec(ref_nodes, nc_vals, n_node)

    # dof id lists restricted to the slab's referenced dofs (the full
    # list is the third O(n) transient — ids only, 4 bytes/entry)
    eff_all = np.asarray(_mm(p("DofEff.bin"), np.int32)[:n_dof_eff],
                         dtype=np.int64)
    stats.transient(eff_all.nbytes)
    dof_eff = np.intersect1d(eff_all, ref_dofs)
    fixed_all = np.asarray(_mm(p("FixedDof.bin"), np.int32)[:n_fixed],
                           dtype=np.int64)
    stats.transient(fixed_all.nbytes)
    fixed_dof = np.intersect1d(fixed_all, ref_dofs)
    stats.retain(dof_eff, fixed_dof)

    # ---- element library / materials / dt (small, full read) ----------
    Ke = scipy.io.loadmat(p("Ke.mat"))["Data"][0]
    Me = (scipy.io.loadmat(p("Me.mat"))["Data"][0]
          if os.path.exists(p("Me.mat")) else None)
    Se = (scipy.io.loadmat(p("Se.mat"))["Data"][0]
          if os.path.exists(p("Se.mat")) else None)
    elem_lib = {}
    for t in range(len(Ke)):
        Ket = np.asarray(Ke[t], float)
        elem_lib[t] = {
            "Ke": Ket, "diagKe": np.diag(Ket).copy(),
            "Me": np.asarray(Me[t], float) if Me is not None else None,
            "Se": np.asarray(Se[t], float) if Se is not None else None,
            "n_nodes": Ket.shape[0] // 3,
        }
    mat_raw = scipy.io.loadmat(p("MatProp.mat"),
                               struct_as_record=False)["Data"][0]
    mat_prop = [{"E": float(m.__dict__["E"][0][0]),
                 "Pos": float(m.__dict__["Pos"][0][0]),
                 "Rho": float(m.__dict__["Rho"][0][0])}
                for m in mat_raw]
    dt = (float(scipy.io.loadmat(p("dt.mat"))["Data"][0][0])
          if os.path.exists(p("dt.mat")) else 1.0)
    grid = None
    if os.path.exists(p("Grid.npz")):
        with np.load(p("Grid.npz")) as z:
            grid = (int(z["nx"]), int(z["ny"]), int(z["nz"]),
                    float(z["h"]))

    return ModelData(
        n_elem=ne, n_node=n_node, n_dof=n_dof,
        node_coords=node_coords, F=F, Ud=Ud, Vd=Vd, diag_M=diag_m,
        fixed_dof=fixed_dof, dof_eff=dof_eff,
        elem_type=elem_type,
        elem_nodes_flat=nodes_flat, elem_nodes_offset=nodes_offset,
        elem_dofs_flat=dofs_flat, elem_dofs_offset=dofs_offset,
        elem_sign_flat=signs_flat.astype(bool),
        ck=ck, cm=cm, ce=ce, level=level, poly_mat=poly_mat,
        sctrs=sctrs, elem_lib=elem_lib, mat_prop=mat_prop, dt=dt,
        grid=grid, elem_ids=e, glob_n_elem=n_elem,
    )


def _ragged_index(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Flat gather indices of the ragged slices [s, s+l) — repeat-based
    (zero-length slices pass through correctly; the cumsum-walk idiom
    ``parallel/partition._csr_take`` uses mis-steps on them)."""
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    starts = np.asarray(starts, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    offset = np.concatenate([[0], np.cumsum(lens)[:-1]])
    return (np.repeat(starts, lens)
            + np.arange(total, dtype=np.int64)
            - np.repeat(offset, lens))


def ingest_archive(archive_path: str, scratch_path: str,
                   model_name: Optional[str] = None) -> str:
    """Unpack a model archive into <scratch>/ModelData/MDF (reference
    read_input_model.py:23-39) and return the MDF path."""
    mdf_path = os.path.join(scratch_path, "ModelData", "MDF")
    os.makedirs(mdf_path, exist_ok=True)
    shutil.unpack_archive(archive_path, mdf_path)
    return mdf_path
