"""Synthetic elastostatic models (host-side, numpy).

The reference's demo model (``concrete.zip``, a 124,693-element octree mesh
from a 512^3-voxel concrete CT image) is absent from the snapshot
(.MISSING_LARGE_BLOBS), so this generator produces structured hexahedral
cube models of arbitrary size with the same data model: pattern-typed
elements, Ck/Cm/Ce scalings, Dirichlet BCs with lifting, a load vector, and
boundary faces for VTK export.  Used by tests and benchmarks.

Two-phase "concrete-like" material heterogeneity (stiff inclusions in a
mortar matrix) is available so the PCG iteration count is realistic rather
than the trivial homogeneous-cube count.
"""

from __future__ import annotations

import numpy as np

from pcg_mpi_solver_tpu.models.element import unit_element_library
from pcg_mpi_solver_tpu.models.model_data import ModelData


def _structured_hex_mesh(nx, ny, nz, h):
    """Structured-grid nodes + VTK-hex connectivity, shared by the cube
    (elasticity) and Poisson generators: returns (nid, coords (n_node, 3),
    conn (n_elem, 8)); node id = ix + nnx*(iy + nny*iz), x fastest."""
    nnx, nny = nx + 1, ny + 1
    n_node = nnx * nny * (nz + 1)
    nid = np.arange(n_node)
    cx = (nid % nnx) * h
    cy = ((nid // nnx) % nny) * h
    cz = (nid // (nnx * nny)) * h
    coords = np.stack([cx, cy, cz], axis=1)
    ex, ey, ez = np.meshgrid(np.arange(nx), np.arange(ny), np.arange(nz),
                             indexing="ij")
    ex = ex.ravel(order="F"); ey = ey.ravel(order="F"); ez = ez.ravel(order="F")
    n0 = ex + nnx * (ey + nny * ez)
    conn = np.stack(
        [n0, n0 + 1, n0 + 1 + nnx, n0 + nnx,
         n0 + nnx * nny, n0 + 1 + nnx * nny,
         n0 + 1 + nnx + nnx * nny, n0 + nnx + nnx * nny], axis=1)
    return nid, coords, conn


def make_cube_model(
    nx: int,
    ny: int = 0,
    nz: int = 0,
    h: float = 1.0,
    E: float = 1.0,
    nu: float = 0.2,
    rho: float = 1.0,
    load: str = "traction",
    load_value: float = 1.0,
    n_types: int = 1,
    heterogeneous: bool = False,
    seed: int = 0,
) -> ModelData:
    """Structured nx x ny x nz hex mesh of an elastic block.

    - Clamped at x=0 (all 3 dofs fixed).
    - ``load='traction'``: uniform nodal forces +x on the x=L face.
    - ``load='dirichlet'``: prescribed displacement +x on the x=L face
      (exercises the Dirichlet-lifting path, pcg_solver.py:226-238).
    - ``n_types > 1``: elements are round-robined over n_types identical
      pattern types — exercises the multi-type batched matvec exactly as a
      real octree library (<=144 types) would.
    - ``heterogeneous``: two-phase E field (10x stiff spherical inclusions).
    """
    ny = ny or nx
    nz = nz or nx
    n_elem = nx * ny * nz
    nnx, nny, nnz = nx + 1, ny + 1, nz + 1
    n_node = nnx * nny * nnz
    n_dof = 3 * n_node

    nid, coords, conn = _structured_hex_mesh(nx, ny, nz, h)
    cx = coords[:, 0]

    dofs = (3 * conn[:, :, None] + np.arange(3)[None, None, :]).reshape(n_elem, 24)

    # Materials / heterogeneity.
    rng = np.random.default_rng(seed)
    centers = coords[conn].mean(axis=1)  # element centroids
    if heterogeneous:
        E_elem = np.full(n_elem, E)
        n_incl = max(1, n_elem // 500)
        L = np.array([nx, ny, nz]) * h
        c_incl = rng.uniform(0, 1, (n_incl, 3)) * L
        # Cell-scaled radii: with one inclusion per ~500 elements this gives
        # a mesh-size-independent ~13% stiff volume fraction (domain-scaled
        # radii saturate to 100% on fine meshes).
        r_incl = rng.uniform(1.5, 3.5, n_incl) * h
        # Stamp each sphere only inside its bounding box on the structured
        # grid (element id = ex + nx*(ey + ny*ez)) — a full-mesh distance
        # field per inclusion is O(n_incl * n_elem) and unusable at 10M dofs.
        E3 = E_elem.reshape(nz, ny, nx)
        ax = (np.arange(nx) + 0.5) * h
        ay = (np.arange(ny) + 0.5) * h
        az = (np.arange(nz) + 0.5) * h
        for c, r in zip(c_incl, r_incl):
            i0, i1 = np.searchsorted(ax, [c[0] - r, c[0] + r])
            j0, j1 = np.searchsorted(ay, [c[1] - r, c[1] + r])
            k0, k1 = np.searchsorted(az, [c[2] - r, c[2] + r])
            if i0 >= i1 or j0 >= j1 or k0 >= k1:
                continue
            d2 = ((ax[i0:i1][None, None, :] - c[0]) ** 2
                  + (ay[j0:j1][None, :, None] - c[1]) ** 2
                  + (az[k0:k1][:, None, None] - c[2]) ** 2)
            E3[k0:k1, j0:j1, i0:i1][d2 < r * r] = 10.0 * E
        E_elem = E3.reshape(-1)
        mat = np.where(E_elem > E, 1, 0).astype(np.int32)
        # NonLocStressParam mirrors the reference MatProp schema
        # (partition_mesh.py:515-520); Lc is the nonlocal length scale.
        mat_prop = [
            {"E": E, "Pos": nu, "Rho": rho, "NonLocStressParam": {"Lc": 2.0 * h}},
            {"E": 10.0 * E, "Pos": nu, "Rho": rho, "NonLocStressParam": {"Lc": 2.0 * h}},
        ]
    else:
        E_elem = np.full(n_elem, E)
        mat = np.zeros(n_elem, dtype=np.int32)
        mat_prop = [{"E": E, "Pos": nu, "Rho": rho, "NonLocStressParam": {"Lc": 2.0 * h}}]

    lib0 = unit_element_library(nu)
    elem_lib = {t: lib0 for t in range(n_types)}
    elem_type = (np.arange(n_elem) % n_types).astype(np.int32)

    ck = E_elem * h                      # stiffness scale
    cm = rho * np.full(n_elem, h**3)     # mass scale
    ce = np.full(n_elem, 1.0 / h)        # strain scale
    level = np.full(n_elem, h)

    # Lumped mass diagonal (bincount: np.add.at is ~50x slower at 10M dofs).
    me_rowsum = lib0["Me"].sum(axis=1)
    diag_M = np.bincount(dofs.ravel(),
                         weights=(cm[:, None] * me_rowsum[None, :]).ravel(),
                         minlength=n_dof)

    # Boundary conditions.
    F = np.zeros(n_dof)
    Ud = np.zeros(n_dof)
    x0_nodes = nid[cx == 0.0]
    fixed = (3 * x0_nodes[:, None] + np.arange(3)[None, :]).ravel()
    xL_nodes = nid[cx == nx * h]
    if load == "traction":
        F[3 * xL_nodes] = load_value  # +x nodal force on the loaded face
    elif load == "dirichlet":
        Ud[3 * xL_nodes] = load_value
        fixed = np.concatenate([fixed, 3 * xL_nodes])
    else:
        raise ValueError(f"unknown load mode {load!r}")
    fixed = np.unique(fixed)
    dof_eff = np.setdiff1d(np.arange(n_dof), fixed, assume_unique=True)

    # Boundary faces (quads) for VTK export.
    faces = _boundary_quads(nx, ny, nz, nnx, nny)

    return ModelData(
        n_elem=n_elem,
        n_node=n_node,
        n_dof=n_dof,
        node_coords=coords,
        F=F,
        Ud=Ud,
        Vd=np.zeros(n_dof),
        diag_M=diag_M,
        fixed_dof=fixed,
        dof_eff=dof_eff,
        elem_type=elem_type,
        elem_nodes_flat=conn.ravel(),
        elem_nodes_offset=np.arange(n_elem + 1) * 8,
        elem_dofs_flat=dofs.ravel(),
        elem_dofs_offset=np.arange(n_elem + 1) * 24,
        elem_sign_flat=np.zeros(n_elem * 24, dtype=bool),
        ck=ck,
        cm=cm,
        ce=ce,
        level=level,
        poly_mat=mat,
        sctrs=centers,
        elem_lib=elem_lib,
        mat_prop=mat_prop,
        dt=1.0,
        faces_flat=faces.ravel(),
        faces_offset=np.arange(len(faces) + 1) * 4,
        grid=(nx, ny, nz, h) if n_types == 1 else None,
    )


def make_glued_blocks_model(
    nx_a: int,
    nx_b: int,
    ny: int,
    nz: int,
    h: float = 1.0,
    E: float = 1.0,
    nu: float = 0.2,
    rho: float = 1.0,
    load_value: float = 1.0,
    penalty: float = 1e3,
    kt_factor: float = 1.0,
) -> ModelData:
    """Two elastic blocks stacked along x, joined by zero-thickness cohesive
    interface elements (reference type -1/-2 scaffolding,
    partition_mesh.py:603-650) at the shared plane.

    The interface plane nodes are DUPLICATED (one set per block); each
    interface element carries the 4+4 coincident nodes, penalty stiffnesses
    kn = penalty*E/h (normal) and kt = kt_factor*kn (tangential) per unit
    area, and is anchored to the adjacent block-a element for partitioning.
    Clamped at x=0, +x traction on the far face of block b.
    """
    a = make_cube_model(nx_a, ny, nz, h=h, E=E, nu=nu, rho=rho,
                        load="traction", load_value=0.0)
    b = make_cube_model(nx_b, ny, nz, h=h, E=E, nu=nu, rho=rho,
                        load="traction", load_value=0.0)
    nn_a, nd_a, ne_a = a.n_node, a.n_dof, a.n_elem

    coords_b = b.node_coords + np.array([nx_a * h, 0.0, 0.0])
    n_node = nn_a + b.n_node
    n_dof = 3 * n_node
    n_elem = ne_a + b.n_elem

    # merged element arrays (block b ids offset)
    conn = np.concatenate([a.elem_nodes_flat, b.elem_nodes_flat + nn_a])
    dofs = np.concatenate([a.elem_dofs_flat, b.elem_dofs_flat + nd_a])

    F = np.zeros(n_dof)
    nnx_b, nny_b = nx_b + 1, ny + 1
    nid_b = np.arange(b.n_node)
    far = nid_b[(nid_b % nnx_b) == nx_b]          # block-b x = L face
    F[3 * (far + nn_a)] = load_value

    fixed = a.fixed_dof                           # block-a x = 0 clamp
    dof_eff = np.setdiff1d(np.arange(n_dof), fixed, assume_unique=True)

    # interface elements on the shared plane
    nnx_a, nny_a = nx_a + 1, ny + 1

    def gid_a(i, j, k):
        return i + nnx_a * (j + nny_a * k)

    def gid_b(i, j, k):
        return i + nnx_b * (j + nny_b * k)

    kn = penalty * E / h
    intfc = []
    for k in range(nz):
        for j in range(ny):
            quad_a = np.array([gid_a(nx_a, j, k), gid_a(nx_a, j + 1, k),
                               gid_a(nx_a, j + 1, k + 1), gid_a(nx_a, j, k + 1)])
            quad_b = np.array([gid_b(0, j, k), gid_b(0, j + 1, k),
                               gid_b(0, j + 1, k + 1), gid_b(0, j, k + 1)]) + nn_a
            adj = (nx_a - 1) + nx_a * (j + ny * k)   # block-a element at the plane
            intfc.append({
                "NodeIdList": np.stack([quad_a, quad_b]),
                "adj_elem": adj,
                "kn": kn,
                "kt": kt_factor * kn,
                "area": h * h,
                "normal_axis": 0,
            })

    diag_M = np.concatenate([a.diag_M, b.diag_M])
    faces = np.concatenate([a.faces_flat, b.faces_flat + nn_a])

    return ModelData(
        n_elem=n_elem,
        n_node=n_node,
        n_dof=n_dof,
        node_coords=np.concatenate([a.node_coords, coords_b]),
        F=F,
        Ud=np.zeros(n_dof),
        Vd=np.zeros(n_dof),
        diag_M=diag_M,
        fixed_dof=fixed,
        dof_eff=dof_eff,
        elem_type=np.concatenate([a.elem_type, b.elem_type]),
        elem_nodes_flat=conn,
        elem_nodes_offset=np.arange(n_elem + 1) * 8,
        elem_dofs_flat=dofs,
        elem_dofs_offset=np.arange(n_elem + 1) * 24,
        elem_sign_flat=np.zeros(n_elem * 24, dtype=bool),
        ck=np.concatenate([a.ck, b.ck]),
        cm=np.concatenate([a.cm, b.cm]),
        ce=np.concatenate([a.ce, b.ce]),
        level=np.concatenate([a.level, b.level]),
        poly_mat=np.concatenate([a.poly_mat, b.poly_mat]),
        sctrs=np.concatenate([a.sctrs, b.sctrs + np.array([nx_a * h, 0.0, 0.0])]),
        elem_lib=a.elem_lib,
        mat_prop=a.mat_prop,
        dt=1.0,
        faces_flat=faces,
        faces_offset=np.arange(len(a.faces_offset) - 1 + len(b.faces_offset) - 1 + 1) * 4,
        grid=None,
        intfc_elems=intfc,
    )


def _boundary_quads(nx, ny, nz, nnx, nny) -> np.ndarray:
    """Quad faces on the 6 boundary planes of the structured mesh."""
    def grid_id(i, j, k):
        return i + nnx * (j + nny * k)

    quads = []
    J, K = np.meshgrid(np.arange(ny), np.arange(nz), indexing="ij")
    J, K = J.ravel(), K.ravel()
    for i in (0, nx):  # x faces
        quads.append(np.stack([grid_id(i, J, K), grid_id(i, J + 1, K),
                               grid_id(i, J + 1, K + 1), grid_id(i, J, K + 1)], axis=1))
    I, K = np.meshgrid(np.arange(nx), np.arange(nz), indexing="ij")
    I, K = I.ravel(), K.ravel()
    for j in (0, ny):  # y faces
        quads.append(np.stack([grid_id(I, j, K), grid_id(I + 1, j, K),
                               grid_id(I + 1, j, K + 1), grid_id(I, j, K + 1)], axis=1))
    I, J = np.meshgrid(np.arange(nx), np.arange(ny), indexing="ij")
    I, J = I.ravel(), J.ravel()
    for k in (0, nz):  # z faces
        quads.append(np.stack([grid_id(I, J, k), grid_id(I + 1, J, k),
                               grid_id(I + 1, J + 1, k), grid_id(I, J + 1, k)], axis=1))
    return np.concatenate(quads, axis=0)


def make_poisson_model(
    nx: int,
    ny: int = 0,
    nz: int = 0,
    h: float = 1.0,
    k: float = 1.0,
    source: float = 1.0,
    load: str = "source",
    load_value: float = 1.0,
    heterogeneous: bool = False,
    seed: int = 0,
) -> ModelData:
    """Structured hex mesh of a SCALAR diffusion (Poisson) problem —
    the framework's second problem class (BASELINE.json config 2: "3D
    Poisson ... on structured cube, Jacobi-PCG"): 1 dof per node, d=8
    trilinear elements, same pattern-type machinery (Ck = k*h).

    - u = 0 on the x=0 face.
    - ``load='source'``: uniform volumetric source f (consistent nodal
      load F_i = f * sum_e h^3 (Me_unit . 1)_i).
    - ``load='dirichlet'``: u = load_value prescribed on the x=L face.
    - ``heterogeneous``: two-phase conductivity (10x k, seeded).

    Runs on the general matvec backend (the flat-scatter path: the
    node-ELL/structured/hybrid fast paths assume 3 dofs per node).
    """
    from pcg_mpi_solver_tpu.models.element import scalar_element_library

    ny = ny or nx
    nz = nz or nx
    n_elem = nx * ny * nz
    nnx, nny, nnz = nx + 1, ny + 1, nz + 1
    n_node = nnx * nny * nnz
    n_dof = n_node                      # 1 dof per node

    nid, coords, conn = _structured_hex_mesh(nx, ny, nz, h)
    cx = coords[:, 0]
    centers = coords[conn].mean(axis=1)

    if heterogeneous:
        rng = np.random.default_rng(seed)
        phase = rng.random(n_elem) < 0.2
        k_elem = np.where(phase, 10.0 * k, k)
        mat = phase.astype(np.int32)
        mat_prop = [
            {"E": k, "Pos": 0.0, "Rho": 1.0,
             "NonLocStressParam": {"Lc": 2.0 * h}},
            {"E": 10.0 * k, "Pos": 0.0, "Rho": 1.0,
             "NonLocStressParam": {"Lc": 2.0 * h}},
        ]
    else:
        k_elem = np.full(n_elem, k)
        mat = np.zeros(n_elem, dtype=np.int32)
        mat_prop = [{"E": k, "Pos": 0.0, "Rho": 1.0,
                     "NonLocStressParam": {"Lc": 2.0 * h}}]

    lib0 = scalar_element_library()
    me_rowsum = lib0["Me"].sum(axis=1)  # ∫ N_i dV on the unit cell

    ck = k_elem * h
    cm = np.full(n_elem, h**3)
    ce = np.full(n_elem, 1.0 / h)

    diag_M = np.bincount(conn.ravel(),
                         weights=(cm[:, None] * me_rowsum[None, :]).ravel(),
                         minlength=n_dof)

    F = np.zeros(n_dof)
    Ud = np.zeros(n_dof)
    fixed = nid[cx == 0.0]
    if load == "source":
        F = source * diag_M.copy()      # f * ∫ N_i dV (same row sums)
    elif load == "dirichlet":
        xL = nid[cx == nx * h]
        Ud[xL] = load_value
        fixed = np.concatenate([fixed, xL])
    else:
        raise ValueError(f"unknown load mode {load!r}")
    fixed = np.unique(fixed)
    F[fixed] = 0.0
    dof_eff = np.setdiff1d(np.arange(n_dof), fixed, assume_unique=True)

    faces = _boundary_quads(nx, ny, nz, nnx, nny)

    return ModelData(
        n_elem=n_elem,
        n_node=n_node,
        n_dof=n_dof,
        node_coords=coords,
        F=F,
        Ud=Ud,
        Vd=np.zeros(n_dof),
        diag_M=diag_M,
        fixed_dof=fixed,
        dof_eff=dof_eff,
        elem_type=np.zeros(n_elem, dtype=np.int32),
        elem_nodes_flat=conn.ravel(),
        elem_nodes_offset=np.arange(n_elem + 1) * 8,
        elem_dofs_flat=conn.ravel().copy(),
        elem_dofs_offset=np.arange(n_elem + 1) * 8,
        elem_sign_flat=np.zeros(n_elem * 8, dtype=bool),
        ck=ck,
        cm=cm,
        ce=ce,
        level=np.full(n_elem, h),
        poly_mat=mat,
        sctrs=centers,
        elem_lib={0: lib0},
        mat_prop=mat_prop,
        dt=1.0,
        faces_flat=faces.ravel(),
        faces_offset=np.arange(len(faces) + 1) * 4,
        grid=None,
    )
