"""Synthetic octree models with real transition pattern types (host-side).

The reference's entire problem class is octree meshes from CT images: cells
fall into a library of <=144 geometric pattern types (partition_mesh.py:1074
asserts ``0<=Type<=143``), each with a precomputed unit stiffness ``Ke``
(loaded from Ke.mat at partition_mesh.py:546-547), grouped per type for the
batched matvec (config_TypeGroupList, partition_mesh.py:420-493), with
boolean per-dof sign vectors handling mirrored pattern instances
(pcg_solver.py:277-280 flips signs around the Ke matmul).  The bundled
concrete model is absent from the snapshot, so this module builds the same
kind of mesh from scratch:

- a 2:1-balanced octree over a block (refinement driven by stiff spherical
  inclusions, CT-concrete style), strong balance over all 26 neighbors;
- hanging nodes are REAL dofs: a coarse cell whose face/edge touches finer
  neighbors includes the shared mid-edge / mid-face nodes, so elements have
  varying node counts (8..26) and dof counts d (24..78);
- each distinct (edge-mask, face-mask) configuration is a pattern type with
  its own unit ``Ke``/``Me``/``Se`` built by a conforming macro-element
  construction: the unit cube is split into 8 trilinear octants whose
  27-lattice corner values interpolate from the element's nodes (absent
  mid-nodes take the average of their edge/face neighbors — both cells
  sharing a face use the same rule, so the basis is C0-conforming across
  coarse/coarse and coarse/fine interfaces);
- with ``canonicalize=True`` patterns are reduced modulo the 8 axis
  reflections: mirrored instances reuse the canonical ``Ke`` with a slot
  permutation plus per-dof sign flips (u-component along each reflected
  axis), exercising the reference's sign machinery with real semantics.

Scalings match the rest of the framework: ``Ck = E*h``, ``Cm = rho*h^3``,
``Ce = 1/h`` per element (element.py).

``faces_flat`` holds EVERY element face (subdivided faces as their 4
sub-quads), so interior faces appear exactly twice and the exporter's
Boundary mode can keep incidence-1 faces (reference export_vtk.py:105-113).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from pcg_mpi_solver_tpu.models.element import (
    HEX_CORNERS, b_matrix, elasticity_matrix, hex_mass, hex_stiffness,
    shape_grad_natural)
from pcg_mpi_solver_tpu.models.model_data import ModelData

# ----------------------------------------------------------------------
# The 27-point lattice of the unit cube at half spacing: p in {0,1,2}^3.
# ----------------------------------------------------------------------

_CORNER_P = (2 * HEX_CORNERS).astype(np.int64)           # (8, 3), VTK order

# Edge midpoints: exactly one coordinate == 1.  Face centers: exactly two.
_EDGE_P = np.array([p for p in np.ndindex(3, 3, 3)
                    if sum(c == 1 for c in p) == 1], dtype=np.int64)
_FACE_P = np.array([p for p in np.ndindex(3, 3, 3)
                    if sum(c == 1 for c in p) == 2], dtype=np.int64)
_CENTER_P = np.array([1, 1, 1], dtype=np.int64)
N_EDGE, N_FACE = len(_EDGE_P), len(_FACE_P)              # 12, 6


def _lat_id(p) -> int:
    return int(p[0] + 3 * p[1] + 9 * p[2])


_CORNER_IDS = [_lat_id(p) for p in _CORNER_P]
_EDGE_IDS = [_lat_id(p) for p in _EDGE_P]
_FACE_IDS = [_lat_id(p) for p in _FACE_P]

# For an absent edge midpoint: average of its two edge-end corners.
_EDGE_ENDS = []
for p in _EDGE_P:
    ax = int(np.where(p == 1)[0][0])
    lo, hi = p.copy(), p.copy()
    lo[ax], hi[ax] = 0, 2
    _EDGE_ENDS.append((_lat_id(lo), _lat_id(hi)))

# For an absent face center: average of the values at its 4 edge midpoints
# (each itself a dof or a corner average).  Both cells sharing the face see
# the same mask for it, so this rule is conforming by construction.
_FACE_EDGES = []
for p in _FACE_P:
    ax = int(np.where(p != 1)[0][0])
    mids = []
    for t in np.where(np.arange(3) != ax)[0]:
        for v in (0, 2):
            q = p.copy()
            q[t] = v
            mids.append(_lat_id(q))
    _FACE_EDGES.append(mids)


def _slot_layout(mask: int) -> Tuple[List[int], Dict[int, int]]:
    """Node slots of a pattern: 8 corners, then present edge mids (edge
    order), then present face centers.  Returns (lattice ids per slot,
    lattice id -> slot)."""
    lat = list(_CORNER_IDS)
    for e in range(N_EDGE):
        if mask >> e & 1:
            lat.append(_EDGE_IDS[e])
    for f in range(N_FACE):
        if mask >> (N_EDGE + f) & 1:
            lat.append(_FACE_IDS[f])
    return lat, {l: s for s, l in enumerate(lat)}


def _interp_matrix(mask: int) -> np.ndarray:
    """A (27 x n_nodes): value at each lattice point as a combination of the
    pattern's nodal values (scalar; per-component via kron with I3)."""
    lat, slot_of = _slot_layout(mask)
    nn = len(lat)
    A = np.zeros((27, nn))
    for lid in _CORNER_IDS:
        A[lid, slot_of[lid]] = 1.0
    for e, lid in enumerate(_EDGE_IDS):
        if lid in slot_of:
            A[lid, slot_of[lid]] = 1.0
        else:
            a, b = _EDGE_ENDS[e]
            A[lid] = 0.5 * (A[a] + A[b])
    for f, lid in enumerate(_FACE_IDS):
        if lid in slot_of:
            A[lid, slot_of[lid]] = 1.0
        else:
            A[lid] = np.mean([A[m] for m in _FACE_EDGES[f]], axis=0)
    A[_lat_id(_CENTER_P)] = np.mean([A[l] for l in _FACE_IDS], axis=0)
    return A


def transition_element(mask: int, nu: float = 0.2) -> dict:
    """Unit (h=1, E=1, rho=1) matrices for one pattern type.

    Macro assembly: 8 trilinear octants (size 1/2), octant corner values
    from the interpolation matrix; Ke = sum_o G_o^T Ke_oct G_o.  SPD with
    exactly 6 rigid-body zero-energy modes by construction."""
    A = _interp_matrix(mask)
    nn = A.shape[1]
    d = 3 * nn
    Ke_oct = hex_stiffness(0.5, 1.0, nu)
    Me_oct = hex_mass(0.5, 1.0)
    Ke = np.zeros((d, d))
    Me = np.zeros((d, d))
    Se = np.zeros((6, d))
    I3 = np.eye(3)
    for o in np.ndindex(2, 2, 2):
        corner_lids = [_lat_id(np.asarray(o, dtype=np.int64) + c)
                       for c in HEX_CORNERS.astype(np.int64)]
        G = np.kron(A[corner_lids], I3)              # (24, d)
        Ke += G.T @ Ke_oct @ G
        Me += G.T @ Me_oct @ G
        # Center-point strain: the macro center (1,1,1) is the corner of
        # every octant at local 0/1 coords (1-o); average the 8 one-sided
        # gradients (reference Se role, partition_mesh.py:580).
        xi = 2.0 * (1.0 - np.asarray(o, dtype=float)) - 1.0
        dN_dx = shape_grad_natural(xi) / 0.25
        Se += b_matrix(dN_dx) @ G / 8.0
    return {"Ke": Ke, "Me": Me, "Se": Se, "diagKe": np.diag(Ke).copy(),
            "n_nodes": nn, "mask": mask}


# ----------------------------------------------------------------------
# Reflection canonicalization (the reference's mirrored-pattern signs).
# ----------------------------------------------------------------------

def _reflect_lattice(p: np.ndarray, r: Tuple[int, int, int]) -> np.ndarray:
    q = p.copy()
    for ax in range(3):
        if r[ax]:
            q[..., ax] = 2 - q[..., ax]
    return q


def _mask_perm(r: Tuple[int, int, int]) -> np.ndarray:
    """Bit permutation of the 18-bit (edges, faces) mask under reflection."""
    perm = np.zeros(N_EDGE + N_FACE, dtype=np.int64)
    eid = {l: i for i, l in enumerate(_EDGE_IDS)}
    fid = {l: i for i, l in enumerate(_FACE_IDS)}
    for e, p in enumerate(_EDGE_P):
        perm[e] = eid[_lat_id(_reflect_lattice(p, r))]
    for f, p in enumerate(_FACE_P):
        perm[N_EDGE + f] = N_EDGE + fid[_lat_id(_reflect_lattice(p, r))]
    return perm


_REFLECTIONS = [(rx, ry, rz) for rx in (0, 1) for ry in (0, 1) for rz in (0, 1)]
_MASK_PERMS = {r: _mask_perm(r) for r in _REFLECTIONS}


def _apply_mask_perm(mask: int, r) -> int:
    perm = _MASK_PERMS[r]
    out = 0
    for b in range(N_EDGE + N_FACE):
        if mask >> b & 1:
            out |= 1 << int(perm[b])
    return out


def canonical_mask(mask: int) -> Tuple[int, Tuple[int, int, int]]:
    """(canonical mask, reflection r with perm_r(mask) == canonical)."""
    best, best_r = None, None
    for r in _REFLECTIONS:
        m = _apply_mask_perm(mask, r)
        if best is None or m < best:
            best, best_r = m, r
    return best, best_r


# ----------------------------------------------------------------------
# Octree construction
# ----------------------------------------------------------------------

_DIRS = [d for d in np.ndindex(3, 3, 3) if d != (1, 1, 1)]


class _Octree:
    """2:1-balanced leaf set over an (nx0, ny0, nz0) root grid, integer
    coordinates in finest-level units (cell at level l has size
    2**(max_level - l))."""

    def __init__(self, nx0, ny0, nz0, max_level):
        self.U = 2 ** max_level
        self.dims = (nx0 * self.U, ny0 * self.U, nz0 * self.U)
        self.leaves = set()
        for z in range(0, self.dims[2], self.U):
            for y in range(0, self.dims[1], self.U):
                for x in range(0, self.dims[0], self.U):
                    self.leaves.add((x, y, z, self.U))

    def find(self, x, y, z) -> Optional[Tuple[int, int, int, int]]:
        """Leaf covering the unit cell at (x, y, z), or None outside."""
        if not (0 <= x < self.dims[0] and 0 <= y < self.dims[1]
                and 0 <= z < self.dims[2]):
            return None
        s = 1
        while s <= self.U:
            key = (x // s * s, y // s * s, z // s * s, s)
            if key in self.leaves:
                return key
            s *= 2
        raise AssertionError(f"no leaf covers {(x, y, z)}")

    def split(self, leaf, created=None) -> list:
        """Split a leaf into 8 children; ripple-refine coarser neighbors so
        the 26-neighbor 2:1 balance is preserved (any coarser leaf touching
        this one covers the entire adjacent region in its direction, so one
        sample point per direction suffices).  Returns every leaf created
        (children + ripple children) so callers need not diff the leaf set
        — diffing was O(n) per split, O(n^2) over a refinement sweep."""
        if created is None:
            created = []
        x, y, z, s = leaf
        assert s >= 2, "cannot split finest-level cell"
        self.leaves.remove(leaf)
        h = s // 2
        for dz in (0, h):
            for dy in (0, h):
                for dx in (0, h):
                    child = (x + dx, y + dy, z + dz, h)
                    self.leaves.add(child)
                    created.append(child)
        for d in _DIRS:
            qx = x - 1 if d[0] == 0 else (x + s if d[0] == 2 else x)
            qy = y - 1 if d[1] == 0 else (y + s if d[1] == 2 else y)
            qz = z - 1 if d[2] == 0 else (z + s if d[2] == 2 else z)
            nb = self.find(qx, qy, qz)
            if nb is not None and nb[3] > s and nb in self.leaves:
                self.split(nb, created)
        return created


def make_octree_model(
    nx0: int = 2,
    ny0: int = 2,
    nz0: int = 2,
    h0: float = 1.0,
    max_level: int = 2,
    E: float = 1.0,
    nu: float = 0.2,
    rho: float = 1.0,
    load: str = "traction",
    load_value: float = 1.0,
    n_incl: int = 3,
    incl_stiff: float = 10.0,
    seed: int = 0,
    canonicalize: bool = True,
    refine_centers: Optional[np.ndarray] = None,
    refine_radii: Optional[np.ndarray] = None,
) -> ModelData:
    """Graded octree block: stiff spherical inclusions, cells cut by an
    inclusion surface refined to ``max_level``, strong 2:1 balance.

    - clamped at x=0 (all nodes on the plane, hanging ones included);
    - ``load='traction'``: uniform pressure ``load_value`` (force/area) on
      the x=L face, distributed area-consistently over the face quads;
    - ``load='dirichlet'``: prescribed +x displacement on the x=L face.
    - ``canonicalize``: reduce the pattern library modulo the 8 axis
      reflections (mirrored instances get sign vectors); ``False`` keeps one
      type per raw mask with all-zero signs (useful as a cross-check).
    """
    rng = np.random.default_rng(seed)
    tree = _Octree(nx0, ny0, nz0, max_level)
    X, Y, Z = tree.dims
    hf = h0 / tree.U                                 # finest cell size
    L = np.array([X, Y, Z]) * hf

    if refine_centers is None:
        refine_centers = rng.uniform(0.15, 0.85, (n_incl, 3)) * L
        refine_radii = rng.uniform(0.12, 0.25, n_incl) * min(L)
    elif refine_radii is None:
        raise ValueError("refine_centers given without refine_radii")
    refine_centers = np.atleast_2d(np.asarray(refine_centers, dtype=float))
    refine_radii = np.atleast_1d(np.asarray(refine_radii, dtype=float))

    def cut_by_surface(x, y, z, s) -> bool:
        lo = np.array([x, y, z]) * hf
        hi = lo + s * hf
        for c, r in zip(refine_centers, refine_radii):
            near = np.clip(c, lo, hi)
            dmin = np.linalg.norm(near - c)
            dmax = np.linalg.norm(np.maximum(hi - c, c - lo))
            if dmin <= r <= dmax:
                return True
        return False

    work = [lf for lf in tree.leaves]
    while work:
        leaf = work.pop()
        if leaf not in tree.leaves or leaf[3] < 2:
            continue
        if cut_by_surface(*leaf):
            work.extend(tree.split(leaf))

    leaves = np.array(sorted(tree.leaves), dtype=np.int64)   # (n_elem, 4)
    n_elem = len(leaves)

    # ---- global nodes: all leaf corners -------------------------------
    stride_y, stride_z = X + 1, (X + 1) * (Y + 1)

    def encode(pts):                                  # pts (..., 3) ints
        return pts[..., 0] + stride_y * pts[..., 1] + stride_z * pts[..., 2]

    # corner lattice coords are {0,2} -> offsets {0,s} for every size incl. 1
    corners = (leaves[:, None, :3]
               + _CORNER_P[None, :, :] // 2 * leaves[:, None, 3:4])
    node_keys = np.unique(encode(corners).ravel())
    n_node = len(node_keys)
    n_dof = 3 * n_node
    coords = np.stack([node_keys % stride_y,
                       (node_keys // stride_y) % (Y + 1),
                       node_keys // stride_z], axis=1) * hf

    # ---- per-leaf pattern masks (membership in the node set is exact:
    # a mid-edge/mid-face node exists iff a finer neighbor created it) ----
    masks = np.zeros(n_elem, dtype=np.int64)
    half = leaves[:, 3] // 2
    big = leaves[:, 3] >= 2
    if big.any():
        EF_P = np.concatenate([_EDGE_P, _FACE_P])     # (18, 3)
        pts = (leaves[big, None, :3]
               + EF_P[None] * half[big, None, None])  # (nb, 18, 3)
        keys = encode(pts)
        pos = np.minimum(np.searchsorted(node_keys, keys), n_node - 1)
        present = node_keys[pos] == keys
        masks[big] = (present.astype(np.int64)
                      << np.arange(18, dtype=np.int64)).sum(axis=1)

    # ---- pattern library (canonical or raw); per-unique-mask lookup ----
    uniq_masks = np.unique(masks)
    if canonicalize:
        canon_u = {int(m): canonical_mask(int(m)) for m in uniq_masks}
    else:
        canon_u = {int(m): (int(m), (0, 0, 0)) for m in uniq_masks}
    upos = np.searchsorted(uniq_masks, masks)
    elem_mask = np.asarray([canon_u[int(m)][0] for m in uniq_masks],
                           dtype=np.int64)[upos]
    refl_u = np.asarray([c[1] for c in
                         (canon_u[int(m)] for m in uniq_masks)],
                        dtype=np.int64)                # (nu, 3)
    elem_refl = refl_u[upos]                           # (n_elem, 3)

    type_masks = sorted(set(int(m) for m in elem_mask))
    mask_to_type = {m: t for t, m in enumerate(type_masks)}
    elem_lib = {t: transition_element(m, nu) for t, m in enumerate(type_masks)}
    elem_type = np.array([mask_to_type[int(m)] for m in elem_mask],
                         dtype=np.int32)

    # ---- connectivity: canonical slot order mapped through the
    # reflection (reflections are involutions: physical lattice point of
    # canonical slot l-hat is r(l-hat)).  Vectorized per
    # (mask, reflection, size-class) group — a few hundred groups at most,
    # each a batched encode + searchsorted. ----------------------------
    lat_cache: Dict[int, np.ndarray] = {}
    for m in set(int(v) for v in elem_mask):
        lat, _ = _slot_layout(m)
        lat_cache[m] = np.array([[l % 3, (l // 3) % 3, l // 9] for l in lat],
                                dtype=np.int64)
    nn_of_mask = {m: len(v) for m, v in lat_cache.items()}
    nn_per = np.asarray([nn_of_mask[int(m)] for m in elem_mask])
    elem_nodes_offset = np.concatenate([[0], np.cumsum(nn_per)])
    elem_dofs_offset = 3 * elem_nodes_offset

    conn_flat = np.zeros(int(nn_per.sum()), dtype=np.int64)
    sign_nodes = np.zeros((int(nn_per.sum()), 3), dtype=bool)
    refl_code = elem_refl @ np.array([1, 2, 4])
    group_key = (elem_mask * 16 + refl_code * 2 + big.astype(np.int64))
    g_order = np.argsort(group_key, kind="stable")
    _, g_starts = np.unique(group_key[g_order], return_index=True)
    for a, b in zip(g_starts, np.append(g_starts[1:], len(g_order))):
        sel = g_order[a:b]
        m = int(elem_mask[sel[0]])
        r = tuple(int(v) for v in elem_refl[sel[0]])
        pts = lat_cache[m]
        phys = _reflect_lattice(pts, r)                # (nn, 3)
        if big[sel[0]]:
            lat_off = phys[None] * half[sel, None, None]
        else:
            lat_off = phys[None] // 2 * leaves[sel, None, 3:4]
        keys = encode(leaves[sel, None, :3] + lat_off)  # (ng, nn)
        nodes = np.searchsorted(node_keys, keys)
        # fail fast if a slot's lattice point is not a mesh node (the old
        # dict lookup raised KeyError; searchsorted would silently alias)
        if not np.array_equal(node_keys[np.minimum(nodes, n_node - 1)], keys):
            raise AssertionError(
                f"pattern slot lattice point missing from the node set "
                f"(mask {m}, reflection {r})")
        flat_pos = (np.repeat(elem_nodes_offset[sel], len(pts))
                    + np.tile(np.arange(len(pts)), len(sel)))
        conn_flat[flat_pos] = nodes.reshape(-1)
        for ax in range(3):
            if r[ax]:
                sign_nodes[flat_pos, ax] = True

    dof_flat_all = (3 * conn_flat[:, None]
                    + np.arange(3)[None, :]).reshape(-1)
    sign_flat_all = sign_nodes.reshape(-1)

    # ---- materials ----------------------------------------------------
    sctrs = (leaves[:, :3] + leaves[:, 3:4] / 2.0) * hf
    E_elem = np.full(n_elem, E)
    for c, r in zip(refine_centers, refine_radii):
        inside = np.linalg.norm(sctrs - c, axis=1) < r
        E_elem[inside] = incl_stiff * E
    mat = (E_elem > E).astype(np.int32)
    mat_prop = [
        {"E": E, "Pos": nu, "Rho": rho, "NonLocStressParam": {"Lc": 2.0 * hf}},
        {"E": incl_stiff * E, "Pos": nu, "Rho": rho,
         "NonLocStressParam": {"Lc": 2.0 * hf}},
    ]

    h_elem = leaves[:, 3] * hf
    ck = E_elem * h_elem
    cm = rho * h_elem ** 3
    ce = 1.0 / h_elem

    # ---- mass diagonal (vectorized per type) -------------------------
    diag_M = np.zeros(n_dof)
    for t, lib in elem_lib.items():
        sel = np.where(elem_type == t)[0]
        if not len(sel):
            continue
        d = lib["Ke"].shape[0]
        me_rowsum = lib["Me"].sum(axis=1)              # (d,)
        dofs = dof_flat_all[
            (elem_dofs_offset[sel, None]
             + np.arange(d)[None, :])]                 # (nt, d)
        np.add.at(diag_M, dofs.reshape(-1),
                  (cm[sel, None] * me_rowsum[None]).reshape(-1))

    # ---- faces (ALL element faces; subdivided ones as 4 sub-quads so
    # interior incidence is exactly 2 — reference export_vtk.py:105-113) --
    face_quads = _collect_faces(leaves, masks, node_keys, encode)

    # ---- BCs ----------------------------------------------------------
    F = np.zeros(n_dof)
    Ud = np.zeros(n_dof)
    on_x0 = np.where(coords[:, 0] == 0.0)[0]
    fixed = (3 * on_x0[:, None] + np.arange(3)[None, :]).ravel()
    xL = X * hf
    if load == "traction":
        for quad, area in _boundary_quads_at(face_quads, coords, axis=0,
                                             value=xL):
            F[3 * quad] += load_value * area / 4.0
    elif load == "dirichlet":
        on_xL = np.where(coords[:, 0] == xL)[0]
        Ud[3 * on_xL] = load_value
        fixed = np.concatenate([fixed, 3 * on_xL])
    else:
        raise ValueError(f"unknown load mode {load!r}")
    fixed = np.unique(fixed)
    dof_eff = np.setdiff1d(np.arange(n_dof), fixed, assume_unique=True)

    return ModelData(
        n_elem=n_elem,
        n_node=n_node,
        n_dof=n_dof,
        node_coords=coords,
        F=F,
        Ud=Ud,
        Vd=np.zeros(n_dof),
        diag_M=diag_M,
        fixed_dof=fixed,
        dof_eff=dof_eff,
        elem_type=elem_type,
        elem_nodes_flat=conn_flat,
        elem_nodes_offset=elem_nodes_offset,
        elem_dofs_flat=dof_flat_all,
        elem_dofs_offset=elem_dofs_offset,
        elem_sign_flat=sign_flat_all,
        ck=ck,
        cm=cm,
        ce=ce,
        level=h_elem,
        poly_mat=mat,
        sctrs=sctrs,
        elem_lib=elem_lib,
        mat_prop=mat_prop,
        dt=1.0,
        faces_flat=np.asarray(face_quads, dtype=np.int64).ravel(),
        faces_offset=np.arange(len(face_quads) + 1) * 4,
        grid=None,
        octree=_octree_meta(leaves, (X, Y, Z), node_keys,
                            (stride_y, stride_z), mask_to_type),
    )


def reconstruct_lattice_meta(model: ModelData) -> bool:
    """Rebuild ``Octree.npz``-equivalent lattice metadata from the
    reference schema's OWN fields, so a genuine reference MDF bundle
    (which has no fast-path sidecars) routes to the hybrid level-grid
    backend instead of the general gather/scatter path (VERDICT r03
    weakness 3).

    Fully geometric — per-element bounding boxes from connectivity +
    node coords (schema-independent: does not trust ``Level``'s unit
    convention), cell sizes snapped to the finest size ``hf``, node
    coords snapped to the finest lattice.  Engages only when EVERY check
    passes exactly (cubic cells, power-of-two size ratios, size-aligned
    min corners, lattice-aligned nodes, unique node keys, an 8-corner
    brick type with zero sign bits); returns False (model untouched)
    otherwise — a non-octree model must silently keep its general-path
    eligibility.  Sets ``model.octree`` (and ``model.grid`` when the
    lattice is a trivially-uniform full box).
    """
    nc = np.asarray(model.node_coords, float)
    conn = np.asarray(model.elem_nodes_flat)
    off = np.asarray(model.elem_nodes_offset)
    n_elem = int(model.n_elem)
    if n_elem == 0 or len(conn) == 0 or nc.ndim != 2 or nc.shape[1] != 3:
        return False
    pts = nc[conn]                                  # (n_flat, 3)
    mins = np.minimum.reduceat(pts, off[:-1], axis=0)
    maxs = np.maximum.reduceat(pts, off[:-1], axis=0)
    ext = maxs - mins                               # (n_elem, 3)
    scale = float(np.max(ext))
    if scale <= 0:
        return False
    tol = 1e-6 * scale
    # cubic cells of positive size
    if (np.any(ext <= 0) or np.any(np.abs(ext[:, 0] - ext[:, 1]) > tol)
            or np.any(np.abs(ext[:, 0] - ext[:, 2]) > tol)):
        return False
    h = ext.mean(axis=1)
    hf = float(h.min())
    s_f = h / hf
    s_int = np.rint(s_f).astype(np.int64)
    # power-of-two size ratios (2:1-graded octree sizes in finest units)
    if (np.any(np.abs(s_f - s_int) * hf > tol) or np.any(s_int < 1)
            or np.any(s_int & (s_int - 1))):
        return False
    origin = nc.min(axis=0)
    lo_f = (mins - origin) / hf
    leaf_xyz = np.rint(lo_f).astype(np.int64)
    if np.any(np.abs(lo_f - leaf_xyz) * hf > tol) or np.any(leaf_xyz < 0):
        return False
    if np.any(leaf_xyz % s_int[:, None]):           # octree cells are
        return False                                # size-aligned
    # cross-check the schema's own cell centers where present
    if model.sctrs is not None and len(model.sctrs):
        centers = mins + 0.5 * h[:, None]
        if np.any(np.abs(np.asarray(model.sctrs, float) - centers)
                  > 10 * tol):
            return False
    nlat_f = (nc - origin) / hf
    nlat = np.rint(nlat_f).astype(np.int64)
    if np.any(np.abs(nlat_f - nlat) * hf > tol) or np.any(nlat < 0):
        return False
    dims = (leaf_xyz + s_int[:, None]).max(axis=0)
    if np.any(nlat > dims[None, :]) or np.any(nlat.max(axis=0) != dims):
        return False
    X, Y, Z = (int(d) for d in dims)
    sy, sz = X + 1, (X + 1) * (Y + 1)
    node_keys = nlat[:, 0] + sy * nlat[:, 1] + sz * nlat[:, 2]
    if len(np.unique(node_keys)) != len(node_keys):
        return False

    # ---- brick type: the 8-node type whose connectivity is exactly the
    # 8 cell corners, for EVERY element of the type, in the level-grid
    # stencil's corner order, with no sign flips.  All checks are GLOBAL
    # (vectorized over every element of the candidate type): a sampled
    # check that misses one mis-oriented element would make the hybrid
    # stencil apply Ke with the wrong orientation — a silently wrong
    # solution, the one failure mode reconstruction must never risk. ----
    from pcg_mpi_solver_tpu.parallel.hybrid import _CORNERS

    nn_per = np.diff(off)
    brick_type = None
    brick_corners = None
    best_count = 0
    sign_off = np.asarray(model.elem_dofs_offset)
    sflat = np.asarray(model.elem_sign_flat)
    for t, lib in model.elem_lib.items():
        if lib.get("n_nodes") != 8:
            continue
        sel = np.where(np.asarray(model.elem_type) == t)[0]
        if not len(sel) or np.any(nn_per[sel] != 8):
            continue
        nodes = conn[off[sel, None] + np.arange(8)[None]]       # (k, 8)
        offs = ((nlat[nodes] - leaf_xyz[sel, None, :])
                // s_int[sel, None, None])                      # (k, 8, 3)
        # partition_hybrid hard-requires _CORNERS order (hybrid.py:190);
        # any other constant order must DECLINE (general path), not
        # engage-and-crash
        if not np.array_equal(offs, np.broadcast_to(_CORNERS, offs.shape)):
            continue
        # brick rows must be unsigned (sign flips would re-orient Ke)
        segs = sflat[sign_off[sel, None] + np.arange(24)[None]]
        if segs.any():
            continue
        if len(sel) > best_count:
            best_count = len(sel)
            brick_type = int(t)
            brick_corners = np.asarray(_CORNERS, np.int64).copy()
    if brick_type is None:
        return False

    leaves = np.concatenate([leaf_xyz, s_int[:, None]], axis=1)
    model.octree = {
        "leaves": leaves,
        "dims": (X, Y, Z),
        "node_keys": node_keys,
        "strides": (sy, sz),
        "brick_type": brick_type,
        "brick_corners": brick_corners,
    }
    if (model.grid is None and np.all(s_int == 1)
            and n_elem == X * Y * Z and best_count == n_elem
            # the structured backend additionally hardcodes the lattice
            # ORDERINGS (parallel/structured.py:88,94): element id
            # x-fastest over (z, y, x) and node id = lattice raveling —
            # engage the grid fast path only when the bundle matches
            and np.array_equal(node_keys,
                               np.arange((X + 1) * (Y + 1) * (Z + 1)))
            and np.array_equal(
                leaf_xyz,
                np.stack(np.meshgrid(np.arange(X), np.arange(Y),
                                     np.arange(Z), indexing="ij"),
                         axis=-1).transpose(2, 1, 0, 3).reshape(-1, 3))):
        model.grid = (X, Y, Z, hf)      # trivially-uniform full box
    return True


def _octree_meta(leaves, dims, node_keys, strides, mask_to_type):
    """Lattice metadata consumed by the hybrid level-grid backend
    (parallel/hybrid.py).  The "brick" pattern is mask 0 (no mid-edge/face
    nodes); its canonical reflection is the identity (canonical_mask(0) ==
    (0, (0,0,0))), so brick connectivity has zero signs and its node order
    is _slot_layout(0)'s corner order recorded here."""
    brick_type = mask_to_type.get(0)
    brick_corners = None
    if brick_type is not None:
        lat, _ = _slot_layout(0)
        brick_corners = np.array(
            [[l % 3, (l // 3) % 3, l // 9] for l in lat], dtype=np.int64) // 2
    return {
        "leaves": leaves,
        "dims": tuple(int(d) for d in dims),
        "node_keys": node_keys,
        "strides": tuple(int(s) for s in strides),
        "brick_type": brick_type,
        "brick_corners": brick_corners,
    }


# Face f of a cell (lattice point p with two coords == 1): the 4 corner
# lattice points of the face, in a consistent quad order.
def _face_corner_lats(p: np.ndarray) -> np.ndarray:
    ax = int(np.where(p != 1)[0][0])
    t1, t2 = [t for t in range(3) if t != ax]
    quad = []
    for a, b in ((0, 0), (2, 0), (2, 2), (0, 2)):
        q = p.copy()
        q[t1], q[t2] = a, b
        quad.append(q)
    return np.array(quad)


_FACE_CORNERS = [_face_corner_lats(p) for p in _FACE_P]


def _collect_faces(leaves, masks, node_keys, encode) -> np.ndarray:
    """All element faces as node-id quads, vectorized per (face, case):
    subdivided faces (mask bit set) as their 4 sub-quads."""
    big = leaves[:, 3] >= 2
    h2 = np.maximum(leaves[:, 3] // 2, 1)
    quad_batches = []
    order = []                      # (elem id, face id, sub id) for ordering

    def lookup(keys):
        ids = np.searchsorted(node_keys, keys)
        if not np.array_equal(
                node_keys[np.minimum(ids, len(node_keys) - 1)], keys):
            raise AssertionError("face corner missing from the node set")
        return ids

    for f, p in enumerate(_FACE_P):
        corners = _FACE_CORNERS[f]                      # (4, 3)
        sub = big & ((masks >> (N_EDGE + f)) & 1).astype(bool)
        # whole faces (coarse lattice for size-1 cells)
        sel = np.where(~sub)[0]
        if len(sel):
            lat = np.where(big[sel, None, None], corners[None] * h2[sel, None, None],
                           corners[None] // 2 * leaves[sel, None, 3:4])
            quad_batches.append(lookup(encode(leaves[sel, None, :3] + lat)))
            order.append(sel * 24 + f * 4)
        # subdivided faces: 4 sub-quads each
        sel = np.where(sub)[0]
        for k in range(4):
            if not len(sel):
                continue
            q0 = corners[k]
            q1 = (corners[k] + corners[(k + 1) % 4]) // 2
            q3 = (corners[k] + corners[(k - 1) % 4]) // 2
            lat = np.stack([q0, q1, p, q3])             # (4, 3)
            quad_batches.append(lookup(encode(
                leaves[sel, None, :3] + lat[None] * h2[sel, None, None])))
            order.append(sel * 24 + f * 4 + k)
    quads = np.concatenate(quad_batches, axis=0)
    # restore per-element, per-face order (stable downstream exports)
    return quads[np.argsort(np.concatenate(order), kind="stable")]


def _boundary_quads_at(face_quads, coords, axis: int, value: float):
    """Quads whose 4 nodes all lie on the plane coords[axis] == value, with
    their areas, deduplicated (interior faces appear twice)."""
    on = np.abs(coords[face_quads, axis] - value) < 1e-12
    sel = face_quads[on.all(axis=1)]
    if not len(sel):
        return
    _, first = np.unique(np.sort(sel, axis=1), axis=0, return_index=True)
    sel = sel[np.sort(first)]
    pts = coords[sel]                                   # (n, 4, 3)
    areas = np.linalg.norm(
        np.cross(pts[:, 1] - pts[:, 0], pts[:, 3] - pts[:, 0]), axis=1)
    for quad, area in zip(sel, areas):
        yield quad, float(area)
