"""In-memory global model representation (host-side, numpy).

Plays the role of the reference's on-disk Model Definition Files (MDF) bundle
— the 12 per-element binary arrays + 7 nodal arrays + ``Ke.mat``/``Me.mat``
element library + ``GlobN.mat`` counts (schema at partition_mesh.py:172-175,
324-330; counts at run_metis.py:19-38) — as one typed object.  Produced either
by the synthetic generator (models/synthetic.py) or by the MDF reader
(models/mdf.py) for models exported in the reference's format.

Element connectivity is CSR-style (flat + offsets) exactly because octree
pattern types have differing node counts; dof ids and sign flags are stored
per element-dof (the sign encodes mirrored-pattern reflection: the matvec is
S.Ke.(S.u) with S = diag(+-1), pcg_solver.py:277-280).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


class SparseVec:
    """Sparse view of a global ``(n,[k])`` nodal array restricted to a
    sorted id subset — the slab-ingest representation of F/Ud/diag_M/
    node_coords (models/mdf.read_mdf_slab): a process holding only its
    slab's referenced dofs still serves the ``model.F[global_ids]``
    gathers the partition build performs, without ever materializing the
    full vector.  Lookups outside the restriction return ``fill``
    (never legitimately read by a build restricted to the same slab —
    asserted in tests via ``strict=True``)."""

    __slots__ = ("ids", "vals", "n", "fill", "strict")

    def __init__(self, ids: np.ndarray, vals: np.ndarray, n: int,
                 fill: float = 0.0, strict: bool = False):
        self.ids = np.asarray(ids)
        self.vals = np.asarray(vals)
        if len(self.ids) != len(self.vals):
            raise ValueError("SparseVec: ids/vals length mismatch")
        if len(self.ids) > 1 and not bool(np.all(np.diff(self.ids) > 0)):
            raise ValueError("SparseVec: ids must be strictly increasing")
        self.n = int(n)
        self.fill = fill
        self.strict = bool(strict)

    def __len__(self) -> int:
        return self.n

    @property
    def dtype(self):
        return self.vals.dtype

    @property
    def shape(self):
        return (self.n,) + self.vals.shape[1:]

    def __getitem__(self, idx):
        idx = np.asarray(idx)
        scalar = idx.ndim == 0
        flat = np.atleast_1d(idx).astype(np.int64).ravel()
        if len(self.ids) == 0:
            hit = np.zeros(len(flat), dtype=bool)
            posc = np.zeros(len(flat), dtype=np.int64)
        else:
            pos = np.searchsorted(self.ids, flat)
            posc = np.minimum(pos, len(self.ids) - 1)
            hit = self.ids[posc] == flat
        if self.strict and not hit.all():
            missing = flat[~hit][:5]
            raise IndexError(
                f"SparseVec: lookup outside the slab restriction "
                f"(ids {missing.tolist()}...)")
        out = self.vals[posc].copy()
        out[~hit] = self.fill
        # idx.shape (not atleast_1d) so a scalar lookup returns a scalar
        # (0-d -> [()]), matching the dense-array contract exactly
        out = out.reshape(idx.shape + self.vals.shape[1:])
        return out[()] if scalar else out

    def materialize(self) -> np.ndarray:
        """Dense global array (testing/small models only)."""
        out = np.full((self.n,) + self.vals.shape[1:], self.fill,
                      dtype=self.vals.dtype)
        out[self.ids] = self.vals
        return out

    def __repr__(self) -> str:       # deterministic (hash-friendly)
        return (f"SparseVec(n={self.n}, nnz={len(self.ids)}, "
                f"dtype={self.vals.dtype})")


@dataclasses.dataclass
class ModelData:
    # Counts
    n_elem: int
    n_node: int
    n_dof: int

    # Nodal data
    node_coords: np.ndarray        # (n_node, 3) float64
    F: np.ndarray                  # (n_dof,) reference load vector
    Ud: np.ndarray                 # (n_dof,) prescribed displacement (Dirichlet values)
    Vd: np.ndarray                 # (n_dof,) prescribed velocity (dynamics; zeros here)
    diag_M: np.ndarray             # (n_dof,) lumped mass diagonal
    fixed_dof: np.ndarray          # (n_fixed,) int — Dirichlet-constrained dof ids
    dof_eff: np.ndarray            # (n_eff,) int — effective (free) dof ids

    # Per-element data (CSR-style ragged)
    elem_type: np.ndarray          # (n_elem,) int32 pattern-type id
    elem_nodes_flat: np.ndarray    # (sum nnodes,) int
    elem_nodes_offset: np.ndarray  # (n_elem+1,) int
    elem_dofs_flat: np.ndarray     # (sum ndofs,) int
    elem_dofs_offset: np.ndarray   # (n_elem+1,) int
    elem_sign_flat: np.ndarray     # (sum ndofs,) bool — reflection sign per elem-dof
    ck: np.ndarray                 # (n_elem,) stiffness scale  (= E*h)
    cm: np.ndarray                 # (n_elem,) mass scale       (= rho*h^3)
    ce: np.ndarray                 # (n_elem,) strain scale     (= 1/h)
    level: np.ndarray              # (n_elem,) cell size h
    poly_mat: np.ndarray           # (n_elem,) int material id
    sctrs: np.ndarray              # (n_elem, 3) element centroids

    # Element library: type id -> {'Ke','Me','Se','diagKe','n_nodes'}
    elem_lib: Dict[int, dict]

    # Materials: list of {'E','Pos','Rho'}
    mat_prop: List[dict]

    # Time step (dynamics era; quasi-statics uses it only for TimeList labels)
    dt: float = 1.0

    # Optional visualization topology (boundary faces of the mesh)
    faces_flat: Optional[np.ndarray] = None    # (sum face nnodes,)
    faces_offset: Optional[np.ndarray] = None  # (n_faces+1,)

    # Structured-grid metadata (nx, ny, nz, h) when the mesh is a single
    # uniform block — unlocks the slice-based TPU fast path
    # (parallel/structured.py); None for general octree/unstructured models.
    grid: Optional[tuple] = None

    # Octree lattice metadata (set by models/octree.py) — unlocks the
    # hybrid level-grid fast path (parallel/hybrid.py): uniform 8-node
    # "brick" cells of each refinement level run as dense structured
    # stencils, only transition cells stay on the gather/scatter path.
    #   {"leaves": (n_elem, 4) lattice origin+size in finest units,
    #    "dims": (X, Y, Z) finest-lattice extents,
    #    "node_keys": (n_node,) lattice key of node id i at index i —
    #                 ORDER UNSPECIFIED (models/octree.py generation
    #                 happens to yield sorted keys; reconstruct_lattice_meta
    #                 yields node-id order).  Consumers needing binary
    #                 search must argsort first (as partition_hybrid does),
    #    "strides": (stride_y, stride_z) of the key encoding,
    #    "brick_type": type id of the pure 8-node pattern (or None),
    #    "brick_corners": (8, 3) corner offsets in that type's node order}
    octree: Optional[dict] = None

    # Cohesive interface elements (reference type -1/-2 scaffolding,
    # partition_mesh.py:603-650 — built there but never solved with; here the
    # capability is live).  Each entry is a zero-thickness 4+4-node quad:
    #   {'NodeIdList': (2, 4) int  — [side-a nodes, side-b nodes], pairwise
    #                   coincident,
    #    'adj_elem':   int         — a volume element adjacent to side a
    #                                (anchors partitioning),
    #    'kn': float, 'kt': float  — normal/tangential penalty stiffness per
    #                                unit area,
    #    'area': float,            — interface element area
    #    'normal_axis': int}       — 0/1/2 (octree interfaces are axis-aligned)
    intfc_elems: Optional[List[dict]] = None

    # Slab-ingest view (ISSUE 14, models/mdf.read_mdf_slab): when set,
    # the per-element arrays above cover ONLY the slab's elements (in
    # this order) and ``elem_ids[i]`` is element i's GLOBAL id; nodal
    # arrays are SparseVec restrictions to the slab's referenced ids.
    # ``n_elem`` is then the SLAB count (the global count is
    # ``glob_n_elem``); node/dof ids and counts stay global throughout,
    # so partitioning and the interface reduction are unchanged.
    # None = a full dense model (every existing producer).
    elem_ids: Optional[np.ndarray] = None
    glob_n_elem: Optional[int] = None

    def elem_nodes(self, e: int) -> np.ndarray:
        return self.elem_nodes_flat[self.elem_nodes_offset[e]:self.elem_nodes_offset[e + 1]]

    def elem_dofs(self, e: int) -> np.ndarray:
        return self.elem_dofs_flat[self.elem_dofs_offset[e]:self.elem_dofs_offset[e + 1]]

    def elem_signs(self, e: int) -> np.ndarray:
        return self.elem_sign_flat[self.elem_dofs_offset[e]:self.elem_dofs_offset[e + 1]]

    # ------------------------------------------------------------------
    # Interface springs: flattened node-pair penalty form
    # ------------------------------------------------------------------
    def interface_springs(self):
        """Flatten interface elements to per-dof penalty springs.

        Each coincident node pair contributes, per component c, a spring of
        stiffness k_c = area/4 * (kn if c == normal_axis else kt) acting on
        the jump u_a - u_b.  Returns (dof_a, dof_b, k, adj_elem) flat arrays
        (empty if the model has no interface elements)."""
        if not self.intfc_elems:
            z = np.zeros(0, dtype=np.int64)
            return z, z, np.zeros(0), z
        dof_a, dof_b, k, adj = [], [], [], []
        for ie in self.intfc_elems:
            nodes = np.asarray(ie["NodeIdList"])
            per_pair = ie["area"] / nodes.shape[1]
            for c in range(3):
                kc = per_pair * (ie["kn"] if c == ie["normal_axis"] else ie["kt"])
                dof_a.append(3 * nodes[0] + c)
                dof_b.append(3 * nodes[1] + c)
                k.append(np.full(nodes.shape[1], kc))
                adj.append(np.full(nodes.shape[1], ie["adj_elem"], dtype=np.int64))
        return (np.concatenate(dof_a), np.concatenate(dof_b),
                np.concatenate(k), np.concatenate(adj))

    # ------------------------------------------------------------------
    # Validation helpers (test oracle): dense/sparse global assembly.
    # ------------------------------------------------------------------
    def assemble_csr(self):
        """Assemble the global stiffness K as scipy CSR (small models only).

        The matrix the matrix-free path must reproduce:
        K = sum_e  P_e^T S_e (ck_e * Ke_type) S_e P_e.
        """
        from scipy.sparse import coo_matrix

        rows, cols, vals = [], [], []
        for e in range(self.n_elem):
            dofs = self.elem_dofs(e)
            signs = self.elem_signs(e)
            Ke = self.elem_lib[int(self.elem_type[e])]["Ke"]
            s = np.where(signs, -1.0, 1.0)
            Ke_e = self.ck[e] * (s[:, None] * Ke * s[None, :])
            d = len(dofs)
            rows.append(np.repeat(dofs, d))
            cols.append(np.tile(dofs, d))
            vals.append(Ke_e.ravel())
        sa, sb, sk, _ = self.interface_springs()
        if len(sa):
            rows.append(np.concatenate([sa, sb, sa, sb]))
            cols.append(np.concatenate([sa, sb, sb, sa]))
            vals.append(np.concatenate([sk, sk, -sk, -sk]))
        K = coo_matrix(
            (np.concatenate(vals), (np.concatenate(rows), np.concatenate(cols))),
            shape=(self.n_dof, self.n_dof),
        )
        return K.tocsr()

    def assemble_diag(self) -> np.ndarray:
        """Diagonal of K (Jacobi preconditioner oracle, pcg_solver.py:282-287)."""
        diag = np.zeros(self.n_dof)
        for e in range(self.n_elem):
            dofs = self.elem_dofs(e)
            dK = self.elem_lib[int(self.elem_type[e])]["diagKe"]
            np.add.at(diag, dofs, self.ck[e] * dK)
        sa, sb, sk, _ = self.interface_springs()
        if len(sa):
            np.add.at(diag, sa, sk)
            np.add.at(diag, sb, sk)
        return diag
